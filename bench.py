#!/usr/bin/env python
"""Headline benchmark: prints ONE JSON line for the driver.

The full results JSON is additionally written (and fsynced) to
``RAFT_TPU_BENCH_JSON`` (default ``artifacts/bench_full.json``) BEFORE
anything hits stdout, and the headline entry sorts first in ``entries``
— so a truncated stdout capture can never lose measurements again.

Measures QPS at recall@10 for the BASELINE.md configs on a SIFT-like
synthetic corpus (clustered gaussian mixture; queries are FRESH samples
from the mixture, not perturbed corpus rows, so the nprobe sweep shows a
real recall frontier), plus brute-force QPS and an on-device roofline
probe so kernel throughput is reported against the measured peak of the
chip actually in use.

Two timings per entry:

* ``latency_ms`` — per-call-blocked median: every call pays the full
  dispatch round trip (~90 ms through the axon tunnel). Reported for
  context; dropped (-1) when the backend window lies about it.
* ``qps`` — the VALUE-READ PIPELINED WALL (``measure_wall``): N calls on
  content-distinct query permutations dispatched back-to-back (dispatch
  overlaps compute — the reference harness's ``items_per_second``
  semantics, cpp/bench/ann/src/common/benchmark.hpp:337), every output
  folded into a scalar accumulator, and the window closed by a host-side
  ``float()`` of that accumulator. The value read is load-bearing: this
  backend's lying modes extend to ``block_until_ready`` itself (observed
  returning in 0.8 ms for a 2.56 TFLOP batch on content-distinct
  inputs), and a host value transitively dependent on every output
  cannot materialize before the compute ran.

Every timing is additionally gated by a per-lane PHYSICAL floor —
FLOPs/(datasheet peak) for GEMM lanes, grouped-scan bytes/(HBM peak) for
list scans — because lying windows have produced numbers just above any
generic floor. Measurements below the floor are discarded, not recorded.
All data is generated ON DEVICE (host<->device transfers through remote
tunnels are slow and would pollute build/search timings); recall is
computed on device against exact ground truth and only scalars leave the
chip.

The 1M (full) scale never compiles a 1M-row program — the tunnel's
compile endpoint has hung on those for 25+ minutes where 500k compiles
in ~134 s — instead the corpus is split into two 500k parts sharing ONE
compiled executable per algorithm (index as jit argument), and per-part
top-k results are merged exactly (knn_merge_parts). This is the
single-chip form of the reference's data-sharded MNMG search
(detail/knn_merge_parts.cuh:172).
"""
import contextlib
import json
import os
import sys
import time

# persistent executable cache: lets compile probes / child processes
# pre-pay fragile compiles for the parent. NOTE: ops.autotune.measure
# disables this cache around its fresh-executable re-measure — a cache
# hit there would replay the very executable whose timing is under
# suspicion.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")

import jax  # noqa: E402

# RAFT_TPU_BENCH_CPU=1 pins the CPU backend IN-PROCESS (the env-var form
# JAX_PLATFORMS=cpu is unreliably honored under the axon tunnel — see
# tests/conftest.py); used by the micro harness-smoke lane so it never
# contends with a TPU run
if os.environ.get("RAFT_TPU_BENCH_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# --- reference baselines (QPS @ recall@10 = 0.95, 10k-query batches) -----
# RAFT 24.02 publishes QPS-vs-recall Pareto PLOTS, not numeric tables
# (docs/source/raft_ann_benchmarks.md:255-257; the positioning claim —
# CAGRA outperforming CPU HNSW and GPU state of the art at all recall
# levels — is README.md:74). The numbers below are therefore derived
# A100-class estimates; each derivation is pinned to the reference file
# it reads from and reported in the output so vs_baseline is traceable.
BASELINES = {
    "raft_brute_force": {
        "qps": 300_000.0,
        "derivation": (
            "GEMM+select design of detail/knn_brute_force.cuh:61: A100 "
            "TF32 peak ~156 TFLOP/s, 2*n*d = 256 MFLOP/query at 1Mx128 "
            "-> ~600k QPS GEMM ceiling; ~2x tiled select_k overhead -> "
            "300k"),
    },
    "raft_ivf_flat": {
        "qps": 50_000.0,
        "derivation": (
            "list-scan bandwidth bound (ivf_flat_interleaved_scan-inl."
            "cuh): nprobe=20 of nlist=1024 over 1Mx128xf32 reads ~10-30 "
            "MB/query depending on imbalance; A100 HBM 1.55 TB/s -> "
            "~50k QPS. Param envelope: ann_benchmarks_param_tuning.md:"
            "10-33"),
    },
    "raft_ivf_pq": {
        "qps": 200_000.0,
        "derivation": (
            "same probe fraction over 64B codes (ivf_pq_compute_"
            "similarity-inl.cuh:271 LUT scan) = ~8x less traffic than "
            "ivf_flat -> ~400k ceiling; LUT + refine overhead ~2x -> "
            "200k. Param envelope: ann_benchmarks_param_tuning.md:34-68"),
    },
    "raft_cagra": {
        "qps": 500_000.0,
        "derivation": (
            "published H100 batch-10 Pareto plots put graph search at "
            "~500k-1M QPS @0.95 for million-scale corpora (raft_ann_"
            "benchmarks.md:255-257, img/raft-vector-search-batch-10."
            "png); 500k is the conservative read"),
    },
}
BASELINE_QPS = {k: v["qps"] for k, v in BASELINES.items()}

# corpus geometry: a LOW-INTRINSIC-DIMENSION clustered mixture. Real ANN
# corpora (SIFT ~16 effective dims in 128 ambient) are hard for IVF
# because neighborhoods straddle partition boundaries in the low-dim
# manifold; full-rank gaussian clusters are trivially recoverable at any
# nprobe (measured: recall@np20 = 1.0 for every full-rank variant —
# scratch/exp_corpus_hard.py). Queries are fresh mixture draws, never
# perturbed corpus rows.
CORPUS_SCALE = float(os.environ.get("RAFT_TPU_BENCH_CSCALE", "1.0"))
CORPUS_INTRINSIC_D = int(os.environ.get("RAFT_TPU_BENCH_INTRINSIC_D", "16"))
CORPUS_CLUSTERS = int(os.environ.get("RAFT_TPU_BENCH_NCLUSTERS", "200"))


def robust_call(fn, what: str, tries: int = 3, deadline: float = 0.0):
    """Run a build/setup stage with retries (same transport-flake story as
    median_time; builds are minutes of work we must not lose to one
    dropped connection).

    ``deadline``: absolute ``time.perf_counter()`` cutoff — when a retry
    would start past it, give up immediately instead."""
    for t in range(tries):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            log(f"# {what}: attempt {t + 1}/{tries} failed: "
                f"{type(e).__name__}: {e}")
            if t + 1 == tries:
                raise
            if deadline and time.perf_counter() > deadline:
                log(f"# {what}: stage deadline passed; not retrying")
                raise
            time.sleep(20 * (t + 1))


def median_time(fn, *args, reps=5, tries=3, floor=0.0):
    """Per-call-blocked median (latency). Returns None after ``tries``
    consecutive failures or when the backend window is lying."""
    from raft_tpu.ops.autotune import TimingUnreliableError, measure

    for t in range(tries):
        try:
            return measure(fn, *args, reps=reps, suspect_floor_s=floor)
        except TimingUnreliableError as e:
            log(f"# measurement unreliable (no retry): {e}")
            return None
        except Exception as e:  # noqa: BLE001 - transport/compile flakes
            log(f"# measurement attempt {t + 1}/{tries} failed: "
                f"{type(e).__name__}: {e}")
            if t + 1 < tries:
                time.sleep(15 * (t + 1))
    return None


@contextlib.contextmanager
def algo_section(name):
    """One algorithm's persistent failure (or a deliberate budget skip)
    must not cost the whole run its output line: log and continue with
    the entries recorded so far."""
    try:
        yield
    except Exception as e:  # noqa: BLE001
        log(f"# {name} section ended early ({type(e).__name__}: {e}); "
            "continuing with remaining algos")


def make_corpus(n, d, nq, n_clusters=None, seed=0, scale=None,
                intrinsic_d=None):
    """Low-intrinsic-dimension clustered mixture; queries are FRESH
    mixture samples (the structure real ANN corpora + query sets have;
    all on device). Points live near a random ``intrinsic_d``-dim
    subspace (cluster centers and within-cluster spread both low-rank)
    plus small ambient noise, so neighborhoods straddle IVF partition
    boundaries the way SIFT's do."""
    scale = CORPUS_SCALE if scale is None else scale
    n_clusters = CORPUS_CLUSTERS if n_clusters is None else n_clusters
    intrinsic_d = CORPUS_INTRINSIC_D if intrinsic_d is None else intrinsic_d
    kw, kc, kx, ka, kq, kp, ke, kf = jax.random.split(
        jax.random.PRNGKey(seed), 8)
    w = jax.random.normal(kw, (intrinsic_d, d), jnp.float32)
    w = w / jnp.linalg.norm(w, axis=1, keepdims=True)
    centers_z = jax.random.normal(kc, (n_clusters, intrinsic_d),
                                  jnp.float32) * scale
    assign = jax.random.randint(ka, (n,), 0, n_clusters)
    z = centers_z[assign] + jax.random.normal(kx, (n, intrinsic_d),
                                              jnp.float32)
    data = z @ w + 0.1 * jax.random.normal(ke, (n, d), jnp.float32)
    qassign = jax.random.randint(kq, (nq,), 0, n_clusters)
    qz = centers_z[qassign] + jax.random.normal(kp, (nq, intrinsic_d),
                                                jnp.float32)
    queries = qz @ w + 0.1 * jax.random.normal(kf, (nq, d), jnp.float32)
    return jax.block_until_ready(data), jax.block_until_ready(queries)


def device_recall(ids, gt):
    """Mean recall@k, computed on device; one scalar leaves the chip."""
    hit = jnp.any(ids[:, :, None] == gt[:, None, :], axis=2) & (gt >= 0)
    return float(jnp.sum(hit) / jnp.sum(gt >= 0))


def exercise_fbin_io(data, rows=100_000):
    """Round-trip a corpus slice through the raft-ann-bench fbin loader
    (bench/datasets.py) so the recorded artifact exercises the dataset IO
    path; returns the artifact note. Deliberately outside all timed
    sections — host<->device transfer through the tunnel is slow."""
    from raft_tpu.bench import datasets as bds

    rows = min(rows, len(data))
    path = "/tmp/raft_tpu_bench_corpus.fbin"
    host = np.asarray(data[:rows])
    bds.write_fbin(path, host)
    back = bds.read_fbin(path)
    ok = back.shape == host.shape and bool(np.array_equal(back, host))
    os.remove(path)
    return {"fbin_roundtrip_rows": rows, "ok": ok}


# the probe compiles EXACTLY the ground-truth program shape (same matmul
# engine, same workspace chunking) so a persistent-cache hit in the
# parent is possible and memory behavior matches the real path
_PART_PROBE_SRC = """
import os, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from raft_tpu.neighbors import brute_force
n = int(os.environ.get("RAFT_TPU_PROBE_N", "500000"))
d, nq = 128, 1000
k1, k2 = jax.random.split(jax.random.PRNGKey(99))
data = jax.random.normal(k1, (n, d), jnp.float32)
q = jax.random.normal(k2, (nq, d), jnp.float32)
jax.block_until_ready((data, q))
print("PROBE_INIT_OK", flush=True)   # backend init + device alloc worked
bfi = brute_force.build(data)
fn = jax.jit(lambda qq, idx: brute_force.search(idx, qq, 10,
                                                algo="matmul")[1])
jax.block_until_ready(fn(q, bfi))
print("PART_PROBE_OK")
""".format(repo=os.path.dirname(os.path.abspath(__file__)))


def probe_part_compile(timeout_s: float = 450.0, n: int = 500_000) -> bool:
    """Compile+run the 500k part-shape search program in a KILLABLE
    subprocess (an in-process deadline cannot interrupt a blocked
    compile). The full (1M) scale only ever compiles 500k-part programs,
    so this one probe bounds the go/no-go decision for both full and mid
    scales."""
    import subprocess

    env = dict(os.environ)
    env["RAFT_TPU_PROBE_N"] = str(n)
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PART_PROBE_SRC],
            timeout=timeout_s, capture_output=True, text=True, env=env)
    except subprocess.TimeoutExpired:
        log(f"# {n}-part compile probe exceeded {timeout_s:.0f}s "
            "(hung compile endpoint); downscaling")
        return False
    if r.returncode == 0 and "PART_PROBE_OK" in r.stdout:
        return True
    err = (r.stderr or "").strip()
    log(f"# {n}-part compile probe rc={r.returncode}: {err[-300:]}")
    if "PROBE_INIT_OK" not in (r.stdout or ""):
        # the child never got past backend init (import error, device
        # exclusively held, ...): says nothing about the program's
        # compile viability — keep the scale
        log("# probe failed before backend init completed; keeping scale")
        return True
    return False


def preflight_scale(default: str = "full", limit_s: float = 120.0,
                    probe_timeout_s: float = 450.0) -> str:
    """Backend health probe: a fresh tiny compile+run takes ~1-40s on a
    healthy chip. Tunneled backends degrade by orders of magnitude under
    shared load; recording a smaller result beats timing out and
    recording nothing. The two-part design means only the 500k part
    shape ever compiles — measured 2026-07-31: 500k compiles+runs in
    ~134s where a 1M program hangs >600s."""
    t0 = time.perf_counter()
    try:
        x = jax.random.normal(jax.random.PRNGKey(99), (512, 512))
        jax.block_until_ready(jax.jit(lambda a: (a @ a).sum())(x))
        probe_s = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001
        log(f"# pre-flight probe failed ({type(e).__name__}); downscaling")
        probe_s = float("inf")
    if probe_s > limit_s:
        log(f"# pre-flight probe took {probe_s:.0f}s: degraded backend, "
            "downscaling corpus to 100k")
        return "small"
    if default in ("full", "mid"):
        if probe_part_compile(probe_timeout_s):
            return default
        return "small"
    return default


class TwoPart:
    """Search a corpus split into equal-shape parts with ONE compiled
    executable, merging per-part top-k exactly. ``search_jit`` must be a
    jitted (queries, index, *extra) -> (dist, ids) callable with
    part-local ids; ``offsets`` map part-local ids to global; ``extras``
    optionally zips additional per-part jit arguments (e.g. a bf16 refine
    corpus). Indexes ride as jit ARGUMENTS, never closures — baked index
    constants exceed the tunnel's remote-compile request limit (observed
    HTTP 413 at 500k rows) — and ``fresh_executable`` keeps that true on
    ops.autotune's plausibility-floor re-measure path."""

    def __init__(self, search_jit, indexes, offsets, k, extras=None):
        from raft_tpu.neighbors import brute_force as _bf

        self.search_jit = search_jit
        self.indexes = indexes
        self.offsets = offsets
        self.extras = extras or [()] * len(indexes)
        self._merge = jax.jit(
            lambda d, i: _bf.knn_merge_parts(d, i, True))
        self.k = k

    def __call__(self, q, *_):
        ds, is_ = [], []
        for idx, off, extra in zip(self.indexes, self.offsets, self.extras):
            d, i = self.search_jit(q, idx, *extra)
            ds.append(d[:, : self.k])
            is_.append(jnp.where(i[:, : self.k] >= 0,
                                 i[:, : self.k] + off, -1))
        if len(ds) == 1:
            return ds[0], is_[0]
        return self._merge(jnp.stack(ds), jnp.stack(is_))

    def fresh_executable(self):
        inner = self.search_jit
        fresh = TwoPart(jax.jit(lambda q, idx, *e: inner(q, idx, *e)),
                        self.indexes, self.offsets, self.k, self.extras)
        return fresh


def store_bytes_of(indexes) -> dict:
    """{store_bytes, bytes_per_vector} for an index or list of part
    indexes, via the memz decomposition (serve/quality.device_bytes) —
    the storage-ladder evidence block recorded on cagra/ivf entries
    (ISSUE 13). Host-streamed indexes divide by ALL answered rows (cold
    included), so the number IS the rung's capacity claim."""
    from raft_tpu.serve import quality as _q

    idxs = indexes if isinstance(indexes, (list, tuple)) else [indexes]
    reps = [_q.device_bytes(ix) for ix in idxs]
    total = sum(r["total_device_bytes"] for r in reps)
    rows = sum(int(r.get("n_total") or r["n"]) for r in reps)
    return {"store_bytes": total,
            "bytes_per_vector": round(total / max(rows, 1), 2)}


def run_storage_ladder(lad_n: int, d: int, nq: int = 1000, k: int = 10,
                       out_json: str = None, graph_degree: int = 32,
                       hbm_budget_frac: float = 0.5) -> list:
    """Storage-ladder capacity rung (ROADMAP "Scale ladder, rung 1"):
    one corpus at ``lad_n`` rows, every cagra edge-store rung
    (int8 → int4 → pq) measured at fixed k with the exact-refine
    recipe, then the ivf_flat HBM-resident vs host-streamed
    decomposition under an HBM budget of ``hbm_budget_frac`` of the
    resident store. Each entry records ``store_bytes``,
    ``bytes_per_vector`` and the ratio vs the int8 rung — the
    ladder's capacity claims as bench artifacts, not README math.

    Standalone so the 10M TPU run and the CPU-gated proxy
    (``RAFT_TPU_BENCH_LADDER_N``) share one code path; ``main()`` wires
    it behind RAFT_TPU_BENCH_LADDER."""
    from raft_tpu.neighbors import (brute_force, cagra, ivf_flat,
                                    refine as refine_mod)

    entries = []
    t0 = time.perf_counter()
    data, queries = make_corpus(lad_n, d, nq, seed=21)
    qj = jnp.asarray(queries)
    # exact GT through the parted brute path (compile-cap safe at 10M)
    gt = jnp.asarray(np.argsort(
        (queries**2).sum(1)[:, None] - 2.0 * queries @ data[:100_000].T
        + (data[:100_000]**2).sum(1)[None, :],
        axis=1)[:, :k]) if lad_n <= 100_000 else None
    if gt is None:
        part_cap = 500_000
        parts = [data[i:i + part_cap] for i in range(0, lad_n, part_cap)]
        bfs = [brute_force.build(p) for p in parts]
        fn = jax.jit(lambda q, ix: brute_force.search(ix, q, k,
                                                      algo="matmul"))
        tp = TwoPart(fn, bfs,
                     [i * part_cap for i in range(len(parts))], k)
        gt = robust_call(lambda: tp(qj)[1], "ladder gt")
        del bfs
    log(f"# ladder corpus {lad_n}x{d} + gt in "
        f"{time.perf_counter() - t0:.0f}s")

    t0 = time.perf_counter()
    ci = robust_call(lambda: cagra.build(data, cagra.IndexParams(
        graph_degree=graph_degree,
        intermediate_graph_degree=graph_degree + graph_degree // 2,
        seed=0)), "ladder cagra build", tries=1)
    build_s = time.perf_counter() - t0
    log(f"# ladder cagra built in {build_s:.0f}s")
    dj = jnp.asarray(data)
    itopk = max(64, 4 * k)
    sp = cagra.SearchParams(itopk_size=itopk, search_width=2,
                            max_iterations=10)

    def refined(qs):
        _, cand = cagra.search(ci, qs, itopk, sp, engine="edge")
        return refine_mod.refine(dj, qs, cand, k)

    rung_bytes = {}
    for rung in ("int8", "int4", "pq"):
        ci.__dict__.pop("_edge_store", None)
        t0 = time.perf_counter()
        robust_call(lambda r=rung: cagra.prepare_traversal(ci, r),
                    f"ladder prepare {rung}", tries=1)
        prep_s = time.perf_counter() - t0
        sb = store_bytes_of(ci)
        ev = ci._edge_store[1]
        rung_bytes[rung] = int(ev.size * ev.dtype.itemsize)
        thr = median_time(lambda: jax.block_until_ready(
            refined(qj)), reps=3)
        rec = robust_call(lambda: device_recall(refined(qj)[1], gt),
                          f"ladder {rung} recall")
        e = {"algo": "storage_ladder",
             "name": f"storage_ladder.cagra.deg{graph_degree}.{rung}",
             "qps": round(nq / thr, 1) if thr else None,
             "latency_ms": None,
             "recall": round(float(rec), 4),
             "build_s": round(build_s + prep_s, 1),
             "corpus_n": lad_n, "engine": "edge",
             "edge_store_bytes": rung_bytes[rung],
             "edge_bytes_per_vector": round(rung_bytes[rung] / lad_n, 2),
             **sb}
        if "int8" in rung_bytes:
            e["edge_bytes_vs_int8"] = round(
                rung_bytes["int8"] / max(rung_bytes[rung], 1), 2)
        entries.append(e)
        log(f"#   {e['name']}: qps={e['qps']} recall={rec:.4f} "
            f"edge store {rung_bytes[rung]:,}B "
            f"({e.get('edge_bytes_vs_int8', 1.0)}x under int8)")
    ci.__dict__.pop("_edge_store", None)

    # ivf_flat: resident vs host-streamed under an HBM budget
    n_lists = max(64, min(8192, int(np.sqrt(lad_n) * 3)))
    fi = robust_call(lambda: ivf_flat.build(
        data, ivf_flat.IndexParams(n_lists=n_lists, seed=0)),
        "ladder ivf build", tries=1)
    ivf_flat.prepare_scan(fi)
    spf = ivf_flat.SearchParams(n_probes=max(8, n_lists // 50))
    res_bytes = store_bytes_of(fi)
    t_res = median_time(lambda: jax.block_until_ready(
        ivf_flat.search(fi, qj, k, spf, algo="pallas")), reps=3)
    rec_res = robust_call(lambda: device_recall(
        ivf_flat.search(fi, qj, k, spf, algo="pallas")[1], gt),
        "ladder ivf resident recall")
    # budget against the RAW list rows (what the planner admits), not
    # the memz total (which counts scan caches the tier doesn't move)
    budget_gb = lad_n * (d * 4 + 8) * hbm_budget_frac / (1 << 30)
    ivf_flat.prepare_host_stream(fi, budget_gb=budget_gb,
                                 sample_queries=queries[:256])
    tier = getattr(fi, "_host_tier", None)
    t_hs = median_time(lambda: jax.block_until_ready(
        ivf_flat.search(fi, qj, k, spf, algo="pallas")), reps=3)
    rec_hs = robust_call(lambda: device_recall(
        ivf_flat.search(fi, qj, k, spf, algo="pallas")[1], gt),
        "ladder ivf streamed recall")
    hs_bytes = store_bytes_of(fi)
    entries.append({
        "algo": "storage_ladder",
        "name": f"storage_ladder.ivf_flat.nlist{n_lists}.host_stream",
        "qps": round(nq / t_hs, 1) if t_hs else None, "latency_ms": None,
        "recall": round(float(rec_hs), 4), "build_s": 0.0,
        "corpus_n": lad_n, "hbm_budget_gb": round(budget_gb, 3),
        # the HBM-resident vs host-streamed decomposition the ROADMAP
        # bench gate asks for: where the bytes sit, what PCIe moved,
        # and what the split cost at fixed probes
        "decomposition": {
            "resident_qps": round(nq / t_res, 1) if t_res else None,
            "resident_recall": round(float(rec_res), 4),
            "resident_store_bytes": res_bytes["store_bytes"],
            "streamed_device_bytes": hs_bytes["store_bytes"],
            "host_tier": tier.snapshot() if tier is not None else None,
        },
        **hs_bytes})
    log(f"#   host_stream: resident {res_bytes['store_bytes']:,}B -> "
        f"device {hs_bytes['store_bytes']:,}B + host tier; streamed "
        f"recall {rec_hs:.4f} (resident {rec_res:.4f}) at "
        f"{budget_gb:.3f} GB budget")

    if out_json:
        payload = {"schema": "raft_tpu_bench_v1", "lane": "storage_ladder",
                   "n": lad_n, "d": d, "entries": entries}
        os.makedirs(os.path.dirname(out_json) or ".", exist_ok=True)
        tmp = out_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, out_json)
        log(f"# ladder artifact -> {out_json}")
    return entries


def run_fleet_ladder(n: int, d: int, nq: int = 256, k: int = 10,
                     out_json: str = None, hosts: int = 2, devs: int = 2,
                     hbm_budget_frac: float = 0.5) -> list:
    """Fleet storage-ladder rung (ISSUE 19 / docs/mnmg.md "Per-host
    storage tiers"): one virtual ``hosts × devs`` fleet, every
    ``FLEET_STORE_RUNGS`` rung built under a per-host HBM budget of
    ``hbm_budget_frac`` × the f32 resident rows, measured end-to-end
    through :meth:`Fleet.search` (resident + host-streamed cold lists).
    Each entry records rows/host, device bytes/host (budgeted AND
    unbudgeted-resident), host-tier bytes/host, recall, and the bytes
    ratio vs the float32 rung — the per-host capacity claims as
    artifacts, not README math. Exact rungs (float32/int8/int4)
    additionally assert bit-parity against their unbudgeted build: a
    capacity number from a build that changed the answers would be
    worthless. Run with ``d >= 64``: below that the int4 rung's 64-byte
    sublane-pair padding (``quant.int4_half_width``) dominates and the
    ladder is not byte-monotone."""
    from raft_tpu.neighbors import ivf_flat, ivf_pq
    from raft_tpu.parallel import fleet as fleet_mod
    from raft_tpu.serve import quality as _q

    fl = fleet_mod.Fleet.virtual(hosts, devs)
    data, queries = make_corpus(n, d, nq, seed=23)
    data = np.asarray(data, np.float32)       # host packing wants numpy
    queries = np.asarray(queries, np.float32)
    qj = jnp.asarray(queries)
    gt = np.argsort(
        (queries ** 2).sum(1)[:, None] - 2.0 * queries @ data.T
        + (data ** 2).sum(1)[None, :], axis=1)[:, :k]

    n_lists = max(8, min(256, int(np.sqrt(n))))
    pq_dim = max(4, d // 4)
    # pq_bits=4: the edge-store books (16 entries/subspace). At bench
    # corpus sizes an 8-bit book is a ~400 KB fixed cost that swamps the
    # codes and would make the per-host capacity ratio measure the
    # quantizer, not the ladder; at fleet corpus sizes it amortizes away.
    p0 = ivf_pq.IndexParams(n_lists=n_lists, pq_dim=pq_dim, pq_bits=4,
                            seed=0)
    n_probes = max(4, n_lists // 8)
    rows_host = -(-n // hosts)
    budget_b = int(rows_host * fleet_mod.store_row_bytes("float32", d)
                   * hbm_budget_frac)

    def host_recall(ids):
        ids = np.asarray(ids)
        return float(np.mean([len(set(ids[m]) & set(gt[m])) / k
                              for m in range(nq)]))

    def per_host_bytes(idx):
        rep = _q.device_bytes(idx)
        return (int(rep["total_device_bytes"]) // fl.n_shards
                * fl.topology.devs_per_host)

    entries = []
    f32_bytes_host = f32_resident_host = None
    for rung in fleet_mod.FLEET_STORE_RUNGS:
        sp = (ivf_pq.SearchParams(n_probes=n_probes) if rung == "pq"
              else ivf_flat.SearchParams(n_probes=n_probes))
        idx0 = robust_call(lambda r=rung: fl.build_ivf_pq(
            data, p0, store_dtype=r), f"fleet ladder {rung} build",
            tries=1)
        d0, i0, _ = fl.search(idx0, qj, k, sp)
        bytes0_host = per_host_bytes(idx0)
        idx = robust_call(lambda r=rung: fl.build_ivf_pq(
            data, p0, store_dtype=r, hbm_budget_gb=budget_b / (1 << 30),
            sample_queries=queries), f"fleet ladder {rung} budgeted",
            tries=1)
        d1, i1, _ = fl.search(idx, qj, k, sp)
        if rung != "pq":
            assert (np.array_equal(np.asarray(d0), np.asarray(d1))
                    and np.array_equal(np.asarray(i0), np.asarray(i1))), \
                f"budgeted {rung} diverged from unbudgeted build"
        thr = median_time(lambda: jax.block_until_ready(
            fl.search(idx, qj, k, sp)[0]), reps=3)
        bytes_host = per_host_bytes(idx)
        tier_host = max(
            (sum(int(idx._fleet_tiers[s].host_bytes)
                 for s in fl.topology.shards_of(h)
                 if s in idx._fleet_tiers) for h in range(hosts)),
            default=0)
        cold = {h: int((~m).sum())
                for h, m in idx._fleet_ctx["hot"].items()}
        if rung == "float32":
            f32_bytes_host = bytes_host
            f32_resident_host = bytes0_host
        e = {"algo": "fleet_ladder",
             "name": f"fleet_ladder.{hosts}x{devs}.{rung}",
             "qps": round(nq / thr, 1) if thr else None,
             "latency_ms": None,
             "recall": round(host_recall(i1), 4),
             "recall_unbudgeted": round(host_recall(i0), 4),
             "build_s": 0.0, "corpus_n": n,
             "store": rung, "topology": f"{hosts}x{devs}",
             "rows_per_host": rows_host,
             "device_bytes_per_host": bytes_host,
             "device_bytes_per_host_unbudgeted": bytes0_host,
             "host_tier_bytes_per_host": tier_host,
             "bytes_per_vector": round(bytes_host / rows_host, 2),
             "hbm_budget_bytes_per_host": budget_b,
             "cold_lists_per_host": cold,
             "bitwise_vs_unbudgeted": rung != "pq"}
        if f32_bytes_host:
            e["bytes_vs_float32"] = round(
                bytes_host / max(f32_bytes_host, 1), 4)
            # the ISSUE acceptance ratio: budgeted bytes vs the FULLY
            # RESIDENT f32 build (what an unladdered fleet would hold)
            e["bytes_vs_float32_resident"] = round(
                bytes_host / max(f32_resident_host, 1), 4)
        entries.append(e)
        log(f"#   {e['name']}: qps={e['qps']} recall={e['recall']} "
            f"bytes/host {bytes_host:,} "
            f"({e.get('bytes_vs_float32', 1.0)}x of f32) "
            f"cold={cold}")

    if out_json:
        payload = {"schema": "raft_tpu_bench_v1", "lane": "fleet_ladder",
                   "n": n, "d": d, "topology": f"{hosts}x{devs}",
                   "hbm_budget_bytes_per_host": budget_b,
                   "entries": entries}
        os.makedirs(os.path.dirname(out_json) or ".", exist_ok=True)
        tmp = out_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, out_json)
        log(f"# fleet ladder artifact -> {out_json}")
    return entries


def run_filter_sweep(n: int, d: int, nq: int = 100, k: int = 10,
                     out_json: str = None) -> list:
    """Filtered-search selectivity sweep (docs/perf.md "Filtered
    search"): at each filtered-out fraction × family, measure the
    ADAPTIVE policy (survivor-aware pruning + auto-widening +
    survivor-brute crossover — the defaults) against the FIXED policy
    (widen ladder pinned to level 1, crossover disabled), recording
    recall against the exact filtered oracle, p50 batch latency, the
    decision the policy took (widen level, effective probes, lists
    pruned, crossover routing) and the measured scan-vs-brute race
    verdict under the selectivity-bucketed autotune key. The summary
    block carries the acceptance verdicts: at 99.9% filtered-out the
    adaptive policy must hold ≥0.95× the family's unfiltered recall
    where the fixed policy collapses, and the survivor-brute must beat
    the widened scan. Standalone; ``main()`` wires it behind
    RAFT_TPU_BENCH_FILTER."""
    from raft_tpu.core.bitset import Bitset
    from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq
    from raft_tpu.ops import filter_policy

    data, queries = make_corpus(n, d, nq, seed=33)
    X, Q = np.asarray(data), np.asarray(queries)
    qj = jnp.asarray(queries)
    rng = np.random.default_rng(51)

    def oracle(mask):
        """Exact filtered top-k ids, -1-padded past the survivor count."""
        ids = np.nonzero(mask)[0]
        sub = X[ids]
        dd = ((Q ** 2).sum(1)[:, None] + (sub ** 2).sum(1)[None, :]
              - 2.0 * Q @ sub.T)
        order = np.argsort(dd, axis=1, kind="stable")[:, :min(k, ids.size)]
        out = np.full((nq, k), -1, np.int64)
        out[:, :order.shape[1]] = ids[order]
        return out

    def recall_of(found, want):
        found = np.asarray(found)
        hits = sum(len(set(found[i][found[i] >= 0].tolist())
                       & set(want[i][want[i] >= 0].tolist()))
                   for i in range(found.shape[0]))
        return hits / max(int((want >= 0).sum()), 1)

    def with_env(tmp, fn):
        old = {kk: os.environ.get(kk) for kk in tmp}
        os.environ.update(tmp)
        try:
            return fn()
        finally:
            for kk, vv in old.items():
                if vv is None:
                    os.environ.pop(kk, None)
                else:
                    os.environ[kk] = vv

    gt = oracle(np.ones(n, bool))
    n_probes = 8
    fi = robust_call(lambda: ivf_flat.build(
        data, ivf_flat.IndexParams(n_lists=64, seed=0)),
        "filter ivf_flat build", tries=1)
    pi = robust_call(lambda: ivf_pq.build(
        data, ivf_pq.IndexParams(n_lists=64, pq_dim=16, seed=0)),
        "filter ivf_pq build", tries=1)
    ci = robust_call(lambda: cagra.build(data, cagra.IndexParams(
        graph_degree=32, intermediate_graph_degree=48, seed=0)),
        "filter cagra build", tries=1)
    spf = ivf_flat.SearchParams(n_probes=n_probes)
    spp = ivf_pq.SearchParams(n_probes=n_probes)
    spc = cagra.SearchParams(itopk_size=max(64, 4 * k))
    fams = {
        "ivf_flat": lambda f: ivf_flat.search(fi, qj, k, spf, filter=f),
        "ivf_pq": lambda f: ivf_pq.search(pi, qj, k, spp, filter=f),
        "cagra": lambda f: cagra.search(ci, qj, k, spc, filter=f),
    }
    brutes = {
        "ivf_flat": lambda f: filter_policy.survivor_brute_ivf(
            fi, ivf_flat.reconstruct, qj, k, f),
        "ivf_pq": lambda f: filter_policy.survivor_brute_ivf(
            pi, ivf_pq.reconstruct, qj, k, f),
        "cagra": lambda f: filter_policy.survivor_brute_dense(
            ci.dataset, ci.metric, qj, k, f),
    }
    unfiltered = {fam: round(recall_of(fn(None)[1], gt), 4)
                  for fam, fn in fams.items()}
    log(f"# filter sweep {n}x{d} nq={nq} k={k}; unfiltered recall "
        + " ".join(f"{f}={r}" for f, r in unfiltered.items()))

    FIXED = {"RAFT_TPU_FILTER_WIDEN_MAX": "1",
             "RAFT_TPU_FILTER_BRUTE_MAX": "0"}
    SCAN_ONLY = {"RAFT_TPU_FILTER_BRUTE_MAX": "0"}
    entries, extreme = [], {}
    for frac_out in (0.5, 0.9, 0.99, 0.999):
        surv_n = max(k, int(round(n * (1.0 - frac_out))))
        mask = np.zeros(n, bool)
        mask[rng.choice(n, surv_n, replace=False)] = True
        want = oracle(mask)
        selectivity = surv_n / n
        for fam, fn in fams.items():
            bs = Bitset.from_mask(jnp.asarray(mask))
            if fam == "cagra":
                fd = filter_policy.decide_graph(bs, n, d, k)
            else:
                fd = filter_policy.decide_ivf(
                    fi if fam == "ivf_flat" else pi, bs, n_probes, k, fam)
            t_ad = median_time(lambda: jax.block_until_ready(
                fn(bs)[1]), reps=3)
            r_ad = recall_of(fn(bs)[1], want)
            t_fx = with_env(FIXED, lambda: median_time(
                lambda: jax.block_until_ready(fn(bs)[1]), reps=3))
            r_fx = with_env(FIXED, lambda: recall_of(fn(bs)[1], want))
            # race the widened scan vs the compacted brute under the
            # bucketed key — the recorded winner steers later filtered
            # calls in this selectivity decade
            _key, winner, timings = filter_policy.tune_crossover(
                fam, n, d, k, selectivity,
                lambda: with_env(SCAN_ONLY, lambda: fn(bs)[1]),
                lambda: brutes[fam](bs)[1], reps=2)
            e = {"algo": "filter_sweep",
                 "name": f"filter_sweep.{fam}.out{frac_out}",
                 "family": fam, "filtered_out": frac_out,
                 "selectivity": round(selectivity, 6),
                 "survivors": surv_n,
                 "qps": round(nq / t_ad, 1) if t_ad else None,
                 "latency_ms": round(t_ad * 1e3, 2) if t_ad else None,
                 "recall": round(r_ad, 4),
                 "unfiltered_recall": unfiltered[fam],
                 "widen_level": fd.level,
                 "effective_probes": fd.n_probes or None,
                 "lists_pruned": fd.lists_pruned or None,
                 "crossover": bool(fd.use_brute),
                 "fixed_policy": {
                     "recall": round(r_fx, 4),
                     "latency_ms": round(t_fx * 1e3, 2) if t_fx else None},
                 "race": {"winner": winner,
                          "scan_s": round(timings.get("scan", 0), 4),
                          "brute_s": round(timings.get("brute", 0), 4)}}
            entries.append(e)
            if frac_out == 0.999:
                extreme[fam] = e
            log(f"#   {e['name']}: adaptive recall={r_ad:.4f} "
                f"({t_ad * 1e3:.1f}ms, level={fd.level} "
                f"pruned={fd.lists_pruned} brute={fd.use_brute}) "
                f"fixed recall={r_fx:.4f} ({t_fx * 1e3:.1f}ms) "
                f"race->{winner}")

    summary = {fam: {
        "adaptive_holds": e["recall"] >= 0.95 * e["unfiltered_recall"],
        "fixed_collapses": e["fixed_policy"]["recall"]
        < 0.95 * e["unfiltered_recall"],
        "brute_beats_scan": e["race"]["brute_s"] < e["race"]["scan_s"],
    } for fam, e in extreme.items()}
    for fam, v in summary.items():
        log(f"#   extreme-point verdict {fam}: {v}")

    if out_json:
        payload = {"schema": "raft_tpu_bench_v1", "lane": "filter_sweep",
                   "n": n, "d": d, "nq": nq, "k": k,
                   "unfiltered_recall": unfiltered,
                   "extreme_point_verdicts": summary,
                   "entries": entries}
        os.makedirs(os.path.dirname(out_json) or ".", exist_ok=True)
        tmp = out_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, out_json)
        log(f"# filter sweep artifact -> {out_json}")
    return entries


def main():
    t_wall0 = time.perf_counter()
    budget_s = float(os.environ.get("RAFT_TPU_BENCH_BUDGET_S", "2400"))
    scale_env = os.environ.get("RAFT_TPU_BENCH_SCALE")
    scale = scale_env or "full"
    if scale_env is None:
        scale = preflight_scale(
            "full", probe_timeout_s=min(450.0, 0.2 * budget_s))
    budget_s = max(600.0, budget_s - (time.perf_counter() - t_wall0))
    t_start = time.perf_counter()
    # micro: CPU-runnable harness smoke; small: single-chip quick run;
    # mid: one 500k part; full: the BASELINE 1M scale as two 500k parts
    n = {"full": 1_000_000, "mid": 500_000, "small": 100_000,
         "micro": 20_000}[scale]
    part_n = min(n, 500_000)
    n = (n // part_n) * part_n
    n_parts = n // part_n
    d, nq, k = 128, 10_000 if scale != "micro" else 1_000, 10
    # plausibility floor: tunnel dispatch alone is ~1 ms, and observed
    # replay-mode lies are ~50 us
    suspect_floor = 0.001 if scale == "micro" else 0.002

    from raft_tpu.bench import roofline
    from raft_tpu.ops import autotune as _autotune
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq, refine

    log(f"# corpus: {n}x{d} ({n_parts} part(s) of {part_n}), {nq} queries, "
        f"k={k}, mixture scale {CORPUS_SCALE}")
    data, queries = robust_call(lambda: make_corpus(n, d, nq), "corpus")
    parts = [data[i * part_n : (i + 1) * part_n] for i in range(n_parts)]
    offsets = [i * part_n for i in range(n_parts)]

    # ground truth: exact search over each part with one shared
    # executable, exact cross-part merge; query chunks give retries a
    # small failure unit
    # one jit object shared by the main GT stage and the capacity lane:
    # both search (1000, d) query chunks against 500k-part indexes, so
    # the capacity lane's ground truth is a cache hit, not a recompile
    gt_search_jit = jax.jit(lambda q, idx: brute_force.search(
        idx, q, k, algo="matmul"))

    def compute_gt():
        bfs = [brute_force.build(p, metric="sqeuclidean") for p in parts]
        tp = TwoPart(gt_search_jit, bfs, offsets, k)
        gchunk = 1000
        gt_deadline = t_start + 0.35 * budget_s
        big = part_n > 100_000
        parts_out = []
        for c0 in range(0, nq, gchunk):
            if big and time.perf_counter() > gt_deadline:
                raise RuntimeError(
                    f"ground truth stage deadline exceeded at [{c0}]")
            parts_out.append(robust_call(
                lambda c0=c0: jax.block_until_ready(
                    tp(queries[c0 : c0 + gchunk])[1]),
                f"ground truth [{c0}:{c0 + gchunk}]", tries=5,
                deadline=gt_deadline if big else 0.0))
        return bfs, jnp.concatenate(parts_out)

    try:
        bfs, gt = compute_gt()
    except Exception as e:  # noqa: BLE001
        if n <= 100_000:
            raise
        log(f"# part-scale ground truth failed ({type(e).__name__}): "
            "regenerating a 100k corpus and continuing")
        n = part_n = 100_000
        n_parts, scale = 1, "small"
        data, queries = robust_call(lambda: make_corpus(n, d, nq), "corpus")
        parts, offsets = [data], [0]
        bfs, gt = compute_gt()
    log("# ground truth done")
    gt_elapsed = time.perf_counter() - t_start
    hurry = gt_elapsed > budget_s / 6
    if hurry:
        log(f"# slow backend (corpus+GT took {gt_elapsed:.0f}s): "
            "trimming sweeps")

    entries = []

    def add_entry(algo, name, dt_thr, dt_lat, recall, build_s, extra=None,
                  batch=None, baseline_key="algo"):
        """``baseline_key``: "algo" (default) normalizes vs_baseline by the
        algo's 1M-lane reference QPS; None omits the ratio — entries whose
        corpus shape doesn't match the baseline derivation (the 2M
        capacity lane) must not report an apples-to-oranges number."""
        qps = (batch or nq) / dt_thr if dt_thr else 0.0
        e = {"algo": algo, "name": name, "qps": round(qps, 1),
             "latency_ms": round(dt_lat * 1e3, 1) if dt_lat else -1.0,
             "recall": round(recall, 4), "build_s": round(build_s, 1)}
        if baseline_key is not None:
            key = algo if baseline_key == "algo" else baseline_key
            e["vs_baseline"] = round(qps / BASELINE_QPS[key], 3)
        if extra:
            e.update(extra)
        entries.append(e)
        log(f"#   {name}: qps={qps:,.0f} (lat "
            f"{e['latency_ms']}ms) recall={recall:.4f}")
        return e

    # physically-derived per-lane plausibility floors (seconds/call): the
    # generic ~2 ms floor misses lies that land just above it (observed:
    # a "2.49 ms" 500k brute-force batch = 514 TFLOP/s, then a "4.0 ms"
    # 1M batch = 640 TFLOP/s after a 2x-peak floor — the lying window
    # scales its answers). Floors are therefore the DATASHEET peaks
    # themselves (v5e: 197 TFLOP/s bf16, 819 GB/s HBM): no real call can
    # beat them. The r5 slope-fit roofline (raft_tpu/bench/roofline.py)
    # reads ~657 GB/s stream / ~175 TFLOP/s bf16 — 80-89% of datasheet —
    # so honest timings sit a modest but real margin above these floors.
    def floor_brute():
        return max(suspect_floor, 2.0 * nq * n * d / 197e12)

    def floor_ivf(probes, row_bytes):
        # the query-grouped scan DMAs each probed list ONCE per 128-query
        # group (ops/ivf_scan.py pack_pairs), so kernel traffic scales
        # with (pairs/128) list windows — NOT per-query row counts; a
        # per-query model here once rejected an honest 92 ms measurement
        # with a 122 ms "floor"
        groups = nq * probes / 128.0
        window_rows = 1.5 * (part_n / 1024)   # imbalance slack
        scanned = groups * window_rows * row_bytes * n_parts
        return max(suspect_floor, scanned / 819e9)

    def floor_ivf_for(probes, row_bytes, batch_q, parts):
        """floor_ivf generalized to another corpus shape (the capacity
        lane): same scan-traffic model, same suspect_floor clamp."""
        groups = batch_q * probes / 128.0
        scanned = groups * 1.5 * (part_n / 1024) * row_bytes * parts
        return max(suspect_floor, scanned / 819e9)

    def measure_wall(tp, *args, floor=0.0, what="", calls: int = 10,
                     qset=None):
        """THE throughput measurement: pipelined, content-distinct,
        value-read wall.

        ``calls`` query sets with genuinely different CONTENT
        (device-side permutations) are dispatched back-to-back (no
        per-call blocking — dispatch overlaps compute, GBench
        items_per_second semantics), every call's output feeds a scalar
        accumulator, and the window closes with a host-side ``float()``
        of that accumulator. The value read is the load-bearing part:
        this backend's lying modes extend to READINESS itself
        (block_until_ready returned in 0.8 ms for a 2.56 TFLOP batch
        even on content-distinct inputs), and a host value transitively
        dependent on every output cannot materialize before the compute
        actually ran. The single read's round trip amortizes over
        ``calls``. Results below the lane's physical floor are
        discarded — no honest number exists in that window."""
        qs = queries if qset is None else qset
        try:
            # calls+1 permutations: the warm-up runs on a THROWAWAY set so
            # no timed call repeats content the backend has already served
            perms = [jnp.take(qs,
                              jax.random.permutation(
                                  jax.random.PRNGKey(100 + i), qs.shape[0]),
                              axis=0)
                     for i in range(calls + 1)]
            jax.block_until_ready(perms)
            d0 = tp(perms.pop(), *args[1:])[0]      # warm/compile
            float(jnp.sum(jnp.where(jnp.isfinite(d0[:, 0]), d0[:, 0], 0.0)))
            t0 = time.perf_counter()
            acc = None
            for p in perms:
                d = tp(p, *args[1:])[0]
                s = jnp.sum(jnp.where(jnp.isfinite(d[:, 0]), d[:, 0], 0.0))
                acc = s if acc is None else acc + s
            _ = float(acc)                          # forced value read
            dt = (time.perf_counter() - t0) / calls
        except Exception as e:  # noqa: BLE001
            log(f"# {what} wall measurement failed: "
                f"{type(e).__name__}: {e}")
            return None
        if dt < floor:
            log(f"# {what} wall {dt*1e3:.1f}ms below the physical floor "
                f"{floor*1e3:.1f}ms; lane unmeasurable in this window")
            return None
        return dt

    def measure_tp(tp, *args, reps=5, floor=None, what="", qset=None):
        """(throughput s/call, latency s/call). Throughput is the
        value-read pipelined wall; latency is the per-call-blocked
        median (reported for context, dropped when the window lies)."""
        floor = suspect_floor if floor is None else floor
        lat = median_time(tp, *args, reps=reps, floor=floor)
        thr = measure_wall(tp, *args, floor=floor, what=what, qset=qset)
        return thr, lat

    # --- brute force (BASELINE config 1): measured-best engine ----------
    with algo_section('brute_force'):
        winner, timings = robust_call(
            lambda: brute_force.tune_search(bfs[0], queries, k, reps=3,
                                            suspect_floor_s=suspect_floor),
            "engine autotune")

        # per-engine decomposition: WHY the headline moved, not just that
        # it did. gemm_only times the bare distance GEMM (no select) on
        # one part; select_overhead is the GEMM engine's select cost on
        # top of it; fused_tflops is the fused engine's sustained rate
        # from the same race reps. All rates are per-part (scale-free).
        decomp = {}
        try:
            flops_part = 2.0 * nq * part_n * d

            def _gemm_only(qq, idx):
                dot = jax.lax.dot_general(
                    qq, idx.dataset, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision("highest"))
                return jnp.sum(jnp.where(jnp.isfinite(dot), dot, 0.0))

            g_s = _autotune.measure(
                jax.jit(_gemm_only), queries, bfs[0], reps=3,
                suspect_floor_s=max(suspect_floor, flops_part / 197e12),
                value_read=True)
            decomp["gemm_only_tflops"] = round(flops_part / g_s / 1e12, 2)
            if timings.get("matmul"):
                decomp["select_overhead_ms"] = round(
                    (timings["matmul"] - g_s) * 1e3, 2)
            if timings.get("pallas"):
                decomp["fused_tflops"] = round(
                    flops_part / timings["pallas"] / 1e12, 2)
        except Exception as e:  # noqa: BLE001 - diagnostics must not
            log(f"# brute decomposition probe failed: "  # cost the lane
                f"{type(e).__name__}: {e}")

        sfn = jax.jit(lambda q, idx: brute_force.search(idx, q, k,
                                                        algo=winner))
        tp = TwoPart(sfn, bfs, offsets, k)
        thr, lat = measure_tp(tp, queries, floor=floor_brute(),
                              what="brute f32")
        if thr is not None:
            add_entry("raft_brute_force", f"raft_brute_force.{winner}",
                      thr, lat, 1.0, 0.0,
                      {"engine_timings_ms":
                       {kk: round(v * 1e3, 1) for kk, v in timings.items()},
                       "decomposition": decomp})
        # bf16 storage: half the scan HBM traffic; recall measured
        # against the f32 ground truth. Skipped in hurry mode.
        if not hurry:
            bf16s = robust_call(
                lambda: [brute_force.build(p, dtype=jnp.bfloat16)
                         for p in parts], "brute bf16 build")
            hfn = jax.jit(lambda q, idx: brute_force.search(
                idx, q, k, algo="matmul"))
            tph = TwoPart(hfn, bf16s, offsets, k)
            thr, lat = measure_tp(tph, queries, floor=floor_brute(),
                                  what="brute bf16")
            if thr is not None:
                rec = robust_call(
                    lambda: device_recall(tph(queries)[1], gt),
                    "brute bf16 recall")
                add_entry("raft_brute_force", "raft_brute_force.matmul.bf16",
                          thr, lat, rec, 0.0)
            del bf16s

    # --- ivf_flat (config 2: n_lists=1024/part, probe sweep) ------------
    flat_best = None
    with algo_section('ivf_flat'):
        t0 = time.perf_counter()
        fis = robust_call(lambda: [
            ivf_flat.build(p, ivf_flat.IndexParams(n_lists=1024, seed=0))
            for p in parts], "ivf_flat build")
        jax.block_until_ready(jax.tree.leaves(fis))
        flat_build = time.perf_counter() - t0
        for fi in fis:
            ivf_flat.prepare_scan(fi)
        log(f"# ivf_flat built in {flat_build:.0f}s")

        def measure_flat(probes):
            nonlocal flat_best
            sp = ivf_flat.SearchParams(n_probes=probes)
            fn = jax.jit(lambda q, idx, s=sp: ivf_flat.search(idx, q, k, s))
            tp = TwoPart(fn, fis, offsets, k)
            thr, lat = measure_tp(tp, queries,
                                  floor=floor_ivf(probes, d * 4),
                                  what=f"ivf_flat np{probes}")
            if thr is None:
                return None
            rec = robust_call(lambda: device_recall(tp(queries)[1], gt),
                              "ivf_flat recall")
            add_entry("raft_ivf_flat",
                      f"raft_ivf_flat.nlist1024.nprobe{probes}",
                      thr, lat, rec, flat_build,
                      extra=store_bytes_of(fis))
            if rec >= 0.95 and (flat_best is None
                                or nq / thr > flat_best[0]):
                # FULL entry name: the headline-first sort matches on it
                flat_best = (nq / thr, rec,
                             f"raft_ivf_flat.nlist1024.nprobe{probes}")
            return rec

        # config-2 anchor (nprobe=20) always measured; walk DOWN while
        # recall holds >=0.95, or UP if the anchor misses
        best_probes = 20
        rec20 = measure_flat(20)
        if not hurry and rec20 is not None:
            if rec20 >= 0.95:
                # bisect-capable down-walk: np15 sits in the gap where
                # the qualifying frontier usually lives (np20 barely
                # clears, np10 misses — r4: 0.9506 vs 0.8766)
                for probes in (15, 10, 5):
                    r = measure_flat(probes)
                    if r is None or r < 0.95:
                        break
                    best_probes = probes
            else:
                for probes in (25, 30, 40, 50, 100) if rec20 >= 0.93 \
                        else (50, 100):
                    best_probes = probes
                    r = measure_flat(probes)
                    if r is not None and r >= 0.95:
                        break
        # bf16 list storage at the best qualifying probe count
        if not hurry:
            t0 = time.perf_counter()
            fihs = robust_call(lambda: [
                ivf_flat.build(p, ivf_flat.IndexParams(
                    n_lists=1024, seed=0, dtype="bfloat16"))
                for p in parts], "ivf_flat bf16 build")
            jax.block_until_ready(jax.tree.leaves(fihs))
            bf16_build = time.perf_counter() - t0
            for fi in fihs:
                ivf_flat.prepare_scan(fi)
            fnh = jax.jit(lambda q, idx: ivf_flat.search(
                idx, q, k, ivf_flat.SearchParams(n_probes=best_probes)))
            tph = TwoPart(fnh, fihs, offsets, k)
            thr, lat = measure_tp(tph, queries,
                                  floor=floor_ivf(best_probes, d * 2),
                                  what="ivf_flat bf16")
            if thr is not None:
                rec = robust_call(
                    lambda: device_recall(tph(queries)[1], gt),
                    "ivf_flat bf16 recall")
                add_entry("raft_ivf_flat",
                          f"raft_ivf_flat.nlist1024.nprobe{best_probes}"
                          ".bf16",
                          thr, lat, rec, bf16_build,
                          extra=store_bytes_of(fihs))
                if rec >= 0.95 and nq / thr > (flat_best or (0,))[0]:
                    flat_best = (nq / thr, rec,
                                 f"raft_ivf_flat.nlist1024"
                                 f".nprobe{best_probes}.bf16")
            del fihs

    # --- serving_latency: p50/p99 per-request latency at fixed recall ---
    # The ROADMAP "kill the dispatch floor" success metric: requests
    # served through the serve/ runtime (admission -> coalesce -> bucket
    # pad -> dispatch -> demux) with stage telemetry sampling EVERY
    # batch, so the entry decomposes per-request latency into the five
    # stages the dispatch-floor attack must move (queue_wait /
    # bucket_pad / dispatch / device / demux, straight from the
    # <name>.stage.* histograms). Recall is fixed by construction: the
    # serving closure is the ivf_flat sweep's best qualifying probe
    # config over the same index parts, so the lane reports that entry's
    # measured recall. Closed-loop at bounded in-flight depth — an
    # open-loop flood would only measure queue saturation.
    with algo_section('serving_latency'):
        from raft_tpu.serve import metrics as serve_metrics
        from raft_tpu.serve.batcher import BucketLadder, MicroBatcher

        remaining = budget_s - (time.perf_counter() - t_start)
        from raft_tpu.core.errors import expects as _expects
        _expects(remaining > 240, "serving lane skip: %.0fs left < 240s",
                 remaining)
        sp_serve = ivf_flat.SearchParams(n_probes=best_probes)
        flat_name = f"raft_ivf_flat.nlist1024.nprobe{best_probes}"
        flat_entry = next((e for e in entries if e["name"] == flat_name),
                          None)
        kb_serve = 16          # one k bucket; requests ask k=10
        sfn_serve = jax.jit(lambda q, idx, s=sp_serve: ivf_flat.search(
            idx, q, kb_serve, s))
        tp_serve = TwoPart(sfn_serve, fis, offsets, kb_serve)

        def serve_search(q, kk, res=None):
            return tp_serve(jnp.asarray(q))

        reg_serve = serve_metrics.Registry()
        qhost = np.asarray(queries[:1000])
        # quality half of the lane (docs/observability.md "Quality"):
        # a recall sentinel re-executes sampled served requests through
        # the exact brute-force parts (the GT executables) and the lane
        # records its rolling serve.recall estimate next to the latency
        # numbers. Sampled shapes are padded to one fixed row count so
        # the reference costs exactly one extra compile.
        from raft_tpu.serve.quality import RecallSentinel
        _ref_tp = TwoPart(gt_search_jit, bfs, offsets, k)
        _ref_rows = 64

        def _sentinel_ref(qs, kk):
            m = qs.shape[0]
            pad = np.zeros((_ref_rows, d), np.float32)
            pad[:m] = qs
            rd, ri = _ref_tp(jnp.asarray(pad))
            return (np.asarray(rd)[:m, :kk], np.asarray(ri)[:m, :kk])

        sentinel = RecallSentinel(_sentinel_ref, sample=0.25,
                                  registry=reg_serve, family="ivf_flat",
                                  engine=f"nprobe{best_probes}",
                                  window=64, max_pending=16)
        # robustness half of the lane (docs/robustness.md): an SLO
        # engine + brownout controller ride along so a run that browned
        # out (stepped the degradation ladder) is distinguishable from a
        # clean one — the artifact records every level transition and
        # the final circuit-breaker states next to the stage
        # decomposition. Targets are generous (2x the ivf_flat lane's
        # typical p99) so a healthy run records zero transitions.
        from raft_tpu.ops import guarded as serve_guarded
        from raft_tpu.serve.degrade import BrownoutController
        from raft_tpu.serve.slo import SLOEngine, Targets
        slo_serve = SLOEngine(
            Targets(p99_latency_s=0.5, recall_floor=0.9,
                    recall_family="ivf_flat", recall_min_samples=4),
            registry=reg_serve, name="serve",
            fast_window_s=2.0, slow_window_s=6.0)
        brownout = BrownoutController(
            [{"max_wait_scale": 2.0}], slo=slo_serve,
            registry=reg_serve, min_dwell_s=2.0)
        b = MicroBatcher(serve_search, d,
                         ladder=BucketLadder((16, 64), (kb_serve,)),
                         registry=reg_serve, name="serve",
                         trace_sample=1.0, max_wait_s=0.002,
                         sentinel=sentinel, degrade=brownout)
        try:
            warm_compiles = b.warmup()
            rng_s = np.random.default_rng(11)
            n_req, inflight_cap = 200, 8
            req_sizes = rng_s.choice(
                [1, 2, 4, 8, 16, 32], size=n_req,
                p=[.3, .2, .2, .15, .1, .05])
            t0 = time.perf_counter()
            inflight = []
            for i_req, m in enumerate(req_sizes):
                s0 = int(rng_s.integers(0, len(qhost) - int(m)))
                inflight.append(b.submit(qhost[s0:s0 + int(m)], k))
                if len(inflight) >= inflight_cap:
                    inflight.pop(0).result(300)
                if (i_req + 1) % 50 == 0:
                    brownout.poll()     # the serving maintenance tick
            for r in inflight:
                r.result(300)
            serve_wall = time.perf_counter() - t0
            brownout.poll()
        finally:
            b.close()
            sentinel.drain(120.0)
            sentinel.close()
        snap = reg_serve.snapshot()
        sent_snap = sentinel.snapshot()
        serve_recall = sentinel.estimate("ivf_flat")
        lat = snap["histograms"]["serve.latency_s"]
        stage_hists = {s: snap["histograms"][f"serve.stage.{s}_s"]
                       for s in ("queue_wait", "bucket_pad", "dispatch",
                                 "device", "demux")}
        add_entry(
            "serving_latency",
            f"serving_latency.ivf_flat.nprobe{best_probes}",
            serve_wall, lat["p50"],
            flat_entry["recall"] if flat_entry else -1.0, 0.0,
            {"p50_ms": round(lat["p50"] * 1e3, 2),
             "p99_ms": round(lat["p99"] * 1e3, 2),
             "stage_p50_ms": {s: round(h["p50"] * 1e3, 3)
                              for s, h in stage_hists.items()},
             "stage_p99_ms": {s: round(h["p99"] * 1e3, 3)
                              for s, h in stage_hists.items()},
             "requests": n_req, "closed_loop_inflight": inflight_cap,
             "batches": int(snap["counters"]["serve.batches"]),
             "warmup_compiles": warm_compiles,
             "steady_state_recompiles": int(
                 serve_metrics.counter("serve.recompiles").value),
             # the online estimate next to the offline recall: these two
             # agreeing is the sentinel's calibration check
             "serve_recall_estimate": None if serve_recall is None
             else round(serve_recall, 4),
             "recall_sentinel": {
                 "sampled": sent_snap["sampled"],
                 "scored": sent_snap["scored"],
                 "dropped": sent_snap["dropped"],
                 "sample_rate": 0.25},
             # a silently-browned-out run must be distinguishable from
             # a clean one: final ladder level + every transition, and
             # the final breaker state of every site that opened
             "brownout": {
                 "level": brownout.level,
                 "transitions": brownout.snapshot()["transitions"]},
             "breakers": {site: ent["state"] for site, ent in
                          serve_guarded.breaker_snapshot().items()},
             "recall_source": flat_name, "trace_sample": 1.0},
            batch=n_req, baseline_key=None)

    # --- mutation: the mutable-tier write path (docs/mutation.md) -------
    # Records what mutability COSTS: WAL'd acked-upsert throughput, the
    # delta-tier search penalty (p50 with a populated delta fan-out vs
    # after the background merge folds it), and recall before/after the
    # merge scored by the RecallSentinel against an exact reference over
    # the live logical corpus. RAFT_TPU_BENCH_MUTATION=0 skips /
    # =1 forces past the budget gate.
    mut_env = os.environ.get("RAFT_TPU_BENCH_MUTATION")
    mut_left = budget_s - (time.perf_counter() - t_start)
    if mut_env != "0" and (mut_env == "1" or mut_left > 180):
        with algo_section('mutation'):
            import shutil
            import tempfile

            from raft_tpu.neighbors import mutable as mutable_mod
            from raft_tpu.serve.metrics import Registry as _MutReg
            from raft_tpu.serve.quality import RecallSentinel as _MutSent

            mut_dir = tempfile.mkdtemp(prefix="raft_tpu_mut_")
            try:
                base_n = min(100_000, int(parts[0].shape[0]))
                base = np.asarray(jax.device_get(parts[0][:base_n]),
                                  np.float32)
                qh = np.asarray(jax.device_get(queries[:256]), np.float32)
                t0 = time.perf_counter()
                midx = mutable_mod.create(os.path.join(mut_dir, "idx"),
                                          base, family="brute_force")
                mut_build = time.perf_counter() - t0

                def _mut_search(qs=qh, kk=k):
                    dd, ii = midx.search(qs, kk)
                    return float(jnp.sum(dd).block_until_ready())

                sealed_p50 = median_time(_mut_search, reps=7)
                # WAL'd upsert throughput: every batch is acked
                # (framed + CRC'd + fsynced) before the next starts
                up_rows, up_batch = 8192, 1024
                rng_m = np.random.default_rng(17)
                up = base[rng_m.integers(0, base_n, up_rows)] + \
                    rng_m.normal(scale=0.05,
                                 size=(up_rows, d)).astype(np.float32)
                t0 = time.perf_counter()
                for b0 in range(0, up_rows, up_batch):
                    midx.upsert(None, up[b0:b0 + up_batch])
                upsert_wall = time.perf_counter() - t0
                # measured BEFORE the merge rotates the log: WAL bytes
                # actually paid per acked row (frames + npy framing)
                wal_row_bytes = midx.wal_bytes() / up_rows
                delta_p50 = median_time(_mut_search, reps=7)

                # exact reference over the live logical corpus (ids in
                # the mutable tier == row positions in this concat)
                from raft_tpu.neighbors import brute_force as _bf
                _ref_idx = _bf.build(np.concatenate([base, up]))

                def _mut_ref(qs, kk):
                    rd, ri = _bf.search(_ref_idx, jnp.asarray(qs), kk)
                    return np.asarray(rd), np.asarray(ri)

                def _mut_recall(tag):
                    sent = _MutSent(_mut_ref, sample=1.0,
                                    registry=_MutReg(), family="mutable",
                                    engine=tag, window=64, max_pending=8)
                    dd, ii = midx.search(qh[:64], k)
                    sent.offer(qh[:64], k, np.asarray(dd), np.asarray(ii))
                    sent.drain(120.0)
                    est = sent.estimate("mutable")
                    sent.close()
                    return None if est is None else round(est, 4)

                recall_before = _mut_recall("pre_merge")
                t0 = time.perf_counter()
                verdict = midx.merge()
                merge_s = time.perf_counter() - t0
                merged_p50 = median_time(_mut_search, reps=7)
                recall_after = _mut_recall("post_merge")
                add_entry(
                    "mutation", f"mutation.brute{base_n // 1000}k",
                    upsert_wall, delta_p50,
                    recall_after if recall_after is not None else -1.0,
                    mut_build,
                    {"upsert_rows_per_s": round(up_rows / upsert_wall, 1),
                     "acked_batches": up_rows // up_batch,
                     "wal_bytes_per_row": round(wal_row_bytes, 1),
                     "sealed_p50_ms": None if sealed_p50 is None
                     else round(sealed_p50 * 1e3, 3),
                     "delta_p50_ms": None if delta_p50 is None
                     else round(delta_p50 * 1e3, 3),
                     "delta_p50_delta_ms": None
                     if None in (sealed_p50, delta_p50)
                     else round((delta_p50 - sealed_p50) * 1e3, 3),
                     "merged_p50_ms": None if merged_p50 is None
                     else round(merged_p50 * 1e3, 3),
                     "merge_verdict": verdict,
                     "merge_s": round(merge_s, 2),
                     "recall_sentinel_before_merge": recall_before,
                     "recall_sentinel_after_merge": recall_after},
                    batch=up_rows, baseline_key=None)
            finally:
                shutil.rmtree(mut_dir, ignore_errors=True)
    else:
        log(f"# mutation lane skipped ({mut_left:.0f}s left; "
            "set RAFT_TPU_BENCH_MUTATION=1 to force)")

    # --- multi_tenant: the serving fabric (docs/serving.md) -------------
    # 3 tenants over one shared index (co-batched dispatch): one
    # Zipfian-hot repeat-heavy tenant behind a token bucket, two cold
    # tenants. Records per-tenant p50/p99, the ISOLATION RATIO (cold
    # tenants' p99 with vs without the hot tenant — the fabric's
    # whole point), and the query-cache hit rate on the hot stream.
    # RAFT_TPU_BENCH_TENANCY=0 skips / =1 forces past the budget gate.
    ten_env = os.environ.get("RAFT_TPU_BENCH_TENANCY")
    ten_left = budget_s - (time.perf_counter() - t_start)
    if ten_env != "0" and (ten_env == "1" or ten_left > 120):
        with algo_section('multi_tenant'):
            from raft_tpu.serve import warmup as _twarm
            from raft_tpu.serve.batcher import BucketLadder as _TLad
            from raft_tpu.serve.metrics import Registry as _TReg
            from raft_tpu.serve.qcache import QueryCache as _TQC
            from raft_tpu.serve.tenancy import (RateLimitedError as _TRle,
                                                ServeFabric as _TFab)

            ten_n = min(50_000, int(parts[0].shape[0]))
            ten_idx = brute_force.build(parts[0][:ten_n])
            # ONE searcher closure shared by every tenant: same index +
            # params => the fabric co-batches across tenants, and
            # tenancy adds zero ladder shapes / zero extra compiles
            sfn_ten = brute_force.make_searcher(ten_idx)
            ten_ladder = _TLad((1, 8, 32), (16,))
            qh_t = np.asarray(jax.device_get(queries[:512]), np.float32)
            rng_t = np.random.default_rng(5)
            pool = qh_t[:64]    # the hot tenant's repeat pool
            zipf_picks = np.minimum(rng_t.zipf(1.3, size=4096) - 1, 63)
            _twarm.warmup(sfn_ten, ten_ladder, d, registry=_TReg(),
                          name="tenancy.warm")

            from raft_tpu.serve.admission import QueueFullError as _TQFE

            def _ten_submit(fab, nm, q_row, futs):
                # a cold submit outrunning the worker is backpressure,
                # not a lane failure: wait out the queue (bounded)
                for _ in range(600):
                    try:
                        futs.append(fab.submit(nm, q_row, k))
                        return
                    except _TQFE:
                        time.sleep(0.01)
                raise RuntimeError(f"tenant {nm} queue never drained")

            def _tenancy_pass(with_hot):
                cache = _TQC(capacity=4096, registry=_TReg())
                fab = _TFab(d, ladder=ten_ladder, cache=cache,
                            registry=_TReg(), name="tfab")
                try:
                    for nm in ("cold1", "cold2"):
                        fab.add_tenant(nm, search_fn=sfn_ten,
                                       queue_depth=1024)
                    if with_hot:
                        fab.add_tenant("hot", search_fn=sfn_ten,
                                       rate=2000.0, burst=64.0,
                                       queue_depth=1024)
                    futs, hot_shed, hp = [], 0, 0
                    for i in range(400):
                        _ten_submit(fab, "cold1",
                                    qh_t[(7 * i) % 512][None, :], futs)
                        _ten_submit(fab, "cold2",
                                    qh_t[(11 * i + 31) % 512][None, :],
                                    futs)
                        if with_hot:
                            for _ in range(2):
                                try:
                                    futs.append(fab.submit(
                                        "hot",
                                        pool[zipf_picks[hp]][None, :], k))
                                except _TRle:
                                    hot_shed += 1
                                except _TQFE:
                                    pass
                                hp += 1
                    for f in futs:
                        f.result(300)
                    if with_hot:
                        # steady-state repeat wave: the burst above is
                        # all submitted before its duplicates get
                        # served, so cache hits only show once entries
                        # exist — THIS wave is the repeat-traffic claim
                        wave = []
                        for j in range(200):
                            try:
                                wave.append(fab.submit(
                                    "hot",
                                    pool[zipf_picks[j]][None, :], k))
                            except (_TRle, _TQFE):
                                pass
                        for f in wave:
                            f.result(300)
                        futs += wave
                    lat = {}
                    for t in fab.tenants():
                        h = t.registry.histogram(f"{t.name}.latency_s")
                        lat[t.name] = (h.percentile(50), h.percentile(99))
                    served = len(futs)
                    hit = cache.snapshot()
                    cob = int(fab.snapshot()["cobatched_dispatches"])
                    return lat, hit, hot_shed, served, cob
                finally:
                    # a timeout/dispatch error must not leak the drain
                    # worker into the next lane's timings
                    fab.close()

            solo_lat, _, _, _, _ = _tenancy_pass(False)
            # qps is the COMBINED pass only (batch counts its futures;
            # folding the solo calibration pass in would halve it)
            t0 = time.perf_counter()
            comb_lat, hit, hot_shed, served, cob = _tenancy_pass(True)
            ten_wall = time.perf_counter() - t0
            iso = max(comb_lat[nm][1] / max(solo_lat[nm][1], 1e-6)
                      for nm in ("cold1", "cold2"))
            add_entry(
                "multi_tenant", f"tenancy.brute{ten_n // 1000}k.3tenants",
                ten_wall, comb_lat["cold1"][1], -1.0, 0.0,
                {"per_tenant_ms": {
                    nm: {"p50": round(p50 * 1e3, 3),
                         "p99": round(p99 * 1e3, 3)}
                    for nm, (p50, p99) in comb_lat.items()},
                 "cold_solo_p99_ms": {
                     nm: round(p99 * 1e3, 3)
                     for nm, (_p, p99) in solo_lat.items()},
                 # >1 means the hot tenant degraded the cold tenants;
                 # the ISSUE 15 isolation bar is 1.5
                 "isolation_ratio": round(iso, 3),
                 "hot_shed": hot_shed,
                 "cobatched_dispatches": cob,
                 "qcache": {"hit_rate": hit["hit_rate"],
                            "hits": hit["hits"],
                            "misses": hit["misses"],
                            "entries": hit["entries"]}},
                batch=served, baseline_key=None)
    else:
        log(f"# multi_tenant lane skipped ({ten_left:.0f}s left; "
            "set RAFT_TPU_BENCH_TENANCY=1 to force)")

    # --- ivf_pq (config 3) + refine -------------------------------------
    # kernel round 4: pq_bits=4 with pq_dim=d (same 512 code bits/row as
    # pq64x8 but an 8x narrower one-hot decode) + int8-quantized LUT (the
    # fp8-LUT role, double-rate MXU) + bf16 refine corpus (half the
    # gather traffic). See scratch/exp_hard_tune.py for the sweep.
    with algo_section('ivf_pq'):
        t0 = time.perf_counter()
        pis = robust_call(lambda: [
            ivf_pq.build(p, ivf_pq.IndexParams(
                n_lists=1024, pq_dim=min(d, 128), pq_bits=4, seed=0))
            for p in parts], "ivf_pq build")
        jax.block_until_ready(jax.tree.leaves(pis))
        pq_build = time.perf_counter() - t0
        for pi in pis:
            ivf_pq.prepare_scan(pi)
        log(f"# ivf_pq built in {pq_build:.0f}s")
        parts_bf16 = [jnp.asarray(p, jnp.bfloat16) for p in parts]
        jax.block_until_ready(parts_bf16)

        def pq_refined_tp(probes, ratio):
            """Per-part scan + per-part bf16 refine, exact merge (refine
            before merge == refine after merge for top-k)."""
            sp = ivf_pq.SearchParams(n_probes=probes, lut_dtype="int8")

            def body(q, idx, dd):
                _, cand = ivf_pq.search(idx, q, ratio * k, sp)
                return refine.refine(dd, q, cand, k)

            return TwoPart(jax.jit(body), pis, offsets, k,
                           extras=[(pb,) for pb in parts_bf16])

        def measure_pq(probes, ratio):
            tp = pq_refined_tp(probes, ratio)
            thr, lat = measure_tp(tp, queries,
                                  floor=floor_ivf(probes,
                                                  min(d, 128) // 2 + 4),
                                  what=f"ivf_pq np{probes} r{ratio}")
            if thr is None:
                return None
            rec = robust_call(
                lambda: device_recall(tp(queries)[1], gt), "ivf_pq recall")
            add_entry("raft_ivf_pq",
                      f"raft_ivf_pq.nlist1024.pq{min(d, 128)}x4.int8"
                      f".nprobe{probes}.refine{ratio}",
                      thr, lat, rec, pq_build,
                      extra=store_bytes_of(pis))
            return rec

        rec_a = measure_pq(20, 2)
        if not hurry:
            if rec_a is None:
                measure_pq(10, 2)
                measure_pq(20, 4)
            elif rec_a >= 0.95:
                measure_pq(10, 2)
                if rec_a < 0.995:
                    measure_pq(20, 4)
            else:
                # diagnose WHICH axis binds: if doubling refine doesn't
                # move recall, it is probe-limited (low-intrinsic-dim
                # corpora) and the probe walk should keep the cheap r2
                r4 = measure_pq(20, 4)
                quant_limited = (r4 is not None and rec_a is not None
                                 and r4 > rec_a + 0.01)
                ratio = 4 if quant_limited else 2
                # bisect-capable up-walk: a near-miss anchor (r4's
                # 0.9491 @ np20) explores 25/30/40 so a measured point
                # actually lands at the gate instead of jumping to
                # np50's 0.991 with the frontier unmeasured; 100 caps the
                # walk so the 0.95 gate always has a qualifying endpoint
                # (matching ivf_flat's walk)
                ups = (25, 30, 40, 50, 100) if rec_a >= 0.93 else (50, 100)
                for probes in ups:
                    r = measure_pq(probes, ratio)
                    if r is not None and r >= 0.95:
                        break
        del parts_bf16

    def cagra_decomposition(ci, eng_timings):
        """Per-hop decomposition of the CAGRA traversal: candidate
        fetch+score through each engine (the gather-tax evidence), the
        resident-vector score alone, and the dedup+merge — plus the
        gathered vs streamed byte counts per hop. All probes ride
        value-read measurements; diagnostics must not cost the lane."""
        from raft_tpu.matrix.select_k import select_k as _sel
        from raft_tpu.neighbors import cagra as _cg
        from raft_tpu.ops import graph_expand as _ge

        deg = ci.graph_degree
        w, itopk = 4, 32                  # probe anchor == the r5 headline
        # the block self-describes its operating point: it rides on the
        # sweep's OPENER entry, whose (itopk, width) can differ
        decomp = {"probe_itopk": itopk, "probe_width": w}
        kprime = min(deg, itopk)
        m = queries.shape[0]
        kk = jax.random.PRNGKey(5)
        cand = jax.random.randint(kk, (m, w * deg), 0, ci.size)
        parents = jax.random.randint(kk, (m, w), 0, ci.size,
                                     dtype=jnp.int32)
        mt = ci.metric

        def _fin(x):
            return jnp.sum(jnp.where(jnp.isfinite(x), x, 0.0))

        def probe(name, fn, *args):
            try:
                decomp[name] = round(_autotune.measure(
                    jax.jit(fn), *args, reps=3,
                    suspect_floor_s=suspect_floor, value_read=True) * 1e3,
                    2)
            except Exception as e:  # noqa: BLE001
                log(f"# cagra decomp probe {name} failed: "
                    f"{type(e).__name__}: {e}")

        # the old hop's HBM op: a random (m, w·deg) row gather + score
        probe("gather_ms",
              lambda q, c, ix: _fin(_cg._gather_score(
                  ix._score_bf16, None, c, q, mt)), queries, cand, ci)
        decomp["gathered_mb"] = round(m * w * deg * ci.dim * 2 / 1e6, 1)
        store = getattr(ci, "_edge_store", None)
        if store is not None:
            # the new hop's HBM op: streamed contiguous edge tiles
            probe("expand_ms",
                  lambda q, p, ix: _fin(_ge.graph_expand(
                      p, q, ix._edge_store[1], ix._edge_store[2], kprime,
                      metric="ip" if mt.name == "InnerProduct" else "l2",
                      degree=deg)[0]), queries, parents, ci)
            meta = store[0]
            itemsize = 2 if meta[0] == "bfloat16" else 1
            decomp["streamed_mb"] = round(
                m * w * meta[2] * meta[3] * itemsize / 1e6, 1)
        # score alone on resident vectors — isolates fetch from math
        vs = (getattr(ci, "_score_bf16", ci.dataset))[cand]
        probe("score_ms", lambda q, v: _fin(_cg._query_dists(q, v, mt)),
              queries, vs)
        del vs
        # dedup + merge at each engine's width (edge: w·kprime candidate
        # columns vs gather: w·deg — the shrink the per-parent top-k'
        # emission buys)
        def _merge(c, ids):
            dup = _cg._dup_mask(ids[:, itopk:], keep=ids[:, :itopk])
            c = jnp.concatenate(
                [c[:, :itopk], jnp.where(dup, jnp.inf, c[:, itopk:])],
                axis=1)
            return _fin(_sel(c, itopk, select_min=True)[0])

        for tag, cw in (("merge_ms", w * kprime),
                        ("merge_gather_ms", w * deg)):
            probe(tag, _merge,
                  jax.random.uniform(kk, (m, itopk + cw)),
                  jax.random.randint(kk, (m, itopk + cw), 0, ci.size))
        if eng_timings:
            decomp["engine_timings_ms"] = {
                kk_: round(v * 1e3, 1) for kk_, v in eng_timings.items()}
        return decomp

    # --- cagra (config 4: graph_degree=64) ------------------------------
    with algo_section('cagra'):
        remaining = budget_s - (time.perf_counter() - t_start)
        # round 6: knn_graph auto → nn_descent at 500k (the fused exact
        # pass below RAFT_TPU_CAGRA_BRUTE_N) cut the build from 366.8s
        # to minutes-fraction scale; the gates shrink accordingly. One
        # part only — the graph index demonstrates single-index scaling
        # (the sharded form is dryrun_multichip's job).
        cagra_n = part_n if remaining > 700 and part_n >= 500_000 else \
            min(n, 100_000 if scale != "micro" else 20_000)
        cagra_env = os.environ.get("RAFT_TPU_BENCH_CAGRA_N")
        if cagra_env:
            cagra_n = int(cagra_env)
        else:
            need_s = 400 if cagra_n > 50_000 else 120
            from raft_tpu.core.errors import expects as _expects
            _expects(remaining > need_s,
                     "budget skip: %.0fs left < %ds needed for a %d-row "
                     "cagra build", remaining, need_s, cagra_n)
        cdata = data[:cagra_n]
        if cagra_n == n:
            cgt = gt
        elif cagra_n == part_n:
            # part A's ground truth: rerun the part-A search fn
            cgt_fn = jax.jit(lambda q, idx: brute_force.search(
                idx, q, k, algo="matmul")[1])
            cgt = robust_call(lambda: jnp.concatenate(
                [cgt_fn(queries[c0 : c0 + 1000], bfs[0])
                 for c0 in range(0, nq, 1000)]), "cagra part gt")
        else:
            cgt_fn = jax.jit(lambda q, cd: brute_force.search(
                brute_force.build(cd), q, k, algo="matmul")[1])
            cgt = robust_call(lambda: cgt_fn(queries, cdata), "cagra gt")
        t0 = time.perf_counter()
        ci = robust_call(lambda: cagra.build(cdata, cagra.IndexParams(
            graph_degree=64, intermediate_graph_degree=96, seed=0)),
            "cagra build")
        jax.block_until_ready(jax.tree.leaves(ci))
        cagra_build = time.perf_counter() - t0
        # phase decomposition (knn_graph_s/optimize_s/seeds_s + which
        # builder auto picked): the evidence block for build-time PRs
        build_decomp = dict(getattr(ci, "build_stats", {}))
        cagra.prepare_search(ci)
        log(f"# cagra built ({cagra_n} rows) in {cagra_build:.0f}s: "
            f"{build_decomp}")
        # engine race: the streamed edge-store hop (prepare_traversal +
        # Pallas frontier expansion) vs the XLA gather hop, at the
        # anchor config. The winner is cached; when edge wins the store
        # stays attached and every algo-auto sweep search dispatches on
        # it, when gather wins the store is dropped (no idle HBM).
        eng_winner, eng_timings = "gather", {}
        if jax.default_backend() == "tpu":
            try:
                eng_winner, eng_timings = cagra.tune_search(
                    ci, queries, k,
                    cagra.SearchParams(itopk_size=32, search_width=4,
                                       max_iterations=5),
                    reps=3, suspect_floor_s=suspect_floor)
                log(f"# cagra engine race -> {eng_winner}")
            except Exception as e:  # noqa: BLE001
                log(f"# cagra engine race failed ({type(e).__name__}: "
                    f"{e}); staying on gather")
        try:
            cagra_decomp = cagra_decomposition(ci, eng_timings)
            log(f"# cagra decomposition: {cagra_decomp}")
        except Exception as e:  # noqa: BLE001
            log(f"# cagra decomposition failed ({type(e).__name__}: {e})")
            cagra_decomp = {}
        # sweep (itopk, search_width, max_iterations); measured sweep
        # 2026-07-31 (see bench.py history): covering seeds + few hops
        # (40,4,5) targets the [0.95, 0.965] recall band the r4 sweep
        # straddled (0.9401 @ itopk40.mi4 vs 0.9688 @ itopk32.mi5)
        sweep = (((32, 4, 5),) if hurry
                 else ((16, 8, 2), (32, 4, 3), (40, 4, 4), (40, 4, 5),
                       (32, 4, 5), (64, 4, 8)))
        opener = sweep[0]
        for itopk, width, mi in sweep:
            sp = cagra.SearchParams(itopk_size=itopk, search_width=width,
                                    max_iterations=mi)
            fn = jax.jit(lambda q, idx, s=sp: cagra.search(idx, q, k, s))
            thr, lat = measure_tp(fn, queries, ci, reps=3,
                                  what=f"cagra itopk{itopk}")
            if thr is None:
                continue
            rec = robust_call(lambda: device_recall(fn(queries, ci)[1], cgt),
                              "cagra recall")
            extra = {"corpus_n": cagra_n, "engine": eng_winner,
                     "build_decomposition": build_decomp,
                     **store_bytes_of(ci)}
            if (itopk, width, mi) == opener:
                extra["decomposition"] = cagra_decomp
            add_entry("raft_cagra",
                      f"raft_cagra.degree64.itopk{itopk}.w{width}"
                      f".mi{mi or 'auto'}",
                      thr, lat, rec, cagra_build, extra)
            if rec >= 0.995 and (itopk, width, mi) != opener:
                break

    # --- serving_latency.cagra: the one-dispatch megakernel behind the
    # serve runtime (ISSUE 12). The per-request story the ivf_flat
    # serving lane tells, on the graph index with engine="fused" — the
    # whole traversal is ONE kernel launch, so stage_p50_ms.dispatch is
    # the number the megakernel exists to move. `one_dispatch` is
    # verified structurally (jaxpr: no device-side hop loop survives,
    # each of whose iterations would be a separate kernel launch) and
    # recorded on the entry next to a per-batch host-dispatch counter.
    with algo_section('serving_latency.cagra'):
        from raft_tpu.ops import cagra_fused
        from raft_tpu.serve import metrics as cserve_metrics
        from raft_tpu.serve.batcher import BucketLadder as _CLadder, \
            MicroBatcher as _CBatcher

        remaining = budget_s - (time.perf_counter() - t_start)
        from raft_tpu.core.errors import expects as _expects
        _expects(remaining > 120,
                 "cagra serving lane skip: %.0fs left < 120s", remaining)
        sp_cs = cagra.SearchParams(itopk_size=32, search_width=4,
                                   max_iterations=5)
        es = getattr(ci, "_edge_store", None)
        if es is None:
            cagra.prepare_traversal(ci)
            es = ci._edge_store
        can_fuse = cagra_fused.fused_capable(
            32, 4, es[1].shape[1], es[1].shape[2], es[1].dtype, 5)
        serve_eng = ("fused" if can_fuse
                     and jax.default_backend() == "tpu" else eng_winner)
        kb_cs = 16
        # structural one-dispatch check: trace the fused program (cheap,
        # no compile/execution) and count surviving device-side loops
        disp_stats = {}
        if can_fuse:
            try:
                disp_stats = cagra_fused.one_dispatch_stats(
                    lambda q: cagra.search(ci, q, kb_cs, sp_cs,
                                           engine="fused"),
                    queries[:16])
            except Exception as e:  # noqa: BLE001
                log(f"# one_dispatch trace failed ({type(e).__name__}: "
                    f"{e})")
        # donate="auto": the donated double-buffered pair is the lane's
        # subject; the kernel path was just raced/rehearsed above, and a
        # dispatch failure here fails the lane's futures, not the run
        searcher_cs = cagra.make_searcher(ci, sp_cs, engine=serve_eng,
                                          donate="auto")
        host_dispatches = [0]

        def cs_search(q, kk, res=None):
            host_dispatches[0] += 1
            return searcher_cs(q, kk, res=res)

        reg_cs = cserve_metrics.Registry()
        bc = _CBatcher(cs_search, d, ladder=_CLadder((16, 64), (kb_cs,)),
                       registry=reg_cs, name="serve_cagra",
                       trace_sample=1.0, max_wait_s=0.002)
        try:
            cs_warm = bc.warmup()
            rng_cs = np.random.default_rng(13)
            qhost_cs = np.asarray(queries[:1000])
            n_req_cs, inflight_cap = 120, 8
            sizes = rng_cs.choice([1, 2, 4, 8, 16], size=n_req_cs,
                                  p=[.3, .25, .2, .15, .1])
            t0 = time.perf_counter()
            inflight = []
            for m_cs in sizes:
                s0 = int(rng_cs.integers(0, len(qhost_cs) - int(m_cs)))
                inflight.append(bc.submit(qhost_cs[s0:s0 + int(m_cs)], k))
                if len(inflight) >= inflight_cap:
                    inflight.pop(0).result(300)
            for r in inflight:
                r.result(300)
            cs_wall = time.perf_counter() - t0
        finally:
            bc.close()
        snap_cs = reg_cs.snapshot()
        # recall at the serving params, same engine (fused is
        # bit-identical to edge, but record what actually served)
        rec_cs = robust_call(lambda: device_recall(
            cagra.search(ci, queries[:1000], k, sp_cs,
                         engine=serve_eng)[1], cgt[:1000]),
            "cagra serve recall")
        lat_cs = snap_cs["histograms"]["serve_cagra.latency_s"]
        stage_cs = {s: snap_cs["histograms"][f"serve_cagra.stage.{s}_s"]
                    for s in ("queue_wait", "bucket_pad", "dispatch",
                              "device", "demux")}
        batches_cs = int(snap_cs["counters"]["serve_cagra.batches"])
        add_entry(
            "serving_latency",
            f"serving_latency.cagra.{serve_eng}.itopk32",
            cs_wall, lat_cs["p50"], rec_cs, 0.0,
            {"p50_ms": round(lat_cs["p50"] * 1e3, 2),
             "p99_ms": round(lat_cs["p99"] * 1e3, 2),
             "stage_p50_ms": {s: round(h["p50"] * 1e3, 3)
                              for s, h in stage_cs.items()},
             "stage_p99_ms": {s: round(h["p99"] * 1e3, 3)
                              for s, h in stage_cs.items()},
             "engine": serve_eng,
             # the acceptance bit: no device-side hop loop survives in
             # the fused program AND the serving path issued exactly one
             # host dispatch per batch
             "one_dispatch": bool(
                 disp_stats.get("one_dispatch", False)
                 and serve_eng == "fused"
                 and host_dispatches[0] - len(bc.ladder.shapes())
                 == batches_cs),
             "dispatch_structure": disp_stats,
             "host_dispatches": host_dispatches[0],
             "requests": n_req_cs, "closed_loop_inflight": inflight_cap,
             "batches": batches_cs, "warmup_compiles": cs_warm,
             "steady_state_recompiles": int(cserve_metrics.counter(
                 "serve.recompiles").value),
             "trace_sample": 1.0},
            batch=n_req_cs, baseline_key=None)

    # --- cagra at the BASELINE 1M scale (the lane's missing point) ------
    # The graph build is the cost. knn_graph auto → nn_descent at 1M
    # (O(rounds·n·C·d), batch-shaped programs — the 1M single-program
    # compile hang structurally cannot happen), which replaced the
    # parted exact pass whose n²·d ≈ 2.6e17 FLOP was ~25 min of MXU
    # time. Still budget-gated (build + optimize + sweep is minutes) and
    # a REDUCED sweep (one config, no vs_baseline ratio: a one-point
    # sweep is not the Pareto frontier the A100 baseline derivation
    # describes). RAFT_TPU_BENCH_CAGRA_1M=1 forces; =0 skips regardless.
    with algo_section('cagra_1m'):
        remaining = budget_s - (time.perf_counter() - t_start)
        from raft_tpu.core.errors import expects as _expects
        force_1m = os.environ.get("RAFT_TPU_BENCH_CAGRA_1M")
        _expects(force_1m != "0" and n >= 1_000_000,
                 "cagra 1M skip: forced=%s n=%d", force_1m, n)
        _expects(force_1m == "1" or (not hurry and remaining > 1200),
                 "cagra 1M skip: %.0fs left < 1200s for the nn_descent "
                 "graph build (set RAFT_TPU_BENCH_CAGRA_1M=1 to force)",
                 remaining)
        t0 = time.perf_counter()
        ci1m = robust_call(lambda: cagra.build(data, cagra.IndexParams(
            graph_degree=64, intermediate_graph_degree=96, seed=0)),
            "cagra 1M build", tries=1)
        jax.block_until_ready(jax.tree.leaves(ci1m))
        build_1m = time.perf_counter() - t0
        decomp_1m = dict(getattr(ci1m, "build_stats", {}))
        cagra.prepare_search(ci1m)
        log(f"# cagra 1M built in {build_1m:.0f}s: {decomp_1m}")
        # edge store at 1M: deg64×dim128 int8 = 8.2 GB — fits v5e HBM
        # next to the f32 dataset + bf16 copy; a build/OOM failure just
        # keeps the lane on the gather engine
        eng_1m = "gather"
        if jax.default_backend() == "tpu":
            try:
                cagra.prepare_traversal(ci1m)
                eng_1m = "edge"
            except Exception as e:  # noqa: BLE001
                log(f"# cagra 1M prepare_traversal failed "
                    f"({type(e).__name__}: {e}); gather engine")
        for itopk, width, mi in ((32, 4, 5), (40, 4, 5)):
            sp = cagra.SearchParams(itopk_size=itopk, search_width=width,
                                    max_iterations=mi)
            fn = jax.jit(lambda q, idx, s=sp: cagra.search(idx, q, k, s))
            thr, lat = measure_tp(fn, queries, ci1m, reps=3,
                                  what=f"cagra1M itopk{itopk}")
            if thr is None:
                continue
            rec = robust_call(
                lambda: device_recall(fn(queries, ci1m)[1], gt),
                "cagra 1M recall")
            add_entry("raft_cagra",
                      f"raft_cagra.1M.degree64.itopk{itopk}.w{width}"
                      f".mi{mi}",
                      thr, lat, rec, build_1m,
                      {"corpus_n": n, "reduced_sweep": True,
                       "engine": eng_1m,
                       "build_decomposition": decomp_1m,
                       **store_bytes_of(ci1m)},
                      baseline_key=None)
            if rec >= 0.95:
                break

    # --- storage ladder capacity rung (ISSUE 13 / ROADMAP rung 1) -------
    # Edge-store rungs int8 -> int4 -> pq at fixed k with exact refine,
    # plus the ivf_flat HBM-resident vs host-streamed decomposition, at
    # n=10M (RAFT_TPU_BENCH_LADDER_N overrides — the CPU-gated proxy).
    # RAFT_TPU_BENCH_LADDER=1 forces / =0 skips.
    with algo_section('storage_ladder'):
        remaining = budget_s - (time.perf_counter() - t_start)
        from raft_tpu.core.errors import expects as _expects
        force_lad = os.environ.get("RAFT_TPU_BENCH_LADDER")
        _expects(force_lad != "0" and (
            force_lad == "1" or (jax.default_backend() == "tpu"
                                 and not hurry and remaining > 2400)),
            "storage ladder skip: forced=%s %.0fs left < 2400s "
            "(set RAFT_TPU_BENCH_LADDER=1 to force)", force_lad,
            remaining)
        lad_n = int(os.environ.get("RAFT_TPU_BENCH_LADDER_N",
                                   str(10_000_000)))
        entries.extend(run_storage_ladder(lad_n, d, nq=1000, k=k))

    # --- fleet storage ladder (per-host HBM-budget tiers) ---------------
    # Every FLEET_STORE_RUNGS rung on a virtual 2x2 fleet under a
    # per-host budget (docs/mnmg.md "Per-host storage tiers").
    # RAFT_TPU_BENCH_FLEET_LADDER=1 runs it (default: skip — an
    # on-demand lane; scratch/check_bench_artifact.py validates it).
    with algo_section('fleet_ladder'):
        from raft_tpu.core.errors import expects as _expects
        _expects(os.environ.get("RAFT_TPU_BENCH_FLEET_LADDER") == "1",
                 "fleet ladder skip (set RAFT_TPU_BENCH_FLEET_LADDER=1 "
                 "to run)")
        _expects(len(jax.devices()) >= 4,
                 "fleet ladder skip: %d devices < 4 (CPU runs need "
                 "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
                 len(jax.devices()))
        fn_n = int(os.environ.get("RAFT_TPU_BENCH_FLEET_LADDER_N",
                                  "8192"))
        entries.extend(run_fleet_ladder(
            fn_n, d, nq=256, k=k,
            out_json=os.path.join("artifacts",
                                  "bench_fleet_ladder.json")))

    # --- filtered-search selectivity sweep ------------------------------
    # Adaptive vs fixed filter policy across filtered-out fractions
    # (docs/perf.md "Filtered search"). RAFT_TPU_BENCH_FILTER=1 runs it
    # (default: skip — an on-demand lane; its artifact backs the docs).
    with algo_section('filter_sweep'):
        from raft_tpu.core.errors import expects as _expects
        _expects(os.environ.get("RAFT_TPU_BENCH_FILTER") == "1",
                 "filter sweep skip (set RAFT_TPU_BENCH_FILTER=1 to run)")
        fs_n = int(os.environ.get("RAFT_TPU_BENCH_FILTER_N", "20000"))
        entries.extend(run_filter_sweep(
            fs_n, d, nq=100, k=k,
            out_json=os.path.join("artifacts", "bench_filter_sweep.json")))

    # --- graph-build race: fused exact all-pairs vs NN-descent ----------
    # The two CAGRA graph builders at one shape (100k×128 at k=96, the
    # real build's intermediate degree): wall-clock race plus the
    # approximate builder's graph-edge recall against the exact graph.
    # The winner is recorded in the autotune bucket build_knn_graph's
    # algo="auto" consults, so the race steers later builds of this
    # shape class the way the search-engine races steer dispatch.
    # RAFT_TPU_BENCH_GRAPH_LANE=1 forces / =0 skips.
    with algo_section('graph_build'):
        remaining = budget_s - (time.perf_counter() - t_start)
        from raft_tpu.core.errors import expects as _expects
        force_gl = os.environ.get("RAFT_TPU_BENCH_GRAPH_LANE")
        _expects(force_gl != "0" and n >= 100_000,
                 "graph lane skip: forced=%s n=%d", force_gl, n)
        _expects(force_gl == "1" or (not hurry and remaining > 400),
                 "graph lane skip: %.0fs left < 400s", remaining)
        gn, gk = 100_000, 96
        gdata = np.asarray(data[:gn])
        t0 = time.perf_counter()
        g_exact = cagra.build_knn_graph(gdata, gk, algo="brute")
        brute_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        g_nnd = cagra.build_knn_graph(gdata, gk, algo="nn_descent")
        nnd_s = time.perf_counter() - t0
        # graph-edge recall vs exact, chunked on device (equal chunks,
        # every slot valid -> the mean of chunk recalls is exact)
        ge, gj = jnp.asarray(g_exact), jnp.asarray(g_nnd)
        step = gn // 10
        g_rec = float(np.mean([device_recall(gj[c:c + step],
                                             ge[c:c + step])
                               for c in range(0, gn, step)]))
        # the verdict steers later algo="auto" builds of this shape
        # class, so speed alone must not crown a degraded graph: the
        # approximate builder only wins with edge recall at the bar the
        # PR's quality gate is built on (optimize() + the exact re-rank
        # absorb ~0.9; below it, downstream search recall drifts)
        winner = ("nn_descent" if nnd_s < brute_s and g_rec >= 0.9
                  else "brute")
        from raft_tpu.distance.distance_types import DistanceType as _DT
        _autotune.record(cagra._graph_algo_key(gn, d, gk,
                                               _DT.L2Expanded), winner)
        log(f"# graph build race: brute(fused) {brute_s:.0f}s vs "
            f"nn_descent {nnd_s:.0f}s (edge recall {g_rec:.4f}) "
            f"-> {winner}")
        add_entry("cagra_build", f"cagra_build.race100k.k{gk}",
                  min(brute_s, nnd_s), None, g_rec,
                  min(brute_s, nnd_s),
                  {"corpus_n": gn, "graph_k": gk,
                   "brute_fused_s": round(brute_s, 1),
                   "nn_descent_s": round(nnd_s, 1), "winner": winner,
                   "recall_note": "graph-edge recall of nn_descent vs "
                                  "the exact graph"},
                  batch=gn, baseline_key=None)
        del gdata, g_exact, g_nnd, ge, gj

    # --- ivf_pq capacity (config 3's structural win: 2M rows) -----------
    # PQ's reason to exist is corpora where raw f32 pressures memory
    # (the reference's DEEP-1B positioning): 2M x 128 = 1.02 GB raw vs
    # ~0.26 GB of pq128x4 codes. A fresh 2M mixture (its own exact
    # ground truth, 2k-query batches to bound the GT stage) makes this a
    # recorded, recall-checked, floor-gated bench entry instead of the
    # r4 one-off artifact.
    with algo_section('ivf_pq_capacity'):
        remaining = budget_s - (time.perf_counter() - t_start)
        from raft_tpu.core.errors import expects as _expects
        _expects(scale == "full" and not hurry and remaining > 650,
                 "capacity skip: scale=%s hurry=%s %.0fs left < 650s",
                 scale, hurry, remaining)
        cap_nq = 2_000
        # ~2.5 GB of host/device working set below: the try/finally
        # guarantees the release even when a stage raises mid-lane (an
        # OOM'd capacity lane must not starve every later section)
        cdata = cq = cparts = cbfs = ctp = cgt = None
        cpis = cparts_bf16 = None
        try:
            cdata, cq = robust_call(
                lambda: make_corpus(2_000_000, d, cap_nq, seed=7),
                "capacity corpus")
            cparts = [cdata[i * part_n:(i + 1) * part_n]
                      for i in range(len(cdata) // part_n)]
            coffs = [i * part_n for i in range(len(cparts))]
            cbfs = [brute_force.build(p, metric="sqeuclidean")
                    for p in cparts]
            ctp = TwoPart(gt_search_jit, cbfs, coffs, k)
            cgt = jnp.concatenate([
                robust_call(lambda c0=c0: jax.block_until_ready(
                    ctp(cq[c0:c0 + 1000])[1]), f"capacity gt [{c0}]")
                for c0 in range(0, cap_nq, 1000)])
            cbfs = ctp = None
            t0 = time.perf_counter()
            cpis = robust_call(lambda: [
                ivf_pq.build(p, ivf_pq.IndexParams(
                    n_lists=1024, pq_dim=min(d, 128), pq_bits=4, seed=0))
                for p in cparts], "capacity pq build")
            jax.block_until_ready(jax.tree.leaves(cpis))
            cap_build = time.perf_counter() - t0
            for pi in cpis:
                ivf_pq.prepare_scan(pi)
            cparts_bf16 = [jnp.asarray(p, jnp.bfloat16) for p in cparts]
            jax.block_until_ready(cparts_bf16)
            code_gb = sum(int(np.prod(pi.codes.shape))
                          for pi in cpis) / 1e9

            def measure_capacity(probes):
                sp = ivf_pq.SearchParams(n_probes=probes, lut_dtype="int8")

                def cap_body(q, idx, dd, s=sp):
                    _, cand = ivf_pq.search(idx, q, 2 * k, s)
                    return refine.refine(dd, q, cand, k)

                tp = TwoPart(jax.jit(cap_body), cpis, coffs, k,
                             extras=[(pb,) for pb in cparts_bf16])
                thr, lat = measure_tp(
                    tp, cq,
                    floor=floor_ivf_for(probes, min(d, 128) // 2 + 4,
                                        cap_nq, len(cparts)),
                    what=f"pq capacity np{probes}", qset=cq)
                if thr is None:
                    return None
                rec = robust_call(lambda: device_recall(tp(cq)[1], cgt),
                                  "pq capacity recall")
                # baseline_key=None: BASELINE_QPS['raft_ivf_pq'] is the
                # 1M-lane derivation — a 2M/2k-batch entry normalized by
                # it reads as a regression that isn't one
                add_entry("raft_ivf_pq",
                          f"raft_ivf_pq.capacity2M.nlist1024.pq{min(d, 128)}"
                          f"x4.int8.nprobe{probes}.refine2",
                          thr, lat, rec, cap_build,
                          {"corpus_n": len(cdata), "batch_queries": cap_nq,
                           "code_gb": round(code_gb, 3),
                           "raw_gb": round(len(cdata) * d * 4 / 1e9, 3)},
                          batch=cap_nq, baseline_key=None)
                return rec

            rec_cap = measure_capacity(20)
            if rec_cap is not None and rec_cap < 0.95:
                for probes in (30, 50):
                    r = measure_capacity(probes)
                    if r is not None and r >= 0.95:
                        break
        finally:
            del cdata, cq, cparts, cbfs, ctp, cgt, cparts_bf16, cpis

    # --- dataset IO: exercise the raft-ann-bench fbin loader ------------
    try:
        dataset_io = exercise_fbin_io(data)
        log(f"# fbin round-trip: {dataset_io}")
    except Exception as e:  # noqa: BLE001
        dataset_io = {"ok": False, "error": f"{type(e).__name__}: {e}"}

    # --- roofline: report utilization against the measured chip peak ----
    log("# probing roofline")
    try:
        # micro is the CPU harness smoke: the amortized 8192-wide matmul
        # loops are minutes of host time there and probe nothing real
        peaks = roofline.probe(quick=True) if scale != "micro" else {}
    except Exception as e:  # noqa: BLE001
        log(f"# roofline probe failed ({type(e).__name__}: {e}); "
            "omitting utilization")
        peaks = {}
    # utilization of the f32 matmul entry specifically (the bf16 variant
    # divides by the bf16 peak)
    util = -1.0
    bf_f32 = [e for e in entries if e["algo"] == "raft_brute_force"
              and ".bf16" not in e["name"]]
    if bf_f32 and peaks.get("matmul_f32_tflops"):
        gemm_tflops = (2.0 * n * d * bf_f32[0]["qps"]) / 1e12
        util = gemm_tflops / max(peaks["matmul_f32_tflops"], 1e-9)

    # headline: BASELINE config 2 (ivf_flat QPS @ recall>=0.95)
    if flat_best is not None:
        value, rec, tag = flat_best
        met = True
    else:
        flat_entries = [e for e in entries if e["algo"] == "raft_ivf_flat"]
        if flat_entries:
            top = max(flat_entries, key=lambda e: e["recall"])
            value, rec, tag = top["qps"], top["recall"], top["name"]
        else:   # every ivf_flat point flaked: say so, don't substitute
            value, rec, tag = 0.0, 0.0, "no-ivf-flat-measurements"
        met = False
    # headline entry FIRST in the list: a truncated tail capture of the
    # stdout line must lose padding entries, never the headline (round 5
    # lost the headline and the 1M entries to a 2000-char tail)
    entries.sort(key=lambda e: e["name"] != tag)
    out = {
        "metric": ("ivf_flat_qps_at_recall095_synth1M" if n >= 1_000_000
                   else f"ivf_flat_qps_at_recall095_synth{n // 1000}k"),
        "value": round(value, 1),
        "unit": "queries/s",
        "vs_baseline": round(value / BASELINE_QPS["raft_ivf_flat"], 3),
        "recall": round(rec, 4),
        "recall_target_met": met,
        "corpus": {"n": n, "d": d, "nq": nq, "k": k, "parts": n_parts,
                   "kind": "low-intrinsic-dim-clustered-synthetic",
                   "mixture_scale": CORPUS_SCALE,
                   "intrinsic_d": CORPUS_INTRINSIC_D,
                   "clusters": CORPUS_CLUSTERS,
                   "queries": "fresh-mixture-samples"},
        "qps_methodology": "value-read pipelined wall over content-"
                           "distinct query permutations (GBench "
                           "items_per_second analog; host float() of an "
                           "all-outputs accumulator closes the window); "
                           "latency_ms = per-call-blocked median",
        "entries": entries,
        "dataset_io": dataset_io,
        "roofline": peaks,
        "bf_gemm_utilization_of_measured_peak": round(util, 4),
        "timing_floor_trips": _autotune.suspect_events,
        "baselines": {a: b["derivation"] for a, b in BASELINES.items()},
        # BASELINE config 5 (multi-node sharded ivf_pq) has no QPS here:
        # one physical chip. Its correctness path runs elsewhere.
        "sharded_config5": {
            "status": "validated-functionally",
            "evidence": "8-device CPU-mesh tests (tests/test_sharded_ann"
                        ".py) + driver dryrun_multichip (brute force, "
                        "ivf_pq AND cagra recall-checked vs exact) + "
                        "2-process jax.distributed DCN smoke "
                        "(RAFT_TPU_DIST_TEST=1 tests/test_distributed.py"
                        ", passed 2026-07-31)"},
    }
    # durable artifact BEFORE any stdout: the full results JSON goes to a
    # file first (fsynced), so no stdout capture window can ever drop data
    # again; the one-line stdout summary then carries the file path
    artifact = os.environ.get("RAFT_TPU_BENCH_JSON",
                              os.path.join("artifacts", "bench_full.json"))
    try:
        adir = os.path.dirname(artifact)
        if adir:
            os.makedirs(adir, exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(out, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        out["results_file"] = artifact
        log(f"# full results written to {artifact}")
    except OSError as e:
        log(f"# bench artifact write FAILED ({e}); stdout line is the "
            "only copy")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
