#!/usr/bin/env python
"""Headline benchmark: prints ONE JSON line for the driver.

Measures QPS at recall@10 for the BASELINE.md configs on a SIFT-like
synthetic corpus (clustered gaussian mixture, 1M x 128 by default —
IVF probing is partition-limited on *unclustered* gaussian noise, which
real ANN corpora are not), plus brute-force QPS and an on-device roofline
probe so kernel throughput is reported against the measured peak of the
chip actually in use.

Methodology (see raft_tpu/ops/autotune.py): every timing is a median of
per-call-blocked runs — some backends elide never-awaited dispatches, so
block-once-after-N under-reports by orders of magnitude. All data is
generated ON DEVICE (host<->device transfers through remote tunnels are
slow and would pollute build/search timings); recall is computed on
device against exact ground truth and only scalars leave the chip.

vs_baseline: reference numbers are *derived A100 estimates* (RAFT 24.02
publishes Pareto plots, not tables — BASELINE.md): each entry's
`baseline_qps` carries its derivation in the source below.
"""
import json
import os
import sys
import time

# persistent executable cache: lets the full-scale compile probe's child
# process pre-pay the fragile 1M compile for the parent. NOTE:
# ops.autotune.measure disables this cache around its fresh-executable
# re-measure — a cache hit there would replay the very executable whose
# timing is under suspicion.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# --- derived reference baselines (QPS @ recall@10 = 0.95, batch 10k) -----
# brute force:  A100 TF32 GEMM ~156 TFLOP/s; 2*n*d = 256 MFLOP/query at
#               1M x 128 -> ~600k QPS roofline; tiled select_k overhead
#               ~2x -> 300k.
# ivf_flat:     probing ~6% of a 1M corpus reads ~30 MB/query; A100 HBM
#               1.55 TB/s -> ~50k QPS.
# ivf_pq+refine: same probe fraction over 64B codes = 3.75 MB/query ->
#               ~400k QPS roofline; LUT + refine overhead ~2x -> 200k.
# cagra:        published H100 plots put graph search at ~500k-1M QPS
#               @0.95 for million-scale corpora; use 500k.
BASELINE_QPS = {
    "raft_brute_force": 300_000.0,
    "raft_ivf_flat": 50_000.0,
    "raft_ivf_pq": 200_000.0,
    "raft_cagra": 500_000.0,
}


def robust_call(fn, what: str, tries: int = 3, deadline: float = 0.0):
    """Run a build/setup stage with retries (same transport-flake story as
    median_time; builds are minutes of work we must not lose to one
    dropped connection).

    ``deadline``: absolute ``time.perf_counter()`` cutoff — when a retry
    would start past it, give up immediately instead. On fragile nights a
    single 1M-program compile retry can run 15+ minutes; without a
    deadline the ground-truth stage can consume the whole bench budget
    before any measurement exists (the caller's downscale fallback needs
    time left to be useful)."""
    for t in range(tries):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            log(f"# {what}: attempt {t + 1}/{tries} failed: "
                f"{type(e).__name__}: {e}")
            if t + 1 == tries:
                raise
            if deadline and time.perf_counter() > deadline:
                log(f"# {what}: stage deadline passed; not retrying")
                raise
            time.sleep(20 * (t + 1))


def median_time(fn, *args, reps=5, tries=3, floor=0.0):
    """Per-call-blocked median with retries: tunneled backends drop the
    remote-compile transport transiently; one flake must not kill a
    half-hour bench. Returns None after ``tries`` consecutive failures,
    or immediately when the timing is declared unreliable (a lying
    backend window is not a flake — retrying just re-trips the floor and
    re-pays fresh compiles)."""
    from raft_tpu.ops.autotune import TimingUnreliableError, measure

    for t in range(tries):
        try:
            return measure(fn, *args, reps=reps,
                           suspect_floor_s=floor)
        except TimingUnreliableError as e:
            log(f"# measurement unreliable (no retry): {e}")
            return None
        except Exception as e:  # noqa: BLE001 - transport/compile flakes
            log(f"# measurement attempt {t + 1}/{tries} failed: "
                f"{type(e).__name__}: {e}")
            if t + 1 < tries:
                time.sleep(15 * (t + 1))
    return None


import contextlib  # noqa: E402


@contextlib.contextmanager
def algo_section(name):
    """One algorithm's persistent failure (or a deliberate budget skip)
    must not cost the whole run its output line: log and continue with
    the entries recorded so far."""
    try:
        yield
    except Exception as e:  # noqa: BLE001
        log(f"# {name} section ended early ({type(e).__name__}: {e}); "
            "continuing with remaining algos")


def make_corpus(n, d, nq, n_clusters=2000, seed=0):
    """Clustered gaussian mixture + queries perturbed from corpus points
    (the structure real ANN corpora have; all on device)."""
    kc, kx, ka, kq, kp = jax.random.split(jax.random.PRNGKey(seed), 5)
    centers = jax.random.normal(kc, (n_clusters, d), jnp.float32) * 4.0
    assign = jax.random.randint(ka, (n,), 0, n_clusters)
    data = centers[assign] + jax.random.normal(kx, (n, d), jnp.float32)
    qrows = jax.random.randint(kq, (nq,), 0, n)
    queries = data[qrows] + 0.1 * jax.random.normal(kp, (nq, d), jnp.float32)
    return jax.block_until_ready(data), jax.block_until_ready(queries)


def device_recall(ids, gt):
    """Mean recall@k, computed on device; one scalar leaves the chip."""
    hit = jnp.any(ids[:, :, None] == gt[:, None, :], axis=2) & (gt >= 0)
    return float(jnp.sum(hit) / jnp.sum(gt >= 0))


# the probe compiles EXACTLY the ground-truth program (same shapes, same
# matmul engine, same workspace chunking) so a persistent-cache hit in
# the parent is possible and memory behavior matches the real path
_FULL_PROBE_SRC = """
import os, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from raft_tpu.neighbors import brute_force
n = int(os.environ.get("RAFT_TPU_PROBE_N", "1000000"))
d, nq = 128, 1000
k1, k2 = jax.random.split(jax.random.PRNGKey(99))
data = jax.random.normal(k1, (n, d), jnp.float32)
q = jax.random.normal(k2, (nq, d), jnp.float32)
jax.block_until_ready((data, q))
print("PROBE_INIT_OK", flush=True)   # backend init + device alloc worked
bfi = brute_force.build(data)
fn = jax.jit(lambda qq: brute_force.search(bfi, qq, 10, algo="matmul")[1])
jax.block_until_ready(fn(q))
print("FULL_PROBE_OK")
""".format(repo=os.path.dirname(os.path.abspath(__file__)))


def probe_full_scale_compile(timeout_s: float = 600.0,
                             n: int = 1_000_000) -> bool:
    """Compile+run an n-shape search program in a KILLABLE subprocess.

    The tunnel's compile endpoint has been observed *hanging* (not
    erroring) on 1M-scale programs for 25+ minutes while trivial probes
    pass — an in-process deadline cannot interrupt a blocked compile, so
    the probe runs where SIGKILL works. The persistent compilation cache
    (enabled in main via JAX_COMPILATION_CACHE_DIR) lets a successful
    probe's executable be reused by the parent where the backend supports
    it; where it doesn't, the probe still bounds the go/no-go decision.
    """
    import subprocess

    env = dict(os.environ)
    env["RAFT_TPU_PROBE_N"] = str(n)
    try:
        r = subprocess.run(
            [sys.executable, "-c", _FULL_PROBE_SRC],
            timeout=timeout_s, capture_output=True, text=True, env=env)
    except subprocess.TimeoutExpired:
        log(f"# {n}-scale compile probe exceeded {timeout_s:.0f}s "
            "(hung compile endpoint); downscaling")
        return False
    if r.returncode == 0 and "FULL_PROBE_OK" in r.stdout:
        return True
    err = (r.stderr or "").strip()
    log(f"# {n}-scale compile probe rc={r.returncode}: {err[-300:]}")
    if "PROBE_INIT_OK" not in (r.stdout or ""):
        # the child never got past backend init / device alloc (import
        # error, device exclusively held, ...): says nothing about the
        # program's compile viability — keep the scale; the mid-run GT
        # deadline + downscale fallback still protects it
        log("# probe failed before backend init completed; keeping scale")
        return True
    # init worked, the program itself failed: treat as a genuine
    # backend no (compile rejection / OOM / transport death)
    return False


def preflight_scale(default: str = "full", limit_s: float = 120.0,
                    probe_timeout_s: float = 600.0) -> str:
    """Backend health probe: a fresh tiny compile+run takes ~1-40s on a
    healthy chip. Tunneled backends degrade by orders of magnitude under
    shared load; recording a smaller result beats timing out on a 1M
    corpus and recording nothing. When the tiny probe passes and full
    scale is on the table, killable subprocesses prove the 1M-shape
    program actually compiles — and if 1M hangs (the tunnel's observed
    ceiling is between 500k and 1M), a 500k probe arbitrates the "mid"
    scale before falling all the way back to 100k."""
    t0 = time.perf_counter()
    try:
        x = jax.random.normal(jax.random.PRNGKey(99), (512, 512))
        jax.block_until_ready(jax.jit(lambda a: (a @ a).sum())(x))
        probe_s = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001
        log(f"# pre-flight probe failed ({type(e).__name__}); downscaling")
        probe_s = float("inf")
    if probe_s > limit_s:
        log(f"# pre-flight probe took {probe_s:.0f}s: degraded backend, "
            "downscaling corpus to 100k")
        return "small"
    if default == "full":
        if probe_full_scale_compile(probe_timeout_s):
            return "full"
        # measured 2026-07-31: 500k compiles+runs in ~134s where 1M
        # hangs >600s — half scale beats a 10x downscale
        if probe_full_scale_compile(min(probe_timeout_s, 450.0),
                                    n=500_000):
            return "mid"
        return "small"
    return default


def main():
    t_wall0 = time.perf_counter()
    budget_s = float(os.environ.get("RAFT_TPU_BENCH_BUDGET_S", "2400"))
    scale_env = os.environ.get("RAFT_TPU_BENCH_SCALE")
    scale = scale_env or "full"
    if scale_env is None:
        scale = preflight_scale(
            "full", probe_timeout_s=min(600.0, 0.25 * budget_s))
    # deduct preflight from the budget (keeping a floor for the actual
    # measurements) so total wall time stays within what the caller set,
    # while a slow compile probe doesn't starve the GT deadline
    budget_s = max(600.0, budget_s - (time.perf_counter() - t_wall0))
    t_start = time.perf_counter()
    # micro: CPU-runnable harness smoke (drives every code path in
    # minutes); small: single-chip quick run; full: the BASELINE scale
    n = {"full": 1_000_000, "mid": 500_000, "small": 100_000,
         "micro": 20_000}[scale]
    d, nq, k = 128, 10_000 if scale != "micro" else 1_000, 10
    # plausibility floor: tunnel dispatch alone is ~1 ms, and the
    # observed replay-mode lies are ~50 us — a low floor catches the lies
    # while keeping false trips (each costs one fresh recompile) rare on
    # genuinely fast windows
    suspect_floor = 0.001 if scale == "micro" else 0.002

    from raft_tpu.bench import roofline
    from raft_tpu.ops import autotune as _autotune
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq, refine

    log(f"# corpus: {n}x{d}, {nq} queries, k={k}")
    data, queries = robust_call(lambda: make_corpus(n, d, nq), "corpus")

    # ground truth: exact search, f32-accurate GEMM. Computed in
    # same-shape query chunks (one compile, reused) with per-chunk
    # retries, so a transport flake costs one chunk, not the stage.
    def compute_gt(corpus, qs):
        bfi = brute_force.build(corpus, metric="sqeuclidean")
        fn = jax.jit(
            lambda q: brute_force.search(bfi, q, k, algo="matmul")[1])
        gchunk = 1000
        # stage deadline: if full-scale GT can't land inside ~35% of the
        # budget, stop retrying so the downscale fallback still has time
        # to produce a recorded result
        gt_deadline = t_start + 0.35 * budget_s
        full_scale = len(corpus) > 100_000
        parts = []
        for c0 in range(0, nq, gchunk):
            # deadline applies before each launch too: slow-but-succeeding
            # chunks must not eat the budget any more than failing ones
            if full_scale and time.perf_counter() > gt_deadline:
                raise RuntimeError(
                    f"ground truth stage deadline exceeded at [{c0}]")
            parts.append(robust_call(
                lambda c0=c0: jax.block_until_ready(
                    fn(qs[c0 : c0 + gchunk])),
                f"ground truth [{c0}:{c0 + gchunk}]", tries=5,
                deadline=gt_deadline if full_scale else 0.0))
        return bfi, jnp.concatenate(parts)

    try:
        bf, gt = compute_gt(data, queries)
    except Exception as e:  # noqa: BLE001
        # the 1M-program compile is the tunnel's most fragile path; a
        # 100k result beats recording nothing (observed: 100k compiles
        # survive windows where 1M consistently dies). Regenerate a
        # *matched* 100k corpus+queries (slicing would orphan queries
        # perturbed from dropped rows and skew the distance structure).
        if n <= 100_000:
            raise
        log(f"# full-scale ground truth failed ({type(e).__name__}): "
            "regenerating a 100k corpus and continuing")
        n = 100_000
        data, queries = robust_call(lambda: make_corpus(n, d, nq), "corpus")
        bf, gt = compute_gt(data, queries)
    log("# ground truth done")
    # pace check: corpus+GT is ~5% of the full-pipeline device work; when
    # the backend is this slow (shared tenancy, degraded tunnel), trim the
    # sweeps to one point per algo rather than overrun the budget
    gt_elapsed = time.perf_counter() - t_start
    hurry = gt_elapsed > budget_s / 6
    if hurry:
        log(f"# slow backend (corpus+GT took {gt_elapsed:.0f}s): "
            "trimming sweeps")

    entries = []

    def add_entry(algo, name, qps, recall, build_s, extra=None):
        e = {"algo": algo, "name": name, "qps": round(qps, 1),
             "recall": round(recall, 4), "build_s": round(build_s, 1),
             "vs_baseline": round(qps / BASELINE_QPS[algo], 3)}
        if extra:
            e.update(extra)
        entries.append(e)
        log(f"#   {name}: qps={qps:,.0f} recall={recall:.4f}")

    # --- brute force (BASELINE config 1): measured-best engine ----------
    with algo_section('brute_force'):
        winner, timings = robust_call(
            lambda: brute_force.tune_search(bf, queries, k, reps=3,
                                            suspect_floor_s=suspect_floor),
            "engine autotune")
        # all lanes pass the index as a jit ARGUMENT (not closure):
        # baked index constants exceed remote-compile request limits at
        # memory scale (observed HTTP 413 at 500k)
        sfn = jax.jit(lambda q, idx: brute_force.search(idx, q, k,
                                                        algo=winner))
        dt = median_time(sfn, queries, bf, floor=suspect_floor)
        if dt is not None:
            add_entry("raft_brute_force", f"raft_brute_force.{winner}",
                      nq / dt, 1.0, 0.0,
                      {"engine_timings_ms":
                       {kk: round(v * 1e3, 1) for kk, v in timings.items()}})
        # bf16 storage: half the scan HBM traffic (the exact path's
        # bandwidth bound); recall measured against the f32 ground truth.
        # Optional variant — skipped in hurry mode.
        if not hurry:
            bf16i = robust_call(
                lambda: brute_force.build(data, dtype=jnp.bfloat16),
                "brute bf16 build")
            hfn = jax.jit(lambda q, idx: brute_force.search(
                idx, q, k, algo="matmul"))
            dt = median_time(hfn, queries, bf16i, floor=suspect_floor)
            if dt is not None:
                rec = robust_call(
                    lambda: device_recall(hfn(queries, bf16i)[1], gt),
                    "brute bf16 recall")
                add_entry("raft_brute_force", "raft_brute_force.matmul.bf16",
                          nq / dt, rec, 0.0)

    # --- ivf_flat (config 2: n_lists=1024, probe sweep) -----------------
    with algo_section('ivf_flat'):
        flat_best = None
        t0 = time.perf_counter()
        fi = robust_call(lambda: ivf_flat.build(
            data, ivf_flat.IndexParams(n_lists=1024, seed=0)), "ivf_flat build")
        jax.block_until_ready(jax.tree.leaves(fi))
        flat_build = time.perf_counter() - t0
        ivf_flat.prepare_scan(fi)   # scan prep out of the timed search graph
        log(f"# ivf_flat built in {flat_build:.0f}s")
        def measure_flat(probes):
            nonlocal flat_best
            sp = ivf_flat.SearchParams(n_probes=probes)
            # index as jit ARGUMENT (not closure): see the ivf_pq lane
            fn = jax.jit(lambda q, idx, s=sp: ivf_flat.search(idx, q, k, s))
            dt = median_time(fn, queries, fi, floor=suspect_floor)
            if dt is None:
                return None
            rec = robust_call(lambda: device_recall(fn(queries, fi)[1], gt),
                              "ivf_flat recall")
            add_entry("raft_ivf_flat",
                      f"raft_ivf_flat.nlist1024.nprobe{probes}",
                      nq / dt, rec, flat_build)
            # update the headline candidate AS measured: a later-probe
            # failure swallowed by algo_section must not discard an
            # already-measured qualifying point
            if rec >= 0.95 and (flat_best is None or nq / dt > flat_best[0]):
                flat_best = (nq / dt, rec, f"nprobe{probes}")
            return rec

        # the BASELINE config-2 anchor (nprobe=20) is always measured;
        # then walk the probe count DOWN while recall holds ≥0.95 (fewer
        # probes = proportionally less list scanning = the headline
        # lever), or UP if the anchor misses the target
        best_probes = 20
        rec20 = measure_flat(20)
        if not hurry and rec20 is not None:
            if rec20 >= 0.95:
                for probes in (10, 5):
                    r = measure_flat(probes)
                    if r is None or r < 0.95:
                        break
                    best_probes = probes
            else:
                for probes in (50, 100):
                    best_probes = probes
                    r = measure_flat(probes)
                    if r is not None and r >= 0.95:
                        break
        # bf16 list storage at the best qualifying probe count: half the
        # list-scan HBM traffic for ~1e-3 relative distance error.
        # Optional variant — skipped in hurry mode.
        if not hurry:
            t0 = time.perf_counter()
            fih = robust_call(lambda: ivf_flat.build(
                data, ivf_flat.IndexParams(n_lists=1024, seed=0,
                                           dtype="bfloat16")),
                "ivf_flat bf16 build")
            jax.block_until_ready(jax.tree.leaves(fih))
            bf16_build = time.perf_counter() - t0
            ivf_flat.prepare_scan(fih)
            fnh = jax.jit(lambda q, idx: ivf_flat.search(
                idx, q, k, ivf_flat.SearchParams(n_probes=best_probes)))
            dt = median_time(fnh, queries, fih, floor=suspect_floor)
            if dt is not None:
                rec = robust_call(
                    lambda: device_recall(fnh(queries, fih)[1], gt),
                    "ivf_flat bf16 recall")
                add_entry("raft_ivf_flat",
                          f"raft_ivf_flat.nlist1024.nprobe{best_probes}"
                          ".bf16",
                          nq / dt, rec, bf16_build)
                if rec >= 0.95 and nq / dt > (flat_best or (0,))[0]:
                    flat_best = (nq / dt, rec, f"nprobe{best_probes}.bf16")

    # --- ivf_pq (config 3: pq_dim=64) + refine --------------------------
    with algo_section('ivf_pq'):
        t0 = time.perf_counter()
        pi = robust_call(lambda: ivf_pq.build(
            data, ivf_pq.IndexParams(n_lists=1024, pq_dim=64, seed=0)),
            "ivf_pq build")
        jax.block_until_ready(jax.tree.leaves(pi))
        pq_build = time.perf_counter() - t0
        ivf_pq.prepare_scan(pi)     # scan prep out of the timed search graph
        log(f"# ivf_pq built in {pq_build:.0f}s")
        # sweep the refine ratio (the recall axis once probes stop binding —
        # measured: recall plateaus in n_probes at fixed candidate count)
        # and a reduced-probe point (the QPS axis, as in the ivf_flat walk)
        def measure_pq(probes, ratio):
            sp = ivf_pq.SearchParams(n_probes=probes)

            # index + corpus ride as jit ARGUMENTS (the Index pytree
            # carries its scan-prep cache): closure-baking them as HLO
            # constants exceeds the tunnel's remote-compile request
            # limit at 500k rows (observed HTTP 413). Queries stay the
            # FIRST argument — measure()'s anti-replay perturbation
            # keys off args[0] being a float array.
            def pq_refined(q, idx, dd, s=sp, r=ratio):
                _, cand = ivf_pq.search(idx, q, r * k, s)
                return refine.refine(dd, q, cand, k)

            fn = jax.jit(pq_refined)
            dt = median_time(fn, queries, pi, data, floor=suspect_floor)
            if dt is None:
                return None
            rec = robust_call(
                lambda: device_recall(fn(queries, pi, data)[1], gt),
                "ivf_pq recall")
            add_entry("raft_ivf_pq",
                      f"raft_ivf_pq.nlist1024.pq64.nprobe{probes}"
                      f".refine{ratio}",
                      nq / dt, rec, pq_build)
            return rec

        rec_a = measure_pq(20, 2)
        if not hurry:
            if rec_a is None:
                # a transient anchor failure must not zero the lane:
                # still record the secondary operating points
                measure_pq(10, 2)
                measure_pq(20, 4)
            elif rec_a >= 0.95:
                measure_pq(10, 2)
                if rec_a < 0.995:
                    measure_pq(20, 4)
            else:
                # at bigger corpora the anchor misses 0.95 (bigger lists
                # per probe, same candidate count): walk recall up via
                # refine ratio first (cheap), then probes
                for probes, ratio in ((20, 4), (50, 4)):
                    r = measure_pq(probes, ratio)
                    if r is not None and r >= 0.95:
                        break

    # --- cagra (config 4: graph_degree=64) ------------------------------
    with algo_section('cagra'):
        remaining = budget_s - (time.perf_counter() - t_start)
        # full-corpus CAGRA builds only when the budget clearly allows
        # (a 500k optimize pass alone is ~15 min through the tunnel);
        # mid/small scales cap the graph corpus at 100k
        cagra_n = n if remaining > 1200 and scale == "full" else \
            min(n, 100_000 if scale != "micro" else 20_000)
        cagra_env = os.environ.get("RAFT_TPU_BENCH_CAGRA_N")
        if cagra_env:
            cagra_n = int(cagra_env)
        else:
            # budget gate scaled to the corpus actually being built (100k
            # builds have taken 500-1300s in degraded windows; small builds
            # are cheap) — a recorded three-algo result beats dying
            # mid-build. An explicit CAGRA_N override always runs: the
            # operator asked for this data point.
            need_s = 700 if cagra_n > 50_000 else 120
            from raft_tpu.core.errors import expects as _expects
            _expects(remaining > need_s,
                     "budget skip: %.0fs left < %ds needed for a %d-row "
                     "cagra build", remaining, need_s, cagra_n)
        cdata = data[:cagra_n]
        if cagra_n != n:
            # corpus as a jit argument (not closure) like every other
            # lane: a 500k+ CAGRA_N override must not 413 the section
            cgt_fn = jax.jit(lambda q, cd: brute_force.search(
                brute_force.build(cd), q, k, algo="matmul"))
            _, cgt = cgt_fn(queries, cdata)
        else:
            cgt = gt
        t0 = time.perf_counter()
        ci = robust_call(lambda: cagra.build(cdata, cagra.IndexParams(
            graph_degree=64, intermediate_graph_degree=96, seed=0)),
            "cagra build")
        jax.block_until_ready(jax.tree.leaves(ci))
        cagra_build = time.perf_counter() - t0
        cagra.prepare_search(ci)    # bf16 traversal copy out of the timed graph
        log(f"# cagra built ({cagra_n} rows) in {cagra_build:.0f}s")
        # sweep (itopk, search_width, max_iterations): the covering seed
        # set (one GEMM) plus a few gather-bound hops is the operating
        # regime — measured sweep 2026-07-31 (seeds=1558, 100k corpus):
        # (16,8,mi2) 58.6k @ 0.956, (32,4,mi3) 58.6k @ 0.959,
        # (32,4,mi5) 47.0k @ 0.972, (64,4,mi8) 29.6k @ 0.982;
        # vs 31.8k @ 0.948 for the best random-seeded point
        sweep = (((32, 4, 5),) if hurry
                 else ((16, 8, 2), (32, 4, 3), (32, 4, 5), (64, 4, 8)))
        opener = sweep[0]
        for itopk, width, mi in sweep:
            sp = cagra.SearchParams(itopk_size=itopk, search_width=width,
                                    max_iterations=mi)
            fn = jax.jit(lambda q, idx, s=sp: cagra.search(idx, q, k, s))
            dt = median_time(fn, queries, ci, reps=3, floor=suspect_floor)
            if dt is None:
                continue
            rec = robust_call(lambda: device_recall(fn(queries, ci)[1], cgt),
                              "cagra recall")
            add_entry("raft_cagra",
                      f"raft_cagra.degree64.itopk{itopk}.w{width}"
                      f".mi{mi or 'auto'}",
                      nq / dt, rec, cagra_build, {"corpus_n": cagra_n})
            # never break on the low-recall opener: the baseline-comparable
            # ≥0.95-recall anchor must always be measured
            if rec >= 0.995 and (itopk, width, mi) != opener:
                break

    # --- roofline: report utilization against the measured chip peak ----
    # never let the probe kill the run: after an earlier section OOMs,
    # the backend can stay resource-exhausted, and losing the JSON line
    # over a diagnostic probe would discard every recorded measurement
    log("# probing roofline")
    try:
        peaks = roofline.probe(quick=True)
    except Exception as e:  # noqa: BLE001
        log(f"# roofline probe failed ({type(e).__name__}: {e}); "
            "omitting utilization")
        peaks = {}
    bf_entries = [e for e in entries if e["algo"] == "raft_brute_force"]
    if bf_entries and peaks.get("matmul_f32_tflops"):
        gemm_tflops = 2.0 * nq * n * d / (nq / bf_entries[0]["qps"]) / 1e12
        util = gemm_tflops / max(peaks["matmul_f32_tflops"], 1e-9)
    else:
        util = -1.0

    # headline: BASELINE config 2 (ivf_flat QPS @ recall>=0.95)
    if flat_best is not None:
        value, rec, tag = flat_best
        met = True
    else:
        flat_entries = [e for e in entries if e["algo"] == "raft_ivf_flat"]
        if flat_entries:
            top = max(flat_entries, key=lambda e: e["recall"])
            value, rec, tag = top["qps"], top["recall"], top["name"]
        else:   # every ivf_flat point flaked: say so, don't substitute
            value, rec, tag = 0.0, 0.0, "no-ivf-flat-measurements"
        met = False
    out = {
        "metric": ("ivf_flat_qps_at_recall095_synth1M" if n >= 1_000_000
                   else f"ivf_flat_qps_at_recall095_synth{n // 1000}k"),
        "value": round(value, 1),
        "unit": "queries/s",
        "vs_baseline": round(value / BASELINE_QPS["raft_ivf_flat"], 3),
        "recall": round(rec, 4),
        "recall_target_met": met,
        "corpus": {"n": n, "d": d, "nq": nq, "k": k,
                   "kind": "clustered-gaussian-synthetic"},
        "entries": entries,
        "roofline": peaks,
        "bf_gemm_utilization_of_measured_peak": round(util, 4),
        # how many timings tripped the plausibility floor and were
        # re-measured through a fresh executable (ops.autotune.measure)
        "timing_floor_trips": _autotune.suspect_events,
        # BASELINE config 5 (multi-node sharded ivf_pq) has no QPS here:
        # one physical chip. Its correctness path runs elsewhere.
        "sharded_config5": {
            "status": "validated-functionally",
            "evidence": "8-device CPU-mesh tests (tests/test_sharded_ann"
                        ".py) + driver dryrun_multichip (sharded brute "
                        "force AND ivf_pq steps); no multi-chip hardware "
                        "for QPS"},
        "baseline_note": "derived A100 estimates (see bench.py); RAFT "
                         "24.02 publishes plots, not tables",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
