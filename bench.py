#!/usr/bin/env python
"""Headline benchmark: prints ONE JSON line for the driver.

Metric: brute-force kNN QPS on a SIFT-like synthetic workload (L2, k=10),
the first BASELINE.md config. Will widen to IVF/CAGRA QPS@recall as those
land. vs_baseline compares against a fixed reference throughput target.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from raft_tpu.neighbors import brute_force

    n, d, nq, k = 100_000, 128, 10_000, 10
    rng = np.random.default_rng(0)
    dataset = jnp.asarray(rng.standard_normal((n, d), dtype=np.float32))
    queries = jnp.asarray(rng.standard_normal((nq, d), dtype=np.float32))

    index = brute_force.build(dataset, metric="sqeuclidean")
    # warmup/compile at the measured shape
    dist, idx = brute_force.search(index, queries, k)
    jax.block_until_ready((dist, idx))

    t0 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        dist, idx = brute_force.search(index, queries, k)
        jax.block_until_ready((dist, idx))
    dt = (time.perf_counter() - t0) / reps
    qps = nq / dt

    # Reference point: RAFT brute-force on A100 is ~O(10k) QPS at this shape;
    # use 10k QPS as the provisional baseline until the harness regenerates it.
    baseline_qps = 10_000.0
    out = {
        "metric": "brute_force_knn_qps_100k_d128_k10",
        "value": round(qps, 2),
        "unit": "queries/s",
        "vs_baseline": round(qps / baseline_qps, 3),
    }
    if jax.default_backend() == "tpu":
        # roofline accounting for the fused kernel (the path auto-dispatch
        # takes on TPU; off-TPU the scan fallback ran and these numbers
        # would describe a kernel that never executed): GEMM flops and one
        # full dataset HBM read per query tile, tile size from the kernel's
        # own heuristic
        import importlib
        import math
        _pick = importlib.import_module("raft_tpu.ops.fused_knn")._pick_tiles
        tm, _ = _pick(d, k)
        n_qtiles = math.ceil(nq / tm)
        out["achieved_gflops"] = round(2.0 * nq * n * d / dt / 1e9, 1)
        out["hbm_read_gbps"] = round(n_qtiles * n * d * 4 / dt / 1e9, 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
