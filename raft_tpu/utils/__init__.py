"""Small integer/shape utilities shared across raft_tpu.

TPU analog of the reference's ``raft/util/`` helpers (pow2_utils.cuh,
integer_utils.hpp): alignment and tiling arithmetic used to size Pallas
blocks and padded layouts.
"""
from __future__ import annotations

import math

__all__ = [
    "cdiv",
    "env_float",
    "hdot",
    "round_up_to",
    "round_down_to",
    "next_pow2",
    "is_pow2",
    "pad_to",
    "run_query_chunks",
    "shard_map_compat",
    "LANES",
    "SUBLANES_F32",
    "SUBLANES_BF16",
]

# TPU register tiling: last dim is always 128 lanes; sublane count depends on
# dtype (8 for f32, 16 for bf16, 32 for int8).
LANES = 128
SUBLANES_F32 = 8
SUBLANES_BF16 = 16


def cdiv(a: int, b: int) -> int:
    """Ceiling division."""
    return -(-a // b)


def env_float(name: str, default: float) -> float:
    """Float env knob with a silent fall-back to ``default`` on unset or
    unparseable values (operator knobs must never crash a serving
    process over a typo)."""
    import os

    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    """Integer twin of :func:`env_float` — same never-crash contract."""
    import os

    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def round_up_to(x: int, m: int) -> int:
    """Round ``x`` up to the nearest multiple of ``m``."""
    return cdiv(x, m) * m


def round_down_to(x: int, m: int) -> int:
    """Round ``x`` down to the nearest multiple of ``m``."""
    return (x // m) * m


def next_pow2(x: int) -> int:
    """Smallest power of two >= x (x >= 1)."""
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def is_pow2(x: int) -> bool:
    return x > 0 and (x & (x - 1)) == 0


def pad_to(x: int, m: int) -> int:
    """Amount of padding needed to reach the next multiple of ``m``."""
    return round_up_to(x, m) - x


def log2i(x: int) -> int:
    """Integer log2 of a power of two."""
    return int(math.log2(x))


def hdot(x, y):
    """f32-accurate matmul (MXU 3-pass; JAX's default precision does
    single-pass bf16 multiplies, ~1e-3 relative distance error — enough to
    mis-rank near-ties in exact kNN). Matches the reference's fp32 cuBLAS
    GEMMs (linalg/gemm.cuh)."""
    import jax.numpy as jnp

    return jnp.matmul(x, y, precision="highest")


def run_query_chunks(fn, q, chunk: int, res=None):
    """THE chunked-search loop: apply ``fn((m_c, d) chunk, start_row)``
    over row-chunks of ``q`` and concatenate the (vals, ids) pairs.

    ``res`` (a Resources or bare Deadline, optional) adds a
    cancellation + deadline checkpoint between chunk dispatches;
    ``DeadlineExceeded`` carries the completed chunks' partial results.
    Every chunked search entry point and guarded XLA fallback routes
    through this one audited implementation."""
    from ..core import deadline

    outs_d, outs_i = [], []
    for s0 in range(0, q.shape[0], chunk):
        deadline.checkpoint(
            res, partial=lambda: deadline.partial_topk(outs_d, outs_i))
        d_c, i_c = fn(q[s0 : s0 + chunk], s0)
        outs_d.append(d_c)
        outs_i.append(i_c)
    if len(outs_d) == 1:
        return outs_d[0], outs_i[0]
    import jax.numpy as jnp

    return jnp.concatenate(outs_d), jnp.concatenate(outs_i)


def shard_map_compat(f, mesh, in_specs, out_specs, check=False):
    """``jax.shard_map`` across jax versions (resilience: a version skew
    must degrade to the equivalent API, not crash the sharded path).
    Newer jax exposes ``jax.shard_map(..., check_vma=)``; the promotion
    window spelled the kwarg ``check_rep``; older releases only have
    ``jax.experimental.shard_map.shard_map(..., check_rep=)``. The kwarg
    is feature-tested, not version-guessed."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check)
    except TypeError:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check)


def in_jax_trace() -> bool:
    """True when called during a jax trace (jit/vmap/...). Used to gate
    side-effecting caches: storing traced arrays on a Python object leaks
    tracers out of the transformation."""
    try:
        from jax._src.core import trace_state_clean

        return not trace_state_clean()
    except ImportError:  # fallback probe: ops under a trace yield Tracers
        import jax
        import jax.numpy as jnp

        return isinstance(jnp.zeros(()) + 0, jax.core.Tracer)
