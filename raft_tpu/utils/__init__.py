"""Small integer/shape utilities shared across raft_tpu.

TPU analog of the reference's ``raft/util/`` helpers (pow2_utils.cuh,
integer_utils.hpp): alignment and tiling arithmetic used to size Pallas
blocks and padded layouts.
"""
from __future__ import annotations

import math

__all__ = [
    "cdiv",
    "hdot",
    "round_up_to",
    "round_down_to",
    "next_pow2",
    "is_pow2",
    "pad_to",
    "LANES",
    "SUBLANES_F32",
    "SUBLANES_BF16",
]

# TPU register tiling: last dim is always 128 lanes; sublane count depends on
# dtype (8 for f32, 16 for bf16, 32 for int8).
LANES = 128
SUBLANES_F32 = 8
SUBLANES_BF16 = 16


def cdiv(a: int, b: int) -> int:
    """Ceiling division."""
    return -(-a // b)


def round_up_to(x: int, m: int) -> int:
    """Round ``x`` up to the nearest multiple of ``m``."""
    return cdiv(x, m) * m


def round_down_to(x: int, m: int) -> int:
    """Round ``x`` down to the nearest multiple of ``m``."""
    return (x // m) * m


def next_pow2(x: int) -> int:
    """Smallest power of two >= x (x >= 1)."""
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def is_pow2(x: int) -> bool:
    return x > 0 and (x & (x - 1)) == 0


def pad_to(x: int, m: int) -> int:
    """Amount of padding needed to reach the next multiple of ``m``."""
    return round_up_to(x, m) - x


def log2i(x: int) -> int:
    """Integer log2 of a power of two."""
    return int(math.log2(x))


def hdot(x, y):
    """f32-accurate matmul (MXU 3-pass; JAX's default precision does
    single-pass bf16 multiplies, ~1e-3 relative distance error — enough to
    mis-rank near-ties in exact kNN). Matches the reference's fp32 cuBLAS
    GEMMs (linalg/gemm.cuh)."""
    import jax.numpy as jnp

    return jnp.matmul(x, y, precision="highest")


def in_jax_trace() -> bool:
    """True when called during a jax trace (jit/vmap/...). Used to gate
    side-effecting caches: storing traced arrays on a Python object leaks
    tracers out of the transformation."""
    try:
        from jax._src.core import trace_state_clean

        return not trace_state_clean()
    except ImportError:  # fallback probe: ops under a trace yield Tracers
        import jax
        import jax.numpy as jnp

        return isinstance(jnp.zeros(()) + 0, jax.core.Tracer)
