"""Spectral graph partitioning: analog of ``raft/spectral/``.

Reference: spectral/partition.cuh:33 (partition = Lanczos smallest
eigenpairs of the Laplacian → kmeans on the embedding),
spectral/eigen_solvers.cuh (lanczos wrapper), cluster_solvers.cuh
(kmeans wrapper), and analyzePartition (edge cut / cost metrics).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects

__all__ = ["laplacian", "fit_embedding", "partition", "analyze_partition"]


def laplacian(graph, normalized: bool = False):
    """Graph Laplacian L = D - A as COO (spectral/matrix_wrappers
    laplacian_matrix_t role)."""
    from ..sparse import COO
    from ..sparse.linalg import symmetrize

    coo = graph.to_coo() if hasattr(graph, "to_coo") else graph
    coo = symmetrize(coo, op="max")
    n = coo.shape[0]
    deg = np.zeros(n, np.float64)
    np.add.at(deg, np.asarray(coo.rows), np.asarray(coo.vals, np.float64))
    if normalized:
        dinv = 1.0 / np.sqrt(np.maximum(deg, 1e-12))
        off_vals = -np.asarray(coo.vals, np.float64) * \
            dinv[np.asarray(coo.rows)] * dinv[np.asarray(coo.cols)]
        diag_vals = np.ones(n)
    else:
        off_vals = -np.asarray(coo.vals, np.float64)
        diag_vals = deg
    rows = np.concatenate([np.asarray(coo.rows), np.arange(n)])
    cols = np.concatenate([np.asarray(coo.cols), np.arange(n)])
    vals = np.concatenate([off_vals, diag_vals]).astype(np.float32)
    return COO(jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32),
               jnp.asarray(vals), (n, n))


def fit_embedding(graph, n_components: int = 2, seed: int = 0,
                  normalized: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Smallest nontrivial Laplacian eigenpairs → (eigenvalues,
    embedding (n, n_components)) — partition.cuh step 1-2."""
    from ..sparse import lanczos_smallest

    lap = laplacian(graph, normalized)
    vals, vecs = lanczos_smallest(lap, n_components + 1, seed=seed)
    # drop the trivial constant eigenvector (eigenvalue ~0)
    return vals[1:], vecs[:, 1:]


def partition(graph, n_clusters: int, n_components: int = 0, seed: int = 0
              ) -> Tuple[np.ndarray, jax.Array, jax.Array]:
    """Spectral partition (partition.cuh:33) → (labels, eigenvalues,
    embedding): Lanczos embedding + kmeans labels."""
    from ..cluster import kmeans

    if n_components <= 0:
        n_components = max(2, n_clusters - 1)
    vals, emb = fit_embedding(graph, n_components, seed)
    labels, _, _ = kmeans.fit_predict(
        np.asarray(emb),
        kmeans.KMeansParams(n_clusters=n_clusters, seed=seed))
    return np.asarray(labels), vals, emb


def analyze_partition(graph, labels) -> Tuple[float, float]:
    """(edge_cut, cost) of a partition (partition.cuh analyzePartition)."""
    coo = graph.to_coo() if hasattr(graph, "to_coo") else graph
    l = np.asarray(labels)
    r = np.asarray(coo.rows)
    c = np.asarray(coo.cols)
    v = np.asarray(coo.vals, np.float64)
    cut = float(v[l[r] != l[c]].sum()) / 2.0  # undirected: each edge twice
    sizes = np.bincount(l)
    cost = float((sizes.astype(np.float64) ** 2).sum())
    return cut, cost
