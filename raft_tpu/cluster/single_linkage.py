"""Single-linkage agglomerative clustering: analog of
``raft::cluster::single_linkage``.

Reference: cluster/detail/{connectivities,mst,agglomerative,
single_linkage}.cuh — kNN-graph connectivities → MST → dendrogram →
flat labels at n_clusters.

TPU design: the kNN graph comes from the fused brute-force kernel
(connectivities_knn analog, exact), the MST from the sparse Boruvka
solver; dendrogram/label extraction is host union-find (agglomerative.cuh
runs host-side in the reference too).
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import tracing
from ..core.errors import expects

__all__ = ["SingleLinkageOutput", "single_linkage"]


@dataclasses.dataclass
class SingleLinkageOutput:
    """Mirror of raft::cluster::linkage_output."""

    labels: np.ndarray          # (n,) flat cluster labels
    children: np.ndarray        # (n-1, 2) merged cluster ids (scipy layout)
    deltas: np.ndarray          # (n-1,) merge distances
    sizes: np.ndarray           # (n-1,) merged cluster sizes
    n_clusters: int


def _knn_connectivities(x: np.ndarray, c: int):
    """Symmetric kNN edge list via the exact brute-force path
    (detail/connectivities.cuh knn_graph_connectivities)."""
    from ..neighbors import brute_force

    n = len(x)
    k = min(c + 1, n)
    d, i = brute_force.knn(x, x, k, metric="sqeuclidean")
    d, i = np.asarray(d), np.asarray(i)
    rows = np.repeat(np.arange(n, dtype=np.int32), k)
    cols = i.reshape(-1)
    vals = np.sqrt(np.maximum(d.reshape(-1), 0.0))
    keep = (cols >= 0) & (cols != rows)
    return rows[keep], cols[keep], vals[keep]


def _connect_components(x, ms, md, mw, n):
    """Bridge a disconnected kNN forest: per round, every component adds its
    minimum cross-component edge (detail/connectivities.cuh
    connect_components / FixConnectivitiesRedOp role), Boruvka-style until
    one tree remains. Cross edges carry true L2 distances. The per-round
    engine is one vectorized cross_component_nn scan (all components at
    once), not a search per component."""
    from ..sparse.neighbors import cross_component_nn

    ms, md, mw = list(ms), list(md), list(mw)
    for _ in range(64):
        parent = np.arange(n)

        def find(i):
            while parent[i] != i:
                parent[i] = parent[parent[i]]
                i = parent[i]
            return i

        for a, b in zip(ms, md):
            ra, rb = find(int(a)), find(int(b))
            if ra != rb:
                parent[max(ra, rb)] = min(ra, rb)
        comp = np.array([find(i) for i in range(n)])
        comps, comp_dense = np.unique(comp, return_inverse=True)
        if len(comps) == 1:
            break
        d, i = cross_component_nn(x, jnp.asarray(comp_dense))
        d, i = np.asarray(d), np.asarray(i)
        for c in range(len(comps)):               # min edge per component
            members = np.nonzero(comp_dense == c)[0]
            best = members[np.argmin(d[members])]
            ms.append(int(best))
            md.append(int(i[best]))
            mw.append(float(np.sqrt(max(d[best], 0.0))))
    # the added bridges may include duplicates across components; the
    # dendrogram pass ignores cycle edges, but trim to a forest here so the
    # n-1 contract holds
    parent = np.arange(n)

    def find2(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    order = np.argsort(np.asarray(mw), kind="stable")
    ks, kd, kw = [], [], []
    for e in order:
        ra, rb = find2(int(ms[e])), find2(int(md[e]))
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
            ks.append(int(ms[e]))
            kd.append(int(md[e]))
            kw.append(float(mw[e]))
    return (np.asarray(ks, np.int32), np.asarray(kd, np.int32),
            np.asarray(kw, np.float32))


@tracing.annotate("raft_tpu::cluster::single_linkage")
def single_linkage(x, n_clusters: int, c: int = 15) -> SingleLinkageOutput:
    """Fit single-linkage over a c-NN connectivity graph
    (single_linkage.cuh API: x, n_clusters, c)."""
    from ..sparse import COO, mst

    x = np.asarray(x, np.float32)
    n = len(x)
    expects(1 <= n_clusters <= n, "bad n_clusters %d", n_clusters)

    rows, cols, vals = _knn_connectivities(x, c)
    coo = COO(jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(vals),
              (n, n))
    ms, md, mw = mst(coo)
    if len(mw) < n - 1:
        ms, md, mw = _connect_components(x, ms, md, mw, n)
    expects(len(mw) == n - 1, "could not connect kNN graph (%d of %d edges)",
            len(mw), n - 1)

    # dendrogram: merge MST edges ascending (scipy linkage layout:
    # cluster ids >= n are merge nodes)
    order = np.argsort(mw, kind="stable")
    parent = np.arange(2 * n - 1)
    cluster_of = np.arange(n)       # current scipy-id of each root
    size = np.ones(2 * n - 1, np.int64)
    children = np.zeros((n - 1, 2), np.int64)
    deltas = np.zeros(n - 1, np.float64)
    sizes = np.zeros(n - 1, np.int64)

    def find(p, x0):
        while p[x0] != x0:
            p[x0] = p[p[x0]]
            x0 = p[x0]
        return x0

    for t, e in enumerate(order):
        ra, rb = find(parent, int(ms[e])), find(parent, int(md[e]))
        ca, cb = cluster_of[ra], cluster_of[rb]
        children[t] = (min(ca, cb), max(ca, cb))
        deltas[t] = mw[e]
        new_id = n + t
        sizes[t] = size[ca] + size[cb]
        size[new_id] = sizes[t]
        root = min(ra, rb)
        parent[max(ra, rb)] = root
        cluster_of[root] = new_id

    # flat labels: cut before the last (n_clusters - 1) merges
    parent = np.arange(n)
    for t, e in enumerate(order[: n - n_clusters]):
        ra, rb = find(parent, int(ms[e])), find(parent, int(md[e]))
        parent[max(ra, rb)] = min(ra, rb)
    roots = np.array([find(parent, i) for i in range(n)])
    _, labels = np.unique(roots, return_inverse=True)
    return SingleLinkageOutput(labels.astype(np.int32), children, deltas,
                               sizes, n_clusters)
