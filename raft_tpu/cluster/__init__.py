"""Clustering: Lloyd k-means, balanced hierarchical k-means, single-linkage
(SURVEY.md §2.7). single_linkage lands with the sparse/MST subsystem."""
from . import kmeans, kmeans_balanced

__all__ = ["kmeans", "kmeans_balanced"]
