"""Clustering: Lloyd k-means, balanced hierarchical k-means, single-linkage
(SURVEY.md §2.7)."""
from . import kmeans, kmeans_balanced
from .single_linkage import SingleLinkageOutput, single_linkage

__all__ = ["kmeans", "kmeans_balanced", "single_linkage",
           "SingleLinkageOutput"]
