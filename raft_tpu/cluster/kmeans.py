"""Lloyd's k-means with k-means++ init: analog of ``raft::cluster::kmeans``.

Reference: raft/cluster/kmeans.cuh:88,152,215 and detail/kmeans.cuh (1254
LoC): kmeans++ init (sampleCentroids), fit/predict/fit_predict/transform,
mini-batch variant, cluster_cost.

TPU design: the label assignment is the fused L2+argmin scan
(distance/fused_l2_nn.py) — the same hot loop the reference uses
(fused_l2_nn inside kmeans predict); centroid update is one
`segment_sum`, which XLA lowers to an efficient scatter-add; the Lloyd
iteration is a `lax.while_loop` on (centers, shift), so the whole fit is a
single compiled program with no host round-trips.
"""
from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import interop, tracing
from ..core.errors import expects
from ..distance.fused_l2_nn import fused_l2_nn_argmin
from ..distance.pairwise import pairwise_distance

__all__ = [
    "InitMethod", "KMeansParams", "init_plus_plus", "fit", "predict",
    "fit_predict", "transform", "cluster_cost", "compute_new_centroids",
    "fit_mini_batch", "auto_find_k",
]


class InitMethod(enum.Enum):
    """kmeans.cuh InitMethod."""

    KMeansPlusPlus = "kmeans++"
    Random = "random"
    Array = "array"


@dataclasses.dataclass
class KMeansParams:
    """Mirror of raft::cluster::kmeans::params (kmeans_types.hpp)."""

    n_clusters: int = 8
    init: InitMethod = InitMethod.KMeansPlusPlus
    max_iter: int = 300
    tol: float = 1e-4
    seed: int = 0
    metric: str = "sqeuclidean"
    n_init: int = 1
    oversampling_factor: float = 2.0   # accepted for parity; ++ init is exact
    batch_samples: int = 1 << 15       # mini-batch size


@partial(jax.jit, static_argnums=(2,))
def _plus_plus(key, x, k):
    """Exact k-means++ D² sampling, one center per scan step."""
    n = x.shape[0]
    key, sub = jax.random.split(key)
    first = x[jax.random.randint(sub, (), 0, n)]

    def step(carry, key_i):
        centers, min_d2, i = carry
        newest = centers[i]
        d2 = jnp.sum((x - newest[None, :]) ** 2, axis=1)
        min_d2 = jnp.minimum(min_d2, d2)
        probs = min_d2 / jnp.maximum(jnp.sum(min_d2), 1e-30)
        nxt = x[jax.random.categorical(key_i, jnp.log(jnp.maximum(probs, 1e-30)))]
        centers = centers.at[i + 1].set(nxt)
        return (centers, min_d2, i + 1), None

    centers0 = jnp.zeros((k, x.shape[1]), x.dtype).at[0].set(first)
    init = (centers0, jnp.full((n,), jnp.inf, jnp.float32), 0)
    keys = jax.random.split(key, k - 1)
    (centers, _, _), _ = jax.lax.scan(step, init, keys)
    return centers


@interop.auto_convert_output
def init_plus_plus(x, n_clusters: int, seed: int = 0) -> jax.Array:
    """Public k-means++ seeding (analog of kmeans::init_plus_plus)."""
    x = jnp.asarray(x, jnp.float32)
    expects(n_clusters <= x.shape[0], "n_clusters %d > n_samples %d",
            n_clusters, x.shape[0])
    return _plus_plus(jax.random.key(seed), x, n_clusters)


def _update_centers(x, labels, k, old_centers):
    """Segment-sum centroid update; empty clusters keep their old center
    (the reference re-seeds them in adjust_centers — balanced kmeans does)."""
    sums = jax.ops.segment_sum(x, labels, num_segments=k)
    counts = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), labels,
                                 num_segments=k)
    safe = jnp.maximum(counts, 1.0)
    centers = sums / safe[:, None]
    return jnp.where((counts > 0)[:, None], centers, old_centers), counts


@interop.auto_convert_output
def compute_new_centroids(x, centroids, labels=None):
    """One centroid update step given (or computing) the sample→centroid
    assignment — the pylibraft ``cluster.kmeans.compute_new_centroids``
    entry (SURVEY §2.7; cluster/kmeans.pyx). Empty clusters keep their
    previous center."""
    from ..utils import in_jax_trace

    x = jnp.asarray(x, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    user_labels = labels is not None
    if not user_labels:
        labels, _ = predict(x, centroids)
    labels = jnp.asarray(labels, jnp.int32)
    if user_labels and not in_jax_trace() and labels.size:
        # segment_sum drops out-of-range indices silently; fail loudly on
        # untrusted input (predict-computed labels are in range by
        # construction). One fused fetch: a single device->host sync.
        lo, hi = np.asarray(jnp.stack([labels.min(), labels.max()]))
        expects(lo >= 0 and hi < centroids.shape[0],
                "labels out of range [0, %d): saw [%d, %d]",
                centroids.shape[0], lo, hi)
    centers, _ = _update_centers(x, labels, centroids.shape[0], centroids)
    return centers


@partial(jax.jit, static_argnums=(2, 3))
def _lloyd(x, centers0, max_iter, tol):
    k = centers0.shape[0]

    def cond(state):
        _, shift, it = state
        return (shift > tol) & (it < max_iter)

    def body(state):
        centers, _, it = state
        labels, _ = fused_l2_nn_argmin(x, centers)
        new_centers, _ = _update_centers(x, labels, k, centers)
        shift = jnp.sum((new_centers - centers) ** 2)
        return new_centers, shift, it + 1

    centers, _, n_iter = jax.lax.while_loop(
        cond, body, (centers0, jnp.float32(jnp.inf), 0))
    labels, d2 = fused_l2_nn_argmin(x, centers)
    return centers, labels, jnp.sum(d2), n_iter


@interop.auto_convert_output
@tracing.annotate("raft_tpu::cluster::kmeans::fit")
def fit(x, params: KMeansParams, centroids: Optional[jax.Array] = None):
    """Fit k-means → (centroids (k, d), inertia, n_iter).

    ``centroids`` seeds the fit when params.init == Array
    (kmeans.cuh:88 takes the same optional seed matrix).
    """
    x = jnp.asarray(x, jnp.float32)
    k = params.n_clusters
    expects(k > 0 and k <= x.shape[0], "bad n_clusters %d for n=%d", k, x.shape[0])

    best = None
    for trial in range(max(1, params.n_init)):
        seed = params.seed + trial
        if params.init is InitMethod.Array:
            expects(centroids is not None, "init=Array requires centroids")
            c0 = jnp.asarray(centroids, jnp.float32)
        elif params.init is InitMethod.Random:
            idx = jax.random.choice(jax.random.key(seed), x.shape[0], (k,),
                                    replace=False)
            c0 = x[idx]
        else:
            c0 = _plus_plus(jax.random.key(seed), x, k)
        centers, labels, inertia, n_iter = _lloyd(x, c0, params.max_iter,
                                                  params.tol)
        if best is None or float(inertia) < float(best[1]):
            best = (centers, inertia, n_iter)
    return best


@interop.auto_convert_output
def predict(x, centroids) -> Tuple[jax.Array, jax.Array]:
    """Labels + per-sample squared distance (kmeans::predict)."""
    return fused_l2_nn_argmin(jnp.asarray(x, jnp.float32),
                              jnp.asarray(centroids, jnp.float32))


@interop.auto_convert_output
def fit_predict(x, params: KMeansParams):
    centers, inertia, n_iter = fit(x, params)
    labels, _ = predict(x, centers)
    return labels, centers, inertia


@interop.auto_convert_output
def transform(x, centroids) -> jax.Array:
    """Distance of each sample to every centroid (kmeans::transform)."""
    return pairwise_distance(x, centroids, "sqeuclidean")


@interop.auto_convert_output
def cluster_cost(x, centroids) -> jax.Array:
    """Total squared distance to nearest centroid (kmeans::cluster_cost)."""
    _, d2 = predict(x, centroids)
    return jnp.sum(d2)


@interop.auto_convert_output
@tracing.annotate("raft_tpu::cluster::kmeans::fit_mini_batch")
def fit_mini_batch(x, params: KMeansParams):
    """Mini-batch k-means (detail/kmeans.cuh fit_main mini-batch path):
    per-batch assignment + running per-center counts with incremental
    center updates."""
    x = jnp.asarray(x, jnp.float32)
    k = params.n_clusters
    n = x.shape[0]
    b = min(params.batch_samples, n)
    c0 = _plus_plus(jax.random.key(params.seed), x, k)

    def step(carry, key):
        centers, counts = carry
        idx = jax.random.randint(key, (b,), 0, n)
        xb = x[idx]
        labels, _ = fused_l2_nn_argmin(xb, centers)
        bsum = jax.ops.segment_sum(xb, labels, num_segments=k)
        bcnt = jax.ops.segment_sum(jnp.ones((b,), x.dtype), labels,
                                   num_segments=k)
        new_counts = counts + bcnt
        lr = jnp.where(new_counts > 0, bcnt / jnp.maximum(new_counts, 1.0), 0.0)
        target = bsum / jnp.maximum(bcnt, 1.0)[:, None]
        centers = jnp.where(
            (bcnt > 0)[:, None],
            centers + lr[:, None] * (target - centers),
            centers,
        )
        return (centers, new_counts), None

    steps = max(1, params.max_iter)
    keys = jax.random.split(jax.random.key(params.seed + 1), steps)
    (centers, _), _ = jax.lax.scan(step, (c0, jnp.zeros((k,), jnp.float32)), keys)
    labels, d2 = fused_l2_nn_argmin(x, centers)
    return centers, jnp.sum(d2), steps


def auto_find_k(x, k_min: int = 2, k_max: int = 20, tol: float = 0.1,
                params: "KMeansParams | None" = None):
    """Pick the cluster count automatically → (best_k, centroids, labels).

    Analog of cluster/detail/kmeans_auto_find_k.cuh: sweep candidate k and
    stop at the inertia elbow — the smallest k whose next increment stops
    paying (relative inertia improvement < ``tol``). A spherical-gaussian
    BIC over-rewards extra clusters on well-separated data, so the elbow
    is the decision rule; the sweep keeps each k's fit so the winner's
    centroids come for free.
    """
    x = jnp.asarray(x, jnp.float32)
    n, d = x.shape
    expects(2 <= k_min <= k_max < n, "bad k range [%d, %d] for n=%d",
            k_min, k_max, n)
    base = params or KMeansParams(n_clusters=k_min)

    prev = None                       # (k, centers, inertia)
    best_k, centers = k_max, None
    for k in range(k_min, k_max + 1):
        p = dataclasses.replace(base, n_clusters=k)
        c, inertia, _ = fit(x, p)
        inertia = max(float(inertia), 1e-30)
        if prev is not None and (prev[2] - inertia) / prev[2] < tol:
            best_k, centers = prev[0], prev[1]
            break
        prev = (k, c, inertia)
        centers = c
    labels, _ = predict(x, centers)
    return best_k, centers, labels
