"""Hierarchical balanced k-means: analog of ``raft::cluster::kmeans_balanced``.

Reference: raft/cluster/detail/kmeans_balanced.cuh:956 (`build_hierarchical`):
train mesoclusters on the full set, then fine clusters per mesocluster, then
rebalance with `adjust_centers` (:258) — undersized clusters are re-seeded
near points of oversized clusters — interleaved with Lloyd steps. This is
the IVF coarse quantizer trainer (ivf_pq_build.cuh:1825).

TPU design: assignments ride the fused L2+argmin scan; per-mesocluster fine
training batches all mesoclusters' Lloyd updates into ONE segment-sum over a
combined label space (meso-id × fine-id), so the hierarchy adds no serial
kernel launches; adjust_centers is a vectorized re-seed driven by cluster
size ranks.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import tracing
from ..core.errors import expects
from ..distance.fused_l2_nn import fused_l2_nn_argmin
from .kmeans import _lloyd, _plus_plus, _update_centers

__all__ = ["BalancedKMeansParams", "fit", "predict", "fit_predict", "adjust_centers"]


@dataclasses.dataclass
class BalancedKMeansParams:
    """Mirror of kmeans_balanced_params (kmeans_balanced.cuh)."""

    n_iters: int = 20              # per-level Lloyd iterations
    metric: str = "sqeuclidean"
    seed: int = 0
    # adjust_centers threshold: clusters smaller than avg/ratio are re-seeded
    balancing_pessimism: float = 2.5
    balancing_rounds: int = 4
    max_train_points: int = 1 << 20  # subsample bound for meso training


def adjust_centers(centers, counts, x, labels, threshold_ratio: float, key):
    """Re-seed undersized clusters near members of oversized ones.

    Vectorized analog of kmeans_balanced.cuh:258 (adjust_centers): any
    cluster with count < avg/ratio takes a new center drawn from the points
    of large clusters (sampling weight = size of the point's cluster),
    nudged toward the global spread to avoid duplicate seeds.
    """
    k = centers.shape[0]
    avg = x.shape[0] / k
    small = counts < (avg / threshold_ratio)
    # weight each point by its cluster's size → points in big clusters win
    w = counts[labels]
    probs = w / jnp.maximum(jnp.sum(w), 1e-30)
    picks = jax.random.categorical(
        key, jnp.log(jnp.maximum(probs, 1e-30)), shape=(k,))
    # offset each re-seed slightly toward its pick's neighborhood mean to
    # decorrelate multiple re-seeds landing on the same donor cluster
    donors = x[picks]
    jitter = 1e-3 * (donors - centers)
    new_centers = donors + jitter
    return jnp.where(small[:, None], new_centers, centers), small.sum()


@partial(jax.jit, static_argnums=(2, 3, 4))
def _balanced_lloyd(x, centers0, n_iters, rounds, pessimism, key):
    """Lloyd iterations with periodic adjust_centers re-balancing."""
    k = centers0.shape[0]

    def one_round(carry, key_r):
        centers = carry
        def lloyd_step(c, _):
            labels, _ = fused_l2_nn_argmin(x, c)
            c2, _ = _update_centers(x, labels, k, c)
            return c2, None
        centers, _ = jax.lax.scan(lloyd_step, centers, None, length=n_iters)
        labels, _ = fused_l2_nn_argmin(x, centers)
        counts = jax.ops.segment_sum(jnp.ones((x.shape[0],), jnp.float32),
                                     labels, num_segments=k)
        centers, _ = adjust_centers(centers, counts, x, labels, pessimism, key_r)
        return centers, None

    keys = jax.random.split(key, rounds)
    centers, _ = jax.lax.scan(one_round, centers0, keys)
    # final polish without a trailing re-seed
    def lloyd_step(c, _):
        labels, _ = fused_l2_nn_argmin(x, c)
        c2, _ = _update_centers(x, labels, k, c)
        return c2, None
    centers, _ = jax.lax.scan(lloyd_step, centers, None, length=n_iters // 2 + 1)
    return centers


@tracing.annotate("raft_tpu::cluster::kmeans_balanced::fit")
def fit(x, n_clusters: int, params: BalancedKMeansParams | None = None) -> jax.Array:
    """Train ``n_clusters`` balanced centroids → (n_clusters, d).

    Hierarchy as in build_hierarchical: n_meso ≈ sqrt(n_clusters)
    mesoclusters trained first; each mesocluster trains a proportional share
    of fine centers on its own points; all fine centers are then polished
    jointly with balancing rounds.
    """
    p = params or BalancedKMeansParams()
    x = jnp.asarray(x, jnp.float32)
    n, d = x.shape
    expects(0 < n_clusters <= n, "bad n_clusters %d for n=%d", n_clusters, n)
    key = jax.random.key(p.seed)

    if n > p.max_train_points:
        stride = n // p.max_train_points
        x = x[::stride][: p.max_train_points]
        n = x.shape[0]

    if n_clusters <= 4:
        c0 = _plus_plus(key, x, n_clusters)
        centers, *_ = _lloyd(x, c0, p.n_iters, 1e-6)
        return centers

    n_meso = max(2, int(math.sqrt(n_clusters)))
    k_meso, k_fine_key = jax.random.split(key)

    # level 1: mesoclusters
    c0 = _plus_plus(k_meso, x, n_meso)
    meso_centers, *_ = _lloyd(x, c0, p.n_iters, 1e-6)
    meso_labels, _ = fused_l2_nn_argmin(x, meso_centers)

    # proportional fine-cluster allocation (host-side, sizes are tiny)
    counts = np.asarray(jax.ops.segment_sum(
        jnp.ones((n,), jnp.float32), meso_labels, num_segments=n_meso))
    alloc = np.maximum(1, np.floor(counts / counts.sum() * n_clusters)).astype(int)
    while alloc.sum() < n_clusters:
        alloc[np.argmax(counts / alloc)] += 1
    while alloc.sum() > n_clusters:
        i = np.argmax(alloc)
        if alloc[i] <= 1:
            break
        alloc[i] -= 1

    # level 2: seed fine centers per mesocluster from a random sample of its
    # own points. Only O(n_meso) counts ever reach the host: the dataset and
    # its meso labels stay on device (a meso-sorted row *order* plus one
    # n_clusters-row gather replaces the old per-meso host loop, whose
    # np.asarray(x) was a full-dataset device→host transfer). A jitted
    # per-meso kmeans++ would recompile per (|meso|, alloc) shape; the joint
    # _balanced_lloyd polish below does the quality work, as in
    # build_hierarchical.
    order = jnp.argsort(meso_labels)                  # meso-sorted row ids
    starts = np.concatenate([[0], np.cumsum(counts.astype(np.int64))[:-1]])
    seed_rng = np.random.default_rng(p.seed ^ 0x9E3779B9)
    pos = np.zeros(n_clusters, np.int64)              # slot → sorted row
    slot_meso = np.repeat(np.arange(n_meso), alloc)
    valid = np.zeros(n_clusters, bool)
    s = 0
    for m in range(n_meso):
        km, cm = int(alloc[m]), int(counts[m])
        if cm > 0:
            if cm > km:
                local = seed_rng.choice(cm, km, replace=False)
            else:
                local = np.arange(km) % cm            # cycle the members
            pos[s : s + km] = starts[m] + local
            valid[s : s + km] = True
        s += km
    picks = jnp.take(order, jnp.asarray(pos))         # device gather
    centers0 = jnp.where(jnp.asarray(valid)[:, None], x[picks],
                         meso_centers[jnp.asarray(slot_meso)])

    key_bal = jax.random.key(p.seed + 17)
    return _balanced_lloyd(x, centers0, p.n_iters, p.balancing_rounds,
                           p.balancing_pessimism, key_bal)


def predict(x, centroids) -> Tuple[jax.Array, jax.Array]:
    """Batch label assignment via fused L2+argmin (kmeans_balanced::predict)."""
    return fused_l2_nn_argmin(jnp.asarray(x, jnp.float32),
                              jnp.asarray(centroids, jnp.float32))


def fit_predict(x, n_clusters: int, params: BalancedKMeansParams | None = None):
    centers = fit(x, n_clusters, params)
    labels, _ = predict(x, centers)
    return centers, labels
