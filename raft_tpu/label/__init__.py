"""Label utilities: analog of ``raft/label/``.

Reference: label/classlabels.cuh (getUniquelabels, make_monotonic) and
label/merge_labels.cuh (union-find-flavored label merging over an
adjacency).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["get_unique_labels", "make_monotonic", "merge_labels"]


def get_unique_labels(labels) -> jax.Array:
    """Sorted unique labels (classlabels.cuh getUniquelabels)."""
    return jnp.unique(jnp.asarray(labels))


def make_monotonic(labels, ignore: int | None = None) -> Tuple[jax.Array, int]:
    """Remap labels to 0..n_unique-1 preserving order
    (classlabels.cuh make_monotonic). ``ignore``: label left untouched
    (the reference's MLCommon convention uses -1 noise labels).
    Host-side: unique count is data-dependent."""
    l = np.asarray(labels)
    mask = np.ones_like(l, bool) if ignore is None else (l != ignore)
    uniq = np.unique(l[mask])
    lut = {v: i for i, v in enumerate(uniq.tolist())}
    out = np.array([lut[v] if m else v
                    for v, m in zip(l.tolist(), mask.tolist())])
    return jnp.asarray(out), len(uniq)


def merge_labels(labels_a, labels_b, mask=None) -> jax.Array:
    """Merge two labelings: rows where ``mask`` is set act as merge points —
    every label connected through a shared row collapses to the smallest
    member label (merge_labels.cuh, the label-equivalence propagation).

    Implemented as host union-find (the reference's iterative min-
    propagation kernel has data-dependent trip count)."""
    a = np.asarray(labels_a).copy()
    b = np.asarray(labels_b)
    m = np.ones_like(a, bool) if mask is None else np.asarray(mask, bool)

    parent: dict[int, int] = {}

    def find(x: int) -> int:
        while parent.setdefault(x, x) != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(x: int, y: int) -> None:
        rx, ry = find(x), find(y)
        if rx != ry:
            parent[max(rx, ry)] = min(rx, ry)

    for av, bv, mv in zip(a.tolist(), b.tolist(), m.tolist()):
        if mv:
            union(av, bv)
    out = np.array([find(v) for v in a.tolist()])
    return jnp.asarray(out)
