"""Dense linear algebra façade: analog of ``raft/linalg/*.cuh``.

The reference's layer-3 linalg surface is cuBLAS/cuSOLVER wrappers plus
element-wise/reduction kernel templates (SURVEY.md §2.6). On TPU nearly
all of it is XLA built-ins, so this module is deliberately thin: it
collects the reference's API surface in one place (gemm/gemv/axpy/dot,
eig/eigh/qr/svd/lstsq, norms, reductions, transpose) and implements the
few pieces XLA does not ship — randomized SVD (``rsvd``, raft/linalg/
rsvd.cuh) and the rank-1 Cholesky update (``cholesky_rank_one_update``,
raft/linalg/cholesky_r1_update.cuh).

All matmuls default to f32-accurate MXU precision (utils.hdot rationale).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..utils import hdot

__all__ = [
    "gemm", "gemv", "dot", "axpy", "add", "subtract", "multiply", "divide",
    "power", "sqrt", "map_reduce", "matrix_vector_op", "norm", "normalize",
    "reduce_rows", "reduce_cols", "reduce_rows_by_key", "transpose",
    "eig", "eigh", "qr", "svd", "rsvd", "lstsq",
    "cholesky", "cholesky_rank_one_update",
]

# ---- BLAS-like (raft/linalg/gemm.cuh, gemv.cuh, axpy.cuh, dot.cuh) ------

def gemm(a, b, alpha: float = 1.0, beta: float = 0.0, c=None) -> jax.Array:
    """alpha·a@b (+ beta·c) — cublasLt gemm's role, on the MXU."""
    out = alpha * hdot(a, b)
    return out if c is None or beta == 0.0 else out + beta * c


def gemv(a, x, alpha: float = 1.0, beta: float = 0.0, y=None) -> jax.Array:
    out = alpha * hdot(a, x[:, None])[:, 0]
    return out if y is None or beta == 0.0 else out + beta * y


def dot(x, y) -> jax.Array:
    return jnp.vdot(x, y)


def axpy(alpha: float, x, y) -> jax.Array:
    return alpha * x + y


# ---- element-wise (raft/linalg/add.cuh … sqrt.cuh) ----------------------

add = jnp.add
subtract = jnp.subtract
multiply = jnp.multiply
divide = jnp.divide
power = jnp.power
sqrt = jnp.sqrt


def map_reduce(x, map_op, reduce_op=jnp.add, axis=None, init=0.0):
    """map then tree-reduce (raft/linalg/map_then_reduce.cuh)."""
    mapped = map_op(x)
    return jax.lax.reduce(mapped, jnp.asarray(init, mapped.dtype),
                          reduce_op,
                          tuple(range(mapped.ndim)) if axis is None
                          else (axis,))


def matrix_vector_op(m, v, op=jnp.add, along_rows: bool = True) -> jax.Array:
    """Broadcast a vector op over rows/cols (raft/linalg/matrix_vector_op.cuh)."""
    return op(m, v[None, :] if along_rows else v[:, None])


# ---- reductions / norms (raft/linalg/norm.cuh, reduce.cuh) --------------

def norm(x, ord: int = 2, axis: Optional[int] = None) -> jax.Array:
    """Row/col/global L1/L2 norms (raft/linalg/norm.cuh L1Norm/L2Norm —
    note the reference's L2Norm is the *squared* sum; use ord=2 for the
    true norm, ord=-2 for the reference's squared convention)."""
    if ord == -2:
        return jnp.sum(x * x, axis=axis)
    return jnp.linalg.norm(x, ord=ord, axis=axis)


def normalize(x, axis: int = 1, eps: float = 1e-30) -> jax.Array:
    """Row-normalize (raft/linalg/normalize.cuh)."""
    n = jnp.linalg.norm(x, axis=axis, keepdims=True)
    return x / jnp.maximum(n, eps)


def reduce_rows(x, op=jnp.sum) -> jax.Array:
    return op(x, axis=0)


def reduce_cols(x, op=jnp.sum) -> jax.Array:
    return op(x, axis=1)


def reduce_rows_by_key(x, keys, n_keys: int) -> jax.Array:
    """Segment-sum rows by key (raft/linalg/reduce_rows_by_key.cuh)."""
    return jax.ops.segment_sum(x, keys, num_segments=n_keys)


def transpose(x) -> jax.Array:
    return jnp.swapaxes(x, -1, -2)


# ---- factorizations (raft/linalg/eig.cuh, qr.cuh, svd.cuh, lstsq.cuh) ---

def eig(a) -> Tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition (eig.cuh eigDC) → (vals, vecs)."""
    return jnp.linalg.eigh(a)


eigh = eig


def qr(a) -> Tuple[jax.Array, jax.Array]:
    return jnp.linalg.qr(a)


def svd(a, full_matrices: bool = False):
    return jnp.linalg.svd(a, full_matrices=full_matrices)


def rsvd(key, a, k: int, p: int = 10, n_iter: int = 2):
    """Randomized SVD (raft/linalg/rsvd.cuh): range-finder with ``p``
    oversampling columns and ``n_iter`` power iterations, then exact SVD
    of the small projection. Returns (u (m, k), s (k,), vT (k, n))."""
    m, n = a.shape
    expects(0 < k <= min(m, n), "bad rsvd rank %d for %s", k, a.shape)
    l = min(k + p, n)
    omega = jax.random.normal(key, (n, l), a.dtype)
    y = hdot(a, omega)                       # (m, l)
    q, _ = jnp.linalg.qr(y)
    for _ in range(n_iter):                  # power iterations sharpen the
        q, _ = jnp.linalg.qr(hdot(a.T, q))   # spectrum separation
        q, _ = jnp.linalg.qr(hdot(a, q))
    b = hdot(q.T, a)                         # (l, n)
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    return hdot(q, ub)[:, :k], s[:k], vt[:k]


def lstsq(a, b):
    """Least squares via economy QR (raft/linalg/lstsq.cuh lstsqQR)."""
    q, r = jnp.linalg.qr(a)
    return jax.scipy.linalg.solve_triangular(r, hdot(q.T, b), lower=False)


def cholesky(a, lower: bool = True) -> jax.Array:
    return jax.scipy.linalg.cholesky(a, lower=lower)


def cholesky_rank_one_update(l, x, alpha: float = 1.0) -> jax.Array:
    """L' with L'L'ᵀ = LLᵀ + alpha·xxᵀ (raft/linalg/cholesky_r1_update.cuh).

    Classic hyperbolic-rotation update, expressed as a lax.scan over
    columns (sequential by nature; n is small in every reference use —
    incremental kernel matrices)."""
    n = l.shape[0]
    x = jnp.sqrt(jnp.asarray(alpha, l.dtype)) * x

    def col(carry, j):
        l, x = carry
        ljj = l[j, j]
        r = jnp.sqrt(ljj * ljj + x[j] * x[j])
        c, s = r / ljj, x[j] / ljj
        colj = l[:, j]
        mask = jnp.arange(n) > j
        new_col = jnp.where(mask, (colj + s * x) / c, colj)
        new_col = new_col.at[j].set(r)
        x = jnp.where(mask, c * x - s * new_col, x)
        return (l.at[:, j].set(new_col), x), None

    (l, _), _ = jax.lax.scan(col, (l, x), jnp.arange(n))
    return l
