"""NN-descent kNN-graph construction: analog of ``raft::neighbors::nn_descent``.

Reference: raft/neighbors/detail/nn_descent.cuh:342 (class GNND: iterative
local join over sampled new/old neighbors + reverse neighbors, bloom-filter
dedup, termination threshold), build at :1371, params nn_descent_types.hpp:49.

TPU design: the per-node hash/bloom bookkeeping is replaced by fixed-shape
batched tensor ops — each round proposes candidates from (a) the current
neighbor lists, (b) a random sample of neighbors-of-neighbors (the local
join), and (c) a reverse-edge sample (computed host-side between rounds;
the graph is host data between rounds anyway). Candidates are scored with
one gather+einsum and merged into the (n, k) lists by ``select_k``;
convergence = fraction of list entries that changed in a round
(termination_threshold, nn_descent_types.hpp:53).

NOTE: this is the original reference-shaped port, kept for its direct
API and parity tests. ``cagra.build`` (``BuildAlgo.NN_DESCENT``) and
``cagra.build_knn_graph(algo="nn_descent")`` route through the
device-resident batched rewrite in ``raft_tpu/ops/nn_descent.py``
instead — same algorithm family, but state never round-trips the host
between rounds and every round shape is a cached executable (see
docs/perf.md "Index build").
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core import tracing
from ..core.errors import expects
from ..distance.distance_types import DistanceType, canonical_metric
from ..matrix.select_k import select_k

__all__ = ["build"]


def _dedup_rows(cand: np.ndarray) -> np.ndarray:
    """Per-row candidate dedup (the _round_batch precondition): sort desc,
    mask adjacent repeats with -1; padding collects at the end."""
    cand = -np.sort(-cand, axis=1)
    cand[:, 1:][cand[:, 1:] == cand[:, :-1]] = -1
    return cand


def _pair_dists(x_rows, vecs, mt):
    ip = jnp.einsum("bcd,bd->bc", vecs, x_rows, precision="highest")
    if mt is DistanceType.InnerProduct:
        return -ip
    q2 = jnp.sum(x_rows * x_rows, axis=1, keepdims=True)
    v2 = jnp.sum(vecs * vecs, axis=2)
    return jnp.maximum(q2 + v2 - 2.0 * ip, 0.0)


@partial(jax.jit, static_argnames=("k", "mt_val"))
def _round_batch(dataset, rows, g_ids, g_dist, g_new, cand, k, mt_val):
    """One NN-descent merge for a node batch.

    rows: (b,) node ids; g_ids/g_dist/g_new: (b, k) lists + new-flags;
    cand: (b, C) proposals.
    """
    mt = DistanceType(mt_val)
    x_rows = dataset[rows]
    # invalidate self and duplicate proposals (mark later occurrences, and
    # anything already present in the current list)
    self_hit = cand == rows[:, None]
    in_list = jnp.any(cand[:, :, None] == g_ids[:, None, :], axis=2)
    # intra-candidate duplicates are removed host-side (sorted dedup) before
    # the call — no O(C²) mask here
    ok = ~(self_hit | in_list) & (cand >= 0)
    cd = _pair_dists(x_rows, dataset[jnp.maximum(cand, 0)], mt)
    cd = jnp.where(ok, cd, jnp.inf)

    all_d = jnp.concatenate([g_dist, cd], axis=1)
    all_i = jnp.concatenate([g_ids, cand], axis=1)
    all_n = jnp.concatenate([g_new, jnp.ones_like(cand, bool)], axis=1)
    new_d, sel = select_k(all_d, k, select_min=True)
    new_i = jnp.take_along_axis(all_i, sel, axis=1)
    new_n = jnp.take_along_axis(all_n, sel, axis=1) & jnp.isfinite(new_d)
    changed = jnp.sum(sel >= k)                           # entries from cand
    return new_i, new_d, new_n, changed


def _group_by_target(targets: np.ndarray, cands: np.ndarray, n: int,
                     cap: int, rng=None) -> np.ndarray:
    """Proposal edge list → (n, cap) per-target candidate table (-1 pad).

    Vectorized: shuffle edges (arrival order when ``rng`` is None),
    stable-sort by target, keep the first ``cap`` arrivals per target.
    """
    live = (targets >= 0) & (cands >= 0)
    targets, cands = targets[live], cands[live]
    if rng is not None:
        perm = rng.permutation(len(targets))
        tp, cp = targets[perm], cands[perm]
    else:
        tp, cp = targets, cands
    order = np.argsort(tp, kind="stable")
    ts, cs = tp[order], cp[order]
    counts = np.bincount(ts, minlength=n)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(ts)) - starts[ts]
    keep = pos < cap
    out = np.full((n, cap), -1, np.int32)
    out[ts[keep], pos[keep]] = cs[keep]
    return out


def _sample_cols(flags_match: np.ndarray, s: int, rng) -> np.ndarray:
    """Per row, sample up to ``s`` column indices where flags_match is True
    (uniformly, without replacement); -1 where unavailable."""
    n, k = flags_match.shape
    score = rng.random((n, k)) + (~flags_match) * 10.0
    cols = np.argsort(score, axis=1)[:, :s]
    ok = np.take_along_axis(flags_match, cols, axis=1)
    return np.where(ok, cols, -1)


def _local_join_proposals(graph: np.ndarray, is_new: np.ndarray, s: int,
                          cap: int, rng):
    """The NN-descent local join (GNND local_join, nn_descent.cuh):

    each node gathers a joint set of sampled *new* neighbors (forward +
    reverse) and sampled *old* neighbors; every ordered pair with at least
    one new member proposes its members to each other. Proposals are
    regrouped per target node, capped at ``cap``. Sampled-new entries are
    demoted to old in-place (the GNND flag update).
    """
    n, k = graph.shape
    idx = np.arange(n, dtype=np.int32)

    new_cols = _sample_cols(is_new, s, rng)
    old_cols = _sample_cols(~is_new & (graph >= 0), s, rng)
    take = lambda cols: np.where(
        cols >= 0, np.take_along_axis(graph, np.maximum(cols, 0), axis=1), -1)
    fwd_new, fwd_old = take(new_cols), take(old_cols)

    # demote the sampled new entries (they are being joined this round)
    rows = np.repeat(idx, s)
    csel = new_cols.reshape(-1)
    ok = csel >= 0
    is_new[rows[ok], csel[ok]] = False

    # reverse samples, split by flag: for a new edge (i→j), i joins j's set
    src = np.repeat(idx, k)
    dst = graph.reshape(-1)
    nf = is_new.reshape(-1) | False
    # note: use pre-demotion flags for reverse too — close enough and cheap
    rev_new = _group_by_target(dst[nf], src[nf], n, s, rng)
    rev_old = _group_by_target(dst[~nf], src[~nf], n, s, rng)

    jn = np.concatenate([fwd_new, rev_new], axis=1)           # (n, 2s) new
    jo = np.concatenate([fwd_old, rev_old], axis=1)           # (n, 2s) old
    m = jn.shape[1]

    # pairs: new×new (both directions implicit by symmetry of the loop) and
    # new×old / old×new
    a_nn = np.broadcast_to(jn[:, :, None], (n, m, m)).reshape(-1)
    b_nn = np.broadcast_to(jn[:, None, :], (n, m, m)).reshape(-1)
    a_no = np.broadcast_to(jn[:, :, None], (n, m, m)).reshape(-1)
    b_no = np.broadcast_to(jo[:, None, :], (n, m, m)).reshape(-1)
    a = np.concatenate([a_nn, a_no, b_no])
    b = np.concatenate([b_nn, b_no, a_no])
    neq = a != b
    return _group_by_target(a[neq], b[neq], n, cap, rng)


@tracing.annotate("raft_tpu::nn_descent::build")
def build(dataset, k: int, metric=DistanceType.L2Expanded, n_iters: int = 20,
          termination_threshold: float = 0.0001, seed: int = 0,
          sample: int = 0, batch: int = 4096) -> np.ndarray:
    """Build an (n, k) kNN graph by NN-descent; returns int32 neighbor ids.

    ``sample``: neighbors sampled per node for the local join (0 → k//2,
    GNND's default samples=32 ballpark).
    """
    dataset = np.asarray(dataset, np.float32)
    n, d = dataset.shape
    mt = canonical_metric(metric)
    expects(mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                   DistanceType.InnerProduct),
            "nn_descent supports L2/IP metrics, got %s", mt.name)
    expects(k < n, "k %d >= n %d", k, n)
    s = sample or max(4, k // 2)
    rng = np.random.default_rng(seed)
    data_j = jnp.asarray(dataset)

    # random init (distinct-ish): k draws per node, self fixed in round 0
    graph = rng.integers(0, n, (n, k)).astype(np.int32)
    dist = np.full((n, k), np.inf, np.float32)
    is_new = np.zeros((n, k), bool)
    rows_all = np.arange(n, dtype=np.int32)

    # score the random init (everything that survives is a new entry);
    # _round_batch's precondition: intra-candidate duplicates removed
    # host-side (sort desc, mask adjacent repeats)
    init_cand = _dedup_rows(graph.copy())
    for b0 in range(0, n, batch):
        rows = rows_all[b0 : b0 + batch]
        g_i, g_d, g_n, _ = _round_batch(
            data_j, jnp.asarray(rows),
            jnp.full((len(rows), k), -1, jnp.int32),
            jnp.full((len(rows), k), jnp.inf, jnp.float32),
            jnp.zeros((len(rows), k), bool),
            jnp.asarray(init_cand[b0 : b0 + batch]), k, mt.value)
        graph[b0 : b0 + batch] = np.asarray(g_i)
        dist[b0 : b0 + batch] = np.asarray(g_d)
        is_new[b0 : b0 + batch] = np.asarray(g_n)

    # each node generates ~2s×4s join proposals; keep enough of what lands
    # on it that the round's information isn't thrown away, but bound the
    # (n, cap) int32 table to ~512 MB host RAM — an uncapped 4s² is
    # gigabytes at n=1M. Dropped proposals are a uniform random subset
    # (_group_by_target shuffles), so extra rounds recover the recall the
    # way GNND's capped internal lists do.
    cap = min(4 * s * s, max(4 * k, (512 << 20) // (4 * n)))
    for _ in range(n_iters):
        cand = _dedup_rows(_local_join_proposals(graph, is_new, s, cap, rng))

        changed = 0
        for b0 in range(0, n, batch):
            rows = rows_all[b0 : b0 + batch]
            g_i, g_d, g_n, ch = _round_batch(
                data_j, jnp.asarray(rows),
                jnp.asarray(graph[b0 : b0 + batch]),
                jnp.asarray(dist[b0 : b0 + batch]),
                jnp.asarray(is_new[b0 : b0 + batch]),
                jnp.asarray(cand[b0 : b0 + batch]), k, mt.value)
            graph[b0 : b0 + batch] = np.asarray(g_i)
            dist[b0 : b0 + batch] = np.asarray(g_d)
            is_new[b0 : b0 + batch] = np.asarray(g_n)
            changed += int(ch)
        if changed < termination_threshold * n * k:
            break
    return graph
