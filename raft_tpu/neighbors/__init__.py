"""ANN vector search: the crown jewels (SURVEY.md §2.3).

Families mirror the reference: ``brute_force`` (exact), ``ivf_flat``,
``ivf_pq``, ``cagra`` (+ ``nn_descent`` builder), ``refine``, ``hnsw``
(CPU interop), ``ball_cover``, ``epsilon_neighborhood``; sample filters in
``filters``. ``mutable`` wraps any family in the crash-safe
upsert/delete tier (WAL'd delta segment + tombstones + background
merge; docs/mutation.md).
"""
from . import (ann_types, brute_force, cagra, ivf_flat, ivf_pq, mutable,
               nn_descent, refine)

__all__ = ["ann_types", "brute_force", "cagra", "ivf_flat", "ivf_pq",
           "mutable", "nn_descent", "refine"]
