"""CAGRA graph-based ANN: analog of ``raft::neighbors::cagra``.

Reference: raft/neighbors/cagra_types.hpp:66-113,134 (params: intermediate/
graph degree, build_algo IVF_PQ|NN_DESCENT; index = dataset + fixed-degree
graph), detail/cagra/cagra_build.cuh:43-343 (build_knn_graph via ivf_pq
search + refine, then optimize), detail/cagra/graph_core.cuh:128-191
(kern_prune detour counting + reverse-edge merge) and
detail/cagra/search_single_cta_kernel-inl.cuh:51-200 (persistent per-query
loop: pickup parents → fetch neighbors → hashmap dedup → distances →
bitonic merge into itopk).

TPU design differences:

* **Search is one jitted ``lax.while_loop`` over a batched frontier**: all
  queries advance in lockstep; per iteration the top ``search_width``
  unexplored itopk entries are expanded, their graph neighbors deduped
  *against the itopk buffer itself* (a (cand × itopk) equality mask — the
  vectorizable stand-in for the reference's per-CTA visited hashmap),
  scored with one gather+einsum, and bitonic-merged by a single
  ``select_k`` over the concatenated buffer. The three CUDA strategies
  (SINGLE_CTA/MULTI_CTA/MULTI_KERNEL, factory.cuh:31-91) collapse into
  this one program — XLA handles the batch/occupancy tradeoffs.
* **Graph optimize** keeps the reference's detour-count rule but computes
  all nodes' neighbor-pair adjacency in batched searchsorted membership
  probes instead of a per-edge kernel; the reverse-edge grouping runs on
  device too (stable sort by target + segment positions — see
  ``_rev_group_jit``).
* Graph build has two TPU-native fast paths (see ``build_knn_graph``):
  an *exact* all-pairs sweep through the streaming fused
  distance+select kernel (corpus HBM-resident in storage width, no
  per-batch full-width top_k) up to ``RAFT_TPU_CAGRA_BRUTE_N`` rows,
  and batched NN-descent (``ops/nn_descent.py``, O(rounds·n·C·d))
  above it. The reference's IVF-PQ+refine candidate pass remains as
  the structured fallback, and ``IndexParams.build_algo`` NN_DESCENT
  routes through the batched builder.
"""
from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import deadline, interop, tracing
from ..core.bitset import Bitset
from ..core.errors import expects
from ..core.serialize import load_arrays, save_arrays
from ..distance.distance_types import DistanceType, canonical_metric
from ..matrix.select_k import select_k
from ..ops.guarded import guarded_call
from ..utils import round_up_to, run_query_chunks
from . import ivf_pq as ivf_pq_mod
from . import refine as refine_mod

__all__ = ["BuildAlgo", "IndexParams", "SearchParams", "Index", "build",
           "build_knn_graph", "optimize", "search", "save", "load",
           "prepare_search", "prepare_traversal", "tune_search",
           "make_searcher", "health", "ENGINES"]

_SERIAL_VERSION = 2   # v2 adds optional seed_nodes

# the concrete traversal engines (SearchParams.engine / search(engine=)
# besides "auto"). THE registry the engine drift guard reads
# (tests/test_quality.py): every member must appear in the tune_search
# race and be warmable through serve/warmup.py's ladder, so a new
# engine cannot ship without a measured race lane and a pre-compile
# path — a first-request compile stall is exactly the regression the
# serving warmup exists to prevent.
ENGINES = ("gather", "edge", "fused")


class BuildAlgo(enum.Enum):
    """cagra_types.hpp graph_build_algo."""

    IVF_PQ = 0
    NN_DESCENT = 1


@dataclasses.dataclass
class IndexParams:
    """Mirror of cagra::index_params (cagra_types.hpp:66)."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    build_algo: BuildAlgo = BuildAlgo.IVF_PQ
    metric: DistanceType | str = DistanceType.L2Expanded
    nn_descent_niter: int = 20
    seed: int = 0
    # candidate pass for the BuildAlgo.IVF_PQ route: "auto" substitutes
    # the exact fused all-pairs sweep below the brute cutover and
    # batched NN-descent above it (see build_knn_graph);
    # "brute"/"nn_descent"/"ivf_pq" force a specific pass
    knn_graph_algo: str = "auto"
    # shared traversal seed set: nearest dataset rows to this many
    # balanced-kmeans centroids, stored in the index. All queries score
    # the same rows, so seeding is one dense MXU GEMM instead of a
    # per-query random gather — starting the walk near a covering set
    # cuts hops at equal recall (measured at 100k×128: 39.9k QPS @ 0.975
    # in 6 hops vs 31.8k @ 0.948 in 10 hops random-seeded). -1 → auto
    # (max(128, min(2048, n // 64))); 0 disables (reference behavior:
    # random-only seeding, search_plan.cuh rand_xor_mask).
    seed_nodes: int = -1


@dataclasses.dataclass
class SearchParams:
    """Mirror of cagra::search_params (cagra_types.hpp:113).

    ``candidate_dtype``: dtype for candidate scoring during traversal —
    bf16 halves the gather bandwidth of the hot loop, int8 (per-row
    scaled) quarters it (the returned top-k is always re-scored exactly
    in f32); "float32" scores exactly throughout. ``seed``: RNG seed for
    the random seed-node init (rand_xor_mask's role, search_plan.cuh)."""

    itopk_size: int = 64
    search_width: int = 1          # parents expanded per iteration
    max_iterations: int = 0        # 0 → auto
    min_iterations: int = 0        # traverse at least this many hops
    num_random_samplings: int = 1  # random seed nodes multiplier
    candidate_dtype: str = "bfloat16"   # "bfloat16" | "float32" | "int8"
    seed: int = 0x5EED
    # the reference's SINGLE_CTA/MULTI_CTA/MULTI_KERNEL strategies
    # (factory.cuh:31-91) collapse into one batched-frontier program on
    # TPU; "auto"/"single_cta"/"multi_cta"/"multi_kernel" are all accepted
    # and run the same plan (XLA owns the occupancy tradeoffs)
    algo: str = "auto"
    # hop engine: "edge" streams each parent's contiguous neighbor tile
    # from the edge-resident candidate store (prepare_traversal) through
    # the Pallas frontier-expansion kernel; "fused" folds the WHOLE hop
    # loop into one megakernel launch (ops/cagra_fused.py — frontier in
    # VMEM, bit-identical to "edge", kills the per-hop dispatch floor);
    # "gather" is the composed-XLA random-row-gather path; "auto"
    # consults the ops.autotune race cache (tune_search populates it)
    # and otherwise picks "edge" only when a store is already attached
    # on TPU — a read-only query never grows the index's HBM footprint
    # as a side effect, and the megakernel only dispatches off a
    # measured race verdict
    engine: str = "auto"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Dataset + fixed-degree neighbor graph (cagra_types.hpp:134).

    ``seed_nodes``: optional (s,) *sorted unique* row ids of a shared
    covering seed set (see IndexParams.seed_nodes; the search-time
    collision probe relies on sortedness); None → random-only seeding."""

    dataset: jax.Array        # (n, dim) float32
    graph: jax.Array          # (n, degree) int32
    metric: DistanceType
    seed_nodes: Optional[jax.Array] = None   # (s,) int32

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]

    def tree_flatten(self):
        # traversal-dtype caches travel WITH the index so jitted
        # functions can take it as an ARGUMENT (closure-baking the
        # dataset + bf16 copy as HLO constants exceeds remote-compile
        # request limits at memory scale); the edge-resident candidate
        # store (prepare_traversal) rides the same way, its static meta
        # tuple in aux_data so executables re-key on geometry changes
        es = getattr(self, "_edge_store", None)
        cbs = es[4] if es is not None and len(es) > 4 else None
        leaves = (self.dataset, self.graph, self.seed_nodes,
                  getattr(self, "_score_bf16", None),
                  getattr(self, "_score_i8", None),
                  es[1] if es is not None else None,
                  es[2] if es is not None else None,
                  es[3] if es is not None else None,
                  cbs[0] if cbs is not None else None,
                  cbs[1] if cbs is not None else None)
        return leaves, (self.metric, es[0] if es is not None else None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        out = cls(leaves[0], leaves[1], aux[0], leaves[2])
        if leaves[3] is not None:
            out._score_bf16 = leaves[3]
        if leaves[4] is not None:
            out._score_i8 = leaves[4]
        if len(aux) > 1 and aux[1] is not None and leaves[5] is not None:
            cbs = (leaves[8], leaves[9]) if leaves[8] is not None else None
            out._edge_store = (aux[1], leaves[5], leaves[6], leaves[7],
                               cbs)
        return out


def _brute_n_threshold() -> int:
    """The exact-pass crossover row count — ONE reader, because the auto
    resolver and the guarded nn_descent fallback must agree on it."""
    import os

    return int(os.environ.get("RAFT_TPU_CAGRA_BRUTE_N", "200000"))


def _graph_algo_key(n: int, dim: int, k: int, mt) -> str:
    """Autotune bucket for the graph-builder race: the bench graph-build
    lane records the measured winner per shape class and ``algo="auto"``
    consults it before falling back to the cost-model threshold. The
    metric rides as a categorical tag — crossovers are measured per
    distance family, and a verdict raced under L2 must not steer an
    InnerProduct (or descent-incapable) build in the same shape class."""
    from ..ops import autotune

    return autotune.shape_bucket("cagra_knn_graph", m=mt.name, n=n,
                                 d=dim, k=k)


def _resolve_graph_algo(n: int, dim: int, k: int, algo: str, mt) -> str:
    """Concrete builder for ``algo="auto"``: a recorded race verdict for
    this shape bucket wins; otherwise the cost-model threshold.

    Threshold math (re-derive the measured crossover with
    ``scratch/exp_build_crossover.py``; anchors are BENCH_r05's
    roofline): the n²·d GEMM is never the wall — 2n²·d at 500k×128 is
    64 TFLOP ≈ 0.4 s at the measured 154.7 TF/s. The exact pass's real
    cost is **O(n²) corpus re-streaming + select**: every 16k-query
    chunk re-reads the n·d·4-byte corpus, so the HBM floor alone is
    ~0.5 s at 100k, ~12 s at 500k, ~50 s at 1M (639.8 GB/s streamed),
    and the in-kernel select rides on top (the k=96 build shape merges
    more than the k≤10 search shapes PR 3 measured near GEMM rate).
    NN-descent is ~linear: rounds·n·C candidate-row gathers (C ≈ 800 at
    the default knobs — tens of seconds at 500k, early-stop usually
    halves the round budget). The crossover therefore sits in the
    low-hundreds-of-k band; 200k is the conservative default — below it
    the exact graph costs ≤ a few seconds more and is better
    conditioned. (The old 1.2M default compared the exact pass against
    the far slower quarter-corpus IVF-PQ probe sweep that NN-descent
    replaced — that crossover died with the sweep.)"""
    if algo != "auto":
        return algo
    from ..ops import autotune
    from ..ops import nn_descent as nnd

    hit = autotune.lookup(_graph_algo_key(n, dim, k, mt))
    if hit in ("brute", "ivf_pq", "nn_descent") and (
            hit != "nn_descent" or nnd.supports(mt)):
        return hit
    if n <= _brute_n_threshold():
        return "brute"
    # past the exact pass's budget: the batched descent when it can
    # serve the metric, else the reference's ivf_pq candidate pass
    # (auto must never resolve to a builder that would reject the
    # request — that would poison the cagra.nn_descent guard site)
    return "nn_descent" if nnd.supports(mt) else "ivf_pq"


@tracing.annotate("raft_tpu::cagra::build_knn_graph")
def build_knn_graph(dataset, k: int, metric=DistanceType.L2Expanded,
                    seed: int = 0, batch: int = 32768,
                    algo: str = "auto", engine: str = "auto",
                    nnd_rounds: int = 0, init_graph=None,
                    progress=None, info=None) -> np.ndarray:
    """All-points kNN graph (cagra_build.cuh:43 build_knn_graph).

    ``algo``:

    * ``"brute"`` — exact all-pairs kNN, one query batch at a time.
      ``engine="fused"`` streams each batch through the fused
      distance+select kernel (``brute_force.prepare_fused`` + the
      ``pallas`` engine): the corpus stays HBM-resident in storage
      width and the in-kernel two-level select replaces the per-batch
      full-width top_k that dominated the exact build wall (366.8 s at
      500k×128, BENCH_r05). ``engine="matmul"`` is the GEMM + block-min
      top_k reference engine; the two produce BIT-IDENTICAL graphs
      (the fused kernel retires ties in lax.top_k order —
      tests/test_graph_build.py asserts it), so ``"auto"`` freely picks
      fused on TPU for fused-capable metrics and matmul elsewhere.
    * ``"nn_descent"`` — batched neighbor-of-neighbor descent
      (``ops/nn_descent.py``): O(rounds·n·C·d) instead of O(n²·d), the
      builder past the exact pass's budget. Approximate by design —
      graph-edge recall ~0.9+ at the bench operating points, absorbed
      by optimize()'s pruning and the search-time exact re-rank, the
      same tolerance the reference's IVF-PQ candidate pass leans on.
      Guarded: a builder failure falls back to the exact/ivf_pq path
      with the demotion recorded (``cagra.nn_descent`` site).
    * ``"ivf_pq"`` — the reference's own path: IVF-PQ search for 2k
      candidates, exact refine to k (gpu_top_k = k * refine_rate).
      Kept for reference parity and as nn_descent's large-n fallback.
    * ``"auto"`` — a measured race verdict for this shape bucket when
      one is recorded (the bench graph-build lane records them), else
      brute below ``RAFT_TPU_CAGRA_BRUTE_N`` rows (default 200k — see
      :func:`_resolve_graph_algo` for the crossover math), nn_descent
      above.

    ``nnd_rounds``/``init_graph``: NN-descent round cap (0 → knob
    default) and optional (n, k0) warm-start candidate lists (e.g. an
    IVF-PQ candidate pass). ``progress``: optional 3-arg hook — the
    batch loops call ``progress(done_rows, total_rows, elapsed_s)``;
    NN-descent reports rounds in the same shape,
    ``progress(round, rounds, elapsed_s)`` (one hook serves every
    builder, so ``algo="auto"`` and the guarded fallback can hand it to
    whichever path actually runs). ``info``: optional dict the call
    fills with the builder that actually ran (``info["algo"]``, plus
    ``info["engine"]`` on the brute path) — under ``algo="auto"`` or
    the ``cagra.nn_descent`` guard the resolved/demoted choice is
    otherwise invisible to the caller.

    Returns (n, k) int32 neighbor ids (self-edges removed).
    """
    import os

    from . import brute_force as bf_mod

    dataset = np.asarray(dataset, np.float32)
    n, dim = dataset.shape
    mt = canonical_metric(metric)
    expects(algo in ("auto", "brute", "ivf_pq", "nn_descent"),
            "unknown knn_graph algo %r", algo)
    expects(engine in ("auto", "fused", "matmul"),
            "unknown brute graph engine %r", engine)
    algo = _resolve_graph_algo(n, dim, k, algo, mt)

    if algo == "nn_descent":
        from ..ops import nn_descent as nnd

        # an unservable metric is an invalid REQUEST, not a builder
        # failure: raise before guarded_call so it can't persist a
        # demotion of the site (auto never routes here — see
        # _resolve_graph_algo — so this only fires on explicit asks)
        expects(nnd.supports(mt),
                "nn_descent supports L2/IP metrics, got %s", mt.name)

        # adapt the uniform 3-arg hook to build_graph's 4-arg per-round
        # call (the update rate stays a direct-API detail)
        nnd_progress = (None if progress is None else
                        lambda r, total, rate, s: progress(r, total, s))

        def _nnd():
            g = nnd.build_graph(dataset, k, metric=mt,
                                rounds=nnd_rounds, seed=seed,
                                init_graph=init_graph,
                                progress=nnd_progress)
            if info is not None:
                info["algo"] = "nn_descent"
            return g

        def _exact():
            return build_knn_graph(
                dataset, k, mt, seed, batch,
                algo="brute" if n <= _brute_n_threshold() else "ivf_pq",
                engine=engine, progress=progress, info=info)

        # a builder failure (compile OOM on an unrehearsed shape, device
        # loss mid-round) costs a demotion log line and a slower exact/
        # ivf_pq build, never the index
        return guarded_call("cagra.nn_descent", _nnd, _exact)

    if info is not None:
        info["algo"] = algo

    graph = np.zeros((n, k), np.int32)
    drop_self = jax.jit(partial(_drop_self_pad, k=k, n=n))
    batch = min(batch, n)

    if algo == "brute":
        if engine == "auto":
            # fused when the streaming kernel can serve the metric on
            # real hardware (interpret mode exists as the parity-test
            # twin, not a build engine); matmul elsewhere — both
            # produce the same graph bit for bit
            engine = ("fused" if jax.default_backend() == "tpu"
                      and bf_mod.fused_capable(mt) else "matmul")
        if info is not None:
            info["engine"] = engine
        # at memory scale, bigger distance-block chunks amortize the
        # matmul engine's per-chunk top_k fixed cost; respect an
        # explicit user workspace choice (the fused engine has no
        # distance block — its VMEM working set is per-tile)
        ws = (4096 if n > 400_000 and engine == "matmul"
              and "RAFT_TPU_MATMUL_WORKSPACE_MB" not in os.environ
              else None)
        part_cap = int(os.environ.get("RAFT_TPU_CAGRA_BRUTE_PART_N",
                                      "500000"))
        if n <= part_cap:
            index = bf_mod.build(dataset, mt)
            _brute_graph_loop(bf_mod, dataset, index, graph, drop_self,
                              k, n, batch, ws, engine, progress)
            return graph
        _parted_brute_graph(bf_mod, dataset, graph, drop_self, k, n, dim,
                            mt, batch, ws, part_cap, engine, progress)
        return graph

    n_lists = max(16, min(1024, int(np.sqrt(n) * 2)))
    # pq_bits=4 at pq_dim=dim: same code bits/row as pq_dim=dim/2 @ 8-bit
    # but an 8x narrower one-hot decode; int8 LUT doubles the MXU decode
    # rate (the round-4 scan rework — candidate quality is recovered by
    # the exact refine below)
    pq_dim = min(dim, 4 * ivf_pq_mod._default_pq_dim(dim))
    index = ivf_pq_mod.build(dataset, ivf_pq_mod.IndexParams(
        n_lists=n_lists, pq_dim=pq_dim, pq_bits=4, metric=mt, seed=seed))
    # candidate recall, not search recall, is the bar here (refine +
    # optimize()'s detour pruning tolerate imperfect candidates):
    # a quarter-of-corpus probe sweep would be minutes per batch at 500k
    n_probes = max(16, min(64, n_lists // 8))
    gpu_k = min(n, k * 2 + 1)  # refine_rate=2 + room for the self match
    dataset_bf16 = jnp.asarray(dataset, jnp.bfloat16)  # half the gather
    sp = ivf_pq_mod.SearchParams(n_probes, lut_dtype="int8")

    def step(idx_rows):
        qb = dataset[idx_rows]
        _, cand = ivf_pq_mod.search(index, qb, gpu_k, sp)
        _, ref = refine_mod.refine(dataset_bf16, qb, cand, k + 1, mt)
        return drop_self(ref, jnp.asarray(idx_rows))

    _graph_batch_loop(graph, batch, step, "cagra.knn_graph[ivf_pq]",
                      progress)
    return graph


def _graph_batch_loop(graph, batch, step, what, progress=None):
    """The ONE batch loop every graph-construction sweep shares (brute
    single-index, brute parted, ivf_pq candidate pass): tail batches
    wrap back to the full batch shape so every iteration hits the same
    compiled executable — tunnel compiles cost tens of seconds each —
    and a progress hook breaks the minutes-long silence between build
    log lines (default: one log line at most every 30 s).
    ``step(idx_rows) -> (batch, k) ids``; the loop owns the tail slice
    and the host write-back."""
    import time as _time

    from ..core import logging as rlog

    n = graph.shape[0]
    t0 = last = _time.perf_counter()
    for b0 in range(0, n, batch):
        hi = min(b0 + batch, n)
        idx_rows = (np.arange(b0, b0 + batch) % n).astype(np.int32)
        graph[b0:hi] = np.asarray(step(idx_rows))[: hi - b0]
        now = _time.perf_counter()
        if progress is not None:
            progress(hi, n, now - t0)
        elif now - last > 30.0 and hi < n:
            rlog.log_info("%s: %d/%d rows (%.0fs)", what, hi, n, now - t0)
            last = now


def _parted_brute_graph(bf_mod, dataset, graph, drop_self, k, n, dim, mt,
                        batch, workspace_mb, part_cap, engine,
                        progress=None):
    """Exact kNN-graph sweep for corpora past the single-program compile
    cap: 1M-row single-GEMM programs hang the tunneled compiler (bench
    probe_part_compile, 2026-07-31), so the corpus splits into equal
    ≤``part_cap`` parts — ONE shared search executable, padding rows
    masked by ``valid_rows``, per-part top-(k+1) merged exactly
    (knn_merge_parts) before self-edge removal. Shares the fused/matmul
    engine choice and the common batch loop with the single-index
    path."""
    from ..distance.distance_types import is_min_close

    # split against the 128-aligned cap, so the later round-up to the
    # 128-row tile can never push a part past part_cap (the compile-cap
    # this path exists to respect): n_parts = ceil(n / cap_al) guarantees
    # ceil(n / n_parts) <= cap_al, and rounding a value <= cap_al up to
    # 128 stays <= cap_al
    cap_al = max(128, (part_cap // 128) * 128)
    n_parts = -(-n // cap_al)
    part_n = ((-(-n // n_parts) + 127) // 128) * 128

    def part_slice(i):
        """Equal-shape part i, zero-padding only the tail slice (a full
        padded corpus copy would double host memory at the 1M scale
        this path exists for)."""
        sl = dataset[i * part_n:(i + 1) * part_n]
        if len(sl) < part_n:
            sl = np.concatenate(
                [sl, np.zeros((part_n - len(sl), dim), np.float32)])
        return sl

    indexes = [bf_mod.build(part_slice(i), mt) for i in range(n_parts)]
    valid = [max(0, min(part_n, n - i * part_n)) for i in range(n_parts)]
    kq = min(n, k + 1)
    if engine == "fused":
        # eager alignment BEFORE the jit trace (caches are never written
        # under a trace); each part's corpus then stays HBM-resident in
        # tile-aligned form across the whole sweep
        for ix in indexes:
            bf_mod.prepare_fused(ix)
        sfn = jax.jit(lambda q, idx, v: bf_mod.search(
            idx, q, kq, algo="pallas", valid_rows=v))
    else:
        sfn = jax.jit(lambda q, idx, v: bf_mod.search(
            idx, q, kq, algo="matmul", valid_rows=v,
            workspace_mb=workspace_mb))
    select_min = is_min_close(mt)

    def step(idx_rows):
        qb = jnp.asarray(dataset[idx_rows])
        ds_, is_ = [], []
        for i, (ix, v) in enumerate(zip(indexes, valid)):
            dd, ii = sfn(qb, ix, jnp.int32(v))
            ds_.append(dd)
            is_.append(jnp.where(ii >= 0, ii + i * part_n, -1))
        _, merged = bf_mod.knn_merge_parts(jnp.stack(ds_), jnp.stack(is_),
                                           select_min)
        return drop_self(merged, jnp.asarray(idx_rows))

    _graph_batch_loop(graph, batch, step,
                      f"cagra.knn_graph[brute.{engine}.parted]", progress)


def _brute_graph_loop(bf_mod, dataset, index, graph, drop_self, k, n,
                      batch, workspace_mb, engine, progress=None):
    """Exact-graph batch loop over one index: per query batch, either
    the streaming fused kernel (corpus HBM-resident in storage width,
    in-kernel two-level select — the per-batch full-width top_k wall is
    gone) or one MXU GEMM + block-min top_k."""
    kq = min(n, k + 1)
    if engine == "fused":
        # one eager alignment; every batch then reads the resident
        # corpus instead of re-padding per dispatch. The search itself
        # is guarded ("brute_force.fused" site): a kernel failure
        # demotes the sweep to the bit-identical GEMM engine mid-build.
        bf_mod.prepare_fused(index)

        def step(idx_rows):
            qb = jnp.asarray(dataset[idx_rows])
            _, cand = bf_mod.search(index, qb, kq, algo="pallas")
            return drop_self(cand, jnp.asarray(idx_rows))
    else:
        def step(idx_rows):
            qb = jnp.asarray(dataset[idx_rows])
            _, cand = bf_mod.search(index, qb, kq, algo="matmul",
                                    workspace_mb=workspace_mb)
            return drop_self(cand, jnp.asarray(idx_rows))

    _graph_batch_loop(graph, batch, step,
                      f"cagra.knn_graph[brute.{engine}]", progress)


def _drop_self_pad(ref, rows, *, k: int, n: int):
    """Per row: first k entries of ``ref`` that are valid and not the row
    itself, cycling valid neighbors to fill a shortfall ((n+1)%n fallback
    when empty). Vectorized replacement for the old per-row host loop."""
    w = ref.shape[1]
    valid = (ref >= 0) & (ref != rows[:, None])
    pos = jnp.arange(w, dtype=jnp.int32)
    order = jnp.argsort(jnp.where(valid, pos, w + pos), axis=1)
    ref_s = jnp.take_along_axis(ref, order, axis=1)
    ok_s = jnp.take_along_axis(valid, order, axis=1)
    n_ok = jnp.sum(ok_s, axis=1, keepdims=True)             # (b, 1)
    idx = jnp.where(n_ok > 0, pos[None, :k] % jnp.maximum(n_ok, 1), 0)
    out = jnp.take_along_axis(ref_s, idx, axis=1)
    return jnp.where(n_ok > 0, out, (rows[:, None] + 1) % n).astype(jnp.int32)


def _detour_counts(graph_j, batch_nodes):
    """(b, d0) detour counts for a batch of nodes (kern_prune analog).

    Edge (i, N_i[b]) is detourable through N_i[a] (a < b, i.e. a closer
    neighbor) if the graph has the edge N_i[a] → N_i[b]. Membership is an
    all-compare with the equality reduction over the adjacency minor axis
    — O(d0³) VPU compares per node, but every op is a dense vector op
    XLA fuses into the reduction (order-insensitive: no pre-sorted
    adjacency needed). The O(d0² log d0) searchsorted alternative is
    asymptotically better and catastrophically slower here: its
    per-bisection-step ``take_along_axis`` lowers to per-ELEMENT gathers
    (~470M scalar loads per batch, measured 12.3 s/batch vs <0.5 s for
    this form — full optimize 277.8 s → 37.3 s at 100k).
    """
    nbrs = graph_j[batch_nodes]                       # (B, d0)
    b, d0 = nbrs.shape
    nbr_rows = graph_j[nbrs]                          # (B, d0, d0)
    # adj[x, a, t] = any_c nbr_rows[x, a, c] == nbrs[x, t]; the 4-D
    # broadcast never materializes — XLA fuses compare into the c-reduce
    adj = jnp.any(nbr_rows[:, :, :, None] == nbrs[:, None, None, :],
                  axis=2)                             # (B, a, t)
    tri = jnp.tril(jnp.ones((d0, d0), bool), k=-1).T  # a < t strictly
    return jnp.sum(adj & tri[None], axis=1)           # (B, d0)


@partial(jax.jit, static_argnames=("tail_w",))
def _merge_tail_batch(kept, cand, rows, tail_w: int):
    """Per-row: first ``tail_w`` candidates from ``cand`` (in order) that
    are valid, not self, and not already in ``kept`` or earlier in ``cand``;
    shortfall filled with the last kept edge. All batched tensor ops — the
    vectorized form of the reference's per-node rev/fwd merge loop."""
    b, w = cand.shape
    dup_kept = jnp.any(cand[:, :, None] == kept[:, None, :], axis=2)
    dup_prior = jnp.tril(cand[:, :, None] == cand[:, None, :], k=-1).any(axis=2)
    valid = (cand >= 0) & (cand != rows[:, None]) & ~dup_kept & ~dup_prior
    pos = jnp.arange(w, dtype=jnp.int32)
    order = jnp.argsort(jnp.where(valid, pos, w + pos), axis=1)[:, :tail_w]
    tail = jnp.take_along_axis(cand, order, axis=1)
    ok = jnp.take_along_axis(valid, order, axis=1)
    return jnp.where(ok, tail, kept[:, -1:])


@partial(jax.jit, static_argnames=("graph_degree",))
def _prune_batch(graph_j, nodes, graph_degree: int):
    """One node-batch of detour counting + rank-composite prune
    (kern_prune analog): count, argsort the (detours, rank) key, keep
    the best ``graph_degree`` — all on device, only the (B, degree)
    result leaves the chip."""
    d0 = graph_j.shape[1]
    detours = _detour_counts(graph_j, nodes)
    # composite key (detours ≤ d0 ≤ 512 keeps it well inside int32)
    key = detours * d0 + jnp.arange(d0, dtype=jnp.int32)[None, :]
    order = jnp.argsort(key, axis=1, stable=True)[:, :graph_degree]
    return jnp.take_along_axis(graph_j[nodes], order, axis=1)


@partial(jax.jit, static_argnames=("keep_fwd", "rev_cap"))
def _rev_group_jit(pruned, keep_fwd: int, rev_cap: int):
    """Reverse-edge table (kern_make_rev_graph analog): stable sort by
    target + segment positions, capped at ``rev_cap`` per node."""
    n = pruned.shape[0]
    # column-major flatten: all rank-0 forward edges arrive first, so a
    # capped reverse list keeps edges from the *closest* forward links
    # rather than from low row ids (rank priority of the reference merge)
    tgt = pruned[:, :keep_fwd].T.reshape(-1)
    src = jnp.tile(jnp.arange(n, dtype=jnp.int32), keep_fwd)
    tgt = jnp.where((tgt >= 0) & (tgt < n), tgt, n)   # junk edges → row n
    so = jnp.argsort(tgt, stable=True)
    ts, cs = tgt[so], src[so]
    counts = jnp.bincount(ts, length=n + 1)
    seg_start = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]])
    pos = jnp.arange(ts.shape[0], dtype=jnp.int32) - seg_start[ts]
    keep = (pos < rev_cap) & (ts < n)
    rev = jnp.full((n + 1, rev_cap), -1, jnp.int32)
    return rev.at[jnp.where(keep, ts, n),
                  jnp.where(keep, pos.astype(jnp.int32), 0)].set(
        jnp.where(keep, cs, -1))[:n]




def _rev_group_host(pruned: np.ndarray, keep_fwd: int,
                    rev_cap: int) -> np.ndarray:
    """Host mirror of :func:`_rev_group_jit` for node counts where the
    one monolithic device sort is unrehearsed (large fused programs have
    crashed the tunneled TPU worker; a 32M-element np.argsort is ~2 s)."""
    n = pruned.shape[0]
    tgt = pruned[:, :keep_fwd].T.reshape(-1).astype(np.int64)
    src = np.tile(np.arange(n, dtype=np.int32), keep_fwd)
    tgt = np.where((tgt >= 0) & (tgt < n), tgt, n)
    so = np.argsort(tgt, kind="stable")
    ts, cs = tgt[so], src[so]
    counts = np.bincount(ts, minlength=n + 1)
    seg_start = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(ts)) - seg_start[ts]
    keep = (pos < rev_cap) & (ts < n)
    rev = np.full((n, rev_cap), -1, np.int32)
    rev[ts[keep], pos[keep].astype(np.int64)] = cs[keep]
    return rev


@tracing.annotate("raft_tpu::cagra::optimize")
def optimize(knn_graph: np.ndarray, graph_degree: int,
             batch: int = 2048) -> np.ndarray:
    """Detour-count prune + reverse-edge merge (graph_core.cuh:128-191).

    Keep the ``graph_degree`` edges with fewest detours (ties → closer
    rank), then replace the tail half with reverse edges where available —
    the reference merges forward and reverse graphs 50/50. All phases
    run on device (kern_prune / kern_make_rev_graph analogs); prune and
    merge advance in constant-shape node batches (wrapped tails, one
    compiled executable each — large monolithic lax.map variants of
    these programs have crashed the tunneled TPU worker at 100k-node
    scale, and per-batch dispatch costs only milliseconds each).
    """
    knn_graph = np.asarray(knn_graph, np.int32)
    n, d0 = knn_graph.shape
    expects(graph_degree <= d0, "graph_degree %d > intermediate %d",
            graph_degree, d0)
    # bound the live membership working set — the (B, d0, d0) adjacency
    # gather (int32) plus the (B, d0, d0) adj/hit planes; the 4-D
    # broadcast compare itself fuses into its reduction and never
    # materializes (measured: see _detour_counts)
    batch = max(256, min(batch * 8, (1 << 30) // max(d0 * d0 * 16, 1)))
    batch = min(batch, n)
    keep_fwd = graph_degree - graph_degree // 2
    tail_w = graph_degree - keep_fwd
    graph_j = jnp.asarray(knn_graph)

    pruned = np.zeros((n, graph_degree), np.int32)
    for b0 in range(0, n, batch):
        hi = min(b0 + batch, n)
        nodes = jnp.asarray(np.arange(b0, b0 + batch) % n)
        pruned[b0:hi] = np.asarray(_prune_batch(
            graph_j, nodes, graph_degree))[: hi - b0]

    pruned_j = jnp.asarray(pruned)
    import os as _os
    rev_jit_edges = int(_os.environ.get("RAFT_TPU_REV_JIT_EDGES",
                                        str(20 << 20)))
    if n * keep_fwd > rev_jit_edges:
        # scale guard (rehearsed to 500k nodes on device): beyond it the
        # stable argsort+scatter over all n*keep_fwd edges runs on host
        rev = jnp.asarray(_rev_group_host(pruned, keep_fwd, graph_degree))
    else:
        rev = _rev_group_jit(pruned_j, keep_fwd, graph_degree)

    # interleave reverse and forward-tail candidates 1:1 (rev first)
    fwd_tail = jnp.full((n, graph_degree), -1, jnp.int32)
    fwd_tail = fwd_tail.at[:, :tail_w].set(pruned_j[:, keep_fwd:])
    cand_j = jnp.stack([rev, fwd_tail], axis=2).reshape(n, 2 * graph_degree)

    out = pruned.copy()
    kept_j = pruned_j[:, :keep_fwd]
    for b0 in range(0, n, batch):
        b1 = min(b0 + batch, n)
        sel = jnp.asarray(np.arange(b0, b0 + batch) % n)
        out[b0:b1, keep_fwd:] = np.asarray(_merge_tail_batch(
            jnp.take(kept_j, sel, axis=0), jnp.take(cand_j, sel, axis=0),
            sel.astype(jnp.int32), tail_w))[: b1 - b0]
    return out


@tracing.annotate("raft_tpu::cagra::build")
def build(dataset, params: IndexParams | None = None) -> Index:
    """kNN graph (IVF-PQ path) → optimize → index (cagra_build.cuh:292)."""
    import time as _time

    from ..core import logging as rlog

    p = params or IndexParams()
    dataset = np.asarray(dataset, np.float32)
    n = len(dataset)
    mt = canonical_metric(p.metric)
    expects(mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                   DistanceType.InnerProduct),
            "cagra supports L2/IP metrics, got %s", mt.name)
    d0 = min(p.intermediate_graph_degree, n - 1)
    degree = min(p.graph_degree, d0)
    t0 = _time.perf_counter()
    ginfo = {}
    if p.build_algo is BuildAlgo.NN_DESCENT:
        # the batched device-resident builder (ops/nn_descent.py) with
        # the guarded exact/ivf_pq fallback; nn_descent_niter caps the
        # rounds (update-rate early stop usually fires first)
        knn = build_knn_graph(dataset, d0, mt, p.seed, algo="nn_descent",
                              nnd_rounds=p.nn_descent_niter, info=ginfo)
    else:
        # nnd_rounds rides along for the knn_graph_algo="nn_descent" and
        # auto-resolved descent routes — the knob must not silently work
        # on the BuildAlgo branch only
        knn = build_knn_graph(dataset, d0, mt, p.seed,
                              algo=p.knn_graph_algo,
                              nnd_rounds=p.nn_descent_niter, info=ginfo)
    # the builder that actually ran — under algo="auto" or a
    # cagra.nn_descent demotion this differs from the requested one, and
    # build_stats is the evidence block perf runs read
    galgo = ginfo.get("algo", p.knn_graph_algo)
    t1 = _time.perf_counter()
    graph = optimize(knn, degree)
    t2 = _time.perf_counter()
    seeds = build_covering_seeds(dataset, p, mt)
    t3 = _time.perf_counter()
    rlog.log_info(
        "cagra.build n=%d: knn_graph %.1fs (%s), optimize %.1fs, "
        "seeds %.1fs", n, t1 - t0, galgo, t2 - t1, t3 - t2)
    index = Index(jnp.asarray(dataset), jnp.asarray(graph), mt, seeds)
    # phase decomposition for harnesses (the bench records it on CAGRA
    # entries): a plain host attribute, NOT part of the pytree — it is
    # diagnostics, not index state
    index.build_stats = {"n": n, "knn_algo": galgo,
                         "knn_graph_s": round(t1 - t0, 1),
                         "optimize_s": round(t2 - t1, 1),
                         "seeds_s": round(t3 - t2, 1)}
    return index


def build_covering_seeds(dataset, p: "IndexParams", mt):
    """The seed-set POLICY (sizing + <64-row clamp) applied to a
    corpus → (s,) seed rows or None. One home for the policy so every
    index constructor — ``build`` and the mutable tier's warm-started
    merge rebuild (neighbors/mutable.py), which bypasses ``build`` to
    feed ``build_knn_graph`` an init graph — sizes seeds identically;
    a rebuild path that skipped this would silently regress to
    random-only seeding after the first merge."""
    from ..core import logging as rlog

    n = len(dataset)
    if p.seed_nodes < 0:
        # auto: scale coverage with the corpus; skip tiny corpora where
        # random seeding already covers the space
        n_seed = max(128, min(2048, n // 64))
        n_seed = n_seed if n > 4 * n_seed else 0
    else:
        # explicit request: honor it, clamped so the seed set stays a
        # strict covering subset; requests below search()'s 64-row
        # eligibility threshold would build dead weight (search ignores
        # smaller seed sets), so clamp them to 0 and say so
        n_seed = min(p.seed_nodes, n // 4)
        if 0 < n_seed < 64:
            rlog.log_warn(
                "cagra.build: seed_nodes=%d is below the 64-row search "
                "threshold; skipping seed construction", n_seed)
            n_seed = 0
    return _covering_seeds(dataset, n_seed, mt, p.seed) if n_seed > 0 \
        else None


def _covering_seeds(dataset, s: int, mt, seed: int) -> jax.Array:
    """(s,) sorted unique dataset row ids nearest to kmeans centroids:
    the shared traversal seed set (one small GEMM scores it for every
    query at search time).

    Coverage needs *spread*, not balanced partition quality, so the
    centroids come from a fixed-iteration Lloyd over a bounded subsample
    — one compiled executable (a full balanced-kmeans here was 125 s of
    the 100k build, >10x the phase's usefulness). The centroid→row step
    always uses L2: the seed set must cover the *geometry* of the corpus
    — under InnerProduct a max-IP pick would collapse onto a few
    high-norm rows and cover nothing."""
    from . import brute_force as bf_mod
    from .ivf_pq import _kmeans_fixed

    dataset = np.asarray(dataset, np.float32)
    n = len(dataset)
    rng = np.random.default_rng(seed)
    t = min(n, max(8 * s, 20_000))
    rows = rng.choice(n, size=t, replace=False)
    cent = _kmeans_fixed(jnp.asarray(dataset[rows]), s, 10,
                         jax.random.PRNGKey(seed))
    index = bf_mod.build(dataset, DistanceType.L2Expanded)
    _, ids = bf_mod.search(index, cent, 1, algo="matmul")
    return jnp.asarray(np.unique(np.asarray(ids[:, 0])), jnp.int32)


def _query_dists(qc, vecs, mt):
    """(m, c, d) candidate vectors → (m, c) distances to qc (m, d).
    bf16 ``vecs`` (the bandwidth-saving traversal mode) stay bf16 into
    the MXU contraction and accumulate in f32 — no (m, c, d) f32
    materialization between the gather and the dot."""
    if vecs.dtype == jnp.bfloat16:
        qcv = qc.astype(jnp.bfloat16)
        kw = {"preferred_element_type": jnp.float32}
    else:
        qcv = qc
        vecs = vecs.astype(jnp.float32)
        kw = {"precision": "highest", "preferred_element_type": jnp.float32}
    ip = jnp.einsum("mcd,md->mc", vecs, qcv, **kw)
    if mt is DistanceType.InnerProduct:
        return -ip
    q2 = jnp.sum(qc * qc, axis=1, keepdims=True)
    v2 = jnp.einsum("mcd,mcd->mc", vecs, vecs, **kw)
    return jnp.maximum(q2 + v2 - 2.0 * ip, 0.0)


def _gather_score(score, score_scales, cand, qc, mt):
    """Gather candidate rows + score against queries; the traversal's one
    HBM-bound op (cand rows are random 128-256 B lines, so bytes gathered
    — not FLOPs — bound the hop). int8 rows apply per-row scales after
    the gather (half the bf16 traffic)."""
    vecs = score[cand]
    if score_scales is not None:
        vecs = vecs.astype(jnp.float32) * score_scales[cand][..., None]
    return _query_dists(qc, vecs, mt)


def _seed_dists(qc, vecs, mt):
    """(s, d) shared seed vectors → (m, s) distances: one dense GEMM
    (every query scores the same rows — no gather)."""
    if vecs.dtype == jnp.bfloat16:
        qcv = qc.astype(jnp.bfloat16)
        kw = {"preferred_element_type": jnp.float32}
    else:
        qcv = qc
        vecs = vecs.astype(jnp.float32)
        kw = {"precision": "highest", "preferred_element_type": jnp.float32}
    ip = jnp.einsum("md,sd->ms", qcv, vecs, **kw)
    if mt is DistanceType.InnerProduct:
        return -ip
    q2 = jnp.sum(qc * qc, axis=1, keepdims=True)
    v2 = jnp.einsum("sd,sd->s", vecs, vecs, **kw)
    return jnp.maximum(q2 + v2[None, :] - 2.0 * ip, 0.0)


def _dup_mask(cand, keep=None):
    """(m, c) bool: ``cand[i, j]`` duplicates an entry of ``keep[i]`` or
    an *earlier* ``cand[i, j' < j]``.

    Sort-based replacement for the former O(c²)/O(c·itopk) broadcast
    equality planes (``jnp.tril(eq)`` over (m, c, c) — VMEM-hungry at
    itopk64·w4 and quadratic in ``search_width``): one stable argsort of
    the concatenated ids brings every duplicate run together, a single
    neighbor compare flags all but the run's first element, and the
    inverse permutation (a second integer argsort) carries the flags
    back. Stability makes "first" = lowest original position, and
    ``keep`` entries precede equal candidates in the concat order, so
    the semantics match the old masks exactly: any candidate equal to a
    keep entry, or to an earlier candidate, is flagged."""
    m, c = cand.shape
    allv = cand if keep is None else jnp.concatenate([keep, cand], axis=1)
    b = allv.shape[1] - c
    order = jnp.argsort(allv, axis=1, stable=True)
    sv = jnp.take_along_axis(allv, order, axis=1)
    dup_s = jnp.concatenate(
        [jnp.zeros((m, 1), bool), sv[:, 1:] == sv[:, :-1]], axis=1)
    inv = jnp.argsort(order, axis=1, stable=True)
    return jnp.take_along_axis(dup_s, inv, axis=1)[:, b:]


@partial(jax.jit, static_argnames=("itopk", "width", "max_iter", "k",
                                   "n_seeds", "mt_val", "min_iter",
                                   "engine", "kprime", "interp", "smode"))
def _search_jit(dataset, dataset_score, score_scales, graph, qc, mask_bits,
                seed_key, seed_rows, edge_vecs, edge_aux, edge_gp, itopk,
                width, max_iter, k, n_seeds, mt_val, min_iter=0,
                engine="gather", kprime=0, interp=False, edge_cb=None,
                edge_cbs=None, smode="dense"):
    """``dataset_score`` feeds the seed scoring and (engine="gather") the
    traversal's candidate gathers (bf16 in the default bandwidth-saving
    mode, int8 + per-row ``score_scales`` in the quarter-traffic mode);
    ``dataset`` (f32) re-scores the final top-k exactly, so returned
    distances are exact regardless. ``seed_rows``: optional (s,) shared
    covering seed set — scored by one GEMM and mixed with the per-query
    random seeds. ``engine="edge"``: the hop streams each parent's
    contiguous neighbor tile from ``edge_vecs``/``edge_aux`` (the
    prepare_traversal store) through the Pallas frontier-expansion
    kernel, which emits a per-parent top-``kprime`` — the merge width
    shrinks from width·degree to width·kprime. ``engine="fused"``: the
    whole hop loop collapses into ONE megakernel launch
    (ops/cagra_fused.py) — the frontier lives in VMEM across grid steps
    and ``edge_gp`` (the store's tile-padded graph rows) feeds the
    in-kernel id extraction; bit-identical to the edge engine by
    construction."""
    mt = DistanceType(mt_val)
    m, dim = qc.shape
    n = dataset.shape[0]
    degree = graph.shape[1]
    metric_s = "ip" if mt is DistanceType.InnerProduct else "l2"

    if engine in ("edge", "fused") and mask_bits is not None:
        # the bitset filter in edge-major layout: the kernel adds this
        # penalty in-VMEM, so filtered edges never reach the merge. One
        # (n, degree) gather per CALL (not per hop), loop-invariant
        pen_node = jnp.where(mask_bits, 0.0, jnp.inf).astype(jnp.float32)
        edge_pen = jnp.pad(pen_node[graph],
                           ((0, 0), (0, edge_vecs.shape[1] - degree)))
    else:
        edge_pen = None

    # seed the itopk buffer: per-query random nodes (random_seed init,
    # search_plan.cuh), plus the shared covering set when present
    if mask_bits is not None:
        # survivor-aware seeding (ops/filter_policy.py): uniform-over-n
        # seeds can ALL land on filtered rows under a high-selectivity
        # filter (empty result despite survivors). Sampling the r-th
        # set bit via the mask's cumulative sum is uniform over the
        # surviving rows by construction; an all-cleared mask keeps
        # every seed at +inf, so the empty-result contract holds.
        csum = jnp.cumsum(mask_bits.astype(jnp.int32))
        r = jax.random.randint(seed_key, (m, n_seeds), 0,
                               jnp.maximum(csum[-1], 1))
        seeds = jnp.minimum(jnp.searchsorted(csum, r + 1), n - 1)
        seed_d = _gather_score(dataset_score, score_scales, seeds, qc, mt)
        seed_d = jnp.where(mask_bits[seeds], seed_d, jnp.inf)
    else:
        seeds = jax.random.randint(seed_key, (m, n_seeds), 0, n)
        seed_d = _gather_score(dataset_score, score_scales, seeds, qc, mt)
    # dedup identical random seeds (mark later occurrences)
    seed_d = jnp.where(_dup_mask(seeds), jnp.inf, seed_d)
    if seed_rows is not None:
        svecs = dataset_score[seed_rows]              # (s, d) — tiny
        if score_scales is not None:
            svecs = svecs.astype(jnp.float32) \
                * score_scales[seed_rows][:, None]
        sd = _seed_dists(qc, svecs, mt)               # (m, s)
        if mask_bits is not None:
            sd = jnp.where(mask_bits[seed_rows][None, :], sd, jnp.inf)
        # a random seed colliding with a shared seed is a duplicate;
        # seed_rows is sorted unique (np.unique in _covering_seeds), so
        # membership is a searchsorted probe — not an (m, n_seeds, s)
        # broadcast compare
        pos = jnp.searchsorted(seed_rows, seeds)
        coll = jnp.take(seed_rows,
                        jnp.clip(pos, 0, seed_rows.shape[0] - 1)) == seeds
        seed_d = jnp.where(coll, jnp.inf, seed_d)
        seeds = jnp.concatenate(
            [jnp.broadcast_to(seed_rows[None, :], (m, seed_rows.shape[0])),
             seeds], axis=1)
        seed_d = jnp.concatenate([sd, seed_d], axis=1)
    total = seed_d.shape[1]
    if total < itopk:
        seed_d = jnp.concatenate(
            [seed_d, jnp.full((m, itopk - total), jnp.inf, jnp.float32)],
            axis=1)
        seeds = jnp.concatenate(
            [seeds, jnp.full((m, itopk - total), -1, jnp.int32)], axis=1)
    buf_d, srt = select_k(seed_d, itopk, select_min=True)
    buf_i = jnp.take_along_axis(seeds, srt, axis=1)
    explored = jnp.zeros((m, itopk), bool)

    def cond(state):
        _, buf_d, explored, it = state
        frontier_open = jnp.any(~explored & jnp.isfinite(buf_d))
        return (it < max_iter) & (frontier_open | (it < min_iter))

    cand_w = width * (kprime if engine == "edge" else degree)

    def body(state):
        buf_i, buf_d, explored, it = state
        # pick top `width` unexplored parents (pickup_next_parents :51)
        cand_d = jnp.where(explored, jnp.inf, buf_d)
        _, psel = select_k(cand_d, width, select_min=True)   # (m, w) positions
        parent_ids = jnp.take_along_axis(buf_i, psel, axis=1)
        parent_ok = jnp.isfinite(jnp.take_along_axis(cand_d, psel, axis=1))
        explored = explored.at[jnp.arange(m)[:, None], psel].set(True)
        psafe = jnp.where(parent_ok, parent_ids, 0)

        if engine == "edge":
            # streamed expansion: one contiguous edge-store tile per
            # parent through the Pallas kernel (bitset penalty applied
            # in-kernel), emitting per-parent top-kprime — only the
            # (m, w, deg) int32 graph rows are still gathered, 1/dim-th
            # of the former vector-gather bytes
            from ..ops.graph_expand import graph_expand

            pvals, pepos = graph_expand(psafe, qc, edge_vecs, edge_aux,
                                        kprime, metric=metric_s,
                                        degree=degree, pen=edge_pen,
                                        interpret=interp, mode=smode,
                                        cbm=edge_cb, cb_scale=edge_cbs)
            nbr = graph[psafe]                               # (m, w, deg)
            cand = jnp.take_along_axis(nbr, jnp.maximum(pepos, 0), axis=2)
            # empty kernel slots (epos -1) must not alias a real node id:
            # a phantom occurrence would dup-flag a later genuine one
            cand = jnp.where(pepos >= 0, cand, -1).reshape(m, cand_w)
            cd = pvals.reshape(m, cand_w)
            cand_ok = (jnp.repeat(parent_ok, kprime, axis=1)
                       & (pepos >= 0).reshape(m, cand_w))
        else:
            # expand: graph neighbors of parents (the random row gather)
            cand = graph[psafe].reshape(m, cand_w)           # (m, w·deg)
            cand_ok = jnp.repeat(parent_ok, degree, axis=1)
            cd = _gather_score(dataset_score, score_scales, cand, qc, mt)
            if mask_bits is not None:
                cand_ok = cand_ok & mask_bits[cand]
        # dedup vs itopk buffer (the hashmap stand-in) and within the
        # candidate block. Without this, near convergence most of the
        # block duplicates top buffer entries, floods the merge's top
        # slots, and evicts genuinely new candidates — measured recall
        # collapse 0.97 → 0.70 (sort-based: see _dup_mask)
        cand_ok = cand_ok & ~_dup_mask(cand, keep=buf_i)
        cd = jnp.where(cand_ok, cd, jnp.inf)

        # merge candidates into itopk (bitonic merge analog :94-200)
        all_d = jnp.concatenate([buf_d, cd], axis=1)
        all_i = jnp.concatenate([buf_i, cand], axis=1)
        all_e = jnp.concatenate(
            [explored, jnp.zeros((m, cand_w), bool)], axis=1)
        new_d, sel = select_k(all_d, itopk, select_min=True)
        new_i = jnp.take_along_axis(all_i, sel, axis=1)
        new_e = jnp.take_along_axis(all_e, sel, axis=1)
        return new_i, new_d, new_e, it + 1

    if engine == "fused":
        # ONE kernel launch for the whole traversal: the seeded buffer
        # goes in, the converged buffer comes out — no host-visible hop
        # loop remains (the fixed grid runs max_iter hops; converged
        # hops are exact no-ops, see ops/cagra_fused.fused_traverse)
        from ..ops.cagra_fused import fused_traverse

        buf_d, buf_i = fused_traverse(
            qc, buf_d, buf_i, edge_vecs, edge_aux, edge_gp, edge_pen,
            itopk=itopk, width=width, max_iter=int(max_iter),
            kprime=kprime, degree=degree, metric=metric_s,
            interpret=interp, mode=smode)
    else:
        state = (buf_i, buf_d, explored, jnp.int32(0))
        buf_i, buf_d, explored, _ = jax.lax.while_loop(cond, body, state)

    # exact f32 re-score + re-rank of the returned k (fixes any bf16
    # traversal rounding; one (m, k, d) gather)
    out_i = buf_i[:, :k]
    finite = jnp.isfinite(buf_d[:, :k])
    exact = _query_dists(qc, dataset[jnp.maximum(out_i, 0)], mt)
    exact = jnp.where(finite, exact, jnp.inf)
    out_d, order = select_k(exact, k, select_min=True)
    out_i = jnp.take_along_axis(out_i, order, axis=1)
    if mt is DistanceType.L2SqrtExpanded:
        out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
    elif mt is DistanceType.InnerProduct:
        out_d = jnp.where(jnp.isfinite(out_d), -out_d, -jnp.inf)
    out_i = jnp.where(jnp.isfinite(out_d) if mt is not DistanceType.InnerProduct
                      else out_d > -jnp.inf, out_i, -1)
    return out_d, out_i


def prepare_search(index: Index, candidate_dtype: str = "bfloat16") -> None:
    """Eagerly attach the low-precision traversal copy of the dataset
    (used by the matching ``SearchParams.candidate_dtype``). jit users
    call this once before tracing — an unprepared index re-quantizes
    inside every jitted call."""
    if candidate_dtype in ("bfloat16", "bf16"):
        if getattr(index, "_score_bf16", None) is None:
            index._score_bf16 = index.dataset.astype(jnp.bfloat16)
    elif candidate_dtype in ("int8", "i8"):
        if getattr(index, "_score_i8", None) is None:
            from .brute_force import quantize_rows

            index._score_i8 = quantize_rows(index.dataset, jnp.int8)


def prepare_traversal(index: Index, candidate_dtype: str = "int8",
                      pq_dim: int = 0, pq_lut: str = "int8") -> None:
    """Eagerly build the edge-resident candidate store and attach it to
    the index: for every node, its ``degree`` neighbors' coded vectors
    packed into one contiguous ``(n, deg_p, W)`` HBM array (plus a
    ``(n, 2, deg_p)`` f32 aux of per-edge dequant scales and norms), so
    the ``engine="edge"`` hop streams one contiguous tile per expanded
    parent instead of ``degree`` random 128-256 B lines — the GGNN
    co-location move (arXiv:1912.01059) in TPU form.

    Storage rungs (docs/perf.md "Storage ladder"; ``W`` = minor width at
    1M·deg64·d128):

    * ``"bfloat16"`` — W=dim_p bf16 (16.8 GB);
    * ``"int8"`` (default) — W=dim_p int8, per-edge scales (8.4 GB);
    * ``"int4"`` — W=dim_p/2 nibble-packed int8 (ops/quant.py
      split-half layout; unpacked in-kernel, 4.2 GB);
    * ``"pq"`` — W=pq_dim uint8 PQ codes per edge, decoded in-kernel by
      the ivf_pq one-hot LUT GEMM (~0.5 GB of codes at pq8·book256 —
      the rung that puts 100M·deg32 within one host's HBM). ``pq_dim``
      overrides the ``ops.quant.default_pq_dim`` subspace count;
      ``pq_lut`` picks the decode matrix precision ("int8" = the
      fp8-LUT role with exact int32 accumulation, or "f32").

    OPT-IN, exactly like ``brute_force.prepare_fused``: a read-only
    query never doubles index HBM as a side effect; ``tune_search``
    attaches it for the race and drops it again if the gather engine
    wins. Idempotent on a matching (dtype, degree) geometry — a second
    call is a no-op, no HBM double-alloc. The store travels through the
    Index pytree, so jitted functions taking the index as an argument
    reuse it; it is derived data and is NOT serialized (rebuild after
    :func:`load`). Never built under a jax trace (cache writes there
    would store tracers)."""
    from ..utils import in_jax_trace

    if in_jax_trace():
        return
    expects(candidate_dtype in ("int8", "i8", "bfloat16", "bf16",
                                "int4", "i4", "pq"),
            "edge store dtype must be int8/bfloat16/int4/pq, got %r",
            candidate_dtype)
    from ..ops import quant

    int8 = candidate_dtype in ("int8", "i8")
    int4 = candidate_dtype in ("int4", "i4")
    pq = candidate_dtype == "pq"
    dtype_str = ("int8" if int8 else "int4" if int4 else
                 "pq" if pq else "bfloat16")
    degree = index.graph_degree
    deg_p = round_up_to(degree, 32)       # int8 sublane tile (bf16 needs 16)
    dim_p = round_up_to(index.dim, 128)
    meta = (dtype_str, degree, deg_p, dim_p)
    cur = getattr(index, "_edge_store", None)
    if cur is not None and cur[0] == meta:
        return
    g = index.graph
    cbs = None
    if int8:
        cached = getattr(index, "_score_i8", None)
        if cached is None:
            cached = quant.quantize_rows(index.dataset, jnp.int8)
            index._score_i8 = cached   # int8 candidate_dtype searches reuse it
        stored, scales = cached
        en = (scales * scales) * jnp.sum(
            jnp.square(stored.astype(jnp.float32)), axis=1)
        es = scales[g]
    elif int4:
        stored, scales = quant.quantize_int4(index.dataset)
        low, high = quant.int4_nibbles(stored.astype(jnp.int32))
        en = (scales * scales) * jnp.sum(low * low + high * high, axis=1)
        es = scales[g]
    elif pq:
        # PQ row codes + the subspace-major decode table the expand
        # kernel consumes (ops/quant.pq_decode_table; int8 mode applies
        # the same per-subspace symmetric quantization as the ivf_pq
        # scan's fp8-LUT role)
        pqd = pq_dim or quant.default_pq_dim(index.dim)
        expects(dim_p % pqd == 0,
                "pq_dim %d must divide the padded dim %d", pqd, dim_p)
        cb = quant.train_pq_rows(index.dataset, pqd)
        stored = quant.encode_pq_rows(index.dataset, cb)   # (n, pqd) u8
        en = quant.pq_decoded_norms(stored, cb)
        es = jnp.ones(g.shape, jnp.float32)    # decode carries magnitude
        tbl = quant.pq_decode_table(cb)        # (pqd*book, dim_p) f32
        if pq_lut == "int8":
            cb_mat, cb_scale = quant.pq_int8_cb(tbl, pqd, cb.shape[1])
        else:
            cb_mat, cb_scale = tbl, jnp.ones((1, dim_p), jnp.float32)
        cbs = (cb_mat, cb_scale)
    else:
        stored = getattr(index, "_score_bf16", None)
        if stored is None:
            stored = index.dataset.astype(jnp.bfloat16)
            index._score_bf16 = stored
        en = jnp.sum(jnp.square(stored.astype(jnp.float32)), axis=1)
        es = jnp.ones(g.shape, jnp.float32)
    pad_d = deg_p - degree
    pad_f = 0 if (int4 or pq) else dim_p - index.dim
    if pad_d or pad_f:
        # gather + pad under one jit write a single padded output buffer;
        # eagerly, stored[g] then jnp.pad holds TWO copies of the store
        # transiently (jnp.pad copies even at zero width) — 2x of 8.2 GB
        # at the 1M int8 point would OOM a v5e.
        ev = jax.jit(lambda s, gg: jnp.pad(
            s[gg], ((0, 0), (0, pad_d), (0, pad_f))))(stored, g)
    else:
        ev = stored[g]
    aux = jnp.stack([es, en[g]], axis=1)
    if pad_d:
        aux = jnp.pad(aux, ((0, 0), (0, 0), (0, pad_d)))
    # tile-padded graph rows ride with the store: the fused megakernel
    # DMAs each parent's id row next to its edge tile (pad edges are
    # masked in-kernel by `col < degree`, so the pad id value is inert)
    gp = jnp.pad(g, ((0, 0), (0, pad_d))) if pad_d else g
    index._edge_store = (meta, ev, aux, gp, cbs)


def _store_mode(store) -> str:
    """Edge-store meta → the expand kernels' storage mode ("dense" for
    int8/bf16 rows, "int4"/"pq" for the packed rungs)."""
    if store is None:
        return "dense"
    tag = store[0][0]
    return tag if tag in ("int4", "pq") else "dense"


def _plan_dims(p: "SearchParams", k: int):
    """(itopk, width, max_iter) of the traversal plan — ONE derivation,
    because ``search`` (the dispatch) and ``tune_search`` (the fused
    VMEM-capability gate) must agree on the hop budget a shape implies."""
    itopk = max(p.itopk_size, k)
    width = max(1, p.search_width)
    max_iter = p.max_iterations or (itopk // width + 16)
    # min_iterations must win over the auto max (the reference adjusts
    # max_iterations up the same way)
    return itopk, width, max(int(max_iter), int(p.min_iterations))


def _tune_key(index: Index, m: int, k: int, p: "SearchParams",
              store) -> str:
    """Autotune bucket for the engine race. Dtype-aware: the edge store's
    storage width (or the gather path's candidate_dtype) is part of the
    key — HBM-traffic-bound crossovers move with the element width, so a
    winner measured for one storage mode must not steer another's
    dispatch (the brute-force race set the precedent)."""
    from ..ops import autotune

    sd = store[0][0] if store is not None else str(p.candidate_dtype)
    return autotune.shape_bucket("cagra_search", n=index.size, m=m,
                                 d=index.dim, k=k, deg=index.graph_degree,
                                 itopk=max(p.itopk_size, k),
                                 w=max(1, p.search_width), store=sd)


def tune_search(index: Index, queries, k: int,
                params: SearchParams | None = None, reps: int = 3,
                suspect_floor_s: float = 0.0,
                store_dtype: str = "int8", engines=None):
    """Measure the traversal engines on-device for this shape class and
    cache the winner (consulted by ``engine="auto"``): the streamed
    edge-store hop (Pallas frontier expansion) and the one-dispatch
    megakernel (``engine="fused"``) race the XLA gather hop — every
    member of :data:`ENGINES` runs (the fused lane is skipped only when
    its VMEM working set exceeds the megakernel cap, see
    ``ops.cagra_fused.fused_capable``). Attaches the edge store for the
    race and DROPS it again when the gather engine wins — the store is
    ~``n·degree·dim`` bytes of extra HBM and only earns it behind a
    store-backed winning engine. Call eagerly (not under jit) — e.g.
    once at serving start, or from the bench harness. Returns
    (winner, timings)."""
    from ..ops import autotune
    from ..ops.cagra_fused import fused_capable

    p = params or SearchParams()
    q = jnp.asarray(queries, jnp.float32)
    prepare_traversal(index, store_dtype)
    prepare_search(index, p.candidate_dtype)
    key = _tune_key(index, q.shape[0], k, p, index._edge_store)

    # the index rides as a jit ARGUMENT (closure-baking the dataset +
    # edge store as HLO constants exceeds remote-compile request limits
    # at memory scale); JitArgFn keeps that true on autotune's
    # plausibility-floor re-measure path
    def _engine(eng):
        return autotune.JitArgFn(jax.jit(
            lambda qq, idx, e=eng: search(idx, qq, k, p, engine=e)), index)

    itopk, width, max_iter = _plan_dims(p, k)
    ev = index._edge_store[1]
    # engines=None races the full registry (the drift guard holds the
    # default to ENGINES); an explicit subset is a caller's cost choice.
    # The megakernel sits the race out for PQ stores (no in-kernel PQ
    # decode — those shapes serve the per-hop edge engine) and for
    # over-VMEM working sets.
    cands = {e: _engine(e) for e in (engines or ENGINES)
             if e != "fused" or (
                 _store_mode(index._edge_store) != "pq" and fused_capable(
                     itopk, width, ev.shape[1], ev.shape[2], ev.dtype,
                     max_iter))}
    winner, timings = autotune.tune_best(key, cands, q, reps=reps,
                                         force=True,
                                         suspect_floor_s=suspect_floor_s,
                                         value_read=True)
    if winner not in ("edge", "fused"):
        index.__dict__.pop("_edge_store", None)
        # the raced key carried the STORE dtype; with the store dropped,
        # auto queries are storeless and key on candidate_dtype — mirror
        # the verdict there so the measured gather win stays reachable
        autotune.record(_tune_key(index, q.shape[0], k, p, None), winner)
    return winner, timings


@interop.auto_convert_output
@tracing.annotate("raft_tpu::cagra::search")
def search(
    index: Index,
    queries,
    k: int,
    params: SearchParams | None = None,
    filter: Optional[Bitset] = None,  # noqa: A002
    res=None,
    query_chunk: int = 0,
    engine: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Batched-frontier graph traversal (search_single_cta analog).

    ``res``/``query_chunk``: when a Resources carries a Deadline (or an
    explicit ``query_chunk`` is given), queries traverse in host-level
    chunks with a cancellation/deadline checkpoint between dispatches —
    ``DeadlineExceeded`` carries the completed chunks' partial results.
    ``engine``: overrides ``SearchParams.engine`` — "edge" (streamed
    edge-store hop via the Pallas frontier-expansion kernel; requires /
    eagerly builds the ``prepare_traversal`` store, and is guarded onto
    the gather path on kernel failure), "fused" (the one-dispatch
    traversal megakernel, ops/cagra_fused.py — same store requirement,
    guarded onto the edge→gather chain via ``cagra.fused_search``),
    "gather" (composed-XLA random row gather), or "auto" (autotune
    cache, then store-attached heuristic; fused only off a measured
    race verdict).
    """
    p = params or SearchParams()
    q = jnp.asarray(queries, jnp.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "bad query shape %s",
            tuple(q.shape))
    itopk, width, max_iter = _plan_dims(p, k)
    if filter is not None:
        from ..ops import filter_policy
        from ..utils import in_jax_trace

        if not in_jax_trace() and not filter_policy.adaptive_off():
            # selectivity-adaptive policy (ops/filter_policy.py): widen
            # itopk along the brownout ladder so survivor hits are not
            # crowded out of the frontier, and at extreme selectivity
            # cross over to an exact brute pass on the compacted
            # survivors (a graph walk through mostly-filtered nodes
            # stops converging long before that point). Ladder levels
            # land on existing compile buckets — zero new compiles.
            import dataclasses as _dc

            fd = filter_policy.decide_graph(filter, index.size, index.dim,
                                            k)
            if fd.use_brute:
                return filter_policy.crossover(
                    fd, "cagra",
                    lambda: filter_policy.survivor_brute_dense(
                        index.dataset, index.metric, q, k, filter),
                    lambda: search(index, q, k, p, filter, res,
                                   query_chunk, engine))
            if fd.level > 1:
                p = _dc.replace(p, itopk_size=min(
                    max(p.itopk_size, k) * fd.level,
                    max(index.size, k)))
                itopk, width, max_iter = _plan_dims(p, k)
    if (index.seed_nodes is not None and filter is None
            and index.seed_nodes.shape[0] >= 64):
        # the shared covering set does the heavy seeding; random seeds
        # stay only as degenerate-case insurance. Under a filter the
        # whole shared set can be masked out (a selective tenant
        # slice), and a degenerately small set (duplicate-heavy corpus)
        # covers too little — keep the full random count in both cases.
        n_seeds = min(itopk, 16 * p.num_random_samplings)
    else:
        n_seeds = min(itopk, max(width * index.graph_degree // 2,
                                 16 * p.num_random_samplings))
    mask_bits = filter.to_mask() if filter is not None else None
    key = jax.random.key(p.seed)
    expects(p.candidate_dtype in ("bfloat16", "bf16", "int8", "i8",
                                  "float32", "f32"),
            "unknown candidate_dtype %r", p.candidate_dtype)
    scales = None
    if p.candidate_dtype in ("bfloat16", "bf16", "int8", "i8"):
        # low-precision traversal copy, cached per index object (one
        # quantize pass) — never stored from inside a jax trace (leaked
        # tracers); see prepare_search
        int8 = p.candidate_dtype in ("int8", "i8")
        attr = "_score_i8" if int8 else "_score_bf16"
        cached = getattr(index, attr, None)
        if cached is None:
            from ..utils import in_jax_trace

            if in_jax_trace():
                if int8:
                    from .brute_force import quantize_rows

                    cached = quantize_rows(index.dataset, jnp.int8)
                else:
                    cached = index.dataset.astype(jnp.bfloat16)
            else:
                prepare_search(index, p.candidate_dtype)
                cached = getattr(index, attr)
        score, scales = cached if int8 else (cached, None)
    else:
        score = index.dataset
    expects(p.algo in ("auto", "single_cta", "multi_cta", "multi_kernel"),
            "unknown cagra search algo %r", p.algo)

    eng = engine or p.engine
    expects(eng in ("auto",) + ENGINES,
            "unknown cagra traversal engine %r", eng)
    store = getattr(index, "_edge_store", None)
    if eng == "auto":
        from ..ops import autotune

        hit = autotune.lookup(_tune_key(index, q.shape[0], k, p, store))
        if hit == "gather" or (hit in ("edge", "fused")
                               and store is not None):
            eng = hit
        elif store is not None and jax.default_backend() == "tpu":
            # a store someone paid for implies the streamed hop; without
            # one, auto never builds it — tune_search / prepare_traversal
            # are the opt-ins (a read-only query must not double HBM).
            # The megakernel only dispatches off a measured race verdict
            # (tune_search) — an unraced shape stays on the rehearsed
            # per-hop kernel.
            eng = "edge"
        else:
            eng = "gather"
    if eng in ("edge", "fused") and store is None:
        from ..utils import in_jax_trace

        expects(not in_jax_trace(),
                "engine=%r requires prepare_traversal(index) before "
                "tracing (the edge store cannot be built under jit)", eng)
        prepare_traversal(index)
        store = index._edge_store
    smode = _store_mode(store)
    if eng == "fused" and smode == "pq":
        # the megakernel has no in-kernel PQ decode (the edge engine
        # carries that rung); a PQ store serves the per-hop kernel —
        # same results, one launch per hop
        eng = "edge"
    kprime = min(index.graph_degree, itopk)
    interp = jax.default_backend() != "tpu"

    def run(qc, key=key):
        def _go(e):
            ev, ea, gp = ((store[1], store[2], store[3])
                          if e in ("edge", "fused") else (None, None, None))
            cbs = (store[4] if e in ("edge", "fused")
                   and len(store) > 4 and store[4] is not None
                   else (None, None))
            return _search_jit(index.dataset, score, scales, index.graph,
                               qc, mask_bits, key, index.seed_nodes, ev,
                               ea, gp, itopk, width, int(max_iter), k,
                               n_seeds, index.metric.value,
                               int(p.min_iterations), engine=e,
                               kprime=kprime, interp=interp,
                               edge_cb=cbs[0], edge_cbs=cbs[1],
                               smode=smode if e in ("edge", "fused")
                               else "dense")

        def _edge_guarded():
            # a frontier-kernel failure demotes this site to the exact
            # XLA gather path (ops/guarded.py) — one log line and a
            # slower call, never the request. The PQ rung carries its
            # own breaker (cagra.pq_expand): its in-kernel LUT decode is
            # a different program from the dense expand, and demoting
            # one rung must not take the other's kernel down with it.
            # (Two literal guarded_call sites on purpose — the drift
            # guard's source sweep discovers sites by string literal.)
            if smode == "pq":
                return guarded_call("cagra.pq_expand",
                                    lambda: _go("edge"),
                                    lambda: _go("gather"))
            return guarded_call("cagra.graph_expand",
                                lambda: _go("edge"), lambda: _go("gather"))

        if eng == "fused":
            # megakernel failure → the per-hop edge engine (itself
            # guarded onto the gather path): the fallback chain serves
            # bit-identical results at worst two demotion log lines
            from ..ops.cagra_fused import FUSED_SITE

            return guarded_call(FUSED_SITE,
                                lambda: _go("fused"), _edge_guarded)
        if eng == "edge":
            return _edge_guarded()
        return _go("gather")

    if query_chunk <= 0 and deadline.carried(res) is not None:
        query_chunk = max(1, min(q.shape[0], 1024))
    # a carried deadline always takes the chunked path: even a single
    # chunk needs its pre-dispatch checkpoint (an already-expired budget
    # must raise, not dispatch)
    if query_chunk > 0 and (query_chunk < q.shape[0]
                            or deadline.carried(res) is not None):
        # distinct key per chunk: reusing one key would hand every chunk
        # the same random seed rows (correlated sampling). Chunked runs
        # therefore draw different random seeds than the unchunked call
        # — neighbor quality is seed-robust (covering seed set + exact
        # f32 re-rank), but byte-level parity across chunk sizes is not
        # promised.
        return run_query_chunks(
            lambda qc, s0: run(qc, key=jax.random.fold_in(key, s0)),
            q, query_chunk, res)
    return run(q)


def save(index: Index, path) -> None:
    """Serialize dataset + graph (cagra_serialize.cuh analog). Files
    without a seed set are written as v1 so older readers stay able to
    load them."""
    arrs = {"dataset": index.dataset, "graph": index.graph}
    version = 1
    if index.seed_nodes is not None:
        arrs["seed_nodes"] = index.seed_nodes
        version = _SERIAL_VERSION
    save_arrays(path, "cagra", version,
                {"metric": index.metric.value}, arrs)


def load(path) -> Index:
    _, version, meta, arrs = load_arrays(path, "cagra")
    # v1 files have no seed_nodes; everything else is unchanged
    expects(version in (1, _SERIAL_VERSION),
            "unsupported version %d", version)
    seeds = arrs.get("seed_nodes")
    if seeds is not None:
        # canonicalize at the boundary: the search-time collision probe
        # (jnp.searchsorted) requires sorted unique ids — an externally
        # edited file with unsorted seeds would silently degrade dedup
        seeds = jnp.asarray(np.unique(np.asarray(seeds)), jnp.int32)
    return Index(jnp.asarray(arrs["dataset"]), jnp.asarray(arrs["graph"]),
                 DistanceType(meta["metric"]), seeds)


def health(index: Index, sample: int = 256) -> dict:
    """Index health report (docs/observability.md "Quality"): graph
    connectivity + quantization quality.

    The fixed out-degree graph's quality signal is its **in-degree
    distribution**: a node no edge points at is unreachable by traversal
    (only random/covering seeding can surface it), and a heavy-tailed
    in-degree concentrates traffic on hub rows. Because the index keeps
    the f32 dataset next to its quantized traversal caches
    (``prepare_search``/``prepare_traversal``), the report carries a
    *measured* sampled reconstruction error per cache, not just a bound.
    """
    from .brute_force import health_sample_rows, quantization_error

    # the connectivity half is graph-derived and the graph is immutable
    # post-build, but computing it means pulling the WHOLE graph to host
    # (256 MB at 1M x deg64) + a full bincount — far too heavy to repeat
    # inside every 10s SnapshotWriter tick once the index is watched.
    # Cache it on the index keyed by the array identities (both alive as
    # long as the index is).
    key = (id(index.graph), id(index.seed_nodes))
    cached = getattr(index, "_health_conn_cache", None)
    if cached is not None and cached[0] == key:
        conn = cached[1]
    elif index.size == 0:
        # an empty graph must report, not raise (np.min on an empty
        # in-degree array would)
        conn = {"graph_degree": int(index.graph.shape[1]),
                "in_degree": {"min": 0, "mean": 0.0, "p99": 0, "max": 0},
                "unreachable_nodes": 0, "unreachable_frac": 0.0,
                "unseeded_unreachable": 0, "seed_nodes": 0}
        index._health_conn_cache = (key, conn)
    else:
        g = np.asarray(index.graph)
        n, deg = g.shape
        flat = g.reshape(-1)
        indeg = np.bincount(flat[(flat >= 0) & (flat < n)], minlength=n)
        unreachable = indeg == 0
        seeds = None if index.seed_nodes is None \
            else np.asarray(index.seed_nodes)
        # unreachable AND outside the covering seed set: invisible to
        # traversal except through random seeding — the number that
        # predicts a recall ceiling
        unseeded = unreachable.copy()
        if seeds is not None and seeds.size:
            valid = seeds[(seeds >= 0) & (seeds < n)]
            unseeded[valid] = False
        conn = {
            "graph_degree": int(deg),
            "in_degree": {
                "min": int(indeg.min()),
                "mean": round(float(indeg.mean()), 2),
                "p99": int(np.percentile(indeg, 99)),
                "max": int(indeg.max())},
            "unreachable_nodes": int(unreachable.sum()),
            "unreachable_frac": round(float(unreachable.mean()), 5),
            "unseeded_unreachable": int(unseeded.sum()),
            "seed_nodes": 0 if seeds is None else int(seeds.shape[0]),
        }
        index._health_conn_cache = (key, conn)
    report = {"family": "cagra", "n": int(index.size),
              "dim": int(index.dim), "metric": index.metric.name, **conn}
    rows = health_sample_rows(index.size, sample)
    quant = {}
    orig = np.asarray(index.dataset[rows]) if rows.size else None
    i8 = getattr(index, "_score_i8", None)
    if i8 is not None and rows.size:
        q8, sc = i8
        deq = np.asarray(q8[rows], np.float32) * np.asarray(sc[rows])[:, None]
        quant["int8"] = quantization_error(orig, deq)
    bf = getattr(index, "_score_bf16", None)
    if bf is not None and rows.size:
        quant["bfloat16"] = quantization_error(
            orig, np.asarray(bf[rows], np.float32))
    es = getattr(index, "_edge_store", None)
    if es is not None:
        ev = es[1]
        quant["edge_store"] = {"dtype": es[0][0],
                               "shape": tuple(int(s) for s in ev.shape),
                               "bytes": int(ev.size * ev.dtype.itemsize)}
    if quant:
        report["quant"] = quant
    return report


def make_searcher(index: Index, params: SearchParams | None = None, *,
                  degrade=None, donate=False, **opts):
    """Stable batchable signature for the serving runtime
    (:mod:`raft_tpu.serve`): returns ``fn(queries, k, res=None) ->
    (distances, indices)`` with the traversal policy frozen at closure
    build time, so repeated bucketed-shape calls hit the same cached
    executables. ``opts`` forwards to :func:`search` (``filter``,
    ``query_chunk``, ``engine``, ...). Pinning ``engine="edge"`` or
    ``"fused"`` (via opts or ``params.engine``) builds the edge-resident
    candidate store at closure-build time, not on the first request —
    serve warmup then only pays the per-shape compiles.

    ``donate``: OPT-IN (default off) — donate the per-call query
    block's device buffer to the jitted search
    (``jax.jit(..., donate_argnums=)``), letting XLA reuse it for
    outputs; with the batcher's double-buffered dispatch two batches
    are in flight, and donation keeps that from doubling the transient
    buffer footprint. ``"auto"`` donates on TPU only (CPU ignores
    donation and warns per call). Caveats (docs/perf.md "One-dispatch
    search"): the donated path wraps ``search`` in an OUTER jit, so
    guarded-site breakers are consulted at trace time, not per call —
    a kernel-engine failure surfaces as the compile error instead of
    the demoted fallback, which is why it is opt-in; donation is
    skipped for deadline-carrying requests (the chunked host loop owns
    those), under ``degrade`` (per-call param changes would defeat the
    jit cache), and for caller-owned device arrays (donating those
    would delete the caller's buffer — only host-side blocks, the
    batcher's case, are donated).

    ``degrade``: a :class:`~raft_tpu.serve.degrade.BrownoutController`
    — under brownout its current level overrides
    ``itopk_size``/``search_width`` per call (docs/robustness.md)."""
    eng = opts.get("engine") or (params.engine if params is not None
                                 else None)
    if eng in ("edge", "fused"):
        prepare_traversal(index)
    base = params or SearchParams()
    if donate == "auto":
        donate = jax.default_backend() == "tpu"
    jits: dict = {}

    def _fn(queries, k, res=None):
        p = base if degrade is None else degrade.params(base)
        if (donate and res is None and degrade is None
                and not isinstance(queries, jax.Array)):
            fn = jits.get(k)
            if fn is None:
                fn = jax.jit(
                    lambda qq, ix, kk=k: search(ix, qq, kk, base, **opts),
                    donate_argnums=(0,))
                jits[k] = fn
            return fn(jnp.asarray(queries, jnp.float32), index)
        return search(index, queries, k, p, res=res, **opts)

    return _fn
