"""Device-side inverted-list layout shared by IVF-Flat and IVF-PQ.

Lists are contiguous row ranges of dense device arrays (the TPU-friendly
replacement for the reference's grouped-interleaved lists,
detail/ivf_flat_build.cuh:87-158), optionally with per-list *capacity
slack* so that `extend` is an O(batch) device scatter instead of a full
repack (role of the reference's in-place list packing,
detail/ivf_pq_build.cuh:1550). Rows in [offset + size, offset + capacity)
are slack: scan kernels and the XLA gather path mask by true size, so
slack contents are never read.

Everything large stays on device: the only host traffic is O(n_lists)
size counts. Offsets/sizes live as host numpy so downstream shapes stay
static under jit.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["count_sizes", "plan_offsets", "scatter_build", "scatter_extend",
           "gather_dense", "streaming_build", "list_skew"]

_ALIGN = 8   # sublane multiple: keeps list starts DMA-friendly


def count_sizes(labels: jax.Array, n_lists: int) -> np.ndarray:
    """Per-list row counts; the single O(n_lists) device→host transfer."""
    counts = jax.ops.segment_sum(
        jnp.ones((labels.shape[0],), jnp.int32), labels,
        num_segments=n_lists)
    return np.asarray(counts, np.int64)


def plan_offsets(sizes: np.ndarray, growth: float = 1.0) -> np.ndarray:
    """(n_lists+1,) offsets with capacity = align(ceil(size * growth)).

    growth=1.0 → capacities equal aligned sizes (near-dense); growth>1
    leaves slack so subsequent extends amortize to O(batch).
    """
    caps = np.maximum(sizes, np.ceil(sizes * growth)).astype(np.int64)
    caps = (caps + _ALIGN - 1) // _ALIGN * _ALIGN
    offsets = np.zeros(len(sizes) + 1, np.int64)
    np.cumsum(caps, out=offsets[1:])
    return offsets


def _dest_rows(labels: jax.Array, sizes: np.ndarray, offsets: np.ndarray,
               base_sizes: np.ndarray | None = None) -> jax.Array:
    """Destination row per input row: offset[l] + base[l] + rank-within-l."""
    order = jnp.argsort(labels, stable=True)
    lsort = jnp.take(labels, order)
    starts = np.zeros(len(sizes), np.int64)
    if len(sizes) > 1:
        np.cumsum(sizes[:-1], out=starts[1:])
    rank = jnp.arange(labels.shape[0], dtype=jnp.int64) - jnp.take(
        jnp.asarray(starts), lsort)
    base = offsets[:-1] if base_sizes is None else offsets[:-1] + base_sizes
    dest_sorted = jnp.take(jnp.asarray(base), lsort) + rank
    return order, dest_sorted


def scatter_build(labels: jax.Array, arrays: Sequence[jax.Array],
                  fills: Sequence, n_lists: int, growth: float = 1.0
                  ) -> Tuple[list, np.ndarray, np.ndarray]:
    """Cluster-sort ``arrays`` into a fresh capacity layout (all on device).

    Returns ([scattered arrays (cap_total, ...)], offsets (n_lists+1,),
    sizes (n_lists,)).
    """
    sizes = count_sizes(labels, n_lists)
    offsets = plan_offsets(sizes, growth)
    order, dest = _dest_rows(labels, sizes, offsets)
    cap_total = int(offsets[-1])
    out = []
    for arr, fill in zip(arrays, fills):
        shape = (cap_total,) + tuple(arr.shape[1:])
        buf = jnp.full(shape, fill, arr.dtype)
        out.append(buf.at[dest].set(jnp.take(arr, order, axis=0)))
    return out, offsets, sizes


def scatter_extend(labels: jax.Array, new_arrays: Sequence[jax.Array],
                   old_arrays: Sequence[jax.Array], fills: Sequence,
                   offsets: np.ndarray, old_sizes: np.ndarray,
                   growth: float = 1.0
                   ) -> Tuple[list, np.ndarray, np.ndarray]:
    """Append a batch into an existing layout.

    Fits entirely in slack → one O(batch) device scatter per array (the
    amortized fast path). Any list overflowing its capacity → gather the
    valid rows dense and rebuild the layout with ``growth`` slack
    (amortized out when growth > 1).
    """
    n_lists = len(old_sizes)
    add = count_sizes(labels, n_lists)
    caps = np.diff(offsets)
    if (old_sizes + add <= caps).all():
        order, dest = _dest_rows(labels, add, offsets, base_sizes=old_sizes)
        out = [old.at[dest].set(jnp.take(new, order, axis=0))
               for old, new in zip(old_arrays, new_arrays)]
        return out, offsets, old_sizes + add

    # overflow: densify old rows + labels on device, then rebuild
    old_dense, old_labels = gather_dense(old_arrays, offsets, old_sizes)
    merged = [jnp.concatenate([o, n]) for o, n in zip(old_dense, new_arrays)]
    all_labels = jnp.concatenate([old_labels, labels])
    return scatter_build(all_labels, merged, fills, n_lists, growth)


def gather_dense(arrays: Sequence[jax.Array], offsets: np.ndarray,
                 sizes: np.ndarray) -> Tuple[list, jax.Array]:
    """Valid rows of a capacity layout, dense and list-ordered (on device).

    Returns ([dense arrays (n, ...)], labels (n,)) — the inverse of
    scatter_build, used by repacks and serialization.
    """
    n = int(sizes.sum())
    starts = np.zeros(len(sizes), np.int64)
    if len(sizes) > 1:
        np.cumsum(sizes[:-1], out=starts[1:])
    pos = jnp.arange(n, dtype=jnp.int64)
    list_of = jnp.searchsorted(jnp.asarray(np.cumsum(sizes)), pos,
                               side="right")
    rows = (jnp.take(jnp.asarray(offsets[:-1]), list_of)
            + (pos - jnp.take(jnp.asarray(starts), list_of)))
    return [jnp.take(a, rows, axis=0) for a in arrays], list_of.astype(jnp.int32)


def list_skew(sizes: np.ndarray) -> dict:
    """List-size skew summary shared by the IVF health reports
    (docs/observability.md "Quality"): a few hot lists carrying most of
    the rows means probe budgets blow up (``max_rows`` follows the
    largest probed lists) and recall concentrates risk — the classic
    unbalanced-kmeans failure the balanced trainer exists to avoid."""
    s = np.asarray(sizes, np.float64)
    if s.size == 0 or s.sum() == 0:
        return {"n_lists": int(s.size), "rows": 0, "empty_lists": int(s.size)}
    mean = float(s.mean())
    return {
        "n_lists": int(s.size),
        "rows": int(s.sum()),
        "min": int(s.min()),
        "mean": round(mean, 1),
        "p99": int(np.percentile(s, 99)),
        "max": int(s.max()),
        # coefficient of variation + largest/mean: the two skew numbers
        # an operator compares across builds
        "cv": round(float(s.std() / max(mean, 1e-30)), 4),
        "max_over_mean": round(float(s.max() / max(mean, 1e-30)), 2),
        "empty_lists": int((s == 0).sum()),
    }


def streaming_build(batches, params, build_fn, extend_fn, replace_fn,
                    trainset=None):
    """Shared streaming-build driver for IVF indexes: train quantizers on
    ``trainset`` (or the first batch), then extend batch by batch — host
    memory stays O(batch). ``replace_fn`` is dataclasses.replace for the
    module's IndexParams; capacity slack is floored at 1.2 so the merges
    amortize to O(batch) in-place scatters."""
    import jax.numpy as jnp

    from ..core.errors import expects

    p = replace_fn(params, add_data_on_build=False,
                   list_growth=max(1.2, params.list_growth))
    it = iter(batches)
    first = next(it, None)
    expects(first is not None, "streaming build got an empty batch iterable")
    first = jnp.asarray(first, jnp.float32)
    index = build_fn(first if trainset is None else trainset, p)
    index = extend_fn(index, first)
    for b in it:
        index = extend_fn(index, b)
    return index
