"""Random-ball-cover kNN + epsilon neighborhoods: analog of
``raft::neighbors::ball_cover`` / ``epsilon_neighborhood``.

Reference: spatial/knn/detail/ball_cover.cuh:62-168 — sqrt(n) landmarks,
points grouped under their closest landmark, queries probe landmarks in
distance order with triangle-inequality pruning
(|d(q,L) - d(L,x)| <= d(q,x)); eps queries in
neighbors/epsilon_neighborhood.cuh (dense adj + vertex degrees) with an
RBC-pruned variant (eps_nn ball_cover.cuh:120).

TPU design note: the reference's per-thread landmark pruning is a
SIMT-divergence optimization — it saves lanes on a GPU, but on the MXU a
distance tile costs the same whether half its rows would have been
pruned, so exact kNN rides the fused brute-force kernel unchanged. What
the RBC *structure* buys on TPU is the probe-limited approximate mode
(landmark-grouped gathers, same machinery as IVF-Flat with the landmark
set as the coarse quantizer) and landmark-level (not row-level) pruning
for eps queries. Radii are kept per landmark so the eps path can skip
whole groups, which is the part that does vectorize.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cluster import kmeans_balanced
from ..core import interop, tracing
from ..core.errors import expects
from ..distance.distance_types import DistanceType, canonical_metric
from ..distance.pairwise import pairwise_distance
from . import brute_force, ivf_flat

__all__ = ["BallCoverIndex", "build", "knn", "eps_nn",
           "epsilon_neighborhood"]


@dataclasses.dataclass
class BallCoverIndex:
    """Landmark-grouped dataset (ball_cover.cuh BallCoverIndex).

    Internally an IVF-Flat layout whose "lists" are landmark balls, plus
    per-landmark radii (max member distance) for group-level pruning.
    """

    ivf: ivf_flat.Index
    radii: jax.Array          # (n_landmarks,) max member distance (L2)
    metric: DistanceType

    @property
    def size(self) -> int:
        return self.ivf.size

    @property
    def dim(self) -> int:
        return self.ivf.dim

    @property
    def n_landmarks(self) -> int:
        return self.ivf.n_lists


@tracing.annotate("raft_tpu::ball_cover::build")
def build(dataset, n_landmarks: int = 0, metric="sqeuclidean",
          seed: int = 0) -> BallCoverIndex:
    """Group the dataset under ~sqrt(n) landmarks (ball_cover.cuh:62).

    Landmarks come from balanced k-means (the reference samples random
    points; trained landmarks give tighter balls → better pruning).
    """
    dataset = np.asarray(dataset, np.float32)
    n = len(dataset)
    mt = canonical_metric(metric)
    expects(mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded),
            "ball_cover supports L2 metrics, got %s", mt.name)
    if n_landmarks <= 0:
        n_landmarks = max(1, int(np.sqrt(n)))
    expects(n_landmarks <= n, "n_landmarks %d > n %d", n_landmarks, n)

    idx = ivf_flat.build(dataset, ivf_flat.IndexParams(
        n_lists=n_landmarks, metric=DistanceType.L2Expanded, seed=seed))
    # per-landmark radius: max member distance (exact, for rigorous
    # bounds). Physical rows span list *capacities*; slack rows
    # (source_id -1) are masked out of the max.
    labels = np.repeat(np.arange(idx.n_lists), np.diff(idx.list_offsets))
    member_d = np.sqrt(np.maximum(np.asarray(
        jnp.sum((idx.data - idx.centers[jnp.asarray(labels)]) ** 2, axis=1)),
        0.0))
    valid = np.asarray(idx.source_ids) >= 0
    radii = np.zeros(idx.n_lists, np.float32)
    np.maximum.at(radii, labels[valid], member_d[valid])
    return BallCoverIndex(idx, jnp.asarray(radii), mt)


@interop.auto_convert_output
@tracing.annotate("raft_tpu::ball_cover::knn")
def knn(index: BallCoverIndex, queries, k: int, n_probes: int = 0
        ) -> Tuple[jax.Array, jax.Array]:
    """k nearest neighbors.

    ``n_probes`` = 0 → exact (the reference's all-knn contract), served by
    the fused brute-force kernel — see the module docstring for why
    row-level triangle pruning is a no-op on the MXU. ``n_probes`` > 0 →
    probe that many closest landmarks (the RBC approximate mode; recall
    rises with probes exactly as IVF-Flat).
    """
    q = jnp.asarray(queries, jnp.float32)
    if n_probes <= 0:
        from ..core.bitset import Bitset

        bf = brute_force.Index(index.ivf.data, index.ivf.data_norms,
                               index.metric)
        # capacity-slack rows (source_id -1) must not act as candidates
        filt = Bitset.from_mask(index.ivf.source_ids >= 0)
        d, loc = brute_force.search(bf, q, k, filter=filt)
        ids = jnp.where(loc >= 0,
                        jnp.take(index.ivf.source_ids, jnp.maximum(loc, 0)),
                        -1)
        return d, ids
    sp = ivf_flat.SearchParams(n_probes=n_probes)
    ivf = dataclasses.replace(index.ivf, metric=index.metric) \
        if index.ivf.metric is not index.metric else index.ivf
    return ivf_flat.search(ivf, q, k, sp)


@interop.auto_convert_output
@tracing.annotate("raft_tpu::ball_cover::eps_nn")
def eps_nn(index: BallCoverIndex, queries, eps: float
           ) -> Tuple[jax.Array, jax.Array]:
    """Epsilon neighborhood with landmark pruning (ball_cover.cuh:120
    eps_nn) → (adj (m, n) bool over ORIGINAL row ids, degrees (m,)).

    Landmark groups whose ball lies entirely outside the eps-ball of a
    query (d(q, L) > eps + radius(L)) are skipped group-wise; surviving
    groups get exact distances.
    """
    q = jnp.asarray(queries, jnp.float32)
    m = q.shape[0]
    n = index.size
    n_phys = index.ivf.data.shape[0]     # includes capacity slack
    # group-level prune (vectorized over (m, landmarks))
    dql = jnp.sqrt(jnp.maximum(pairwise_distance(
        q, index.ivf.centers, "sqeuclidean"), 0.0))
    alive = dql <= (eps + index.radii)[None, :]          # (m, L)
    # exact distances for members of surviving groups (physical rows span
    # list capacities; slack rows masked by source_id)
    labels = jnp.asarray(np.repeat(np.arange(index.ivf.n_lists),
                                   np.diff(index.ivf.list_offsets)))
    row_alive = jnp.take_along_axis(
        alive, jnp.broadcast_to(labels[None, :], (m, n_phys)), axis=1)
    d2 = pairwise_distance(q, index.ivf.data, "sqeuclidean")
    inside = row_alive & (d2 <= eps * eps) & \
        (index.ivf.source_ids >= 0)[None, :]
    # scatter back to original row order (OR-scatter: slack rows aim at
    # column 0 with inside=False and must never clobber a real True)
    adj = jnp.zeros((m, n), bool)
    adj = adj.at[:, jnp.maximum(index.ivf.source_ids, 0)].max(inside)
    return adj, jnp.sum(inside, axis=1).astype(jnp.int32)


@interop.auto_convert_output
def epsilon_neighborhood(x, y, eps: float) -> Tuple[jax.Array, jax.Array]:
    """Dense eps-neighborhood (neighbors/epsilon_neighborhood.cuh:
    epsUnexpL2SqNeighborhood): adj[i, j] = ||x_i - y_j||² <= eps², plus
    vertex degrees."""
    d2 = pairwise_distance(jnp.asarray(x, jnp.float32),
                           jnp.asarray(y, jnp.float32), "sqeuclidean")
    adj = d2 <= eps * eps
    return adj, jnp.sum(adj, axis=1).astype(jnp.int32)
