"""IVF-PQ index: analog of ``raft::neighbors::ivf_pq``.

Reference: raft/neighbors/ivf_pq_types.hpp:43,110-146,264 (params: pq_bits,
pq_dim, codebook_gen PER_SUBSPACE|PER_CLUSTER, force_random_rotation; index
holds rotation matrix, coarse centers, codebooks, packed code lists),
detail/ivf_pq_build.cuh:1729 (build: kmeans_balanced coarse quantizer →
rotation matrix → train_per_subset/train_per_cluster codebooks → extend
packs codes) and detail/ivf_pq_search.cuh:731 (search: coarse GEMM +
select_k → rotate queries → per-(query,probe) LUT + packed-code scan).

TPU design differences from the CUDA reference:

* **Everything lives in rotated space.** The rotation is orthogonal, so L2
  and inner-product are preserved; we rotate the dataset once at build and
  the queries once at search, and then coarse selection, residuals, and
  codebooks never leave rotated coordinates (the reference rotates queries
  but keeps separate "extended" centers — ivf_pq_search.cuh:69-170 — to
  fold norms into one GEMM; XLA fuses that for free).
* **Lists are contiguous row ranges** of one dense cluster-sorted code
  matrix (codes: (n, pq_dim) uint8) — same layout as our IVF-Flat — instead
  of the reference's bit-packed interleaved groups (ivf_pq_codepacking.cuh):
  a byte per sub-quantizer keeps gathers vectorizable; pq_bits < 8 still
  shrinks the *codebook*, and a packed serialization keeps files small.
* **The LUT-in-shared-memory kernel** (ivf_pq_compute_similarity-inl.cuh:271)
  becomes one einsum building all (query, probe) LUTs at once + a flat
  take_along_axis contraction — both XLA-friendly; VMEM plays the role of
  the LUT smem automatically.
* Codebook training vmaps a fixed-iteration Lloyd over subspaces (or over
  clusters for PER_CLUSTER), replacing the reference's per-subspace stream
  parallelism (ivf_pq_build.cuh:392,469).
"""
from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import interop, tracing
from ..core.bitset import Bitset
from ..core.errors import expects
from ..core.resources import workspace_chunk_bytes
from ..core.serialize import load_arrays, save_arrays
from ..ops.guarded import guarded_call
from ..cluster import kmeans_balanced
from ..distance.distance_types import DistanceType, canonical_metric
from ..matrix.select_k import select_k
from ..utils import cdiv, hdot, in_jax_trace, run_query_chunks
from .ivf_flat import _candidate_rows, _probe_budget

__all__ = ["CodebookGen", "IndexParams", "SearchParams", "Index", "build",
           "build_from_batches", "extend", "search", "prepare_scan",
           "prepare_host_stream", "save", "load", "pack_codes",
           "unpack_codes", "reconstruct", "make_searcher", "health"]

_SERIAL_VERSION = 1

# auto-dispatch downgrade reasons already logged (once per process)
_GATHER_FALLBACK_LOGGED: set = set()


class CodebookGen(enum.Enum):
    """ivf_pq_types.hpp:43 codebook_gen."""

    PER_SUBSPACE = 0
    PER_CLUSTER = 1


@dataclasses.dataclass
class IndexParams:
    """Mirror of ivf_pq::index_params (ivf_pq_types.hpp:110)."""

    n_lists: int = 1024
    metric: DistanceType | str = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8                   # 4..8
    pq_dim: int = 0                    # 0 → dim/4 rounded to a multiple of 8
    codebook_kind: CodebookGen = CodebookGen.PER_SUBSPACE
    force_random_rotation: bool = False
    add_data_on_build: bool = True
    seed: int = 0
    # per-list capacity slack factor: >1 makes extend an O(batch) in-place
    # device scatter until a list overflows (see neighbors/_list_layout.py)
    list_growth: float = 1.0


@dataclasses.dataclass
class SearchParams:
    """Mirror of ivf_pq::search_params (ivf_pq_types.hpp:146).

    The reference's lut_dtype/internal_distance_dtype knobs select smem LUT
    precision; here `lut_dtype` selects the scan compute dtype:
    ``jnp.float32`` exact, ``jnp.bfloat16`` (default, the fp16-LUT role),
    or ``jnp.int8`` / ``"int8"`` (the fp8-LUT role: per-subspace
    symmetrically-quantized codebook, int8 MXU decode at double rate —
    pair with refine for full recall).

    There is deliberately no ``internal_distance_dtype`` knob: the MXU
    accumulates every LUT mode in f32/int32 natively, so the reference's
    fp16-internal-distance speed/accuracy trade (ivf_pq_types.hpp:110-146)
    costs nothing to skip on TPU — internal distances are always full
    precision here."""

    n_probes: int = 20
    lut_dtype: jnp.dtype | str = jnp.bfloat16


def _lut_mode(lut_dtype) -> str:
    """SearchParams.lut_dtype → kernel mode string. Unknown names raise —
    a typo must not silently downgrade precision."""
    if isinstance(lut_dtype, str):
        s = lut_dtype.lower()
        if s in ("int8", "i8", "fp8"):
            return "int8"
        if s in ("f32", "float32", "fp32"):
            return "f32"
        expects(s in ("bf16", "bfloat16", "fp16", "f16"),
                "unknown lut_dtype %r (use float32 / bfloat16 / int8)",
                lut_dtype)
        return "bf16"
    dt = jnp.dtype(lut_dtype)
    if dt == jnp.int8:
        return "int8"
    if dt == jnp.float32:
        return "f32"
    expects(dt in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)),
            "unknown lut_dtype %r (use float32 / bfloat16 / int8)",
            lut_dtype)
    return "bf16"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Rotated-space IVF-PQ index.

    ``codes``: (n, pq_dim) uint8 cluster-sorted; ``centers_rot``:
    (n_lists, rot_dim); ``codebooks``: (pq_dim, 2^bits, pq_len) for
    PER_SUBSPACE or (n_lists, 2^bits, pq_len) for PER_CLUSTER;
    ``rotation``: (rot_dim, dim) with orthonormal columns.
    """

    codes: jax.Array
    source_ids: jax.Array
    centers_rot: jax.Array
    codebooks: jax.Array
    rotation: jax.Array
    list_offsets: np.ndarray        # host-side, static (capacity offsets)
    metric: DistanceType
    pq_bits: int
    codebook_kind: CodebookGen
    list_sizes_arr: Optional[np.ndarray] = None  # None → dense (old files)
    list_growth: float = 1.0

    @property
    def size(self) -> int:
        """Number of indexed vectors (excludes capacity slack)."""
        return int(self.list_sizes.sum())

    @property
    def dim(self) -> int:
        return self.rotation.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.codes.shape[1]

    @property
    def pq_len(self) -> int:
        return self.codebooks.shape[2]

    @property
    def pq_book_size(self) -> int:
        return 1 << self.pq_bits

    @property
    def n_lists(self) -> int:
        return self.centers_rot.shape[0]

    @property
    def list_sizes(self) -> np.ndarray:
        if self.list_sizes_arr is not None:
            return self.list_sizes_arr
        return np.diff(self.list_offsets)

    def tree_flatten(self):
        # the pallas scan-prep cache travels WITH the index: a jitted
        # function taking the index as an argument (the
        # constants-as-parameters pattern — closure-baked index arrays
        # at 500k rows exceed remote-compile request limits) keeps the
        # prepared arrays instead of re-deriving them inside the trace
        cache = getattr(self, "_scan_cache", None)
        cache_leaves = (None if cache is None else
                        (cache["codes_p"], cache["norms_p"], cache["cbm"]))
        cache_aux = (None if cache is None else
                     (cache["n"], cache["lmax"]))
        leaves = (self.codes, self.source_ids, self.centers_rot,
                  self.codebooks, self.rotation, cache_leaves)
        aux = (tuple(self.list_offsets.tolist()), self.metric, self.pq_bits,
               self.codebook_kind,
               None if self.list_sizes_arr is None
               else tuple(self.list_sizes_arr.tolist()),
               self.list_growth, cache_aux)
        return leaves, aux

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        offsets, metric, pq_bits, kind, sizes, growth, cache_aux = aux
        *core, cache_leaves = leaves
        out = cls(*core, np.asarray(offsets, np.int64), metric, pq_bits,
                  kind,
                  None if sizes is None else np.asarray(sizes, np.int64),
                  growth)
        if cache_aux is not None and cache_leaves is not None:
            out._scan_cache = {
                "n": cache_aux[0], "lmax": cache_aux[1],
                "codes_p": cache_leaves[0], "norms_p": cache_leaves[1],
                "cbm": cache_leaves[2]}
        return out


def _default_pq_dim(dim: int) -> int:
    """ivf_pq_types.hpp: pq_dim=0 → dim/4 rounded for alignment."""
    pq = max(1, dim // 4)
    if pq > 8:
        pq = (pq // 8) * 8
    return pq


def make_rotation_matrix(key, rot_dim: int, dim: int,
                         force_random: bool) -> jax.Array:
    """(rot_dim, dim) with orthonormal columns (ivf_pq_build.cuh:119).

    Identity when rot_dim == dim and no rotation is forced; otherwise the Q
    factor of a gaussian (the reference uses RSVD of a gaussian for the same
    effect; like the reference, rot_dim != dim always randomizes).
    """
    if not force_random and rot_dim == dim:
        return jnp.eye(dim, dtype=jnp.float32)
    # rot_dim != dim always gets a random rotation (ivf_pq_types.hpp:87-90):
    # a zero-padded identity would leave the tail subspace mostly zeros,
    # wasting its codebook
    g = jax.random.normal(key, (rot_dim, rot_dim), jnp.float32)
    q, _ = jnp.linalg.qr(g)
    return q[:, :dim]


@partial(jax.jit, static_argnums=(1, 2))
def _kmeans_fixed(x, k, iters, key):
    """Fixed-iteration Lloyd for codebook training — vmappable.

    ``x``: (T, d) with possible repeated/padded rows; init = random distinct
    subsample; empty clusters keep their previous center.
    """
    n, d = x.shape
    perm = jax.random.permutation(key, n)[:k]
    centers0 = x[perm]

    def step(centers, _):
        d2 = (jnp.sum(x * x, axis=1, keepdims=True)
              - 2.0 * hdot(x, centers.T)
              + jnp.sum(centers * centers, axis=1)[None, :])
        labels = jnp.argmin(d2, axis=1)
        sums = jax.ops.segment_sum(x, labels, num_segments=k)
        cnts = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), labels,
                                   num_segments=k)
        new = jnp.where(cnts[:, None] > 0, sums / jnp.maximum(cnts, 1)[:, None],
                        centers)
        return new, None

    centers, _ = jax.lax.scan(step, centers0, None, length=iters)
    return centers


def _train_per_subspace(resid_slices, book_size, iters, key):
    """(pq_dim, T, pq_len) residual slices → (pq_dim, book, pq_len)
    codebooks (ivf_pq_build.cuh:392 train_per_subset)."""
    keys = jax.random.split(key, resid_slices.shape[0])
    return jax.vmap(_kmeans_fixed, in_axes=(0, None, None, 0))(
        resid_slices, book_size, iters, keys)


def _train_per_cluster(resid_rot, labels, n_lists, pq_len, book_size, iters,
                       key, samples_per_list=2048):
    """Per-cluster codebooks over pooled subspace slices
    (ivf_pq_build.cuh:469 train_per_cluster).

    Each cluster trains on min(count*pq_dim, samples) of its residual
    sub-vectors; clusters are padded to a common sample count by sampling
    rows with replacement, so one vmap covers all lists.
    """
    n = resid_rot.shape[0]
    pq_dim = resid_rot.shape[1] // pq_len
    slices = resid_rot.reshape(n, pq_dim, pq_len)
    key_rows, key_fit = jax.random.split(key)

    # per-list row sampling (host: one cluster-sort pass, then slice)
    labels_np = np.asarray(labels)
    order = np.argsort(labels_np, kind="stable")
    counts = np.bincount(labels_np, minlength=n_lists)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    rows = np.zeros((n_lists, samples_per_list), np.int32)
    rng = np.random.default_rng(int(jax.random.randint(key_rows, (), 0, 1 << 30)))
    for l in range(n_lists):
        members = order[starts[l] : starts[l] + counts[l]]
        if len(members) == 0:
            members = np.array([0], np.int64)
        rows[l] = rng.choice(members, size=samples_per_list, replace=True)
    rows_j = jnp.asarray(rows)

    # (n_lists, samples, pq_dim, pq_len) → pool subspaces into the sample axis
    pool = slices[rows_j].reshape(n_lists, samples_per_list * pq_dim, pq_len)
    keys = jax.random.split(key_fit, n_lists)
    return jax.vmap(_kmeans_fixed, in_axes=(0, None, None, 0))(
        pool, book_size, iters, keys)


@partial(jax.jit, static_argnums=(3,))
def _encode(resid_rot, codebooks, labels, kind_per_cluster: bool):
    """Residuals → (n, pq_dim) uint8 codes: per-subspace argmin."""
    n = resid_rot.shape[0]
    if kind_per_cluster:
        pq_len = codebooks.shape[2]
        pq_dim = resid_rot.shape[1] // pq_len
        slices = resid_rot.reshape(n, pq_dim, pq_len)
        books = codebooks[labels]                    # (n, book, pq_len)
        d2 = (jnp.sum(slices * slices, axis=2)[:, :, None]
              - 2.0 * jnp.einsum("nsl,nbl->nsb", slices, books, precision="highest")
              + jnp.sum(books * books, axis=2)[:, None, :])
        return jnp.argmin(d2, axis=2).astype(jnp.uint8)
    pq_dim, _, pq_len = codebooks.shape
    slices = resid_rot.reshape(n, pq_dim, pq_len)
    d2 = (jnp.sum(slices * slices, axis=2)[:, :, None]
          - 2.0 * jnp.einsum("nsl,sbl->nsb", slices, codebooks, precision="highest")
          + jnp.sum(codebooks * codebooks, axis=2)[None, :, :])
    return jnp.argmin(d2, axis=2).astype(jnp.uint8)


@tracing.annotate("raft_tpu::ivf_pq::build")
def build(dataset, params: IndexParams | None = None) -> Index:
    """Train coarse quantizer + rotation + codebooks, then pack the dataset
    (detail/ivf_pq_build.cuh:1729)."""
    p = params or IndexParams()
    dataset = jnp.asarray(dataset, jnp.float32)   # device-resident build
    n, dim = dataset.shape
    mt = canonical_metric(p.metric)
    expects(mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                   DistanceType.InnerProduct),
            "ivf_pq supports L2/IP metrics, got %s", mt.name)
    expects(4 <= p.pq_bits <= 8, "pq_bits must be in [4,8], got %d", p.pq_bits)
    expects(p.n_lists <= n, "n_lists %d > n %d", p.n_lists, n)
    pq_dim = p.pq_dim or _default_pq_dim(dim)
    pq_len = cdiv(dim, pq_dim)
    rot_dim = pq_dim * pq_len
    book_size = 1 << p.pq_bits
    key = jax.random.key(p.seed)
    k_rot, k_book = jax.random.split(key)

    # coarse quantizer on a subsample (ivf_pq_build.cuh:1760-1830)
    n_train = max(p.n_lists, min(n, int(n * p.kmeans_trainset_fraction)))
    stride = max(1, n // n_train)
    trainset = dataset[::stride]
    bparams = kmeans_balanced.BalancedKMeansParams(
        n_iters=p.kmeans_n_iters, seed=p.seed)
    centers = kmeans_balanced.fit(trainset, p.n_lists, bparams)

    rotation = make_rotation_matrix(k_rot, rot_dim, dim,
                                    p.force_random_rotation)
    centers_rot = hdot(centers, rotation.T)

    # codebooks on rotated trainset residuals (ivf_pq_build.cuh:1855-1873)
    train_rot = hdot(trainset, rotation.T)
    t_labels, _ = kmeans_balanced.predict(trainset, centers)
    t_resid = train_rot - centers_rot[t_labels]
    if p.codebook_kind is CodebookGen.PER_SUBSPACE:
        slices = jnp.transpose(
            t_resid.reshape(-1, pq_dim, pq_len), (1, 0, 2))
        codebooks = _train_per_subspace(slices, book_size, p.kmeans_n_iters,
                                        k_book)
    else:
        codebooks = _train_per_cluster(t_resid, t_labels, p.n_lists, pq_len,
                                       book_size, p.kmeans_n_iters, k_book)

    index = Index(
        jnp.zeros((0, pq_dim), jnp.uint8), jnp.zeros((0,), jnp.int32),
        centers_rot, codebooks, rotation,
        np.zeros(p.n_lists + 1, np.int64), mt, p.pq_bits, p.codebook_kind,
        list_sizes_arr=np.zeros(p.n_lists, np.int64),
        list_growth=p.list_growth)
    if p.add_data_on_build:
        index = extend(index, dataset)
    return index


@tracing.annotate("raft_tpu::ivf_pq::build_from_batches")
def build_from_batches(batches, params: IndexParams | None = None,
                       trainset=None) -> Index:
    """Streaming build for memory-scale corpora (DEEP-1B north star;
    detail/ivf_pq_build.cuh:1550 bounded-batch role): quantizers train on
    ``trainset`` (or the first batch), then every batch is assigned,
    encoded and scattered on device — host memory stays O(batch).
    Capacity slack (>=1.2) keeps the merges O(batch) in-place."""
    from ._list_layout import streaming_build

    return streaming_build(batches, params or IndexParams(), build, extend,
                           dataclasses.replace, trainset)


@tracing.annotate("raft_tpu::ivf_pq::extend")
def extend(index: Index, new_vectors, new_ids=None,
           batch_size: int = 1 << 17) -> Index:
    """Assign, encode and merge new vectors (ivf_pq_build.cuh:1550).

    Device-resident: encoding runs in bounded device batches (host memory
    stays O(batch)), and the merge is an O(batch) in-place scatter while
    lists have capacity slack (``IndexParams.list_growth``), else a
    device-side repack.

    .. note:: For *online* mutation prefer the crash-safe tier,
       :class:`raft_tpu.neighbors.mutable.MutableIndex` — durability
       (WAL'd upserts), deletes (tombstones), background merge
       (docs/mutation.md). ``extend`` remains the right call inside
       bulk streaming builds (``build_from_batches``).
    """
    from ._list_layout import scatter_build, scatter_extend

    new_vectors = jnp.asarray(new_vectors, jnp.float32)
    expects(new_vectors.shape[1] == index.dim, "dim mismatch")
    n_new = new_vectors.shape[0]
    if new_ids is None:
        base = int(index.source_ids.max()) + 1 if index.size else 0
        new_ids = jnp.arange(base, base + n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)

    per_cluster = index.codebook_kind is CodebookGen.PER_CLUSTER
    # the per-subspace argmin inside _encode materializes a
    # (batch, pq_dim, book) f32 tensor — bound it to the shared HBM
    # budget, but never above a batch the caller explicitly lowered
    from ..ops.ivf_pq_scan import pq_chunk_rows

    batch_size = min(batch_size,
                     pq_chunk_rows(index.pq_dim, index.codebooks.shape[-2]))
    labels_parts, codes_parts = [], []
    for b0 in range(0, n_new, batch_size):
        xb = new_vectors[b0 : b0 + batch_size]
        xb_rot = hdot(xb, index.rotation.T)
        # nearest rotated center == nearest center (orthogonal rotation)
        d2 = (jnp.sum(xb_rot * xb_rot, axis=1, keepdims=True)
              - 2.0 * hdot(xb_rot, index.centers_rot.T)
              + jnp.sum(index.centers_rot * index.centers_rot, axis=1)[None, :])
        lb = jnp.argmin(d2, axis=1)
        resid = xb_rot - index.centers_rot[lb]
        codes_parts.append(_encode(resid, index.codebooks, lb, per_cluster))
        labels_parts.append(lb.astype(jnp.int32))
    labels = (labels_parts[0] if len(labels_parts) == 1
              else jnp.concatenate(labels_parts))
    new_codes = (codes_parts[0] if len(codes_parts) == 1
                 else jnp.concatenate(codes_parts))

    fills = (0, -1)
    if index.size == 0:
        (codes, ids), offsets, sizes = scatter_build(
            labels, (new_codes, new_ids), fills, index.n_lists,
            index.list_growth)
    else:
        (codes, ids), offsets, sizes = scatter_extend(
            labels, (new_codes, new_ids),
            (index.codes, index.source_ids), fills,
            index.list_offsets, index.list_sizes, index.list_growth)
    return Index(codes, ids, index.centers_rot, index.codebooks,
                 index.rotation, offsets, index.metric, index.pq_bits,
                 index.codebook_kind, sizes, index.list_growth)


def _scan_penalty(index, mask_bits, lmax: int):
    """Sample filter → in-kernel penalty row in sorted row order, padded to
    the scan DMA window (built once per search call, not per query chunk)."""
    from ..ops.ivf_scan import scan_window

    if mask_bits is None:
        return None
    return jnp.pad(jnp.where(mask_bits[index.source_ids], 0.0, jnp.inf),
                   (0, scan_window(lmax)))


def _scan_prep(index: Index, lmax: int) -> dict:
    """Row norms + CB matrix + aligned-DMA padding for the pallas scan —
    full passes over the compressed dataset."""
    from ..ops.ivf_pq_scan import (decoded_row_norms, make_cb_matrix,
                                   pad_codes_for_scan)

    rn = decoded_row_norms(index.codes, index.centers_rot,
                           index.codebooks, index.list_offsets)
    codes_p, norms_p = pad_codes_for_scan(index.codes, rn, lmax,
                                          index.pq_dim)
    return {"n": index.size, "lmax": lmax, "codes_p": codes_p,
            "norms_p": norms_p, "cbm": make_cb_matrix(index.codebooks)}


def prepare_scan(index: Index) -> None:
    """Eagerly attach the pallas scan's per-index prep (see
    ivf_flat.prepare_scan for the caching contract: never written under a
    trace; jit users call this once before tracing)."""
    lmax = int(index.list_sizes.max())
    cache = getattr(index, "_scan_cache", None)
    if cache is None or cache["n"] != index.size or cache["lmax"] != lmax:
        index._scan_cache = _scan_prep(index, lmax)


def _search_pallas(index: Index, q, k, n_probes, lut_dtype, precision,
                   pen_p=None, survivors=None):
    """Fused query-grouped PQ scan (ops/ivf_pq_scan.py) — the TPU perf
    path (expanded-form LUT + one-hot GEMM scoring)."""
    from ..ops.ivf_pq_scan import _ivf_pq_scan_jit
    from ..ops.ivf_scan import coarse_probe

    mt = index.metric
    lmax = int(index.list_sizes.max())
    cache = getattr(index, "_scan_cache", None)
    if cache is None or cache["n"] != index.size or cache["lmax"] != lmax:
        if in_jax_trace():
            cache = _scan_prep(index, lmax)   # traced: compute inline
        else:
            prepare_scan(index)
            cache = index._scan_cache

    q_rot = hdot(q, index.rotation.T)
    coarse_metric = "ip" if mt is DistanceType.InnerProduct else "l2"
    probed = coarse_probe(q_rot, index.centers_rot, n_probes,
                          metric=coarse_metric, precision=precision,
                          survivors=survivors)
    sizes_j = jnp.asarray(index.list_sizes, jnp.int32)
    if survivors is not None:
        # zero-survivor lists scan as empty: sentinel rows only, no DMA
        sizes_j = jnp.where(survivors > 0, sizes_j, 0)
    interpret = jax.default_backend() != "tpu"
    vals, rows = _ivf_pq_scan_jit(
        cache["codes_p"], cache["norms_p"], pen_p, index.centers_rot,
        cache["cbm"], probed,
        jnp.asarray(index.list_offsets[:-1], jnp.int32),
        sizes_j, q_rot, k, lmax,
        index.pq_dim, index.pq_book_size,
        "ip" if mt is DistanceType.InnerProduct else "l2",
        _lut_mode(lut_dtype), interpret, precision)
    ids = jnp.where(rows >= 0,
                    jnp.take(index.source_ids, jnp.maximum(rows, 0)), -1)
    if mt is DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    elif mt is DistanceType.InnerProduct:
        vals = jnp.where(jnp.isfinite(vals), -vals, -jnp.inf)
    return vals, ids



@interop.auto_convert_output
@tracing.annotate("raft_tpu::ivf_pq::search")
def search(
    index: Index,
    queries,
    k: int,
    params: SearchParams | None = None,
    filter: Optional[Bitset] = None,  # noqa: A002
    query_chunk: int = 0,
    algo: str = "auto",
    precision: str = "highest",
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """LUT-based approximate top-k (detail/ivf_pq_search.cuh:731).

    ``algo``: "pallas" (fused query-grouped PQ scan — the TPU perf path;
    PER_SUBSPACE codebooks; ``filter`` rides in-kernel as a penalty row),
    "xla" (gather path, any config), "auto" (pallas on TPU when eligible).
    """
    p = params or SearchParams()
    q = jnp.asarray(queries, jnp.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "bad query shape %s",
            tuple(q.shape))
    tier = getattr(index, "_host_tier", None)
    if tier is not None and not getattr(_hot_local, "skip", False):
        # loud, not silent: a traced search would skip every cold list
        expects(not in_jax_trace(),
                "host-streamed indexes search eagerly (host arrays "
                "cannot ride a jit trace) — drop the outer jit or "
                "search before prepare_host_stream")
        return _search_host_stream(index, tier, q, k, p, filter,
                                   query_chunk, algo, precision, res)
    expects(index.size > 0, "index is empty")
    n_probes = min(p.n_probes, index.n_lists)

    # selectivity-adaptive policy (ops/filter_policy.py): same contract
    # as ivf_flat.search — prune zero-survivor lists, widen the probe
    # set to the survivor-weighted mass target, cross over to the exact
    # compacted brute pass (decode + back-rotate the survivors) at
    # extreme selectivity. Traced searches keep only the device prune.
    surv_dev = None
    if filter is not None:
        from ..ops import filter_policy

        if (in_jax_trace() or getattr(_hot_local, "skip", False)
                or filter_policy.adaptive_off()):
            # traced, the resident half of a host-streamed search (which
            # keeps its own machinery), or a suspended internal filter
            # (mutable tombstones): free prune only
            surv_dev = filter_policy.list_survivors(index, filter)
        else:
            fd = filter_policy.decide_ivf(index, filter, n_probes, k,
                                          "ivf_pq")
            if fd.use_brute:
                return filter_policy.crossover(
                    fd, "ivf_pq",
                    lambda: filter_policy.survivor_brute_ivf(
                        index, reconstruct, q, k, filter),
                    lambda: search(index, q, k, p, filter, query_chunk,
                                   algo, precision, res))
            n_probes = fd.n_probes
            surv_dev = fd.surv_dev

    # wide PQ shapes need the bf16/int8 LUT modes in the kernel (an f32
    # one-hot block would bust VMEM); an explicit f32-LUT request there
    # keeps the exact gather path rather than silently downgrading
    wide_needs_bf16 = (index.pq_dim * index.pq_book_size >= 8192 and
                       _lut_mode(p.lut_dtype) == "f32")
    use_pallas = (algo == "pallas" or
                  (algo == "auto" and
                   index.codebook_kind is CodebookGen.PER_SUBSPACE and
                   not wide_needs_bf16 and
                   jax.default_backend() == "tpu"))
    if (algo == "auto" and not use_pallas
            and jax.default_backend() == "tpu"):
        # make the kernel→gather downgrade visible — once per reason, not
        # per call; fires at trace time too (jitted callers like the
        # bench harnesses only ever execute this body while tracing)
        why = ("PER_CLUSTER codebooks"
               if index.codebook_kind is CodebookGen.PER_CLUSTER
               else "f32 LUT with wide PQ "
                    "(set SearchParams.lut_dtype=bfloat16)")
        if why not in _GATHER_FALLBACK_LOGGED:
            _GATHER_FALLBACK_LOGGED.add(why)
            from ..core.logging import logger

            logger.info("ivf_pq auto: XLA gather path (%s); the pallas "
                        "scan kernel does not cover this config", why)
    mask_bits = filter.to_mask() if filter is not None else None
    if use_pallas:
        expects(index.codebook_kind is CodebookGen.PER_SUBSPACE,
                "algo='pallas' needs PER_SUBSPACE codebooks")
        expects(not wide_needs_bf16,
                "algo='pallas' with pq_dim*2^pq_bits >= 8192 requires the "
                "bf16 LUT mode (SearchParams.lut_dtype=jnp.bfloat16)")
        pen_p = _scan_penalty(index, mask_bits,
                              int(index.list_sizes.max()))
        if query_chunk <= 0:
            per_q = n_probes * index.rot_dim * 4 * 2
            query_chunk = max(1, min(q.shape[0],
                                     workspace_chunk_bytes(res) // max(per_q, 1)))
        fb_state: dict = {}   # built lazily: the fallback almost never runs

        def _xla_fallback(qc):
            # the gather/LUT path's per-query footprint dwarfs the
            # kernel's — re-chunk to ITS workspace budget or the
            # containment path itself OOMs
            if not fb_state:
                sizes_np = index.list_sizes
                fb_state["max_rows"] = _probe_budget(sizes_np, n_probes)
                fb_state["offsets_j"] = jnp.asarray(
                    index.list_offsets[:-1], jnp.int32)
                sizes_j = jnp.asarray(sizes_np, jnp.int32)
                if surv_dev is not None:
                    sizes_j = jnp.where(surv_dev > 0, sizes_j, 0)
                fb_state["sizes_j"] = sizes_j
                per_q = fb_state["max_rows"] * index.pq_dim * 8 + \
                    n_probes * index.pq_dim * index.pq_book_size * 4
                fb_state["chunk"] = max(
                    1, workspace_chunk_bytes(res) // max(per_q, 1))
            return run_query_chunks(
                lambda qs, _s0: _search_chunk(index, qs, k, n_probes,
                                              fb_state["max_rows"],
                                              fb_state["offsets_j"],
                                              fb_state["sizes_j"],
                                              mask_bits, p.lut_dtype,
                                              surv_dev),
                qc, fb_state["chunk"])

        # guarded: a PQ-scan kernel failure demotes this site to the
        # exact XLA gather/LUT path (ops/guarded.py)
        return run_query_chunks(
            lambda qc, _s0: guarded_call(
                "ivf_pq.scan",
                lambda: _search_pallas(index, qc, k, n_probes, p.lut_dtype,
                                       precision, pen_p, surv_dev),
                lambda: _xla_fallback(qc)),
            q, query_chunk, res)

    sizes_np = index.list_sizes
    max_rows = _probe_budget(sizes_np, n_probes)
    if query_chunk <= 0:
        # candidates gather (S × pq_dim) + LUT (p × pq_dim × book) per query
        per_q = max_rows * index.pq_dim * 8 + \
            n_probes * index.pq_dim * index.pq_book_size * 4
        query_chunk = max(1, min(q.shape[0], workspace_chunk_bytes(res) // max(per_q, 1)))

    offsets_j = jnp.asarray(index.list_offsets[:-1], jnp.int32)
    sizes_j = jnp.asarray(sizes_np, jnp.int32)
    if surv_dev is not None:
        sizes_j = jnp.where(surv_dev > 0, sizes_j, 0)

    return run_query_chunks(
        lambda qc, _s0: _search_chunk(index, qc, k, n_probes, max_rows,
                                      offsets_j, sizes_j, mask_bits,
                                      p.lut_dtype, surv_dev),
        q, query_chunk, res)


def _search_chunk(index, qc, k, n_probes, max_rows, offsets_j, sizes_j,
                  mask_bits, lut_dtype, survivors=None):
    mt = index.metric
    m = qc.shape[0]
    pq_dim, book = index.pq_dim, index.pq_book_size
    pq_len = index.pq_len
    q_rot = qc @ index.rotation.T                       # (m, rot_dim)

    # stage 1: coarse probe selection (select_clusters, ivf_pq_search.cuh:69)
    cross = hdot(q_rot, index.centers_rot.T)
    if mt is DistanceType.InnerProduct:
        coarse = -cross
    else:
        c2 = jnp.sum(index.centers_rot * index.centers_rot, axis=1)
        coarse = c2[None, :] - 2.0 * cross              # + q² is rank-constant
    if survivors is not None:
        # filter-pruned lists never win a probe slot (ops/filter_policy.py)
        coarse = jnp.where(survivors[None, :] > 0, coarse, jnp.inf)
    _, probed = select_k(coarse, n_probes, select_min=True)   # (m, p)

    # stage 2: per-(query, probe) LUTs (the smem LUT analog)
    centers_p = index.centers_rot[probed]               # (m, p, rot_dim)
    if mt is DistanceType.InnerProduct:
        qs = q_rot.reshape(m, pq_dim, pq_len)
        if index.codebook_kind is CodebookGen.PER_SUBSPACE:
            lut = -jnp.einsum("msl,sbl->msb", qs, index.codebooks, precision="highest")
            lut = jnp.broadcast_to(lut[:, None], (m, n_probes, pq_dim, book))
        else:
            books = index.codebooks[probed]             # (m, p, book, pq_len)
            lut = -jnp.einsum("msl,mpbl->mpsb", qs, books, precision="highest")
        const = -jnp.einsum("mr,mpr->mp", q_rot, centers_p, precision="highest")
    else:
        resid = q_rot[:, None, :] - centers_p           # (m, p, rot_dim)
        rs = resid.reshape(m, n_probes, pq_dim, pq_len)
        if index.codebook_kind is CodebookGen.PER_SUBSPACE:
            cb2 = jnp.sum(index.codebooks * index.codebooks, axis=2)  # (s, b)
            lut = (jnp.sum(rs * rs, axis=3)[..., None]
                   - 2.0 * jnp.einsum("mpsl,sbl->mpsb", rs, index.codebooks, precision="highest")
                   + cb2[None, None])
        else:
            books = index.codebooks[probed]             # (m, p, book, pq_len)
            cb2 = jnp.sum(books * books, axis=3)        # (m, p, b)
            lut = (jnp.sum(rs * rs, axis=3)[..., None]
                   - 2.0 * jnp.einsum("mpsl,mpbl->mpsb", rs, books, precision="highest")
                   + cb2[:, :, None, :])
        const = jnp.zeros((m, n_probes), jnp.float32)
    # the gather path has no int8 formulation (scores are gathered, not
    # GEMMed); int8 requests ride its bf16 LUT instead
    mode = _lut_mode(lut_dtype)
    lut = lut.astype(jnp.float32 if mode == "f32" else jnp.bfloat16)

    # stage 3: score packed codes via one flat gather per subspace
    rows, valid, probe_of = _candidate_rows(probed, offsets_j, sizes_j,
                                            max_rows)
    codes_c = index.codes[rows].astype(jnp.int32)       # (m, S, pq_dim)
    sub_ids = jnp.arange(pq_dim, dtype=jnp.int32)
    flat = lut.reshape(m, n_probes * pq_dim * book)
    idx = (probe_of[:, :, None] * (pq_dim * book)
           + sub_ids[None, None, :] * book + codes_c)   # (m, S, pq_dim)
    vals = jnp.take_along_axis(flat, idx.reshape(m, -1), axis=1)
    dist = vals.reshape(m, max_rows, pq_dim).sum(axis=2).astype(jnp.float32)
    dist = dist + jnp.take_along_axis(const, probe_of, axis=1)
    if mt is DistanceType.L2SqrtExpanded:
        dist = jnp.sqrt(jnp.maximum(dist, 0.0))

    if mask_bits is not None:
        valid = valid & mask_bits[index.source_ids[rows]]
    dist = jnp.where(valid, dist, jnp.inf)
    kk = min(k, max_rows)
    out_d, locs = select_k(dist, kk, select_min=True)
    out_i = jnp.take_along_axis(index.source_ids[rows], locs, axis=1)
    out_i = jnp.where(jnp.isfinite(out_d), out_i, -1)
    if mt is DistanceType.InnerProduct:
        out_d = -out_d                                  # report true IP
    if kk < k:
        pad = k - kk
        bad = -jnp.inf if mt is DistanceType.InnerProduct else jnp.inf
        out_d = jnp.pad(out_d, ((0, 0), (0, pad)), constant_values=bad)
        out_i = jnp.pad(out_i, ((0, 0), (0, pad)), constant_values=-1)
    return out_d, out_i


_hot_local = __import__("threading").local()   # re-entry guard (the hot
# half of a host-streamed search runs the ordinary resident path)


def prepare_host_stream(index: Index, budget_gb: Optional[float] = None,
                        sample_queries=None, n_probes: int = 20,
                        chunk_mb: float = 64, hot_mask=None) -> None:
    """Move cold PQ lists past the HBM budget into a host-RAM tier —
    same contract as :func:`ivf_flat.prepare_host_stream` (probe-
    frequency pinning, fixed-shape double-buffered chunks, eager-only
    search; ``RAFT_TPU_HBM_BUDGET_GB`` default budget). PQ codes are
    16-32x smaller than raw rows, so this rung matters for indexes whose
    *code* store outgrows HBM (the DEEP-1B shape) or that share a device
    with raw-row indexes. Chunk rows carry codes (scan-padded), decoded
    row norms, source ids and the row's chunk-local list label.

    ``hot_mask`` (bool, ``(n_lists,)``) bypasses the local budget plan
    with an externally-planned hot set — same contract as the ivf_flat
    variant (the fleet layer plans once, fleet-wide)."""
    from ..ops.ivf_pq_scan import decoded_row_norms
    from ..ops.ivf_scan import scan_window
    from ..utils import round_up_to
    from . import host_stream as hs

    if getattr(index, "_host_tier", None) is not None:
        return
    sizes = index.list_sizes
    row_bytes = index.pq_dim + 12
    if hot_mask is not None:
        hot = np.asarray(hot_mask, bool)
        expects(hot.shape == (index.n_lists,),
                f"hot_mask shape {hot.shape} != ({index.n_lists},)")
        if bool(hot.all()):
            return   # externally planned: everything stays resident
    else:
        budget = hs.budget_bytes(budget_gb)
        expects(budget > 0, "prepare_host_stream needs budget_gb or "
                "RAFT_TPU_HBM_BUDGET_GB")
        if int(sizes.sum()) * row_bytes <= budget:
            return
        freq = None
        if sample_queries is not None:
            from ..ops.ivf_scan import coarse_probe

            q_rot = hdot(jnp.asarray(sample_queries, jnp.float32),
                         index.rotation.T)
            probed = np.asarray(coarse_probe(
                q_rot, index.centers_rot, min(n_probes, index.n_lists),
                metric="ip" if index.metric is DistanceType.InnerProduct
                else "l2"))
            freq = hs.probe_frequency(probed, index.n_lists)
        hot = hs.plan_hot_cold(sizes, row_bytes, budget, freq)

    rn = decoded_row_norms(index.codes, index.centers_rot,
                           index.codebooks, index.list_offsets)
    code_pad = round_up_to(index.pq_dim, 128)
    labels = np.repeat(np.arange(index.n_lists),
                       np.diff(index.list_offsets)).astype(np.int32)
    arrays = {
        "codes": np.pad(np.asarray(index.codes, np.uint8),
                        ((0, 0), (0, code_pad - index.pq_dim))),
        "norms": np.asarray(rn, np.float32),
        "ids": np.asarray(index.source_ids, np.int32),
        "labels": labels,
    }
    chunk_rows = max(1, int(float(chunk_mb) * (1 << 20))
                     // max(row_bytes, 1))
    cold_lmax = int(sizes[~hot].max()) if (~hot).any() else 0
    tier, hot_arrays, hot_offsets, hot_sizes = hs.build_tier(
        arrays, index.list_offsets, sizes, hot, chunk_rows,
        pad_tail=scan_window(cold_lmax), fills={"ids": -1})
    # chunk-local labels (build_tier copied GLOBAL list ids' rows; remap
    # each chunk's label rows to chunk-local slots for the XLA fallback)
    cent = np.asarray(index.centers_rot, np.float32)
    for ci, ch in enumerate(tier.chunks):
        lab = ch.arrays["labels"]
        ch.arrays["labels"] = np.where(
            tier.chunk_of[np.clip(lab, 0, index.n_lists - 1)] == ci,
            tier.local_of[np.clip(lab, 0, index.n_lists - 1)],
            0).astype(np.int32)
        loc_cent = np.zeros((tier.chunk_lists, cent.shape[1]), np.float32)
        loc_cent[:len(ch.lists)] = cent[ch.lists]
        tier.extras[ci]["centers"] = loc_cent

    index.codes = jnp.asarray(
        hot_arrays["codes"][:, :index.pq_dim].astype(np.uint8))
    index.source_ids = jnp.asarray(hot_arrays["ids"])
    index.list_offsets = hot_offsets
    index.list_sizes_arr = hot_sizes
    index.__dict__.pop("_scan_cache", None)
    index._host_tier = tier


def _cold_chunk_scan_pq(index, dev, probed_local, qc, k, lut_dtype,
                        precision, mask_bits):
    """Scan one streamed cold chunk with the SAME PQ kernel (and LUT
    mode) as the resident lists (ops/ivf_pq_scan.py): chunk-local
    rotated centers + the index's codebook matrix."""
    from ..ops.ivf_pq_scan import _ivf_pq_scan_jit

    cache = getattr(index, "_scan_cache", None)
    cbm = cache["cbm"] if cache is not None else \
        getattr(index, "_cold_cbm", None)
    if cbm is None:
        from ..ops.ivf_pq_scan import make_cb_matrix

        cbm = make_cb_matrix(index.codebooks)
        if not in_jax_trace():
            index._cold_cbm = cbm
    ids = dev["ids"]
    pen_p = None
    if mask_bits is not None:
        pen_p = jnp.where((ids >= 0)
                          & jnp.take(mask_bits, jnp.maximum(ids, 0)),
                          0.0, jnp.inf).astype(jnp.float32)
    q_rot = hdot(qc, index.rotation.T)
    interpret = jax.default_backend() != "tpu"
    mt = index.metric
    vals, rows = _ivf_pq_scan_jit(
        dev["codes"], dev["norms"], pen_p, dev["centers"], cbm,
        jnp.asarray(probed_local), dev["offsets"].astype(jnp.int32),
        dev["sizes"].astype(jnp.int32), q_rot, k,
        index._host_tier.lmax, index.pq_dim, index.pq_book_size,
        "ip" if mt is DistanceType.InnerProduct else "l2",
        _lut_mode(lut_dtype), interpret, precision)
    out_i = jnp.where(rows >= 0, jnp.take(ids, jnp.maximum(rows, 0)), -1)
    return vals, out_i


def _cold_chunk_xla_pq(index, dev, probed_local, qc, k, mask_bits):
    """Guarded fallback: exact rescore of the streamed chunk's candidate
    rows via decode + GEMM in rotated space — correct, not
    arithmetic-identical to the kernel's LUT path."""
    tier = index._host_tier
    n_probes = probed_local.shape[1]
    offs = dev["offsets"].astype(jnp.int32)
    szs = dev["sizes"].astype(jnp.int32)
    max_rows = tier.lmax * min(n_probes, offs.shape[0])
    rows, valid, _ = _candidate_rows(jnp.asarray(probed_local), offs, szs,
                                     max_rows)
    codes = dev["codes"][rows][..., :index.pq_dim].astype(jnp.int32)
    decoded = index.codebooks[
        jnp.arange(index.pq_dim)[None, None, :], codes]   # (m,S,s,len)
    y = (dev["centers"][dev["labels"][rows]]
         + decoded.reshape(codes.shape[0], codes.shape[1], -1))
    q_rot = hdot(qc, index.rotation.T)
    ip = jnp.einsum("msd,md->ms", y, q_rot, precision="highest")
    mt = index.metric
    if mt is DistanceType.InnerProduct:
        dist = -ip
    else:
        q2 = jnp.sum(q_rot * q_rot, axis=1, keepdims=True)
        dist = jnp.maximum(q2 + dev["norms"][rows] - 2.0 * ip, 0.0)
    ids = dev["ids"][rows]
    valid = valid & (ids >= 0)
    if mask_bits is not None:
        valid = valid & jnp.take(mask_bits, jnp.maximum(ids, 0))
    dist = jnp.where(valid, dist, jnp.inf)
    kk = min(k, max_rows)
    vals, locs = select_k(dist, kk, select_min=True)
    out_i = jnp.where(jnp.isfinite(vals),
                      jnp.take_along_axis(ids, locs, axis=1), -1)
    if kk < k:
        vals = jnp.pad(vals, ((0, 0), (0, k - kk)),
                       constant_values=jnp.inf)
        out_i = jnp.pad(out_i, ((0, 0), (0, k - kk)), constant_values=-1)
    return vals, out_i


def _search_host_stream(index, tier, q, k, p, filter, query_chunk, algo,
                        precision, res):
    """Resident half through the ordinary PQ engines + probed cold lists
    streamed from the host tier, merged like shard results."""
    from ..ops.ivf_scan import coarse_probe
    from .brute_force import knn_merge_parts

    mt = index.metric
    select_min = mt is not DistanceType.InnerProduct
    n_probes = min(p.n_probes, index.n_lists)
    mask_bits = filter.to_mask() if filter is not None else None
    if query_chunk <= 0:
        per_q = n_probes * index.rot_dim * 4 * 2
        query_chunk = max(1, min(q.shape[0],
                                 workspace_chunk_bytes(res) // max(per_q, 1)))

    def _post(vals):
        if mt is DistanceType.L2SqrtExpanded:
            return jnp.sqrt(jnp.maximum(vals, 0.0))
        if mt is DistanceType.InnerProduct:
            return jnp.where(jnp.isfinite(vals), -vals, -jnp.inf)
        return vals

    def one(qc, _s0):
        bad = jnp.inf if select_min else -jnp.inf
        if index.size > 0:
            _hot_local.skip = True
            try:
                hot_d, hot_i = search(index, qc, k, p, filter, 0, algo,
                                      precision)
            finally:
                _hot_local.skip = False
        else:
            hot_d = jnp.full((qc.shape[0], k), bad, jnp.float32)
            hot_i = jnp.full((qc.shape[0], k), -1, jnp.int32)
        # duplicate of the hot half's in-executable coarse probe — see
        # ivf_flat._search_host_stream: one small GEMM buys unchanged
        # resident executables
        q_rot = hdot(qc, index.rotation.T)
        probed = np.asarray(coarse_probe(
            q_rot, index.centers_rot, n_probes,
            metric="ip" if mt is DistanceType.InnerProduct else "l2",
            precision=precision))

        def run(ci, dev, probed_local):
            return guarded_call(
                "ivf.host_stream",
                lambda: _cold_chunk_scan_pq(index, dev, probed_local, qc,
                                            k, p.lut_dtype, precision,
                                            mask_bits),
                lambda: _cold_chunk_xla_pq(index, dev, probed_local, qc,
                                           k, mask_bits))

        cold = tier.stream(probed, run)
        if not cold:
            return hot_d, hot_i
        parts_d = [hot_d] + [_post(cd) for cd, _ in cold]
        parts_i = [hot_i] + [ci_ for _, ci_ in cold]
        return knn_merge_parts(jnp.stack(parts_d), jnp.stack(parts_i),
                               select_min)

    return run_query_chunks(one, q, query_chunk, res)


def reconstruct(index: Index, row_ids) -> jax.Array:
    """Decode rows back to (approximate) input-space vectors
    (ivf_pq helpers reconstruct_list_data, detail/ivf_pq_build.cuh)."""
    row_ids = jnp.asarray(row_ids, jnp.int32)
    # physical row → list id via *capacity* spans (slack-aware)
    labels = jnp.asarray(
        np.repeat(np.arange(index.n_lists),
                  np.diff(index.list_offsets)))[row_ids]
    codes = index.codes[row_ids].astype(jnp.int32)      # (r, pq_dim)
    if index.codebook_kind is CodebookGen.PER_CLUSTER:
        books = index.codebooks[labels]                 # (r, book, pq_len)
        decoded = jnp.take_along_axis(
            books, codes[:, :, None], axis=1)           # (r, pq_dim, pq_len)
    else:
        decoded = index.codebooks[
            jnp.arange(index.pq_dim)[None, :], codes]   # (r, pq_dim, pq_len)
    y_rot = index.centers_rot[labels] + decoded.reshape(len(row_ids), -1)
    return y_rot @ index.rotation                       # back-project


def pack_codes(codes: np.ndarray, pq_bits: int) -> np.ndarray:
    """Bit-pack (n, pq_dim) byte codes → (n, ceil(pq_dim*bits/8)) for
    storage (analog of ivf_pq_codepacking.cuh)."""
    codes = np.asarray(codes, np.uint8)
    n, pq_dim = codes.shape
    bits = np.unpackbits(codes[:, :, None], axis=2, count=8)[:, :, 8 - pq_bits:]
    flat = bits.reshape(n, pq_dim * pq_bits)
    out_bytes = cdiv(pq_dim * pq_bits, 8) * 8
    flat = np.pad(flat, ((0, 0), (0, out_bytes - flat.shape[1])))
    return np.packbits(flat, axis=1)


def unpack_codes(packed: np.ndarray, pq_dim: int, pq_bits: int) -> np.ndarray:
    """Inverse of :func:`pack_codes`."""
    packed = np.asarray(packed, np.uint8)
    n = packed.shape[0]
    flat = np.unpackbits(packed, axis=1)[:, : pq_dim * pq_bits]
    bits = flat.reshape(n, pq_dim, pq_bits)
    weights = (1 << np.arange(pq_bits - 1, -1, -1)).astype(np.uint32)
    return (bits * weights).sum(axis=2).astype(np.uint8)


def save(index: Index, path) -> None:
    """Serialize (analog of detail/ivf_pq_serialize.cuh). Capacity slack is
    stripped: files hold densely-packed valid rows only. Host-streamed
    indexes refuse to serialize (the device arrays hold only the hot
    lists — a silent save would drop every cold row); save before
    :func:`prepare_host_stream`."""
    from ._list_layout import gather_dense

    expects(getattr(index, "_host_tier", None) is None,
            "cannot save a host-streamed index (cold lists live in the "
            "host tier, not the device arrays); save before "
            "prepare_host_stream and re-prepare after load")

    sizes = index.list_sizes
    if index.list_sizes_arr is not None:
        (codes, ids), _ = gather_dense(
            (index.codes, index.source_ids), index.list_offsets, sizes)
    else:
        codes, ids = index.codes, index.source_ids
    dense_offsets = np.zeros(index.n_lists + 1, np.int64)
    np.cumsum(sizes, out=dense_offsets[1:])
    save_arrays(
        path, "ivf_pq", _SERIAL_VERSION,
        {"metric": index.metric.value, "pq_bits": index.pq_bits,
         "codebook_kind": index.codebook_kind.value,
         "pq_dim": index.pq_dim},
        {
            "codes": pack_codes(np.asarray(codes), index.pq_bits),
            "source_ids": ids,
            "centers_rot": index.centers_rot,
            "codebooks": index.codebooks,
            "rotation": index.rotation,
            "list_offsets": dense_offsets,
        })


def load(path) -> Index:
    _, version, meta, arrs = load_arrays(path, "ivf_pq")
    expects(version == _SERIAL_VERSION, "unsupported version %d", version)
    codes = unpack_codes(arrs["codes"], meta["pq_dim"], meta["pq_bits"])
    offsets = np.asarray(arrs["list_offsets"], np.int64)
    return Index(
        jnp.asarray(codes), jnp.asarray(arrs["source_ids"]),
        jnp.asarray(arrs["centers_rot"]), jnp.asarray(arrs["codebooks"]),
        jnp.asarray(arrs["rotation"]), offsets,
        DistanceType(meta["metric"]), meta["pq_bits"],
        CodebookGen(meta["codebook_kind"]),
        list_sizes_arr=np.diff(offsets))


def health(index: Index, sample: int = 256) -> dict:
    """Index health report (docs/observability.md "Quality"): list-size
    skew, PQ geometry, and sampled **codeword utilization** — the
    PQ-specific quality signal available without the f32 originals: a
    subspace using a small fraction of its 2^bits codewords has
    collapsed codebook training (all residuals near one centroid), which
    caps the resolution — and therefore the recall — of every list scan.
    """
    from ._list_layout import list_skew
    from .brute_force import health_sample_rows

    report = {
        "family": "ivf_pq", "n": int(index.size), "dim": int(index.dim),
        "metric": index.metric.name,
        "lists": list_skew(index.list_sizes),
        "pq": {"pq_dim": int(index.pq_dim), "pq_bits": int(index.pq_bits),
               "book_size": int(index.pq_book_size),
               "rot_dim": int(index.rot_dim),
               "codebook_kind": index.codebook_kind.name,
               "compression": round(
                   index.dim * 4.0 / max(index.pq_dim, 1), 1)},
    }
    cap = int(index.codes.shape[0])
    if cap:
        rows = health_sample_rows(cap, sample)
        sid = np.asarray(index.source_ids[rows])
        codes = np.asarray(index.codes[rows])[sid >= 0]
        if codes.size:
            used = np.array([np.unique(codes[:, s]).size
                             for s in range(codes.shape[1])], np.float64)
            # utilization saturates at the sample size on tiny samples —
            # report the bound so the number stays interpretable
            denom = min(index.pq_book_size, codes.shape[0])
            report["pq"]["codeword_utilization"] = {
                "mean": round(float(used.mean() / denom), 4),
                "min": round(float(used.min() / denom), 4),
                "sampled_rows": int(codes.shape[0])}
    return report


def make_searcher(index: Index, params: SearchParams | None = None, *,
                  degrade=None, **opts):
    """Stable batchable signature for the serving runtime
    (:mod:`raft_tpu.serve`): returns ``fn(queries, k, res=None) ->
    (distances, indices)`` with the probe/LUT policy frozen at closure
    build time, so repeated bucketed-shape calls hit the same cached
    executables. ``opts`` forwards to :func:`search` (``algo``,
    ``filter``, ``precision``, ``query_chunk``, ...). ``degrade``: a
    :class:`~raft_tpu.serve.degrade.BrownoutController` — under brownout
    its current level overrides ``n_probes`` per call
    (docs/robustness.md)."""
    base = params or SearchParams()

    def _fn(queries, k, res=None):
        p = base if degrade is None else degrade.params(base)
        return search(index, queries, k, p, res=res, **opts)

    return _fn
