"""CPU hnsw search over an exported CAGRA graph: analog of
``raft::neighbors::hnsw``.

Reference: detail/hnsw_types.hpp:60-95 + detail/hnsw.hpp:32-73 — a thin
wrapper that loads a CAGRA-serialized graph as a *base-layer-only*
hnswlib index and searches it on CPU; the export path is
`serialize_to_hnswlib` (detail/cagra/cagra_serialize.cuh:102, public
wrapper neighbors/cagra_serialize.cuh:212-219).

TPU design: the index is the same (dataset, fixed-degree graph) pair
CAGRA built; search is the canonical base-layer greedy best-first loop
(identical to hnswlib's `searchBaseLayerST`) in numpy — this is the
CPU-serving escape hatch, not a TPU path. When the real `hnswlib`
package is importable, `to_hnswlib` hands the graph over for bit-exact
parity with the reference's serving stack.
"""
from __future__ import annotations

import dataclasses
import heapq
from typing import Optional, Tuple

import numpy as np

from ..core import tracing
from ..core.errors import expects
from ..core.serialize import load_arrays, save_arrays
from ..distance.distance_types import DistanceType, canonical_metric
from . import cagra as cagra_mod

__all__ = ["Index", "from_cagra", "search", "save", "load", "to_hnswlib"]

_SERIAL_VERSION = 1


@dataclasses.dataclass
class Index:
    """Base-layer-only graph index on host memory (hnsw_types.hpp:60)."""

    dataset: np.ndarray     # (n, d) f32
    graph: np.ndarray       # (n, degree) i32
    metric: DistanceType
    entry_point: int = 0

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]


def from_cagra(index: "cagra_mod.Index") -> Index:
    """CAGRA → hnsw (the serialize_to_hnswlib + load path collapsed:
    same arrays, no file round-trip needed in-process)."""
    dataset = np.asarray(index.dataset, np.float32)
    graph = np.asarray(index.graph, np.int32)
    # entry point: the node closest to the dataset centroid (hnswlib uses
    # its insertion-order top level; a centroid-medoid is the standard
    # choice for flat graphs)
    centroid = dataset.mean(axis=0)
    ep = int(np.argmin(((dataset - centroid) ** 2).sum(axis=1)))
    return Index(dataset, graph, index.metric, ep)


def _dist_fn(metric: DistanceType):
    if metric is DistanceType.InnerProduct:
        return lambda q, v: -float(np.dot(v, q))
    return lambda q, v: float(((v - q) ** 2).sum())


@tracing.annotate("raft_tpu::hnsw::search")
def search(index: Index, queries, k: int, ef: int = 64
           ) -> Tuple[np.ndarray, np.ndarray]:
    """Greedy best-first base-layer search (hnsw.hpp:32 search →
    hnswlib searchBaseLayerST), one query at a time on CPU.

    ``ef``: beam width (>= k), the hnswlib ef_search knob.
    """
    q = np.asarray(queries, np.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "bad query shape")
    ef = max(ef, k)
    n = index.size
    dist = _dist_fn(index.metric)
    out_d = np.full((len(q), k), np.inf, np.float32)
    out_i = np.full((len(q), k), -1, np.int32)

    for qi, qv in enumerate(q):
        visited = np.zeros(n, bool)
        d0 = dist(qv, index.dataset[index.entry_point])
        visited[index.entry_point] = True
        # candidates: min-heap by distance; results: max-heap (negated)
        cand = [(d0, index.entry_point)]
        res = [(-d0, index.entry_point)]
        while cand:
            dc, c = heapq.heappop(cand)
            if dc > -res[0][0] and len(res) >= ef:
                break
            for nb in index.graph[c]:
                if nb < 0 or visited[nb]:
                    continue
                visited[nb] = True
                dn = dist(qv, index.dataset[nb])
                if len(res) < ef or dn < -res[0][0]:
                    heapq.heappush(cand, (dn, int(nb)))
                    heapq.heappush(res, (-dn, int(nb)))
                    if len(res) > ef:
                        heapq.heappop(res)
        top = sorted((-nd, i) for nd, i in res)[:k]
        for j, (dv, iv) in enumerate(top):
            out_d[qi, j] = dv
            out_i[qi, j] = iv

    if index.metric is DistanceType.InnerProduct:
        out_d = np.where(np.isfinite(out_d), -out_d, -np.inf)
    elif index.metric is DistanceType.L2SqrtExpanded:
        out_d = np.sqrt(np.maximum(out_d, 0.0))
    return out_d, out_i


def save(index: Index, path) -> None:
    """Serialize (the CAGRA hnswlib-export file role, own format)."""
    save_arrays(path, "hnsw", _SERIAL_VERSION,
                {"metric": index.metric.value,
                 "entry_point": index.entry_point},
                {"dataset": index.dataset, "graph": index.graph})


def load(path) -> Index:
    _, version, meta, arrs = load_arrays(path, "hnsw")
    expects(version == _SERIAL_VERSION, "unsupported version %d", version)
    return Index(np.asarray(arrs["dataset"], np.float32),
                 np.asarray(arrs["graph"], np.int32),
                 DistanceType(meta["metric"]), int(meta["entry_point"]))


def to_hnswlib(index: Index):
    """Build a fresh hnswlib index over the same dataset (convenience
    bridge when the optional package exists; raises ImportError otherwise).

    NOTE: hnswlib's Python API offers no way to transplant an external
    base-layer graph, so this REBUILDS with hnswlib's own construction —
    the CAGRA graph is not carried over. The faithful base-layer search
    over the exported CAGRA graph is the in-tree ``search`` above (the
    reference's serialize_to_hnswlib graph handover needs hnswlib's C++
    internals, which aren't reachable from Python)."""
    import hnswlib  # noqa: F401 — optional dependency

    space = ("ip" if index.metric is DistanceType.InnerProduct
             else "l2")
    p = hnswlib.Index(space=space, dim=index.dim)
    p.init_index(max_elements=index.size,
                 M=index.graph.shape[1] // 2, ef_construction=64)
    p.add_items(index.dataset, np.arange(index.size))
    return p
