"""Base ANN parameter types: analog of ``raft/neighbors/ann_types.hpp``.

The reference's POD param structs (index_params{metric, metric_arg,
add_data_on_build} / search_params) become frozen dataclasses that every
index family extends.
"""
from __future__ import annotations

import dataclasses

from ..distance.distance_types import DistanceType

__all__ = ["IndexParams", "SearchParams"]


@dataclasses.dataclass
class IndexParams:
    """Common build-time parameters (ann_types.hpp:index_params)."""

    metric: DistanceType | str = DistanceType.L2Expanded
    metric_arg: float = 2.0
    add_data_on_build: bool = True


@dataclasses.dataclass
class SearchParams:
    """Common search-time parameters (ann_types.hpp:search_params)."""
