"""Candidate re-ranking: analog of ``raft::neighbors::refine``.

Reference: raft/neighbors/refine-inl.cuh — given candidate neighbor lists
(e.g. from ivf_pq::search with a larger k), recompute exact distances
against the original dataset and keep the best k (device kernel
detail/refine_device.cuh; host/OpenMP path detail/refine_host-inl.hpp).

TPU design: one gather + batched dot products + select_k; -1 candidate ids
(padding from upstream searches) are masked out. The gather is the cost
(random ~d·4-byte rows bound by HBM latency, not FLOPs), so a ``bfloat16``
dataset is kept bf16 through the gather and contracted with f32
accumulation — callers wanting cheaper refine pass a bf16 corpus copy.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core import interop, tracing
from ..core.errors import expects
from ..distance.distance_types import DistanceType, canonical_metric
from ..matrix.select_k import select_k

__all__ = ["refine"]


@interop.auto_convert_output
@tracing.annotate("raft_tpu::refine")
def refine(
    dataset,
    queries,
    candidates,
    k: int,
    metric: DistanceType | str = DistanceType.L2Expanded,
) -> Tuple[jax.Array, jax.Array]:
    """Exact re-rank: (m, c) candidate ids → (m, k) distances + ids."""
    x = jnp.asarray(dataset)
    if x.dtype not in (jnp.bfloat16, jnp.uint8):
        x = x.astype(jnp.float32)
    q = jnp.asarray(queries, jnp.float32)
    cand = jnp.asarray(candidates, jnp.int32)
    mt = canonical_metric(metric)
    expects(mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                   DistanceType.InnerProduct, DistanceType.CosineExpanded),
            "refine supports L2/IP/cosine metrics, got %s", mt.name)
    expects(q.shape[1] == x.shape[1], "dim mismatch")
    expects(cand.ndim == 2 and cand.shape[0] == q.shape[0],
            "candidates must be (n_queries, n_candidates)")
    expects(k <= cand.shape[1], "k %d > n_candidates %d", k, cand.shape[1])

    valid = cand >= 0
    rows = jnp.where(valid, cand, 0)
    vecs = x[rows]                                   # (m, c, d)
    if vecs.dtype == jnp.uint8:
        # byte corpora: the win is the quarter-traffic GATHER; widen to
        # f32 after it so the re-rank stays exact for any f32 queries
        vecs = vecs.astype(jnp.float32)
    bf16 = vecs.dtype == jnp.bfloat16
    if bf16:
        ip = jnp.einsum("mcd,md->mc", vecs, q.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32)
    else:
        ip = jnp.einsum("mcd,md->mc", vecs, q, precision="highest")

    def row_norms2():
        if bf16:
            return jnp.einsum("mcd,mcd->mc", vecs, vecs,
                              preferred_element_type=jnp.float32)
        return jnp.sum(vecs * vecs, axis=2)

    if mt is DistanceType.InnerProduct:
        dist = -ip
    elif mt is DistanceType.CosineExpanded:
        qn = jnp.sqrt(jnp.maximum(jnp.sum(q * q, axis=1, keepdims=True), 1e-30))
        vn = jnp.sqrt(jnp.maximum(row_norms2(), 1e-30))
        dist = 1.0 - ip / (qn * vn)
    else:
        q2 = jnp.sum(q * q, axis=1, keepdims=True)
        dist = jnp.maximum(q2 + row_norms2() - 2.0 * ip, 0.0)
        if mt is DistanceType.L2SqrtExpanded:
            dist = jnp.sqrt(dist)

    dist = jnp.where(valid, dist, jnp.inf)
    vals, locs = select_k(dist, k, select_min=True)
    ids = jnp.take_along_axis(rows, locs, axis=1)
    ids = jnp.where(jnp.isfinite(vals), ids, -1)
    if mt is DistanceType.InnerProduct:
        vals = jnp.where(jnp.isfinite(vals), -vals, -jnp.inf)
    return vals, ids
