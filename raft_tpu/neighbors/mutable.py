"""Crash-safe mutable index: a WAL'd LSM tier over the static families.

Every raft_tpu index family is build-once; the reference's IVF
``extend`` (mirrored by :func:`ivf_flat.extend` / :func:`ivf_pq.extend`)
adds rows but cannot delete and survives nothing. This module makes any
family safely *mutable* with the FreshDiskANN-style decomposition:

* a **sealed segment** — one immutable CAGRA / IVF-Flat / IVF-PQ /
  brute-force index over the corpus as of the last merge;
* a **delta segment** — a small brute-force tier absorbing
  :meth:`~MutableIndex.upsert` (the PR 3 fused streaming kernel is
  exact and fast at delta scale, ≤128k rows);
* **tombstones** — :meth:`~MutableIndex.delete` clears a
  :class:`~raft_tpu.core.bitset.Bitset` bit per sealed slot, masked
  into the sealed search through each family's existing filter path
  (the tombstone is checked INSIDE the sealed search, before the merge,
  so delete-then-reinsert of an id is exact);
* queries fan out sealed + delta and merge through
  :func:`brute_force.knn_merge_parts` — the same select machinery the
  sharded path trusts for bit-identical merges.

Durability (docs/mutation.md): every mutation appends to a CRC32-framed
write-ahead log (:mod:`raft_tpu.core.wal`) and is fsynced BEFORE the
call returns — an acked write survives any crash. :func:`recover`
replays the WAL over the last good snapshot, truncating a torn tail at
the first bad frame (raising only on mid-log corruption) and rebuilding
the sealed segment from the snapshot corpus if its file fails its CRC.
The crash-injection harness (``faults`` kinds ``crash_point`` /
``wal_torn_tail``) kills the process at every named :data:`CRASH_POINTS`
site and drills exactly that contract.

Background merge (:meth:`~MutableIndex.merge`, hung off the
``SnapshotWriter`` maintenance tick via :meth:`~MutableIndex.maintenance`):
rebuilds sealed+delta into a fresh segment (CAGRA rebuilds via
``build_knn_graph`` warm-started from the surviving graph rows — the
PR 5 nn_descent warm-start path), checks the candidate with the
family's ``health()`` plus a sampled self-recall probe, pre-warms the
serving shapes, writes segment + snapshot + manifest atomically, flips
under the serve lock (zero downtime — searchers hold the
:class:`MutableIndex`, not the segment), and retires the old
generation. A merge that crashes, exceeds its deadline, or fails its
post-merge check is ABANDONED with the live index untouched; the
``mutable.merge`` circuit breaker (:mod:`raft_tpu.ops.guarded`) backs
repeated failures off instead of hot-looping the maintenance tick.

Mutations arriving DURING a merge are correct by construction: the WAL
is rotated at merge start (the manifest references both logs until the
flip), new writes land in the new log + the delta tail past the merge
watermark, and ids they touched are re-tombstoned in the flipped
segment — the same records a post-flip recovery would replay.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import events, faults, tracing, wal as wal_mod
from ..core.bitset import Bitset
from ..core.errors import CorruptIndexError, RaftError, expects
from ..core.serialize import fsync_dir, load_arrays, save_arrays
from ..distance.distance_types import DistanceType, canonical_metric, \
    is_min_close
from ..ops.guarded import guarded_call
from ..utils import env_float, env_int

__all__ = ["MutableIndex", "create", "recover", "health", "make_searcher",
           "ops_snapshot", "CRASH_POINTS", "MERGE_SITE"]

_MANIFEST = "MANIFEST"
_SERIAL_VERSION = 1

# the guarded background-merge breaker site (ops/guarded.POLICIES)
MERGE_SITE = "mutable.merge"

# every named process-death site the crash drill must cover
# (tests/test_mutable.py sweeps the source for faults.crash(...) probes
# and fails on a site missing from this tuple — an undrilled crash
# point is an untested recovery path)
CRASH_POINTS = (
    wal_mod.APPEND_SITE,          # mid-WAL-append (core/wal.py)
    "mutable.merge.build",        # mid-merge, nothing written yet
    "mutable.merge.pre_flip",     # new generation written, manifest old
    "mutable.merge.post_flip",    # manifest flipped, old gen not retired
)

_FAMILIES = ("brute_force", "ivf_flat", "ivf_pq", "cagra")

# live mutable indexes for the debugz "mutable" section (weak: dropping
# the index drops the entry; the sharded_ann._LIVE precedent)
_LIVE: "weakref.WeakSet[MutableIndex]" = weakref.WeakSet()


def _family_mod(family: str):
    from . import brute_force, cagra, ivf_flat, ivf_pq

    mods = {"brute_force": brute_force, "ivf_flat": ivf_flat,
            "ivf_pq": ivf_pq, "cagra": cagra}
    expects(family in mods, "unknown sealed family %r (one of %s)",
            family, "/".join(_FAMILIES))
    return mods[family]


def _family_params(mod, family: str, fparams: dict, mt, n: int):
    """A family IndexParams from the JSON-able ``family_params`` dict
    (unknown keys rejected loudly — a typo'd knob must not silently
    build a default segment). n_lists is clamped to the corpus."""
    if family == "brute_force":
        return None
    fields = {f.name for f in dataclasses.fields(mod.IndexParams)}
    bad = set(fparams) - fields
    expects(not bad, "unknown %s family_params: %s", family, sorted(bad))
    p = mod.IndexParams(**fparams)
    p.metric = mt
    if hasattr(p, "n_lists"):
        p.n_lists = max(1, min(p.n_lists, n))
    return p


def _pad_k(vals, ids, k: int, bad):
    """Pad a (m, k') top-k' block out to k columns (inf/-1 slots)."""
    pad = k - vals.shape[1]
    if pad <= 0:
        return vals, ids
    return (jnp.pad(vals, ((0, 0), (0, pad)), constant_values=bad),
            jnp.pad(ids, ((0, 0), (0, pad)), constant_values=-1))


class MutableIndex:
    """One mutable index: sealed segment + delta tier + tombstones + WAL.

    Construct via :meth:`create` (fresh directory) or :meth:`recover`
    (existing directory, crash-safe). All public methods are
    thread-safe: mutations and the merge flip serialize on one RLock,
    searches read a consistent view under it and dispatch outside it
    (the serve lock of the zero-downtime swap)."""

    # -- construction -----------------------------------------------------
    def __init__(self, path: str, family: str, metric: DistanceType,
                 dim: int, family_params: Optional[dict] = None):
        self.path = os.path.abspath(path)
        self.name = os.path.basename(self.path)
        self.family = family
        self.metric = metric
        self.dim = int(dim)
        self.family_params = dict(family_params or {})
        self._mod = _family_mod(family)
        self._lock = threading.RLock()
        # sealed state
        self._sealed = None                       # family Index | None
        self._sealed_ids = np.zeros(0, np.int64)  # slot -> external id
        self._sealed_vecs = np.zeros((0, self.dim), np.float32)
        self._slot_of: Dict[int, int] = {}
        self._alive = np.zeros(0, bool)           # False = tombstoned
        self._n_tomb = 0        # cleared _alive bits — kept as an O(1)
        #                         counter so the per-search view check
        #                         never scans the sealed mask under the
        #                         serve lock
        self._sealed_rev = 0
        self._sealed_cache: Optional[tuple] = None
        # delta state (capacity-padded so search shapes bucket)
        self._d_vecs = np.zeros((0, self.dim), np.float32)
        self._d_ids = np.zeros(0, np.int64)
        self._d_alive = np.zeros(0, bool)
        self._d_n = 0                             # used rows (incl. dead)
        self._d_live = 0                          # alive rows (counter)
        self._d_row_of: Dict[int, int] = {}
        self._delta_rev = 0
        self._delta_cache: Optional[tuple] = None
        # durability
        self._wal: Optional[wal_mod.WriteAheadLog] = None
        self._wal_names: List[str] = []
        self._gen = 0
        self._epoch = 0
        self._next_id = 0
        # merge machinery
        self._merging = False
        self._during: List[Tuple[str, np.ndarray]] = []
        self._last_merge: Optional[dict] = None
        self._last_shape: Optional[Tuple[int, int]] = None
        self._last_request: Tuple[object, dict] = (None, {})
        self._clock = time.monotonic
        self.merge_rows = env_int("RAFT_TPU_MUTABLE_MERGE_ROWS", 65536)
        self.merge_tomb_frac = env_float(
            "RAFT_TPU_MUTABLE_MERGE_TOMB_FRAC", 0.25)
        self.merge_deadline_s = env_float(
            "RAFT_TPU_MUTABLE_MERGE_DEADLINE_S", 0.0)
        self.merge_recall_floor = env_float(
            "RAFT_TPU_MUTABLE_MERGE_RECALL_FLOOR", 0.9)
        _LIVE.add(self)

    @classmethod
    @tracing.annotate("raft_tpu::mutable::create")
    def create(cls, path, dataset=None, ids=None, *,
               family: str = "brute_force", metric="sqeuclidean",
               family_params: Optional[dict] = None,
               dim: Optional[int] = None) -> "MutableIndex":
        """Create a fresh mutable index at directory ``path``.

        ``dataset`` (optional) seeds the sealed segment; ``ids`` are its
        external ids (default: row positions). An empty create
        (``dataset=None`` + ``dim=``) starts all-delta and seals on the
        first merge. ``family_params``: a plain JSON-able dict of the
        sealed family's IndexParams fields (persisted in the manifest so
        merges after :meth:`recover` rebuild the same segment shape).
        """
        path = os.fspath(path)
        os.makedirs(path, exist_ok=True)
        expects(not os.path.exists(os.path.join(path, _MANIFEST)),
                "mutable index already exists at %s (use recover)", path)
        if dataset is None:
            expects(dim is not None and dim > 0,
                    "empty create needs dim=")
            vecs = np.zeros((0, int(dim)), np.float32)
        else:
            vecs = np.asarray(dataset, np.float32)
            expects(vecs.ndim == 2, "dataset must be (n, d)")
        mt = canonical_metric(metric)
        self = cls(path, family, mt, vecs.shape[1], family_params)
        n = vecs.shape[0]
        if ids is None:
            sids = np.arange(n, dtype=np.int64)
        else:
            sids = np.asarray(ids, np.int64)
            expects(sids.shape == (n,), "ids must be (n,)")
            expects(np.unique(sids).size == n, "ids must be unique")
        expects(n == 0 or (sids.min() >= 0 and sids.max() < 2 ** 31),
                "external ids must fit int32")
        # construction is single-threaded, but the helpers below follow
        # the *_locked caller-holds-the-lock convention — hold the
        # (reentrant) serve lock so the discipline is uniform
        with self._lock:
            self._install_sealed_locked(
                self._build_segment(vecs) if n else None, sids, vecs)
            self._next_id = int(sids.max()) + 1 if n else 0
            self._gen = 1
            self._epoch = 1
            if self._sealed is not None:
                self._save_segment_locked(self._gen)
            self._save_snapshot(self._gen)
            w = wal_mod.WriteAheadLog.create(
                os.path.join(self.path, self._wal_name(self._epoch)))
            self._wal = w
            self._wal_names = [self._wal_name(self._epoch)]
            self._save_manifest_locked()
        return self

    @classmethod
    @tracing.annotate("raft_tpu::mutable::recover")
    def recover(cls, path) -> "MutableIndex":
        """Reopen ``path`` exactly as a restarted process would: load the
        manifest's generation, replay its WAL chain over the snapshot
        (torn tail truncated — see :mod:`raft_tpu.core.wal`), rebuild
        the sealed segment from the snapshot corpus if its file is
        corrupt, and remove orphaned files from an interrupted merge.
        Every acked mutation is visible afterwards; raises
        :class:`CorruptIndexError` only when *acked* state is damaged
        (mid-log corruption, unreadable manifest/snapshot)."""
        path = os.fspath(path)
        _, _, meta, _ = load_arrays(
            os.path.join(path, _MANIFEST), "mutable_manifest")
        fparams = json.loads(meta.get("family_params", "{}"))
        self = cls(path, meta["family"], DistanceType(meta["metric"]),
                   meta["dim"], fparams)
        # single-threaded construction, but the *_locked helpers assert
        # caller-holds-the-lock — hold the reentrant serve lock
        with self._lock:
            self._gen = int(meta["generation"])
            self._epoch = int(meta["epoch"])
            self._next_id = int(meta["next_id"])
            # snapshot: the merge-source corpus + external ids
            _, _, smeta, arrs = load_arrays(
                os.path.join(path, meta["snapshot"]), "mutable_snapshot")
            vecs = np.asarray(arrs["corpus"],
                              np.float32).reshape(-1, self.dim)
            sids = np.asarray(arrs["ids"], np.int64)
            sealed = None
            rebuilt = False
            if meta["segment"]:
                try:
                    sealed = self._load_segment(meta["segment"])
                except CorruptIndexError:
                    # the segment is derived state — the snapshot corpus
                    # is the durable source of truth, so rebuild instead
                    # of refusing to serve
                    sealed = (self._build_segment(vecs) if len(vecs)
                              else None)
                    rebuilt = True
            self._install_sealed_locked(sealed, sids, vecs)
            # WAL chain: every log the manifest references, oldest
            # first; only the LAST may carry a torn in-flight append
            self._wal_names = json.loads(meta["wals"])
            replayed = 0
            truncated = 0
            for i, wname in enumerate(self._wal_names):
                last = i == len(self._wal_names) - 1
                records, cut = wal_mod.replay(
                    os.path.join(path, wname), repair=last,
                    allow_torn_tail=last)
                truncated += cut
                for kind, rids, rvecs in records:
                    if kind == "upsert":
                        self._apply_upsert_locked(rids, rvecs)
                    else:
                        self._apply_delete_locked(rids)
                    replayed += 1
            self._wal = wal_mod.WriteAheadLog.open(
                os.path.join(path, self._wal_names[-1]))
            if rebuilt and self._sealed is not None:
                self._save_segment_locked(self._gen)
            gen = self._gen
        self._housekeep(meta)
        self._event("wal_recovered", generation=gen,
                    records=replayed, truncated_bytes=truncated,
                    segment_rebuilt=rebuilt)
        self._count("mutable.recoveries")
        return self

    # -- durable file helpers ---------------------------------------------
    def _wal_name(self, epoch: int) -> str:
        return f"wal-{epoch:06d}.log"

    def _seg_name(self, gen: int) -> str:
        return f"segment-{gen:06d}.idx"

    def _snap_name(self, gen: int) -> str:
        return f"snapshot-{gen:06d}.idx"

    def _save_segment_locked(self, gen: int) -> None:
        self._mod.save(self._sealed, os.path.join(self.path,
                                                  self._seg_name(gen)))

    def _save_segment_of(self, index, gen: int) -> None:
        self._mod.save(index, os.path.join(self.path, self._seg_name(gen)))

    def _load_segment(self, name: str):
        return self._mod.load(os.path.join(self.path, name))

    def _save_snapshot(self, gen: int, vecs=None, sids=None) -> None:
        if vecs is None:
            # defaulted only from locked/construction callers; the
            # off-lock merge path always passes its snapshot explicitly
            # lint: waive(unlocked-attr): locked/construction callers only
            vecs, sids = self._sealed_vecs, self._sealed_ids
        save_arrays(
            os.path.join(self.path, self._snap_name(gen)),
            "mutable_snapshot", _SERIAL_VERSION, {"generation": gen},
            {"corpus": vecs, "ids": sids})

    def _save_manifest_locked(self, gen: Optional[int] = None) -> None:
        g = self._gen if gen is None else gen
        save_arrays(
            os.path.join(self.path, _MANIFEST), "mutable_manifest",
            _SERIAL_VERSION,
            {"generation": g, "family": self.family,
             "metric": self.metric.value, "dim": self.dim,
             "epoch": self._epoch, "next_id": self._next_id,
             "segment": self._seg_name(g) if self._has_segment(g) else "",
             "snapshot": self._snap_name(g),
             "wals": json.dumps(self._wal_names),
             "family_params": json.dumps(self.family_params)}, {})

    def _has_segment(self, gen: int) -> bool:
        return os.path.exists(os.path.join(self.path, self._seg_name(gen)))

    def _housekeep(self, meta: dict) -> None:
        """Remove generation files the manifest does not reference —
        the orphans of a merge that crashed pre-flip (new gen written,
        never flipped) or post-flip (old gen never retired)."""
        keep = {_MANIFEST, meta["snapshot"], *json.loads(meta["wals"])}
        if meta["segment"]:
            keep.add(meta["segment"])
        for fn in os.listdir(self.path):
            if fn in keep:
                continue
            if fn.startswith(("segment-", "snapshot-", "wal-")):
                try:
                    os.unlink(os.path.join(self.path, fn))
                except OSError:
                    pass

    # -- telemetry --------------------------------------------------------
    def _event(self, kind: str, **details) -> None:
        try:
            events.record(kind, self.name, **details)
        except Exception:  # noqa: BLE001 - telemetry must not fail writes
            pass

    def _count(self, name: str, n: int = 1) -> None:
        try:
            from ..serve import metrics as _metrics

            _metrics.counter(name).inc(n)
        except Exception:  # noqa: BLE001
            pass

    # -- segment build / install ------------------------------------------
    def _build_segment(self, vecs: np.ndarray, warm=None):
        """Build a sealed family index over ``vecs`` (slots = row
        positions, so source ids ARE slots for every family)."""
        mod = self._mod
        n = len(vecs)
        if self.family == "brute_force":
            return mod.build(vecs, metric=self.metric,
                             dtype=self.family_params.get(
                                 "dtype", "float32"))
        p = _family_params(mod, self.family, self.family_params,
                           self.metric, n)
        if self.family == "cagra" and warm is not None:
            # merge rebuild: build_knn_graph warm-started from the
            # surviving rows of the previous graph (the PR 5 nn_descent
            # init_graph path; the exact route ignores the seed)
            from . import cagra

            d0 = min(p.intermediate_graph_degree, n - 1)
            degree = min(p.graph_degree, d0)
            knn = cagra.build_knn_graph(vecs, d0, self.metric, p.seed,
                                        algo=p.knn_graph_algo,
                                        nnd_rounds=p.nn_descent_niter,
                                        init_graph=warm)
            graph = cagra.optimize(knn, degree)
            # same seed-set policy as cagra.build — a warm rebuild must
            # not silently lose the covering seeds the first build had
            seeds = cagra.build_covering_seeds(vecs, p, self.metric)
            return cagra.Index(jnp.asarray(vecs), jnp.asarray(graph),
                               self.metric, seeds)
        return mod.build(vecs, p)

    def _warm_graph(self, new_ids: np.ndarray, sealed,
                    sealed_ids: np.ndarray) -> Optional[np.ndarray]:
        """Old sealed CAGRA graph remapped into new-slot space for the
        nn_descent warm start: surviving neighbors keep their edges,
        dead/unknown targets fall back to uniform random new slots.
        ``sealed``/``sealed_ids`` are the caller's under-lock snapshot —
        this runs off-lock on the merge thread and must not re-read the
        live attributes."""
        if self.family != "cagra" or sealed is None:
            return None
        g = np.asarray(sealed.graph)
        n_new = len(new_ids)
        if n_new < 2:
            return None
        slot2 = {int(e): s for s, e in enumerate(new_ids)}
        old2new = np.full(len(sealed_ids), -1, np.int32)
        for old_slot, ext in enumerate(sealed_ids):
            s = slot2.get(int(ext))
            if s is not None:
                old2new[old_slot] = s
        warm = np.full((n_new, g.shape[1]), -1, np.int32)
        mapped = old2new[np.clip(g, 0, len(old2new) - 1)]
        keep = old2new >= 0                 # surviving old rows
        warm[old2new[keep]] = mapped[keep]
        rng = np.random.default_rng(0)
        fill = rng.integers(0, n_new, warm.shape, dtype=np.int64)
        warm = np.where(warm >= 0, warm, fill).astype(np.int32)
        # self-edges are dropped by the builder; good enough as seeds
        return warm

    def _install_sealed_locked(self, sealed, sids: np.ndarray,
                        vecs: np.ndarray) -> None:
        self._sealed = sealed
        self._sealed_ids = np.asarray(sids, np.int64)
        self._sealed_vecs = np.asarray(vecs, np.float32)
        self._slot_of = {int(e): s for s, e in enumerate(self._sealed_ids)}
        self._alive = np.ones(len(self._sealed_ids), bool)
        self._n_tomb = 0
        self._sealed_rev += 1
        self._sealed_cache = None

    # -- in-memory mutation application (shared by live path + replay) ----
    def _ensure_delta_cap_locked(self, need: int) -> None:
        cap = len(self._d_ids)
        if need <= cap:
            return
        new_cap = 64
        while new_cap < need:
            new_cap *= 2
        v = np.zeros((new_cap, self.dim), np.float32)
        i = np.full(new_cap, -1, np.int64)
        a = np.zeros(new_cap, bool)
        v[:self._d_n] = self._d_vecs[:self._d_n]
        i[:self._d_n] = self._d_ids[:self._d_n]
        a[:self._d_n] = self._d_alive[:self._d_n]
        self._d_vecs, self._d_ids, self._d_alive = v, i, a

    def _apply_upsert_locked(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        vecs = np.asarray(vecs, np.float32)
        self._ensure_delta_cap_locked(self._d_n + len(ids))
        for j, ext in enumerate(ids):
            ext = int(ext)
            slot = self._slot_of.get(ext)
            if slot is not None and self._alive[slot]:
                self._alive[slot] = False       # sealed copy superseded
                self._n_tomb += 1
            old = self._d_row_of.get(ext)
            if old is not None:
                self._d_alive[old] = False      # older delta copy dies
                self._d_live -= 1
            row = self._d_n
            self._d_vecs[row] = vecs[j]
            self._d_ids[row] = ext
            self._d_alive[row] = True
            self._d_row_of[ext] = row
            self._d_n += 1
            self._d_live += 1
            self._next_id = max(self._next_id, ext + 1)
        if self._merging:
            self._during.append(("upsert", ids.copy()))
        self._sealed_cache = None
        self._delta_cache = None

    def _apply_delete_locked(self, ids: np.ndarray) -> int:
        ids = np.asarray(ids, np.int64)
        found = 0
        for ext in ids:
            ext = int(ext)
            hit = False
            slot = self._slot_of.get(ext)
            if slot is not None and self._alive[slot]:
                self._alive[slot] = False
                self._n_tomb += 1
                hit = True
            row = self._d_row_of.pop(ext, None)
            if row is not None and self._d_alive[row]:
                self._d_alive[row] = False
                self._d_live -= 1
                hit = True
            found += hit
        if self._merging:
            self._during.append(("delete", ids.copy()))
        self._sealed_cache = None
        self._delta_cache = None
        return found

    # -- public mutation API ----------------------------------------------
    @tracing.annotate("raft_tpu::mutable::upsert")
    def upsert(self, ids, vectors=None) -> np.ndarray:
        """Insert-or-replace rows; returns the external ids used.

        ``ids=None`` auto-assigns sequential ids. Durability: the
        mutation is WAL-appended and fsynced BEFORE this returns — the
        return IS the ack. An id present in the sealed segment is
        tombstoned there (the delta copy serves); an id already in the
        delta replaces its row. Trace-stamped ``upsert`` flight event +
        ``mutable.upserts`` counter."""
        if vectors is None:            # upsert(vectors) convenience form
            ids, vectors = None, ids
        vecs = np.asarray(vectors, np.float32)
        expects(vecs.ndim == 2 and vecs.shape[1] == self.dim,
                "vectors must be (m, %d), got %s", self.dim, vecs.shape)
        with self._lock:
            if ids is None:
                ids = np.arange(self._next_id, self._next_id + len(vecs),
                                dtype=np.int64)
            else:
                ids = np.asarray(ids, np.int64)
                expects(ids.shape == (len(vecs),), "ids must be (m,)")
                expects(len(ids) == 0
                        or (ids.min() >= 0 and ids.max() < 2 ** 31),
                        "external ids must fit int32")
            self._wal.append("upsert", ids, vecs)   # durable before ack
            self._apply_upsert_locked(ids, vecs)
        self._event("upsert", rows=int(len(ids)),
                    delta_rows=self.delta_rows)
        self._count("mutable.upserts", int(len(ids)))
        return ids

    @tracing.annotate("raft_tpu::mutable::delete")
    def delete(self, ids) -> int:
        """Delete rows by external id; returns how many ids were
        present. Durable before return (see :meth:`upsert`); absent ids
        are a no-op, not an error. Trace-stamped ``delete`` flight event
        + ``mutable.deletes`` counter."""
        ids = np.atleast_1d(np.asarray(ids, np.int64))
        with self._lock:
            self._wal.append("delete", ids)
            found = self._apply_delete_locked(ids)
        self._event("delete", rows=int(len(ids)), found=found,
                    tombstones=self.tombstones)
        self._count("mutable.deletes", int(len(ids)))
        return found

    # -- introspection ----------------------------------------------------
    # The properties below are GIL-atomic single-reference/int peeks for
    # the ops surfaces (events, debugz, should_merge's own locked read);
    # a one-mutation-stale value is as good as a fresh one there, and
    # they must stay callable from telemetry paths that already hold (or
    # must never wait on) the serve lock.
    @property
    def sealed_index(self):
        """The live sealed family index (None before the first seal)."""
        # lint: waive(unlocked-attr): atomic reference peek, ops surface
        return self._sealed

    @property
    def sealed_rows(self) -> int:
        # lint: waive(unlocked-attr): atomic counter peek, ops surface
        return len(self._alive) - self._n_tomb

    @property
    def delta_rows(self) -> int:
        # lint: waive(unlocked-attr): atomic counter peek, ops surface
        return self._d_live

    @property
    def tombstones(self) -> int:
        # lint: waive(unlocked-attr): atomic counter peek, ops surface
        return self._n_tomb

    @property
    def size(self) -> int:
        """Live row count across tiers."""
        return self.sealed_rows + self.delta_rows

    @property
    def generation(self) -> int:
        # lint: waive(unlocked-attr): atomic counter peek, ops surface
        return self._gen

    def wal_bytes(self) -> int:
        with self._lock:
            return self._wal.size_bytes() if self._wal else 0

    # -- search -----------------------------------------------------------
    def _sealed_view_locked(self):
        """(index, filter bitset|None, ids_dev) under the lock; cached
        until a mutation or flip invalidates it."""
        if self._sealed is None or self.sealed_rows == 0:
            return None
        if self._sealed_cache is None:
            filt = (None if self._n_tomb == 0
                    else Bitset.from_mask(jnp.asarray(self._alive)))
            ids_dev = jnp.asarray(self._sealed_ids, jnp.int32)
            self._sealed_cache = (self._sealed, filt, ids_dev)
        return self._sealed_cache

    def _delta_view_locked(self):
        """(brute index over the capacity-padded delta, alive bitset,
        ids_dev, cap) — rebuilt only after a mutation, and shaped by the
        power-of-two capacity so repeated searches hit the same
        executables."""
        from . import brute_force

        if self._d_live == 0:
            return None
        if self._delta_cache is None:
            cap = len(self._d_ids)
            idx = brute_force.build(self._d_vecs, metric=self.metric)
            filt = Bitset.from_mask(jnp.asarray(self._d_alive))
            ids_dev = jnp.asarray(self._d_ids, jnp.int32)
            self._delta_cache = (idx, filt, ids_dev, cap)
        return self._delta_cache

    def _search_sealed(self, sealed, q, k, params, filt, opts):
        if self.family == "brute_force":
            return self._mod.search(sealed, q, k, filter=filt, **opts)
        return self._mod.search(sealed, q, k, params, filter=filt, **opts)

    @tracing.annotate("raft_tpu::mutable::search")
    def search(self, queries, k: int, params=None, **opts):
        """k nearest live rows → (distances (m, k), indices (m, k)) with
        EXTERNAL ids. Fans out sealed (tombstones masked in-search via
        the family filter path) + delta (dead rows masked the same way)
        and merges via :func:`brute_force.knn_merge_parts`. ``params``:
        the sealed family's SearchParams (ignored for brute_force);
        ``opts`` forwards to the sealed family search — except
        ``filter``, which is rejected: the tombstone bitset owns the
        sealed filter slot (and a user bitset would be indexed by
        internal slots, not the external ids this API speaks).
        ``delete`` is the supported exclusion path."""
        from . import brute_force

        expects("filter" not in opts,
                "mutable search does not accept a filter — tombstones "
                "own the sealed filter slot; use delete() to exclude "
                "rows")
        q = jnp.asarray(queries, jnp.float32)
        expects(q.ndim == 2 and q.shape[1] == self.dim,
                "queries must be (m, %d), got %s", self.dim, q.shape)
        with self._lock:
            sview = self._sealed_view_locked()
            dview = self._delta_view_locked()
            # what a post-flip request will look like: _prewarm compiles
            # THIS executable (shape + params + engine opts) against the
            # replacement segment, so the flip costs zero compiles for
            # the traffic actually being served
            self._last_shape = (int(q.shape[0]), int(k))
            self._last_request = (params, dict(opts))
            phys = len(self._alive) + len(self._d_ids)
        expects(sview is not None or dview is not None or phys > 0,
                "mutable index is empty")
        select_min = is_min_close(self.metric)
        bad = jnp.inf if select_min else -jnp.inf
        if sview is None and dview is None:
            # rows exist but every one is tombstoned: same (+inf, -1)
            # sentinel padding the immutable families return when a
            # filter leaves fewer than k survivors
            return (jnp.full((q.shape[0], k), bad, jnp.float32),
                    jnp.full((q.shape[0], k), -1, jnp.int32))
        from ..ops import filter_policy

        parts = []
        # tombstone masks are internal shape-stable filters: the views
        # above are capacity-padded precisely so repeated searches reuse
        # executables, and the adaptive crossover would re-gather the
        # survivors into a fresh shape after every delete (one compile
        # per mutation) — suspend it; the free prune stays
        with filter_policy.suspended():
            if sview is not None:
                sealed, filt, ids_dev = sview
                ks = min(k, sealed.size)
                d, i = self._search_sealed(sealed, q, ks, params, filt,
                                           opts)
                ext = jnp.where(i >= 0,
                                jnp.take(ids_dev, jnp.clip(i, 0, None)), -1)
                parts.append(_pad_k(d, ext, k, bad))
            if dview is not None:
                didx, dfilt, dids_dev, cap = dview
                kd = min(k, cap)
                d, i = brute_force.search(didx, q, kd, filter=dfilt)
                ext = jnp.where(i >= 0,
                                jnp.take(dids_dev, jnp.clip(i, 0, None)),
                                -1)
                parts.append(_pad_k(d, ext, k, bad))
        if len(parts) == 1:
            return parts[0]
        return brute_force.knn_merge_parts(
            jnp.stack([p[0] for p in parts]),
            jnp.stack([p[1] for p in parts]), select_min=select_min)

    # -- background merge -------------------------------------------------
    def should_merge(self) -> bool:
        with self._lock:
            if self._merging:
                return False
            n_sealed = len(self._alive)
            tomb_frac = (self.tombstones / n_sealed) if n_sealed else 0.0
            return (self.delta_rows >= self.merge_rows
                    or tomb_frac >= self.merge_tomb_frac)

    def maintenance(self) -> Optional[str]:
        """The ``SnapshotWriter(hooks=[...])`` tick: merge when due,
        through the ``mutable.merge`` breaker (an abandoned merge backs
        off instead of re-failing every tick)."""
        if not self.should_merge():
            return None
        return self.merge()

    def merge(self, deadline_s: Optional[float] = None) -> str:
        """Fold delta + tombstones into a fresh sealed generation.

        Returns ``"committed"``, ``"backoff"`` (the breaker is open from
        an earlier failure — no work attempted this tick), or
        ``"in_progress"``. A failing merge raises inside the guard (so
        the breaker opens), records ``merge_abandoned`` and leaves the
        live index untouched."""
        return guarded_call(
            MERGE_SITE,
            lambda: self._merge_once(deadline_s),
            lambda: "backoff")

    def _check_deadline(self, t0: float, deadline_s: float,
                        phase: str) -> None:
        if deadline_s and deadline_s > 0:
            el = self._clock() - t0
            if el > deadline_s:
                raise RaftError(
                    f"merge deadline exceeded after {phase} "
                    f"({el:.1f}s > {deadline_s:.1f}s)")

    def _post_merge_check(self, index, vecs: np.ndarray,
                          ids: np.ndarray) -> dict:
        """The candidate segment must prove itself BEFORE the flip: the
        family health report must render, and sampled recall against an
        exact brute-force reference over the merge snapshot must clear
        the floor — a structurally broken or low-recall rebuild is
        abandoned, not served. Recall is scored on DISTANCES (returned
        k-th within epsilon of the true k-th, the ann-benchmarks tie
        rule), not returned ids: duplicate vectors tie arbitrarily in
        id, and an id-based self-hit would deterministically fail a
        dedup-free corpus (and is simply wrong under InnerProduct,
        where a row's best match need not be itself)."""
        from . import brute_force
        from .brute_force import health_sample_rows

        rep = self._mod.health(index)
        rows = health_sample_rows(len(vecs), 64)
        if rows.size == 0:
            return {"health_family": rep.get("family"),
                    "merge_recall": 1.0}
        q = jnp.asarray(vecs[rows])
        kc = min(10, len(vecs))
        ref_d, _ = brute_force.search(
            brute_force.build(vecs, metric=self.metric), q, kc)
        cand_d, _ = self._search_sealed(index, q, kc, None, None, {})
        ref_d, cand_d = np.asarray(ref_d), np.asarray(cand_d)
        kth = ref_d[:, -1:]
        eps = 1e-5 + 1e-5 * np.abs(kth)
        if is_min_close(self.metric):
            ok = cand_d <= kth + eps
        else:
            ok = cand_d >= kth - eps
        recall = float(ok.mean())
        if recall < self.merge_recall_floor:
            raise RaftError(
                f"post-merge recall {recall:.3f} below floor "
                f"{self.merge_recall_floor:.3f}")
        return {"health_family": rep.get("family"),
                "merge_recall": recall}

    def _prewarm(self, index) -> None:
        """Pre-warm the replacement segment at the last served shape AND
        params (the serve/warmup.py role, scoped to the swap): the
        executable compiled here is the one the first post-flip request
        dispatches, non-default SearchParams included. ``res`` is
        dropped (a deadline belongs to a request, not a warmup); no
        filter can appear — ``search`` rejects user filters, and a
        fresh merge has no tombstones, so the immediate post-flip trace
        carries filter=None exactly like this warmup."""
        with self._lock:
            # one hold for BOTH: a racing request could otherwise leave
            # a shape from one request paired with another's params
            shape, request = self._last_shape, self._last_request
        if shape is None:
            return
        m, k = shape
        k = min(k, max(1, index.size))
        params, opts = request
        opts = {kk: v for kk, v in opts.items() if kk != "res"}
        out = self._search_sealed(
            index, jnp.zeros((m, self.dim), jnp.float32), k, params,
            None, opts)
        # NO unconditional sync (ISSUE 12 hot-path audit): the compile —
        # the stall _prewarm exists to pre-pay — happens synchronously at
        # the dispatch above; waiting for the warm EXECUTION would only
        # serialize the serve path behind device time (post-flip requests
        # queue behind it on-device either way). Like the batcher's
        # device probe, a sync happens only on the telemetry sample so
        # the warm execution's device wall stays observable.
        try:
            rate = tracing.sample_rate(None)
        except Exception:  # noqa: BLE001 - a malformed knob is
            rate = 0.0     # telemetry; it must never fail the merge
        if rate > 0:
            tick = getattr(self, "_prewarm_tick", 0)
            self._prewarm_tick = tick + 1
            if tick % max(1, math.ceil(1.0 / rate)) == 0:
                t0 = self._clock()
                # deliberately OUTSIDE any swallow: a sampled probe that
                # surfaces a real device-side execution failure must
                # abandon the merge (the pre-ISSUE-12 gate), not flip a
                # segment whose serving shape cannot execute. Unsampled
                # ticks trade that detection for the no-sync mandate —
                # the post-flip breakers/sentinel own it there.
                jax.block_until_ready(jax.tree_util.tree_leaves(out))
                try:
                    from ..serve import metrics as _metrics

                    _metrics.default_registry.histogram(
                        "mutable.prewarm.device_s").observe(
                        self._clock() - t0)
                except Exception:  # noqa: BLE001 - telemetry must not
                    pass           # break the merge

    def _merge_once(self, deadline_s: Optional[float]) -> str:
        t0 = self._clock()
        deadline_s = (self.merge_deadline_s if deadline_s is None
                      else deadline_s)
        old_wal = None
        started = False          # did THIS call claim the merge?
        try:
            # ONE lock hold from the merging-flag set through the
            # watermark capture: a mutation CANNOT slip between them —
            # it either lands pre-watermark with _merging still False
            # (merged into the new segment, not in _during) or
            # post-watermark with _merging True (delta tail + _during +
            # the rotated log). A gap here silently loses acked writes:
            # pre-watermark AND in _during means the flip re-tombstones
            # a row the compaction just dropped.
            with self._lock:
                if self._merging:
                    return "in_progress"
                # rotate the WAL FIRST: mutations arriving during the
                # merge land in the new log, and the manifest references
                # BOTH until the flip — a crash anywhere in the merge
                # replays everything
                self._epoch += 1
                new_wal_name = self._wal_name(self._epoch)
                try:
                    # a failed append may have left torn un-acked bytes
                    # past the last good frame; a rotated-out log is
                    # replayed with allow_torn_tail=False, so it must be
                    # whole-frames-only BEFORE anything references it as
                    # a closed log
                    self._wal.seal()
                    new_wal = wal_mod.WriteAheadLog.create(
                        os.path.join(self.path, new_wal_name))
                    self._wal_names = self._wal_names + [new_wal_name]
                    self._save_manifest_locked()        # still the OLD generation
                except BaseException:
                    # rotation failed mid-way: roll the in-memory view
                    # back to what the on-disk manifest references
                    self._epoch -= 1
                    if self._wal_names and \
                            self._wal_names[-1] == new_wal_name:
                        self._wal_names = self._wal_names[:-1]
                    try:
                        os.unlink(os.path.join(self.path, new_wal_name))
                    except OSError:
                        pass
                    raise
                self._merging = True
                started = True
                self._during = []
                old_wal, self._wal = self._wal, new_wal
                watermark = self._d_n
                # merge snapshot: live rows as of now
                sa = self._alive
                da = self._d_alive[:watermark]
                vecs = np.concatenate(
                    [self._sealed_vecs[sa], self._d_vecs[:watermark][da]])
                ids = np.concatenate(
                    [self._sealed_ids[sa], self._d_ids[:watermark][da]])
                gen0, gen2 = self._gen, self._gen + 1
                # off-lock phases below must not re-read live sealed
                # state: snapshot what the warm start needs here
                sealed0, sealed_ids0 = self._sealed, self._sealed_ids
            self._event("merge_started", generation=gen0,
                        rows=int(len(ids)), delta_rows=int(da.sum()),
                        tombstones=self.tombstones)
            hook = getattr(self, "_after_snapshot_hook", None)
            if hook is not None:
                hook()                    # test seam: mutate mid-merge
            faults.crash("mutable.merge.build")
            warm = self._warm_graph(ids, sealed0, sealed_ids0)
            new_sealed = (self._build_segment(vecs, warm=warm)
                          if len(vecs) else None)
            self._check_deadline(t0, deadline_s, "build")
            check = {}
            if new_sealed is not None:
                check = self._post_merge_check(new_sealed, vecs, ids)
                self._prewarm(new_sealed)
            self._check_deadline(t0, deadline_s, "check")
            # persist the new generation (orphans until the flip)
            if new_sealed is not None:
                self._save_segment_of(new_sealed, gen2)
            self._save_snapshot(gen2, vecs, ids)
            faults.crash("mutable.merge.pre_flip")
            # THE FLIP: one atomic manifest replace moves recovery from
            # (gen0 + both wals) to (gen2 + the rotated wal)
            with self._lock:
                old_names = (self._wal_names[:-1],
                             self._seg_name(gen0), self._snap_name(gen0))
                self._wal_names = self._wal_names[-1:]
                self._gen = gen2
                self._save_manifest_locked()
            faults.crash("mutable.merge.post_flip")
            with self._lock:            # in-memory flip, under serve lock
                during = self._during
                self._during = []
                self._install_sealed_locked(new_sealed, ids, vecs)
                # re-apply mutations that raced the build: any touched
                # id's new sealed slot is stale (delta has newer or it
                # was deleted) — identical to what a WAL replay does
                for _kind, dids in during:
                    for ext in dids:
                        slot = self._slot_of.get(int(ext))
                        if slot is not None and self._alive[slot]:
                            self._alive[slot] = False
                            self._n_tomb += 1
                # compact the delta: merged rows drop, the tail (rows
                # born during the merge) survives with its flags
                tail_v = self._d_vecs[watermark:self._d_n].copy()
                tail_i = self._d_ids[watermark:self._d_n].copy()
                tail_a = self._d_alive[watermark:self._d_n].copy()
                self._d_vecs = np.zeros((0, self.dim), np.float32)
                self._d_ids = np.zeros(0, np.int64)
                self._d_alive = np.zeros(0, bool)
                self._d_n = 0
                self._d_live = 0
                self._d_row_of = {}
                self._delta_cache = None
                if len(tail_i):
                    self._ensure_delta_cap_locked(len(tail_i))
                    self._d_vecs[:len(tail_i)] = tail_v
                    self._d_ids[:len(tail_i)] = tail_i
                    self._d_alive[:len(tail_i)] = tail_a
                    self._d_n = len(tail_i)
                    self._d_live = int(tail_a.sum())
                    self._d_row_of = {
                        int(e): r for r, e in enumerate(tail_i)
                        if tail_a[r]}
                self._merging = False
            # retire the old generation (failure is cosmetic — recovery
            # housekeeps orphans)
            try:
                old_wal.close()
                for fn in (*old_names[0], old_names[1], old_names[2]):
                    p = os.path.join(self.path, fn)
                    if os.path.exists(p):
                        os.unlink(p)
                fsync_dir(self.path)
            except OSError:
                pass
            dur = round(self._clock() - t0, 3)
            self._last_merge = {"verdict": "committed",
                                "generation": gen2, "rows": int(len(ids)),
                                "dur_s": dur, **check}
            self._event("merge_committed", generation=gen2,
                        rows=int(len(ids)), dur_s=dur, **check)
            self._count("mutable.merges.committed")
            return "committed"
        except Exception as e:
            # ABANDON: live index untouched (the rotated WAL + manifest
            # double-reference keep recovery correct); re-raise so the
            # mutable.merge breaker opens and backs the tick off.
            # InjectedCrash (BaseException) deliberately skips this —
            # a dead process runs no abandon handler.
            with self._lock:
                self._merging = False
                self._during = []
                gen_now = self._gen
            if old_wal is not None:
                # the rotated-out log stays ON DISK (the manifest still
                # references it); only the handle closes — nothing will
                # append to it again
                try:
                    old_wal.close()
                except OSError:
                    pass
            self._last_merge = {"verdict": "abandoned",
                                "reason": f"{type(e).__name__}: {e}",
                                "dur_s": round(self._clock() - t0, 3)}
            self._event("merge_abandoned", generation=gen_now,
                        error=e)
            self._count("mutable.merges.abandoned")
            if isinstance(e, faults.InjectedFault):
                # an injected io_error genuinely abandoned this merge —
                # rewrap so the breaker treats it like any other merge
                # failure (guarded_call handles bare InjectedFault as a
                # per-call kernel simulation that must not move the
                # breaker; a merge that did not commit must)
                raise RaftError(f"merge abandoned: {e}") from e
            raise
        finally:
            # InjectedCrash safety net: the simulated-death object is
            # discarded by the drill, but never leave a live object
            # wedged mid-merge. ONLY the call that claimed the merge may
            # clear the flag — the "in_progress" early return must not
            # clobber the in-flight merge's flag (raced mutations would
            # skip _during and survive the flip as stale sealed copies)
            if started:
                with self._lock:
                    self._merging = False

    # -- ops surface ------------------------------------------------------
    def ops_entry(self) -> dict:
        with self._lock:
            ent = {
                "family": self.family, "generation": self._gen,
                "sealed_rows": self.sealed_rows,
                "delta_rows": self.delta_rows,
                "tombstones": self.tombstones,
                "wal_bytes": self._wal.size_bytes() if self._wal else 0,
                "merging": self._merging,
            }
            if self._last_merge is not None:
                ent["last_merge"] = dict(self._last_merge)
            return ent


def create(path, dataset=None, ids=None, **kw) -> MutableIndex:
    """Module-level alias of :meth:`MutableIndex.create`."""
    return MutableIndex.create(path, dataset, ids, **kw)


def recover(path) -> MutableIndex:
    """Module-level alias of :meth:`MutableIndex.recover`."""
    return MutableIndex.recover(path)


def health(index: MutableIndex, sample: int = 256) -> dict:
    """Mutable-tier health report (docs/observability.md "Quality"):
    the tier decomposition plus the sealed family's own report."""
    rep = {**index.ops_entry(), "family": "mutable",
           "sealed_family": index.family, "n": index.size,
           "dim": index.dim, "metric": index.metric.name}
    sealed = index.sealed_index
    if sealed is not None:
        try:
            rep["sealed"] = index._mod.health(sealed, sample=sample)
        except Exception as e:  # noqa: BLE001 - one bad segment must not
            rep["sealed"] = {"error": f"{type(e).__name__}: {e}"}
    return rep


def make_searcher(index: MutableIndex, params=None, **opts):
    """Stable batchable signature for the serving runtime: returns
    ``fn(queries, k, res=None) -> (distances, indices)``. The closure
    holds the :class:`MutableIndex`, not a segment — a background merge
    flips the sealed generation under the serve lock and the very next
    call serves it (zero downtime; the replacement shapes were
    pre-warmed before the flip)."""

    def _fn(queries, k, res=None):
        return index.search(queries, k, params, **opts)

    return _fn


def ops_snapshot() -> dict:
    """Per-index mutable-tier state for the debugz ``mutable`` section:
    delta rows, tombstone count, WAL bytes, last merge verdict."""
    out: Dict[str, dict] = {}
    live: List[MutableIndex] = []
    for _ in range(4):
        try:
            live = list(_LIVE)
            break
        except RuntimeError:     # registration race (sharded precedent)
            continue
    for idx in live:
        key = idx.name
        if key in out:
            key = f"{key}@{id(idx):x}"
        try:
            out[key] = idx.ops_entry()
        except Exception as e:  # noqa: BLE001 - surface must render
            out[key] = {"error": f"{type(e).__name__}: {e}"}
    return {"indexes": out}
