"""Host-RAM cold tier for IVF lists: the beyond-HBM rung of the ladder.

The paper targets 100M–1B-row indexes; a single host's HBM does not
hold 100M×128 f32 lists next to a serving workload. DiskANN (Subramanya
et al., 2019) solves the same problem one tier further out (SSD); here
the cheap tier is **host RAM over PCIe**: lists past an HBM budget
(``RAFT_TPU_HBM_BUDGET_GB``) stay on the host and are double-buffered
onto the device per probed-list batch, while the hottest lists — ranked
by measured probe frequency over a query sample — stay resident.

Mechanics (family-agnostic; ivf_flat/ivf_pq wire their own scorers):

* :func:`plan_hot_cold` picks the resident set: lists sorted by probe
  frequency per byte, admitted until the budget is spent. With no
  sample, list size stands in for frequency (under near-uniform query
  traffic a list's probe probability tracks its share of the corpus).
* :class:`HostTier` holds the cold rows as dense host numpy arrays,
  pre-partitioned into fixed-shape CHUNKS (≤ ``chunk_rows`` rows and
  ≤ ``chunk_lists`` lists each, padded to identical shapes) so every
  chunk upload hits ONE compiled scan executable — the same
  corpus-resident tiling discipline the fused kernels use for HBM,
  applied across PCIe.
* :meth:`HostTier.stream` walks only the chunks the batch actually
  probed and keeps the NEXT chunk's ``jax.device_put`` in flight while
  the current chunk computes (two-deep, the serve/batcher
  double-buffering pattern) — PCIe upload hides behind the scan.
* Cold-list scan results merge with the resident search through
  ``knn_merge_parts`` — per-list kernel results are bit-identical to
  the fully-resident scan (same kernel, same per-list row order), so
  on distinct-valued corpora the merged top-k is bit-identical to the
  resident path; equal-distance ties may order differently across the
  hot/cold boundary (the same caveat query chunking already carries).

Search-time streaming is EAGER-only (host arrays cannot ride a jit
trace); serving dispatch is eager, so this is the serving path's
contract already. The scan of each streamed chunk runs behind the
``ivf.host_stream`` breaker with an XLA rescore of the same chunk as
the fallback — a kernel failure costs arithmetic parity with the
resident scan, never the request.
"""
from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import env_float

__all__ = ["HostTier", "budget_bytes", "plan_hot_cold", "build_tier",
           "probe_frequency"]

# budgets that already flight-recorded a ``host_tier_armed`` activation
# (one event per distinct armed value, not one per budget_bytes() call —
# the planner re-reads the budget on every re-plan)
_armed_seen: set = set()


def budget_bytes(budget_gb: Optional[float] = None) -> int:
    """HBM budget for one index's list data: the explicit argument, else
    ``RAFT_TPU_HBM_BUDGET_GB``, else 0 (no budget → no host tier).

    A malformed env value is a LOUD no-op: it parses through
    :func:`raft_tpu.utils.env_float` (the never-crash operator-knob
    contract) but emits a ``RuntimeWarning`` instead of silently
    disabling the budget — an over-HBM index with a typo'd budget would
    otherwise OOM in prod with the operator convinced a tier was armed.
    Any budget that actually arms a tier (> 0) flight-records one
    ``host_tier_armed`` event per distinct value, so debugz shows
    whether the ladder's beyond-HBM rung is live."""
    source = "arg"
    if budget_gb is None:
        raw = os.environ.get("RAFT_TPU_HBM_BUDGET_GB", "")
        if not raw:
            return 0
        source = "env"
        try:
            float(raw)
        except ValueError:
            warnings.warn(
                f"malformed RAFT_TPU_HBM_BUDGET_GB={raw!r} (not a float): "
                "HBM budget DISABLED, no host tier will be armed",
                RuntimeWarning, stacklevel=2)
            return 0
        budget_gb = env_float("RAFT_TPU_HBM_BUDGET_GB", 0.0)
    b = int(float(budget_gb) * (1 << 30))
    if b > 0 and b not in _armed_seen:
        _armed_seen.add(b)
        try:
            from ..core import events

            events.record("host_tier_armed", "host_stream.budget",
                          budget_gb=float(budget_gb), budget_bytes=b,
                          source=source)
        except Exception:  # noqa: BLE001 - telemetry must not fail a plan
            pass
    return b


def probe_frequency(probed: np.ndarray, n_lists: int) -> np.ndarray:
    """(m, p) probed list ids over a query sample → per-list probe
    counts (the pinning signal)."""
    flat = np.asarray(probed).reshape(-1)
    return np.bincount(flat[(flat >= 0) & (flat < n_lists)],
                       minlength=n_lists).astype(np.int64)


def plan_hot_cold(list_sizes: np.ndarray, row_bytes: float,
                  budget: int, probe_freq: Optional[np.ndarray] = None
                  ) -> np.ndarray:
    """(n_lists,) bool hot mask: admit lists by probe frequency per byte
    until the budget is spent. Frequency defaults to the list size
    itself (≈ uniform-traffic probe probability)."""
    sizes = np.asarray(list_sizes, np.int64)
    freq = (sizes.astype(np.float64) if probe_freq is None
            else np.asarray(probe_freq, np.float64))
    bytes_per = np.maximum(sizes * row_bytes, 1.0)
    # value density: probes served per resident byte; empty lists are
    # free to keep (zero bytes of rows) and sort first
    order = np.argsort(-(freq / bytes_per), kind="stable")
    hot = np.zeros(len(sizes), bool)
    spent = 0
    for li in order:
        b = int(sizes[li] * row_bytes)
        if spent + b <= budget or sizes[li] == 0:
            hot[li] = True
            spent += b
    return hot


@dataclasses.dataclass
class _Chunk:
    lists: np.ndarray        # global list ids in this chunk
    offsets: np.ndarray      # (chunk_lists,) local row offsets (padded)
    sizes: np.ndarray        # (chunk_lists,) local sizes (0 on pad slots)
    arrays: Dict[str, np.ndarray]   # padded host arrays, chunk-local rows


class HostTier:
    """Cold-list host tier: dense host arrays pre-cut into fixed-shape
    streaming chunks, plus the global→chunk-local routing tables."""

    def __init__(self, chunks: List[_Chunk], chunk_of: np.ndarray,
                 local_of: np.ndarray, lmax: int, chunk_rows: int,
                 chunk_lists: int, cold_rows: int, host_bytes: int,
                 device_bytes_saved: int):
        self.chunks = chunks
        self.chunk_of = chunk_of       # (n_lists,) int32, -1 = resident
        self.local_of = local_of       # (n_lists,) int32 slot in chunk
        self.lmax = int(lmax)          # max cold list size (static)
        self.chunk_rows = int(chunk_rows)
        self.chunk_lists = int(chunk_lists)
        self.cold_rows = int(cold_rows)
        self.host_bytes = int(host_bytes)
        self.device_bytes_saved = int(device_bytes_saved)
        self.probe_counts = np.zeros(len(chunk_of), np.int64)
        self.streamed_chunks = 0
        self.streamed_bytes = 0
        # family-filled per-chunk side arrays (e.g. ivf_pq's chunk-local
        # rotated centers) — uploaded with the chunk's row arrays
        self.extras: List[Dict[str, np.ndarray]] = [{} for _ in chunks]

    @property
    def n_cold_lists(self) -> int:
        return int((self.chunk_of >= 0).sum())

    def cold_probed(self, probed: np.ndarray) -> np.ndarray:
        """Chunk ids touched by this batch's probes, ascending."""
        self.probe_counts += probe_frequency(probed, len(self.chunk_of))
        cids = self.chunk_of[probed.reshape(-1)]
        return np.unique(cids[cids >= 0])

    def local_probed(self, probed: np.ndarray, ci: int) -> np.ndarray:
        """(m, p) global probed ids → chunk-local ids; probes outside
        this chunk land on the dead pad slot (size 0 — the scan
        kernel's dead-group gate skips them)."""
        in_chunk = self.chunk_of[probed] == ci
        return np.where(in_chunk, self.local_of[probed],
                        self.chunk_lists - 1).astype(np.int32)

    def stream(self, probed: np.ndarray,
               run: Callable[[int, Dict[str, jax.Array], np.ndarray],
                             Tuple[jax.Array, jax.Array]]
               ) -> List[Tuple[jax.Array, jax.Array]]:
        """Run ``run(chunk_idx, device_arrays, local_probed)`` over every
        chunk this batch probes, keeping the next chunk's host→device
        upload in flight while the current chunk computes."""
        touched = self.cold_probed(probed)
        if touched.size == 0:
            return []

        def put(ci: int) -> Dict[str, jax.Array]:
            ch = self.chunks[ci]
            dev = {k: jax.device_put(v) for k, v in ch.arrays.items()}
            for k, v in self.extras[ci].items():
                dev[k] = jax.device_put(v)
            dev["offsets"] = jax.device_put(ch.offsets)
            dev["sizes"] = jax.device_put(ch.sizes)
            return dev

        results = []
        pending = put(int(touched[0]))     # warm-up upload
        for i, ci in enumerate(touched):
            dev, pending = pending, None
            if i + 1 < len(touched):
                # device_put is async: the NEXT chunk's PCIe transfer
                # overlaps this chunk's dispatch+scan
                pending = put(int(touched[i + 1]))
            self.streamed_chunks += 1
            self.streamed_bytes += sum(
                v.size * v.dtype.itemsize
                for v in self.chunks[int(ci)].arrays.values())
            results.append(run(int(ci), dev,
                               self.local_probed(probed, int(ci))))
        return results

    def snapshot(self) -> dict:
        """Strict-JSON tier stats for debugz/memz."""
        return {
            "cold_lists": self.n_cold_lists,
            "cold_rows": self.cold_rows,
            "host_bytes": self.host_bytes,
            "device_bytes_saved": self.device_bytes_saved,
            "chunks": len(self.chunks),
            "chunk_rows": self.chunk_rows,
            "streamed_chunks": int(self.streamed_chunks),
            "streamed_bytes": int(self.streamed_bytes),
        }


def build_tier(arrays: Dict[str, np.ndarray], list_offsets: np.ndarray,
               list_sizes: np.ndarray, hot: np.ndarray,
               chunk_rows: int, pad_tail: int = 0,
               fills: Optional[Dict[str, float]] = None,
               chunk_shape: Optional[Tuple[int, int, int]] = None
               ) -> Tuple[HostTier, Dict[str, np.ndarray], np.ndarray,
                          np.ndarray]:
    """Split cluster-sorted ``arrays`` (rows axis 0) into a packed
    resident copy (cold lists shrunk to size 0) and a :class:`HostTier`
    of fixed-shape cold chunks.

    ``chunk_rows``: row budget per streamed chunk (rounded up to hold
    at least the largest cold list). ``pad_tail``: extra zero rows past
    ``chunk_rows`` on every chunk's row axis (the scan kernels' aligned
    DMA window — padding HERE means the device never re-pads a streamed
    chunk). ``fills``: per-array pad value (default 0).

    ``chunk_shape``: optional ``(chunk_rows, chunk_lists, lmax)`` pin
    for the padded chunk geometry. Without it the shared shape shrinks
    to the fullest chunk actually planned (host-RAM economy); with it,
    every tier built from the same pin — e.g. every level of a fleet
    budget ladder (:meth:`raft_tpu.parallel.fleet.Fleet` re-tiers) —
    shares ONE padded shape, so a re-tier lands in the already-compiled
    cold-scan executables instead of forking new shapes per level.

    Returns ``(tier, hot_arrays, hot_offsets, hot_sizes)``; the caller
    swaps the resident arrays/offsets into its index and attaches the
    tier."""
    fills = fills or {}
    n_lists = len(list_sizes)
    sizes = np.asarray(list_sizes, np.int64)
    offsets = np.asarray(list_offsets, np.int64)
    cold_ids = np.flatnonzero(~np.asarray(hot))
    cold_sizes = sizes[cold_ids]
    lmax = int(cold_sizes.max()) if cold_ids.size else 0
    if chunk_shape is not None:
        pin_rows, pin_lists, pin_lmax = (int(v) for v in chunk_shape)
        lmax = max(lmax, pin_lmax)
        chunk_rows = max(pin_rows, lmax, 1)
    else:
        chunk_rows = max(int(chunk_rows), lmax, 1)

    # ---- greedy fixed-shape chunk plan over cold lists (+1 dead slot
    # per chunk that out-of-chunk probes are routed to)
    plans: List[List[int]] = []
    cur: List[int] = []
    cur_rows = 0
    for li in cold_ids:
        s = int(sizes[li])
        if cur and cur_rows + s > chunk_rows:
            plans.append(cur)
            cur, cur_rows = [], 0
        cur.append(int(li))
        cur_rows += s
    if cur:
        plans.append(cur)
    if chunk_shape is None:
        # shrink the shared chunk shape to the fullest chunk actually
        # planned: every chunk still hits one executable, and a tier
        # whose cold set is far under the row budget does not pad host
        # RAM (or PCIe uploads) out to the budget
        chunk_rows = max((int(sizes[p].sum()) for p in plans), default=1)
        chunk_lists = max((len(p) for p in plans), default=0) + 1
    else:
        # pinned geometry: never shrink (and never exceed the pin —
        # the greedy plan above cut at the pinned row budget, and any
        # planned chunk holds at most n_lists lists)
        chunk_lists = max(pin_lists,
                          max((len(p) for p in plans), default=0) + 1)

    chunk_of = np.full(n_lists, -1, np.int32)
    local_of = np.zeros(n_lists, np.int32)
    chunks: List[_Chunk] = []
    host_bytes = 0
    for ci, lists in enumerate(plans):
        offs = np.zeros(chunk_lists, np.int64)
        szs = np.zeros(chunk_lists, np.int64)
        ch_arrays: Dict[str, np.ndarray] = {}
        row0 = 0
        sel = []
        for sl, li in enumerate(lists):
            chunk_of[li] = ci
            local_of[li] = sl
            offs[sl] = row0
            szs[sl] = sizes[li]
            sel.append((int(offsets[li]), int(sizes[li])))
            row0 += int(sizes[li])
        total = chunk_rows + pad_tail
        for name, arr in arrays.items():
            out = np.full((total,) + arr.shape[1:], fills.get(name, 0),
                          arr.dtype)
            r = 0
            for off, s in sel:
                out[r:r + s] = arr[off:off + s]
                r += s
            ch_arrays[name] = out
            host_bytes += out.size * out.dtype.itemsize
        chunks.append(_Chunk(np.asarray(lists, np.int64), offs, szs,
                             ch_arrays))

    # ---- packed resident copy: hot lists keep their rows (and order),
    # cold lists shrink to zero-size spans
    hot_offsets = np.zeros(n_lists + 1, np.int64)
    hot_sizes = sizes.copy()
    hot_sizes[cold_ids] = 0
    np.cumsum(hot_sizes, out=hot_offsets[1:])
    hot_arrays: Dict[str, np.ndarray] = {}
    saved = 0
    for name, arr in arrays.items():
        out = np.empty((int(hot_offsets[-1]),) + arr.shape[1:], arr.dtype)
        for li in np.flatnonzero(hot_sizes > 0):
            o, s = int(offsets[li]), int(sizes[li])
            out[int(hot_offsets[li]):int(hot_offsets[li]) + s] = \
                arr[o:o + s]
        hot_arrays[name] = out
        saved += (arr.size - out.size) * arr.dtype.itemsize

    tier = HostTier(chunks, chunk_of, local_of, lmax, chunk_rows,
                    chunk_lists, int(cold_sizes.sum()), host_bytes, saved)
    return tier, hot_arrays, hot_offsets, hot_sizes
