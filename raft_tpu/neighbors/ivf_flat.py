"""IVF-Flat index: analog of ``raft::neighbors::ivf_flat``.

Reference: raft/neighbors/ivf_flat_types.hpp:131 (index = per-cluster
inverted lists of raw vectors), detail/ivf_flat_build.cuh:123-343
(build/extend: kmeans_balanced coarse quantizer + grouped-interleaved list
layout) and detail/ivf_flat_search-inl.cuh:38-255 (coarse GEMM + select_k,
then a fused per-list scan+topk kernel).

TPU design: lists live as *contiguous row ranges of one dense row-sorted
array* (cluster-sorted dataset + offsets) — the TPU analog of the
reference's interleaved group-of-32 layout (ivf_flat_build.cuh:87-158),
whose purpose (coalesced full-width loads) XLA gets for free from dense
rows. Search is two MXU stages: (1) coarse = queries×centroids GEMM +
select_k → n_probes lists; (2) candidate rows of the probed lists are
gathered per query chunk and scored with a batched GEMV + masked select_k.
The probe budget is the sum of the n_probes largest list sizes, so shapes
stay static under jit.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import interop, tracing
from ..core.bitset import Bitset
from ..core.errors import expects
from ..core.resources import workspace_chunk_bytes
from ..core.serialize import load_arrays, save_arrays
from ..ops.guarded import guarded_call
from ..cluster import kmeans_balanced
from ..distance.distance_types import DistanceType, canonical_metric, is_min_close
from ..matrix.select_k import select_k
from ..utils import cdiv, hdot, in_jax_trace, run_query_chunks

__all__ = ["IndexParams", "SearchParams", "Index", "build",
           "build_from_batches", "extend", "search", "prepare_scan",
           "prepare_host_stream", "reconstruct", "save", "load",
           "make_searcher", "health"]

# v2: store_dtype meta + uint16-framed bf16 rows + int8 scales; v1 files
# (dense f32) remain readable
_SERIAL_VERSION = 2


@dataclasses.dataclass
class IndexParams:
    """Mirror of ivf_flat::index_params (ivf_flat_types.hpp).

    ``list_growth``: per-list capacity slack factor. 1.0 packs lists
    (aligned) densely; >1 reserves slack so ``extend`` is an O(batch)
    in-place device scatter until a list overflows (the reference grows
    lists via conservative_memory_allocation, ivf_flat_types.hpp)."""

    n_lists: int = 1024
    metric: DistanceType | str = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    add_data_on_build: bool = True
    seed: int = 0
    list_growth: float = 1.0
    # dataset storage dtype: float32 | bfloat16 (half the scan HBM
    # traffic) | int8 (quarter, per-row scales) | uint8 (quarter, exact
    # for byte corpora like SIFT/DEEP) — role of the per-dtype
    # loadAndComputeDist variants (ivf_flat_interleaved_scan-inl.cuh:99)
    dtype: str = "float32"


@dataclasses.dataclass
class SearchParams:
    """Mirror of ivf_flat::search_params."""

    n_probes: int = 20


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Cluster-sorted IVF-Flat index.

    ``data``: (cap_total, d) rows sorted by list, with per-list capacity
    slack (rows in [offset+size, offset+cap) are unread padding);
    ``source_ids``: (cap_total,) original ids (-1 on slack);
    ``list_offsets``: (n_lists+1,) capacity offsets (host numpy — static
    under jit); ``list_sizes_arr``: (n_lists,) true sizes; ``centers``:
    (n_lists, d).
    """

    data: jax.Array                # (cap_total, d) f32 | bf16 | int8 | uint8
    data_norms: jax.Array          # (cap_total,) exact f32 (of stored rep)
    source_ids: jax.Array
    centers: jax.Array
    center_norms: jax.Array
    list_offsets: np.ndarray       # host-side, static
    metric: DistanceType
    conservative_memory: bool = False
    list_sizes_arr: Optional[np.ndarray] = None  # None → dense (old files)
    list_growth: float = 1.0
    scales: Optional[jax.Array] = None  # (cap_total,) f32, int8 mode only

    @property
    def size(self) -> int:
        """Number of indexed vectors (excludes capacity slack)."""
        return int(self.list_sizes.sum())

    @property
    def dim(self) -> int:
        return self.data.shape[1]

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def list_sizes(self) -> np.ndarray:
        if self.list_sizes_arr is not None:
            return self.list_sizes_arr
        return np.diff(self.list_offsets)

    def tree_flatten(self):
        # the pallas scan-prep cache travels WITH the index so a jitted
        # function can take the index as an ARGUMENT (closure-baked index
        # arrays become HLO constants whose serialized size exceeds
        # remote-compile request limits at memory scale)
        cache = getattr(self, "_scan_pad", None)
        cache_leaves = None if cache is None else tuple(cache[1:])
        leaves = (self.data, self.data_norms, self.source_ids,
                  self.centers, self.center_norms, self.scales,
                  cache_leaves)
        aux = (tuple(self.list_offsets.tolist()), self.metric,
               self.conservative_memory,
               None if self.list_sizes_arr is None
               else tuple(self.list_sizes_arr.tolist()),
               self.list_growth,
               None if cache is None else cache[0])
        return leaves, aux

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        offsets, metric, conservative, sizes, growth, cache_lmax = aux
        out = cls(*leaves[:5], np.asarray(offsets, np.int64), metric,
                  conservative,
                  None if sizes is None else np.asarray(sizes, np.int64),
                  growth, leaves[5])
        if cache_lmax is not None and leaves[6] is not None:
            out._scan_pad = (cache_lmax, *leaves[6])
        return out


@tracing.annotate("raft_tpu::ivf_flat::build")
def build(dataset, params: IndexParams | None = None) -> Index:
    """Train the coarse quantizer on a subsample and fill the lists
    (detail/ivf_flat_build.cuh:123).

    Device-resident end to end: the dataset never round-trips through the
    host (only O(n_lists) list sizes do) — the TPU analog of the
    reference's bounded-batch device build (ivf_pq_build.cuh:1550).
    """
    p = params or IndexParams()
    dataset = jnp.asarray(dataset, jnp.float32)
    n, d = dataset.shape
    mt = canonical_metric(p.metric)
    expects(mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                   DistanceType.InnerProduct, DistanceType.CosineExpanded),
            "ivf_flat supports L2/IP/cosine metrics, got %s", mt.name)
    expects(p.n_lists <= n, "n_lists %d > n %d", p.n_lists, n)

    # trainset subsample (ivf_flat_build.cuh uses a strided subsample)
    n_train = max(p.n_lists, int(n * p.kmeans_trainset_fraction))
    stride = max(1, n // n_train)
    trainset = dataset[::stride]

    bparams = kmeans_balanced.BalancedKMeansParams(
        n_iters=p.kmeans_n_iters, seed=p.seed)
    centers = kmeans_balanced.fit(trainset, p.n_lists, bparams)

    store_t = jnp.dtype(p.dtype)
    index = Index(
        jnp.zeros((0, d), store_t), jnp.zeros((0,), jnp.float32),
        jnp.zeros((0,), jnp.int32), centers,
        jnp.sum(centers * centers, axis=1),
        np.zeros(p.n_lists + 1, np.int64), mt,
        list_sizes_arr=np.zeros(p.n_lists, np.int64),
        list_growth=p.list_growth,
        scales=jnp.zeros((0,), jnp.float32) if store_t == jnp.int8 else None)
    if p.add_data_on_build:
        index = extend(index, dataset)
    return index


@tracing.annotate("raft_tpu::ivf_flat::build_from_batches")
def build_from_batches(batches, params: IndexParams | None = None,
                       trainset=None) -> Index:
    """Streaming build for corpora larger than host/device-transfer
    budgets (role of the reference's bounded-batch extend loop,
    detail/ivf_pq_build.cuh:1550, scaled to DEEP-1B-class inputs).

    ``batches``: iterable of (b, d) row blocks (e.g.
    ``bench.datasets.iter_fbin``); host memory stays O(batch). The coarse
    quantizer trains on ``trainset`` when given, else on the first batch.
    Capacity slack (``params.list_growth``, bumped to >=1.2 here) keeps
    subsequent extends O(batch) in-place scatters.
    """
    from ._list_layout import streaming_build

    return streaming_build(batches, params or IndexParams(), build, extend,
                           dataclasses.replace, trainset)


@tracing.annotate("raft_tpu::ivf_flat::extend")
def extend(index: Index, new_vectors, new_ids=None) -> Index:
    """Add vectors to an existing index (detail/ivf_flat_build.cuh:extend).

    O(batch) device scatter while lists have capacity slack; a list
    overflow triggers a device-side repack with ``list_growth`` slack
    (no host copies of the dataset either way).

    .. note:: For *online* mutation prefer the crash-safe tier,
       :class:`raft_tpu.neighbors.mutable.MutableIndex` — it adds
       durability (WAL'd upserts), deletes (tombstones) and a
       background merge, and its parity test pins
       ``upsert + merge == build`` on the concatenated corpus
       (docs/mutation.md). ``extend`` remains the right call inside
       bulk streaming builds (``build_from_batches``), where the WAL
       would only be overhead.
    """
    from ._list_layout import scatter_build, scatter_extend
    from .brute_force import dequantize_rows, quantize_rows

    new_vectors = jnp.asarray(new_vectors, jnp.float32)
    expects(new_vectors.shape[1] == index.dim, "dim mismatch")
    n_new = new_vectors.shape[0]
    if new_ids is None:
        base = int(index.source_ids.max()) + 1 if index.size else 0
        new_ids = jnp.arange(base, base + n_new, dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)
    labels, _ = kmeans_balanced.predict(new_vectors, index.centers)

    stored, new_scales = quantize_rows(new_vectors, index.data.dtype)
    deq = dequantize_rows(stored, new_scales)
    norms = jnp.sum(deq * deq, axis=1)   # exact norms of the stored rep

    new_arrays = [stored, norms, new_ids]
    old_arrays = [index.data, index.data_norms, index.source_ids]
    fills = [0, 0.0, -1]
    if new_scales is not None:
        new_arrays.append(new_scales)
        old_arrays.append(index.scales)
        fills.append(1.0)
    if index.size == 0:
        out, offsets, sizes = scatter_build(
            labels, new_arrays, fills, index.n_lists, index.list_growth)
    else:
        out, offsets, sizes = scatter_extend(
            labels, new_arrays, old_arrays, fills,
            index.list_offsets, index.list_sizes, index.list_growth)
    scales = out[3] if new_scales is not None else None
    return Index(out[0], out[1], out[2], index.centers, index.center_norms,
                 offsets, index.metric, index.conservative_memory,
                 sizes, index.list_growth, scales)


def _probe_budget(list_sizes: np.ndarray, n_probes: int) -> int:
    """Static upper bound on candidate rows: sum of the n_probes largest
    lists (rounded up for alignment)."""
    top = np.sort(list_sizes)[::-1][:n_probes]
    return max(8, int(top.sum()))


def _candidate_rows(probed_lists, offsets_j, sizes_j, max_rows):
    """(m, n_probes) probed list ids → (m, max_rows) row ids + validity +
    the probe rank covering each slot.

    For each query, the rows of its probed lists are laid out back-to-back;
    slot s maps to probe j = searchsorted(cum_sizes, s) and row
    offsets[list_j] + (s - cum_sizes[j-1]).
    """
    sizes = sizes_j[probed_lists]                       # (m, p)
    cum = jnp.cumsum(sizes, axis=1)                     # (m, p)
    total = cum[:, -1]
    slots = jnp.arange(max_rows, dtype=jnp.int32)       # (S,)
    # probe covering each slot: number of cum entries <= slot
    probe_of = jnp.sum(cum[:, None, :] <= slots[None, :, None], axis=2)  # (m, S)
    probe_of = jnp.minimum(probe_of, sizes.shape[1] - 1)
    prev_cum = jnp.where(probe_of > 0,
                         jnp.take_along_axis(cum, jnp.maximum(probe_of - 1, 0),
                                             axis=1), 0)
    within = slots[None, :] - prev_cum
    list_of = jnp.take_along_axis(probed_lists, probe_of, axis=1)
    rows = offsets_j[list_of] + within
    valid = slots[None, :] < total[:, None]
    rows = jnp.where(valid, rows, 0)
    return rows, valid, probe_of


_PALLAS_METRICS = {
    DistanceType.L2Expanded: "l2",
    DistanceType.L2SqrtExpanded: "l2",
    DistanceType.CosineExpanded: "cos",
    DistanceType.InnerProduct: "ip",
}


def _scan_penalty(index, mask_bits, lmax: int):
    """Sample filter → in-kernel penalty row in sorted row order, padded to
    the scan DMA window (built once per search call, not per query chunk)."""
    from ..ops.ivf_scan import scan_window

    if mask_bits is None:
        return None
    return jnp.pad(jnp.where(mask_bits[index.source_ids], 0.0, jnp.inf),
                   (0, scan_window(lmax)))


def prepare_scan(index: Index) -> None:
    """Eagerly build the pallas scan's aligned-DMA padded copy and attach
    it to the index (a full-dataset pad pass). Called automatically on the
    first *eager* search; jit users should call it once before tracing —
    caches are never written under a trace (storing tracers corrupts
    them), so an unprepared index pays the pad inside every jitted call."""
    lmax = int(index.list_sizes.max())
    cache = getattr(index, "_scan_pad", None)
    if cache is None or cache[0] != lmax:
        from ..ops.ivf_scan import pad_for_scan

        index._scan_pad = (lmax,
                           *pad_for_scan(index.data, index.data_norms,
                                         lmax, index.scales))


def _search_pallas(index, q, k, n_probes, offsets_j, sizes_j, precision,
                   pen_p=None, survivors=None):
    """Fused query-grouped list scan (the TPU perf path; ops/ivf_scan.py)."""
    from ..ops.ivf_scan import _ivf_flat_scan_jit, coarse_probe, pad_for_scan

    mt = index.metric
    probed = coarse_probe(q, index.centers, n_probes,
                          metric=_PALLAS_METRICS[mt],
                          center_norms=index.center_norms,
                          precision=precision, survivors=survivors)
    lmax = int(index.list_sizes.max())
    # the aligned-DMA padding copies the dataset: cached once per index,
    # but NEVER stored from inside a trace (leaked tracers)
    cache = getattr(index, "_scan_pad", None)
    if cache is None or cache[0] != lmax:
        if in_jax_trace():
            # traced: compute inline, never store (leaked tracers)
            cache = (lmax, *pad_for_scan(index.data, index.data_norms,
                                         lmax, index.scales))
        else:
            prepare_scan(index)
            cache = index._scan_pad
    interpret = jax.default_backend() != "tpu"
    vals, rows = _ivf_flat_scan_jit(cache[1], cache[2], pen_p, cache[3],
                                    probed, offsets_j, sizes_j, q, k, lmax,
                                    _PALLAS_METRICS[mt], interpret,
                                    precision)
    ids = jnp.where(rows >= 0,
                    jnp.take(index.source_ids, jnp.maximum(rows, 0)), -1)
    if mt is DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    elif mt is DistanceType.InnerProduct:
        vals = jnp.where(jnp.isfinite(vals), -vals, -jnp.inf)
    return vals, ids



@interop.auto_convert_output
@tracing.annotate("raft_tpu::ivf_flat::search")
def search(
    index: Index,
    queries,
    k: int,
    params: SearchParams | None = None,
    filter: Optional[Bitset] = None,  # noqa: A002
    query_chunk: int = 0,
    algo: str = "auto",
    precision: str = "highest",
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """Probe the n_probes nearest lists per query and return exact top-k over
    their members → (distances (m, k), indices (m, k)) with original ids.

    ``algo``: "pallas" (fused query-grouped list scan — the TPU perf path,
    role of the interleaved-scan kernel; ``filter`` rides in-kernel as a
    penalty row), "xla" (gather-based composed-XLA path), "auto" (pallas
    on TPU).

    A host-streamed index (:func:`prepare_host_stream`) serves its
    resident lists through the same engines and double-buffers the
    probed COLD lists' rows from host RAM per batch; host streaming is
    eager-only (host arrays cannot ride a jit trace).
    """
    p = params or SearchParams()
    q = jnp.asarray(queries, jnp.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "bad query shape %s", q.shape)
    tier = getattr(index, "_host_tier", None)
    if tier is not None and not getattr(_hot_local, "skip", False):
        # loud, not silent: a traced search of a host-streamed index
        # would skip every cold list and return systematically partial
        # results
        expects(not in_jax_trace(),
                "host-streamed indexes search eagerly (host arrays "
                "cannot ride a jit trace) — drop the outer jit or "
                "search before prepare_host_stream")
        return _search_host_stream(index, tier, q, k, p, filter,
                                   query_chunk, algo, precision, res)
    expects(index.size > 0, "index is empty")
    n_probes = min(p.n_probes, index.n_lists)
    mt = index.metric

    offsets_j = jnp.asarray(index.list_offsets[:-1], jnp.int32)
    sizes_np = index.list_sizes
    sizes_j = jnp.asarray(sizes_np, jnp.int32)
    mask_bits = filter.to_mask() if filter is not None else None

    # selectivity-adaptive policy (ops/filter_policy.py): measure per-list
    # survivor counts once, prune zero-survivor lists (their scan size
    # zeroes → sentinel rows, no DMA), widen the probe set to restore the
    # survivor-weighted candidate mass, and at extreme selectivity cross
    # over to an exact brute-force pass on the compacted survivors. The
    # widen/crossover half needs host values, so a traced search keeps
    # only the free device-side prune.
    surv_dev = None
    if filter is not None:
        from ..ops import filter_policy

        if (in_jax_trace() or getattr(_hot_local, "skip", False)
                or filter_policy.adaptive_off()):
            # traced, the resident half of a host-streamed search (which
            # keeps its own machinery), or a suspended internal filter
            # (mutable tombstones): free prune only
            surv_dev = filter_policy.list_survivors(index, filter)
        else:
            fd = filter_policy.decide_ivf(index, filter, n_probes, k,
                                          "ivf_flat")
            if fd.use_brute:
                return filter_policy.crossover(
                    fd, "ivf_flat",
                    lambda: filter_policy.survivor_brute_ivf(
                        index, reconstruct, q, k, filter),
                    lambda: search(index, q, k, p, filter, query_chunk,
                                   algo, precision, res))
            n_probes = fd.n_probes
            surv_dev = fd.surv_dev
        sizes_j = jnp.where(surv_dev > 0, sizes_j, 0)

    # every storage dtype rides the pallas scan: f32/bf16 natively,
    # int8 via per-row scales applied to the dot in-kernel, uint8 exact
    # (byte values are representable in bf16; role of the per-dtype
    # loadAndComputeDist variants, ivf_flat_interleaved_scan-inl.cuh:99)
    use_pallas = (algo == "pallas" or
                  (algo == "auto" and mt in _PALLAS_METRICS and
                   jax.default_backend() == "tpu"))
    if use_pallas:
        expects(mt in _PALLAS_METRICS, "metric %s unsupported by pallas",
                mt.name)
        pen_p = _scan_penalty(index, mask_bits,
                              int(index.list_sizes.max()))
        dim_pad = -(-index.dim // 128) * 128
        if query_chunk <= 0:
            # bound the (pairs × dim) query blocks to ~256 MB
            per_q = n_probes * dim_pad * 4
            query_chunk = max(1, min(q.shape[0],
                                     workspace_chunk_bytes(res) // max(per_q, 1)))
        fb_state: dict = {}   # built lazily: the fallback almost never runs

        def _xla_fallback(qc):
            # the gather path's per-query footprint (max_rows * dim * 4)
            # is orders of magnitude above the kernel's — re-chunk to ITS
            # workspace budget or the containment path itself OOMs
            if not fb_state:
                fb_state["max_rows"] = _probe_budget(sizes_np, n_probes)
                per_q = fb_state["max_rows"] * index.dim * 4
                fb_state["chunk"] = max(
                    1, workspace_chunk_bytes(res) // max(per_q, 1))
            return run_query_chunks(
                lambda qs, _s0: _search_chunk(index, qs, k, n_probes,
                                              fb_state["max_rows"],
                                              offsets_j, sizes_j, mask_bits,
                                              mt, surv_dev),
                qc, fb_state["chunk"])

        # guarded: a scan-kernel failure demotes this site to the exact
        # XLA gather path (ops/guarded.py)
        return run_query_chunks(
            lambda qc, _s0: guarded_call(
                "ivf_flat.scan",
                lambda: _search_pallas(index, qc, k, n_probes, offsets_j,
                                       sizes_j, precision, pen_p, surv_dev),
                lambda: _xla_fallback(qc)),
            q, query_chunk, res)

    max_rows = _probe_budget(sizes_np, n_probes)
    if query_chunk <= 0:
        # bound gathered candidates to ~256 MB
        per_q = max_rows * index.dim * 4
        query_chunk = max(1, min(q.shape[0], workspace_chunk_bytes(res) // max(per_q, 1)))

    return run_query_chunks(
        lambda qc, _s0: _search_chunk(index, qc, k, n_probes, max_rows,
                                      offsets_j, sizes_j, mask_bits, mt,
                                      surv_dev),
        q, query_chunk, res)


def search_arrays(data, data_norms, source_ids, centers, center_norms,
                  offsets_j, sizes_j, qc, k, n_probes, max_rows, mt,
                  mask_bits=None, scales=None, survivors=None,
                  int4_dim=None):
    """Pure-array IVF-Flat search core — everything traced, so it runs under
    jit, vmap and shard_map alike (the multi-chip path stacks per-shard
    arrays and calls this per shard). ``data`` may be stored low-precision
    (bf16/int8 + per-row ``scales``, or nibble-packed int4 when
    ``int4_dim`` names the logical width); gathers dequantize on the
    fly."""
    from .brute_force import dequantize_rows

    from ..ops.ivf_scan import coarse_probe

    select_min = is_min_close(mt)
    # stage 1: coarse probe selection (ivf_flat_search-inl.cuh:38) —
    # shared with the pallas path so both engines probe identical lists
    cmetric = ("ip" if mt is DistanceType.InnerProduct
               else "cos" if mt is DistanceType.CosineExpanded else "l2")
    probed = coarse_probe(qc, centers, n_probes, metric=cmetric,
                          center_norms=center_norms, survivors=survivors)

    # stage 2: gather candidates and score (the fused-scan analog)
    rows, valid, _ = _candidate_rows(probed, offsets_j, sizes_j, max_rows)
    if int4_dim is not None:
        from ..ops.quant import dequantize_int4

        cand = dequantize_int4(data[rows], scales[rows], int4_dim)
    else:
        cand = dequantize_rows(data[rows],
                               None if scales is None else scales[rows])
    if mt is DistanceType.InnerProduct:
        dist = jnp.einsum("msd,md->ms", cand, qc, precision="highest")
    elif mt is DistanceType.CosineExpanded:
        ip = jnp.einsum("msd,md->ms", cand, qc, precision="highest")
        qn = jnp.sqrt(jnp.maximum(jnp.sum(qc * qc, axis=1, keepdims=True), 1e-30))
        cn = jnp.sqrt(jnp.maximum(data_norms[rows], 1e-30))
        dist = 1.0 - ip / (qn * cn)
    else:
        ip = jnp.einsum("msd,md->ms", cand, qc, precision="highest")
        q2 = jnp.sum(qc * qc, axis=1, keepdims=True)
        dist = jnp.maximum(q2 + data_norms[rows] - 2.0 * ip, 0.0)
        if mt is DistanceType.L2SqrtExpanded:
            dist = jnp.sqrt(dist)

    if mask_bits is not None:
        valid = valid & mask_bits[source_ids[rows]]
    bad = jnp.inf if select_min else -jnp.inf
    dist = jnp.where(valid, dist, bad)
    kk = min(k, max_rows)
    vals, locs = select_k(dist, kk, select_min=select_min)
    ids = jnp.take_along_axis(source_ids[rows], locs, axis=1)
    ids = jnp.where(jnp.isfinite(vals) if select_min else vals > -jnp.inf,
                    ids, -1)
    if kk < k:  # pad (tiny indexes)
        pad = k - kk
        vals = jnp.pad(vals, ((0, 0), (0, pad)), constant_values=bad)
        ids = jnp.pad(ids, ((0, 0), (0, pad)), constant_values=-1)
    return vals, ids


def _search_chunk(index, qc, k, n_probes, max_rows, offsets_j, sizes_j,
                  mask_bits, mt, survivors=None):
    return search_arrays(index.data, index.data_norms, index.source_ids,
                         index.centers, index.center_norms, offsets_j,
                         sizes_j, qc, k, n_probes, max_rows, mt, mask_bits,
                         index.scales, survivors)


_hot_local = __import__("threading").local()   # re-entry guard: the hot
# half of a host-streamed search runs the ordinary resident path


def prepare_host_stream(index: Index, budget_gb: Optional[float] = None,
                        sample_queries=None, n_probes: int = 20,
                        chunk_mb: int = 64, hot_mask=None) -> None:
    """Move cold lists past the HBM budget into a host-RAM tier
    (docs/perf.md "Storage ladder", the beyond-HBM rung): the device
    keeps the hottest lists — ranked by measured probe frequency over
    ``sample_queries`` (list size standing in without a sample) — and
    every search double-buffers the probed cold lists' rows from host
    numpy over PCIe, scanning them with the SAME kernel as the resident
    lists and merging via ``knn_merge_parts``.

    ``budget_gb`` defaults to ``RAFT_TPU_HBM_BUDGET_GB``. A corpus that
    already fits is a no-op (no tier, nothing changes). Idempotent.
    Mutates the index in place (resident arrays shrink to the hot
    lists); ``index._host_tier`` carries the cold chunks and stats.
    Host-streamed search is EAGER-only — serving dispatch already is.

    ``hot_mask`` (bool, ``(n_lists,)``) bypasses the local budget plan
    with an externally-planned hot set — the fleet layer plans
    hot/cold ONCE from fleet-wide probe counts and hands each shard its
    slice, so per-shard planners never disagree about what is hot.
    """
    from ..ops.ivf_scan import scan_window
    from ..utils import round_up_to
    from . import host_stream as hs

    if getattr(index, "_host_tier", None) is not None:
        return
    sizes = index.list_sizes
    itemsize = jnp.dtype(index.data.dtype).itemsize
    row_bytes = (index.dim * itemsize + 8
                 + (4 if index.scales is not None else 0))
    if hot_mask is not None:
        hot = np.asarray(hot_mask, bool)
        expects(hot.shape == (index.n_lists,),
                f"hot_mask shape {hot.shape} != ({index.n_lists},)")
        if bool(hot.all()):
            return   # externally planned: everything stays resident
    else:
        budget = hs.budget_bytes(budget_gb)
        expects(budget > 0, "prepare_host_stream needs budget_gb or "
                "RAFT_TPU_HBM_BUDGET_GB")
        if int(sizes.sum()) * row_bytes <= budget:
            return   # everything fits: stay fully resident
        freq = None
        if sample_queries is not None:
            from ..ops.ivf_scan import coarse_probe

            cmetric = ("ip" if index.metric is DistanceType.InnerProduct
                       else "cos" if index.metric is DistanceType.CosineExpanded
                       else "l2")
            probed = np.asarray(coarse_probe(
                jnp.asarray(sample_queries, jnp.float32), index.centers,
                min(n_probes, index.n_lists), metric=cmetric,
                center_norms=index.center_norms))
            freq = hs.probe_frequency(probed, index.n_lists)
        hot = hs.plan_hot_cold(sizes, row_bytes, budget, freq)

    dim_pad = round_up_to(index.dim, 128)
    # cold chunks carry their rows SCAN-READY: dim padded to the lane
    # tile and `scan_window` tail rows for the kernel's aligned DMA —
    # a streamed chunk is never re-padded on device
    data_np = np.asarray(jax.device_get(index.data))
    if data_np.dtype == np.uint16:   # defensive: never expected
        raise AssertionError("unexpected raw-framed dataset")
    arrays = {
        "data": np.pad(np.asarray(data_np),
                       ((0, 0), (0, dim_pad - index.dim))),
        "norms": np.asarray(index.data_norms, np.float32),
        "ids": np.asarray(index.source_ids, np.int32),
    }
    fills = {"ids": -1}
    if index.scales is not None:
        arrays["scales"] = np.asarray(index.scales, np.float32)
        fills["scales"] = 1.0
    chunk_rows = max(1, int(float(chunk_mb) * (1 << 20)) // max(row_bytes, 1))
    cold_lmax = int(sizes[~hot].max()) if (~hot).any() else 0
    tier, hot_arrays, hot_offsets, hot_sizes = hs.build_tier(
        arrays, index.list_offsets, sizes, hot, chunk_rows,
        pad_tail=scan_window(cold_lmax), fills=fills)

    index.data = jnp.asarray(
        hot_arrays["data"][:, :index.dim].astype(data_np.dtype))
    index.data_norms = jnp.asarray(hot_arrays["norms"])
    index.source_ids = jnp.asarray(hot_arrays["ids"])
    if index.scales is not None:
        index.scales = jnp.asarray(hot_arrays["scales"])
    index.list_offsets = hot_offsets
    index.list_sizes_arr = hot_sizes
    index.__dict__.pop("_scan_pad", None)   # stale resident-scan cache
    index._host_tier = tier


@dataclasses.dataclass
class _ColdScanArgs:
    """Static scan geometry shared by every chunk of one tier (one jit
    executable serves all chunks)."""

    k: int
    lmax: int
    metric: str
    precision: str
    # logical row width when the chunk's rows are nibble-packed int4
    # (fleet quant-ladder tiers); None for f32/bf16/int8 storage
    int4_dim: Optional[int] = None


def _cold_chunk_scan_flat(index, dev, probed_local, qc, args, mask_bits):
    """Scan one streamed cold chunk with the SAME kernel as the resident
    lists (ops/ivf_scan.py) — per-list results are bit-identical to the
    fully-resident scan's."""
    from ..ops.ivf_scan import _ivf_flat_scan_jit

    ids = dev["ids"]
    pen_p = None
    if mask_bits is not None:
        pen_p = jnp.where((ids >= 0)
                          & jnp.take(mask_bits, jnp.maximum(ids, 0)),
                          0.0, jnp.inf).astype(jnp.float32)
    interpret = jax.default_backend() != "tpu"
    vals, rows = _ivf_flat_scan_jit(
        dev["data"], dev["norms"], pen_p, dev.get("scales"),
        jnp.asarray(probed_local), dev["offsets"].astype(jnp.int32),
        dev["sizes"].astype(jnp.int32), qc, args.k, args.lmax,
        args.metric, interpret, args.precision)
    out_i = jnp.where(rows >= 0, jnp.take(ids, jnp.maximum(rows, 0)), -1)
    return vals, out_i


def _cold_chunk_xla_flat(index, dev, probed_local, qc, args, mask_bits):
    """Guarded fallback: XLA rescore of the same streamed chunk (the
    search_arrays math on block-local lists) — correct, not
    arithmetic-identical to the kernel."""
    n_probes = probed_local.shape[1]
    max_rows = args.lmax * min(n_probes, dev["offsets"].shape[0])
    rows, valid, _ = _candidate_rows(
        jnp.asarray(probed_local), dev["offsets"].astype(jnp.int32),
        dev["sizes"].astype(jnp.int32), max_rows)
    from .brute_force import dequantize_rows

    sc = dev.get("scales")
    if args.int4_dim is not None:
        from ..ops.quant import dequantize_int4

        cand = dequantize_int4(dev["data"][rows], sc[rows], args.int4_dim)
    else:
        cand = dequantize_rows(dev["data"][rows],
                               None if sc is None else sc[rows])[..., :index.dim]
    mt = index.metric
    ip = jnp.einsum("msd,md->ms", cand, qc, precision="highest")
    if mt is DistanceType.InnerProduct:
        dist = -ip
    elif mt is DistanceType.CosineExpanded:
        qn = jnp.sqrt(jnp.maximum(
            jnp.sum(qc * qc, axis=1, keepdims=True), 1e-30))
        cn = jnp.sqrt(jnp.maximum(dev["norms"][rows], 1e-30))
        dist = 1.0 - ip / (qn * cn)
    else:
        q2 = jnp.sum(qc * qc, axis=1, keepdims=True)
        dist = jnp.maximum(q2 + dev["norms"][rows] - 2.0 * ip, 0.0)
    ids = dev["ids"][rows]
    valid = valid & (ids >= 0)
    if mask_bits is not None:
        valid = valid & jnp.take(mask_bits, jnp.maximum(ids, 0))
    dist = jnp.where(valid, dist, jnp.inf)
    kk = min(args.k, max_rows)
    vals, locs = select_k(dist, kk, select_min=True)
    out_i = jnp.where(jnp.isfinite(vals),
                      jnp.take_along_axis(ids, locs, axis=1), -1)
    if kk < args.k:
        vals = jnp.pad(vals, ((0, 0), (0, args.k - kk)),
                       constant_values=jnp.inf)
        out_i = jnp.pad(out_i, ((0, 0), (0, args.k - kk)),
                        constant_values=-1)
    return vals, out_i


def _postprocess(mt, vals):
    if mt is DistanceType.L2SqrtExpanded:
        return jnp.sqrt(jnp.maximum(vals, 0.0))
    if mt is DistanceType.InnerProduct:
        return jnp.where(jnp.isfinite(vals), -vals, -jnp.inf)
    return vals


def _search_host_stream(index, tier, q, k, p, filter, query_chunk, algo,
                        precision, res):
    """Resident half through the ordinary engines + probed cold lists
    streamed from the host tier, merged exactly like shard results
    (knn_merge_parts)."""
    from ..ops.ivf_scan import coarse_probe

    mt = index.metric
    select_min = is_min_close(mt)
    n_probes = min(p.n_probes, index.n_lists)
    mask_bits = filter.to_mask() if filter is not None else None
    cmetric = ("ip" if mt is DistanceType.InnerProduct
               else "cos" if mt is DistanceType.CosineExpanded else "l2")
    args = _ColdScanArgs(k, tier.lmax, _PALLAS_METRICS.get(mt, "l2"),
                         precision)
    if query_chunk <= 0:
        per_q = n_probes * (-(-index.dim // 128) * 128) * 4
        query_chunk = max(1, min(q.shape[0],
                                 workspace_chunk_bytes(res) // max(per_q, 1)))

    def one(qc, _s0):
        bad = jnp.inf if select_min else -jnp.inf
        if index.size > 0:
            _hot_local.skip = True
            try:
                hot_d, hot_i = search(index, qc, min(k, max(index.size, 1)),
                                      SearchParams(n_probes), filter,
                                      0, algo, precision)
            finally:
                _hot_local.skip = False
            if hot_d.shape[1] < k:
                pad = k - hot_d.shape[1]
                hot_d = jnp.pad(hot_d, ((0, 0), (0, pad)),
                                constant_values=bad)
                hot_i = jnp.pad(hot_i, ((0, 0), (0, pad)),
                                constant_values=-1)
        else:
            hot_d = jnp.full((qc.shape[0], k), bad, jnp.float32)
            hot_i = jnp.full((qc.shape[0], k), -1, jnp.int32)
        # the hot half just probed the same centers inside its own
        # fused executable; re-deriving the (m, p) ids here costs one
        # small GEMM + a host copy and keeps the resident executables
        # byte-identical to the tier-less path (threading probes out of
        # them would fork every compiled signature)
        probed = np.asarray(coarse_probe(
            qc, index.centers, n_probes, metric=cmetric,
            center_norms=index.center_norms, precision=precision))

        def run(ci, dev, probed_local):
            return guarded_call(
                "ivf.host_stream",
                lambda: _cold_chunk_scan_flat(index, dev, probed_local,
                                              qc, args, mask_bits),
                lambda: _cold_chunk_xla_flat(index, dev, probed_local,
                                             qc, args, mask_bits))

        cold = tier.stream(probed, run)
        if not cold:
            return hot_d, hot_i
        parts_d = [hot_d] + [_postprocess(mt, cd) for cd, _ in cold]
        parts_i = [hot_i] + [ci_ for _, ci_ in cold]
        from .brute_force import knn_merge_parts

        return knn_merge_parts(jnp.stack(parts_d), jnp.stack(parts_i),
                               select_min)

    return run_query_chunks(one, q, query_chunk, res)


def reconstruct(index: Index, row_ids) -> jax.Array:
    """Decode stored rows back to f32 input-space vectors by physical row
    id (role of the reference's ivf_flat helpers unpack/reconstruct list
    data, ivf_flat_helpers.cuh / ivf_flat_codepacker.hpp). Exact for f32
    storage; dequantized (per-row scale) for bf16/int8 storage. Physical
    row ids are what ``search`` returns before the source-id remap — i.e.
    positions in the cluster-sorted ``index.data``; use ``source_ids`` to
    map back to original ids.

    Range/slack validation runs eagerly only: under a jax trace invalid
    ids follow gather clamp semantics (no error) — validate before
    jitting."""
    from .brute_force import dequantize_rows

    row_ids = jnp.asarray(row_ids, jnp.int32)
    if not in_jax_trace():
        rid = np.asarray(row_ids)
        cap = index.data.shape[0]
        expects(rid.size == 0 or (rid.min() >= 0 and rid.max() < cap),
                "row_ids out of range [0, %d)", cap)
        # device-side gather, O(len(row_ids)) host transfer
        src = np.asarray(index.source_ids[row_ids]) if rid.size else rid
        expects((src >= 0).all(),
                "row_ids hit capacity-slack rows (source_id -1)")
    rows = index.data[row_ids]
    scales = None if index.scales is None else index.scales[row_ids]
    return dequantize_rows(rows, scales)


def save(index: Index, path) -> None:
    """Serialize (analog of ivf_flat_serialize.cuh). Capacity slack is
    stripped: the file holds densely-packed valid rows (v1 layout), so
    files are slack-free and old readers stay compatible. bf16 rows are
    framed as uint16 (npy has no bfloat16) with the dtype in the header.

    Host-streamed indexes refuse to serialize: the device arrays hold
    only the HOT lists, so a silent save would permanently drop every
    cold row — save before :func:`prepare_host_stream` (the tier is
    derived state; rebuild it after load)."""
    from ._list_layout import gather_dense

    expects(getattr(index, "_host_tier", None) is None,
            "cannot save a host-streamed index (cold lists live in the "
            "host tier, not the device arrays); save before "
            "prepare_host_stream and re-prepare after load")

    sizes = index.list_sizes
    arrays = [index.data, index.source_ids]
    if index.scales is not None:
        arrays.append(index.scales)
    if index.list_sizes_arr is not None:
        arrays, _ = gather_dense(arrays, index.list_offsets, sizes)
    data, ids = arrays[0], arrays[1]
    dense_offsets = np.zeros(index.n_lists + 1, np.int64)
    np.cumsum(sizes, out=dense_offsets[1:])
    if data.dtype == jnp.bfloat16:
        data = np.asarray(jax.device_get(data)).view(np.uint16)
    out = {
        "data": data,
        "source_ids": ids,
        "centers": index.centers,
        "list_offsets": dense_offsets,
    }
    if index.scales is not None:
        out["scales"] = arrays[2]
    save_arrays(
        path, "ivf_flat", _SERIAL_VERSION,
        {"metric": index.metric.value, "n_lists": index.n_lists,
         "store_dtype": str(index.data.dtype)},
        out)


def load(path) -> Index:
    import ml_dtypes

    from .brute_force import dequantize_rows

    _, version, meta, arrs = load_arrays(path, "ivf_flat")
    expects(version in (1, 2), "unsupported version %d", version)
    data_np = np.asarray(arrs["data"])
    if meta.get("store_dtype") == "bfloat16":
        data_np = data_np.view(ml_dtypes.bfloat16)
    data = jnp.asarray(data_np)
    scales = jnp.asarray(arrs["scales"]) if "scales" in arrs else None
    deq = dequantize_rows(data, scales)
    centers = jnp.asarray(arrs["centers"])
    offsets = np.asarray(arrs["list_offsets"], np.int64)
    return Index(
        data, jnp.sum(deq * deq, axis=1), jnp.asarray(arrs["source_ids"]),
        centers, jnp.sum(centers * centers, axis=1), offsets,
        DistanceType(meta["metric"]),
        list_sizes_arr=np.diff(offsets), scales=scales)


def health(index: Index, sample: int = 256) -> dict:
    """Index health report (docs/observability.md "Quality"): list-size
    skew (the probe-budget and recall-concentration signal) + storage
    width. int8 stores report sampled per-row scale stats over real rows
    (slack rows carry no data) — the quantization step bound, since the
    f32 originals are not retained."""
    from ._list_layout import list_skew
    from .brute_force import health_sample_rows, int8_scale_report

    report = {
        "family": "ivf_flat", "n": int(index.size), "dim": int(index.dim),
        "metric": index.metric.name,
        "store_dtype": str(jnp.dtype(index.data.dtype)),
        "lists": list_skew(index.list_sizes),
    }
    dt = jnp.dtype(index.data.dtype)
    if dt == jnp.int8 and index.scales is not None:
        rows = health_sample_rows(index.data.shape[0], sample)
        sid = np.asarray(index.source_ids[rows])
        sc = np.asarray(index.scales[rows], np.float64)[sid >= 0]
        if sc.size:
            report["quant"] = int8_scale_report(sc)
    elif dt == jnp.bfloat16:
        report["quant"] = {"bfloat16": {"rel_step": 2.0 ** -8}}
    elif dt == jnp.uint8:
        report["quant"] = {"uint8": {"exact": True}}
    return report


def make_searcher(index: Index, params: SearchParams | None = None, *,
                  degrade=None, **opts):
    """Stable batchable signature for the serving runtime
    (:mod:`raft_tpu.serve`): returns ``fn(queries, k, res=None) ->
    (distances, indices)`` with the probe policy and engine choice frozen
    at closure build time, so repeated bucketed-shape calls hit the same
    cached executables. ``opts`` forwards to :func:`search` (``algo``,
    ``filter``, ``precision``, ``query_chunk``, ...). ``degrade``: a
    :class:`~raft_tpu.serve.degrade.BrownoutController` — under brownout
    its current level overrides ``n_probes`` per call (same shape
    buckets, one compile per visited level; docs/robustness.md)."""
    base = params or SearchParams()

    def _fn(queries, k, res=None):
        p = base if degrade is None else degrade.params(base)
        return search(index, queries, k, p, res=res, **opts)

    return _fn
