"""Exact brute-force kNN: analog of ``raft::neighbors::brute_force``.

Reference: raft/neighbors/brute_force-inl.cuh with the tiled engine in
detail/knn_brute_force.cuh:61 (`tiled_brute_force_knn`: row×col tiles of
pairwise distance GEMM + per-tile select_k + cross-tile merge) and the
multi-shard merge in detail/knn_merge_parts.cuh:172.

TPU design: one `lax.scan` over dataset tiles. Each step computes a
(n_queries, tile) distance block — the cross term on the MXU for expanded
metrics — takes the tile's top-k, and merges it into the running top-k
(concat + re-select, the `knn_merge_parts` trick applied streamingly).
XLA double-buffers the HBM tile reads against compute, which is exactly the
role the reference's stream-pool round-robin plays (knn_brute_force.cuh:476);
no NxM distance matrix ever exists in HBM.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core import deadline, interop, tracing
from ..core.bitset import Bitset
from ..core.errors import expects
from ..core.serialize import load_arrays, save_arrays
from ..ops.guarded import guarded_call
from ..distance.distance_types import DistanceType, canonical_metric, is_min_close
from ..distance.pairwise import _ELEMENTWISE, _elementwise_tile, _haversine
from ..matrix.select_k import select_k
from ..utils import hdot, in_jax_trace, round_up_to, run_query_chunks

__all__ = ["Index", "build", "search", "knn", "knn_merge_parts", "save",
           "load", "tune_search", "make_searcher"]

# v2: store_dtype meta + uint16-framed bf16 datasets + int8 scales; v1
# files (plain f32) remain readable
_SERIAL_VERSION = 2


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Brute-force index: the dataset plus precomputed row norms
    (brute_force_types.hpp:50 stores exactly these).

    ``dataset`` may be stored low-precision (the per-dtype dataset modes of
    detail/ivf_flat_interleaved_scan-inl.cuh:99-584 applied to brute
    force): bf16 halves and int8 quarters the HBM scan traffic. ``scales``
    holds per-row dequant factors for int8 (row ≈ scale * int8_vec);
    ``norms`` are always exact f32 norms of the *stored* representation.
    """

    dataset: jax.Array          # (n, d) f32 | bf16 | int8 | uint8
    norms: Optional[jax.Array]  # (n,) squared L2 norms, for expanded metrics
    metric: DistanceType
    metric_arg: float = 2.0
    scales: Optional[jax.Array] = None   # (n,) f32, int8 mode only

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def store_dtype(self):
        return self.dataset.dtype

    def tree_flatten(self):
        return ((self.dataset, self.norms, self.scales),
                (self.metric, self.metric_arg))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1], children[2])


def quantize_rows(dataset: jax.Array, dtype) -> Tuple[jax.Array, Optional[jax.Array]]:
    """f32 rows → (stored rows, per-row scales|None) for a storage dtype."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.float32:
        return dataset, None
    if dtype == jnp.bfloat16:
        return dataset.astype(jnp.bfloat16), None
    if dtype == jnp.uint8:
        # byte corpora (SIFT/DEEP): exact for integral [0, 255] inputs,
        # no scales (the reference's native uint8 dataset mode)
        q = jnp.clip(jnp.round(dataset), 0, 255)
        if not in_jax_trace():
            # silent clamping of float data would collapse recall with no
            # error; scaled float data belongs in int8 mode
            expects(bool(jnp.all(jnp.abs(dataset - q) < 1e-3)),
                    "uint8 storage expects byte-valued data (integral in "
                    "[0, 255]); use dtype='int8' for scaled float data")
        return q.astype(jnp.uint8), None
    expects(dtype == jnp.int8,
            "store dtype must be f32/bf16/int8/uint8, got %s", dtype)
    amax = jnp.max(jnp.abs(dataset), axis=1)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(dataset / scale[:, None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_rows(rows: jax.Array, scales: Optional[jax.Array]) -> jax.Array:
    """Stored rows (any dtype) → f32, applying int8 per-row scales."""
    out = rows.astype(jnp.float32)
    if scales is not None:
        out = out * scales[..., None]
    return out


@tracing.annotate("raft_tpu::brute_force::build")
def build(dataset: jax.Array, metric="sqeuclidean", metric_arg: float = 2.0,
          dtype=jnp.float32) -> Index:
    """Build = store dataset + precompute norms (no training).

    ``dtype``: storage dtype — float32 (exact), bfloat16 (half the HBM
    scan traffic, ~1e-3 relative distance error), int8 (quarter
    traffic, per-row symmetric quantization; the ANN-candidate mode) or
    uint8 (quarter traffic, exact — byte-valued corpora like SIFT/DEEP
    only; scaled float data belongs in int8).
    """
    dataset = jnp.asarray(dataset, jnp.float32)
    expects(dataset.ndim == 2, "dataset must be (n, d)")
    mt = canonical_metric(metric)
    stored, scales = quantize_rows(dataset, dtype)
    norms = None
    if mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
              DistanceType.CosineExpanded):
        deq = dequantize_rows(stored, scales)
        norms = jnp.sum(deq * deq, axis=1)
    return Index(stored, norms, mt, metric_arg, scales)


def _tile_distances(q, q_norm, tile, tile_norm, mt, metric_arg):
    """Distance block (n_queries, tile_rows) for one dataset tile."""
    if mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        d = jnp.maximum(q_norm[:, None] + tile_norm[None, :] - 2.0 * hdot(q, tile.T), 0.0)
        return jnp.sqrt(d) if mt is DistanceType.L2SqrtExpanded else d
    if mt is DistanceType.CosineExpanded:
        qn = jnp.sqrt(jnp.maximum(q_norm, 1e-30))
        tn = jnp.sqrt(jnp.maximum(tile_norm, 1e-30))
        return 1.0 - hdot(q, tile.T) / (qn[:, None] * tn[None, :])
    if mt is DistanceType.InnerProduct:
        return hdot(q, tile.T)
    if mt is DistanceType.Haversine:
        return _haversine(q, tile)
    if mt in (DistanceType.CorrelationExpanded, DistanceType.HellingerExpanded,
              DistanceType.RusselRaoExpanded):
        from ..distance.pairwise import _EXPANDED
        return _EXPANDED[mt](q, tile)
    expects(mt in _ELEMENTWISE, "metric %s unsupported by brute force", mt.name)
    return _elementwise_tile(q, tile, mt, metric_arg)


_PALLAS_METRICS = {
    DistanceType.L2Expanded: "l2",
    DistanceType.L2SqrtExpanded: "l2",
    DistanceType.CosineExpanded: "cos",
    DistanceType.InnerProduct: "ip",
}


def _penalty_row(index: Index, filter, valid_rows):
    """(n,) additive min-space penalty: +inf on excluded rows, else 0."""
    if filter is None and valid_rows is None:
        return None
    n = index.size
    pen = jnp.zeros((n,), jnp.float32)
    if filter is not None:
        pen = jnp.where(filter.to_mask(), pen, jnp.inf)
    if valid_rows is not None:
        pen = jnp.where(jnp.arange(n) < valid_rows, pen, jnp.inf)
    return pen


def _wide_select_k(s: jax.Array, k: int):
    """Exact per-row top-k over very wide rows via chunked select_k.

    select_k's KPASS engine caps at 4096 columns (its scoped-VMEM row
    block — 8192-wide blocks compile-OOM on v5e inside larger
    programs); wider rows select per 4096-chunk first, then select
    over the surviving nc·k candidates. Exact, including top_k's lowest-index tie-break:
    per-chunk selection keeps every chunk's own full top-k, and both
    levels break ties by ascending index."""
    from ..matrix.select_k import select_k

    m, n = s.shape
    c = 4096
    if n <= c or k * 4 > c:
        # narrow rows need no chunking; huge k makes chunking both
        # pointless (nc*k ~ n survivors) and ill-formed (the per-chunk
        # select needs k <= chunk width) — lax.top_k handles any k <= n
        return select_k(s, k, select_min=True)
    n_pad = round_up_to(n, c)
    nc = n_pad // c
    sp = jnp.pad(s, ((0, 0), (0, n_pad - n)), constant_values=jnp.inf)
    cv, ci = select_k(sp.reshape(m * nc, c), k, select_min=True)
    base = (jnp.arange(nc, dtype=jnp.int32) * c)[None, :, None]
    cand_v = cv.reshape(m, nc * k)
    cand_i = (ci.reshape(m, nc, k) + base).reshape(m, nc * k)
    v, j = select_k(cand_v, k, select_min=True)
    return v, jnp.take_along_axis(cand_i, j, axis=1)


def _blockmin_topk(s: jax.Array, k: int, blk: int = 32):
    """Exact top-k of a wide distance block via 32-column block minima.

    The binding cost of a naive top_k over (m, n≈500k) is XLA's sort
    (~9 ms per 8k columns, measured); a k-pass extraction is O(k·m·n)
    VPU work — both lose at corpus width. This two-level scheme reads
    the block once for a 32-way min reduce (bandwidth-bound), selects
    the k best BLOCKS per row (n/32-wide select on the KPASS engine),
    and re-reads only the k winning blocks' raw columns (m·k·32 values).

    Exactness: every true top-k element lives in one of the k
    smallest-min blocks — if its block were outside, the k selected
    blocks each contain an element no larger, displacing it (ties
    resolve by ascending block index at level 1 and ascending column at
    level 2, matching top_k's lowest-index-first order).
    Reference role: select_radix.cuh's candidate-pruning pass."""
    from ..matrix.select_k import select_k

    m, n = s.shape
    n_pad = round_up_to(n, blk)
    if k > n_pad // blk:
        # more winners than blocks: the pruning level cannot hold them;
        # plain select (top_k handles any k <= n)
        return select_k(s, k, select_min=True)
    sp = (s if n_pad == n else
          jnp.pad(s, ((0, 0), (0, n_pad - n)), constant_values=jnp.inf))
    s3 = sp.reshape(m, n_pad // blk, blk)
    bm = s3.min(axis=2)                              # (m, B)
    _, bidx = _wide_select_k(bm, k)                  # (m, k) block ids
    # ascending block order, so level-2's lowest-POSITION tie-break is
    # the lowest global COLUMN — exactly top_k's order on ties
    bidx = jnp.sort(bidx, axis=1)
    cand = jnp.take_along_axis(s3, bidx[:, :, None], axis=1)  # (m, k, blk)
    cand_cols = (bidx[:, :, None] * blk
                 + jnp.arange(blk, dtype=jnp.int32)[None, None, :])
    v, j = _wide_select_k(cand.reshape(m, k * blk), k)
    idx = jnp.take_along_axis(cand_cols.reshape(m, k * blk), j, axis=1)
    return v, idx


def _search_matmul(index: Index, q, k, filter, valid_rows, precision,
                   workspace_mb: Optional[int] = None):
    """One-shot GEMM + top_k engine, query-chunked to a workspace budget.

    On backends where XLA's fused GEMM→top_k pipeline outruns the Pallas
    kernel (dispatch-dominated regimes; measured via ops.autotune), this is
    the fastest exact path. Expanded metrics only — the distance block for
    a query chunk is one MXU GEMM plus row/col norm terms.

    ``workspace_mb`` overrides the RAFT_TPU_MATMUL_WORKSPACE_MB budget
    for this call (bigger chunks amortize per-chunk top_k fixed costs).
    """
    import os

    mt = index.metric
    n, m = index.size, q.shape[0]
    prec = jax.lax.Precision(precision)
    pen = _penalty_row(index, filter, valid_rows)

    budget = (workspace_mb if workspace_mb is not None else int(
        os.environ.get("RAFT_TPU_MATMUL_WORKSPACE_MB", "1024"))) << 20
    chunk = int(max(8, min(m, budget // max(n * 4, 1))))
    m_pad = round_up_to(m, chunk)
    qp = jnp.pad(q, ((0, m_pad - m), (0, 0)))
    dn = index.norms
    dns = None if dn is None else (
        jnp.sqrt(jnp.maximum(dn, 1e-30)) if mt is DistanceType.CosineExpanded
        else dn)

    ds = index.dataset

    def one(qc):
        if ds.dtype == jnp.bfloat16:
            lhs = qc.astype(jnp.bfloat16)
            rhs = ds
        elif ds.dtype in (jnp.int8, jnp.uint8):
            # XLA fuses the convert into the GEMM: byte rows stream from
            # HBM at 1/4 the f32 traffic; int8 scales fold in after
            lhs, rhs = qc, ds.astype(jnp.float32)
        else:
            lhs, rhs = qc, ds
        dot = jax.lax.dot_general(lhs, rhs, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32,
                                  precision=prec)
        if index.scales is not None:     # q·(s·v) = s·(q·v)
            dot = dot * index.scales[None, :]
        if mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
            qn = jnp.sum(qc * qc, axis=1, keepdims=True)
            s = jnp.maximum(qn + dns[None, :] - 2.0 * dot, 0.0)
        elif mt is DistanceType.CosineExpanded:
            qn = jnp.sqrt(jnp.maximum(jnp.sum(qc * qc, axis=1, keepdims=True),
                                      1e-30))
            s = 1.0 - dot / (qn * dns[None, :])
        else:                                   # InnerProduct: min-space -dot
            s = -dot
        if pen is not None:
            s = s + pen[None, :]
        if n >= 8192:
            # wide rows: block-min two-level select (see _blockmin_topk)
            return _blockmin_topk(s, k)
        negv, idx = jax.lax.top_k(-s, k)
        return -negv, idx

    if m_pad == chunk:
        vals, idxs = one(qp)
    else:
        vals, idxs = jax.lax.map(one, qp.reshape(m_pad // chunk, chunk, -1))
        vals = vals.reshape(m_pad, k)
        idxs = idxs.reshape(m_pad, k)
    vals, idxs = vals[:m], idxs[:m]
    idxs = jnp.where(jnp.isfinite(vals), idxs, -1)
    if mt is DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    elif mt is DistanceType.InnerProduct:
        vals = jnp.where(jnp.isfinite(vals), -vals, -jnp.inf)
    return vals, idxs


def tune_search(index: Index, queries, k: int, reps: int = 5,
                suspect_floor_s: float = 0.0):
    """Measure the search engines on-device for this shape class and cache
    the winner (consulted by ``algo="auto"``). Returns (winner, timings).

    Call eagerly (not under jit) — e.g. once at serving start, or from the
    bench harness before measuring.
    """
    from ..ops import autotune

    q = jnp.asarray(queries, jnp.float32)
    key = autotune.shape_bucket("bf_search", n=index.size, m=q.shape[0],
                                d=index.dim, k=k)
    # the index rides as a jit ARGUMENT: closure-baking it would trace
    # the dataset into the HLO as a constant, which exceeds the tunnel's
    # remote-compile request limit at memory scale (observed HTTP 413 at
    # 500k rows). The fresh_executable hook keeps that true on
    # autotune's plausibility-floor re-measure path.
    class _EngineFn:
        def __init__(self, fitted):
            self._f = fitted

        def __call__(self, qq):
            return self._f(qq, index)

        def fresh_executable(self):
            inner = self._f
            return _EngineFn(jax.jit(lambda qq, idx: inner(qq, idx)))

    def _engine(algo):
        return _EngineFn(
            jax.jit(lambda qq, idx: search(idx, qq, k, algo=algo)))

    cands = {"matmul": _engine("matmul"), "scan": _engine("scan")}
    if (index.metric in _PALLAS_METRICS and jax.default_backend() == "tpu"
            and index.size <= (128 << 10)):
        # above 128k rows the fused kernel's O(k·m·n) per-tile extraction
        # loses by >20x (r4 measurement) — keep it out of the race rather
        # than spend a tuning rep compiling a known loser
        cands["pallas"] = _engine("pallas")
    # value_read: engine choice must not be steered by a backend that
    # lies about readiness (observed: block_until_ready returning in
    # ~1 ms for TFLOP-scale batches) — each rep closes with a host read
    return autotune.tune_best(key, cands, q, reps=reps, force=True,
                              suspect_floor_s=suspect_floor_s,
                              value_read=True)


def _search_pallas(index: Index, q, k, filter, valid_rows, precision):
    """Fused Pallas distance+top-k path (the perf path on TPU)."""
    from ..ops import fused_knn

    mt = index.metric
    pen = _penalty_row(index, filter, valid_rows)
    vals, idxs = fused_knn(q, index.dataset, k, metric=_PALLAS_METRICS[mt],
                           data_norms=index.norms, penalty=pen,
                           precision=precision)
    if mt is DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    elif mt is DistanceType.InnerProduct:
        # kernel min-selects -dot; report the raw inner products
        vals = jnp.where(jnp.isfinite(vals), -vals, -jnp.inf)
    return vals, idxs


@interop.auto_convert_output
@tracing.annotate("raft_tpu::brute_force::search")
def search(
    index: Index,
    queries: jax.Array,
    k: int,
    tile_size: int = 8192,
    filter: Optional[Bitset] = None,  # noqa: A002 - mirrors reference name
    valid_rows: Optional[jax.Array] = None,
    algo: str = "auto",
    precision: str = "highest",
    workspace_mb: Optional[int] = None,
    res=None,
    query_chunk: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """k nearest neighbors of each query → (distances (m, k), indices (m, k)).

    ``filter``: optional sample bitset; cleared bits are excluded
    (the reference's bitset_filter applied to brute force).
    ``valid_rows``: optional traced scalar; rows at index >= valid_rows are
    excluded. Used by the sharded path where the per-shard row count is only
    known inside shard_map (padding shards).
    ``algo``: "pallas" (fused distance+top-k kernel: the VMEM-resident
    running-k path, role of detail/knn_brute_force.cuh:61 + select_warpsort),
    "matmul" (one-shot GEMM + top_k, query-chunked to a workspace budget),
    "scan" (composed-XLA streaming fallback, any metric), or "auto"
    (consults the ops.autotune measurement cache — populate it with
    ``tune_search`` — falling back to matmul/scan by metric; see
    ops/autotune.py for why dispatch is measured, not hard-coded).
    ``precision``: MXU precision for the distance GEMM ("highest"/"default").
    ``workspace_mb``: matmul-engine distance-block budget override (else
    RAFT_TPU_MATMUL_WORKSPACE_MB, default 1024).
    ``res``/``query_chunk``: when a Resources carries a Deadline (or an
    explicit ``query_chunk`` is given), queries run in host-level chunks
    with a cancellation/deadline checkpoint between dispatches —
    ``DeadlineExceeded`` carries the completed chunks' partial results.
    """
    q = jnp.asarray(queries, jnp.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "queries must be (m, %d), got %s", index.dim, q.shape)
    n = index.size
    expects(0 < k <= n, "k=%d out of range for index of size %d", k, n)
    if query_chunk <= 0 and deadline.carried(res) is not None:
        query_chunk = max(1, min(q.shape[0], 4096))
    # a carried deadline always takes the chunked path: even a single
    # chunk needs its pre-dispatch checkpoint (an already-expired budget
    # must raise, not dispatch)
    if query_chunk > 0 and (query_chunk < q.shape[0]
                            or deadline.carried(res) is not None):
        return run_query_chunks(
            lambda qc, _s0: search(index, qc, k, tile_size, filter,
                                   valid_rows, algo, precision,
                                   workspace_mb),
            q, query_chunk, res)
    mt = index.metric
    select_min = is_min_close(mt)
    expanded = mt in _PALLAS_METRICS

    if algo == "auto":
        import os

        from ..ops import autotune

        hit = autotune.lookup(autotune.shape_bucket(
            "bf_search", n=n, m=q.shape[0], d=index.dim, k=k))
        if hit in ("pallas", "matmul", "scan") and (
                expanded or hit == "scan"):
            algo = hit
        elif not expanded:
            algo = "scan"
        else:
            # untuned heuristic: matmul everywhere it can chunk (the
            # block-min select keeps it competitive at any width); the
            # fused pallas kernel's per-tile k-extraction is O(k·m·n) VPU
            # work and measured 28x behind at 500k rows
            # (scratch/exp_bf_engines.py, r4) — never auto-pick it above
            # 128k rows
            budget = int(os.environ.get("RAFT_TPU_MATMUL_WORKSPACE_MB",
                                        "1024")) << 20
            if n > (128 << 10) or budget // max(n * 4, 1) >= 8:
                algo = "matmul"
            else:
                algo = ("pallas" if jax.default_backend() == "tpu"
                        else "scan")
    if algo == "pallas" and index.store_dtype in (jnp.int8, jnp.uint8):
        algo = "matmul"   # byte rows ride the GEMM engines (fused convert)
    if algo == "pallas":
        expects(mt in _PALLAS_METRICS,
                "algo='pallas' supports L2/cosine/IP, got %s", mt.name)
        # guarded: a fused-kernel failure demotes this site to the exact
        # GEMM engine (ops/guarded.py)
        return guarded_call(
            "brute_force.fused",
            lambda: _search_pallas(index, q, k, filter, valid_rows,
                                   precision),
            lambda: _search_matmul(index, q, k, filter, valid_rows,
                                   precision, workspace_mb))
    if algo == "matmul":
        expects(expanded,
                "algo='matmul' supports L2/cosine/IP, got %s", mt.name)
        return _search_matmul(index, q, k, filter, valid_rows, precision,
                              workspace_mb)

    tile = min(tile_size, round_up_to(n, 128))
    n_pad = round_up_to(n, tile)
    data = jnp.pad(index.dataset, ((0, n_pad - n), (0, 0)))
    norms = index.norms
    if norms is None:
        norms = jnp.zeros((n,), jnp.float32)
    norms_p = jnp.pad(norms, (0, n_pad - n))
    n_tiles = n_pad // tile
    data_t = data.reshape(n_tiles, tile, index.dim)
    norms_t = norms_p.reshape(n_tiles, tile)
    scales_t = None
    if index.scales is not None:
        scales_t = jnp.pad(index.scales, (0, n_pad - n)).reshape(
            n_tiles, tile)

    q_norm = jnp.sum(q * q, axis=1)
    bad = jnp.inf if select_min else -jnp.inf
    col = jnp.arange(tile, dtype=jnp.int32)
    mask_bits = filter.to_mask() if filter is not None else None
    if mask_bits is not None:
        mask_t = jnp.pad(mask_bits, (0, n_pad - n)).reshape(n_tiles, tile)
    kt = min(k, tile)

    def step(carry, inp):
        best_val, best_idx = carry  # (m, k), (m, k)
        tmask = tile_scale = None
        if mask_bits is not None and scales_t is not None:
            tile_data, tile_norm, base, tmask, tile_scale = inp
        elif mask_bits is not None:
            tile_data, tile_norm, base, tmask = inp
        elif scales_t is not None:
            tile_data, tile_norm, base, tile_scale = inp
        else:
            tile_data, tile_norm, base = inp
        tile_data = dequantize_rows(tile_data, tile_scale)
        d = _tile_distances(q, q_norm, tile_data, tile_norm, mt, index.metric_arg)
        limit = n if valid_rows is None else jnp.minimum(valid_rows, n)
        valid = (base + col) < limit
        if tmask is not None:
            valid = valid & tmask
        d = jnp.where(valid[None, :], d, bad)
        t_val, t_loc = select_k(d, kt, select_min=select_min)
        t_idx = t_loc + base
        merged_val = jnp.concatenate([best_val, t_val], axis=1)
        merged_idx = jnp.concatenate([best_idx, t_idx], axis=1)
        new_val, loc = select_k(merged_val, k, select_min=select_min)
        new_idx = jnp.take_along_axis(merged_idx, loc, axis=1)
        return (new_val, new_idx), None

    init = (jnp.full((q.shape[0], k), bad, jnp.float32),
            jnp.full((q.shape[0], k), -1, jnp.int32))
    bases = jnp.arange(n_tiles, dtype=jnp.int32) * tile
    xs = [data_t, norms_t, bases]
    if mask_bits is not None:
        xs.append(mask_t)
    if scales_t is not None:
        xs.append(scales_t)
    (val, idx), _ = jax.lax.scan(step, init, tuple(xs))
    return val, idx


@interop.auto_convert_output
def knn(dataset, queries, k, metric="sqeuclidean", metric_arg: float = 2.0,
        tile_size: int = 8192):
    """One-shot build+search (the reference's free-function ``knn``)."""
    return search(build(dataset, metric, metric_arg), queries, k, tile_size)


def knn_merge_parts(
    part_distances: jax.Array,
    part_indices: jax.Array,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge per-shard top-k results: (p, m, k) → (m, k).

    Analog of detail/knn_merge_parts.cuh:172, used by the sharded (MNMG)
    search path where each shard holds globally-valid indices.
    """
    p, m, k = part_distances.shape
    d = jnp.transpose(part_distances, (1, 0, 2)).reshape(m, p * k)
    i = jnp.transpose(part_indices, (1, 0, 2)).reshape(m, p * k)
    val, loc = select_k(d, k, select_min=select_min)
    return val, jnp.take_along_axis(i, loc, axis=1)


def save(index: Index, path) -> None:
    """Serialize (analog of brute_force_serialize.cuh). bf16 datasets are
    framed as uint16 (npy has no bfloat16) with the dtype recorded in the
    header."""
    import numpy as np

    ds = index.dataset
    meta = {"metric": index.metric.value,
            "metric_arg": float(index.metric_arg),
            "store_dtype": str(ds.dtype)}
    if ds.dtype == jnp.bfloat16:
        ds = np.asarray(jax.device_get(ds)).view(np.uint16)
    arrays = {"dataset": ds}
    if index.norms is not None:
        arrays["norms"] = index.norms
    if index.scales is not None:
        arrays["scales"] = index.scales
    save_arrays(path, "brute_force", _SERIAL_VERSION, meta, arrays)


def load(path) -> Index:
    import ml_dtypes
    import numpy as np

    _, version, meta, arrays = load_arrays(path, "brute_force")
    expects(version in (1, 2), "unsupported serialization version %d", version)
    ds = np.asarray(arrays["dataset"])
    if meta.get("store_dtype") == "bfloat16":
        ds = ds.view(ml_dtypes.bfloat16)
    return Index(
        jnp.asarray(ds),
        jnp.asarray(arrays["norms"]) if "norms" in arrays else None,
        DistanceType(meta["metric"]),
        meta["metric_arg"],
        jnp.asarray(arrays["scales"]) if "scales" in arrays else None,
    )


def make_searcher(index: Index, params=None, **opts):
    """Stable batchable signature for the serving runtime
    (:mod:`raft_tpu.serve`): returns ``fn(queries, k, res=None) ->
    (distances, indices)`` with every engine choice frozen at closure
    build time, so repeated bucketed-shape calls hit the same cached
    executables. ``params`` exists for signature parity across the index
    families (brute force has no SearchParams and rejects one); ``opts``
    forwards to :func:`search` (``algo``, ``precision``, ``filter``,
    ``query_chunk``, ...)."""
    expects(params is None, "brute_force has no SearchParams; pass engine "
            "options as keywords")

    def _fn(queries, k, res=None):
        return search(index, queries, k, res=res, **opts)

    return _fn
