"""Exact brute-force kNN: analog of ``raft::neighbors::brute_force``.

Reference: raft/neighbors/brute_force-inl.cuh with the tiled engine in
detail/knn_brute_force.cuh:61 (`tiled_brute_force_knn`: row×col tiles of
pairwise distance GEMM + per-tile select_k + cross-tile merge) and the
multi-shard merge in detail/knn_merge_parts.cuh:172.

TPU design: one `lax.scan` over dataset tiles. Each step computes a
(n_queries, tile) distance block — the cross term on the MXU for expanded
metrics — takes the tile's top-k, and merges it into the running top-k
(concat + re-select, the `knn_merge_parts` trick applied streamingly).
XLA double-buffers the HBM tile reads against compute, which is exactly the
role the reference's stream-pool round-robin plays (knn_brute_force.cuh:476);
no NxM distance matrix ever exists in HBM.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core import deadline, interop, tracing
from ..core.bitset import Bitset
from ..core.errors import expects
from ..core.serialize import load_arrays, save_arrays
from ..ops.guarded import guarded_call
from ..distance.distance_types import DistanceType, canonical_metric, is_min_close
from ..distance.pairwise import _ELEMENTWISE, _elementwise_tile, _haversine
from ..matrix.select_k import select_k
from ..utils import hdot, in_jax_trace, round_up_to, run_query_chunks

__all__ = ["Index", "build", "search", "knn", "knn_merge_parts", "save",
           "load", "tune_search", "make_searcher", "prepare_fused",
           "health", "quantization_error", "health_sample_rows",
           "int8_scale_report"]

# v2: store_dtype meta + uint16-framed bf16 datasets + int8 scales; v1
# files (plain f32) remain readable
_SERIAL_VERSION = 2


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Brute-force index: the dataset plus precomputed row norms
    (brute_force_types.hpp:50 stores exactly these).

    ``dataset`` may be stored low-precision (the per-dtype dataset modes of
    detail/ivf_flat_interleaved_scan-inl.cuh:99-584 applied to brute
    force): bf16 halves, int8 quarters and int4 (nibble-packed, see
    ops/quant.py) eighths the HBM scan traffic. ``scales`` holds per-row
    dequant factors for int8/int4 (row ≈ scale * quantized_vec);
    ``norms`` are always exact f32 norms of the *stored* representation.
    ``logical_dim`` is set ONLY for int4 stores, whose packed byte width
    is not the row width.
    """

    dataset: jax.Array          # (n, d) f32 | bf16 | int8 | uint8
    norms: Optional[jax.Array]  # (n,) squared L2 norms, for expanded metrics
    metric: DistanceType
    metric_arg: float = 2.0
    scales: Optional[jax.Array] = None   # (n,) f32, int8/int4 modes only
    logical_dim: Optional[int] = None    # int4 mode: the unpacked row width

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return (self.logical_dim if self.logical_dim is not None
                else self.dataset.shape[1])

    @property
    def store_dtype(self):
        return self.dataset.dtype

    @property
    def store_name(self) -> str:
        """Storage-rung tag ("float32" | "bfloat16" | "int8" | "uint8" |
        "int4") — what autotune keys and health reports should use; the
        physical ``store_dtype`` of an int4 store is int8."""
        return ("int4" if self.logical_dim is not None
                else str(jnp.dtype(self.dataset.dtype)))

    def tree_flatten(self):
        # the fused engine's tile-aligned corpus cache (prepare_fused)
        # travels WITH the index so jitted engines can take the index as
        # an ARGUMENT and still skip the per-call pad copy (closure-baking
        # the dataset exceeds remote-compile request limits at memory
        # scale; cagra's _score_* caches set the precedent)
        fp = getattr(self, "_fused_pad", None)
        pad_leaves = tuple(fp[1:]) if fp is not None else (None,) * 4
        return ((self.dataset, self.norms, self.scales) + pad_leaves,
                (self.metric, self.metric_arg, self.logical_dim,
                 fp[0] if fp is not None else None))

    @classmethod
    def tree_unflatten(cls, aux, children):
        out = cls(children[0], children[1], aux[0], aux[1], children[2],
                  aux[2])
        if len(aux) > 3 and aux[3] is not None:
            out._fused_pad = (aux[3],) + tuple(children[3:])
        return out


# the per-row storage coding lives in ops/quant.py (the ladder's shared
# home — cagra/ivf_flat/mutable import these THROUGH this module, so the
# historical names keep working); semantics are byte-identical to the
# former local definitions
from ..ops.quant import (dequantize_rows, int8_scale_report,  # noqa: E402
                         quantize_rows)


@tracing.annotate("raft_tpu::brute_force::build")
def build(dataset: jax.Array, metric="sqeuclidean", metric_arg: float = 2.0,
          dtype=jnp.float32) -> Index:
    """Build = store dataset + precompute norms (no training).

    ``dtype``: storage dtype — float32 (exact), bfloat16 (half the HBM
    scan traffic, ~1e-3 relative distance error), int8 (quarter
    traffic, per-row symmetric quantization; the ANN-candidate mode),
    uint8 (quarter traffic, exact — byte-valued corpora like SIFT/DEEP
    only; scaled float data belongs in int8) or ``"int4"`` (eighth
    traffic: nibble-packed rows, per-row scales, in-kernel unpack on
    the fused engine — expanded metrics only; pair with
    ``refine.refine`` for exact final distances).
    """
    dataset = jnp.asarray(dataset, jnp.float32)
    expects(dataset.ndim == 2, "dataset must be (n, d)")
    mt = canonical_metric(metric)
    int4 = isinstance(dtype, str) and dtype in ("int4", "i4")
    if int4:
        expects(mt in _PALLAS_METRICS,
                "int4 storage supports L2/cosine/IP metrics, got %s",
                mt.name)
    stored, scales = quantize_rows(dataset, dtype)
    norms = None
    if mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
              DistanceType.CosineExpanded):
        from ..ops.quant import dequantize_int4

        deq = (dequantize_int4(stored, scales, dataset.shape[1]) if int4
               else dequantize_rows(stored, scales))
        norms = jnp.sum(deq * deq, axis=1)
    return Index(stored, norms, mt, metric_arg, scales,
                 dataset.shape[1] if int4 else None)


def health_sample_rows(n: int, sample: int):
    """Deterministic evenly-spread row sample for the health reports
    (numpy int array; empty for an empty index — a mid-streaming-build
    index with 0 rows must report, not raise): no RNG, so two snapshots
    of the same index agree."""
    import numpy as np

    if n <= 0:
        return np.zeros((0,), np.int64)
    take = max(1, min(int(sample), int(n)))
    return np.unique(np.linspace(0, n - 1, take).astype(np.int64))


def quantization_error(original, dequantized) -> dict:
    """Measured reconstruction error of a quantized copy vs its f32
    original (sampled rows): relative Frobenius RMSE + worst absolute
    component error — the health-report form shared by every family that
    keeps both representations."""
    import numpy as np

    o = np.asarray(original, np.float32)
    dq = np.asarray(dequantized, np.float32)
    err = o - dq
    denom = max(float(np.sqrt((o * o).mean())), 1e-30)
    return {"rel_rmse": round(float(np.sqrt((err * err).mean())) / denom, 6),
            "max_abs_err": round(float(np.abs(err).max()), 6)}


def health(index: Index, sample: int = 256) -> dict:
    """Index health report (docs/observability.md "Quality"): geometry,
    storage width, and — for int8/int4 stores — sampled per-row scale
    stats (see :func:`int8_scale_report`)."""
    import numpy as np

    report = {
        "family": "brute_force", "n": int(index.size),
        "dim": int(index.dim), "metric": index.metric.name,
        "store_dtype": index.store_name,
        "fused_cache": getattr(index, "_fused_pad", None) is not None,
    }
    dt = jnp.dtype(index.store_dtype)
    if index.logical_dim is not None:
        rows = health_sample_rows(index.size, sample)
        if rows.size:
            # same scale-step summary as int8, under the rung's own key
            report["quant"] = {
                "int4": int8_scale_report(index.scales[rows])["int8"]}
    elif dt == jnp.int8 and index.scales is not None:
        rows = health_sample_rows(index.size, sample)
        if rows.size:
            report["quant"] = int8_scale_report(index.scales[rows])
    elif dt == jnp.bfloat16:
        report["quant"] = {"bfloat16": {"rel_step": 2.0 ** -8}}
    elif dt == jnp.uint8:
        report["quant"] = {"uint8": {"exact": True}}
    return report


def _tile_distances(q, q_norm, tile, tile_norm, mt, metric_arg):
    """Distance block (n_queries, tile_rows) for one dataset tile."""
    if mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        d = jnp.maximum(q_norm[:, None] + tile_norm[None, :] - 2.0 * hdot(q, tile.T), 0.0)
        return jnp.sqrt(d) if mt is DistanceType.L2SqrtExpanded else d
    if mt is DistanceType.CosineExpanded:
        qn = jnp.sqrt(jnp.maximum(q_norm, 1e-30))
        tn = jnp.sqrt(jnp.maximum(tile_norm, 1e-30))
        return 1.0 - hdot(q, tile.T) / (qn[:, None] * tn[None, :])
    if mt is DistanceType.InnerProduct:
        return hdot(q, tile.T)
    if mt is DistanceType.Haversine:
        return _haversine(q, tile)
    if mt in (DistanceType.CorrelationExpanded, DistanceType.HellingerExpanded,
              DistanceType.RusselRaoExpanded):
        from ..distance.pairwise import _EXPANDED
        return _EXPANDED[mt](q, tile)
    expects(mt in _ELEMENTWISE, "metric %s unsupported by brute force", mt.name)
    return _elementwise_tile(q, tile, mt, metric_arg)


_PALLAS_METRICS = {
    DistanceType.L2Expanded: "l2",
    DistanceType.L2SqrtExpanded: "l2",
    DistanceType.CosineExpanded: "cos",
    DistanceType.InnerProduct: "ip",
}


def fused_capable(metric) -> bool:
    """Whether the streaming fused kernel can serve ``metric`` — the
    public predicate callers (e.g. the CAGRA graph build's engine
    choice) consult instead of reading ``_PALLAS_METRICS``."""
    from ..distance.distance_types import canonical_metric

    return canonical_metric(metric) in _PALLAS_METRICS


def _penalty_row(index: Index, filter, valid_rows):
    """(n,) additive min-space penalty: +inf on excluded rows, else 0."""
    if filter is None and valid_rows is None:
        return None
    n = index.size
    pen = jnp.zeros((n,), jnp.float32)
    if filter is not None:
        pen = jnp.where(filter.to_mask(), pen, jnp.inf)
    if valid_rows is not None:
        pen = jnp.where(jnp.arange(n) < valid_rows, pen, jnp.inf)
    return pen


def _wide_select_k(s: jax.Array, k: int):
    """Exact per-row top-k over very wide rows via chunked select_k.

    select_k's KPASS engine caps at 4096 columns (its scoped-VMEM row
    block — 8192-wide blocks compile-OOM on v5e inside larger
    programs); wider rows select per 4096-chunk first, then select
    over the surviving nc·k candidates. Exact, including top_k's lowest-index tie-break:
    per-chunk selection keeps every chunk's own full top-k, and both
    levels break ties by ascending index."""
    from ..matrix.select_k import select_k

    m, n = s.shape
    c = 4096
    if n <= c or k * 4 > c:
        # narrow rows need no chunking; huge k makes chunking both
        # pointless (nc*k ~ n survivors) and ill-formed (the per-chunk
        # select needs k <= chunk width) — lax.top_k handles any k <= n
        return select_k(s, k, select_min=True)
    n_pad = round_up_to(n, c)
    nc = n_pad // c
    sp = jnp.pad(s, ((0, 0), (0, n_pad - n)), constant_values=jnp.inf)
    cv, ci = select_k(sp.reshape(m * nc, c), k, select_min=True)
    base = (jnp.arange(nc, dtype=jnp.int32) * c)[None, :, None]
    cand_v = cv.reshape(m, nc * k)
    cand_i = (ci.reshape(m, nc, k) + base).reshape(m, nc * k)
    v, j = select_k(cand_v, k, select_min=True)
    return v, jnp.take_along_axis(cand_i, j, axis=1)


def _blockmin_topk(s: jax.Array, k: int, blk: int = 32):
    """Exact top-k of a wide distance block via 32-column block minima.

    The binding cost of a naive top_k over (m, n≈500k) is XLA's sort
    (~9 ms per 8k columns, measured); a k-pass extraction is O(k·m·n)
    VPU work — both lose at corpus width. This two-level scheme reads
    the block once for a 32-way min reduce (bandwidth-bound), selects
    the k best BLOCKS per row (n/32-wide select on the KPASS engine),
    and re-reads only the k winning blocks' raw columns (m·k·32 values).

    Exactness: every true top-k element lives in one of the k
    smallest-min blocks — if its block were outside, the k selected
    blocks each contain an element no larger, displacing it (ties
    resolve by ascending block index at level 1 and ascending column at
    level 2, matching top_k's lowest-index-first order).
    Reference role: select_radix.cuh's candidate-pruning pass."""
    from ..matrix.select_k import select_k

    m, n = s.shape
    n_pad = round_up_to(n, blk)
    if k > n_pad // blk:
        # more winners than blocks: the pruning level cannot hold them;
        # plain select (top_k handles any k <= n)
        return select_k(s, k, select_min=True)
    sp = (s if n_pad == n else
          jnp.pad(s, ((0, 0), (0, n_pad - n)), constant_values=jnp.inf))
    s3 = sp.reshape(m, n_pad // blk, blk)
    bm = s3.min(axis=2)                              # (m, B)
    _, bidx = _wide_select_k(bm, k)                  # (m, k) block ids
    # ascending block order, so level-2's lowest-POSITION tie-break is
    # the lowest global COLUMN — exactly top_k's order on ties
    bidx = jnp.sort(bidx, axis=1)
    cand = jnp.take_along_axis(s3, bidx[:, :, None], axis=1)  # (m, k, blk)
    cand_cols = (bidx[:, :, None] * blk
                 + jnp.arange(blk, dtype=jnp.int32)[None, None, :])
    v, j = _wide_select_k(cand.reshape(m, k * blk), k)
    idx = jnp.take_along_axis(cand_cols.reshape(m, k * blk), j, axis=1)
    return v, idx


def _chunked_queries(one, q, chunk: int, k: int):
    """Run the per-chunk engine ``one`` over fixed-size query chunks via
    ``lax.map`` (a single chunk dispatches directly, no map wrapper),
    padding the tail chunk and slicing the pad rows back off. Shared by
    the matmul and fused engines so their chunking semantics cannot
    drift."""
    m = q.shape[0]
    m_pad = round_up_to(m, chunk)
    qp = jnp.pad(q, ((0, m_pad - m), (0, 0)))
    if m_pad == chunk:
        vals, idxs = one(qp)
    else:
        vals, idxs = jax.lax.map(one, qp.reshape(m_pad // chunk, chunk, -1))
        vals = vals.reshape(m_pad, k)
        idxs = idxs.reshape(m_pad, k)
    return vals[:m], idxs[:m]


def _search_matmul(index: Index, q, k, filter, valid_rows, precision,
                   workspace_mb: Optional[int] = None):
    """One-shot GEMM + top_k engine, query-chunked to a workspace budget.

    On backends where XLA's fused GEMM→top_k pipeline outruns the Pallas
    kernel (dispatch-dominated regimes; measured via ops.autotune), this is
    the fastest exact path. Expanded metrics only — the distance block for
    a query chunk is one MXU GEMM plus row/col norm terms.

    ``workspace_mb`` overrides the RAFT_TPU_MATMUL_WORKSPACE_MB budget
    for this call (bigger chunks amortize per-chunk top_k fixed costs).
    """
    import os

    mt = index.metric
    n, m = index.size, q.shape[0]
    prec = jax.lax.Precision(precision)
    pen = _penalty_row(index, filter, valid_rows)

    budget = (workspace_mb if workspace_mb is not None else int(
        os.environ.get("RAFT_TPU_MATMUL_WORKSPACE_MB", "1024"))) << 20
    chunk = int(max(8, min(m, budget // max(n * 4, 1))))
    dn = index.norms
    dns = None if dn is None else (
        jnp.sqrt(jnp.maximum(dn, 1e-30)) if mt is DistanceType.CosineExpanded
        else dn)

    ds = index.dataset

    def one(qc):
        if index.logical_dim is not None:
            # int4 resident fallback: the same split-half nibble dot the
            # fused kernel runs (two half-width GEMMs — identical
            # operand grouping, so values match the kernel's), composed
            # in XLA
            from ..ops.quant import int4_nibbles

            half = ds.shape[1]
            low, high = int4_nibbles(ds.astype(jnp.int32))
            qp = jnp.pad(qc, ((0, 0), (0, 2 * half - qc.shape[1])))
            dot = (jax.lax.dot_general(
                       qp[:, :half], low, (((1,), (1,)), ((), ())),
                       preferred_element_type=jnp.float32, precision=prec)
                   + jax.lax.dot_general(
                       qp[:, half:], high, (((1,), (1,)), ((), ())),
                       preferred_element_type=jnp.float32, precision=prec))
        else:
            if ds.dtype == jnp.bfloat16:
                lhs = qc.astype(jnp.bfloat16)
                rhs = ds
            elif ds.dtype in (jnp.int8, jnp.uint8):
                # XLA fuses the convert into the GEMM: byte rows stream
                # from HBM at 1/4 the f32 traffic; int8 scales fold in
                # after
                lhs, rhs = qc, ds.astype(jnp.float32)
            else:
                lhs, rhs = qc, ds
            dot = jax.lax.dot_general(lhs, rhs, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=prec)
        if index.scales is not None:     # q·(s·v) = s·(q·v)
            dot = dot * index.scales[None, :]
        if mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
            qn = jnp.sum(qc * qc, axis=1, keepdims=True)
            s = jnp.maximum(qn + dns[None, :] - 2.0 * dot, 0.0)
        elif mt is DistanceType.CosineExpanded:
            qn = jnp.sqrt(jnp.maximum(jnp.sum(qc * qc, axis=1, keepdims=True),
                                      1e-30))
            s = 1.0 - dot / (qn * dns[None, :])
        else:                                   # InnerProduct: min-space -dot
            s = -dot
        if pen is not None:
            s = s + pen[None, :]
        if n >= 8192:
            # wide rows: block-min two-level select (see _blockmin_topk)
            return _blockmin_topk(s, k)
        negv, idx = jax.lax.top_k(-s, k)
        return -negv, idx

    vals, idxs = _chunked_queries(one, q, chunk, k)
    idxs = jnp.where(jnp.isfinite(vals), idxs, -1)
    if mt is DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    elif mt is DistanceType.InnerProduct:
        vals = jnp.where(jnp.isfinite(vals), -vals, -jnp.inf)
    return vals, idxs


def _tune_key(index: Index, m: int, k: int) -> str:
    """Autotune bucket for the engine race. The store dtype is part of
    the key: the crossovers move with HBM traffic (a bf16 corpus streams
    at half the bytes, int8 at a quarter), so a winner measured for one
    storage mode must not steer another's dispatch."""
    from ..ops import autotune

    return autotune.shape_bucket("bf_search", n=index.size, m=m,
                                 d=index.dim, k=k,
                                 store=index.store_name)


def _fused_align_key(index: Index):
    """(tn, dim_p) the fused engine derives for this index — the ONE
    place the alignment contract between ``prepare_fused`` and
    ``fused_knn``'s internal padding is computed, so the two sites
    cannot silently desynchronize (tn depends only on dim/itemsize, not
    k: ``_pick_tiles`` varies tm with k, never tn)."""
    from ..ops.fused_knn import _pick_tiles

    if index.logical_dim is not None:
        # int4: the packed byte width IS the corpus minor dim (already
        # sublane-pair aligned by quantize_int4); tiles are sized for
        # the double-half query width the split dot contracts against
        d_w = index.dataset.shape[1]
        return _pick_tiles(2 * d_w, 1, 1)[1], d_w
    dtype = index.store_dtype
    itemsize = (jnp.dtype(dtype).itemsize
                if dtype in (jnp.bfloat16, jnp.int8, jnp.uint8) else 4)
    dim_p = round_up_to(index.dim, 128)
    return _pick_tiles(dim_p, 1, itemsize)[1], dim_p


def prepare_fused(index: Index) -> None:
    """Eagerly build the fused engine's tile-aligned corpus copy and
    attach it to the index (rows padded to the dataset-tile multiple,
    dim to the 128 lane width, plus a base +inf penalty on pad rows).
    The fused kernel then reads the corpus RESIDENT in HBM across calls
    instead of re-padding (a full corpus copy) per dispatch. No-op when
    the cache already matches the current tile geometry; realigns after
    a ``RAFT_TPU_FUSED_TILES`` change. Called automatically on eager
    fused dispatch and by ``tune_search``; jit users should call it once
    before tracing — caches are never written under a trace (storing
    tracers corrupts them), so an unprepared index pays the pad inside
    every jitted call."""
    if in_jax_trace():
        # enforce, not just document: a tracer stored in the cache would
        # poison every later eager dispatch (UnexpectedTracerError →
        # guard demotion) and the key-match early return would keep it
        return
    d = index.dataset
    if d.dtype not in (jnp.bfloat16, jnp.int8, jnp.uint8):
        d = d.astype(jnp.float32)
    n, dim = d.shape
    key = _fused_align_key(index)
    tn, dim_p = key
    n_pad = round_up_to(n, min(tn, round_up_to(n, 128)))
    cache = getattr(index, "_fused_pad", None)
    if cache is not None and cache[0] == key:
        return
    d_pad = jnp.pad(d, ((0, n_pad - n), (0, dim_p - dim)))
    base_pen = jnp.pad(jnp.zeros((n,), jnp.float32), (0, n_pad - n),
                       constant_values=jnp.inf)
    norms_pad = (None if index.norms is None
                 else jnp.pad(jnp.asarray(index.norms, jnp.float32),
                              (0, n_pad - n)))
    scales_pad = (None if index.scales is None
                  else jnp.pad(jnp.asarray(index.scales, jnp.float32),
                               (0, n_pad - n)))
    index._fused_pad = (key, d_pad, norms_pad, base_pen, scales_pad)


def tune_search(index: Index, queries, k: int, reps: int = 5,
                suspect_floor_s: float = 0.0):
    """Measure the search engines on-device for this shape class and cache
    the winner (consulted by ``algo="auto"``). Returns (winner, timings).

    Call eagerly (not under jit) — e.g. once at serving start, or from the
    bench harness before measuring.
    """
    from ..ops import autotune

    q = jnp.asarray(queries, jnp.float32)
    key = _tune_key(index, q.shape[0], k)
    # the index rides as a jit ARGUMENT: closure-baking it would trace
    # the dataset into the HLO as a constant, which exceeds the tunnel's
    # remote-compile request limit at memory scale (observed HTTP 413 at
    # 500k rows). JitArgFn keeps that true on autotune's
    # plausibility-floor re-measure path.
    def _engine(algo):
        return autotune.JitArgFn(
            jax.jit(lambda qq, idx: search(idx, qq, k, algo=algo)), index)

    cands = {"matmul": _engine("matmul"), "scan": _engine("scan")}
    if index.metric in _PALLAS_METRICS and jax.default_backend() == "tpu":
        # the fused engine races at EVERY corpus size: the old 128k cap
        # guarded its O(k·m·n) per-tile extraction (a >20x loss at 500k,
        # r4), but the two-level block-min select reduced the steady-state
        # per-tile cost to one GEMM + one O(tm·tn) reduce, so the corpus
        # scan is bandwidth-bound (~n·d·itemsize bytes per batch) and the
        # race — not a constant — decides the crossover per shape bucket.
        # Only non-TPU backends sit out (the kernel exists there solely
        # as the interpret-mode test twin).
        prepare_fused(index)
        cands["pallas"] = _engine("pallas")
    # value_read: engine choice must not be steered by a backend that
    # lies about readiness (observed: block_until_ready returning in
    # ~1 ms for TFLOP-scale batches) — each rep closes with a host read
    winner, timings = autotune.tune_best(key, cands, q, reps=reps,
                                         force=True,
                                         suspect_floor_s=suspect_floor_s,
                                         value_read=True)
    if winner != "pallas":
        # the tile-aligned corpus copy is ~a corpus of extra HBM; keep it
        # only for the engine that won the race
        index.__dict__.pop("_fused_pad", None)
    return winner, timings


def _search_pallas(index: Index, q, k, filter, valid_rows, precision):
    """Fused Pallas distance+top-k path (the perf path on TPU)."""
    import os

    from ..ops import fused_knn

    mt = index.metric
    pen = _penalty_row(index, filter, valid_rows)
    ds, dn, sc = index.dataset, index.norms, index.scales
    if not in_jax_trace():
        # no-op on a matching key; builds or REALIGNS the cache after a
        # RAFT_TPU_FUSED_TILES change (fused dispatch was already chosen
        # here, so the corpus copy is earning its HBM)
        prepare_fused(index)
    cache = getattr(index, "_fused_pad", None)
    if cache is not None and cache[0] != _fused_align_key(index):
        cache = None   # stale geometry under a trace: inline pad instead
    if cache is not None:
        # tile-aligned corpus resident in HBM: no per-call pad copy
        _, ds, dn, base_pen, sc = cache
        pen = base_pen if pen is None else base_pen + jnp.pad(
            pen, (0, ds.shape[0] - index.size))

    # chunk queries to the fused engine's own budget: the kernel's VMEM
    # working set is per-tile (independent of m), so the chunk exists to
    # bound the (m, kp) output/accumulator footprint and the grid of a
    # single dispatch (graph builds push m to corpus scale). Each chunk
    # re-streams the corpus, so the default stays large — a 10k serving
    # batch is one dispatch.
    chunk = int(os.environ.get("RAFT_TPU_FUSED_QUERY_CHUNK", "16384"))
    m = q.shape[0]

    def one(qc):
        return fused_knn(qc, ds, k, metric=_PALLAS_METRICS[mt],
                         data_norms=dn, penalty=pen,
                         precision=precision, scales=sc,
                         int4_dim=index.logical_dim)

    if m > chunk > 0:
        vals, idxs = _chunked_queries(one, q, chunk, k)
    else:
        vals, idxs = one(q)
    if mt is DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    elif mt is DistanceType.InnerProduct:
        # kernel min-selects -dot; report the raw inner products
        vals = jnp.where(jnp.isfinite(vals), -vals, -jnp.inf)
    return vals, idxs


@interop.auto_convert_output
@tracing.annotate("raft_tpu::brute_force::search")
def search(
    index: Index,
    queries: jax.Array,
    k: int,
    tile_size: int = 8192,
    filter: Optional[Bitset] = None,  # noqa: A002 - mirrors reference name
    valid_rows: Optional[jax.Array] = None,
    algo: str = "auto",
    precision: str = "highest",
    workspace_mb: Optional[int] = None,
    res=None,
    query_chunk: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """k nearest neighbors of each query → (distances (m, k), indices (m, k)).

    ``filter``: optional sample bitset; cleared bits are excluded
    (the reference's bitset_filter applied to brute force).
    ``valid_rows``: optional traced scalar; rows at index >= valid_rows are
    excluded. Used by the sharded path where the per-shard row count is only
    known inside shard_map (padding shards).
    ``algo``: "pallas" (fused distance+top-k kernel: the VMEM-resident
    running-k path with the two-level block-min select, role of
    detail/knn_brute_force.cuh:61 + select_warpsort; streams every
    storage dtype — f32/bf16/int8/uint8 — in its stored width),
    "matmul" (one-shot GEMM + top_k, query-chunked to a workspace budget),
    "scan" (composed-XLA streaming fallback, any metric), or "auto"
    (consults the ops.autotune measurement cache — populate it with
    ``tune_search`` — falling back to matmul/scan by metric; see
    ops/autotune.py for why dispatch is measured, not hard-coded).
    ``precision``: MXU precision for the distance GEMM ("highest"/"default").
    ``workspace_mb``: matmul-engine distance-block budget override (else
    RAFT_TPU_MATMUL_WORKSPACE_MB, default 1024).
    ``res``/``query_chunk``: when a Resources carries a Deadline (or an
    explicit ``query_chunk`` is given), queries run in host-level chunks
    with a cancellation/deadline checkpoint between dispatches —
    ``DeadlineExceeded`` carries the completed chunks' partial results.
    """
    q = jnp.asarray(queries, jnp.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "queries must be (m, %d), got %s", index.dim, q.shape)
    n = index.size
    expects(0 < k <= n, "k=%d out of range for index of size %d", k, n)
    if query_chunk <= 0 and deadline.carried(res) is not None:
        query_chunk = max(1, min(q.shape[0], 4096))
    # a carried deadline always takes the chunked path: even a single
    # chunk needs its pre-dispatch checkpoint (an already-expired budget
    # must raise, not dispatch)
    if query_chunk > 0 and (query_chunk < q.shape[0]
                            or deadline.carried(res) is not None):
        return run_query_chunks(
            lambda qc, _s0: search(index, qc, k, tile_size, filter,
                                   valid_rows, algo, precision,
                                   workspace_mb),
            q, query_chunk, res)
    mt = index.metric
    select_min = is_min_close(mt)
    expanded = mt in _PALLAS_METRICS

    if (filter is not None and valid_rows is None
            and index.logical_dim is None and not in_jax_trace()):
        # selectivity-adaptive crossover (ops/filter_policy.py): at
        # extreme selectivity a full scan pays the whole corpus's HBM
        # traffic to penalize almost every row — gather the survivors
        # and search the compacted set instead (exact either way; int4
        # stores skip it: nibble-packed rows don't row-gather).
        from ..ops import filter_policy

        fd = (None if filter_policy.adaptive_off()
              else filter_policy.decide_graph(filter, n, index.dim, k,
                                              family="brute_force"))
        if fd is not None and fd.use_brute:
            return filter_policy.crossover(
                fd, "brute_force",
                lambda: filter_policy.survivor_brute_dense(
                    index.dataset, mt, q, k, filter, index.scales,
                    index.metric_arg),
                lambda: search(index, q, k, tile_size, filter, valid_rows,
                               algo, precision, workspace_mb))

    if algo == "auto":
        from ..ops import autotune

        hit = autotune.lookup(_tune_key(index, q.shape[0], k))
        if hit in ("pallas", "matmul", "scan") and (
                expanded or hit == "scan"):
            algo = hit
        elif not expanded:
            algo = "scan"
        else:
            # untuned heuristic: the fused engine owns corpus scale on
            # TPU — it pays corpus reads only (~n·d·itemsize bytes per
            # batch) where the GEMM engine materializes the (m, n)
            # distance block through HBM plus a select pass — but auto
            # only routes there when a prepare_fused cache is ALREADY
            # attached: an untuned read-only query must not double the
            # index's HBM footprint as a side effect, and trace-built
            # indexes (shard_map shard-locals) could never cache at all.
            # tune_search/make_searcher(algo='pallas') are the opt-ins;
            # the measured race then owns the bucket.
            if (jax.default_backend() == "tpu" and n >= (32 << 10)
                    and getattr(index, "_fused_pad", None) is not None):
                algo = "pallas"
            else:
                algo = "matmul"
    if algo == "pallas":
        expects(mt in _PALLAS_METRICS,
                "algo='pallas' supports L2/cosine/IP, got %s", mt.name)
        # guarded: a fused-kernel failure demotes this site to the exact
        # GEMM engine (ops/guarded.py)
        return guarded_call(
            "brute_force.fused",
            lambda: _search_pallas(index, q, k, filter, valid_rows,
                                   precision),
            lambda: _search_matmul(index, q, k, filter, valid_rows,
                                   precision, workspace_mb))
    if algo == "matmul":
        expects(expanded,
                "algo='matmul' supports L2/cosine/IP, got %s", mt.name)
        return _search_matmul(index, q, k, filter, valid_rows, precision,
                              workspace_mb)

    tile = min(tile_size, round_up_to(n, 128))
    n_pad = round_up_to(n, tile)
    data = jnp.pad(index.dataset, ((0, n_pad - n), (0, 0)))
    norms = index.norms
    if norms is None:
        norms = jnp.zeros((n,), jnp.float32)
    norms_p = jnp.pad(norms, (0, n_pad - n))
    n_tiles = n_pad // tile
    data_t = data.reshape(n_tiles, tile, data.shape[1])
    norms_t = norms_p.reshape(n_tiles, tile)
    scales_t = None
    if index.scales is not None:
        scales_t = jnp.pad(index.scales, (0, n_pad - n)).reshape(
            n_tiles, tile)

    q_norm = jnp.sum(q * q, axis=1)
    bad = jnp.inf if select_min else -jnp.inf
    col = jnp.arange(tile, dtype=jnp.int32)
    mask_bits = filter.to_mask() if filter is not None else None
    if mask_bits is not None:
        mask_t = jnp.pad(mask_bits, (0, n_pad - n)).reshape(n_tiles, tile)
    kt = min(k, tile)

    def step(carry, inp):
        best_val, best_idx = carry  # (m, k), (m, k)
        tmask = tile_scale = None
        if mask_bits is not None and scales_t is not None:
            tile_data, tile_norm, base, tmask, tile_scale = inp
        elif mask_bits is not None:
            tile_data, tile_norm, base, tmask = inp
        elif scales_t is not None:
            tile_data, tile_norm, base, tile_scale = inp
        else:
            tile_data, tile_norm, base = inp
        if index.logical_dim is not None:
            from ..ops.quant import dequantize_int4

            tile_data = dequantize_int4(tile_data, tile_scale, index.dim)
        else:
            tile_data = dequantize_rows(tile_data, tile_scale)
        d = _tile_distances(q, q_norm, tile_data, tile_norm, mt, index.metric_arg)
        limit = n if valid_rows is None else jnp.minimum(valid_rows, n)
        valid = (base + col) < limit
        if tmask is not None:
            valid = valid & tmask
        d = jnp.where(valid[None, :], d, bad)
        t_val, t_loc = select_k(d, kt, select_min=select_min)
        t_idx = t_loc + base
        merged_val = jnp.concatenate([best_val, t_val], axis=1)
        merged_idx = jnp.concatenate([best_idx, t_idx], axis=1)
        new_val, loc = select_k(merged_val, k, select_min=select_min)
        new_idx = jnp.take_along_axis(merged_idx, loc, axis=1)
        return (new_val, new_idx), None

    init = (jnp.full((q.shape[0], k), bad, jnp.float32),
            jnp.full((q.shape[0], k), -1, jnp.int32))
    bases = jnp.arange(n_tiles, dtype=jnp.int32) * tile
    xs = [data_t, norms_t, bases]
    if mask_bits is not None:
        xs.append(mask_t)
    if scales_t is not None:
        xs.append(scales_t)
    (val, idx), _ = jax.lax.scan(step, init, tuple(xs))
    return val, idx


@interop.auto_convert_output
@tracing.annotate("raft_tpu::brute_force::knn")
def knn(dataset, queries, k, metric="sqeuclidean", metric_arg: float = 2.0,
        tile_size: int = 8192):
    """One-shot build+search (the reference's free-function ``knn``)."""
    return search(build(dataset, metric, metric_arg), queries, k, tile_size)


def knn_merge_parts(
    part_distances: jax.Array,
    part_indices: jax.Array,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge per-shard top-k results: (p, m, k) → (m, k).

    Analog of detail/knn_merge_parts.cuh:172, used by the sharded (MNMG)
    search path where each shard holds globally-valid indices.
    """
    p, m, k = part_distances.shape
    d = jnp.transpose(part_distances, (1, 0, 2)).reshape(m, p * k)
    i = jnp.transpose(part_indices, (1, 0, 2)).reshape(m, p * k)
    val, loc = select_k(d, k, select_min=select_min)
    return val, jnp.take_along_axis(i, loc, axis=1)


def save(index: Index, path) -> None:
    """Serialize (analog of brute_force_serialize.cuh). bf16 datasets are
    framed as uint16 (npy has no bfloat16) with the dtype recorded in the
    header."""
    import numpy as np

    ds = index.dataset
    meta = {"metric": index.metric.value,
            "metric_arg": float(index.metric_arg),
            "store_dtype": index.store_name}
    if index.logical_dim is not None:
        meta["logical_dim"] = int(index.logical_dim)
    if ds.dtype == jnp.bfloat16:
        ds = np.asarray(jax.device_get(ds)).view(np.uint16)
    arrays = {"dataset": ds}
    if index.norms is not None:
        arrays["norms"] = index.norms
    if index.scales is not None:
        arrays["scales"] = index.scales
    save_arrays(path, "brute_force", _SERIAL_VERSION, meta, arrays)


def load(path) -> Index:
    import ml_dtypes
    import numpy as np

    _, version, meta, arrays = load_arrays(path, "brute_force")
    expects(version in (1, 2), "unsupported serialization version %d", version)
    ds = np.asarray(arrays["dataset"])
    if meta.get("store_dtype") == "bfloat16":
        ds = ds.view(ml_dtypes.bfloat16)
    return Index(
        jnp.asarray(ds),
        jnp.asarray(arrays["norms"]) if "norms" in arrays else None,
        DistanceType(meta["metric"]),
        meta["metric_arg"],
        jnp.asarray(arrays["scales"]) if "scales" in arrays else None,
        meta.get("logical_dim"),
    )


def make_searcher(index: Index, params=None, **opts):
    """Stable batchable signature for the serving runtime
    (:mod:`raft_tpu.serve`): returns ``fn(queries, k, res=None) ->
    (distances, indices)`` with every engine choice frozen at closure
    build time, so repeated bucketed-shape calls hit the same cached
    executables. ``params`` exists for signature parity across the index
    families (brute force has no SearchParams and rejects one); ``opts``
    forwards to :func:`search` (``algo``, ``precision``, ``filter``,
    ``query_chunk``, ...)."""
    expects(params is None, "brute_force has no SearchParams; pass engine "
            "options as keywords")
    if opts.get("algo") == "pallas":
        # serving closures dispatch eagerly: align the corpus for the
        # fused engine once at closure build, not on the first request.
        # "auto" defers to the first eager dispatch (absorbed by serve
        # warmup) so an index whose race winner is matmul never holds
        # the extra corpus copy.
        prepare_fused(index)

    def _fn(queries, k, res=None):
        return search(index, queries, k, res=res, **opts)

    return _fn
