"""Random generation: analog of ``raft/random/``.

Reference: rng_state.hpp:29-52 (RngState: seed + stream id, generator
choice), rng.cuh:50-418 (distribution kernels), make_blobs.cuh,
make_regression.cuh, rmat_rectangular_generator.cuh,
sample_without_replacement (rng.cuh:338), permute.cuh.

TPU design: JAX's counter-based PRNG (threefry) replaces
Philox/PCG — same splittable-stream semantics the reference gets from
(seed, subsequence) pairs. ``RngState`` wraps a key and hands out
per-call subkeys, so repeated calls advance state like the reference's
stateful generators. Distributions are `jax.random` one-liners; the value
here is the API surface + the dataset generators the bench harness and
tests consume.
"""
from .rng import (RngState, bernoulli, discrete, exponential, gumbel,
                  laplace, lognormal, logistic, multivariable_gaussian,
                  normal, permute, rayleigh, sample_without_replacement,
                  scaled_bernoulli, uniform, uniform_int)
from .datagen import make_blobs, make_regression, rmat_rectangular_generator

__all__ = [
    "RngState", "uniform", "uniform_int", "normal", "bernoulli",
    "scaled_bernoulli", "gumbel", "lognormal", "logistic", "exponential",
    "rayleigh", "laplace", "discrete", "sample_without_replacement",
    "permute", "multivariable_gaussian", "make_blobs", "make_regression", "rmat_rectangular_generator",
]
