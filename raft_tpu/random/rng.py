"""RngState + distribution generators (raft/random/rng.cuh:50-418)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.errors import expects

__all__ = [
    "RngState", "uniform", "uniform_int", "normal", "bernoulli",
    "scaled_bernoulli", "gumbel", "lognormal", "logistic", "exponential",
    "rayleigh", "laplace", "discrete", "sample_without_replacement",
    "permute", "multivariable_gaussian",
]


class RngState:
    """Seed + stream state (rng_state.hpp:29-52).

    Each draw splits off a fresh subkey, so successive calls produce
    independent streams, mirroring the reference's advancing subsequence
    counter. ``fork(stream)`` gives the deterministic per-stream state the
    reference builds with (seed, subsequence).
    """

    def __init__(self, seed: int = 0, stream: int = 0):
        self.seed = int(seed)
        self.stream = int(stream)
        self._key = jax.random.fold_in(jax.random.key(self.seed), self.stream)

    def next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def fork(self, stream: int) -> "RngState":
        return RngState(self.seed, stream)


def _key_of(rng) -> jax.Array:
    if isinstance(rng, RngState):
        return rng.next_key()
    return rng  # already a jax PRNG key


def uniform(rng, shape, low=0.0, high=1.0, dtype=jnp.float32):
    return jax.random.uniform(_key_of(rng), shape, dtype, low, high)


def uniform_int(rng, shape, low, high, dtype=jnp.int32):
    return jax.random.randint(_key_of(rng), shape, low, high, dtype)


def normal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return mu + sigma * jax.random.normal(_key_of(rng), shape, dtype)


def bernoulli(rng, shape, prob=0.5):
    return jax.random.bernoulli(_key_of(rng), prob, shape)


def scaled_bernoulli(rng, shape, prob=0.5, scale=1.0, dtype=jnp.float32):
    """±scale with P(+) = prob (rng.cuh scaled_bernoulli)."""
    b = jax.random.bernoulli(_key_of(rng), prob, shape)
    return jnp.where(b, dtype(scale), dtype(-scale))


def gumbel(rng, shape, mu=0.0, beta=1.0, dtype=jnp.float32):
    return mu + beta * jax.random.gumbel(_key_of(rng), shape, dtype)


def lognormal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return jnp.exp(normal(rng, shape, mu, sigma, dtype))


def logistic(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.logistic(_key_of(rng), shape, dtype)


def exponential(rng, shape, lam=1.0, dtype=jnp.float32):
    return jax.random.exponential(_key_of(rng), shape, dtype) / lam


def rayleigh(rng, shape, sigma=1.0, dtype=jnp.float32):
    u = jax.random.uniform(_key_of(rng), shape, dtype, 1e-12, 1.0)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def laplace(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return jax.random.laplace(_key_of(rng), shape, dtype) * scale + mu


def discrete(rng, shape, weights):
    """Sample indices with the given (unnormalized) weights."""
    w = jnp.asarray(weights, jnp.float32)
    return jax.random.categorical(_key_of(rng), jnp.log(jnp.maximum(w, 1e-30)),
                                  shape=shape).astype(jnp.int32)


def sample_without_replacement(
    rng, n_samples: int, pool: Optional[jax.Array] = None,
    n_population: Optional[int] = None,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Weighted sampling without replacement (rng.cuh:338).

    Same algorithm family as the reference: one Gumbel-top-k pass over the
    (log-)weights — a single sort, no rejection loop.
    """
    if pool is not None:
        pool = jnp.asarray(pool)
        n_pop = pool.shape[0]
    else:
        expects(n_population is not None, "need pool or n_population")
        n_pop = int(n_population)
    expects(0 < n_samples <= n_pop,
            "n_samples %d out of range for population %d", n_samples, n_pop)
    key = _key_of(rng)
    if weights is None:
        perm_scores = jax.random.uniform(key, (n_pop,))
    else:
        w = jnp.asarray(weights, jnp.float32)
        g = jax.random.gumbel(key, (n_pop,))
        perm_scores = -(jnp.log(jnp.maximum(w, 1e-30)) + g)
    _, idx = jax.lax.top_k(-perm_scores, n_samples)
    idx = idx.astype(jnp.int32)
    return pool[idx] if pool is not None else idx


def permute(rng, n: int) -> jax.Array:
    """Random permutation of [0, n) (permute.cuh)."""
    return jax.random.permutation(_key_of(rng), n).astype(jnp.int32)


def multivariable_gaussian(rng, n_samples: int, mean, cov) -> jax.Array:
    """(n_samples, d) draws from N(mean, cov)
    (random/multi_variable_gaussian.cuh — the reference factors cov with
    cuSOLVER and multiplies; here the same via jax.random's internal
    Cholesky path)."""
    mean = jnp.asarray(mean, jnp.float32)
    cov = jnp.asarray(cov, jnp.float32)
    expects(mean.ndim == 1 and cov.shape == (mean.shape[0], mean.shape[0]),
            "bad mean/cov shapes %s %s", mean.shape, cov.shape)
    return jax.random.multivariate_normal(
        _key_of(rng), mean, cov, shape=(n_samples,), dtype=jnp.float32)
