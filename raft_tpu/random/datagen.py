"""Dataset generators (raft/random/make_blobs.cuh, make_regression.cuh,
rmat_rectangular_generator.cuh)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.errors import expects
from .rng import RngState, _key_of

__all__ = ["make_blobs", "make_regression", "rmat_rectangular_generator"]


def make_blobs(
    n_samples: int,
    n_features: int,
    n_clusters: int = 5,
    cluster_std: float = 1.0,
    centers: Optional[jax.Array] = None,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    shuffle: bool = True,
    rng: RngState | jax.Array | int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Isotropic gaussian blobs → (data (n, d) f32, labels (n,) i32).

    Mirrors raft::random::make_blobs (make_blobs.cuh): uniform centers in
    ``center_box`` unless given, equal-sized clusters, optional shuffle.
    """
    if isinstance(rng, int):
        rng = RngState(rng)
    key_c, key_n, key_s = jax.random.split(_key_of(rng), 3)
    if centers is None:
        centers = jax.random.uniform(
            key_c, (n_clusters, n_features), jnp.float32,
            center_box[0], center_box[1])
    else:
        centers = jnp.asarray(centers, jnp.float32)
        n_clusters = centers.shape[0]
    labels = jnp.arange(n_samples, dtype=jnp.int32) % n_clusters
    noise = cluster_std * jax.random.normal(
        key_n, (n_samples, n_features), jnp.float32)
    data = centers[labels] + noise
    if shuffle:
        perm = jax.random.permutation(key_s, n_samples)
        data, labels = data[perm], labels[perm]
    return data, labels


def make_regression(
    n_samples: int,
    n_features: int,
    n_informative: Optional[int] = None,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    shuffle: bool = True,
    rng: RngState | jax.Array | int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Linear-model data → (X (n, d), y (n, t), coef (d, t))
    (make_regression.cuh)."""
    if isinstance(rng, int):
        rng = RngState(rng)
    n_informative = n_informative or n_features
    expects(n_informative <= n_features, "n_informative > n_features")
    kx, kc, kn, ks = jax.random.split(_key_of(rng), 4)
    x = jax.random.normal(kx, (n_samples, n_features), jnp.float32)
    coef = jnp.zeros((n_features, n_targets), jnp.float32)
    coef = coef.at[:n_informative].set(
        100.0 * jax.random.uniform(kc, (n_informative, n_targets)))
    y = x @ coef + bias
    if noise > 0:
        y = y + noise * jax.random.normal(kn, y.shape, jnp.float32)
    if shuffle:
        perm = jax.random.permutation(ks, n_samples)
        x, y = x[perm], y[perm]
    return x, y, coef


def rmat_rectangular_generator(
    rng: RngState | jax.Array,
    theta: jax.Array,
    r_scale: int,
    c_scale: int,
    n_edges: int,
) -> Tuple[jax.Array, jax.Array]:
    """R-MAT edge generator → (src (e,), dst (e,)) int32
    (rmat_rectangular_generator.cuh).

    ``theta``: (max(r_scale, c_scale), 4) per-level quadrant probabilities
    [a, b, c, d] (rows beyond a side's scale only split along the other
    side), or a single (4,) reused at every level.
    """
    theta = jnp.asarray(theta, jnp.float32)
    if theta.ndim == 1:
        theta = jnp.broadcast_to(theta, (max(r_scale, c_scale), 4))
    expects(theta.shape[1] == 4, "theta must have 4 quadrant probs per level")
    key = _key_of(rng)
    levels = max(r_scale, c_scale)
    u = jax.random.uniform(key, (n_edges, levels))  # one draw per level

    src = jnp.zeros((n_edges,), jnp.int32)
    dst = jnp.zeros((n_edges,), jnp.int32)
    for lvl in range(levels):
        a, b, c, d = theta[lvl]
        split_r = lvl < r_scale
        split_c = lvl < c_scale
        if split_r and split_c:
            # quadrant choice by cumulative [a, a+b, a+b+c]
            x = u[:, lvl]
            right = ((x >= a) & (x < a + b)) | (x >= a + b + c)   # col bit
            bottom = x >= a + b                                   # row bit
        elif split_r:
            p_bottom = (c + d) / jnp.maximum(a + b + c + d, 1e-30)
            bottom = u[:, lvl] < p_bottom
            right = jnp.zeros((n_edges,), bool)
        else:
            p_right = (b + d) / jnp.maximum(a + b + c + d, 1e-30)
            right = u[:, lvl] < p_right
            bottom = jnp.zeros((n_edges,), bool)
        if split_r:
            src = src * 2 + bottom.astype(jnp.int32)
        if split_c:
            dst = dst * 2 + right.astype(jnp.int32)
    return src, dst
