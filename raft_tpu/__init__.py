"""raft_tpu — a TPU-native vector-search and ML-primitives framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of RAFT (Reusable
Accelerated Functions and Tools, the CUDA library; see SURVEY.md): exact
brute-force kNN, IVF-Flat, IVF-PQ and CAGRA index build/search, balanced
k-means, pairwise distances, batched top-k selection, statistics, random data
generation, sparse primitives, and a distributed comms layer over XLA
collectives (ICI/DCN) for multi-chip sharded indexes.

Subpackages mirror the reference's domain split (SURVEY.md §1 layer map):

- ``core``      runtime context/resources, bitset, serialization
- ``distance``  pairwise distances, fused L2+argmin, kernel gram
- ``matrix``    select_k (batched top-k) and matrix ops
- ``linalg``    dense linear algebra conveniences
- ``neighbors`` brute_force / ivf_flat / ivf_pq / cagra / refine / hnsw ...
- ``cluster``   kmeans, balanced hierarchical kmeans, single-linkage
- ``sparse``    COO/CSR ops, sparse distances/kNN, MST, Lanczos
- ``random``    RNG distributions and dataset generators
- ``stats``     summary stats + clustering/ANN quality metrics
- ``solver``    linear assignment problem
- ``spectral``  spectral partitioning
- ``label``     label utilities
- ``comms``     distributed communicator over jax collectives
- ``parallel``  multi-chip (MNMG-analog) sharded algorithms
- ``ops``       Pallas TPU kernels backing the hot paths
- ``serve``     query-serving runtime: micro-batching, admission
                control, warmup, metrics (docs/serving.md)
"""

__version__ = "0.1.0"

from . import core, serve  # noqa: F401

__all__ = ["core", "serve", "__version__"]
