"""Statistics: analog of ``raft/stats/``.

Reference inventory (SURVEY §2.9): summary stats (mean/var/stddev/minmax/
histogram/cov/weighted mean) and model/cluster metrics (accuracy, r2,
rand/adjusted-rand index, mutual info, completeness, homogeneity,
v-measure, entropy, KL, silhouette, trustworthiness, dispersion,
contingency matrix, information criterion) plus the device-side ANN
quality metric ``neighborhood_recall`` (stats/neighborhood_recall.cuh:86).

Most of the reference's LoC here is per-dtype CUDA kernel plumbing; on TPU
each metric is a small jnp program, jitted at the call boundary.
"""
from .basic import (cov, histogram, mean, mean_center, meanvar, minmax,
                    stddev, weighted_mean)
from .metrics import (accuracy, adjusted_rand_index, completeness_score,
                      contingency_matrix, dispersion, entropy,
                      homogeneity_score, information_criterion,
                      kl_divergence, mutual_info_score, neighborhood_recall,
                      r2_score, rand_index, silhouette_score,
                      trustworthiness, v_measure)

__all__ = [
    "mean", "meanvar", "mean_center", "stddev", "minmax", "histogram",
    "cov", "weighted_mean",
    "accuracy", "r2_score", "rand_index", "adjusted_rand_index",
    "mutual_info_score", "completeness_score", "homogeneity_score",
    "v_measure", "entropy", "kl_divergence", "silhouette_score",
    "trustworthiness", "dispersion", "contingency_matrix",
    "information_criterion", "neighborhood_recall",
]
