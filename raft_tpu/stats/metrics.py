"""Model/cluster quality metrics (raft/stats/*.cuh) including the ANN
recall metric ``neighborhood_recall`` (stats/neighborhood_recall.cuh:86)."""
from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.errors import expects

__all__ = [
    "accuracy", "r2_score", "contingency_matrix", "rand_index",
    "adjusted_rand_index", "mutual_info_score", "entropy",
    "completeness_score", "homogeneity_score", "v_measure",
    "kl_divergence", "silhouette_score", "trustworthiness", "dispersion",
    "information_criterion", "neighborhood_recall",
]


def accuracy(predictions, labels) -> jax.Array:
    """Fraction of exact matches (stats/accuracy.cuh)."""
    p, l = jnp.asarray(predictions), jnp.asarray(labels)
    return jnp.mean((p == l).astype(jnp.float32))


def r2_score(y, y_hat) -> jax.Array:
    """Coefficient of determination (stats/regression_metrics.cuh)."""
    y = jnp.asarray(y, jnp.float32)
    y_hat = jnp.asarray(y_hat, jnp.float32)
    ss_res = jnp.sum((y - y_hat) ** 2)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return 1.0 - ss_res / jnp.maximum(ss_tot, 1e-30)


def contingency_matrix(labels_a, labels_b,
                       n_classes: Optional[int] = None) -> jax.Array:
    """(ca, cb) count matrix (stats/contingency_matrix.cuh). Labels must be
    in [0, n_classes); pass n_classes for a static shape under jit."""
    a = jnp.asarray(labels_a, jnp.int32)
    b = jnp.asarray(labels_b, jnp.int32)
    if n_classes is None:
        n_classes = int(max(int(jnp.max(a)), int(jnp.max(b))) + 1)
    m = jnp.zeros((n_classes, n_classes), jnp.int32)
    return m.at[a, b].add(1)


def rand_index(labels_a, labels_b) -> jax.Array:
    """Rand index via pair counts (stats/rand_index.cuh)."""
    a = jnp.asarray(labels_a, jnp.int32)
    b = jnp.asarray(labels_b, jnp.int32)
    same_a = a[:, None] == a[None, :]
    same_b = b[:, None] == b[None, :]
    n = a.shape[0]
    iu = jnp.triu_indices(n, k=1)
    agree = (same_a == same_b)[iu]
    return jnp.mean(agree.astype(jnp.float32))


def _comb2(x):
    return x * (x - 1.0) / 2.0


def adjusted_rand_index(labels_a, labels_b,
                        n_classes: Optional[int] = None):
    """ARI from the contingency matrix (stats/adjusted_rand_index.cuh).

    Counting runs on device; the scalar finish runs host-side in real
    float64 (under JAX's default x64-disabled config a jnp float64 cast is
    silently float32, which loses digits in the large-count cancellation)."""
    import numpy as np

    m = np.asarray(contingency_matrix(labels_a, labels_b, n_classes),
                   np.float64)
    n = m.sum()
    sum_ij = _comb2(m).sum()
    sum_a = _comb2(m.sum(axis=1)).sum()
    sum_b = _comb2(m.sum(axis=0)).sum()
    expected = sum_a * sum_b / max(_comb2(n), 1e-30)
    max_index = 0.5 * (sum_a + sum_b)
    return np.float64((sum_ij - expected) /
                      max(max_index - expected, 1e-30))


def entropy(labels, n_classes: Optional[int] = None) -> jax.Array:
    """Shannon entropy (nats) of a label distribution (stats/entropy.cuh)."""
    l = jnp.asarray(labels, jnp.int32)
    if n_classes is None:
        n_classes = int(jnp.max(l)) + 1
    counts = jnp.zeros((n_classes,), jnp.float32).at[l].add(1.0)
    p = counts / jnp.maximum(jnp.sum(counts), 1e-30)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0))


def mutual_info_score(labels_a, labels_b,
                      n_classes: Optional[int] = None):
    """MI in nats (stats/mutual_info_score.cuh). Device counting, host
    float64 finish (see adjusted_rand_index)."""
    import numpy as np

    m = np.asarray(contingency_matrix(labels_a, labels_b, n_classes),
                   np.float64)
    n = max(m.sum(), 1.0)
    pij = m / n
    pi = pij.sum(axis=1, keepdims=True)
    pj = pij.sum(axis=0, keepdims=True)
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = np.where(pij > 0, pij / np.maximum(pi * pj, 1e-300), 1.0)
        terms = np.where(pij > 0, pij * np.log(ratio), 0.0)
    return np.float64(terms.sum())


def homogeneity_score(labels_true, labels_pred,
                      n_classes: Optional[int] = None) -> jax.Array:
    """MI(t,p)/H(t) (stats/homogeneity_score.cuh)."""
    mi = mutual_info_score(labels_true, labels_pred, n_classes)
    h = entropy(labels_true, n_classes)
    return jnp.where(h > 0, mi / h, 1.0)


def completeness_score(labels_true, labels_pred,
                       n_classes: Optional[int] = None) -> jax.Array:
    """MI(t,p)/H(p) (stats/completeness_score.cuh)."""
    mi = mutual_info_score(labels_true, labels_pred, n_classes)
    h = entropy(labels_pred, n_classes)
    return jnp.where(h > 0, mi / h, 1.0)


def v_measure(labels_true, labels_pred, n_classes: Optional[int] = None,
              beta: float = 1.0) -> jax.Array:
    """Harmonic mean of homogeneity and completeness (stats/v_measure.cuh)."""
    h = homogeneity_score(labels_true, labels_pred, n_classes)
    c = completeness_score(labels_true, labels_pred, n_classes)
    denom = beta * h + c
    return jnp.where(denom > 0, (1 + beta) * h * c / denom, 0.0)


def kl_divergence(p, q) -> jax.Array:
    """KL(p || q) over probability vectors (stats/kl_divergence.cuh)."""
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    return jnp.sum(jnp.where(p > 0, p * jnp.log(p / jnp.maximum(q, 1e-30)),
                             0.0))


def silhouette_score(x, labels, n_clusters: Optional[int] = None,
                     metric="sqeuclidean") -> jax.Array:
    """Mean silhouette coefficient (stats/silhouette_score.cuh)."""
    from ..distance.pairwise import pairwise_distance

    x = jnp.asarray(x, jnp.float32)
    l = jnp.asarray(labels, jnp.int32)
    n = x.shape[0]
    if n_clusters is None:
        n_clusters = int(jnp.max(l)) + 1
    d = pairwise_distance(x, x, metric)                       # (n, n)
    onehot = jax.nn.one_hot(l, n_clusters, dtype=jnp.float32)  # (n, c)
    sums = d @ onehot                                          # (n, c)
    counts = jnp.sum(onehot, axis=0)                           # (c,)
    own = counts[l]
    # a: mean intra-cluster distance excluding self (distance to self = 0)
    a = jnp.take_along_axis(sums, l[:, None], axis=1)[:, 0] / \
        jnp.maximum(own - 1.0, 1.0)
    # b: min mean distance to other clusters
    means = sums / jnp.maximum(counts[None, :], 1.0)
    means = jnp.where(jax.nn.one_hot(l, n_clusters, dtype=bool),
                      jnp.inf, means)
    b = jnp.min(means, axis=1)
    s = (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30)
    s = jnp.where(own > 1, s, 0.0)   # singleton clusters score 0
    return jnp.mean(s)


def trustworthiness(x, x_embedded, n_neighbors: int = 5,
                    metric="sqeuclidean") -> jax.Array:
    """Embedding trustworthiness (stats/trustworthiness_score.cuh)."""
    from ..distance.pairwise import pairwise_distance

    x = jnp.asarray(x, jnp.float32)
    e = jnp.asarray(x_embedded, jnp.float32)
    n = x.shape[0]
    expects(n_neighbors < n // 2, "n_neighbors must be < n/2")
    eye = jnp.eye(n, dtype=bool)
    d_orig = jnp.where(eye, jnp.inf, pairwise_distance(x, x, metric))
    d_emb = jnp.where(eye, jnp.inf, pairwise_distance(e, e, metric))
    # ranks in original space
    order_orig = jnp.argsort(d_orig, axis=1)
    rank_orig = jnp.argsort(order_orig, axis=1)   # rank of j for row i
    nn_emb = jnp.argsort(d_emb, axis=1)[:, :n_neighbors]
    r = jnp.take_along_axis(rank_orig, nn_emb, axis=1)
    penalty = jnp.maximum(r - n_neighbors + 1, 0).astype(jnp.float32)
    scale = 2.0 / (n * n_neighbors * (2.0 * n - 3.0 * n_neighbors - 1.0))
    return 1.0 - scale * jnp.sum(penalty)


def dispersion(centroids, cluster_sizes, global_centroid=None) -> jax.Array:
    """Between-cluster dispersion (stats/dispersion.cuh)."""
    c = jnp.asarray(centroids, jnp.float32)
    sz = jnp.asarray(cluster_sizes, jnp.float32)
    if global_centroid is None:
        global_centroid = jnp.sum(c * sz[:, None], axis=0) / \
            jnp.maximum(jnp.sum(sz), 1e-30)
    return jnp.sqrt(jnp.sum(sz * jnp.sum((c - global_centroid) ** 2, axis=1)))


def information_criterion(log_likelihood, n_params: int, n_samples: int,
                          kind: str = "bic") -> jax.Array:
    """AIC/AICc/BIC batched criterion (stats/information_criterion.cuh)."""
    ll = jnp.asarray(log_likelihood, jnp.float32)
    if kind == "aic":
        return 2.0 * n_params - 2.0 * ll
    if kind == "aicc":
        corr = (2.0 * n_params * (n_params + 1) /
                max(n_samples - n_params - 1, 1))
        return 2.0 * n_params - 2.0 * ll + corr
    expects(kind == "bic", "kind must be aic|aicc|bic, got %s", kind)
    return n_params * jnp.log(jnp.float32(n_samples)) - 2.0 * ll


def neighborhood_recall(indices, ref_indices,
                        distances=None, ref_distances=None,
                        eps: float = 1e-4) -> jax.Array:
    """ANN recall against ground truth (stats/neighborhood_recall.cuh:86).

    Counts matches by id; when both distance arrays are given, a
    distance-tie within ``eps`` also counts (the reference's tied-distance
    relaxation). Returns the scalar recall over all (query, k) slots.
    """
    idx = jnp.asarray(indices)
    ref = jnp.asarray(ref_indices)
    expects(idx.shape == ref.shape, "shape mismatch %s vs %s",
            idx.shape, ref.shape)
    match = jnp.any(idx[:, :, None] == ref[:, None, :], axis=2)
    if distances is not None and ref_distances is not None:
        d = jnp.asarray(distances)
        rd = jnp.asarray(ref_distances)
        tie = jnp.any(jnp.abs(d[:, :, None] - rd[:, None, :]) <= eps, axis=2)
        match = match | tie
    return jnp.mean(match.astype(jnp.float32))
