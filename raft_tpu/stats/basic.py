"""Summary statistics (raft/stats/{mean,meanvar,stddev,minmax,histogram,
cov,weighted_mean}.cuh)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["mean", "meanvar", "mean_center", "stddev", "minmax",
           "histogram", "cov", "weighted_mean"]


def mean(x, axis: int = 0) -> jax.Array:
    return jnp.mean(jnp.asarray(x, jnp.float32), axis=axis)


def meanvar(x, axis: int = 0, sample: bool = True):
    """(mean, var) in one pass (meanvar.cuh)."""
    x = jnp.asarray(x, jnp.float32)
    mu = jnp.mean(x, axis=axis)
    ddof = 1 if sample else 0
    var = jnp.var(x, axis=axis, ddof=ddof)
    return mu, var


def mean_center(x, mu=None, axis: int = 0) -> jax.Array:
    x = jnp.asarray(x, jnp.float32)
    mu = jnp.mean(x, axis=axis, keepdims=True) if mu is None else mu
    return x - mu


def stddev(x, axis: int = 0, sample: bool = True) -> jax.Array:
    return jnp.sqrt(meanvar(x, axis, sample)[1])


def minmax(x, axis: int = 0) -> Tuple[jax.Array, jax.Array]:
    x = jnp.asarray(x)
    return jnp.min(x, axis=axis), jnp.max(x, axis=axis)


def histogram(x, n_bins: int, lo: Optional[float] = None,
              hi: Optional[float] = None) -> Tuple[jax.Array, jax.Array]:
    """Per-column histogram → (counts (bins,) or (bins, cols), edges)."""
    x = jnp.asarray(x, jnp.float32)
    lo = jnp.min(x) if lo is None else lo
    hi = jnp.max(x) if hi is None else hi
    edges = jnp.linspace(lo, hi, n_bins + 1)
    scaled = (x - lo) / jnp.maximum(hi - lo, 1e-30) * n_bins
    b = jnp.clip(scaled.astype(jnp.int32), 0, n_bins - 1)
    if x.ndim == 1:
        counts = jnp.zeros((n_bins,), jnp.int32).at[b].add(1)
    else:
        cols = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape)
        counts = jnp.zeros((n_bins, x.shape[1]), jnp.int32).at[
            b.reshape(-1), cols.reshape(-1)].add(1)
    return counts, edges


def cov(x, sample: bool = True, centered: bool = False) -> jax.Array:
    """(d, d) covariance of rows (cov.cuh)."""
    x = jnp.asarray(x, jnp.float32)
    if not centered:
        x = x - jnp.mean(x, axis=0, keepdims=True)
    denom = x.shape[0] - (1 if sample else 0)
    return jnp.matmul(x.T, x, precision="highest") / denom


def weighted_mean(x, weights, axis: int = 0) -> jax.Array:
    x = jnp.asarray(x, jnp.float32)
    w = jnp.asarray(weights, jnp.float32)
    w = jnp.expand_dims(w, axis=1 - axis) if x.ndim == 2 and w.ndim == 1 else w
    return jnp.sum(x * w, axis=axis) / jnp.maximum(jnp.sum(w, axis=axis), 1e-30)
