"""Batched top-k selection: analog of ``raft::matrix::select_k``.

Reference: raft/matrix/detail/select_radix.cuh (radix "AIR top-k") and
select_warpsort.cuh (bitonic warp queues), with a heuristic auto-choice
(select_k-inl.cuh:48-72). Used by brute force, IVF-Flat, IVF-PQ and CAGRA.

TPU design, two engines (mirroring the reference's two families):

* ``TOPK`` — XLA's ``lax.top_k`` partial sort. Near-free on narrow rows
  (n ≲ 256) but its cost grows super-linearly with row length: ~3 ms at
  (10k, 1024, k=20) and ~9 ms at (10k, 8192, k=10) on the measured chip.
* ``KPASS`` — a Pallas kernel running the flat-scan's k-pass min-extract
  over 128-row blocks (the warpsort-queue role): k vectorized
  min+invalidate sweeps per row block, entirely in VMEM. Slope-measured
  ~6x faster than TOPK at (10k, 1024, k=20) (0.5 vs 3.0 ms) and ~4x at
  (10k, 8192, k=10) (scratch/exp_select_slope_r5.json, r5). Exact, same
  tie-breaking as top_k (lowest index first).

``RADIX`` remains an alias: the radix/AIR histogram engine does not
transfer to TPU (histograms lower to serialized scatters or FLOP-heavy
one-hot contractions; the r3 sweep in bench_select_k_sweep.json showed
no winnable shape). ``AUTO`` picks KPASS on TPU for f32 rows with
k ≤ 64 and 512 ≤ n ≤ 4096, TOPK otherwise. The column cap is a VMEM
bound, not a tuning choice: the kernel keeps ~5 live (128, n) f32/i32
planes on the scoped-VMEM stack, and measured compile-time OOMs on v5e
put (128, 15744) at 24.8 MB and even (128, 8192) at 21.3 MB inside a
larger program against the 16 MB scoped limit — 4096 (~10.5 MB) is the
rehearsed-safe width. Callers with wider rows chunk first
(brute_force._wide_select_k).
"""
from __future__ import annotations

import enum
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..core import interop, tracing

__all__ = ["SelectAlgo", "select_k", "tune_select_k"]

_INT_BIG = 2 ** 30


class SelectAlgo(enum.Enum):
    """Mirror of raft/matrix/select_k_types.hpp:36.

    ``KPASS`` is this library's warpsort-queue analog (see module
    docstring); ``RADIX`` stays an alias of TOPK so reference callers
    porting ``select_k(..., SelectAlgo::kRadix...)`` keep working.
    """

    AUTO = "auto"
    TOPK = "topk"        # direct lax.top_k
    KPASS = "kpass"      # Pallas k-pass min-extract (warpsort role)
    RADIX = "radix"      # alias of TOPK on TPU (no histogram engine)


def _topk_smallest(values: jax.Array, k: int, select_min: bool):
    v = -values if select_min else values
    vals, idxs = jax.lax.top_k(v, k)
    return (-vals if select_min else vals), idxs


# --------------------------------------------------------------------------
# KPASS engine
# --------------------------------------------------------------------------

def _kpass_kernel(x_ref, ov_ref, oi_ref, *, k: int, kp: int, n: int,
                  n_real: int):
    """k passes of (row-min, invalidate) over a (128, n) VMEM block.

    Tie-break matches lax.top_k: among equal values the lowest column
    wins. An explicit alive MASK (not +inf overwrites) tracks extracted
    cells — +inf is a legal input value (filter penalties, pad columns)
    and overwriting with it would re-extract column 0 forever once an
    inf enters the top-k. ``n_real`` confines selection to genuine
    columns so +inf PADDING can never be returned as an index."""
    x = x_ref[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (128, n), 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (128, kp), 1)
    alive0 = col < n_real

    def extract(t, state):
        alive, nv, ni = state
        masked = jnp.where(alive, x, jnp.inf)
        best = jnp.min(masked, axis=1, keepdims=True)
        pos = jnp.min(jnp.where(alive & (masked <= best), col, _INT_BIG),
                      axis=1, keepdims=True)
        at = col == pos
        nv = jnp.where(lane == t, best, nv)
        ni = jnp.where(lane == t, pos, ni)
        return alive & ~at, nv, ni

    state = (alive0, jnp.full((128, kp), jnp.inf, jnp.float32),
             jnp.full((128, kp), -1, jnp.int32))
    if k <= 32:
        for t in range(k):
            state = extract(t, state)
    else:
        state = jax.lax.fori_loop(0, k, extract, state)
    ov_ref[0] = state[1]
    oi_ref[0] = state[2]


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def _kpass_2d(values: jax.Array, k: int, interpret: bool):
    """(m, n) f32 → k smallest per row via the Pallas kernel.

    Rows pad to a 128 multiple (dropped after), columns to a 128
    multiple with +inf."""
    from jax.experimental import pallas as pl

    from ..utils import round_up_to

    m, n = values.shape
    mp = round_up_to(m, 128)
    np_ = round_up_to(n, 128)
    kp = round_up_to(k, 128)
    x = jnp.pad(values.astype(jnp.float32),
                ((0, mp - m), (0, np_ - n)),
                constant_values=jnp.inf)
    mb = mp // 128
    call = pl.pallas_call(
        functools.partial(_kpass_kernel, k=k, kp=kp, n=np_, n_real=n),
        grid=(mb,),
        in_specs=[pl.BlockSpec((1, 128, np_), lambda i: (i, 0, 0))],
        out_specs=[pl.BlockSpec((1, 128, kp), lambda i: (i, 0, 0)),
                   pl.BlockSpec((1, 128, kp), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((mb, 128, kp), jnp.float32),
                   jax.ShapeDtypeStruct((mb, 128, kp), jnp.int32)],
        interpret=interpret,
    )
    v, i = call(x.reshape(mb, 128, np_))
    return (v[:, :, :k].reshape(mp, k)[:m],
            i[:, :, :k].reshape(mp, k)[:m])


def _kpass_smallest(values: jax.Array, k: int, select_min: bool):
    interpret = jax.default_backend() != "tpu"
    v2 = values if select_min else -values
    lead = values.shape[:-1]
    flat = v2.reshape(-1, values.shape[-1])
    vals, idxs = _kpass_2d(flat, k, interpret)
    vals = vals.reshape(*lead, k)
    idxs = idxs.reshape(*lead, k)
    if not select_min:
        vals = -vals
    # match TOPK's dtype contract: values come back in the input dtype
    # (the kernel computes in f32)
    return vals.astype(values.dtype), idxs


def _kpass_safe(values: jax.Array, k: int) -> bool:
    """Shapes the kernel can COMPILE and run sanely: the scoped-VMEM
    column cap, a supported dtype, and a real TPU backend (interpret
    mode exists for unit tests only — dispatching it on hot paths is a
    correctness-of-performance bug)."""
    n = values.shape[-1]
    return (n <= 4096 and jax.default_backend() == "tpu"
            and values.dtype in (jnp.float32, jnp.bfloat16, jnp.float16))


def _kpass_eligible(values: jax.Array, k: int) -> bool:
    """Safety bounds plus the measured-win heuristic window (used when
    no tuning cache entry exists)."""
    rows = 1
    for s in values.shape[:-1]:
        rows *= s
    return (_kpass_safe(values, k) and k <= 64 and values.shape[-1] >= 512
            and rows >= 512)


def tune_select_k(rows: int, n: int, k: int, select_min: bool = True,
                  reps: int = 5):
    """Measure both engines for this shape class on-device and cache the
    winner (the measurement role of the reference's
    ``choose_select_k_algorithm`` table, select_k-inl.cuh:48-72). Call
    eagerly, not under jit."""
    from ..ops import autotune

    x = jax.random.normal(jax.random.PRNGKey(0), (rows, n), jnp.float32)
    key = autotune.shape_bucket("select_k", n=n, k=k)
    cands = {
        "topk": jax.jit(lambda v: _topk_smallest(v, k, select_min)),
    }
    if _kpass_safe(x, k):
        # shapes past the VMEM column cap must not even be measured
        # (compile-time OOM), and off-TPU the kernel only exists in
        # interpret mode — nothing real to measure
        cands["kpass"] = jax.jit(lambda v: _kpass_smallest(v, k, select_min))
    return autotune.tune_best(key, cands, x, reps=reps, force=True)


@interop.auto_convert_output
@tracing.annotate("raft_tpu::matrix::select_k")
def select_k(
    values: jax.Array,
    k: int,
    select_min: bool = True,
    indices: Optional[jax.Array] = None,
    algo: SelectAlgo | str = SelectAlgo.AUTO,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row k smallest (or largest) of ``values`` (..., n).

    Returns (values (..., k), indices i32 (..., k)), sorted best-first.
    ``indices`` optionally maps positions to global ids (the reference's
    in-idx pass-through used when selecting across tiles).
    """
    algo = SelectAlgo(algo) if not isinstance(algo, SelectAlgo) else algo
    n = values.shape[-1]
    expects(0 < k <= n, "k=%d out of range for row length %d", k, n)
    if algo is SelectAlgo.AUTO:
        # measured winner first (tune_select_k's cache), static
        # eligibility heuristic otherwise
        from ..ops import autotune

        hit = autotune.lookup(autotune.shape_bucket("select_k", n=n, k=k))
        if hit == "kpass" and _kpass_safe(values, k):
            # a measured win needs only the safety bounds, not the
            # untuned heuristic window — the tuner's verdict is honored
            # for every shape it could actually have measured
            algo = SelectAlgo.KPASS
        elif hit == "topk":
            algo = SelectAlgo.TOPK
        else:
            algo = (SelectAlgo.KPASS if _kpass_eligible(values, k)
                    else SelectAlgo.TOPK)
    if algo is SelectAlgo.KPASS:
        # guarded: a KPASS compile/execution failure (unrehearsed shape,
        # new chip generation) demotes to the exact TOPK engine instead
        # of failing the call (ops/guarded.py)
        from ..ops.guarded import guarded_call

        vals, idxs = guarded_call(
            "select_k.kpass",
            lambda: _kpass_smallest(values, k, select_min),
            lambda: _topk_smallest(values, k, select_min))
    else:
        vals, idxs = _topk_smallest(values, k, select_min)
    if indices is not None:
        idxs = jnp.take_along_axis(indices, idxs, axis=-1)
    return vals, idxs.astype(jnp.int32) if idxs.dtype != jnp.int32 else idxs
