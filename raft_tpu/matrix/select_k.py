"""Batched top-k selection: analog of ``raft::matrix::select_k``.

Reference: raft/matrix/detail/select_radix.cuh (radix "AIR top-k") and
select_warpsort.cuh (bitonic warp queues), with a heuristic auto-choice
(select_k-inl.cuh:48-72). Used by brute force, IVF-Flat, IVF-PQ and CAGRA.

TPU design: the workhorse is XLA's ``lax.top_k``, which lowers to an
optimized TPU partial-sort — the role the warpsort family plays on GPU.
The reference's second engine (radix/AIR top-k) does NOT transfer: it is
built on fast shared-memory histograms, and a histogram on TPU lowers to
either a scatter-add (serialized) or a (n, 256) one-hot contraction whose
FLOPs exceed the sort it would replace; a bucket pre-filter that merely
masks values feeds the same-shape input to ``lax.top_k`` and cannot win
(its cost is shape-dependent). An on-chip sweep confirmed this: every
(rows, n, k) class measured within dispatch noise of plain top_k
(bench_select_k_sweep.json at the repo root). ``SelectAlgo.RADIX`` is
therefore kept for API parity but documented as an alias of TOPK; the
measured sweep is the evidence the reference encodes in its per-arch
``choose_select_k_algorithm`` table.
"""
from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..core import interop, tracing

__all__ = ["SelectAlgo", "select_k", "tune_select_k"]


class SelectAlgo(enum.Enum):
    """Mirror of raft/matrix/select_k_types.hpp:36.

    On TPU every name maps to the same sort-based engine (see module
    docstring for the measured justification); the enum exists so
    reference callers porting ``select_k(..., SelectAlgo::kRadix...)``
    keep working.
    """

    AUTO = "auto"
    TOPK = "topk"        # direct lax.top_k (warpsort analog)
    RADIX = "radix"      # alias of TOPK on TPU (no histogram engine)


def _topk_smallest(values: jax.Array, k: int, select_min: bool):
    v = -values if select_min else values
    vals, idxs = jax.lax.top_k(v, k)
    return (-vals if select_min else vals), idxs


def tune_select_k(rows: int, n: int, k: int, select_min: bool = True,
                  reps: int = 5):
    """Calibration probe for the (single) top-k engine — call eagerly,
    not under jit.

    With one engine nothing dispatches on the result: the recorded
    timing exists so regressions in the backend's sort lowering are
    visible across runs (the measurement role of the reference's
    ``choose_select_k_algorithm`` table, select_k-inl.cuh:48-72), not to
    steer ``algo="auto"`` — every algo name maps to the same engine on
    TPU (see module docstring)."""
    from ..ops import autotune

    x = jax.random.normal(jax.random.PRNGKey(0), (rows, n), jnp.float32)
    key = autotune.shape_bucket("select_k", n=n, k=k)
    cands = {
        "topk": jax.jit(lambda v: _topk_smallest(v, k, select_min)),
    }
    return autotune.tune_best(key, cands, x, reps=reps, force=True)


@interop.auto_convert_output
@tracing.annotate("raft_tpu::matrix::select_k")
def select_k(
    values: jax.Array,
    k: int,
    select_min: bool = True,
    indices: Optional[jax.Array] = None,
    algo: SelectAlgo | str = SelectAlgo.AUTO,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row k smallest (or largest) of ``values`` (..., n).

    Returns (values (..., k), indices i32 (..., k)), sorted best-first.
    ``indices`` optionally maps positions to global ids (the reference's
    in-idx pass-through used when selecting across tiles).
    """
    algo = SelectAlgo(algo) if not isinstance(algo, SelectAlgo) else algo
    n = values.shape[-1]
    expects(0 < k <= n, "k=%d out of range for row length %d", k, n)
    vals, idxs = _topk_smallest(values, k, select_min)
    if indices is not None:
        idxs = jnp.take_along_axis(indices, idxs, axis=-1)
    return vals, idxs.astype(jnp.int32) if idxs.dtype != jnp.int32 else idxs
