"""Batched top-k selection: analog of ``raft::matrix::select_k``.

Reference: raft/matrix/detail/select_radix.cuh (radix "AIR top-k") and
select_warpsort.cuh (bitonic warp queues), with a heuristic auto-choice
(select_k-inl.cuh:48-72). Used by brute force, IVF-Flat, IVF-PQ and CAGRA.

TPU design: the workhorse is XLA's `lax.top_k`, which lowers to an optimized
TPU sort network — the role the warpsort family plays on GPU. For the shapes
where a two-pass approach wins (huge rows, small k), `algo="radix"`
bucket-filters candidates first (the AIR-top-k idea) before running top_k on
the survivors. `algo="auto"` consults the on-device measurement cache
(populate with ``tune_select_k`` — the measured analog of the reference's
per-arch ``choose_select_k_algorithm`` table, select_k-inl.cuh:48-72),
falling back to a heuristic recorded from an on-chip sweep: radix wins for
very wide rows with small k (see ``_AUTO_RADIX``).
"""
from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..core import interop, tracing

__all__ = ["SelectAlgo", "select_k", "tune_select_k"]


class SelectAlgo(enum.Enum):
    """Mirror of raft/matrix/select_k_types.hpp:36."""

    AUTO = "auto"
    TOPK = "topk"        # direct lax.top_k (warpsort analog)
    RADIX = "radix"      # two-pass threshold filter + top_k (AIR analog)


def _topk_smallest(values: jax.Array, k: int, select_min: bool):
    v = -values if select_min else values
    vals, idxs = jax.lax.top_k(v, k)
    return (-vals if select_min else vals), idxs


def _radix_two_pass(values: jax.Array, k: int, select_min: bool):
    """Histogram-threshold pre-filter, then exact top-k over survivors.

    A simplified AIR-top-k: one 256-bucket histogram pass bounds the k-th
    value's bucket; only candidates at or beyond that bucket go through the
    final sort. On TPU the benefit appears for very wide rows (len >> 16k)
    where the full sort's O(n log n) dominates; the histogram is one
    scan + cumsum.
    """
    v = -values if select_min else values  # selecting largest of v
    n = v.shape[-1]
    lo = jnp.min(v, axis=-1, keepdims=True)
    hi = jnp.max(v, axis=-1, keepdims=True)
    scale = jnp.where(hi > lo, 255.0 / (hi - lo), 0.0)
    buckets = ((v - lo) * scale).astype(jnp.int32)  # 0..255, higher = larger
    hist = jax.vmap(lambda b: jnp.bincount(b, length=256))(
        buckets.reshape(-1, n)).reshape(*v.shape[:-1], 256)
    # count of elements in buckets >= b
    tail = jnp.cumsum(hist[..., ::-1], axis=-1)[..., ::-1]
    # smallest bucket whose tail count >= k: all top-k live at or above it
    thresh_bucket = jnp.argmax((tail >= k).astype(jnp.int32) *
                               jnp.arange(256, dtype=jnp.int32), axis=-1)
    keep = buckets >= thresh_bucket[..., None]
    neg_inf = jnp.array(-jnp.inf, v.dtype)
    vals, idxs = jax.lax.top_k(jnp.where(keep, v, neg_inf), k)
    return (-vals if select_min else vals), idxs


def _auto_choice(n: int, k: int) -> "SelectAlgo":
    """auto = the cached on-device measurement for this (n, k) class, else
    topk. The untuned fallback is deliberately NOT radix: on TPU the
    bucket pre-filter masks values but cannot shrink lax.top_k's input
    (its cost is shape-dependent), so radix only wins where a recorded
    measurement says the masked sort is cheaper on that hardware — run
    ``tune_select_k`` to populate the cache; a recorded on-chip sweep
    ships in bench_select_k_sweep.json at the repo root."""
    from ..ops import autotune

    hit = autotune.lookup(autotune.shape_bucket("select_k", n=n, k=k))
    if hit in ("topk", "radix"):
        return SelectAlgo(hit)
    return SelectAlgo.TOPK


def tune_select_k(rows: int, n: int, k: int, select_min: bool = True,
                  reps: int = 5):
    """Measure topk vs radix for this shape class on the current device and
    cache the winner for ``algo="auto"`` (call eagerly, not under jit)."""
    from ..ops import autotune

    x = jax.random.normal(jax.random.PRNGKey(0), (rows, n), jnp.float32)
    key = autotune.shape_bucket("select_k", n=n, k=k)
    cands = {
        "topk": jax.jit(lambda v: _topk_smallest(v, k, select_min)),
        "radix": jax.jit(lambda v: _radix_two_pass(v, k, select_min)),
    }
    return autotune.tune_best(key, cands, x, reps=reps, force=True)


@interop.auto_convert_output
@tracing.annotate("raft_tpu::matrix::select_k")
def select_k(
    values: jax.Array,
    k: int,
    select_min: bool = True,
    indices: Optional[jax.Array] = None,
    algo: SelectAlgo | str = SelectAlgo.AUTO,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row k smallest (or largest) of ``values`` (..., n).

    Returns (values (..., k), indices i32 (..., k)), sorted best-first.
    ``indices`` optionally maps positions to global ids (the reference's
    in-idx pass-through used when selecting across tiles).
    """
    algo = SelectAlgo(algo) if not isinstance(algo, SelectAlgo) else algo
    n = values.shape[-1]
    expects(0 < k <= n, "k=%d out of range for row length %d", k, n)
    if algo is SelectAlgo.AUTO:
        algo = _auto_choice(n, k)
    if algo is SelectAlgo.RADIX and k < n:
        vals, idxs = _radix_two_pass(values, k, select_min)
    else:
        vals, idxs = _topk_smallest(values, k, select_min)
    if indices is not None:
        idxs = jnp.take_along_axis(indices, idxs, axis=-1)
    return vals, idxs.astype(jnp.int32) if idxs.dtype != jnp.int32 else idxs
