"""Matrix operations: analog of the ``raft/matrix/`` op headers (SURVEY §2.5).

Thin, jit-friendly wrappers: on TPU most of these are single XLA ops; they
exist so consumers of the reference API find the same surface (argmax,
col_sort, gather/scatter, linewise_op, slice, reverse, norm, init, diagonal,
triangular, print).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects

__all__ = [
    "argmax", "argmin", "sort_cols_per_row", "gather", "gather_if", "scatter",
    "linewise_op", "slice_matrix", "col_reverse", "row_reverse", "l2_norm",
    "eye", "fill", "get_diagonal", "set_diagonal", "invert_diagonal",
    "upper_triangular", "lower_triangular", "print_matrix",
    "row_weighted_mean", "col_weighted_mean",
]


def argmax(m: jax.Array) -> jax.Array:
    """Per-row argmax (matrix/argmax.cuh)."""
    return jnp.argmax(m, axis=1).astype(jnp.int32)


def argmin(m: jax.Array) -> jax.Array:
    return jnp.argmin(m, axis=1).astype(jnp.int32)


def sort_cols_per_row(m: jax.Array, ascending: bool = True):
    """Sort each row, returning (sorted, source indices) (matrix/col_wise_sort.cuh)."""
    idx = jnp.argsort(m if ascending else -m, axis=1)
    return jnp.take_along_axis(m, idx, axis=1), idx.astype(jnp.int32)


def gather(m: jax.Array, row_ids: jax.Array) -> jax.Array:
    """Row gather (matrix/gather.cuh)."""
    return jnp.take(m, row_ids, axis=0)


def gather_if(m: jax.Array, row_ids: jax.Array, mask: jax.Array, fill_value=0.0):
    """Row gather with a per-output mask; masked rows become fill_value."""
    out = jnp.take(m, row_ids, axis=0)
    return jnp.where(mask[:, None], out, jnp.asarray(fill_value, out.dtype))


def scatter(m: jax.Array, row_ids: jax.Array, rows: jax.Array) -> jax.Array:
    """Functional row scatter (matrix/scatter.cuh)."""
    return m.at[row_ids].set(rows)


def linewise_op(m: jax.Array, vec: jax.Array, along_rows: bool,
                op: Callable[[jax.Array, jax.Array], jax.Array]) -> jax.Array:
    """Broadcast a vector op along rows or columns (matrix/linewise_op.cuh)."""
    if along_rows:  # vec has one entry per column
        expects(vec.shape[0] == m.shape[1], "vec len %d != ncols %d", vec.shape[0], m.shape[1])
        return op(m, vec[None, :])
    expects(vec.shape[0] == m.shape[0], "vec len %d != nrows %d", vec.shape[0], m.shape[0])
    return op(m, vec[:, None])


def slice_matrix(m: jax.Array, row0: int, col0: int, row1: int, col1: int) -> jax.Array:
    """Submatrix copy [row0:row1, col0:col1] (matrix/slice.cuh)."""
    return m[row0:row1, col0:col1]


def col_reverse(m: jax.Array) -> jax.Array:
    return m[:, ::-1]


def row_reverse(m: jax.Array) -> jax.Array:
    return m[::-1]


def l2_norm(m: jax.Array) -> jax.Array:
    """Frobenius norm (matrix/norm.cuh)."""
    return jnp.sqrt(jnp.sum(m.astype(jnp.float32) ** 2))


def eye(n: int, m: Optional[int] = None, dtype=jnp.float32) -> jax.Array:
    return jnp.eye(n, m, dtype=dtype)


def fill(shape, value, dtype=jnp.float32) -> jax.Array:
    return jnp.full(shape, value, dtype=dtype)


def get_diagonal(m: jax.Array) -> jax.Array:
    return jnp.diagonal(m)


def set_diagonal(m: jax.Array, d: jax.Array) -> jax.Array:
    n = min(m.shape[0], m.shape[1])
    i = jnp.arange(n)
    return m.at[i, i].set(d[:n])


def invert_diagonal(m: jax.Array) -> jax.Array:
    n = min(m.shape[0], m.shape[1])
    i = jnp.arange(n)
    return m.at[i, i].set(1.0 / m[i, i])


def upper_triangular(m: jax.Array, k: int = 0) -> jax.Array:
    return jnp.triu(m, k)


def lower_triangular(m: jax.Array, k: int = 0) -> jax.Array:
    return jnp.tril(m, k)


def row_weighted_mean(m: jax.Array, weights: jax.Array) -> jax.Array:
    """Weighted mean of each row; ``weights`` has one entry per column."""
    return (m @ weights) / jnp.sum(weights)


def col_weighted_mean(m: jax.Array, weights: jax.Array) -> jax.Array:
    """Weighted mean of each column; ``weights`` has one entry per row."""
    return (weights @ m) / jnp.sum(weights)


def print_matrix(m: jax.Array, name: str = "") -> str:
    """Host-side pretty print (matrix/print.cuh)."""
    s = f"{name} {tuple(m.shape)} {m.dtype}\n{np.asarray(m)}"
    print(s)
    return s
