"""Matrix ops and batched top-k selection (SURVEY.md §2.5)."""
from .ops import (
    argmax, argmin, col_reverse, col_weighted_mean, eye, fill, gather,
    gather_if, get_diagonal, invert_diagonal, l2_norm, linewise_op,
    lower_triangular, print_matrix, row_reverse, row_weighted_mean, scatter,
    set_diagonal, slice_matrix, sort_cols_per_row, upper_triangular,
)
from .select_k import SelectAlgo, select_k

__all__ = [
    "argmax", "argmin", "col_reverse", "col_weighted_mean", "eye", "fill",
    "gather", "gather_if", "get_diagonal", "invert_diagonal", "l2_norm",
    "linewise_op", "lower_triangular", "print_matrix", "row_reverse",
    "row_weighted_mean", "scatter", "set_diagonal", "slice_matrix",
    "sort_cols_per_row", "upper_triangular", "SelectAlgo", "select_k",
]
