"""Comms self-tests: analog of ``raft/comms/comms_test.hpp:34-84``.

Each ``test_collective_*`` runs the real collective inside shard_map over
the given mesh and returns True on success — callable from user code for
cluster smoke-tests, exactly like the reference's perform_test_comms_*
entry points surfaced through raft-dask (comms_utils.pyx:78-175).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .comms import AxisComms
from ..utils import shard_map_compat

__all__ = [
    "test_collective_allreduce", "test_collective_broadcast",
    "test_collective_reduce", "test_collective_allgather",
    "test_collective_gather", "test_collective_reducescatter",
    "test_pointToPoint_ring", "test_commsplit", "run_all",
]


def _run(mesh: Mesh, fn, out_specs=P()):
    axis = mesh.axis_names[0]
    comms = AxisComms(axis, size=mesh.shape[axis])
    shmap = shard_map_compat(functools.partial(fn, comms), mesh=mesh,
                          in_specs=(), out_specs=out_specs, check=False)
    return np.asarray(jax.jit(shmap)())


def test_collective_allreduce(mesh: Mesh) -> bool:
    """Each rank contributes 1; result must be size (comms_test.hpp:34)."""
    p = mesh.devices.size

    def body(comms):
        return comms.allreduce(jnp.float32(1.0))

    return bool(_run(mesh, body) == p)


def test_collective_broadcast(mesh: Mesh) -> bool:
    """Root holds 42; everyone must end with 42."""
    def body(comms):
        rank = comms.get_rank()
        val = jnp.where(rank == 0, jnp.float32(42.0), jnp.float32(0.0))
        got = comms.bcast(val, root=0)
        return comms.allreduce((got == 42.0).astype(jnp.float32))

    p = mesh.devices.size
    return bool(_run(mesh, body) == p)


def test_collective_reduce(mesh: Mesh) -> bool:
    def body(comms):
        red = comms.reduce(jnp.float32(1.0), root=0)
        rank = comms.get_rank()
        ok = jnp.where(rank == 0, red == comms.get_size(), red == 0.0)
        return comms.allreduce(ok.astype(jnp.float32))

    p = mesh.devices.size
    return bool(_run(mesh, body) == p)


def test_collective_allgather(mesh: Mesh) -> bool:
    """Gather ranks; every rank must see [0..p)."""
    def body(comms):
        g = comms.allgather(comms.get_rank().astype(jnp.float32))
        want = jnp.arange(comms.get_size(), dtype=jnp.float32)
        return comms.allreduce(jnp.all(g == want).astype(jnp.float32))

    p = mesh.devices.size
    return bool(_run(mesh, body) == p)


def test_collective_gather(mesh: Mesh) -> bool:
    def body(comms):
        g = comms.gather(comms.get_rank().astype(jnp.float32), root=0)
        want = jnp.arange(comms.get_size(), dtype=jnp.float32)
        rank = comms.get_rank()
        ok = jnp.where(rank == 0, jnp.all(g == want), jnp.all(g == 0.0))
        return comms.allreduce(ok.astype(jnp.float32))

    p = mesh.devices.size
    return bool(_run(mesh, body) == p)


def test_collective_reducescatter(mesh: Mesh) -> bool:
    """Each rank contributes [0..p); rank r must end with p * r."""
    def body(comms):
        p = comms.get_size()
        contrib = jnp.arange(p, dtype=jnp.float32)
        mine = comms.reducescatter(contrib)
        want = comms.get_rank().astype(jnp.float32) * p
        return comms.allreduce(jnp.all(mine == want).astype(jnp.float32))

    p = mesh.devices.size
    return bool(_run(mesh, body) == p)


def test_pointToPoint_ring(mesh: Mesh) -> bool:
    """Ring sendrecv: rank r receives from r-1 (comms_test.hpp p2p analog)."""
    def body(comms):
        rank = comms.get_rank().astype(jnp.float32)
        got = comms.device_sendrecv(rank, dest_offset=1)
        want = (comms.get_rank() - 1) % comms.get_size()
        return comms.allreduce((got == want).astype(jnp.float32))

    p = mesh.devices.size
    return bool(_run(mesh, body) == p)


def test_commsplit(mesh: Mesh, n_groups: int = 2) -> bool:
    """Split into groups; in-group allreduce must equal the group size."""
    def body(comms):
        sub = comms.comm_split(n_groups)
        red = sub.allreduce(jnp.float32(1.0))
        ok = red == sub.get_size()
        # in-group rank must be in [0, group size)
        r = sub.get_rank()
        ok = ok & (r >= 0) & (r < sub.get_size())
        return comms.allreduce(ok.astype(jnp.float32))

    p = mesh.devices.size
    return bool(_run(mesh, body) == p)


def run_all(mesh: Mesh) -> dict:
    """Run the full self-test battery → {name: bool}."""
    results = {
        "allreduce": test_collective_allreduce(mesh),
        "broadcast": test_collective_broadcast(mesh),
        "reduce": test_collective_reduce(mesh),
        "allgather": test_collective_allgather(mesh),
        "gather": test_collective_gather(mesh),
        "reducescatter": test_collective_reducescatter(mesh),
        "p2p_ring": test_pointToPoint_ring(mesh),
    }
    if mesh.devices.size % 2 == 0:
        results["commsplit"] = test_commsplit(mesh, 2)
    return results
