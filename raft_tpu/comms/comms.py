"""The communicator protocol + the XLA-collective implementation.

Method-for-method mirror of `comms_t` (core/comms.hpp:335-540): each
reference entry point appears here with the same name and contract, lowered
to the corresponding `jax.lax` collective over a named mesh axis.
"""
from __future__ import annotations

from typing import Optional, Protocol, Sequence, Tuple, runtime_checkable

import jax
import jax.numpy as jnp

from ..core.errors import expects

__all__ = ["Comms", "AxisComms"]

_REDUCE = {
    "sum": jax.lax.psum,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


@runtime_checkable
class Comms(Protocol):
    """Structural protocol for communicators (comms_iface, core/comms.hpp:123).

    Anything with this surface can be injected into ``Resources.set_comms``
    and drives the `parallel/` MNMG algorithms.
    """

    def get_size(self) -> int: ...
    def get_rank(self) -> jax.Array: ...
    def barrier(self) -> None: ...
    def allreduce(self, x, op: str = "sum") -> jax.Array: ...
    def bcast(self, x, root: int = 0) -> jax.Array: ...
    def reduce(self, x, root: int = 0, op: str = "sum") -> jax.Array: ...
    def allgather(self, x) -> jax.Array: ...
    def reducescatter(self, x, op: str = "sum") -> jax.Array: ...
    def comm_split(self, n_groups: int) -> "Comms": ...


class AxisComms:
    """Collectives over one named mesh axis, used inside shard_map/pjit.

    ``groups``: optional static subgroups (`axis_index_groups`), the result
    of `comm_split` — the XLA analog of NCCL's color/key split
    (std_comms.hpp comm_split). All collectives then act within the
    caller's group.
    """

    def __init__(self, axis: str = "shard", size: Optional[int] = None,
                 groups: Optional[Sequence[Sequence[int]]] = None):
        self.axis = axis
        self._size = size
        self.groups = tuple(tuple(g) for g in groups) if groups else None

    # -- topology ----------------------------------------------------------
    def get_size(self) -> int:
        """Ranks in this communicator (group size after a split)."""
        if self.groups is not None:
            return len(self.groups[0])
        if self._size is not None:
            return self._size
        return jax.lax.axis_size(self.axis)

    def get_rank(self) -> jax.Array:
        """Caller's rank (traced; within its group after a split)."""
        idx = jax.lax.axis_index(self.axis)
        if self.groups is None:
            return idx
        # rank within group = position of idx in its group row
        g = jnp.asarray(self.groups)                       # (ng, gs)
        pos = jnp.argmax(jnp.any(g == idx, axis=1))        # group row
        return jnp.argmax(g[pos] == idx)

    def comm_split(self, n_groups: int) -> "AxisComms":
        """Static color split into ``n_groups`` equal contiguous groups
        (core/comms.hpp comm_split; colors must be static under XLA)."""
        size = self.get_size()
        expects(self.groups is None, "nested comm_split not supported")
        expects(size % n_groups == 0, "size %d not divisible into %d groups",
                size, n_groups)
        gs = size // n_groups
        groups = [list(range(g * gs, (g + 1) * gs)) for g in range(n_groups)]
        return AxisComms(self.axis, size, groups)

    # -- collectives (comms_t device API, core/comms.hpp:389-540) ----------
    def barrier(self) -> None:
        """Collective fence: a tiny psum every rank must join
        (comms_t::barrier). Under XLA the program order already sequences
        collectives; this exists for API parity and cross-rank sync tests."""
        jax.lax.psum(jnp.zeros((), jnp.int32), self.axis,
                     axis_index_groups=self.groups)

    def allreduce(self, x, op: str = "sum") -> jax.Array:
        expects(op in _REDUCE, "unsupported reduce op %s", op)
        return _REDUCE[op](x, self.axis, axis_index_groups=self.groups)

    def bcast(self, x, root: int = 0) -> jax.Array:
        """Every rank gets root's value (comms_t::bcast)."""
        rank = jax.lax.axis_index(self.axis) if self.groups is None else \
            self.get_rank()
        masked = jnp.where(rank == root, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, self.axis, axis_index_groups=self.groups)

    def reduce(self, x, root: int = 0, op: str = "sum") -> jax.Array:
        """Reduction delivered to root; other ranks get zeros
        (comms_t::reduce — non-roots' buffers are unspecified there)."""
        red = self.allreduce(x, op)
        rank = self.get_rank()
        return jnp.where(rank == root, red, jnp.zeros_like(red))

    def allgather(self, x) -> jax.Array:
        """(…,) per rank → (size, …) on every rank (comms_t::allgather)."""
        return jax.lax.all_gather(x, self.axis,
                                  axis_index_groups=self.groups)

    def allgatherv(self, x, counts: Sequence[int]) -> Tuple[jax.Array, jax.Array]:
        """Variable-count allgather (role of comms_t::allgatherv): ranks
        contribute ``counts[r]`` valid rows out of a common padded buffer.
        Returns (stacked (size, max_rows, …), counts array).

        Contract difference from the reference: comms_t::allgatherv writes
        ragged results at displacements; the TPU idiom is padded-dense, so
        slot (r, i) for i >= counts[r] is PADDING and the caller must mask
        by ``counts`` before reducing over the gathered axis."""
        g = self.allgather(x)
        return g, jnp.asarray(counts, jnp.int32)

    def gather(self, x, root: int = 0) -> jax.Array:
        """allgather then select at root (comms_t::gather; non-roots get
        zeros — the reference leaves their recv buffers untouched)."""
        g = self.allgather(x)
        rank = self.get_rank()
        return jnp.where(rank == root, g, jnp.zeros_like(g))

    def gatherv(self, x, counts: Sequence[int], root: int = 0):
        g, c = self.allgatherv(x, counts)
        rank = self.get_rank()
        return jnp.where(rank == root, g, jnp.zeros_like(g)), c

    def reducescatter(self, x, op: str = "sum") -> jax.Array:
        """Reduce then scatter blocks by rank (comms_t::reducescatter).
        ``x``: (size * block, …) on each rank → (block, …) per rank."""
        expects(op == "sum", "reducescatter supports sum (psum_scatter)")
        size = self.get_size()
        expects(x.shape[0] % size == 0,
                "leading dim %d not divisible by %d", x.shape[0], size)
        return jax.lax.psum_scatter(
            x.reshape(size, x.shape[0] // size, *x.shape[1:]), self.axis,
            scatter_dimension=0, axis_index_groups=self.groups,
            tiled=False)

    # -- p2p (comms_t::device_send/device_recv/device_sendrecv) ------------
    def device_sendrecv(self, x, dest_offset: int = 1) -> jax.Array:
        """Ring shift: every rank sends to (rank + dest_offset) % size and
        receives from (rank - dest_offset) % size — the collective-safe
        XLA form of paired device_send/device_recv (core/comms.hpp:607-666;
        arbitrary tag-addressed p2p is host-side in the reference via UCX
        and has no in-graph XLA analog)."""
        size = self.get_size()
        if self.groups is None:
            perm = [(s, (s + dest_offset) % size) for s in range(size)]
        else:
            perm = [(g[s], g[(s + dest_offset) % size])
                    for g in self.groups for s in range(size)]
        return jax.lax.ppermute(x, self.axis, perm)

    def device_multicast_sendrecv(self, x, dests: Sequence[int]):
        """One ppermute per destination offset (comms_t::
        device_multicast_sendrecv)."""
        return [self.device_sendrecv(x, d) for d in dests]

    # -- stream-ordering API parity ----------------------------------------
    def sync_stream(self) -> None:
        """No-op: XLA programs are already stream-ordered; exists so MNMG
        call sites can keep the reference's call shape
        (comms_t::sync_stream)."""
