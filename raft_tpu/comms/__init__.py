"""Distributed communicator: analog of ``raft/core/comms.hpp`` + ``raft/comms/``.

Reference: `comms_iface`/`comms_t` (core/comms.hpp:123-230) — rank/size,
comm_split, barrier, collectives (allreduce/bcast/reduce/allgather/
allgatherv/gather/gatherv/reducescatter), p2p send/recv — implemented by
std_comms (NCCL+UCX, comms/detail/std_comms.hpp:56) and mpi_comms
(comms/detail/mpi_comms.hpp:107), injected into `resources` and consumed
by MNMG algorithms.

TPU design: collectives are XLA ops over a *named mesh axis*, so the
communicator is a value that names the axis and is used inside
`shard_map`/`pjit` — the compiler lowers each call to the matching ICI/DCN
collective. `comm_split` maps to `axis_index_groups` (static subgroups, the
XLA analog of a color split); p2p maps to `ppermute`. Multi-host bootstrap
(the raft-dask Comms.init path, python/raft-dask/raft_dask/common/comms.py:
93-245) is `jax.distributed.initialize` + mesh construction — see
``bootstrap``.
"""
from .comms import AxisComms, Comms
from .bootstrap import init_comms, init_distributed, local_mesh
from . import comms_test

__all__ = ["Comms", "AxisComms", "init_comms", "init_distributed",
           "local_mesh", "comms_test"]
