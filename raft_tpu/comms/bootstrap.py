"""Multi-host bootstrap: the raft-dask ``Comms`` analog.

Reference: python/raft-dask/raft_dask/common/comms.py:93-245 — pick an
NCCL root, broadcast the uniqueId, run per-worker init that injects a
ready communicator into each worker's handle (§3.5 call stack).

TPU design: `jax.distributed.initialize` plays the bootstrap role
(coordinator address ≈ the NCCL uniqueId broadcast; process_id ≈ rank);
after it, every process sees the global device set and a `Mesh` over
those devices is the communicator clique. This module is the ONE entry
point for that init — :func:`init_distributed` — so every launcher
(the fleet dryrun, a pod job, a test worker) bootstraps identically:

* **env autodetect**: each field falls back to
  ``RAFT_TPU_COORDINATOR`` / ``RAFT_TPU_NUM_PROCESSES`` /
  ``RAFT_TPU_PROCESS_ID`` (then the ``JAX_*`` equivalents), so a
  launcher can export three variables and every worker just calls
  ``init_comms()`` with no arguments;
* **all-or-nothing**: a partial specification (coordinator set but no
  process id, etc.) is a configuration bug that would otherwise surface
  as a hang at first collective — it raises immediately, naming what is
  set and what is missing;
* **idempotent**: re-init with the same (coordinator, n, rank) triple
  is a no-op (serving code paths may all call it defensively); re-init
  with a DIFFERENT triple raises — one process is one rank for life.

`init_comms` wires the result into a `Resources` so algorithms reach it
via `get_comms()`, exactly the reference's injection pattern
(comms/std_comms.hpp:69).
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..core.errors import expects
from .comms import AxisComms

__all__ = ["init_comms", "init_distributed", "local_mesh"]

# per-field env fallbacks, first hit wins (RAFT_TPU_* preferred so a
# launcher can scope the fleet without touching jax's own variables)
_ENV_VARS = {
    "coordinator_address": ("RAFT_TPU_COORDINATOR", "JAX_COORDINATOR_ADDRESS"),
    "num_processes": ("RAFT_TPU_NUM_PROCESSES", "JAX_NUM_PROCESSES"),
    "process_id": ("RAFT_TPU_PROCESS_ID", "JAX_PROCESS_ID"),
}

# the (coordinator, num_processes, process_id) triple this process was
# initialized with — the idempotence guard's memory
_initialized: Optional[Tuple[str, int, int]] = None


def _resolve_env(coordinator_address=None, num_processes=None,
                 process_id=None, environ=None) -> dict:
    """Merge explicit args over the env fallbacks into one validated
    config: ``{"distributed": False}`` when nothing is specified, else
    the full coerced triple. Raises on a PARTIAL specification — the
    alternative is a silent hang at the first collective. ``environ``
    is injectable for tests."""
    env = os.environ if environ is None else environ
    vals = {"coordinator_address": coordinator_address,
            "num_processes": num_processes, "process_id": process_id}
    source = {}
    for field, names in _ENV_VARS.items():
        if vals[field] is not None:
            source[field] = "argument"
            continue
        for name in names:
            raw = env.get(name)
            if raw is not None and str(raw) != "":
                vals[field] = raw
                source[field] = f"env {name}"
                break
    given = {f for f, v in vals.items() if v is not None}
    if not given:
        return {"distributed": False}
    missing = sorted(set(_ENV_VARS) - given)
    expects(not missing,
            "partial jax.distributed config: %s but missing %s — set all "
            "three (args to init_distributed, or env %s)",
            ", ".join(f"{f}={vals[f]!r} ({source[f]})" for f in sorted(given)),
            ", ".join(f"{f} ({'/'.join(_ENV_VARS[f])})" for f in missing),
            "/".join(v for vs in _ENV_VARS.values() for v in vs[:1]))
    try:
        num = int(vals["num_processes"])
        pid = int(vals["process_id"])
    except (TypeError, ValueError):
        expects(False, "non-integer num_processes=%r / process_id=%r",
                vals["num_processes"], vals["process_id"])
    expects(num >= 1, "num_processes must be >= 1, got %d", num)
    expects(0 <= pid < num, "process_id %d out of range [0, %d)", pid, num)
    return {"distributed": True,
            "coordinator_address": str(vals["coordinator_address"]),
            "num_processes": num, "process_id": pid}


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> dict:
    """THE ``jax.distributed`` entry point (module docstring): resolve
    args+env, initialize once, and return the resolved config —
    ``{"distributed": False}`` (single-process), or the full triple plus
    ``"already": True`` when this process was already initialized with
    the same triple. Call BEFORE any jax operation that touches the
    backend; every process in the fleet must resolve the same
    coordinator and num_processes."""
    global _initialized
    cfg = _resolve_env(coordinator_address, num_processes, process_id)
    if not cfg["distributed"]:
        return cfg
    triple = (cfg["coordinator_address"], cfg["num_processes"],
              cfg["process_id"])
    if _initialized is not None:
        expects(_initialized == triple,
                "jax.distributed already initialized as %s; refusing "
                "re-init as %s (one process is one rank for life)",
                _initialized, triple)
        return {**cfg, "already": True}
    try:
        jax.distributed.initialize(coordinator_address=triple[0],
                                   num_processes=triple[1],
                                   process_id=triple[2])
    except RuntimeError as e:
        # initialized outside this module (e.g. a launcher calling jax
        # directly) — adopt it; anything else is a real bootstrap error
        if "already" not in str(e).lower():
            raise
    _initialized = triple
    return cfg


def local_mesh(n_devices: Optional[int] = None, axis: str = "shard",
               platform: Optional[str] = None) -> Mesh:
    """1-D mesh over local devices (the LocalCUDACluster-style test path).

    Falls back to CPU devices when the default platform has too few (the
    single-TPU-chip + 8-virtual-CPU development setup).
    """
    devices = jax.devices(platform) if platform else jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        devices = jax.devices("cpu")
    if n_devices is not None:
        expects(len(devices) >= n_devices, "need %d devices, have %d",
                n_devices, len(devices))
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def init_comms(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    n_devices: Optional[int] = None,
    axis: str = "shard",
    resources=None,
) -> Tuple[Mesh, AxisComms]:
    """Bootstrap a communicator clique → (mesh, comms).

    Single-process (nothing specified by arg OR env): a mesh over local
    devices — the raft-dask LocalCluster path. Multi-process: runs
    :func:`init_distributed` first (DCN bootstrap, env-autodetected:
    a worker under a launcher that exported ``RAFT_TPU_COORDINATOR``/
    ``_NUM_PROCESSES``/``_PROCESS_ID`` calls ``init_comms()`` bare),
    then builds the mesh over the *global* device set.

    When ``resources`` is given, the comms object is injected via
    ``set_comms`` (the build_comms_nccl_only analog).
    """
    init_distributed(coordinator_address, num_processes, process_id)
    mesh = local_mesh(n_devices, axis)
    comms = AxisComms(axis, size=mesh.shape[axis])
    if resources is not None:
        resources.set_comms(comms)
    return mesh, comms
