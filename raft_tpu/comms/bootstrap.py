"""Multi-host bootstrap: the raft-dask ``Comms`` analog.

Reference: python/raft-dask/raft_dask/common/comms.py:93-245 — pick an
NCCL root, broadcast the uniqueId, run per-worker init that injects a
ready communicator into each worker's handle (§3.5 call stack).

TPU design: `jax.distributed.initialize` plays the bootstrap role
(coordinator address ≈ the NCCL uniqueId broadcast; process_id ≈ rank);
after it, every process sees the global device set and a `Mesh` over
those devices is the communicator clique. `init_comms` wires the result
into a `Resources` so algorithms reach it via `get_comms()`, exactly the
reference's injection pattern (comms/std_comms.hpp:69).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..core.errors import expects
from .comms import AxisComms

__all__ = ["init_comms", "local_mesh"]


def local_mesh(n_devices: Optional[int] = None, axis: str = "shard",
               platform: Optional[str] = None) -> Mesh:
    """1-D mesh over local devices (the LocalCUDACluster-style test path).

    Falls back to CPU devices when the default platform has too few (the
    single-TPU-chip + 8-virtual-CPU development setup).
    """
    devices = jax.devices(platform) if platform else jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        devices = jax.devices("cpu")
    if n_devices is not None:
        expects(len(devices) >= n_devices, "need %d devices, have %d",
                n_devices, len(devices))
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def init_comms(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    n_devices: Optional[int] = None,
    axis: str = "shard",
    resources=None,
) -> Tuple[Mesh, AxisComms]:
    """Bootstrap a communicator clique → (mesh, comms).

    Single-process (coordinator_address None): a mesh over local devices —
    the raft-dask LocalCluster path. Multi-process: initializes
    `jax.distributed` first (DCN bootstrap; every process must call this
    with the same coordinator, mirroring Comms.init's client.run fan-out),
    then builds the mesh over the *global* device set.

    When ``resources`` is given, the comms object is injected via
    ``set_comms`` (the build_comms_nccl_only analog).
    """
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
    mesh = local_mesh(n_devices, axis)
    comms = AxisComms(axis, size=mesh.shape[axis])
    if resources is not None:
        resources.set_comms(comms)
    return mesh, comms
