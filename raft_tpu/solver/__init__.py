"""Solvers: analog of ``raft/solver/`` — the batched linear assignment
problem (Hungarian) solver.

Reference: solver/linear_assignment.cuh:54 (`LinearAssignmentProblem`,
a GPU Hungarian/LAP batched over problem instances; lap/lap.cuh is the
deprecated alias).

TPU design: the auction algorithm instead of Hungarian row/col reduction
— auction is synchronous-parallel by construction (all unassigned rows
bid simultaneously each round: one argmax + one scatter-max, both native
XLA), converges with eps-scaling, and batches over instances with vmap.
"""
from .lap import LinearAssignmentProblem, solve_lap

__all__ = ["solve_lap", "LinearAssignmentProblem"]
