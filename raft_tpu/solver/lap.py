"""Batched linear assignment via the auction algorithm
(solver/linear_assignment.cuh:54 role).

Bertsekas auction with eps-scaling: every unassigned row bids for its
best object simultaneously (one row-wise top-2 + one column argmax per
round — all dense XLA ops, no sequential augmenting paths), objects go
to the highest bidder, eps shrinks geometrically to below 1/(n+1) which
certifies optimality for integer costs and near-optimality for floats.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects

__all__ = ["solve_lap", "LinearAssignmentProblem"]

_NEG = -1e30


@partial(jax.jit, static_argnames=("max_rounds",))
def _auction(benefit: jax.Array, eps_schedule: jax.Array,
             max_rounds: int) -> jax.Array:
    """One LAP instance: (n, n) benefit → row→object assignment (n,)."""
    n = benefit.shape[0]
    rows = jnp.arange(n)

    def phase(carry, eps):
        assign, prices = carry
        # eps phase: release all assignments, keep prices (standard scaling)
        assign = jnp.full((n,), -1, jnp.int32)
        owner = jnp.full((n,), -1, jnp.int32)

        def cond(st):
            assign, owner, prices, it = st
            return jnp.any(assign < 0) & (it < max_rounds)

        def body(st):
            assign, owner, prices, it = st
            unassigned = assign < 0
            values = benefit - prices[None, :]
            top2, idx2 = jax.lax.top_k(values, 2)
            jstar = idx2[:, 0]
            bid_amt = prices[jstar] + (top2[:, 0] - top2[:, 1]) + eps
            # bid matrix: rows bid only on their jstar, only if unassigned
            bids = jnp.full((n, n), _NEG)
            bids = bids.at[rows, jstar].set(
                jnp.where(unassigned, bid_amt, _NEG))
            best_bid = jnp.max(bids, axis=0)                 # per object
            best_row = jnp.argmax(bids, axis=0).astype(jnp.int32)
            has_bid = best_bid > _NEG / 2
            # previous owners of re-auctioned objects become unassigned
            # (max-scatter: a no-bid object must not clear slot 0)
            prev = jnp.where(has_bid, owner, -1)
            lost = jnp.zeros((n,), bool).at[
                jnp.where(prev >= 0, prev, 0)].max(prev >= 0)
            assign = jnp.where(lost[rows], -1, assign)
            # assign winners; objects with no bid scatter out of bounds and
            # are dropped (a masked in-bounds write could race a real win)
            assign = assign.at[jnp.where(has_bid, best_row, n)].set(
                jnp.arange(n, dtype=jnp.int32), mode="drop")
            owner = jnp.where(has_bid, best_row, owner)
            prices = jnp.where(has_bid, best_bid, prices)
            return assign, owner, prices, it + 1

        assign, owner, prices, _ = jax.lax.while_loop(
            cond, body, (assign, owner, prices, jnp.int32(0)))
        return (assign, prices), None

    init = (jnp.full((n,), -1, jnp.int32), jnp.zeros((n,), jnp.float32))
    (assign, _), _ = jax.lax.scan(phase, init, eps_schedule)
    return assign


def solve_lap(cost, maximize: bool = False,
              max_rounds: int = 10_000) -> Tuple[jax.Array, jax.Array]:
    """Solve min-cost (or max-benefit) square assignment.

    cost: (n, n) or batched (b, n, n). Returns (row→col assignment i32,
    total cost per instance).
    """
    c = jnp.asarray(cost, jnp.float32)
    expects(c.shape[-1] == c.shape[-2], "LAP needs square cost, got %s",
            tuple(c.shape))
    squeeze = c.ndim == 2
    if squeeze:
        c = c[None]
    n = c.shape[-1]
    benefit = c if maximize else -c
    # scale-invariant eps schedule: from ~range/2 down past 1/(n+1)
    rng = jnp.maximum(jnp.max(benefit) - jnp.min(benefit), 1.0)
    n_phases = int(np.ceil(np.log2(float(2 * (n + 1))))) + 2
    eps_schedule = jnp.asarray(
        [float(rng) / 2.0 / (2.0 ** t) for t in range(n_phases)],
        jnp.float32)
    eps_schedule = jnp.maximum(eps_schedule, 1.0 / (2 * (n + 1)))

    assign = jax.vmap(lambda b: _auction(b, eps_schedule, max_rounds))(benefit)
    total = jnp.take_along_axis(
        c.reshape(c.shape[0], n * n),
        jnp.arange(n)[None, :] * n + assign, axis=1).sum(axis=1)
    if squeeze:
        return assign[0], total[0]
    return assign, total


class LinearAssignmentProblem:
    """Class-shaped mirror of raft::solver::LinearAssignmentProblem
    (linear_assignment.cuh:54): construct with batch/size, then solve."""

    def __init__(self, size: int, batch_size: int = 1):
        self.size = size
        self.batch_size = batch_size
        self._assign = None
        self._costs = None

    def solve(self, cost_matrices, maximize: bool = False):
        c = jnp.asarray(cost_matrices, jnp.float32).reshape(
            self.batch_size, self.size, self.size)
        self._assign, self._costs = solve_lap(c, maximize)
        return self._assign

    @property
    def row_assignments(self):
        return self._assign

    @property
    def objective(self):
        return self._costs
