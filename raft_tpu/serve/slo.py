"""Declarative SLO engine over the serving metrics registry
(docs/observability.md "Quality").

The registry answers "what is the p99 *ever*"; an operator needs "are
we inside our objectives *now*". This module evaluates a declarative
:class:`Targets` set — p99 latency, recall floor, shed rate, demotion
rate — against the existing metrics over **burn-rate windows** (the
multi-window SRE alerting shape): every :meth:`SLOEngine.evaluate`
snapshots the counters/histograms into a bounded history ring and
diffs against baselines one fast window and one slow window back, so a
breach means "the *recent* traffic violates the objective", not "a bad
minute an hour ago still taints the lifetime average".

Verdicts: ``ok`` / ``warn`` (one window violated — a burn starting or
burning off) / ``breach`` (both windows violated). A target's
transition into ``breach`` emits one ``slo_breach`` flight-recorder
event (re-armed on recovery) and counts under ``<name>.slo.breaches``.
The recall target reads the :class:`~raft_tpu.serve.quality.RecallSentinel`'s
rolling ``<name>.recall.<family>`` gauge — already a moving window — and
gates on its published sample count.

``SLOEngine.install()`` registers the engine for the debugz snapshot's
``slo`` section (one engine per process slot, like the tracing timer);
``debugz.snapshot(slo=engine)`` overrides explicitly. The engine is a
plain instance over an injectable registry, so the multi-tenant fabric
(:mod:`raft_tpu.serve.tenancy`) runs ONE engine per tenant against that
tenant's private registry — the process-global ``install()`` slot stays
the single-tenant default; per-tenant verdicts land in the debugz
``tenants`` section instead.
"""
from __future__ import annotations

import dataclasses
import math
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from ..core import events

__all__ = ["Targets", "SLOEngine", "install", "installed", "uninstall"]

_VERDICT_RANK = {"ok": 0, "warn": 1, "breach": 2}


@dataclasses.dataclass(frozen=True)
class Targets:
    """Declarative serving objectives; None disables a target.

    ``max_shed_rate``/``max_demotion_rate`` are fractions (sheds per
    admitted request, guarded demotions per dispatched batch) over the
    evaluation window. ``recall_floor`` applies to the sentinel's
    rolling ``<name>.recall.<recall_family>`` estimate, gated on
    ``recall_min_samples``; ``recall_warn_margin`` arms the warn band
    above the floor."""

    p99_latency_s: Optional[float] = None
    recall_floor: Optional[float] = None
    max_shed_rate: Optional[float] = None
    max_demotion_rate: Optional[float] = None
    recall_family: str = "default"
    recall_warn_margin: float = 0.02
    recall_min_samples: int = 1


def _p_from_counts(bounds: Tuple[float, ...], counts: List[int], q: float,
                   hi_max: float) -> Optional[float]:
    """Percentile estimate from windowed (diffed) histogram bucket
    counts — the metrics.Histogram interpolation applied to a delta."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = (q / 100.0) * total
    cum = 0
    for i, c in enumerate(counts):
        if c and cum + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else hi_max
            if not math.isfinite(hi):
                return hi_max if math.isfinite(hi_max) else lo
            return lo + ((rank - cum) / c) * (hi - lo)
        cum += c
    return hi_max if math.isfinite(hi_max) else None


class SLOEngine:
    """Evaluate :class:`Targets` from a metrics registry over burn-rate
    windows. ``registry``: the serving registry (``<name>.*`` counters,
    latency histogram, recall gauges); guarded demotions are always read
    from the default process registry — that is where
    ``ops/guarded._demote`` records them. ``clock`` is injectable for
    deterministic tests."""

    def __init__(self, targets: Targets, registry=None, name: str = "serve",
                 fast_window_s: float = 60.0, slow_window_s: float = 300.0,
                 clock: Callable[[], float] = time.monotonic,
                 history: int = 256):
        from . import metrics as _metrics

        self.targets = targets
        self._name = name
        self._reg = registry or _metrics.default_registry
        self._default_reg = _metrics.default_registry
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._clock = clock
        self._history: List[dict] = []
        self._history_cap = int(history)
        self._state: Dict[str, str] = {}
        self._breaches = self._reg.counter(f"{name}.slo.breaches")
        # one lock over history + transition state: a background
        # SnapshotWriter evaluating the installed engine must not race a
        # foreground debugz.snapshot into double-counting a breach
        # transition (the one-event-per-transition contract)
        self._lock = threading.Lock()

    # -- sampling ---------------------------------------------------------
    def _sample(self) -> dict:
        snap = self._reg.snapshot()
        cs, hs = snap["counters"], snap["histograms"]
        lat = hs.get(f"{self._name}.latency_s")
        demotions = self._default_reg.counter("guarded.demotions").value
        s = {
            "t": self._clock(),
            "shed": cs.get(f"{self._name}.shed", 0.0),
            "requests": cs.get(f"{self._name}.requests", 0.0),
            "demotions": demotions,
            "batches": cs.get(f"{self._name}.batches", 0.0),
            "lat_counts": None if lat is None
            else list(lat["buckets"].values()),
            "lat_bounds": None if lat is None
            else tuple(float(b) for b in list(lat["buckets"])[:-1]),
            "lat_max": (lat or {}).get("max"),
            "gauges": snap["gauges"],
        }
        return s

    def tick(self) -> None:
        """Record one history sample without evaluating (a background
        loop can tick finer than it alerts)."""
        s = self._sample()
        with self._lock:
            self._push_locked(s)

    def _push_locked(self, s: dict) -> None:
        self._history.append(s)
        if len(self._history) > self._history_cap:
            del self._history[: len(self._history) - self._history_cap]

    def _baseline_locked(self, now: float, window_s: float) -> dict:
        """Latest sample at least ``window_s`` old; oldest sample when
        history is younger than the window."""
        base = self._history[0]
        for s in self._history:
            if now - s["t"] >= window_s:
                base = s
            else:
                break
        return base

    # -- evaluation -------------------------------------------------------
    @staticmethod
    def _rate(cur: dict, base: dict, num: str, den: str) -> Optional[float]:
        dn = cur[den] - base[den]
        if dn <= 0:
            return None
        return max(0.0, cur[num] - base[num]) / dn

    @staticmethod
    def _win_p99(cur: dict, base: dict) -> Optional[float]:
        if cur["lat_counts"] is None:
            return None
        if base["lat_counts"] is None:
            diff = list(cur["lat_counts"])
        else:
            diff = [max(0, a - b) for a, b in
                    zip(cur["lat_counts"], base["lat_counts"])]
        hi = cur["lat_max"]
        return _p_from_counts(cur["lat_bounds"], diff, 99.0,
                              hi if hi is not None else math.inf)

    def _value_verdict(self, fast, slow, target) -> str:
        vf = fast is not None and fast > target
        vs = slow is not None and slow > target
        if vf and vs:
            return "breach"
        if vf or vs:
            return "warn"
        return "ok"

    def evaluate(self) -> dict:
        """Take a sample, judge every configured target, fire breach
        transitions, and return the JSON-safe verdict report (the
        debugz ``slo`` section). Thread-safe: concurrent evaluations
        (a background SnapshotWriter + a foreground snapshot) serialize,
        so a transition fires exactly one event."""
        cur = self._sample()
        with self._lock:
            return self._evaluate_locked(cur)

    def _evaluate_locked(self, cur: dict) -> dict:
        self._push_locked(cur)
        now = cur["t"]
        fast = self._baseline_locked(now, self.fast_window_s)
        slow = self._baseline_locked(now, self.slow_window_s)
        t = self.targets
        out: dict = {}
        if t.p99_latency_s is not None:
            vf, vs = self._win_p99(cur, fast), self._win_p99(cur, slow)
            out["p99_latency_s"] = {
                "target": t.p99_latency_s, "fast": vf, "slow": vs,
                "verdict": self._value_verdict(vf, vs, t.p99_latency_s)}
        if t.max_shed_rate is not None:
            vf = self._rate(cur, fast, "shed", "requests")
            vs = self._rate(cur, slow, "shed", "requests")
            out["shed_rate"] = {
                "target": t.max_shed_rate, "fast": vf, "slow": vs,
                "verdict": self._value_verdict(vf, vs, t.max_shed_rate)}
        if t.max_demotion_rate is not None:
            vf = self._rate(cur, fast, "demotions", "batches")
            vs = self._rate(cur, slow, "demotions", "batches")
            out["demotion_rate"] = {
                "target": t.max_demotion_rate, "fast": vf, "slow": vs,
                "verdict": self._value_verdict(vf, vs, t.max_demotion_rate)}
        if t.recall_floor is not None:
            g = cur["gauges"]
            est = g.get(f"{self._name}.recall.{t.recall_family}")
            n = g.get(f"{self._name}.recall.{t.recall_family}.samples", 0)
            rep = {"target": t.recall_floor, "value": est,
                   "samples": int(n), "family": t.recall_family}
            if est is None or n < t.recall_min_samples:
                rep["verdict"] = "ok"
                rep["note"] = "insufficient_samples"
            elif est < t.recall_floor:
                rep["verdict"] = "breach"
            elif est < t.recall_floor + t.recall_warn_margin:
                rep["verdict"] = "warn"
            else:
                rep["verdict"] = "ok"
            out["recall"] = rep
        overall = "ok"
        for key, rep in out.items():
            v = rep["verdict"]
            if _VERDICT_RANK[v] > _VERDICT_RANK[overall]:
                overall = v
            prev = self._state.get(key, "ok")
            if v == "breach" and prev != "breach":
                self._breaches.inc()
                try:
                    events.record(
                        "slo_breach", f"{self._name}.slo.{key}",
                        target=rep.get("target"),
                        value=rep.get("value", rep.get("fast")))
                except Exception:  # noqa: BLE001 - telemetry must not
                    pass           # fail the evaluation
            self._state[key] = v
        return {"verdict": overall, "targets": out,
                "windows": {"fast_s": self.fast_window_s,
                            "slow_s": self.slow_window_s},
                "samples": len(self._history)}

    def install(self) -> "SLOEngine":
        install(self)
        return self


# -- process slot for the debugz snapshot ----------------------------------
_installed: Optional["weakref.ref"] = None


def install(engine: SLOEngine) -> None:
    """Register ``engine`` as the process's debugz SLO source (weak:
    dropping the engine uninstalls it)."""
    global _installed
    _installed = weakref.ref(engine)


def installed() -> Optional[SLOEngine]:
    return _installed() if _installed is not None else None


def uninstall() -> None:
    global _installed
    _installed = None
