"""Multi-tenant serving fabric: many indexes, one process, per-tenant
SLOs (docs/serving.md "Multi-tenant fabric").

The reference's top layer hands MANY indexes to one process group
(PAPER layer 8: raft-dask's multi-index serving surface), and the
ROADMAP north star — heavy traffic from millions of users — is
namespaces and tenants, not one corpus. Every per-engine mechanism
already exists (micro-batching, SLO engine, brownout controller,
recall sentinel, breakers, debugz); this module composes them into the
subsystem that makes the process a *service*:

* **Tenants**: a :class:`ServeFabric` owns N named :class:`Tenant`\\ s,
  each binding an index (any family, including
  :class:`~raft_tpu.neighbors.mutable.MutableIndex` and sharded), a
  searcher closure built through the family's ``make_searcher`` path,
  its own metrics :class:`~raft_tpu.serve.metrics.Registry`, and its
  own :class:`~raft_tpu.serve.slo.SLOEngine` +
  :class:`~raft_tpu.serve.degrade.BrownoutController` — the
  process-global ``install()`` slots stay the single-tenant default.
* **Weighted-fair admission**: per-tenant bounded
  :class:`~raft_tpu.serve.admission.AdmissionQueue`\\ s drained by one
  worker running deficit-weighted round robin (each round credits
  ``weight × RAFT_TPU_TENANT_QUANTUM`` query rows per tenant), so a
  backlogged heavy tenant gets its share and no more. Drained requests
  **co-batch across tenants** when their tenants share a searcher
  closure (same index + params), and every dispatch pads to the ONE
  shared :class:`~raft_tpu.serve.batcher.BucketLadder` — tenancy adds
  zero new shapes, hence zero extra XLA compiles.
* **Token-bucket self-shedding**: a tenant with a configured
  ``rate`` sheds its own over-rate submits at admission
  (``RateLimitedError``, counted under ``<tenant>.shed``, one
  trace-stamped ``tenant_shed`` event each) — the hot tenant burns its
  own budget, brownouts itself through its own SLO engine, and the
  other tenants' p99 holds (the isolation drill in
  tests/test_tenancy.py asserts exactly this).
* **Repeat-traffic cache**: an optional
  :class:`~raft_tpu.serve.qcache.QueryCache` answers byte-identical
  repeats without touching the device; entries are keyed by the
  tenant's swap generation (and a mutable index's merge generation) so
  a flip invalidates them, and sampled hits are offered to the
  tenant's :class:`~raft_tpu.serve.quality.RecallSentinel` under the
  ``qcache`` family so a stale entry surfaces as a recall regression +
  ``qcache_stale`` event instead of serving wrong neighbors forever.
* **Zero-downtime swap**: :meth:`Tenant.swap` warms the replacement
  searcher at the tenant's actually-served shapes off the hot path
  (:func:`raft_tpu.serve.warmup.warmup` ``shapes=``), then flips it in
  atomically under the tenant lock — in-flight dispatches finish on
  the old closure, queued requests dispatch on the new one, nothing is
  dropped or mis-routed — and records one ``tenant_swap``
  flight-recorder event. The retired index is released on the next
  maintenance :meth:`ServeFabric.tick` (wire it into
  ``SnapshotWriter(hooks=[fabric.tick])`` alongside the SLO poll).

Knobs: ``RAFT_TPU_TENANT_QUANTUM`` (WRR row credit per weight unit per
round, default 64), ``RAFT_TPU_TENANT_RATE`` / ``RAFT_TPU_TENANT_BURST``
(default token-bucket rate/burst for tenants that don't set their own;
rate 0 = unlimited), plus the ``RAFT_TPU_QCACHE_*`` cache knobs
(serve/qcache.py).
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core import events, tracing
from ..core.deadline import DeadlineExceeded
from ..core.errors import expects
from ..utils import env_float, env_int
from . import warmup as _warmup
from .admission import AdmissionQueue, QueueFullError, Request, SearchResult
from .batcher import BucketLadder, coalesce_block, triage_partial

__all__ = ["ServeFabric", "Tenant", "TokenBucket", "RateLimitedError",
           "install", "installed", "uninstall"]


class RateLimitedError(QueueFullError):
    """Raised by ``submit`` when the tenant's token bucket is empty —
    the tenant exceeded ITS OWN admission rate (backpressure scoped to
    one tenant; the others are unaffected)."""


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``.
    One token per request; ``try_take`` never blocks."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last = clock()

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def level(self) -> float:
        """Current token level (refreshed, not consumed)."""
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            return self._tokens


class _TenantRequest(Request):
    """A :class:`~raft_tpu.serve.admission.Request` plus its tenant
    back-reference and (optional) cache key — what the fabric worker
    needs at demux to credit the right registry and populate the
    cache."""

    __slots__ = ("tenant", "cache_key")

    def __init__(self, tenant: "Tenant", queries, k, deadline=None,
                 enqueued_at: float = 0.0):
        super().__init__(queries, k, deadline, enqueued_at=enqueued_at)
        self.tenant = tenant
        self.cache_key = None


def _build_searcher(index, params, opts: dict) -> Callable:
    """Family dispatch onto the existing ``make_searcher`` hooks (the
    quality.health pattern): sharded first (duck-typed), then mutable,
    then the single-device families."""
    if hasattr(index, "shards_ok") and hasattr(index, "family"):
        from ..parallel import sharded_ann

        return sharded_ann.make_searcher(index, params, **opts)
    from ..neighbors import brute_force, cagra, ivf_flat, ivf_pq, mutable

    if isinstance(index, mutable.MutableIndex):
        return mutable.make_searcher(index, params, **opts)
    for mod in (cagra, ivf_flat, ivf_pq, brute_force):
        if isinstance(index, mod.Index):
            return mod.make_searcher(index, params, **opts)
    raise TypeError(
        f"no make_searcher for index type {type(index).__name__}")


def _params_sig(params, opts: dict) -> str:
    """Stable cache-key component for a tenant's frozen search policy.

    Opt values that are device bitsets (a per-tenant ``filter=``) sign
    by CONTENT digest, not ``repr``: a large jnp array reprs truncated
    ("..."), so two different filters could collide on one signature —
    and the query cache would then serve one tenant-slice's answer to
    another. ``Bitset.fingerprint()`` is a blake2b over the packed
    words, so equal-content filters still share cache entries."""
    def _sig(v):
        if hasattr(v, "fingerprint") and hasattr(v, "n_bits"):
            return f"bitset:{v.fingerprint()}"
        return repr(v)

    sig_opts = [(name, _sig(v)) for name, v in sorted(opts.items())]
    return f"{params!r}|{sig_opts!r}"


class Tenant:
    """One named tenant inside a :class:`ServeFabric` (construct via
    :meth:`ServeFabric.add_tenant`). Public attributes: ``name``,
    ``weight``, ``registry`` (the tenant's private metrics registry),
    ``queue``, ``slo``, ``brownout``, ``sentinel``, ``bucket``."""

    def __init__(self, fabric: "ServeFabric", name: str, search_fn,
                 index=None, *, weight: float = 1.0, queue_depth: int = 256,
                 registry=None, slo=None, brownout=None, sentinel=None,
                 bucket: Optional[TokenBucket] = None,
                 params_sig: str = ""):
        from . import metrics as _metrics

        expects(weight > 0, "tenant weight must be positive, got %s", weight)
        self._fabric = weakref.proxy(fabric)
        self.name = str(name)
        self.weight = float(weight)
        self.registry = registry or _metrics.Registry()
        self.queue = AdmissionQueue(queue_depth, registry=self.registry,
                                    prefix=self.name, clock=fabric._clock)
        self.slo = slo
        self.brownout = brownout
        self.sentinel = sentinel
        self.bucket = bucket
        r = self.registry
        self._requests = r.counter(f"{self.name}.requests")
        self._served = r.counter(f"{self.name}.served")
        self._shed_n = r.counter(f"{self.name}.shed")
        self._batches = r.counter(f"{self.name}.batches")
        self._errors = r.counter(f"{self.name}.errors")
        self._dlx = r.counter(f"{self.name}.deadline_exceeded")
        self._latency = r.histogram(f"{self.name}.latency_s")
        self._hits = r.counter(f"{self.name}.qcache.hits")
        self._misses = r.counter(f"{self.name}.qcache.misses")
        # swap/search state under the tenant lock (the fabric worker
        # reads the closure per drain round via searcher())
        self._lock = threading.Lock()
        self._search = search_fn
        self._index = index
        self._gen = 0
        self._retired_refs: List[tuple] = []
        self._params_sig = params_sig
        # worker-thread-only state (never touched under a lock): WRR
        # deficit credit and the set of (rows, k) buckets this tenant
        # has actually been served at (the swap warm set)
        self._deficit = 0
        self._shapes: set = set()

    # -- hot-ish reads ----------------------------------------------------
    def searcher(self) -> Tuple[Callable, int]:
        """The current (closure, generation) pair, read atomically —
        the fabric worker calls this once per drain round, so a swap
        lands between rounds, never inside one."""
        with self._lock:
            return self._search, self._gen

    @property
    def generation(self) -> int:
        with self._lock:
            return self._gen

    def cache_params_key(self) -> str:
        """Cache-key component folding in the frozen search policy, the
        swap generation, and — for a mutable index — the merge
        generation, so a generation flip orphans every older entry."""
        with self._lock:
            gen, idx, sig = self._gen, self._index, self._params_sig
        mg = getattr(idx, "generation", None)
        key = f"{sig}|g{gen}"
        return key if mg is None else f"{key}|m{int(mg)}"

    # -- swap -------------------------------------------------------------
    def swap(self, new_index=None, *, search_fn=None, params=None,
             warm: bool = True, **opts) -> int:
        """Replace this tenant's index with zero downtime: build the
        replacement's searcher, pre-warm it at the shapes this tenant
        has served (full shared ladder before any traffic), then flip
        atomically under the tenant lock. Queued and future requests
        dispatch on the replacement; a dispatch already in flight
        finishes on the old closure (its results are still this
        tenant's — nothing is dropped or mis-routed). The old index is
        retained until the next :meth:`ServeFabric.tick` retires it.

        Returns the new generation. ``search_fn`` overrides the family
        ``make_searcher`` dispatch (stub closures, custom engines)."""
        expects(new_index is not None or search_fn is not None,
                "swap needs a new index or an explicit search_fn")
        fab = self._fabric
        fn = search_fn if search_fn is not None else _build_searcher(
            new_index, params, opts)
        # the worker mutates _shapes concurrently (set.add is atomic but
        # iterating a growing set can raise) — retry, the quality
        # ops_snapshot precedent
        served: list = []
        for _ in range(4):
            try:
                served = sorted(self._shapes)
                break
            except RuntimeError:
                continue
        if warm:
            # off the hot path: the worker keeps serving the old
            # generation while every served shape compiles (warmup
            # labels these compiles warmup=True, so the recompile watch
            # stays quiet)
            _warmup.warmup(fn, fab.ladder, fab._dim,
                           registry=self.registry,
                           name=f"{self.name}.swap",
                           shapes=served or None)
        with self._lock:
            old_index, old_fn = self._index, self._search
            self._search = fn
            if new_index is not None:
                # a search_fn-only swap keeps the index binding: the
                # closure changed, the backing (and its mutable merge
                # generation, which cache_params_key folds in) did not
                self._index = new_index
            self._gen += 1
            gen = self._gen
            self._params_sig = _params_sig(params, opts) \
                if search_fn is None else self._params_sig
            # hold the old pair until tick(): an in-flight dispatch may
            # still be computing on it
            self._retired_refs.append((old_index, old_fn, fab._clock()))
        cache = fab.cache
        if cache is not None:
            cache.invalidate_tenant(self.name)
        self.registry.counter(f"{self.name}.swaps").inc()
        try:
            events.record("tenant_swap", f"{self.name}.swap",
                          generation=gen,
                          warmed_shapes=[f"{m}x{k}" for m, k in served],
                          family=type(new_index).__module__.rsplit(
                              ".", 1)[-1] if new_index is not None else None)
        except Exception:  # noqa: BLE001 - telemetry must not fail a swap
            pass
        return gen

    def retire(self) -> int:
        """Release retired (index, searcher) pairs (maintenance-tick
        half of :meth:`swap`); returns how many were dropped."""
        with self._lock:
            dropped, self._retired_refs = self._retired_refs, []
        return len(dropped)

    # -- introspection ----------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-safe per-tenant view for the debugz ``tenants``
        section: queue state, weight, traffic counters, brownout level,
        SLO verdict, cache hit rate, swap generation."""
        with self._lock:
            gen = self._gen
            retired = len(self._retired_refs)
        # the worker mutates _shapes concurrently (same hazard as
        # swap's warm-set read) — retry the iteration
        shapes: list = []
        for _ in range(4):
            try:
                shapes = sorted(f"{m}x{k}" for m, k in self._shapes)
                break
            except RuntimeError:
                continue
        hits, misses = self._hits.value, self._misses.value
        out = {
            "weight": self.weight,
            "generation": gen,
            "retired_pending": retired,
            "queue_depth": len(self.queue),
            "queue_max_depth": self.queue.max_depth,
            "requests": int(self._requests.value),
            "served": int(self._served.value),
            "shed": int(self._shed_n.value),
            "errors": int(self._errors.value),
            "served_shapes": shapes,
            "qcache": {
                "hits": int(hits), "misses": int(misses),
                "hit_rate": round(hits / (hits + misses), 4)
                if (hits + misses) > 0 else None,
            },
        }
        if self.bucket is not None:
            out["tokens"] = round(self.bucket.level(), 2)
            out["rate"] = self.bucket.rate
        if self.brownout is not None:
            out["brownout_level"] = self.brownout.level
        if self.slo is not None:
            try:
                rep = self.slo.evaluate()
                out["slo"] = {"verdict": rep["verdict"],
                              "targets": rep["targets"]}
            except Exception as e:  # noqa: BLE001 - one broken engine
                out["slo"] = {"error": f"{type(e).__name__}: {e}"}
        return out


class ServeFabric:
    """The multi-tenant serving front end: per-tenant queues, one
    weighted-round-robin drain worker, co-batched dispatch at one
    shared :class:`~raft_tpu.serve.batcher.BucketLadder`, an optional
    :class:`~raft_tpu.serve.qcache.QueryCache`, and per-tenant
    SLO/brownout wiring (module docstring).

    ``dim`` is the query width every tenant serves (one fabric per
    embedding space — co-batching requires one pad geometry).
    ``cache=None`` disables result caching; pass a
    :class:`~raft_tpu.serve.qcache.QueryCache`. ``autostart=False``
    lets tests enqueue a deterministic backlog and drive
    :meth:`drain_once` by hand. ``clock`` is injectable for
    deterministic tests."""

    _IDLE_WAIT_S = 0.02

    def __init__(self, dim: int, *, ladder: Optional[BucketLadder] = None,
                 name: str = "fabric", max_wait_s: float = 0.002,
                 max_batch_requests: int = 64, cache=None,
                 registry=None, quantum_rows: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 autostart: bool = True):
        from . import metrics as _metrics

        self._dim = int(dim)
        self.ladder = ladder or BucketLadder()
        self._name = name
        self._max_wait_s = float(max_wait_s)
        self._max_batch = int(max_batch_requests)
        self.cache = cache
        self._clock = clock
        self._reg = registry or _metrics.default_registry
        self._quantum = (env_int("RAFT_TPU_TENANT_QUANTUM", 64)
                         if quantum_rows is None else int(quantum_rows))
        expects(self._quantum > 0, "quantum_rows must be positive")
        self._batches = self._reg.counter(f"{name}.batches")
        self._errors = self._reg.counter(f"{name}.errors")
        self._cobatched = self._reg.counter(f"{name}.cobatched_dispatches")
        # fabric lock guards the tenant table + rotation order + closed
        # flag; the condition wakes the drain worker on submits
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tenants: Dict[str, Tenant] = {}
        self._order: List[str] = []
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        try:
            _warmup.install_recompile_watch()
        except RuntimeError:
            pass
        if autostart:
            self.start()

    # -- tenant management ------------------------------------------------
    def add_tenant(self, name: str, index=None, *, search_fn=None,
                   params=None, weight: float = 1.0, queue_depth: int = 256,
                   targets=None, slo=None, brownout=None, levels=None,
                   sentinel=None, rate: Optional[float] = None,
                   burst: Optional[float] = None, registry=None,
                   warm: bool = False, **opts) -> Tenant:
        """Bind one tenant: an index (dispatched through its family's
        ``make_searcher``; or an explicit ``search_fn``), a WRR
        ``weight``, an optional token-bucket ``rate``/``burst``
        (``None`` reads ``RAFT_TPU_TENANT_RATE``/``_BURST``; rate 0 =
        unlimited), optional ``targets`` (builds a per-tenant
        :class:`~raft_tpu.serve.slo.SLOEngine` +
        :class:`~raft_tpu.serve.degrade.BrownoutController` over the
        tenant's private registry; pass prebuilt ``slo``/``brownout``
        instances for injected clocks or custom windows, ``levels``
        for the controller ladder), and an optional per-tenant
        ``sentinel`` (its ``on_regression`` hook is wired to emit
        ``qcache_stale`` + invalidate the tenant's cache entries when
        the ``qcache`` family crosses the floor). ``warm=True`` sweeps
        the full shared ladder through the searcher before the tenant
        serves."""
        from . import degrade as _degrade
        from . import metrics as _metrics
        from . import slo as _slo

        expects(search_fn is not None or index is not None,
                "add_tenant needs an index or a search_fn")
        fn = search_fn if search_fn is not None else _build_searcher(
            index, params, opts)
        reg = registry or _metrics.Registry()
        if slo is None and targets is not None:
            slo = _slo.SLOEngine(targets, registry=reg, name=name)
        if brownout is None and slo is not None:
            brownout = _degrade.BrownoutController(
                levels, slo=slo, registry=reg, name=name)
        if rate is None:
            rate = env_float("RAFT_TPU_TENANT_RATE", 0.0)
        if burst is None:
            env_burst = env_float("RAFT_TPU_TENANT_BURST", 0.0)
            burst = env_burst if env_burst > 0 else None
        bucket = (TokenBucket(rate, burst, clock=self._clock)
                  if rate and rate > 0 else None)
        t = Tenant(self, name, fn, index, weight=weight,
                   queue_depth=queue_depth, registry=reg, slo=slo,
                   brownout=brownout, sentinel=sentinel, bucket=bucket,
                   params_sig=_params_sig(params, opts))
        if sentinel is not None and self.cache is not None \
                and sentinel.on_regression is None:
            sentinel.on_regression = self._stale_hook(t)
        with self._cond:
            expects(name not in self._tenants,
                    "tenant %r already exists", name)
            expects(not self._closed, "fabric is closed")
            self._tenants[name] = t
            self._order.append(name)
            self._cond.notify()
        if warm:
            _warmup.warmup(fn, self.ladder, self._dim, registry=reg,
                           name=f"{name}.warmup")
        return t

    def _stale_hook(self, tenant: Tenant) -> Callable:
        """on_regression hook for a tenant's sentinel: a ``qcache``
        family floor crossing means the cache served provably-degraded
        answers — flight-record it and eagerly drop the tenant's
        entries."""
        fab_ref = weakref.ref(self)
        t_name, t_reg = tenant.name, tenant.registry

        def _hook(family, estimate, samples, trace_id):
            if family != "qcache":
                return
            try:
                events.record("qcache_stale", f"{t_name}.qcache",
                              trace_id=trace_id,
                              estimate=round(float(estimate), 4),
                              samples=int(samples))
            except Exception:  # noqa: BLE001 - telemetry must not kill
                pass           # the sentinel worker
            t_reg.counter(f"{t_name}.qcache.stale").inc()
            fab = fab_ref()
            if fab is not None and fab.cache is not None:
                fab.cache.invalidate_tenant(t_name)

        return _hook

    def tenant(self, name: str) -> Tenant:
        with self._lock:
            t = self._tenants.get(name)
        expects(t is not None, "unknown tenant %r", name)
        return t

    def tenants(self) -> List[Tenant]:
        with self._lock:
            return [self._tenants[n] for n in self._order]

    # -- client API -------------------------------------------------------
    def submit(self, tenant: str, queries, k: int, deadline=None,
               cache: bool = True) -> Request:
        """Enqueue one request for ``tenant``; returns its future.
        Raises :class:`RateLimitedError` past the tenant's token bucket
        (the tenant shedding ITSELF), ``QueueFullError`` past its queue
        depth, and ValueError-family errors for off-ladder shapes. A
        cache hit completes the future immediately — no queue, no
        dispatch."""
        t = self.tenant(tenant)
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        expects(q.ndim == 2 and q.shape[1] == self._dim,
                "queries must be (m, %d), got %s", self._dim, q.shape)
        self.ladder.bucket_queries(q.shape[0])
        self.ladder.bucket_k(k)
        t._requests.inc()
        req = _TenantRequest(t, q, k, deadline,
                             enqueued_at=self._clock())
        if t.bucket is not None and not t.bucket.try_take():
            # the token-bucket self-shed: the hot tenant pays with its
            # own error budget, nobody else's
            t._shed_n.inc()
            try:
                events.record("tenant_shed", f"{t.name}.admission",
                              trace_id=req.trace_id, reason="rate_limited",
                              rows=req.rows, k=req.k)
            except Exception:  # noqa: BLE001 - telemetry must not block
                pass           # admission
            raise RateLimitedError(
                f"tenant {t.name!r} over its admission rate "
                f"({t.bucket.rate:g}/s); retry after backoff")
        if self.cache is not None:
            if cache:
                ck = self.cache.key(t.name, q, k, t.cache_params_key())
                hit = self.cache.get(ck)
                if hit is not None:
                    t._hits.inc()
                    req.set_result(SearchResult(hit[0], hit[1], None))
                    t._served.inc()
                    t._latency.observe(self._clock() - req.enqueued_at)
                    if t.sentinel is not None:
                        # police the hit: a stale entry must surface as
                        # a qcache-family recall regression
                        try:
                            t.sentinel.offer(q, k, hit[0], hit[1],
                                             family="qcache",
                                             trace_id=req.trace_id)
                        except Exception:  # noqa: BLE001 - telemetry
                            pass           # must not break serving
                    return req
                if ck is not None:
                    t._misses.inc()
                req.cache_key = ck
            else:
                self.cache.bypass()
        t.queue.submit(req)
        with self._cond:
            self._cond.notify()
        return req

    def search(self, tenant: str, queries, k: int, deadline=None,
               timeout: Optional[float] = None,
               cache: bool = True) -> SearchResult:
        """Synchronous convenience: submit + block for the result."""
        return self.submit(tenant, queries, k, deadline,
                           cache=cache).result(timeout)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"{self._name}-fabric", daemon=True)
        self._thread.start()

    def close(self, timeout: float = 10.0) -> None:
        """Stop admitting on every tenant, drain what is queued, stop
        the worker."""
        for t in self.tenants():
            t.queue.close()
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def __enter__(self) -> "ServeFabric":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- maintenance ------------------------------------------------------
    def tick(self) -> dict:
        """One maintenance round — the fabric's ``SnapshotWriter`` hook
        (``SnapshotWriter(hooks=[fabric.tick])``): poll every tenant's
        brownout controller (which evaluates its SLO engine), and
        release indexes retired by swaps. Returns per-tenant verdict
        levels (JSON-safe)."""
        out: dict = {}
        for t in self.tenants():
            rep: dict = {"retired": t.retire()}
            try:
                if t.brownout is not None:
                    poll = t.brownout.poll()
                    rep["brownout_level"] = poll.get("brownout_level")
                    rep["slo_verdict"] = poll.get("verdict")
                elif t.slo is not None:
                    rep["slo_verdict"] = t.slo.evaluate()["verdict"]
            except Exception as e:  # noqa: BLE001 - one broken engine must
                rep["error"] = f"{type(e).__name__}: {e}"  # not kill the tick
            out[t.name] = rep
        return out

    def snapshot(self) -> dict:
        """JSON-safe fabric view for the debugz ``tenants`` section."""
        with self._lock:
            names = list(self._order)
            closed = self._closed
        out = {
            "name": self._name,
            "closed": closed,
            "quantum_rows": self._quantum,
            "dim": self._dim,
            "ladder": {"query_buckets": list(self.ladder.query_buckets),
                       "k_buckets": list(self.ladder.k_buckets)},
            "batches": int(self._batches.value),
            "cobatched_dispatches": int(self._cobatched.value),
            "tenants": {},
        }
        for n in names:
            try:
                out["tenants"][n] = self.tenant(n).snapshot()
            except Exception as e:  # noqa: BLE001 - one broken tenant
                out["tenants"][n] = {                 # must not hide the rest
                    "error": f"{type(e).__name__}: {e}"}
        if self.cache is not None:
            out["qcache"] = self.cache.snapshot()
        return out

    # -- worker -----------------------------------------------------------
    def _run(self) -> None:
        while True:
            try:
                n = self.drain_once()
            except Exception:  # noqa: BLE001 - the worker must survive
                self._errors.inc()  # any single round going wrong
                n = 0
            if n:
                continue
            with self._cond:
                if self._closed and all(
                        len(self._tenants[x].queue) == 0
                        for x in self._order):
                    return
                self._cond.wait(self._IDLE_WAIT_S)
                has_work = any(len(self._tenants[x].queue)
                               for x in self._order)
            # leading-edge coalescing: when traffic arrives on an idle
            # fabric, give co-batchable arrivals one max-wait window
            # before the round (under sustained load the rounds are
            # back-to-back and this never runs)
            if has_work and self._max_wait_s > 0:
                time.sleep(self._max_wait_s)

    def drain_once(self) -> int:
        """One deficit-weighted-round-robin round: credit every tenant
        ``weight × quantum`` rows, pop what the credit covers, group
        the drained requests by (searcher, k-bucket) — co-batching
        tenants that share a closure — and dispatch each group at the
        shared ladder. Public so tests and single-threaded embeddings
        can drive the fabric deterministically (``autostart=False``).
        Returns the number of requests drained."""
        with self._lock:
            order = list(self._order)
            if order:
                # rotate the visit order so equal-weight tenants take
                # turns going first
                self._order.append(self._order.pop(0))
            tenants = [self._tenants[n] for n in order]
        groups: Dict[tuple, dict] = {}
        total = 0
        for t in tenants:
            t._deficit = min(
                t._deficit + max(1, int(round(t.weight * self._quantum))),
                4 * self._quantum * max(1, int(round(t.weight))))
            reqs = t.queue.pop_nowait(
                self._max_batch, max_rows=min(t._deficit,
                                              self.ladder.max_queries))
            if not reqs:
                # classic DRR: an empty queue forfeits its credit (a
                # silent tenant must not bank unbounded burst rights)
                t._deficit = 0
                continue
            popped_rows = sum(r.rows for r in reqs)
            t._deficit = max(0, t._deficit - popped_rows)
            total += len(reqs)
            fn, _gen = t.searcher()
            for r in reqs:
                kb = self.ladder.bucket_k(r.k)
                g = groups.setdefault((id(fn), kb),
                                      {"fn": fn, "kb": kb, "reqs": [],
                                       "tenants": set()})
                g["reqs"].append(r)
                g["tenants"].add(t.name)
        for g in groups.values():
            if len(g["tenants"]) > 1:
                self._cobatched.inc()
            # chunk so a co-batched group never exceeds the top bucket
            chunk: List[_TenantRequest] = []
            rows = 0
            for r in g["reqs"]:
                if chunk and rows + r.rows > self.ladder.max_queries:
                    self._dispatch(g["fn"], g["kb"], chunk)
                    chunk, rows = [], 0
                chunk.append(r)
                rows += r.rows
            if chunk:
                self._dispatch(g["fn"], g["kb"], chunk)
        return total

    # -- dispatch (the batcher's coalesce/pad/demux, tenant-aware) --------
    def _dispatch(self, fn: Callable, kb: int,
                  reqs: List[_TenantRequest]) -> None:
        live: List[_TenantRequest] = []
        for r in reqs:
            if r.deadline is not None and r.deadline.expired():
                r.tenant.queue.shed(r)
            else:
                live.append(r)
        if not live:
            return
        rows = sum(r.rows for r in live)
        mb = self.ladder.bucket_queries(rows)
        block, offs = coalesce_block(live, mb, self._dim)
        carried = [r.deadline for r in live if r.deadline is not None]
        dl = min(carried, key=lambda d: d.remaining()) if carried else None
        try:
            with tracing.bind_trace(*(r.trace_id for r in live)), \
                    _warmup.compile_context(f"{self._name}:{mb}x{kb}"):
                out = fn(block, kb, res=dl)
        except DeadlineExceeded as e:
            self._deliver_partial(fn, kb, live, offs, e)
            return
        except Exception as e:  # noqa: BLE001 - the worker must survive
            self._errors.inc()
            try:
                events.record("dispatch_error", f"{self._name}.batch",
                              trace_id=[r.trace_id for r in live],
                              error=f"{type(e).__name__}: {e}")
            except Exception:  # noqa: BLE001 - a record failure must not
                pass           # strand the futures
            for r in live:
                r.tenant._errors.inc()
                if not r.done():
                    r.set_exception(e)
            return
        self._demux(live, offs, out, mb, kb)

    def _demux(self, live: List[_TenantRequest], offs: List[int], out,
               mb: int, kb: int) -> None:
        shards_ok = None
        if isinstance(out, tuple) and len(out) == 3:
            d, i, shards_ok = out
        else:
            d, i = out
        d = np.asarray(d)
        i = np.asarray(i)
        if shards_ok is not None:
            shards_ok = np.asarray(shards_ok, bool)
        now = self._clock()
        self._batches.inc()
        seen = set()
        for r, o in zip(live, offs):
            res_r = SearchResult(d[o:o + r.rows, :r.k],
                                 i[o:o + r.rows, :r.k], shards_ok)
            r.set_result(res_r)
            t = r.tenant
            t._served.inc()
            t._latency.observe(now - r.enqueued_at)
            t._shapes.add((mb, kb))
            if t.name not in seen:
                seen.add(t.name)
                t._batches.inc()
                t.registry.counter(f"{t.name}.dispatch.{mb}x{kb}").inc()
            if r.cache_key is not None and self.cache is not None and (
                    shards_ok is None or bool(shards_ok.all())):
                # never cache a DEGRADED sharded answer: a replayed hit
                # drops shards_ok, and the degradation would outlive
                # the shard's recovery (no generation flip defeats it)
                self.cache.put(r.cache_key, res_r.distances, res_r.indices)
            if t.sentinel is not None:
                try:
                    t.sentinel.offer(r.queries, r.k, res_r.distances,
                                     res_r.indices, trace_id=r.trace_id)
                except Exception:  # noqa: BLE001 - telemetry must not
                    pass           # break serving

    def _deliver_partial(self, fn: Callable, kb: int,
                         live: List[_TenantRequest], offs: List[int],
                         e: DeadlineExceeded) -> None:
        """Mid-batch deadline expiry — the batcher contract
        (:func:`raft_tpu.serve.batcher.triage_partial` owns the
        slicing/triage and the termination argument), credited to each
        request's own tenant."""
        served, expired, retry = triage_partial(live, offs, e)
        now = self._clock()
        for r, res_r in served:
            r.set_result(res_r)
            r.tenant._served.inc()
            r.tenant._latency.observe(now - r.enqueued_at)
        for r, covered, own in expired:
            r.tenant._dlx.inc()
            try:
                events.record("deadline_exceeded",
                              f"{self._name}.dispatch",
                              trace_id=r.trace_id, rows=r.rows,
                              covered_rows=covered)
            except Exception:  # noqa: BLE001 - telemetry must not strand
                pass           # the future
            r.set_exception(DeadlineExceeded(
                f"raft_tpu fabric: deadline exceeded mid-batch; "
                f"{covered} of {r.rows} query rows completed",
                partial=own))
        if retry:
            self._dispatch(fn, kb, retry)


# -- process slot for the debugz snapshot (mirrors serve/slo.py) -----------
_installed: Optional["weakref.ref"] = None


def install(fabric: ServeFabric) -> None:
    """Register ``fabric`` as the process's debugz tenants source
    (weak: dropping the fabric uninstalls it)."""
    global _installed
    _installed = weakref.ref(fabric)


def installed() -> Optional[ServeFabric]:
    return _installed() if _installed is not None else None


def uninstall() -> None:
    global _installed
    _installed = None
