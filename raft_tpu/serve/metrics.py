"""Process-local serving metrics: counters, gauges and histograms with a
snapshot API and text export.

The reference library ships no operational telemetry — RAFT leaves that
to the services wrapping it (Milvus/raft-dask collect their own). A
serving runtime needs its own signals (queue depth, batch fill ratio,
padding waste, latency percentiles, shed/degraded counters), so this
module provides the smallest registry that covers them:

* **dependency-free and cheap**: plain Python, one lock per instrument,
  no jax import — recordable from any layer (ops/guarded demotion
  events, core/tracing span timing, the serve scheduler) without import
  cycles;
* **fixed-bucket histograms** (the Prometheus shape): bounded memory at
  any traffic level, and percentile estimates by linear interpolation
  inside the owning bucket, clamped to the observed min/max;
* a **default process registry** plus injectable instances so tests and
  multi-tenant batchers can isolate their numbers — the serving fabric
  (:mod:`raft_tpu.serve.tenancy`) gives every tenant its own
  ``Registry``, which is what makes per-tenant SLO engines and brownout
  controllers possible: each one diffs only its own tenant's counters
  (process-level signals — ``guarded.demotions``, ``serve.compiles`` —
  stay in the default registry by design).

Span timing: :func:`enable_span_metrics` installs a
:mod:`raft_tpu.core.tracing` timer, so every ``tracing.annotate`` /
``tracing.range`` span records a duration histogram under
``span.<name>`` — per-stage latency breakdowns for free wherever the
library already traces.
"""
from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Callable, Dict, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "default_registry",
    "registry", "counter", "gauge", "histogram", "snapshot",
    "render_text", "reset", "enable_span_metrics", "disable_span_metrics",
    "LATENCY_BUCKETS_S", "RATIO_BUCKETS", "MTTR_BUCKETS_S",
]

# Seconds-latency bounds, log-spaced from sub-ms dispatch to multi-second
# stragglers; the implicit final bucket is +inf.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0)

# Bounds for [0, 1] ratios (batch fill, padding waste).
RATIO_BUCKETS: Tuple[float, ...] = tuple(i / 8 for i in range(1, 9))

# Recovery-time bounds (``heal.mttr.<site>``, ``shard.mttr``): breaker
# probation alone is 30s by default and backoff caps at 600s, so MTTR
# lives in seconds-to-tens-of-minutes — far past LATENCY_BUCKETS_S'
# 10s ceiling, which would flatten every recovery into the +inf bucket.
MTTR_BUCKETS_S: Tuple[float, ...] = (
    0.1, 0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
    1800.0, 3600.0)


class Counter:
    """Monotonic count (requests served, batches shed, demotions)."""

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins level (queue depth, healthy shard count)."""

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def set_max(self, v: float) -> None:
        """Raise the gauge to ``v`` if higher (peak tracking)."""
        with self._lock:
            self._value = max(self._value, float(v))

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with count/sum/min/max and estimated
    percentiles. ``buckets`` are ascending upper bounds; values above the
    last bound land in an implicit +inf bucket whose percentile estimate
    is the observed max."""

    def __init__(self, name: str,
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS_S):
        if not buckets:
            raise ValueError(f"histogram {name!r} needs at least one bucket")
        self.name = name
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self.buckets, v)] += 1
            self._count += 1
            self._sum += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Estimated q-th percentile (0..100): linear interpolation inside
        the owning bucket, clamped to the observed [min, max]. NaN when
        empty."""
        with self._lock:
            counts = list(self._counts)
            total, lo_seen, hi_seen = self._count, self._min, self._max
        if total == 0:
            return math.nan
        rank = (q / 100.0) * total
        cum = 0
        for i, c in enumerate(counts):
            if c and cum + c >= rank:
                lo = self.buckets[i - 1] if i > 0 else min(lo_seen, self.buckets[0])
                hi = self.buckets[i] if i < len(self.buckets) else hi_seen
                v = lo + ((rank - cum) / c) * (hi - lo)
                return min(max(v, lo_seen), hi_seen)
            cum += c
        return hi_seen

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            lo, hi = self._min, self._max
        return {
            "count": total,
            "sum": s,
            "min": lo if total else math.nan,
            "max": hi if total else math.nan,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
            "buckets": {**{f"{b:g}": c for b, c in zip(self.buckets, counts)},
                        "+inf": counts[-1]},
        }


class Registry:
    """Named instrument registry. Instruments are get-or-create: the first
    caller fixes the type (and a histogram's buckets); a later request for
    the same name with a different type raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls, factory: Callable[[], object]):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter, lambda: Counter(name))

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge, lambda: Gauge(name))

    def histogram(self, name: str,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        return self._get(
            name, Histogram,
            lambda: Histogram(name, buckets or LATENCY_BUCKETS_S))

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Point-in-time plain-dict view (JSON-safe)."""
        with self._lock:
            items = sorted(self._metrics.items())
        out: Dict[str, Dict[str, object]] = {
            "counters": {}, "gauges": {}, "histograms": {}}
        for name, m in items:
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def render_text(self) -> str:
        """Prometheus-flavoured text export (counter/gauge/histogram with
        cumulative ``_bucket{le=...}`` lines)."""
        with self._lock:
            items = sorted(self._metrics.items())
        lines = []
        for name, m in items:
            n = _sanitize(name)
            if isinstance(m, Counter):
                lines += [f"# TYPE {n} counter", f"{n} {m.value:g}"]
            elif isinstance(m, Gauge):
                lines += [f"# TYPE {n} gauge", f"{n} {m.value:g}"]
            else:
                snap = m.snapshot()
                lines.append(f"# TYPE {n} histogram")
                cum = 0
                for b, c in snap["buckets"].items():
                    cum += c
                    le = b if b != "+inf" else "+Inf"
                    lines.append(f'{n}_bucket{{le="{le}"}} {cum}')
                lines += [f"{n}_sum {snap['sum']:g}",
                          f"{n}_count {snap['count']}"]
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


# -- default process registry ---------------------------------------------
default_registry = Registry()


def registry() -> Registry:
    return default_registry


def counter(name: str) -> Counter:
    return default_registry.counter(name)


def gauge(name: str) -> Gauge:
    return default_registry.gauge(name)


def histogram(name: str, buckets=None) -> Histogram:
    return default_registry.histogram(name, buckets)


def snapshot() -> Dict[str, Dict[str, object]]:
    return default_registry.snapshot()


def render_text() -> str:
    return default_registry.render_text()


def reset() -> None:
    default_registry.reset()


# -- tracing integration ---------------------------------------------------
def enable_span_metrics(reg: Optional[Registry] = None) -> None:
    """Route :mod:`raft_tpu.core.tracing` span durations into ``reg``
    (default registry when None): every annotate/range span observes a
    ``span.<name>`` latency histogram.

    One consumer per process: tracing has a single timer slot, so the
    last ``enable_span_metrics`` wins and ``disable_span_metrics``
    stops span metrics process-wide. Multi-tenant isolation applies to
    the serve runtime's own metrics (pass ``registry=`` to
    MicroBatcher), not to spans."""
    target = reg or default_registry
    from ..core import tracing

    tracing.set_timer(
        lambda name, seconds: target.histogram(f"span.{name}").observe(seconds))


def disable_span_metrics() -> None:
    from ..core import tracing

    tracing.set_timer(None)
