"""The exportable ops surface: one place an operator (or a test, or a
post-mortem) reads the serving runtime's live state.

``/debugz`` in spirit: :func:`snapshot` assembles a JSON-safe dict of
everything the telemetry layer knows — the metrics registry, the bucket
ladder's occupancy (per-bucket dispatch counts + admission queue
depth), the autotune verdict table, the guarded-demotion table, the
flight-recorder tail, the sampled span log, and any armed faults —
and :func:`render_text` renders the same as a human-readable page.
:class:`SnapshotWriter` persists snapshots on an interval so a crashed
or wedged process leaves its last state on disk.

Everything here is read-only over layers that are already process-local
and lock-cheap; a snapshot never blocks the serving hot path.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Optional

from ..core import events, faults, tracing

__all__ = ["snapshot", "render_text", "write_snapshot", "SnapshotWriter"]


def _ladder_view(batcher, reg_snap: dict) -> dict:
    """Bucket-ladder occupancy: dispatch counts per (rows × k) shape plus
    live queue state (``reg_snap``: the snapshot already computed for the
    metrics key — one instant, not two, and no double percentile sort)."""
    prefix = f"{batcher._name}.dispatch."
    dispatch = {name[len(prefix):]: int(v)
                for name, v in reg_snap["counters"].items()
                if name.startswith(prefix)}
    return {
        "query_buckets": list(batcher.ladder.query_buckets),
        "k_buckets": list(batcher.ladder.k_buckets),
        "dispatches": {f"{mb}x{kb}": dispatch.get(f"{mb}x{kb}", 0)
                       for mb, kb in batcher.ladder.shapes()},
        "queue_depth": len(batcher.queue),
        "queue_max_depth": batcher.queue.max_depth,
        "queue_closed": batcher.queue.closed,
    }


def _json_safe(obj):
    """Strict-JSON scrub: non-finite floats (an empty histogram's
    min/max/percentiles are NaN) become None — a post-mortem snapshot
    must parse under every strict JSON reader (jq, JSON.parse), not only
    Python's lenient loads."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def snapshot(batcher=None, registry=None, events_n: int = 50,
             spans_n: int = 20, slo=None, fabric=None) -> dict:
    """Point-in-time ops snapshot (strict-JSON-safe: no NaN/Inf leaves).

    ``batcher``: include its bucket-ladder occupancy and queue state.
    ``registry``: metrics source. When None: the batcher's own registry
    (its dispatch/stage metrics live there, wherever the operator put
    them), else the default process registry (also home of
    ``guarded.demotions`` / ``serve.recompiles``).
    ``events_n`` / ``spans_n``: flight-recorder / span-log tail sizes
    (0 = omit the tail).
    ``slo``: a :class:`~raft_tpu.serve.slo.SLOEngine` to evaluate into
    the ``slo`` section; None uses the process-installed engine
    (``slo.install``). The quality sections ride automatically: every
    live :class:`~raft_tpu.serve.quality.RecallSentinel` reports under
    ``quality`` and every ``quality.watch_index``-registered index
    under ``health``.
    ``fabric``: a :class:`~raft_tpu.serve.tenancy.ServeFabric` for the
    ``tenants`` section (per-tenant queue depth, weight, shed/served,
    brownout level, SLO verdict, cache hit rate, swap generation);
    None uses the process-installed fabric (``tenancy.install``).
    """
    from ..ops import autotune, guarded
    from . import metrics as _metrics

    if registry is None and batcher is not None:
        registry = batcher._reg
    reg = registry or _metrics.default_registry
    # SLO verdicts FIRST: an evaluation crossing into breach records an
    # slo_breach event, and this snapshot's flight-recorder tail (read
    # below) must already contain it
    slo_report = None
    try:
        from . import slo as _slo

        eng = slo if slo is not None else _slo.installed()
        if eng is not None:
            slo_report = eng.evaluate()
    except Exception:  # noqa: BLE001 - a broken engine must not take
        pass           # down the snapshot
    reg_snap = reg.snapshot()
    out = {
        "ts": time.time(),
        "metrics": reg_snap,
        "autotune": autotune.entries(),
        "demotions": guarded.demoted_sites(),
        "events": events.recent(events_n),
        "event_counts": events.counts(),
        "spans": tracing.recent_spans(spans_n),
        "faults_armed": [
            {"kind": f.kind, "pattern": f.pattern, "count": f.count,
             "value": f.value, "fires": f.fires} for f in faults.active()],
    }
    # circuit breakers (ops/guarded.py): per-site state, open-since,
    # probe count, next-probe ETA — the recovery half of the demotion
    # table above (docs/robustness.md)
    try:
        bs = guarded.breaker_snapshot()
        if bs:
            out["breakers"] = bs
    except Exception:  # noqa: BLE001 - surface must render regardless
        pass
    # brownout controller (serve/degrade.py): current ladder level +
    # recent transitions
    try:
        from . import degrade as _degrade

        ctl = _degrade.installed()
        if ctl is None and batcher is not None:
            ctl = getattr(batcher, "_degrade", None)
        if ctl is not None:
            out["brownout"] = ctl.snapshot()
    except Exception:  # noqa: BLE001 - surface must render without degrade
        pass
    # sharded-serving health: per-family shards_ok of every live sharded
    # index, the merge engine actually serving each family, and the ring
    # demotion count (previously visible only as bare counters)
    try:
        from ..parallel import sharded_ann

        out["sharded"] = sharded_ann.ops_snapshot()
    except Exception:  # noqa: BLE001 - surface must render without parallel/
        pass
    # multi-host fleet (docs/mnmg.md): per-fleet topology, per-host
    # health, served_frac, merge plan and the last host probe
    try:
        from ..parallel import fleet as _fleet

        fl = _fleet.ops_snapshot()
        if fl["fleets"]:
            out["fleet"] = fl["fleets"]
    except Exception:  # noqa: BLE001 - surface must render without fleet
        pass
    # mutable-tier state (docs/mutation.md): per-index delta rows,
    # tombstone count, WAL bytes and the last merge verdict
    try:
        from ..neighbors import mutable as _mutable

        mu = _mutable.ops_snapshot()
        if mu["indexes"]:
            out["mutable"] = mu["indexes"]
    except Exception:  # noqa: BLE001 - surface must render without mutable
        pass
    # quality half of the ops surface (docs/observability.md "Quality"):
    # sentinel rolling-recall estimates + watched-index health reports
    try:
        from . import quality as _quality

        q = _quality.ops_snapshot()
        if q["sentinels"]:
            out["quality"] = q["sentinels"]
        if q["health"]:
            out["health"] = q["health"]
        # memz: per-watched-index device bytes by component +
        # bytes_per_vector — the storage ladder's capacity claims,
        # inspectable in prod (docs/perf.md "Storage ladder")
        mz = _quality.memz_snapshot()
        if mz:
            out["memz"] = mz
    except Exception:  # noqa: BLE001 - surface must render without quality
        pass
    # multi-tenant fabric (serve/tenancy.py): per-tenant queue/SLO/
    # brownout/cache state + the shared qcache counters
    try:
        from . import tenancy as _tenancy

        fab = fabric if fabric is not None else _tenancy.installed()
        if fab is not None:
            out["tenants"] = fab.snapshot()
    except Exception:  # noqa: BLE001 - surface must render without
        pass           # the fabric
    if slo_report is not None:
        out["slo"] = slo_report
    if batcher is not None:
        out["ladder"] = _ladder_view(batcher, reg_snap)
    # scrub the WHOLE snapshot, not just the metrics sub-dict: an armed
    # fault's value or an event detail can carry inf/NaN too
    return _json_safe(out)


def _fmt_hist(name: str, h: dict) -> str:
    # unit by naming convention: only *_s histograms are seconds —
    # ratio histograms (batch_fill, padding_waste) render unitless
    u = "s" if name.endswith("_s") else ""
    return (f"  {name}: n={h['count']} p50={h['p50']:.4g}{u} "
            f"p90={h['p90']:.4g}{u} p99={h['p99']:.4g}{u} max={h['max']:.4g}{u}")


def render_text(batcher=None, registry=None, events_n: int = 20,
                spans_n: int = 5, slo=None, fabric=None) -> str:
    """Human-readable rendering of :func:`snapshot` (the text half of the
    text/JSON ops surface; the Prometheus export stays
    ``metrics.render_text``)."""
    s = snapshot(batcher, registry, events_n=events_n, spans_n=spans_n,
                 slo=slo, fabric=fabric)
    lines = [f"== raft_tpu debugz @ {time.strftime('%Y-%m-%dT%H:%M:%S')} =="]
    if "ladder" in s:
        lad = s["ladder"]
        lines += ["", "-- bucket ladder --",
                  f"  queue: {lad['queue_depth']}/{lad['queue_max_depth']}"
                  f"{' (closed)' if lad['queue_closed'] else ''}"]
        lines += [f"  {shape}: {n} dispatches"
                  for shape, n in lad["dispatches"].items()]
    m = s["metrics"]
    lines += ["", "-- counters --"]
    lines += [f"  {k}: {v:g}" for k, v in m["counters"].items()]
    lines += ["", "-- gauges --"]
    lines += [f"  {k}: {v:g}" for k, v in m["gauges"].items()]
    hists = m["histograms"]
    if hists:
        lines += ["", "-- histograms --"]
        lines += [_fmt_hist(k, h) for k, h in hists.items() if h["count"]]
    if s.get("breakers"):
        lines += ["", "-- circuit breakers --"]
        for site, b in sorted(s["breakers"].items()):
            extra = ""
            if b["state"] != "closed":
                eta = b.get("next_probe_in_s")
                extra = (f" open_for={b.get('open_for_s', 0):g}s "
                         f"next_probe_in="
                         f"{'-' if eta is None else f'{eta:g}s'}"
                         f" ({b.get('reason', '')})")
            lines.append(
                f"  {site}: {b['state'].upper()} opens={b['opens']} "
                f"probes={b['probes']} closes={b['closes']}" + extra)
    if s.get("tenants"):
        fb = s["tenants"]
        qc = fb.get("qcache") or {}
        lines += ["", f"-- tenants (fabric {fb.get('name', '?')}"
                  f"{' CLOSED' if fb.get('closed') else ''}) --"]
        if qc:
            hr = qc.get("hit_rate")
            lines.append(
                f"  qcache: {qc.get('entries', 0)}/{qc.get('capacity', 0)}"
                f" entries hit_rate="
                f"{'-' if hr is None else f'{hr:.2%}'}"
                f" hits={qc.get('hits', 0)} misses={qc.get('misses', 0)}"
                f" bypass={qc.get('bypass', 0)}"
                f" invalidated={qc.get('invalidated', 0)}")
        for tn, te in sorted((fb.get("tenants") or {}).items()):
            if "error" in te:
                lines.append(f"  {tn}: error {te['error']}")
                continue
            thr = (te.get("qcache") or {}).get("hit_rate")
            slo_v = (te.get("slo") or {}).get("verdict", "-")
            lines.append(
                f"  {tn}: w={te.get('weight', 1):g} gen="
                f"{te.get('generation', 0)} queue="
                f"{te.get('queue_depth', 0)}/{te.get('queue_max_depth', 0)}"
                f" served={te.get('served', 0)} shed={te.get('shed', 0)}"
                f" slo={slo_v}"
                + (f" brownout={te['brownout_level']}"
                   if "brownout_level" in te else "")
                + (f" tokens={te['tokens']:g}" if "tokens" in te else "")
                + (f" cache_hit="
                   f"{'-' if thr is None else f'{thr:.2%}'}"))
    if s.get("brownout"):
        bw = s["brownout"]
        lines += ["", f"-- brownout (level {bw['level']}/{bw['max_level']})"
                  " --"]
        for tr in bw.get("transitions", [])[-5:]:
            lines.append(f"  {tr['from']} -> {tr['to']} ({tr['reason']})")
    sh = s.get("sharded") or {}
    if sh.get("families"):
        lines += ["", "-- sharded search --"]
        for fam, ent in sorted(sh["families"].items()):
            ok = ent.get("shards_ok") or []
            health = " ".join(
                "".join(".X"[not b] for b in per) for per in ok) or "-"
            lines.append(
                f"  {fam}: engine={ent.get('merge_engine') or '-'} "
                f"indexes={ent.get('indexes', 0)} shards[{health}]")
            for n_idx, probes in enumerate(ent.get("last_probe", [])):
                for shard, pr in sorted(probes.items()):
                    lines.append(
                        f"    idx{n_idx} shard{shard} probe: "
                        f"{'ok' if pr.get('ok') else 'FAILED'}"
                        + (f" ({pr['error']})" if pr.get("error") else ""))
        lines.append(
            f"  ring demotions: {sh.get('ring_demotions', 0)}"
            + (" (site demoted)" if sh.get("ring_demoted") else ""))
    for fl in s.get("fleet") or []:
        hosts = "".join(".X"[not b] for b in fl.get("hosts_ok", [])) or "-"
        lines += ["", f"-- fleet ({fl.get('topology', '?')}) --",
                  f"  hosts[{hosts}] served_frac="
                  f"{fl.get('served_frac', 1.0):g} "
                  f"indexes={fl.get('n_indexes', 0)} "
                  f"engine={fl.get('merge', {}).get('engine', '?')} "
                  f"dcn_reduction="
                  f"{fl.get('merge', {}).get('dcn_reduction', 1)}x"]
        for hm in fl.get("hosts") or []:
            lines.append(
                f"  host{hm.get('host', '?')}: "
                f"device_bytes={hm.get('device_bytes', 0)} "
                f"tier_bytes={hm.get('host_tier_bytes', 0)} "
                f"rows={hm.get('rows', 0)} "
                f"bytes/vec={hm.get('bytes_per_vector', 0)}")
        lp = fl.get("last_probe") or {}
        if lp:
            lines.append(
                f"  last probe: restored={lp.get('hosts_restored', [])} "
                f"shards={lp.get('shards', {})}")
    if s.get("mutable"):
        lines += ["", "-- mutable indexes --"]
        for name, ent in sorted(s["mutable"].items()):
            if "error" in ent:
                lines.append(f"  {name}: error {ent['error']}")
                continue
            lm = ent.get("last_merge") or {}
            lines.append(
                f"  {name}: {ent['family']} gen={ent['generation']} "
                f"sealed={ent['sealed_rows']} delta={ent['delta_rows']} "
                f"tombstones={ent['tombstones']} "
                f"wal={ent['wal_bytes']}B"
                + (" MERGING" if ent.get("merging") else "")
                + (f" last_merge={lm.get('verdict')}"
                   f"({lm.get('reason', '')})" if lm else ""))
    if s.get("slo"):
        sv = s["slo"]
        lines += ["", f"-- slo ({sv['verdict']}) --"]
        for key, rep in sorted(sv["targets"].items()):
            vals = ", ".join(
                f"{f}={rep[f]:.4g}" for f in ("value", "fast", "slow")
                if isinstance(rep.get(f), (int, float)))
            lines.append(f"  {key}: {rep['verdict']} "
                         f"(target {rep['target']:g}"
                         + (f", {vals}" if vals else "") + ")")
    for q in s.get("quality") or []:
        lines += ["", f"-- recall sentinel ({q['name']}) --",
                  f"  sampled={q['sampled']} scored={q['scored']} "
                  f"dropped={q['dropped']} pending={q['pending']}"
                  + (f" floor={q['floor']:g}" if q.get("floor") is not None
                     else "")]
        for fam, ent in sorted(q["families"].items()):
            est = ent["estimate"]
            lines.append(
                f"  {fam}: recall={est if est is not None else '-'} "
                f"(n={ent['samples']})"
                + (" BELOW FLOOR" if ent.get("below_floor") else ""))
    if s.get("memz"):
        lines += ["", "-- memz (device bytes) --"]
        for name, rep in sorted(s["memz"].items()):
            if "error" in rep:
                lines.append(f"  {name}: error {rep['error']}")
                continue
            parts = " ".join(f"{c}={v}" for c, v in
                             sorted((rep.get("components") or {}).items()))
            bpv = rep.get("bytes_per_vector")
            lines.append(
                f"  {name}: {rep.get('family', '?')} "
                f"total={rep.get('total_device_bytes', 0)}B "
                f"b/vec={bpv if bpv is not None else '-'} {parts}")
            hsn = rep.get("host_stream")
            if hsn:
                lines.append(
                    f"    host tier: {hsn['cold_lists']} cold lists "
                    f"{hsn['host_bytes']}B host, saved "
                    f"{hsn['device_bytes_saved']}B device, streamed "
                    f"{hsn['streamed_chunks']} chunks")
    if s.get("health"):
        lines += ["", "-- index health --"]
        for name, rep in sorted(s["health"].items()):
            if "error" in rep:
                lines.append(f"  {name}: error {rep['error']}")
                continue
            bits = [rep.get("family", "?"), f"n={rep.get('n', rep.get('n_total', '?'))}"]
            if "unreachable_nodes" in rep:
                bits.append(f"unreachable={rep['unreachable_nodes']}")
            if "lists" in rep:
                bits.append(f"list_cv={rep['lists'].get('cv', '-')}")
            if "healthy_shards" in rep:
                bits.append(f"shards={rep['healthy_shards']}/{rep['n_shards']}")
            if "quant" in rep:
                bits.append(f"quant={','.join(sorted(rep['quant']))}")
            lines.append(f"  {name}: " + " ".join(str(b) for b in bits))
    if s["demotions"]:
        lines += ["", "-- guarded demotions --"]
        lines += [f"  {site}: {why}" for site, why in s["demotions"].items()]
    if s["autotune"]:
        lines += ["", "-- autotune verdicts --"]
        lines += [f"  {k} -> {v}" for k, v in sorted(s["autotune"].items())]
    if s["faults_armed"]:
        lines += ["", "-- armed faults --"]
        lines += [f"  {f['kind']}@{f['pattern']} fires={f['fires']}"
                  for f in s["faults_armed"]]
    if s["events"]:
        lines += ["", f"-- flight recorder (last {len(s['events'])}) --"]
        for e in s["events"]:
            extra = {k: v for k, v in e.items()
                     if k not in ("seq", "ts", "kind", "site", "trace_id")}
            lines.append(
                f"  #{e['seq']} {e['kind']} @ {e['site']}"
                + (f" trace={e['trace_id']}" if e.get("trace_id") else "")
                + (f" {extra}" if extra else ""))
    if s["spans"]:
        lines += ["", f"-- sampled request spans (last {len(s['spans'])}) --"]
        for sp in s["spans"]:
            stages = " ".join(f"{k}={v * 1e3:.2f}ms"
                              for k, v in sp["stages"].items())
            lines.append(f"  {sp['trace_id']}: {stages}")
    return "\n".join(lines) + "\n"


def write_snapshot(path: str, batcher=None, registry=None, slo=None,
                   fabric=None) -> dict:
    """Write one JSON snapshot atomically (tmp + rename); returns it."""
    s = snapshot(batcher, registry, slo=slo, fabric=fabric)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(s, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return s


class SnapshotWriter:
    """Background ops-snapshot persistence: a daemon thread writing
    :func:`write_snapshot` to ``path`` every ``interval_s`` (and once on
    ``stop``, so the final state always lands). Context-manager form
    scopes it to a serving run.

    ``hooks``: callables invoked (guarded) each tick BEFORE the write —
    the serving loop's maintenance slot. The self-healing layer hangs
    its periodic work here: ``sharded_ann.probe_all`` re-probes dead
    shards, ``BrownoutController.poll`` consumes SLO verdicts
    (docs/robustness.md), and a multi-tenant fabric hangs
    ``ServeFabric.tick`` (per-tenant SLO poll + swap retire,
    docs/serving.md) — so the snapshot that lands each tick already
    reflects that tick's probes, ladder moves and retires."""

    def __init__(self, path: str, interval_s: float = 10.0, batcher=None,
                 registry=None, slo=None, hooks=(), fabric=None):
        self.path = path
        self.interval_s = float(interval_s)
        self._batcher = batcher
        self._registry = registry
        self._slo = slo
        self._fabric = fabric
        self._hooks = tuple(hooks)
        if fabric is not None:
            # the fabric's maintenance tick rides the hook slot
            self._hooks = self._hooks + (fabric.tick,)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-hook failure latches (index-aligned with _hooks): a soak
        # must surface a dead maintenance hook, so failures are counted
        # per hook and flight-recorded once per TRANSITION (first
        # failure / recovery), never once per tick
        self._hook_failing = [False] * len(self._hooks)

    @staticmethod
    def _hook_name(h) -> str:
        name = getattr(h, "__qualname__", None) \
            or getattr(h, "__name__", None) or repr(h)
        return name.replace("<", "").replace(">", "")

    def tick(self) -> None:
        """Run the maintenance hooks once (each guarded — one failing
        hook must not starve the rest or the write). Failures are
        counted under ``debugz.hook_errors.<name>`` and recorded as one
        ``hook_error`` event per transition."""
        from . import metrics as _metrics

        reg = self._registry or _metrics.default_registry
        for i, h in enumerate(self._hooks):
            try:
                h()
            except Exception as exc:  # noqa: BLE001 - a broken hook
                # must not kill the maintenance loop
                name = self._hook_name(h)
                try:
                    reg.counter(f"debugz.hook_errors.{name}").inc()
                    if not self._hook_failing[i]:
                        self._hook_failing[i] = True
                        events.record("hook_error", f"debugz.{name}",
                                      action="failed", error=exc)
                except Exception:  # noqa: BLE001 - telemetry best-effort
                    pass
            else:
                if self._hook_failing[i]:
                    self._hook_failing[i] = False
                    try:
                        events.record("hook_error",
                                      f"debugz.{self._hook_name(h)}",
                                      action="recovered")
                    except Exception:  # noqa: BLE001
                        pass

    def write_once(self) -> dict:
        return write_snapshot(self.path, self._batcher, self._registry,
                              slo=self._slo, fabric=self._fabric)

    def start(self) -> "SnapshotWriter":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="debugz-snapshots", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()
            try:
                self.write_once()
            except Exception:  # noqa: BLE001 - a failed write must not
                pass           # kill the writer (disk full, path gone)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval_s + 5.0)
            self._thread = None
        try:
            self.write_once()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self) -> "SnapshotWriter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
