"""Startup warmup for the bucket ladder, plus compilation-count
instrumentation and the always-on recompile watch.

The recompile-avoidance guarantee of :mod:`raft_tpu.serve.batcher` is
only worth anything if every ladder shape is compiled BEFORE traffic
arrives — an un-warmed bucket turns the first unlucky request into a
multi-second XLA compile stall. :func:`warmup` dispatches a dummy batch
through the live search closure at every (query-bucket × k-bucket)
shape and blocks on the results, so steady-state serving hits only
cached executables.

The matching measurement wraps ``jax._src.compiler.backend_compile`` —
the single funnel both the jit cache-miss path and
``compile_or_get_cached`` route through on jax 0.4.x — and comes in two
layers:

* :func:`install_recompile_watch` patches the funnel ONCE per process
  (idempotent) with a spy that (a) increments the always-on
  ``serve.compiles`` total, and (b) for compiles carrying a
  non-warmup :func:`compile_context` label (the batcher sets its
  ``<name>:<rows>x<k>`` shape bucket around every dispatch) — i.e. a
  SERVING-PATH post-warmup recompile, the rare degradation signal —
  additionally increments ``serve.recompiles`` and records an
  ``xla_compile`` flight-recorder event. Warmup-context and unlabeled
  compiles (a warmup sweep, an index build mid-serve) are counted but
  get no ring event: hundreds of legitimate first compiles must not
  churn the demotion/shed events out of the bounded recorder.
  ``serve.recompiles`` reads 0 right after a clean warmup.
* :func:`count_compilations` subscribes a counter to that stream for
  the duration of a block, letting the load test assert the headline
  property literally: after warmup, a stream of mixed-size requests
  causes **zero** new XLA compilations.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional

import jax
import numpy as np

from ..core.errors import expects

__all__ = ["CompileCounter", "count_compilations", "warmup",
           "warmup_sharded", "install_recompile_watch", "compile_context"]


class CompileCounter:
    """Mutable count of XLA backend compiles inside a
    :func:`count_compilations` block."""

    def __init__(self):
        self.count = 0


# persistent watch state: original funnel + live subscriber counters
_watch_lock = threading.Lock()
_watch_subs: List[CompileCounter] = []
_watch_installed = False
_ctx = threading.local()        # .label (str), .warmup (bool)


def _compile_funnel():
    """The versioned private compile funnel (raises RuntimeError if this
    jax moved it — a vacuous zero would silently gut every recompile
    assertion, and callers degrading gracefully catch RuntimeError)."""
    try:
        from jax._src import compiler as _compiler  # versioned private API
    except ImportError as e:
        raise RuntimeError(
            f"jax._src.compiler not importable on jax {jax.__version__} "
            f"({e}); update serve.warmup to this version's compile "
            "funnel") from e

    orig = getattr(_compiler, "backend_compile", None)
    if orig is None:
        raise RuntimeError(
            "jax._src.compiler.backend_compile not found on jax "
            f"{jax.__version__}; update serve.warmup to this version's "
            "compile funnel")
    return _compiler, orig


@contextlib.contextmanager
def compile_context(label: str, warmup: bool = False):
    """Label compiles observed by the watch for the dynamic extent of the
    block (thread-local — the batcher worker labels its own dispatches).
    ``warmup=True`` additionally exempts them from ``serve.recompiles``.
    Cheap: two attribute writes; safe with the watch uninstalled."""
    prev = (getattr(_ctx, "label", None), getattr(_ctx, "warmup", False))
    _ctx.label, _ctx.warmup = label, warmup
    try:
        yield
    finally:
        _ctx.label, _ctx.warmup = prev


def install_recompile_watch() -> None:
    """Install the persistent compile spy (idempotent; see module
    docstring). Raises RuntimeError when the compile funnel moved."""
    global _watch_installed
    with _watch_lock:
        if _watch_installed:
            return
        _compiler, orig = _compile_funnel()

        def _spy(*args, **kwargs):
            with _watch_lock:
                subs = list(_watch_subs)
            for c in subs:
                c.count += 1
            label = getattr(_ctx, "label", None)
            in_warmup = bool(getattr(_ctx, "warmup", False))
            try:
                from . import metrics as _metrics

                # total compile magnitude, visible in any snapshot
                _metrics.counter("serve.compiles").inc()
                # SERVING-PATH post-warmup recompiles are the rare
                # degradation signal: only those earn a flight-recorder
                # event + the serve.recompiles counter (the batcher
                # labels every dispatch). A warmup sweep is ~100+
                # compiles and an operator building a second index
                # mid-serve is hundreds of legitimate first compiles —
                # per-compile ring events would churn the demotion/shed
                # events out of the bounded ring (same dampening as
                # faults._emit_fire / sharded _mark_shard).
                if label is not None and not in_warmup:
                    from ..core import events as _events

                    _events.record("xla_compile", label, warmup=False)
                    _metrics.counter("serve.recompiles").inc()
            except Exception:  # noqa: BLE001 - telemetry must not break compiles
                pass
            return orig(*args, **kwargs)

        _compiler.backend_compile = _spy
        _watch_installed = True


@contextlib.contextmanager
def count_compilations():
    """Count XLA compilations during the block (yields a
    :class:`CompileCounter`). Installs the persistent watch on first use
    and subscribes to it — nested/concurrent blocks each see every
    compile. Raises if this jax version moved the compile funnel."""
    install_recompile_watch()
    counter = CompileCounter()
    with _watch_lock:
        _watch_subs.append(counter)
    try:
        yield counter
    finally:
        with _watch_lock:
            _watch_subs.remove(counter)


def warmup(search_fn, ladder, dim: int, dtype=np.float32, registry=None,
           name: str = "serve", prepare=None, engines=None,
           shapes=None) -> int:
    """Dispatch a dummy batch through ``search_fn`` at every ladder shape
    and block on each result. Returns the number of XLA compilations the
    sweep triggered (0 when the process is already warm). Records
    ``<name>.warmup.shapes`` (gauge) and ``<name>.warmup.compiles``
    (counter); warmup compiles are exempt from ``serve.recompiles``
    (they are the warmup, not a post-warmup regression).

    ``prepare``: optional zero-arg callable run BEFORE the sweep for
    index-side cache builds that must not land on the first unlucky
    request — e.g. ``lambda: brute_force.prepare_fused(index)``,
    ``lambda: cagra.prepare_traversal(index, "pq")`` (an edge store is
    seconds of gather+pack — and the PQ rung minutes of codebook
    training — at corpus scale, and the jitted ladder shapes can only
    reuse it if it exists before their first trace), or
    ``lambda: ivf_flat.prepare_host_stream(index)`` (restructuring the
    resident layout mid-traffic would recompile every bucket).

    ``engines``: optional ``{engine_name: search_fn}`` mapping — every
    engine closure is swept across the FULL ladder (``search_fn`` may
    be None then). This is how a multi-engine family pre-compiles every
    traversal engine at the serving buckets (the cagra fused megakernel
    must never be first-request compiled; the engine drift guard in
    tests/test_quality.py holds every registered engine to it).

    ``shapes``: optional explicit ``[(query_bucket, k_bucket), ...]``
    subset to warm instead of the ladder's full cross product — a
    tenant swap (:meth:`raft_tpu.serve.tenancy.Tenant.swap`) warms the
    replacement index only at the shapes that tenant has actually
    served, off the hot path."""
    from . import metrics as _metrics

    reg = registry or _metrics.default_registry
    if prepare is not None:
        prepare()
    if engines is not None:
        # an explicitly-empty mapping (every engine capability-filtered
        # out) warms nothing — it must NOT fall back to search_fn, which
        # the engines contract allows to be None
        fns = dict(engines)
    else:
        expects(search_fn is not None,
                "warmup needs a search_fn or an engines mapping")
        fns = {"": search_fn}
    if shapes is None:
        sweep = [(mb, kb) for mb in ladder.query_buckets
                 for kb in ladder.k_buckets]
    else:
        sweep = [(int(mb), int(kb)) for mb, kb in shapes]
    n_shapes = 0
    with count_compilations() as cc:
        for eng, fn in fns.items():
            tag = f":{eng}" if eng else ""
            for mb, kb in sweep:
                q = np.zeros((mb, int(dim)), dtype)
                with compile_context(f"{name}:warmup{tag}:{mb}x{kb}",
                                     warmup=True):
                    out = fn(q, kb)
                    # block the FULL output pytree: compiles are lazy
                    # until the dispatch executes, and a 3-tuple
                    # (shards_ok) or donated-closure output whose
                    # tail leaves were never forced would leave the
                    # first real request a residual trace to pay
                    jax.block_until_ready(out)
                n_shapes += 1
    reg.gauge(f"{name}.warmup.shapes").set(n_shapes)
    reg.counter(f"{name}.warmup.compiles").inc(cc.count)
    return cc.count


def warmup_sharded(index, k_buckets, m_buckets=(8, 64), *, dim=None,
                   dtype=np.float32, params=None, registry=None,
                   name: str = "sharded", fleet=None, **opts) -> int:
    """Pre-compile a sharded/fleet index's dispatch ladder: every
    (m-bucket × k-bucket) shape, for the base params AND every
    degradation auto-widen ``n_probes`` rung a shard/host loss can
    produce (:func:`raft_tpu.parallel.sharded_ann.widen_rungs`) — so a
    ``mark_host_failed`` widen or a tier step lands on a cached
    executable with ZERO compiles, and steady-state sharded serving
    never traces.

    The searchers themselves stay sync-free on the hot path — all the
    blocking happens here, inside the warmup compile context, so the
    sweep's compiles are counted but exempt from ``serve.recompiles``
    and the ``xla_compile`` ring (module docstring).

    ``fleet``: pass the owning :class:`~raft_tpu.parallel.fleet.Fleet`
    for fleet-adopted indexes — the rung closures then dispatch through
    ``Fleet.search`` so a budgeted build's cold-list merge warms with
    the resident programs. ``dim`` defaults to the index's query
    dimensionality; extra ``opts`` reach the searchers (e.g.
    ``allow_partial=True``, ``merge_engine=``). Returns the compile
    count of the sweep (0 when already warm)."""
    from ..parallel import sharded_ann

    if fleet is not None:
        engines = fleet.warmup_searchers(index, params, **opts)
    else:
        engines = sharded_ann.warmup_searchers(index, params, **opts)
    if dim is None:
        dim = sharded_ann.searcher_dim(index)
    shapes = [(int(mb), int(kb)) for mb in m_buckets for kb in k_buckets]
    return warmup(None, None, dim, dtype, registry=registry, name=name,
                  engines=engines, shapes=shapes)
