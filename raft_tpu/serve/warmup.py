"""Startup warmup for the bucket ladder, plus compilation-count
instrumentation.

The recompile-avoidance guarantee of :mod:`raft_tpu.serve.batcher` is
only worth anything if every ladder shape is compiled BEFORE traffic
arrives — an un-warmed bucket turns the first unlucky request into a
multi-second XLA compile stall. :func:`warmup` dispatches a dummy batch
through the live search closure at every (query-bucket × k-bucket)
shape and blocks on the results, so steady-state serving hits only
cached executables.

:func:`count_compilations` is the matching measurement: it wraps
``jax._src.compiler.backend_compile`` — the single funnel both the jit
cache-miss path and ``compile_or_get_cached`` route through on jax
0.4.x — and counts invocations, letting the load test assert the
headline property literally: after warmup, a stream of mixed-size
requests causes **zero** new XLA compilations.
"""
from __future__ import annotations

import contextlib

import jax
import numpy as np

__all__ = ["CompileCounter", "count_compilations", "warmup"]


class CompileCounter:
    """Mutable count of XLA backend compiles inside a
    :func:`count_compilations` block."""

    def __init__(self):
        self.count = 0


@contextlib.contextmanager
def count_compilations():
    """Count XLA compilations during the block (yields a
    :class:`CompileCounter`). Raises if this jax version moved the
    compile funnel — a vacuous zero would silently gut the load test's
    recompile assertion."""
    from jax._src import compiler as _compiler  # versioned private API

    orig = getattr(_compiler, "backend_compile", None)
    if orig is None:
        raise RuntimeError(
            "jax._src.compiler.backend_compile not found on jax "
            f"{jax.__version__}; update count_compilations() to this "
            "version's compile funnel")
    counter = CompileCounter()

    def _spy(*args, **kwargs):
        counter.count += 1
        return orig(*args, **kwargs)

    _compiler.backend_compile = _spy
    try:
        yield counter
    finally:
        _compiler.backend_compile = orig


def warmup(search_fn, ladder, dim: int, dtype=np.float32, registry=None,
           name: str = "serve", prepare=None) -> int:
    """Dispatch a dummy batch through ``search_fn`` at every ladder shape
    and block on each result. Returns the number of XLA compilations the
    sweep triggered (0 when the process is already warm). Records
    ``<name>.warmup.shapes`` (gauge) and ``<name>.warmup.compiles``
    (counter).

    ``prepare``: optional zero-arg callable run BEFORE the sweep for
    index-side cache builds that must not land on the first unlucky
    request — e.g. ``lambda: brute_force.prepare_fused(index)`` or
    ``lambda: cagra.prepare_traversal(index)`` (the edge-resident
    candidate store is seconds of gather+pack at corpus scale, and the
    jitted ladder shapes can only reuse it if it exists before their
    first trace)."""
    from . import metrics as _metrics

    reg = registry or _metrics.default_registry
    if prepare is not None:
        prepare()
    shapes = 0
    with count_compilations() as cc:
        for mb in ladder.query_buckets:
            q = np.zeros((mb, int(dim)), dtype)
            for kb in ladder.k_buckets:
                out = search_fn(q, kb)
                # block: compiles are lazy until the dispatch executes
                jax.block_until_ready((out[0], out[1]))
                shapes += 1
    reg.gauge(f"{name}.warmup.shapes").set(shapes)
    reg.counter(f"{name}.warmup.compiles").inc(cc.count)
    return cc.count
