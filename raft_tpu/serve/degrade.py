"""SLO-driven adaptive degradation: the brownout controller
(docs/robustness.md "Brownout ladder").

The SLO engine (PR 8) made breaches *visible*; this module makes them
*actuate*. A :class:`BrownoutController` consumes
:class:`~raft_tpu.serve.slo.SLOEngine` verdicts and walks a ladder of
progressively cheaper serving configurations — the Tail-at-Scale
playbook of trading a little recall for a lot of tail latency, bounded
by the recall floor the sentinel measures online:

* **step down** (level += 1, cheaper) on a latency or shed-rate
  *breach*: shrink ``n_probes``/``itopk_size``, widen the batcher's
  max-wait (bigger batches, fewer dispatches), prefer a cheaper engine;
* **step up** (level -= 1, toward baseline) on a recall-floor breach —
  quality beats latency, always — or after the objectives have been
  green for ``up_after_s`` (brownouts must be temporary);
* **never step down past the floor**: while the recall sentinel has
  samples and reports ``warn``/``breach``, further degradation is
  refused — the controller cannot trade away recall it can already see
  is at the floor;
* **hysteresis**: at most one step per ``min_dwell_s``, and stepping up
  requires a sustained-green window — a controller that flaps between
  levels is worse than either level.

Ladder levels are plain dicts of search-param overrides (applied via
``dataclasses.replace`` to whatever ``SearchParams`` the family uses,
unknown keys ignored) plus the reserved key ``max_wait_scale``. Every
level's params MUST land on shapes the serving ladder has already
compiled — the overrides change traced *values* with the same shape
buckets, so each level costs one compile on first use and zero after
(pre-warm the levels you expect to visit). ``make_searcher(...,
degrade=ctl)`` on ivf_flat/ivf_pq/cagra and ``MicroBatcher(...,
degrade=ctl)`` pick the current level up per call — no rebuild, no
recompile mid-traffic.

Every transition is a trace-stamped ``brownout`` flight-recorder event
and moves the ``<name>.brownout.level`` gauge, so a bench run or
post-mortem that silently browned out is distinguishable from a clean
one. ``install()`` registers the controller for the debugz snapshot
(one per process slot, like the SLO engine); wire ``ctl.poll`` into
``SnapshotWriter(hooks=[...])`` to evaluate on the ops cadence.

Knobs: ``RAFT_TPU_BROWNOUT_MIN_DWELL_S`` (default 5),
``RAFT_TPU_BROWNOUT_UP_AFTER_S`` (default 15),
``RAFT_TPU_BROWNOUT_MAX_LEVEL`` (cap the ladder depth; default = all
configured levels).

Like the SLO engine, the controller is a plain instance: the
multi-tenant fabric (:mod:`raft_tpu.serve.tenancy`) runs one per tenant
(each consuming its own tenant's SLO verdicts, so one tenant browning
out never degrades another's params); the process-global ``install()``
slot stays the single-tenant default.
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import time
import weakref
from typing import Callable, Optional, Sequence

from ..core import events
from ..utils import env_float

__all__ = ["BrownoutController", "DEFAULT_LEVELS", "install", "installed",
           "uninstall"]

# a conservative generic ladder: level 0 is always baseline (no
# overrides); operators serving a specific family should write their own
# levels against its tuned params (docs/robustness.md has worked
# examples). Values here only bite where the field exists on the
# family's SearchParams.
DEFAULT_LEVELS: tuple = (
    {"max_wait_scale": 2.0},
    {"n_probes": 10, "itopk_size": 32, "max_wait_scale": 4.0},
)


class BrownoutController:
    """Walks a degradation ladder from SLO verdicts; see module
    docstring. ``levels``: the degraded steps (level 0 = baseline = no
    overrides is implicit). ``slo``: an engine for :meth:`poll` to
    evaluate (verdicts can also be fed directly via
    :meth:`on_report`). ``clock`` is injectable for deterministic
    tests."""

    def __init__(self, levels: Optional[Sequence[dict]] = None, *,
                 slo=None, min_dwell_s: Optional[float] = None,
                 up_after_s: Optional[float] = None,
                 registry=None, name: str = "serve",
                 clock: Callable[[], float] = time.monotonic):
        from . import metrics as _metrics

        self._ladder = [{}] + [dict(lv) for lv in
                               (DEFAULT_LEVELS if levels is None
                                else levels)]
        max_lv = int(env_float("RAFT_TPU_BROWNOUT_MAX_LEVEL",
                            len(self._ladder) - 1))
        self.max_level = max(0, min(max_lv, len(self._ladder) - 1))
        self.min_dwell_s = (
            env_float("RAFT_TPU_BROWNOUT_MIN_DWELL_S", 5.0)
            if min_dwell_s is None else float(min_dwell_s))
        self.up_after_s = (
            env_float("RAFT_TPU_BROWNOUT_UP_AFTER_S", 15.0)
            if up_after_s is None else float(up_after_s))
        self._slo = slo
        self._name = name
        self._clock = clock
        self._reg = registry or _metrics.default_registry
        self._gauge = self._reg.gauge(f"{name}.brownout.level")
        self._gauge.set(0)
        self._steps = self._reg.counter(f"{name}.brownout.transitions")
        self._lock = threading.Lock()
        self._level = 0
        self._last_step_at = -float("inf")
        self._green_since: Optional[float] = None
        # bounded transition log: the bench artifact and debugz read it
        self._transitions: collections.deque = collections.deque(maxlen=64)

    # -- hot-path reads ---------------------------------------------------
    # These three run per request/batch on the serving path and read the
    # current level lock-free by design: ``_level`` is a GIL-atomic int,
    # ``_ladder`` is frozen after __init__, and a one-step-stale level is
    # exactly as correct as a fresh one (the controller's own dwell is
    # seconds). Taking the lock here would serialize every dispatch
    # against the control loop for nothing.
    @property
    def level(self) -> int:
        # lint: waive(unlocked-attr): GIL-atomic int peek, hot path
        return self._level

    def params(self, base):
        """Apply the current level's overrides to a ``SearchParams``
        dataclass (fields the class doesn't have are ignored — one
        ladder can serve several families). Returns ``base`` unchanged
        at level 0."""
        # lint: waive(unlocked-attr): GIL-atomic int peek, hot path
        lv = self._ladder[self._level]
        if not lv or base is None:
            return base
        names = {f.name for f in dataclasses.fields(base)}
        over = {k: v for k, v in lv.items() if k in names}
        return dataclasses.replace(base, **over) if over else base

    def max_wait_scale(self) -> float:
        """Batch max-wait multiplier at the current level (>= 1.0):
        under brownout the batcher coalesces harder — bigger batches,
        fewer dispatches — at the cost of queue wait."""
        # lint: waive(unlocked-attr): GIL-atomic int peek, hot path
        return float(self._ladder[self._level].get("max_wait_scale", 1.0))

    # -- control loop -----------------------------------------------------
    def poll(self) -> dict:
        """Evaluate the attached SLO engine and act on its verdicts.
        Returns the engine report with ``brownout_level`` attached."""
        if self._slo is None:
            with self._lock:
                return {"brownout_level": self._level}
        report = self._slo.evaluate()
        # on_report returns the post-step level from under its own lock
        # hold — re-reading self._level here could see a racing step
        report["brownout_level"] = self.on_report(report)
        return report

    def on_report(self, report: dict) -> int:
        """Consume one SLO verdict report (``SLOEngine.evaluate()``
        shape) and maybe step the ladder; returns the level after."""
        t = report.get("targets", {})

        def verdict(key):
            return t.get(key, {}).get("verdict", "ok")

        lat_verdicts = (verdict("p99_latency_s"), verdict("shed_rate"))
        lat_breach = "breach" in lat_verdicts
        mem_breach = verdict("device_bytes") == "breach"
        rec = t.get("recall", {})
        rec_v = rec.get("verdict", "ok")
        rec_watched = (int(rec.get("samples", 0) or 0) > 0
                       and rec.get("note") != "insufficient_samples")
        with self._lock:
            now = self._clock()
            # the recovery timer requires GREEN, not merely not-breach:
            # a sustained latency "warn" (one window still violated)
            # accruing green time would step up straight back into the
            # breach — the flap the sustained-green rule exists to stop
            all_ok = (all(v == "ok" for v in lat_verdicts)
                      and rec_v == "ok" and not mem_breach)
            if not all_ok:
                self._green_since = None
            elif self._green_since is None:
                self._green_since = now
            if mem_breach:
                # the MEMORY axis (ROADMAP item 3): measured over the
                # HBM budget steps DOWN the ladder instead of OOMing.
                # Memory outranks even the recall-floor refusal — a
                # floor defended into an OOM serves nothing — and skips
                # the dwell: the breach is measured headroom, not a
                # tail blip
                self._step_locked(+1, now, "memory", urgent=True)
            elif rec_v == "breach" and rec_watched:
                # quality floor wins over everything: climb back toward
                # baseline even while latency still burns — and without
                # waiting out the dwell (hysteresis exists to stop
                # flapping, not to hold serving below a measured floor)
                self._step_locked(-1, now, "recall_floor", urgent=True)
            elif lat_breach:
                if rec_watched and rec_v != "ok":
                    # the sentinel says recall is AT the floor: refuse
                    # to trade away quality we can see is already gone
                    pass
                else:
                    self._step_locked(+1, now, "latency")
            elif (all_ok and self._level > 0
                    and self._green_since is not None
                    and now - self._green_since >= self.up_after_s):
                self._step_locked(-1, now, "recovered")
            return self._level

    def _step_locked(self, delta: int, now: float, reason: str,
                     urgent: bool = False) -> None:
        if not urgent and now - self._last_step_at < self.min_dwell_s:
            return
        new = max(0, min(self._level + delta, self.max_level))
        if new == self._level:
            return
        old, self._level = self._level, new
        self._last_step_at = now
        tr = {"ts": time.time(), "from": old, "to": new, "reason": reason}
        self._transitions.append(tr)
        self._gauge.set(new)
        self._steps.inc()
        try:
            events.record("brownout", f"{self._name}.brownout",
                          level_from=old, level_to=new, reason=reason)
        except Exception:  # noqa: BLE001 - telemetry must not block
            pass           # the control loop

    # -- introspection ----------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-safe view for the debugz ``brownout`` section and the
        bench artifact."""
        with self._lock:
            return {
                "level": self._level,
                "max_level": self.max_level,
                "min_dwell_s": self.min_dwell_s,
                "up_after_s": self.up_after_s,
                "ladder": [dict(lv) for lv in self._ladder],
                "transitions": [dict(tr) for tr in self._transitions],
            }

    def install(self) -> "BrownoutController":
        install(self)
        return self


# -- process slot for the debugz snapshot (mirrors serve/slo.py) -----------
_installed: Optional["weakref.ref"] = None


def install(controller: BrownoutController) -> None:
    """Register ``controller`` as the process's debugz brownout source
    (weak: dropping the controller uninstalls it)."""
    global _installed
    _installed = weakref.ref(controller)


def installed() -> Optional[BrownoutController]:
    return _installed() if _installed is not None else None


def uninstall() -> None:
    global _installed
    _installed = None
