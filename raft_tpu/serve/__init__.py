"""Query-serving runtime: shape-bucketed micro-batching, admission
control, warmup, and a metrics registry (docs/serving.md).

RAFT is consumed through a handle/stream-pool runtime that multiplexes
concurrent callers onto the device (SURVEY §1 layer 1); the
TPU-idiomatic equivalent is a micro-batching scheduler: requests
coalesce under a max-wait/max-batch policy, pad to a fixed ladder of
pre-compiled shape buckets (steady-state traffic never triggers an XLA
recompile), dispatch through the existing ``search()`` paths, and
demultiplex back to callers — with bounded-queue backpressure, deadline
shedding/partial results, degraded sharded serving, and process-local
operational metrics.

- ``metrics``   counters/gauges/histograms, snapshot + text export,
                tracing-span timing (dependency-free)
- ``admission`` bounded request queue, backpressure, deadline shedding
- ``batcher``   BucketLadder + MicroBatcher (coalesce/pad/dispatch/demux)
- ``warmup``    ladder pre-compile + recompile watch + compile counting
- ``debugz``    exportable ops snapshot/text surface + background writer
                (docs/observability.md)
- ``quality``   online recall sentinel + index health introspection
                (docs/observability.md "Quality")
- ``slo``       declarative SLO engine over the metrics registry
                (burn-rate windows, slo_breach events)
- ``degrade``   SLO-driven brownout controller: adaptive degradation
                ladder with hysteresis (docs/robustness.md)
- ``tenancy``   multi-tenant serving fabric: per-tenant queues +
                SLO/brownout, weighted-fair drain, token-bucket
                isolation, zero-downtime swap (docs/serving.md
                "Multi-tenant fabric")
- ``qcache``    exact-match bounded-LRU result cache for repeat
                traffic, generation-keyed invalidation

Submodules import lazily, so telemetry-only consumers (ops/guarded
demotion events, core/tracing span timing) pull in none of the
scheduler's jax-facing dependencies.
"""
from __future__ import annotations

import importlib
from typing import Any

_SUBMODULES = ("admission", "batcher", "debugz", "degrade", "metrics",
               "qcache", "quality", "slo", "tenancy", "warmup")
_EXPORTS = {
    "MicroBatcher": "batcher",
    "BucketLadder": "batcher",
    "AdmissionQueue": "admission",
    "Request": "admission",
    "SearchResult": "admission",
    "QueueFullError": "admission",
    "count_compilations": "warmup",
    "SnapshotWriter": "debugz",
    "RecallSentinel": "quality",
    "SLOEngine": "slo",
    "Targets": "slo",
    "BrownoutController": "degrade",
    "ServeFabric": "tenancy",
    "Tenant": "tenancy",
    "RateLimitedError": "tenancy",
    "QueryCache": "qcache",
}

__all__ = list(_SUBMODULES) + list(_EXPORTS)


def __getattr__(name: str) -> Any:
    if name in _SUBMODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in _EXPORTS:
        val = getattr(__getattr__(_EXPORTS[name]), name)
        globals()[name] = val
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
