"""Exact-match query-result cache for the multi-tenant serving fabric
(docs/serving.md "Multi-tenant fabric").

Repeat traffic is a first-class serving pattern — autocomplete,
trending queries, retry storms — and an ANN answer for a byte-identical
query is deterministic until the index changes. This module is the
smallest cache that exploits that safely:

* **Exact-match only**: the key is ``(tenant, blake2b(query_bytes), k,
  params_key)`` — no semantic similarity, no approximate reuse. A hit
  returns the *identical* host arrays a dispatch would have produced
  (for the same index generation), so cached traffic is
  indistinguishable from served traffic to the caller.
* **Bounded LRU**: ``capacity`` entries, least-recently-used eviction.
  Row blocks above ``max_rows`` are never cached (one 512-row block
  would evict hundreds of useful single-query entries) — those count
  under ``<name>.qcache.bypass``.
* **Generation-keyed invalidation**: the fabric folds the tenant's
  swap generation and (for a :class:`~raft_tpu.neighbors.mutable.MutableIndex`)
  the mutable-index generation into ``params_key``, so an entry written
  against an old generation can never hit after a swap or a background
  merge flip. :meth:`invalidate_tenant` additionally drops a tenant's
  entries eagerly (a swap must also free the memory, not only defeat
  the lookups).
* **Policed, not trusted**: the fabric offers sampled *hits* back to
  the tenant's :class:`~raft_tpu.serve.quality.RecallSentinel` under
  the ``qcache`` family, so a stale or corrupted entry surfaces as a
  recall regression (and a ``qcache_stale`` flight-recorder event via
  the sentinel's ``on_regression`` hook) instead of silently serving
  wrong neighbors forever.

Metrics (in the owning registry): ``<name>.qcache.hits`` / ``.misses``
/ ``.bypass`` / ``.invalidated`` / ``.evictions`` counters and a
``<name>.qcache.entries`` gauge.

Knobs: ``RAFT_TPU_QCACHE_CAP`` (default 4096 entries),
``RAFT_TPU_QCACHE_MAX_ROWS`` (default 16 rows per cached block).
"""
from __future__ import annotations

import collections
import hashlib
import threading
from typing import Optional, Tuple

import numpy as np

from ..utils import env_int

__all__ = ["QueryCache", "query_digest"]


def query_digest(queries) -> str:
    """Stable content digest of one query block (C-contiguous float32
    bytes — the fabric normalizes dtype/layout at submit, so equal
    queries always collide)."""
    q = np.ascontiguousarray(queries, np.float32)
    return hashlib.blake2b(q.tobytes(), digest_size=16).hexdigest()


class QueryCache:
    """Bounded exact-match LRU of served (distances, indices) blocks.

    Thread-safe: one lock over the ordered map (get/put/invalidate all
    run on the fabric worker or a submit thread). Stored arrays are
    host copies — a cached result must not pin device buffers nor alias
    a caller-mutable block.
    """

    def __init__(self, capacity: Optional[int] = None,
                 max_rows: Optional[int] = None,
                 registry=None, name: str = "fabric"):
        from . import metrics as _metrics

        self.capacity = (env_int("RAFT_TPU_QCACHE_CAP", 4096)
                         if capacity is None else int(capacity))
        if self.capacity <= 0:
            raise ValueError(
                f"qcache capacity must be positive, got {self.capacity}")
        self.max_rows = (env_int("RAFT_TPU_QCACHE_MAX_ROWS", 16)
                         if max_rows is None else int(max_rows))
        reg = registry or _metrics.default_registry
        self._name = name
        self._hits = reg.counter(f"{name}.qcache.hits")
        self._misses = reg.counter(f"{name}.qcache.misses")
        self._bypass = reg.counter(f"{name}.qcache.bypass")
        self._invalidated = reg.counter(f"{name}.qcache.invalidated")
        self._evictions = reg.counter(f"{name}.qcache.evictions")
        self._entries = reg.gauge(f"{name}.qcache.entries")
        self._lock = threading.Lock()
        self._map: "collections.OrderedDict[tuple, tuple]" = \
            collections.OrderedDict()

    # -- keying -----------------------------------------------------------
    def key(self, tenant: str, queries, k: int,
            params_key: str) -> Optional[tuple]:
        """Cache key for one request, or None when the request is not
        cacheable (too many rows — counted as a bypass at lookup)."""
        if queries.shape[0] > self.max_rows:
            return None
        return (str(tenant), query_digest(queries), int(k),
                str(params_key))

    # -- lookup / insert --------------------------------------------------
    def get(self, key: Optional[tuple]) -> Optional[Tuple[np.ndarray,
                                                          np.ndarray]]:
        """Hit returns ``(distances, indices)`` host arrays; miss (or a
        non-cacheable ``key=None``) returns None. Counts hit/miss/bypass."""
        if key is None:
            self._bypass.inc()
            return None
        with self._lock:
            hit = self._map.get(key)
            if hit is not None:
                self._map.move_to_end(key)
        if hit is None:
            self._misses.inc()
            return None
        self._hits.inc()
        # copies OUT as well as in: a caller post-processing a hit's
        # arrays in place must not poison every future hit
        return (hit[0].copy(), hit[1].copy())

    def bypass(self) -> None:
        """Count one deliberate non-lookup (caller opted out via
        ``submit(..., cache=False)``) — distinguishable from misses on a
        dashboard."""
        self._bypass.inc()

    def put(self, key: Optional[tuple], distances, indices) -> bool:
        """Insert one served answer (host copies); evicts LRU beyond
        capacity. ``key=None`` (non-cacheable) is a no-op."""
        if key is None:
            return False
        val = (np.array(distances, copy=True), np.array(indices, copy=True))
        evicted = 0
        with self._lock:
            self._map[key] = val
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)
                evicted += 1
            n = len(self._map)
        if evicted:
            self._evictions.inc(evicted)
        self._entries.set(n)
        return True

    # -- invalidation -----------------------------------------------------
    def invalidate_tenant(self, tenant: str) -> int:
        """Eagerly drop every entry of ``tenant`` (swap / merge flip —
        the generation baked into ``params_key`` already defeats lookups;
        this frees the memory too). Returns the count dropped."""
        tenant = str(tenant)
        with self._lock:
            dead = [k for k in self._map if k[0] == tenant]
            for k in dead:
                del self._map[k]
            n = len(self._map)
        if dead:
            self._invalidated.inc(len(dead))
        self._entries.set(n)
        return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._map.clear()
        self._entries.set(0)

    # -- introspection ----------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def hit_rate(self) -> Optional[float]:
        """Lifetime hit rate over (hits + misses); None before any
        lookup."""
        h, m = self._hits.value, self._misses.value
        return h / (h + m) if (h + m) > 0 else None

    def snapshot(self) -> dict:
        """JSON-safe view for the debugz ``tenants`` section."""
        with self._lock:
            n = len(self._map)
        hr = self.hit_rate()
        return {
            "entries": n,
            "capacity": self.capacity,
            "max_rows": self.max_rows,
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "bypass": int(self._bypass.value),
            "invalidated": int(self._invalidated.value),
            "evictions": int(self._evictions.value),
            "hit_rate": None if hr is None else round(hr, 4),
        }
