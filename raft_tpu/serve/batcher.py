"""Shape-bucketed micro-batching scheduler: the query-serving runtime's
core loop.

Every search entry point in this library is a bare function call — one
caller, one pre-shaped query batch. A serving stack has neither: many
concurrent callers, each with 1..few-hundred queries and its own k and
latency budget. The standard inference-server answer, TPU-idiomatic
form:

* **Coalesce**: requests drain from an :class:`~.admission.AdmissionQueue`
  under a max-wait / max-batch policy and are concatenated row-wise.
* **Bucket, don't recompile**: the concatenated block is padded up to a
  fixed :class:`BucketLadder` of (query-rows × k) shapes. XLA
  executables are cached by input shape, so after
  :meth:`MicroBatcher.warmup` has dispatched every ladder shape once,
  steady-state traffic of ANY mix of request sizes hits only cached
  executables — zero recompiles (asserted by the load test with
  :func:`~.warmup.count_compilations`). Padding rows are zeros and k is
  rounded up a bucket; both are sliced away at demux (top-k lists are
  sorted, so the first k of a k-bucket answer IS the exact k answer, and
  per-row results are independent of other rows in the batch).
* **Dispatch through the existing paths**: the batcher is generic over a
  ``search_fn(queries, k, res=None)`` closure — build one with the
  ``make_searcher`` helpers on brute_force / ivf_flat / ivf_pq / cagra
  or :func:`raft_tpu.parallel.sharded_ann.make_searcher` (whose
  ``allow_partial=True`` degraded merges surface ``shards_ok`` per
  response and in the metrics).
* **Deadlines end-to-end**: a request's
  :class:`~raft_tpu.core.deadline.Deadline` is enforced at admission pop
  and again pre-dispatch (shed, ``<name>.shed``); the tightest live
  deadline rides into the search as ``res``, so a mid-batch expiry
  raises between chunk dispatches and completed rows are still
  delivered — fully-covered requests succeed, the rest fail with their
  own partial slice attached (``<name>.deadline_exceeded``).

The worker is one daemon thread: TPU dispatch is asynchronous, so a
single submitting thread keeps the device pipelined while callers block
on per-request futures. Dispatch and demux are **double-buffered**
(ISSUE 12): while batch N's device→host transfer and per-request
slicing run on the host, batch N+1 is already dispatched and computing
— the demux wall overlaps device time instead of serializing with it.
Depth is exactly two, and an idle queue demuxes immediately, so the
overlap never delays delivery. Pair with
``make_searcher(..., donate=)`` closures so the in-flight pair does not
double the transient device-buffer footprint (docs/serving.md).

A popped batch splits per k bucket before dispatch (one k per
executable), so heavily mixed-k traffic trades fill ratio for
k-padding — watch ``<name>.batch_fill`` and give hot k values their own
bucket rather than widening an existing one.

**Request-lifecycle telemetry** (docs/observability.md): every request
carries a trace ID, and with ``trace_sample > 0`` (ctor arg or the
``RAFT_TPU_TRACE_SAMPLE`` env knob) sampled batches record a five-stage
latency decomposition per request — ``queue_wait`` (submit → worker
pop), ``bucket_pad`` (coalesce + zero-pad), ``dispatch`` (host-side
search-call wall), ``device`` (a ``block_until_ready`` probe — measured
only on sampled batches, so steady-state dispatch stays asynchronous),
``demux`` (device→host transfer + per-request slicing) — into
``<name>.stage.*_s`` histograms and the sampled span log
(:func:`raft_tpu.core.tracing.recent_spans`). The worker binds the
batch's trace IDs around dispatch, so demotions/faults/recompiles
firing mid-batch land in the flight recorder stamped with the requests
they hit. With sampling off the hot path pays one falsy check per
probe site.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core import events, faults, logging as rlog, tracing
from ..core.deadline import Deadline, DeadlineExceeded
from ..core.errors import expects
from . import warmup as _warmup
from .admission import AdmissionQueue, Request, SearchResult

__all__ = ["BucketLadder", "MicroBatcher", "coalesce_block"]

# the five per-request stages (docs/observability.md)
STAGES = ("queue_wait", "bucket_pad", "dispatch", "device", "demux")


def triage_partial(live: Sequence, offs: Sequence[int],
                   e: DeadlineExceeded):
    """Classify every request of a mid-batch deadline expiry (pure —
    callers credit their own counters/events): returns
    ``(served, expired, retry)`` where ``served`` is
    ``[(request, SearchResult)]`` for rows fully inside the delivered
    partial, ``expired`` is ``[(request, covered_rows, own_partial)]``
    for requests whose OWN deadline is spent (``own_partial`` their
    slice or None), and ``retry`` the collateral co-batched requests to
    re-dispatch. Shared by :class:`MicroBatcher` and the multi-tenant
    fabric so the slicing boundary math and the termination argument
    (every recursion drops the expired owners, so the retried group's
    tightest deadline is strictly looser) live in exactly one place."""
    from .admission import SearchResult

    if e.partial is not None:
        pd, pi = np.asarray(e.partial[0]), np.asarray(e.partial[1])
        done = pd.shape[0]
    else:
        pd = pi = None
        done = 0
    served, expired, retry = [], [], []
    for r, o in zip(live, offs):
        if o + r.rows <= done:
            served.append((r, SearchResult(pd[o:o + r.rows, :r.k],
                                           pi[o:o + r.rows, :r.k], None)))
            continue
        if r.deadline is None or not r.deadline.expired():
            retry.append(r)
            continue
        own = None
        if done > o:
            own = (pd[o:done, :r.k], pi[o:done, :r.k])
        expired.append((r, max(0, done - o), own))
    return served, expired, retry


def coalesce_block(live: Sequence, mb: int, dim: int):
    """Concatenate the live requests' query rows into one zero-padded
    (mb, dim) f32 block; returns ``(block, offsets)`` with each
    request's row offset. Shared by :class:`MicroBatcher` and the
    multi-tenant fabric (:mod:`raft_tpu.serve.tenancy`) so co-batched
    dispatch and demux slicing agree on one layout."""
    block = np.zeros((mb, dim), np.float32)
    offs: List[int] = []
    off = 0
    for r in live:
        block[off:off + r.rows] = r.queries
        offs.append(off)
        off += r.rows
    return block, offs


class BucketLadder:
    """The fixed set of dispatch shapes: ascending query-row buckets ×
    ascending k buckets. ``bucket_queries``/``bucket_k`` round a request
    up to the smallest covering bucket; anything beyond the largest
    bucket is a submit-time error (split such callers upstream)."""

    def __init__(self,
                 query_buckets: Sequence[int] = (8, 32, 128, 512),
                 k_buckets: Sequence[int] = (16, 64, 128)):
        self.query_buckets = tuple(int(b) for b in query_buckets)
        self.k_buckets = tuple(int(b) for b in k_buckets)
        for name, bs in (("query_buckets", self.query_buckets),
                         ("k_buckets", self.k_buckets)):
            expects(len(bs) > 0, "%s must be non-empty", name)
            expects(all(b > 0 for b in bs), "%s must be positive", name)
            expects(tuple(sorted(set(bs))) == bs,
                    "%s must be ascending and unique, got %s", name, bs)

    @property
    def max_queries(self) -> int:
        return self.query_buckets[-1]

    @property
    def max_k(self) -> int:
        return self.k_buckets[-1]

    def bucket_queries(self, m: int) -> int:
        expects(1 <= m <= self.max_queries,
                "request of %d query rows outside ladder (max bucket %d)",
                m, self.max_queries)
        return next(b for b in self.query_buckets if b >= m)

    def bucket_k(self, k: int) -> int:
        expects(1 <= k <= self.max_k,
                "k=%d outside ladder (max k bucket %d)", k, self.max_k)
        return next(b for b in self.k_buckets if b >= k)

    def shapes(self) -> List[Tuple[int, int]]:
        """Every (query_bucket, k_bucket) pair — the warmup set."""
        return [(mb, kb) for mb in self.query_buckets
                for kb in self.k_buckets]


class MicroBatcher:
    """Micro-batching front end over one built index's search closure.

    ``search_fn(queries, k, res=None) -> (distances, indices)`` (or a
    3-tuple ending in ``shards_ok`` for degraded sharded searchers) must
    accept any ladder shape; ``dim`` is the query width used for padding
    and warmup. ``autostart=False`` lets tests enqueue a deterministic
    backlog before the worker drains it. ``trace_sample`` is the
    request-telemetry sampling rate (None reads ``RAFT_TPU_TRACE_SAMPLE``,
    validated; 0 disables stage decomposition entirely — see module
    docstring). ``sentinel``: an optional
    :class:`~raft_tpu.serve.quality.RecallSentinel` — served requests
    are offered to it after delivery for online recall estimation
    (docs/observability.md "Quality"). ``degrade``: an optional
    :class:`~raft_tpu.serve.degrade.BrownoutController` — its current
    level scales the coalescing max-wait (pair it with
    ``make_searcher(..., degrade=...)`` so search params degrade too;
    docs/robustness.md).
    """

    def __init__(self, search_fn: Callable, dim: int, *,
                 ladder: Optional[BucketLadder] = None,
                 max_wait_s: float = 0.002,
                 max_batch_requests: int = 64,
                 queue_depth: int = 256,
                 registry=None,
                 name: str = "serve",
                 autostart: bool = True,
                 trace_sample: Optional[float] = None,
                 sentinel=None,
                 degrade=None,
                 clock: Callable[[], float] = time.monotonic):
        from . import metrics as _metrics

        self._search = search_fn
        self._dim = int(dim)
        self.ladder = ladder or BucketLadder()
        self._max_wait_s = float(max_wait_s)
        self._max_batch = int(max_batch_requests)
        self._name = name
        self._clock = clock
        self._reg = registry or _metrics.default_registry
        # optional quality probe (serve/quality.RecallSentinel): served
        # requests are offered AFTER delivery; its disabled cost is one
        # None check here plus one flag check inside offer()
        self._sentinel = sentinel
        # optional brownout controller (serve/degrade.py): under a
        # latency brownout the batcher widens its max-wait by the
        # level's scale — bigger batches, fewer dispatches
        self._degrade = degrade
        rate = tracing.sample_rate(trace_sample)
        # stage telemetry: None = off (the hot path checks exactly this);
        # every ceil(1/rate)-th batch gets the full five-stage story
        self._probe_every = math.ceil(1.0 / rate) if rate > 0 else 0
        self._probe_tick = 0
        self._stages = None
        if self._probe_every:
            self._stages = {s: self._reg.histogram(f"{name}.stage.{s}_s")
                            for s in STAGES}
        try:
            # always-on recompile stream: a post-warmup recompile must be
            # visible in any snapshot (serve.recompiles + xla_compile
            # events labeled with this batcher's dispatch buckets)
            _warmup.install_recompile_watch()
        except RuntimeError as e:
            rlog.log_warn("serve %s: recompile watch unavailable (%s)",
                          name, e)
        self.queue = AdmissionQueue(queue_depth, registry=self._reg,
                                    prefix=name, clock=clock)
        r = self._reg
        self._requests = r.counter(f"{name}.requests")
        self._served = r.counter(f"{name}.served")
        self._batches = r.counter(f"{name}.batches")
        self._errors = r.counter(f"{name}.errors")
        self._dlx = r.counter(f"{name}.deadline_exceeded")
        self._degraded = r.counter(f"{name}.degraded_batches")
        self._healthy = r.gauge(f"{name}.healthy_shards")
        self._latency = r.histogram(f"{name}.latency_s")
        self._batch_latency = r.histogram(f"{name}.batch_latency_s")
        self._fill = r.histogram(f"{name}.batch_fill",
                                 _metrics.RATIO_BUCKETS)
        self._padding = r.histogram(f"{name}.padding_waste",
                                    _metrics.RATIO_BUCKETS)
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"{self._name}-batcher", daemon=True)
        self._thread.start()

    def close(self, timeout: float = 10.0) -> None:
        """Stop admitting, drain what is queued, stop the worker."""
        self.queue.close()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def __enter__(self) -> "MicroBatcher":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- client API -------------------------------------------------------
    def submit(self, queries, k: int,
               deadline: Optional[Deadline] = None) -> Request:
        """Enqueue a request; returns its future. Raises
        :class:`~.admission.QueueFullError` under backpressure and
        ValueError-family errors for off-ladder shapes."""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        expects(q.ndim == 2 and q.shape[1] == self._dim,
                "queries must be (m, %d), got %s", self._dim, q.shape)
        self.ladder.bucket_queries(q.shape[0])   # validate against ladder
        self.ladder.bucket_k(k)
        req = Request(q, k, deadline, enqueued_at=self._clock())
        self.queue.submit(req)
        self._requests.inc()
        return req

    def search(self, queries, k: int, deadline: Optional[Deadline] = None,
               timeout: Optional[float] = None) -> SearchResult:
        """Synchronous convenience: submit + block for the result."""
        return self.submit(queries, k, deadline).result(timeout)

    def warmup(self) -> int:
        """Pre-compile every ladder shape through the live search path;
        returns the number of XLA compilations that took (0 on a warm
        process). See :func:`raft_tpu.serve.warmup.warmup`."""
        return _warmup.warmup(self._search, self.ladder, self._dim,
                              registry=self._reg, name=self._name)

    # -- worker -----------------------------------------------------------
    def _run(self) -> None:
        # double-buffered dispatch (docs/serving.md): `pending` is a
        # dispatched-but-not-demuxed group. Batch N+1 is DISPATCHED
        # before batch N is demuxed, so the device computes N+1 while
        # the host blocks on N's device→host transfer — the demux wall
        # no longer serializes with device time. Depth is exactly two:
        # one group on device, one being coalesced. When the queue is
        # idle the pending group is demuxed immediately (overlap must
        # never delay delivery behind the coalescing wait).
        pending = None
        while True:
            wait = self._max_wait_s
            if self._degrade is not None:
                try:
                    wait *= self._degrade.max_wait_scale()
                except Exception:  # noqa: BLE001 - a broken controller
                    pass           # must not stall the worker
            if pending is not None and len(self.queue) == 0:
                pending = self._safe_demux(pending)
            batch = self.queue.pop_batch(
                self._max_batch, 0.0 if pending is not None else wait,
                max_rows=self.ladder.max_queries)
            if not batch:
                if pending is not None:
                    pending = self._safe_demux(pending)
                    continue
                if self.queue.closed:
                    return
                continue
            # operator knob: simulate a stalled worker/device
            # (RAFT_TPU_FAULTS='slow_dispatch@<name>.batch=0.1')
            faults.sleep_if(f"{self._name}.batch")
            if self._stages is not None:
                now = self._clock()
                for r in batch:
                    r.dequeued_at = now
            groups: dict = {}
            for r in batch:
                groups.setdefault(self.ladder.bucket_k(r.k), []).append(r)
            for kb in sorted(groups):
                reqs = groups[kb]
                # a deadline-carrying group dispatches through the
                # blocking chunked host loop — deliver the finished
                # pending batch BEFORE entering it (the overlap contract
                # assumes dispatch returns asynchronously; post-warmup
                # zero-recompile steady state covers the compile case)
                if pending is not None and any(r.deadline is not None
                                               for r in reqs):
                    pending = self._safe_demux(pending)
                cur = None
                try:
                    cur = self._dispatch_phase(kb, reqs)
                except Exception as e:  # noqa: BLE001 - worker must survive
                    self._errors.inc()
                    rlog.log_warn(
                        "serve %s: batch dispatch failed (%s: %s)",
                        self._name, type(e).__name__, e)
                    try:
                        events.record(
                            "dispatch_error", f"{self._name}.batch",
                            trace_id=[r.trace_id for r in reqs],
                            error=f"{type(e).__name__}: {e}")
                    except Exception:  # noqa: BLE001 - a record failure
                        pass           # must not strand the futures
                    for r in reqs:
                        if not r.done():
                            r.set_exception(e)
                # demux N only AFTER N+1's dispatch is in flight
                if pending is not None:
                    pending = self._safe_demux(pending)
                pending = cur

    def _safe_demux(self, pend) -> None:
        """Demux a dispatched group; a demux failure (a poisoned device
        buffer surfacing at transfer) fails that group's futures, never
        the worker. Returns None (the cleared pending slot)."""
        try:
            self._demux_phase(pend)
        except Exception as e:  # noqa: BLE001 - worker must survive
            self._errors.inc()
            rlog.log_warn("serve %s: batch demux failed (%s: %s)",
                          self._name, type(e).__name__, e)
            for r in pend["live"]:
                if not r.done():
                    r.set_exception(e)
        return None

    def _tightest_deadline(self, reqs: List[Request]) -> Optional[Deadline]:
        carried = [r.deadline for r in reqs if r.deadline is not None]
        if not carried:
            return None
        return min(carried, key=lambda d: d.remaining())

    def _dispatch_group(self, kb: int, reqs: List[Request]) -> None:
        """Dispatch + demux in one step (the unpipelined path: partial
        re-dispatch after a mid-batch deadline expiry)."""
        pend = self._dispatch_phase(kb, reqs)
        if pend is not None:
            self._demux_phase(pend)

    def _dispatch_phase(self, kb: int, reqs: List[Request]):
        """Coalesce + pad + issue the (asynchronous) search dispatch.
        Returns the pending-demux state, or None when nothing was
        dispatched (all shed, or a deadline expired mid-dispatch and
        partials were delivered)."""
        # late shed: a deadline can expire between admission pop and here
        # (e.g. an earlier group's dispatch, or an armed slow worker)
        live = []
        for r in reqs:
            if r.deadline is not None and r.deadline.expired():
                self.queue.shed(r)
            else:
                live.append(r)
        if not live:
            return None
        # stage-telemetry probe decision: one falsy check when disabled;
        # when enabled, every _probe_every-th group tells the full story
        probe = False
        if self._stages is not None:
            self._probe_tick += 1
            probe = (self._probe_tick - 1) % self._probe_every == 0
        rows = sum(r.rows for r in live)
        mb = self.ladder.bucket_queries(rows)
        t_pad = self._clock() if probe else 0.0
        block, offs = coalesce_block(live, mb, self._dim)
        pad_dt = self._clock() - t_pad if probe else 0.0
        t0 = self._clock()
        try:
            # bind the batch's trace IDs + label the compile context:
            # a demotion, fault or recompile firing inside the search is
            # stamped with the requests (and shape bucket) it hit
            with tracing.bind_trace(*(r.trace_id for r in live)), \
                    _warmup.compile_context(f"{self._name}:{mb}x{kb}"):
                out = self._search(block, kb,
                                   res=self._tightest_deadline(live))
        except DeadlineExceeded as e:
            self._deliver_partial(kb, live, offs, e)
            return None
        dt = self._clock() - t0
        return {"kb": kb, "live": live, "offs": offs, "out": out,
                "probe": probe, "pad_dt": pad_dt, "dt": dt, "mb": mb,
                "rows": rows}

    def _demux_phase(self, pend) -> None:
        """Block on the dispatched group's results, slice them back to
        requests, deliver, and record the stage telemetry. Runs AFTER
        the next group's dispatch is in flight (the double buffer)."""
        kb, live, offs, out = (pend["kb"], pend["live"], pend["offs"],
                               pend["out"])
        probe, pad_dt, dt, mb, rows = (pend["probe"], pend["pad_dt"],
                                       pend["dt"], pend["mb"],
                                       pend["rows"])
        device_dt = 0.0
        if probe:
            # the off-hot-path device probe: dispatch is asynchronous, so
            # the search call above returns before the device finishes;
            # only sampled batches pay this sync (steady state never does)
            t_dev = self._clock()
            jax.block_until_ready(out)
            device_dt = self._clock() - t_dev
        shards_ok = None
        if isinstance(out, tuple) and len(out) == 3:
            d, i, shards_ok = out
        else:
            d, i = out
        t_dmx = self._clock() if probe else 0.0
        d = np.asarray(d)
        i = np.asarray(i)
        if shards_ok is not None:
            ok = np.asarray(shards_ok, bool)
            self._healthy.set(int(ok.sum()))
            if not ok.all():
                self._degraded.inc()
        results = [SearchResult(d[o:o + r.rows, :r.k],
                                i[o:o + r.rows, :r.k], shards_ok)
                   for r, o in zip(live, offs)]
        demux_dt = self._clock() - t_dmx if probe else 0.0
        now = self._clock()
        for r, res_r in zip(live, results):
            r.set_result(res_r)
            self._latency.observe(now - r.enqueued_at)
        if self._sentinel is not None:
            # recall sampling: AFTER delivery (results are already in
            # callers' hands) and guarded — the sentinel contract is
            # never-blocks, but a hostile replacement must not strand a
            # served batch either
            try:
                for r, res_r in zip(live, results):
                    self._sentinel.offer(
                        r.queries, r.k, res_r.distances, res_r.indices,
                        trace_id=r.trace_id)
            except Exception:  # noqa: BLE001 - telemetry must not break
                pass           # serving
        if probe:
            # AFTER delivery, and guarded: a failing observer (a
            # user-supplied registry) must not fail a batch whose
            # results were already computed, nor delay them behind
            # 5 histogram writes per co-batched request
            try:
                tel = self._stages
                bucket = f"{mb}x{kb}"
                for r in live:
                    stages = {"queue_wait": max(0.0, r.dequeued_at
                                                - r.enqueued_at),
                              "bucket_pad": pad_dt, "dispatch": dt,
                              "device": device_dt, "demux": demux_dt}
                    for s, v in stages.items():
                        tel[s].observe(v)
                    tracing.log_spans(r.trace_id, stages, rows=r.rows,
                                      k=r.k, bucket=bucket)
            except Exception:  # noqa: BLE001 - telemetry must not
                pass           # break serving
        self._served.inc(len(live))
        self._batches.inc()
        self._reg.counter(f"{self._name}.dispatch.{mb}x{kb}").inc()
        self._batch_latency.observe(dt)
        self._fill.observe(rows / mb)
        self._padding.observe((mb - rows) / mb)

    def _deliver_partial(self, kb: int, live: List[Request],
                         offs: List[int], e: DeadlineExceeded) -> None:
        """Mid-batch deadline expiry: the search delivered rows
        [0, done). Requests fully inside succeed; requests whose OWN
        deadline is spent fail with their slice of the partial attached
        (may be None); the rest were collateral of a co-batched tighter
        deadline and are re-dispatched — a request without a budget must
        never fail on someone else's. Terminates: every recursion drops
        the expired-deadline owners, so the retried group carries a
        strictly looser tightest deadline."""
        served, expired, retry = triage_partial(live, offs, e)
        now = self._clock()
        for r, res_r in served:
            r.set_result(res_r)
            self._latency.observe(now - r.enqueued_at)
            self._served.inc()
        for r, covered, own in expired:
            self._dlx.inc()
            try:
                events.record("deadline_exceeded", f"{self._name}.dispatch",
                              trace_id=r.trace_id, rows=r.rows,
                              covered_rows=covered)
            except Exception:  # noqa: BLE001 - telemetry must not strand
                pass           # the future
            r.set_exception(DeadlineExceeded(
                f"raft_tpu serve: deadline exceeded mid-batch; "
                f"{covered} of {r.rows} query rows completed "
                f"({'attached' if own is not None else 'empty'})",
                partial=own))
        if retry:
            self._reg.counter(f"{self._name}.redispatched").inc(len(retry))
            self._dispatch_group(kb, retry)
