"""Quality observability: the online recall sentinel and index-health
introspection (docs/observability.md "Quality").

PR 6 made *latency* legible; this module makes *recall* legible — the
axis the paper competes on, and the one every graceful degradation
(guarded demotion, degraded shard merge, quantized edge store, stale
autotune verdict) silently moves. The DiskANN/ScaNN serving literature
(PAPERS.md) is explicit that quantized/graph indexes under production
traffic need continuous quality monitoring; the ROADMAP's online
mutation tier is unshippable without it.

Two halves:

* :class:`RecallSentinel` — samples a fraction of served requests
  (``RAFT_TPU_RECALL_SAMPLE``, same ceil-cadence pattern as
  ``tracing.sample_rate``), re-executes them through an **exact
  brute-force reference** on a bounded background worker, scores them
  with :func:`raft_tpu.stats.metrics.neighborhood_recall`, and publishes
  rolling per-family/per-engine ``<name>.recall.<family>`` gauges into
  the metrics registry. A rolling estimate crossing the configured floor
  emits a trace-stamped ``recall_regression`` flight-recorder event.
  The contract mirrors the stage-telemetry probes: **disabled cost is
  one flag check**, the sentinel never blocks or re-orders the hot path
  (a saturated queue drops samples — counted — instead of applying
  backpressure), and the reference work is budgeted by the bounded
  queue.
* :func:`health` — a per-family index health report (CAGRA in-degree
  distribution + unreachable nodes + sampled quantization
  reconstruction error, IVF list-size skew, PQ codeword utilization,
  sharded per-shard row counts + ``shards_ok``), surfaced in the debugz
  snapshot for every index registered with :func:`watch_index`.
"""
from __future__ import annotations

import collections
import math
import threading
import weakref
from typing import Callable, Dict, Optional

import numpy as np

from ..core import events, tracing

__all__ = ["RecallSentinel", "make_reference", "health", "watch_index",
           "unwatch_index", "health_snapshot", "export_health_jsonl",
           "ops_snapshot", "device_bytes", "memz_snapshot"]

# live sentinels (weak, like sharded_ann._LIVE): debugz reports every
# sentinel the process is running without explicit plumbing
_SENTINELS: "weakref.WeakSet[RecallSentinel]" = weakref.WeakSet()

# name -> weakref to a watched index (the operator's opt-in health set)
_WATCHED: Dict[str, "weakref.ref"] = {}


class RecallSentinel:
    """Online recall estimation against an exact reference.

    ``reference_fn(queries, k) -> (distances, indices)`` must be the
    exact answer for the served corpus (build one with
    :func:`make_reference`, or pass any callable — the acceptance tests
    use plain numpy). ``sample``: sampling rate in [0, 1] (None reads
    ``RAFT_TPU_RECALL_SAMPLE``; 0 disables — no worker thread is ever
    started and :meth:`offer` is one flag check). ``floor``: rolling
    recall below this emits a ``recall_regression`` event (None reads
    ``RAFT_TPU_RECALL_FLOOR``; unset = never). ``window``: rolling
    sample count per family; ``min_samples`` gates the floor check (and
    the published estimate's trustworthiness). ``max_pending`` bounds
    the background queue — offers beyond it are DROPPED (counted under
    ``<name>.recall.dropped``), never queued unboundedly and never
    blocking dispatch.
    """

    def __init__(self, reference_fn: Callable, *,
                 sample: Optional[float] = None,
                 floor: Optional[float] = None,
                 window: int = 32, min_samples: int = 4,
                 max_pending: int = 8,
                 registry=None, name: str = "serve",
                 family: str = "default", engine: str = "-",
                 eps: float = 1e-4, autostart: bool = True):
        import os

        from . import metrics as _metrics

        self._ref = reference_fn
        rate = tracing.sample_rate(sample, env="RAFT_TPU_RECALL_SAMPLE",
                                   name="recall sample")
        # ceil-cadence (the tracing.sample_rate contract): every
        # ceil(1/rate)-th offer is sampled, so the configured rate is an
        # upper bound on reference work, never exceeded
        self._every = math.ceil(1.0 / rate) if rate > 0 else 0
        self._tick = 0
        if floor is None:
            raw = os.environ.get("RAFT_TPU_RECALL_FLOOR", "")
            floor = float(raw) if raw else None
        if floor is not None and not 0.0 <= float(floor) <= 1.0:
            raise ValueError(
                f"recall floor must be in [0, 1], got {floor!r}")
        self.floor = None if floor is None else float(floor)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.max_pending = int(max_pending)
        self._eps = float(eps)
        self._name = name
        self._family = family
        self._engine = engine
        self._reg = registry or _metrics.default_registry
        self._sampled = self._reg.counter(f"{name}.recall.sampled")
        self._dropped = self._reg.counter(f"{name}.recall.dropped")
        self._scored = self._reg.counter(f"{name}.recall.scored")
        self._errors = self._reg.counter(f"{name}.recall.errors")
        self._regressions = self._reg.counter(f"{name}.recall.regressions")
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: collections.deque = collections.deque()
        self._inflight = 0
        # per-family rolling windows + per-(family, engine) splits
        self._windows: Dict[str, collections.deque] = {}
        self._engine_windows: Dict[tuple, collections.deque] = {}
        # floor-crossing state per family: one event per crossing, not
        # one per sample below the floor; re-arms on recovery
        self._below: Dict[str, bool] = {}
        # optional floor-crossing hook, called (guarded) AFTER the
        # recall_regression event with (family, estimate, samples,
        # trace_id) — the multi-tenant fabric wires it to turn a
        # ``qcache``-family regression into a ``qcache_stale`` event +
        # eager cache invalidation (serve/tenancy.py); settable
        # post-construction (one consumer per sentinel, like the
        # tracing timer slot)
        self.on_regression: Optional[Callable] = None
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        _SENTINELS.add(self)
        if autostart and self._every:
            self.start()

    # -- lifecycle --------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return bool(self._every)

    def start(self) -> "RecallSentinel":
        if self._thread is None and self._every:
            with self._cond:
                self._stop = False
            self._thread = threading.Thread(
                target=self._run, name=f"{self._name}-recall-sentinel",
                daemon=True)
            self._thread.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def __enter__(self) -> "RecallSentinel":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- hot-path probe ---------------------------------------------------
    def offer(self, queries, k: int, distances, indices, *,
              family: Optional[str] = None, engine: Optional[str] = None,
              trace_id: Optional[str] = None) -> bool:
        """Offer one served request for recall sampling. Returns True
        when the request was enqueued for scoring.

        Never blocks and never raises into serving: disabled = one flag
        check; unsampled tick = one increment; a full queue drops the
        sample (counted). The tick race under concurrent callers is
        benign — cadence skews, the rate bound holds."""
        if not self._every:
            return False
        self._tick += 1
        if (self._tick - 1) % self._every:
            return False
        # GIL-atomic flag peek on the serving hot path; the locked
        # re-check below stays authoritative.
        # lint: waive(unlocked-attr): hot-path peek, locked re-check below
        if self._stop:
            return False
        # pre-copy check: when the queue is already saturated, the
        # dispatch thread must not pay the host copies just to drop
        # them (the locked re-check below stays authoritative — this
        # unlocked read only saves work, never admits past the bound)
        # lint: waive(unlocked-attr): hot-path peek, locked re-check below
        if len(self._pending) >= self.max_pending:
            self._dropped.inc()
            return False
        try:
            item = {
                # host copies: the sample must not pin device buffers
                # nor see later in-place mutation
                "queries": np.array(queries, np.float32, copy=True),
                "k": int(k),
                "distances": None if distances is None
                else np.asarray(distances).copy(),
                "indices": np.asarray(indices).copy(),
                "family": family or self._family,
                "engine": engine or self._engine,
                "trace_id": trace_id,
            }
        except Exception:  # noqa: BLE001 - a hostile payload must not
            self._errors.inc()   # break serving
            return False
        with self._cond:
            if self._stop:
                # stopped is not pressure: counting these as drops would
                # read as a saturated worker on the dashboard forever
                return False
            if len(self._pending) >= self.max_pending:
                self._dropped.inc()
                return False
            self._pending.append(item)
            self._cond.notify()
        self._sampled.inc()
        return True

    # -- background worker ------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait(0.1)
                if self._stop and not self._pending:
                    return
                item = self._pending.popleft()
                self._inflight += 1
            try:
                self._score(item)
            except Exception:  # noqa: BLE001 - a reference failure must
                self._errors.inc()  # not kill the sentinel
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _score(self, item: dict) -> None:
        from ..stats import metrics as stats_metrics

        rd, ri = self._ref(item["queries"], item["k"])
        rec = float(stats_metrics.neighborhood_recall(
            item["indices"], np.asarray(ri),
            item["distances"],
            None if item["distances"] is None else np.asarray(rd),
            eps=self._eps))
        self._scored.inc()
        fam, eng = item["family"], item["engine"]
        with self._lock:
            win = self._windows.setdefault(
                fam, collections.deque(maxlen=self.window))
            win.append(rec)
            ewin = self._engine_windows.setdefault(
                (fam, eng), collections.deque(maxlen=self.window))
            ewin.append(rec)
            est = sum(win) / len(win)
            n_samples = len(win)
            eest = sum(ewin) / len(ewin)
        self._reg.gauge(f"{self._name}.recall.{fam}").set(est)
        self._reg.gauge(f"{self._name}.recall.{fam}.samples").set(n_samples)
        self._reg.gauge(f"{self._name}.recall.{fam}.{eng}").set(eest)
        self._check_floor(fam, est, n_samples, item["trace_id"])

    def _check_floor(self, fam: str, est: float, n_samples: int,
                     trace_id) -> None:
        if self.floor is None or n_samples < self.min_samples:
            return
        below = est < self.floor
        if below and not self._below.get(fam):
            self._regressions.inc()
            try:
                # stamped with the sample that crossed the floor — the
                # post-mortem starts from a concrete degraded request
                events.record(
                    "recall_regression", f"{self._name}.recall.{fam}",
                    trace_id=trace_id, estimate=round(est, 4),
                    floor=self.floor, samples=n_samples)
            except Exception:  # noqa: BLE001 - telemetry must not kill
                pass           # the worker
            hook = self.on_regression
            if hook is not None:
                try:
                    hook(fam, est, n_samples, trace_id)
                except Exception:  # noqa: BLE001 - a hostile hook must
                    pass           # not kill the scoring worker
        self._below[fam] = below

    # -- introspection ----------------------------------------------------
    def estimate(self, family: Optional[str] = None) -> Optional[float]:
        """Rolling recall estimate for ``family`` (ctor default when
        None); None until a sample has been scored."""
        with self._lock:
            win = self._windows.get(family or self._family)
            return sum(win) / len(win) if win else None

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every enqueued sample has been scored (tests,
        bench lanes). Returns False on timeout or when disabled with
        work pending."""
        import time as _time

        end = _time.monotonic() + timeout
        with self._cond:
            while self._pending or self._inflight:
                left = end - _time.monotonic()
                if left <= 0 or (self._thread is None and not self._stop):
                    return not (self._pending or self._inflight)
                self._cond.wait(min(left, 0.1))
        return True

    def snapshot(self) -> dict:
        """JSON-safe view for the debugz ``quality`` section."""
        with self._lock:
            fams = {
                fam: {
                    "estimate": round(sum(w) / len(w), 4) if w else None,
                    "samples": len(w),
                    "below_floor": bool(self._below.get(fam, False)),
                    "engines": {
                        e: round(sum(ew) / len(ew), 4)
                        for (f, e), ew in self._engine_windows.items()
                        if f == fam and ew},
                } for fam, w in self._windows.items()}
            pending = len(self._pending)
        return {
            "name": self._name,
            "enabled": self.enabled,
            "sample_every": self._every,
            "floor": self.floor,
            "window": self.window,
            "families": fams,
            "pending": pending,
            "sampled": int(self._sampled.value),
            "scored": int(self._scored.value),
            "dropped": int(self._dropped.value),
            "errors": int(self._errors.value),
        }


def make_reference(dataset, metric="sqeuclidean") -> Callable:
    """Exact brute-force reference closure over ``dataset`` (f32) for
    :class:`RecallSentinel`: ``ref(queries, k) -> (distances, indices)``
    host arrays. The sentinel's sampled re-executions all dispatch the
    same shapes as serving, so steady state hits cached executables."""
    import jax.numpy as jnp

    from ..neighbors import brute_force

    idx = brute_force.build(jnp.asarray(dataset, jnp.float32),
                            metric=metric)

    def ref(queries, k):
        d, i = brute_force.search(idx, jnp.asarray(queries, jnp.float32), k)
        return np.asarray(d), np.asarray(i)

    return ref


# -- index health introspection --------------------------------------------
def health(index, sample: int = 256) -> dict:
    """Per-family index health report (dispatches on index type):
    structural quality signals an operator can read without re-running
    any search. ``sample`` bounds the sampled passes (quantization
    reconstruction error, PQ codeword utilization)."""
    # sharded families first: they carry shards_ok + a family tag
    if hasattr(index, "shards_ok") and hasattr(index, "family"):
        from ..parallel import sharded_ann

        return sharded_ann.health(index)
    from ..neighbors import brute_force, cagra, ivf_flat, ivf_pq, mutable

    if isinstance(index, mutable.MutableIndex):
        # the mutable tier: its own decomposition plus the sealed
        # segment's family report nested under "sealed"
        return mutable.health(index, sample=sample)
    for mod in (cagra, ivf_flat, ivf_pq, brute_force):
        if isinstance(index, mod.Index):
            return mod.health(index, sample=sample)
    raise TypeError(
        f"no health report for index type {type(index).__name__}")


def watch_index(name: str, index) -> None:
    """Register ``index`` under ``name`` for the debugz ``health``
    section (weakly: dropping the index drops the watch)."""
    _WATCHED[name] = weakref.ref(index)


def unwatch_index(name: str) -> None:
    _WATCHED.pop(name, None)


def health_snapshot(sample: int = 256) -> dict:
    """Health reports for every live watched index (debugz ``health``
    section). A failing report becomes an ``{"error": ...}`` entry —
    one bad index must not take down the ops surface."""
    out: dict = {}
    for name, ref in list(_WATCHED.items()):
        idx = ref()
        if idx is None:
            _WATCHED.pop(name, None)
            continue
        try:
            out[name] = health(idx, sample=sample)
        except Exception as e:  # noqa: BLE001
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _nbytes(a) -> int:
    """Size of a device/host array leaf; 0 for None/non-arrays."""
    try:
        return int(a.size) * int(a.dtype.itemsize)
    except AttributeError:
        return 0


def device_bytes(index) -> dict:
    """Per-component DEVICE byte decomposition of one index — the memz
    half of the ops surface (debugz ``memz``): where the ladder rung's
    capacity actually went. Components: ``dataset`` (the primary row
    store + norms/ids/scales), ``edge_store`` / ``pq_codes`` (the cagra
    traversal store, keyed by its rung), ``score_cache`` (cagra's
    candidate-dtype copies), ``fused_cache`` (brute_force's tile-aligned
    corpus), ``scan_cache`` (the IVF aligned-DMA copies), ``delta_tier``
    (a mutable index's un-merged tier; host-resident numpy, reported so
    the serving footprint is honest). ``bytes_per_vector`` divides the
    device total by ALL rows the index answers for — host-streamed cold
    rows included — so a rung's capacity claim is inspectable in prod;
    an attached host tier reports its own ``host_stream`` block."""
    from ..neighbors import brute_force, cagra, ivf_flat, ivf_pq, mutable

    comp: dict = {}
    n = 0
    family = type(index).__module__.rsplit(".", 1)[-1]
    if isinstance(index, mutable.MutableIndex):
        rep = {"family": "mutable"}
        if index._sealed is not None:
            rep["sealed"] = device_bytes(index._sealed)
        # the delta tier (brute-force fan-out rows + ids + alive bits)
        # lives in host numpy until merged; its cached device view is
        # the bucketed copy the fan-out searches
        delta = (_nbytes(index._d_vecs) + _nbytes(index._d_ids)
                 + _nbytes(index._d_alive))
        cache = index._delta_cache
        if cache is not None:
            delta += sum(_nbytes(leaf) for leaf in cache
                         if leaf is not None and hasattr(leaf, "dtype"))
        rep["components"] = {"delta_tier": delta}
        rep["total_device_bytes"] = (
            rep.get("sealed", {}).get("total_device_bytes", 0) + delta)
        rep["n"] = int(index.size) if hasattr(index, "size") else 0
        return rep
    if isinstance(index, cagra.Index):
        family = "cagra"
        n = int(index.size)
        comp["dataset"] = _nbytes(index.dataset) + _nbytes(index.graph)
        es = getattr(index, "_edge_store", None)
        if es is not None:
            store = sum(_nbytes(x) for x in es[1:4])
            if len(es) > 4 and es[4] is not None:
                store += sum(_nbytes(x) for x in es[4])
            comp["pq_codes" if es[0][0] == "pq" else "edge_store"] = store
        sc = (_nbytes(getattr(index, "_score_bf16", None))
              + sum(_nbytes(x) for x in
                    (getattr(index, "_score_i8", None) or ())))
        if sc:
            comp["score_cache"] = sc
    elif isinstance(index, brute_force.Index):
        family = "brute_force"
        n = int(index.size)
        comp["dataset"] = (_nbytes(index.dataset) + _nbytes(index.norms)
                           + _nbytes(index.scales))
        fp = getattr(index, "_fused_pad", None)
        if fp is not None:
            comp["fused_cache"] = sum(_nbytes(x) for x in fp[1:])
    elif isinstance(index, ivf_flat.Index):
        family = "ivf_flat"
        n = int(index.size)
        comp["dataset"] = (_nbytes(index.data) + _nbytes(index.data_norms)
                           + _nbytes(index.source_ids)
                           + _nbytes(index.scales))
        sp = getattr(index, "_scan_pad", None)
        if sp is not None:
            comp["scan_cache"] = sum(_nbytes(x) for x in sp[1:])
    elif isinstance(index, ivf_pq.Index):
        family = "ivf_pq"
        n = int(index.size)
        comp["pq_codes"] = _nbytes(index.codes)
        comp["dataset"] = (_nbytes(index.source_ids)
                           + _nbytes(index.centers_rot)
                           + _nbytes(index.codebooks)
                           + _nbytes(index.rotation))
        sc = getattr(index, "_scan_cache", None)
        if sc is not None:
            comp["scan_cache"] = sum(
                _nbytes(v) for v in sc.values() if hasattr(v, "dtype"))
    else:
        try:
            from ..parallel import sharded_ann as _sharded
        except Exception:  # noqa: BLE001 - parallel layer optional here
            _sharded = None
        if _sharded is not None and isinstance(
                index, (_sharded.ShardedIvfFlat, _sharded.ShardedIvfPq,
                        _sharded.ShardedCagra)):
            # fleet/sharded indexes: stacked (p, ...) arrays — the
            # totals cover the WHOLE fleet (parallel/fleet.py divides
            # host-major for the per-host tier-budget measurement)
            family = "sharded_" + index.family
            n = int(index.n_total)
            if isinstance(index, _sharded.ShardedIvfFlat):
                comp["dataset"] = (_nbytes(index.data)
                                   + _nbytes(index.data_norms)
                                   + _nbytes(index.source_ids)
                                   + _nbytes(index.scales))
                comp["quantizer"] = (_nbytes(index.centers)
                                     + _nbytes(index.center_norms)
                                     + _nbytes(index.offsets)
                                     + _nbytes(index.sizes))
            elif isinstance(index, _sharded.ShardedIvfPq):
                comp["pq_codes"] = _nbytes(index.codes)
                comp["dataset"] = (_nbytes(index.source_ids)
                                   + _nbytes(index.centers_rot)
                                   + _nbytes(index.codebooks)
                                   + _nbytes(index.rotations)
                                   + _nbytes(index.offsets)
                                   + _nbytes(index.sizes))
            else:
                comp["dataset"] = (_nbytes(index.data)
                                   + _nbytes(index.graphs))
        else:
            raise TypeError(
                f"no memz report for index type {type(index).__name__}")
    total = int(sum(comp.values()))
    rep = {"family": family, "n": n, "components": comp,
           "total_device_bytes": total}
    tier = getattr(index, "_host_tier", None)
    if tier is not None:
        rep["host_stream"] = tier.snapshot()
        n += int(tier.cold_rows)
        rep["n_total"] = n
    tiers = getattr(index, "_fleet_tiers", None)
    if tiers:
        # per-shard fleet tiers (this process's shards): one aggregated
        # host_stream block, same shape as the single-index tier's
        snaps = [t.snapshot() for _, t in sorted(tiers.items())]
        rep["host_stream"] = {
            key: int(sum(s[key] for s in snaps)) for key in snaps[0]}
        rep["host_stream"]["shards"] = len(snaps)
    rep["bytes_per_vector"] = round(total / n, 2) if n else None
    return rep


def memz_snapshot() -> dict:
    """Device-memory decomposition for every live watched index (debugz
    ``memz`` section; strict-JSON). A failing report becomes an
    ``{"error": ...}`` entry."""
    out: dict = {}
    for name, ref in list(_WATCHED.items()):
        idx = ref()
        if idx is None:
            _WATCHED.pop(name, None)
            continue
        try:
            out[name] = device_bytes(idx)
        except Exception as e:  # noqa: BLE001
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def export_health_jsonl(path: str, sample: int = 256) -> int:
    """Write one JSON line per watched index's health report; returns
    the report count (the JSONL half of the health surface, next to
    ``events.export_jsonl``)."""
    import json
    import time as _time

    snap = health_snapshot(sample=sample)
    with open(path, "w") as f:
        for name, report in sorted(snap.items()):
            f.write(json.dumps({"ts": _time.time(), "index": name,
                                **report}, sort_keys=True, default=repr)
                    + "\n")
    return len(snap)


def ops_snapshot() -> dict:
    """The quality ops surface read by serve/debugz.py: every live
    sentinel's rolling estimates plus the watched-index health set."""
    sentinels = []
    # WeakSet iteration can race a concurrent construction (the sharded
    # _LIVE precedent); retry rather than lose the section
    for _ in range(4):
        try:
            sentinels = [s.snapshot() for s in _SENTINELS]
            break
        except RuntimeError:
            continue
    return {"sentinels": sentinels, "health": health_snapshot()}
