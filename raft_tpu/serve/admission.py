"""Admission control for the query-serving runtime: a bounded request
queue with backpressure, coalescing pops, and deadline shedding.

The reference is consumed through a handle/stream-pool runtime that
multiplexes concurrent callers onto the device (SURVEY §1 layer 1); the
part of that runtime that decides *whether work gets in at all* is this
module. The contract:

* **Backpressure, not buffering**: :meth:`AdmissionQueue.submit` raises
  :class:`QueueFullError` once ``max_depth`` requests are waiting —
  callers (or their load balancer) must retry/deflect. An unbounded
  queue converts overload into unbounded latency; a bounded one converts
  it into an explicit, metered signal (``<prefix>.rejected``).
* **Shedding over zombie work**: a request whose
  :class:`~raft_tpu.core.deadline.Deadline` is already spent is never
  dispatched — it is completed exceptionally with
  :class:`~raft_tpu.core.deadline.DeadlineExceeded` (``partial=None``)
  at pop time and counted under ``<prefix>.shed``. Mid-dispatch expiry
  (partial results attached) is the batcher's half of the contract.
* **Coalescing pops**: :meth:`AdmissionQueue.pop_batch` blocks for the
  first admissible request, then keeps draining until a request-count /
  row-count cap is hit or ``max_wait_s`` has elapsed since the first pop
  — the micro-batching window.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, NamedTuple, Optional, Tuple

from ..core import events, tracing
from ..core.deadline import Deadline, DeadlineExceeded
from ..core.errors import RaftError

__all__ = ["QueueFullError", "SearchResult", "Request", "AdmissionQueue"]


class QueueFullError(RaftError):
    """Raised by ``submit`` when the admission queue is at ``max_depth``
    (backpressure: the caller must retry or deflect)."""


class SearchResult(NamedTuple):
    """One request's demultiplexed answer. ``shards_ok`` is the per-shard
    health vector when the backing searcher ran a degraded sharded merge
    (``allow_partial=True``), else None."""

    distances: object
    indices: object
    shards_ok: object = None


class Request:
    """One in-flight query request: the payload plus a one-shot future.

    ``queries`` is a host (m, d) float32 block; ``k`` the requested
    neighbor count; ``deadline`` an optional
    :class:`~raft_tpu.core.deadline.Deadline` enforced at admission pop,
    pre-dispatch and between search chunks. Every request carries a
    ``trace_id`` (generated when not supplied) that stage decompositions
    and flight-recorder events are stamped with; ``dequeued_at`` is
    stamped by the batcher worker when stage telemetry is enabled
    (queue-wait measurement).
    """

    __slots__ = ("queries", "k", "deadline", "enqueued_at", "trace_id",
                 "dequeued_at", "_event", "_result", "_error")

    def __init__(self, queries, k: int, deadline: Optional[Deadline] = None,
                 enqueued_at: float = 0.0, trace_id: Optional[str] = None):
        self.queries = queries
        self.k = int(k)
        self.deadline = deadline
        self.enqueued_at = enqueued_at
        self.trace_id = trace_id or tracing.new_trace_id()
        self.dequeued_at = 0.0
        self._event = threading.Event()
        self._result: Optional[SearchResult] = None
        self._error: Optional[BaseException] = None

    @property
    def rows(self) -> int:
        return self.queries.shape[0]

    def done(self) -> bool:
        return self._event.is_set()

    def set_result(self, result: SearchResult) -> None:
        self._result = result
        self._event.set()

    def set_exception(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def result(self, timeout: Optional[float] = None) -> SearchResult:
        """Block for completion; re-raises the stored exception (e.g.
        DeadlineExceeded with this request's partial slice attached)."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request not completed within {timeout}s (batcher not "
                "started, or the worker died)")
        if self._error is not None:
            raise self._error
        return self._result


class AdmissionQueue:
    """Bounded FIFO of :class:`Request` with coalescing pops and deadline
    shedding. Metrics (``<prefix>.queue_depth`` / ``.queue_depth_peak``
    gauges, ``.shed`` / ``.rejected`` counters) land in ``registry``
    (default process registry when None)."""

    # pop_batch wakes at least this often so close() is always responsive
    _WAIT_SLICE_S = 0.05

    def __init__(self, max_depth: int = 256, registry=None,
                 prefix: str = "serve",
                 clock: Callable[[], float] = time.monotonic):
        from . import metrics as _metrics

        reg = registry or _metrics.default_registry
        self.max_depth = int(max_depth)
        self._prefix = prefix
        self._clock = clock
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._depth = reg.gauge(f"{prefix}.queue_depth")
        self._depth_peak = reg.gauge(f"{prefix}.queue_depth_peak")
        self._shed_n = reg.counter(f"{prefix}.shed")
        self._rejected = reg.counter(f"{prefix}.rejected")

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def submit(self, req: Request) -> None:
        """Enqueue or raise :class:`QueueFullError` (backpressure)."""
        with self._cond:
            if self._closed:
                raise RaftError("admission queue is closed")
            if len(self._items) >= self.max_depth:
                self._rejected.inc()
                raise QueueFullError(
                    f"admission queue full ({self.max_depth} requests "
                    "waiting); retry after backoff")
            self._items.append(req)
            self._depth.set(len(self._items))
            self._depth_peak.set_max(len(self._items))
            self._cond.notify()

    def shed(self, req: Request) -> None:
        """Complete ``req`` exceptionally as shed (deadline spent before
        its dispatch) and count it. The shed lands in the flight recorder
        stamped with the request's trace ID — a shed request produced no
        work, so the recorder is its only footprint."""
        self._shed_n.inc()
        spent = req.deadline.seconds if req.deadline is not None else 0.0
        try:
            events.record("deadline_shed", f"{self._prefix}.shed",
                          trace_id=req.trace_id, budget_s=spent,
                          rows=req.rows, k=req.k)
        except Exception:  # noqa: BLE001 - telemetry must not strand
            pass           # the future
        req.set_exception(DeadlineExceeded(
            f"raft_tpu serve: request shed (deadline of {spent:.4g}s "
            "spent before dispatch); partial results empty", partial=None))

    def _drain_locked(self, batch: List[Request], rows: int,
                      max_requests: int,
                      max_rows: Optional[int]) -> Tuple[int, bool]:
        """Caller holds the lock: pop admissible requests into
        ``batch`` (shedding expired ones) until the request/row caps;
        the first request always pops regardless of ``max_rows``.
        Returns ``(rows, rows_full)`` — ONE admissibility loop shared
        by the blocking coalescing pop and the fabric's non-blocking
        drain, so shed semantics and the row-cap boundary can never
        diverge between them."""
        rows_full = False
        while self._items and len(batch) < max_requests:
            nxt = self._items[0]
            if nxt.deadline is not None and nxt.deadline.expired():
                self._items.popleft()
                self.shed(nxt)
                continue
            if (max_rows is not None and batch
                    and rows + nxt.rows > max_rows):
                rows_full = True
                break
            self._items.popleft()
            batch.append(nxt)
            rows += nxt.rows
        self._depth.set(len(self._items))
        return rows, rows_full

    def pop_batch(self, max_requests: int, max_wait_s: float,
                  max_rows: Optional[int] = None) -> List[Request]:
        """Blocking coalescing pop (see module docstring). Returns [] only
        once the queue is closed and drained; expired requests are shed
        here and never returned."""
        batch: List[Request] = []
        rows = 0
        window_end = None     # clock() bound set by the first pop
        with self._cond:
            while True:
                rows, rows_full = self._drain_locked(
                    batch, rows, max_requests, max_rows)
                if batch and window_end is None:
                    window_end = self._clock() + max_wait_s
                if batch and (self._closed or rows_full
                              or len(batch) >= max_requests
                              or self._clock() >= window_end):
                    return batch
                if self._closed and not self._items:
                    return batch
                remaining = (self._WAIT_SLICE_S if window_end is None
                             else max(0.0, window_end - self._clock()))
                self._cond.wait(min(remaining, self._WAIT_SLICE_S))

    def pop_nowait(self, max_requests: int,
                   max_rows: Optional[int] = None) -> List[Request]:
        """Non-blocking drain: whatever is admissible right now, up to
        the request/row caps, shedding expired requests on the way —
        the multi-tenant fabric's weighted-round-robin primitive
        (:mod:`raft_tpu.serve.tenancy` visits many queues per round and
        must never park on an empty one)."""
        batch: List[Request] = []
        with self._cond:
            self._drain_locked(batch, 0, max_requests, max_rows)
        return batch

    def close(self) -> None:
        """Stop admitting; pop_batch drains what is queued, then returns
        empty batches."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
