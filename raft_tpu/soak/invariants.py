"""Continuous invariants for the soak harness.

Violations are the soak's currency: the verdict is PASS exactly when
this suite's list is empty at the end of the run. Every check runs
every tick (not just at quiesce), so a transient bug — a stale cache
hit that later self-corrects, a briefly-stranded future, a recompile
burst that settles — is caught in the act instead of washed out by the
final state.

The suite owns every check that needs *cross-tick* state:

* breaker event legality per site (open → probe → close|re-open, from
  the drained event stream);
* brownout ladder legality (one step at a time, continuous levels);
* the zero-steady-state-recompile watch (``serve.recompiles`` deltas,
  with a short grace window after merge flips / swaps / recoveries,
  whose *first* post-change dispatch may legitimately compile a fresh
  tombstone-filter executable);
* acked-write durability (exact ``index.size == oracle`` row-count
  equality plus sampled id-visibility probes);
* strict-JSON debugz snapshots (``json.dumps(..., allow_nan=False)``).

Point-in-time checks (recall vs oracle, stranded futures, cold-tenant
p99 bounds) come in through :meth:`expect` with the harness holding
the context.
"""
from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Violation", "InvariantSuite"]

# events that legitimately change a tenant's executable set: the next
# dispatch or two may compile (new sealed row-count, first tombstone
# filter at the new shape) without that being a steady-state recompile
_RECOMPILE_EXEMPT_KINDS = ("merge_committed", "tenant_swap",
                           "wal_recovered")


@dataclasses.dataclass
class Violation:
    t_s: float
    name: str
    detail: dict

    def to_dict(self) -> dict:
        return {"t_s": round(self.t_s, 3), "name": self.name,
                "detail": self.detail}


class InvariantSuite:
    def __init__(self, *, recall_floor: float = 0.75,
                 cold_p99_s: float = 0.25, recompile_grace_ticks: int = 2,
                 registry=None):
        from ..serve import metrics as _metrics

        self.recall_floor = float(recall_floor)
        self.cold_p99_s = float(cold_p99_s)
        self.violations: List[Violation] = []
        self._reg = registry or _metrics.default_registry
        self._breaker: Dict[str, str] = {}          # site -> state
        self._brown: Dict[str, int] = {}            # name -> level
        self._last_recompiles: Optional[float] = None
        self._grace = 0
        self._grace_ticks = int(recompile_grace_ticks)

    # -- plumbing ---------------------------------------------------------
    def fail(self, t: float, name: str, **detail) -> None:
        self.violations.append(Violation(float(t), name, detail))

    def expect(self, cond: bool, t: float, name: str, **detail) -> bool:
        if not cond:
            self.fail(t, name, **detail)
        return bool(cond)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_list(self) -> List[dict]:
        return [v.to_dict() for v in self.violations]

    # -- event-stream legality (cross-tick state machines) ----------------
    def on_events(self, t: float, evts: List[dict]) -> None:
        """Consume this tick's drained events: breaker and brownout
        transition legality, and recompile-grace bookkeeping."""
        for e in evts:
            kind, site = e.get("kind"), e.get("site", "")
            if kind in _RECOMPILE_EXEMPT_KINDS:
                self._grace = self._grace_ticks + 1
            if kind == "dispatch_error":
                # no chaos stage in the soak legitimately errors a
                # dispatch (kernel faults fall back, crashes recover):
                # a request-visible error is always a violation
                self.fail(t, "dispatch_error", site=site,
                          error=e.get("error"))
            elif kind == "breaker_open":
                # legal from any state: first demotion opens, a failed
                # probe re-opens with doubled backoff
                self._breaker[site] = "open"
            elif kind == "breaker_probe":
                self.expect(self._breaker.get(site) == "open", t,
                            "breaker_probe_without_open", site=site,
                            state=self._breaker.get(site, "closed"))
                self._breaker[site] = "probing"
            elif kind == "breaker_close":
                self.expect(self._breaker.get(site) == "probing", t,
                            "breaker_close_without_probe", site=site,
                            state=self._breaker.get(site, "closed"))
                self._breaker[site] = "closed"
            elif kind == "brownout":
                lv_from = int(e.get("level_from", -1))
                lv_to = int(e.get("level_to", -1))
                last = self._brown.get(site, 0)
                self.expect(lv_from == last, t, "brownout_discontinuity",
                            site=site, expected_from=last, got=lv_from)
                self.expect(abs(lv_to - lv_from) == 1 and lv_to >= 0, t,
                            "brownout_step_illegal", site=site,
                            level_from=lv_from, level_to=lv_to)
                self._brown[site] = lv_to

    # -- steady-state recompiles ------------------------------------------
    def on_tick_end(self, t: float, *, steady: bool) -> None:
        """Close out one tick: diff ``serve.recompiles``. A positive
        delta is a violation only in a steady phase outside the
        post-flip grace window — chaos and recovery ticks may compile
        (new generations, recovered indexes), steady traffic must
        not."""
        cur = self._reg.counter("serve.recompiles").value
        prev, self._last_recompiles = self._last_recompiles, cur
        in_grace = self._grace > 0
        if self._grace > 0:
            self._grace -= 1
        if prev is None:
            return
        delta = cur - prev
        if delta > 0 and steady and not in_grace:
            self.fail(t, "steady_state_recompile", count=delta)

    # -- durability -------------------------------------------------------
    def check_durability(self, t: float, tenant: str, index,
                         oracle, sample_ids=(), *, k: int = 8,
                         pad_rows: int = 8) -> None:
        """Exact live-row-count equality plus sampled acked-id
        visibility: the stored vector's nearest neighbor must be the id
        itself (exact tenants). The probe batch is padded to the served
        dispatch shape ``(pad_rows, k)`` so it reuses the executable the
        fabric already compiled — a durability check must not perturb
        the zero-steady-state-recompile invariant it runs beside."""
        self.expect(index.size == oracle.size, t, "durability_row_count",
                    tenant=tenant, index_rows=int(index.size),
                    oracle_rows=int(oracle.size))
        if not sample_ids:
            return
        ids = [int(i) for i in sample_ids]
        block = np.stack([oracle.vector(i) for i in ids])
        reps = -(-pad_rows // len(ids))
        block = np.tile(block, (reps, 1))[:pad_rows]
        _, got = index.search(block, min(k, index.size))
        got = np.asarray(got)
        for j, row_id in enumerate(ids):
            top1 = int(got[j, 0])
            self.expect(top1 == row_id, t, "acked_write_invisible",
                        tenant=tenant, row_id=row_id, got=top1)

    # -- recall -----------------------------------------------------------
    def check_recall(self, t: float, tenant: str, queries, got_ids,
                     k: int, oracle) -> float:
        r = oracle.recall_of(queries, np.asarray(got_ids), k)
        self.expect(r >= self.recall_floor, t, "recall_below_floor",
                    tenant=tenant, recall=round(float(r), 4),
                    floor=self.recall_floor)
        return r

    # -- debugz strict JSON -----------------------------------------------
    def check_json_snapshot(self, t: float, snap: dict) -> None:
        try:
            json.dumps(snap, allow_nan=False)
        except (TypeError, ValueError) as exc:
            self.fail(t, "debugz_snapshot_not_strict_json",
                      error=repr(exc))

    # -- latency isolation ------------------------------------------------
    def check_cold_p99(self, t: float, tenant: str, registry) -> None:
        h = registry.histogram(f"{tenant}.latency_s")
        if h.count == 0:
            return
        p99 = h.percentile(99)
        self.expect(math.isfinite(p99) and p99 <= self.cold_p99_s, t,
                    "cold_tenant_p99_unbounded", tenant=tenant,
                    p99_s=round(float(p99), 4), bound_s=self.cold_p99_s)
