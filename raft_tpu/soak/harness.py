"""The soak harness: a time-compressed production day on one clock.

:class:`SoakHarness` wires everything the tree ships into one seeded,
deterministic run:

* a :class:`~raft_tpu.serve.tenancy.ServeFabric` (``autostart=False``
  — the harness drives ``drain_once`` itself, so scheduling is a pure
  function of the seed) serving three mutable-tier tenants, each an
  exact ``brute_force``-family :class:`MutableIndex` shadowed by a
  numpy :class:`~raft_tpu.soak.workload.ShadowCorpus` oracle;
* a :class:`~raft_tpu.serve.debugz.SnapshotWriter` used hook-first
  (its thread never starts): per-index ``maintenance`` wrappers,
  ``sharded_ann.probe_all``, and the fabric's own tick (SLO poll,
  brownout, swap retires) all run from ``writer.tick()`` every
  simulated second;
* a :class:`~raft_tpu.soak.chaos.ChaosPlan` arming kernel faults, WAL
  torn tails, merge crash points, io errors, shard deaths, overload
  bursts and a live swap against the same
  :class:`~raft_tpu.soak.workload.SimClock` every other component
  reads — a 30 s breaker probation, a 600 s backoff cap and a chaos
  window all compress into however fast the loop can tick;
* an :class:`~raft_tpu.soak.invariants.InvariantSuite` checked every
  tick, not at the end.

An :class:`~raft_tpu.core.faults.InjectedCrash` anywhere in the tick
(a WAL append, a merge crash point) is handled the only honest way: the
in-memory index object is discarded, ``mutable.recover`` replays the
WAL chain from disk, and the recovered index is swapped into the
serving tenant under live traffic — the durability invariant then
states that exactly the acked writes survived.

The run's verdict is a strict-JSON artifact: phase timeline, the chaos
plan as armed, per-fault-kind MTTR (simulated seconds), the violation
list (empty = PASS), and per-tenant serving totals. Every field is a
pure function of the seed — the determinism test diffs two same-seed
artifacts byte-for-byte.
"""
from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import events, faults
from ..neighbors import mutable as mutable_mod
from ..ops import guarded
from ..parallel import sharded_ann
from ..serve import debugz
from ..serve import degrade as degrade_mod
from ..serve import metrics
from ..serve import slo as slo_mod
from ..serve import warmup as warmup_mod
from ..serve.batcher import BucketLadder
from ..serve.qcache import QueryCache
from ..serve.tenancy import RateLimitedError, ServeFabric
from .chaos import ChaosPlan, standard_plan
from .invariants import InvariantSuite
from .workload import ShadowCorpus, SimClock, TenantLoad, WorkloadGen

__all__ = ["SoakConfig", "SoakHarness", "run_soak"]

ARTIFACT_SCHEMA = "soak/v1"

# the hot tenant's guarded serving site: primary and fallback are the
# same exact search, so kernel_fault drills the breaker arc (and its
# heal.mttr verdict) with zero recall impact; registered in
# ops/guarded.POLICIES like every other guarded site
SERVE_SITE = "soak.serve"


@dataclasses.dataclass
class SoakConfig:
    """Knobs for one soak run. Defaults are the tier-1 smoke scale; the
    full drill stretches ``duration_s`` (see ``RAFT_TPU_SOAK_SECONDS``
    in tests/test_soak.py and scratch/run_soak.py)."""

    seed: int = 0
    duration_s: float = 120.0      # simulated seconds
    dt: float = 1.0                # simulated seconds per tick
    dim: int = 16
    k: int = 8
    initial_rows: int = 256
    merge_rows: int = 40           # mutable delta threshold → frequent merges
    service_dt: float = 0.01       # sim-clock cost of one drain round
    chaos_t0: float = 30.0
    chaos_window: float = 30.0
    overload_extra: int = 60       # extra hot requests/tick during burst
    crash_restart_s: float = 2.0   # simulated process-restart cost
    recall_floor: float = 0.75
    cold_p99_s: float = 0.25
    hot_p99_target_s: float = 0.2
    sample_every: int = 10         # timeline sample cadence (ticks)
    durability_every: int = 5      # sampled id-visibility cadence (ticks)
    recall_samples: int = 2        # served batches recall-checked per tick

    @classmethod
    def smoke(cls, seed: int = 0) -> "SoakConfig":
        """Tier-1 scale: every chaos stage and every MTTR arc still
        land (the plan's probe/backoff arithmetic needs ~56 sim-s after
        chaos onset), compressed to a few wall-seconds on CPU."""
        return cls(seed=seed, duration_s=72.0, chaos_t0=16.0,
                   chaos_window=20.0)

    def phases(self) -> List[Tuple[str, float, float]]:
        t0, w, dur = self.chaos_t0, self.chaos_window, self.duration_s
        warm_end = min(10.0, t0 / 2.0)
        rec_end = min(dur - 4.0, t0 + w + 40.0)
        return [("warmup", 0.0, warm_end),
                ("steady", warm_end, t0),
                ("chaos", t0, t0 + w),
                ("recovery", t0 + w, rec_end),
                ("steady2", rec_end, dur - 2.0),
                ("quiesce", dur - 2.0, dur)]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# phases in which the zero-recompile and cold-p99 invariants are armed:
# chaos and recovery ticks may legitimately compile (crash recovery,
# merge probes); steady traffic must not
_STEADY_PHASES = ("steady", "steady2", "quiesce")


class SoakHarness:
    """One composed soak run. Build, call :meth:`run`, read the
    artifact. Construction wires but does not serve; ``run`` owns the
    tick loop and restores every patched global on exit."""

    def __init__(self, config: SoakConfig, workdir: str,
                 plan: Optional[ChaosPlan] = None):
        self.cfg = config
        self.workdir = pathlib.Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.clock = SimClock()
        self.plan = plan if plan is not None else standard_plan(
            self.clock, t0=config.chaos_t0, window=config.chaos_window,
            guard_site=SERVE_SITE, burst=config.overload_extra)
        self.suite = InvariantSuite(recall_floor=config.recall_floor,
                                    cold_p99_s=config.cold_p99_s)
        self.workload = WorkloadGen(
            config.seed, config.dim,
            [TenantLoad("hot", rows_per_request=4, requests_per_tick=6.0,
                        upserts_per_tick=4, deletes_per_tick=1),
             TenantLoad("mut", rows_per_request=4, requests_per_tick=3.0,
                        upserts_per_tick=6, deletes_per_tick=2),
             TenantLoad("cold", rows_per_request=4, requests_per_tick=2.0,
                        query_pool=8)],
            k=config.k)
        self._indexes: Dict[str, mutable_mod.MutableIndex] = {}
        self._oracles: Dict[str, ShadowCorpus] = {}
        self._paths: Dict[str, pathlib.Path] = {}
        self._maint_tenant: Optional[str] = None
        self._mttr: Dict[str, List[float]] = {}
        self._overload: Dict[str, Optional[float]] = {
            "first": None, "last": None}
        self._swap_count = 0
        self.fabric: Optional[ServeFabric] = None
        self.writer: Optional[debugz.SnapshotWriter] = None
        self.sharded = None
        self._cursor = 0
        self._hist_base: Dict[str, Tuple[int, float]] = {}

    # -- construction -----------------------------------------------------
    def _make_index(self, name: str, ids, vecs) -> mutable_mod.MutableIndex:
        path = self.workdir / f"{name}_g{self._swap_count}"
        idx = mutable_mod.create(str(path), dataset=vecs, ids=ids,
                                 family="brute_force")
        idx._clock = self.clock
        idx.merge_rows = self.cfg.merge_rows
        self._paths[name] = path
        self._indexes[name] = idx
        return idx

    def _hot_search_fn(self):
        def soak_hot_search(queries, k, res=None):
            idx = self._indexes["hot"]
            return guarded.guarded_call(
                "soak.serve",
                lambda: idx.search(queries, k),
                lambda: idx.search(queries, k))
        return soak_hot_search

    def _maintenance_hook(self, name: str):
        def hook():
            self._maint_tenant = name
            self._indexes[name].maintenance()
        hook.__name__ = hook.__qualname__ = f"soak_maintenance_{name}"
        return hook

    def _make_sharded_target(self):
        """A handmade two-shard CAGRA as the shard-death chaos target:
        probe_all (already on the writer's hook slot) detects the armed
        ``shard_dead`` and later restores the shard, driving the
        ``shard.mttr`` histogram."""
        import jax
        from jax.sharding import Mesh

        from ..distance.distance_types import DistanceType

        devs = jax.devices()
        mesh = Mesh(np.array((devs * 2)[:2]), ("shard",))
        rng = np.random.default_rng(self.cfg.seed + 1)
        data = rng.standard_normal((2, 8, 4)).astype(np.float32)
        graphs = rng.integers(0, 8, (2, 8, 2)).astype(np.int32)
        return sharded_ann.ShardedCagra(
            mesh, data, graphs, np.array([0, 5]), np.array([5, 3]),
            n_total=8, metric=DistanceType.L2Expanded)

    def _shard_watch_hook(self):
        """The serving path's shard-death detection on the maintenance
        cadence: a shard with an armed ``shard_dead``/``shard_timeout``
        is marked failed (consuming the firing, exactly like a sharded
        search's ``_shard_health`` would); ``probe_all`` later restores
        it once the fault clears, closing the ``shard.mttr`` arc."""
        def soak_shard_watch():
            idx = self.sharded
            ok = np.asarray(idx.shards_ok, bool)
            for i in range(len(ok)):
                site = f"sharded_ann.{idx.family}.shard{i}"
                if ok[i] and (
                        faults.fired("shard_dead", site) is not None
                        or faults.fired("shard_timeout", site)
                        is not None):
                    idx.mark_shard_failed(i)
        return soak_shard_watch

    def _build(self) -> None:
        cfg = self.cfg
        ladder = BucketLadder((8,), (cfg.k,))
        cache = QueryCache(capacity=256, max_rows=16)
        self.fabric = ServeFabric(cfg.dim, ladder=ladder, name="soak",
                                  cache=cache, clock=self.clock,
                                  autostart=False)
        for spec in self.workload.tenants:
            name = spec.name
            ids, vecs = self.workload.initial_corpus(name, cfg.initial_rows)
            idx = self._make_index(name, ids, vecs)
            oracle = ShadowCorpus(cfg.dim)
            oracle.apply_upsert(ids, vecs)
            self._oracles[name] = oracle
            reg = metrics.Registry()
            # the hot tenant also watches its shed rate: the overload
            # burst drives it past the target, the SLO breach steps the
            # brownout ladder, and recovery steps it back — the full
            # degrade arc the invariant suite checks for legality
            targets = slo_mod.Targets(
                p99_latency_s=cfg.hot_p99_target_s,
                max_shed_rate=0.3 if name == "hot" else None)
            eng = slo_mod.SLOEngine(
                targets, registry=reg, name=name, fast_window_s=5.0,
                slow_window_s=15.0, clock=self.clock)
            ctl = degrade_mod.BrownoutController(
                [{"max_wait_scale": 2.0}], slo=eng, min_dwell_s=3.0,
                up_after_s=10.0, registry=reg, name=name, clock=self.clock)
            kwargs: dict = {"registry": reg, "slo": eng, "brownout": ctl}
            if name == "hot":
                kwargs.update(search_fn=self._hot_search_fn(),
                              rate=12.0, burst=16.0, warm=True)
            elif name == "cold":
                kwargs.update(warm=True)
            self.fabric.add_tenant(name, index=idx, **kwargs)
        self.sharded = self._make_sharded_target()
        hooks = [self._maintenance_hook(n) for n in self._indexes]
        hooks.append(self._shard_watch_hook())
        hooks.append(sharded_ann.probe_all)
        self.writer = debugz.SnapshotWriter(
            str(self.workdir / "debugz.json"), hooks=hooks,
            fabric=self.fabric)

    # -- crash handling ---------------------------------------------------
    def _recover(self, name: str, kind: str) -> None:
        """Simulated process restart for one tenant: pay the restart
        cost on the sim clock, replay the WAL chain from disk, swap the
        recovered index into the live tenant."""
        t_down = self.clock.now
        self.clock.advance(self.cfg.crash_restart_s)
        idx = mutable_mod.recover(str(self._paths[name]))
        idx._clock = self.clock
        idx.merge_rows = self.cfg.merge_rows
        self._indexes[name] = idx
        tenant = self.fabric.tenant(name)
        if name == "hot":
            tenant.swap(new_index=idx, search_fn=self._hot_search_fn(),
                        warm=True)
        else:
            tenant.swap(new_index=idx, warm=True)
        self._mttr.setdefault(kind, []).append(
            self.clock.now - t_down)

    # -- chaos actions ----------------------------------------------------
    def _apply_actions(self) -> Dict[str, int]:
        extra: Dict[str, int] = {}
        for act in self.plan.active("overload"):
            extra[act.payload.get("tenant", "hot")] = \
                int(act.payload.get("extra", self.cfg.overload_extra))
        for act in self.plan.due_instants():
            if act.name == "swap":
                self._do_swap(act.payload.get("tenant", "cold"))
        return extra

    def _do_swap(self, name: str) -> None:
        """Zero-downtime swap under live traffic: rebuild the tenant's
        corpus from the oracle into a fresh index and flip."""
        self._swap_count += 1
        oracle = self._oracles[name]
        ids = np.asarray(oracle.ids(), dtype=np.int64)
        vecs = (np.stack([oracle.vector(int(i)) for i in ids])
                if len(ids) else
                np.zeros((0, self.cfg.dim), np.float32))
        idx = self._make_index(name, ids, vecs)
        tenant = self.fabric.tenant(name)
        if name == "hot":
            tenant.swap(new_index=idx, search_fn=self._hot_search_fn(),
                        warm=True)
        else:
            tenant.swap(new_index=idx, warm=True)

    # -- MTTR bookkeeping -------------------------------------------------
    _HIST_KINDS = {"kernel_fault": f"heal.mttr.{SERVE_SITE}",
                   "io_error": f"heal.mttr.{mutable_mod.MERGE_SITE}",
                   "shard_dead": "shard.mttr"}

    def _hist_baseline(self) -> None:
        for hname in self._HIST_KINDS.values():
            h = metrics.histogram(hname, metrics.MTTR_BUCKETS_S)
            self._hist_base[hname] = (h.count, h.sum)

    def _hist_delta(self, hname: str) -> Tuple[int, float]:
        h = metrics.histogram(hname, metrics.MTTR_BUCKETS_S)
        c0, s0 = self._hist_base.get(hname, (0, 0.0))
        return h.count - c0, h.sum - s0

    def _note_overload(self, sheds: int, active: bool) -> None:
        ov = self._overload
        if sheds > 0:
            if ov["first"] is None:
                ov["first"] = self.clock.now
            ov["last"] = self.clock.now
        elif ov["first"] is not None and ov["last"] is not None \
                and not active and "overload" not in self._mttr:
            self._mttr["overload"] = [
                self.clock.now - ov["first"]]

    def _mttr_verdict(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for kind in self.plan.fault_kinds() + ["overload"]:
            hist = self._HIST_KINDS.get(kind)
            if hist is not None:
                cnt, ssum = self._hist_delta(hist)
                mean = ssum / cnt if cnt else None
                out[kind] = {"count": int(cnt),
                             "mean_s": None if mean is None
                             else round(mean, 3),
                             "source": hist}
            else:
                vals = self._mttr.get(kind, [])
                out[kind] = {"count": len(vals),
                             "mean_s": (round(sum(vals) / len(vals), 3)
                                        if vals else None),
                             "source": "harness"}
        return out

    # -- the tick loop ----------------------------------------------------
    def _phase_at(self, t: float) -> str:
        for name, t0, t1 in self.cfg.phases():
            if t0 <= t < t1:
                return name
        return "quiesce"

    def run(self) -> dict:
        cfg = self.cfg
        saved = (guarded._clock, sharded_ann._clock)
        guarded._clock = self.clock
        sharded_ann._clock = self.clock
        # re-arm exactly the breakers this soak drills: a prior run in
        # the same process may have left them open past its own end
        # (probation outlives short runs), which would silently skip
        # the fault arc and break same-seed determinism
        guarded.reset(sites=(SERVE_SITE, "mutable.merge"))
        warmup_mod.install_recompile_watch()
        events.attach_sink(str(self.workdir / "events.jsonl"))
        _, self._cursor = events.drain_new(0)
        timeline: List[dict] = []
        phase_log: List[dict] = []
        last_phase = None
        try:
            self._build()
            self._hist_baseline()
            self.plan.start()
            tick = 0
            while self.clock.now < cfg.duration_s:
                t = self.clock.now
                phase = self._phase_at(t)
                if phase != last_phase:
                    if phase_log:
                        phase_log[-1]["t1_s"] = round(t, 3)
                    phase_log.append({"name": phase, "t0_s": round(t, 3),
                                      "t1_s": None})
                    events.record("soak_phase", "soak.harness",
                                  phase=phase, t_s=round(t, 3))
                    last_phase = phase
                self._tick(tick, phase, timeline)
                tick += 1
                self.clock.advance(cfg.dt)
            if phase_log:
                phase_log[-1]["t1_s"] = round(self.clock.now, 3)
            self.plan.stop()
            return self._artifact(tick, phase_log, timeline)
        finally:
            self.plan.stop()
            try:
                if self.fabric is not None:
                    self.fabric.close(timeout=1.0)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            events.detach_sink()
            guarded._clock, sharded_ann._clock = saved

    def _tick(self, tick: int, phase: str,
              timeline: List[dict]) -> None:
        cfg = self.cfg
        quiesce = phase == "quiesce"
        self.plan.step()
        extra = self._apply_actions()

        # 1. mutations: oracle applied ONLY after the index call
        # returned — the WAL fsync'd return IS the durability ack
        if not quiesce:
            for mut in self.workload.mutations_for_tick(self._oracles):
                idx = self._indexes[mut.tenant]
                try:
                    if mut.kind == "upsert":
                        idx.upsert(np.asarray(mut.ids, np.int64),
                                   mut.vectors)
                        self._oracles[mut.tenant].apply_upsert(
                            mut.ids, mut.vectors)
                    else:
                        idx.delete(np.asarray(mut.ids, np.int64))
                        self._oracles[mut.tenant].apply_delete(mut.ids)
                except faults.InjectedCrash as crash:
                    self._recover(mut.tenant, crash.kind)

        # 2. submits (overload extras ride the same stream)
        submitted = []
        sheds = 0
        if not quiesce:
            for qb in self.workload.queries_for_tick(extra):
                try:
                    req = self.fabric.submit(qb.tenant, qb.queries, cfg.k)
                    submitted.append((qb.tenant, qb.queries, req))
                except RateLimitedError:
                    sheds += 1
        self._note_overload(sheds, bool(extra))

        # 3. drain: every round costs service_dt on the sim clock, so
        # queue depth becomes real (simulated) latency — overload
        # backlogs breach the hot SLO, cold isolation stays checkable
        while True:
            n = self.fabric.drain_once()
            if n == 0:
                break
            self.clock.advance(cfg.service_dt)

        # 4. maintenance slot: per-index merges, shard probes, fabric
        # tick (SLO poll + brownout + swap retires). An InjectedCrash
        # here is a merge crash point — recover the index it hit.
        self._maint_tenant = None
        try:
            self.writer.tick()
        except faults.InjectedCrash as crash:
            self._recover(self._maint_tenant or "mut", crash.kind)

        # 5. continuous invariants
        t = self.clock.now
        suite = self.suite
        evts, self._cursor = events.drain_new(self._cursor)
        suite.on_events(t, evts)
        for name, _, req in submitted:
            suite.expect(req.done(), t, "stranded_future", tenant=name)
        if submitted and cfg.recall_samples:
            k = min(len(submitted), cfg.recall_samples)
            picks = self.workload.rng.choice(len(submitted), size=k,
                                             replace=False)
            for pi in sorted(int(i) for i in picks):
                name, queries, req = submitted[pi]
                if not req.done():
                    continue
                try:
                    res = req.result(timeout=1.0)
                except Exception:  # noqa: BLE001 - shed/err counted above
                    continue
                suite.check_recall(t, name, queries,
                                   np.asarray(res.indices), cfg.k,
                                   self._oracles[name])
        for name, idx in self._indexes.items():
            oracle = self._oracles[name]
            sample_ids: tuple = ()
            if tick % cfg.durability_every == 0 and oracle.size:
                live = oracle.ids()
                picks = self.workload.rng.choice(
                    len(live), size=min(2, len(live)), replace=False)
                sample_ids = tuple(int(live[i])
                                   for i in sorted(int(p) for p in picks))
            suite.check_durability(t, name, idx, oracle, sample_ids,
                                   k=cfg.k, pad_rows=8)
        suite.check_cold_p99(t, "cold",
                             self.fabric.tenant("cold").registry)
        suite.check_json_snapshot(
            t, debugz.snapshot(registry=metrics.default_registry,
                               fabric=self.fabric))
        suite.on_tick_end(t, steady=phase in _STEADY_PHASES)

        # 6. timeline sample
        if tick % cfg.sample_every == 0:
            sample = {"t_s": round(t, 3), "phase": phase,
                      "tenants": {}}
            for tn in self.fabric.tenants():
                reg = tn.registry.snapshot()["counters"]
                sample["tenants"][tn.name] = {
                    "rows": int(self._indexes[tn.name].size),
                    "requests": int(reg.get(f"{tn.name}.requests", 0)),
                    "served": int(reg.get(f"{tn.name}.served", 0)),
                    "shed": int(reg.get(f"{tn.name}.shed", 0)),
                    "generation": int(tn.generation),
                }
            timeline.append(sample)

    # -- verdict ----------------------------------------------------------
    def _artifact(self, ticks: int, phase_log: List[dict],
                  timeline: List[dict]) -> dict:
        tenants = {}
        for tn in self.fabric.tenants():
            cs = tn.registry.snapshot()["counters"]
            tenants[tn.name] = {
                "rows": int(self._indexes[tn.name].size),
                "requests": int(cs.get(f"{tn.name}.requests", 0)),
                "served": int(cs.get(f"{tn.name}.served", 0)),
                "shed": int(cs.get(f"{tn.name}.shed", 0)),
                "generation": int(tn.generation),
                "qcache_hits": int(cs.get(f"{tn.name}.qcache.hits", 0)),
            }
        violations = self.suite.to_list()
        mttr = self._mttr_verdict()
        art = {
            "schema": ARTIFACT_SCHEMA,
            "seed": int(self.cfg.seed),
            "config": self.cfg.to_dict(),
            "sim_duration_s": round(self.clock.now, 3),
            "ticks": int(ticks),
            "phases": phase_log,
            "chaos": self.plan.describe(),
            "tenants": tenants,
            "mttr": mttr,
            "violations": violations,
            "verdict": "PASS" if not violations else "FAIL",
        }
        # the artifact IS the verdict — it must hold itself to the same
        # strict-JSON bar the debugz snapshots are held to
        json.dumps(art, allow_nan=False)
        return art


def run_soak(config: Optional[SoakConfig] = None,
             workdir: Optional[str] = None,
             plan: Optional[ChaosPlan] = None,
             artifact_path: Optional[str] = None) -> dict:
    """Build, run, and optionally persist one soak. The convenience
    entry scratch/run_soak.py and the tests both come through here."""
    import tempfile

    cfg = config or SoakConfig()
    wd = workdir or tempfile.mkdtemp(prefix="raft_tpu_soak_")
    art = SoakHarness(cfg, wd, plan=plan).run()
    if artifact_path:
        p = pathlib.Path(artifact_path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "w") as f:
            json.dump(art, f, indent=2, sort_keys=True)
            f.write("\n")
    return art
