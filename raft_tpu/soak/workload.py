"""Deterministic load generation for the soak harness: a simulated
clock, a numpy shadow-corpus oracle, and a seeded Zipfian multi-tenant
query + mutation stream.

Three pieces, each independently testable:

* :class:`SimClock` — the injectable monotonic clock every latency-,
  backoff- and schedule-bearing component in the harness shares
  (ServeFabric, SLOEngine, BrownoutController, faults.Scenario,
  guarded breakers, sharded MTTR, MutableIndex merge deadlines). One
  clock means a 30-second breaker probation elapses in one
  ``advance(30)`` call: hours of production time compress into seconds
  of wall time without loosening a single timeout.
* :class:`ShadowCorpus` — a per-tenant id→vector dict mirroring every
  *acknowledged* mutation. Because the soak serves exact brute-force
  tenants, the oracle's top-k is the ground truth the served results
  must match, and ``len(oracle) == index.size`` is an exact live-row
  durability check at any instant.
* :class:`WorkloadGen` — one ``numpy.random.default_rng(seed)`` drives
  every draw in a fixed per-tick order (tenant choice, query noise,
  mutation ids), so two same-seed runs submit byte-identical traffic.
  Tenant skew is Zipfian over the declared tenant order; the "cold"
  style tenant draws from a fixed query pool so repeats can hit the
  fabric's query cache.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["SimClock", "ShadowCorpus", "TenantLoad", "WorkloadGen",
           "Mutation", "QueryBatch"]


class SimClock:
    """Injectable monotonic clock. Calling it returns the current
    simulated time; only :meth:`advance` moves it, and only forward —
    every component that observes it therefore sees one coherent,
    reproducible timeline."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        dt = float(dt)
        if dt < 0:
            raise ValueError(f"SimClock cannot run backwards (dt={dt})")
        self._now += dt
        return self._now


class ShadowCorpus:
    """Numpy oracle of one tenant's acknowledged rows.

    The harness applies a mutation here only after the index call
    returned (WAL fsync'd — the return *is* the ack); a mutation that
    raised (torn WAL, injected crash) is deliberately not applied, so
    after crash recovery ``index.size == len(oracle)`` states exactly
    the durability contract: every acked write survived, no ghost rows
    from un-acked writes.
    """

    def __init__(self, dim: int):
        self.dim = int(dim)
        self._rows: Dict[int, np.ndarray] = {}
        self._cache: Optional[Tuple[np.ndarray, np.ndarray]] = None

    def __len__(self) -> int:
        return len(self._rows)

    @property
    def size(self) -> int:
        return len(self._rows)

    def ids(self) -> List[int]:
        return sorted(self._rows)

    def vector(self, row_id: int) -> np.ndarray:
        return self._rows[int(row_id)]

    def apply_upsert(self, ids: Sequence[int], vectors: np.ndarray) -> None:
        vectors = np.asarray(vectors, dtype=np.float32)
        for i, row_id in enumerate(ids):
            self._rows[int(row_id)] = vectors[i]
        self._cache = None

    def apply_delete(self, ids: Sequence[int]) -> int:
        found = 0
        for row_id in ids:
            if self._rows.pop(int(row_id), None) is not None:
                found += 1
        if found:
            self._cache = None
        return found

    def _matrix(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._cache is None:
            ids = np.asarray(sorted(self._rows), dtype=np.int64)
            mat = (np.stack([self._rows[int(i)] for i in ids])
                   if len(ids) else
                   np.zeros((0, self.dim), dtype=np.float32))
            self._cache = (ids, mat)
        return self._cache

    def true_knn(self, queries: np.ndarray, k: int) -> np.ndarray:
        """Exact sqeuclidean top-k ids, float32 to match the index's
        arithmetic; rows short of ``k`` pad with -1."""
        ids, mat = self._matrix()
        queries = np.asarray(queries, dtype=np.float32)
        out = np.full((queries.shape[0], k), -1, dtype=np.int64)
        if len(ids) == 0:
            return out
        d = ((queries[:, None, :] - mat[None, :, :]) ** 2).sum(-1)
        kk = min(k, len(ids))
        order = np.argsort(d, axis=1, kind="stable")[:, :kk]
        out[:, :kk] = ids[order]
        return out

    def recall_of(self, queries: np.ndarray, got_ids: np.ndarray,
                  k: int) -> float:
        """Mean id-overlap@k of served neighbors vs the oracle's."""
        truth = self.true_knn(queries, k)
        got = np.asarray(got_ids)[:, :k]
        hits = 0
        denom = 0
        for row_truth, row_got in zip(truth, got):
            want = set(int(i) for i in row_truth if i >= 0)
            if not want:
                continue
            hits += len(want & set(int(i) for i in row_got))
            denom += len(want)
        return hits / denom if denom else 1.0


@dataclasses.dataclass(frozen=True)
class TenantLoad:
    """One tenant's traffic shape. Zipf share comes from declaration
    order (first tenant is the hottest); ``query_pool`` > 0 draws
    queries from a fixed pool (byte-identical repeats → cacheable),
    0 generates fresh queries each time."""

    name: str
    rows_per_request: int = 4
    requests_per_tick: float = 4.0
    upserts_per_tick: int = 0
    deletes_per_tick: int = 0
    query_pool: int = 0


@dataclasses.dataclass(frozen=True)
class QueryBatch:
    tenant: str
    queries: np.ndarray


@dataclasses.dataclass(frozen=True)
class Mutation:
    tenant: str
    kind: str                      # "upsert" | "delete"
    ids: Tuple[int, ...]
    vectors: Optional[np.ndarray]  # None for deletes


class WorkloadGen:
    """Seeded multi-tenant traffic source. All randomness flows through
    one generator in a fixed per-tick order, so the full stream is a
    pure function of (seed, tenant specs, tick index)."""

    def __init__(self, seed: int, dim: int, tenants: Sequence[TenantLoad],
                 *, zipf_s: float = 1.1, k: int = 8):
        self.dim = int(dim)
        self.k = int(k)
        self.tenants = list(tenants)
        self.rng = np.random.default_rng(int(seed))
        shares = np.array([1.0 / (r + 1) ** zipf_s
                           for r in range(len(self.tenants))])
        self._shares = shares / shares.sum()
        self._pools: Dict[str, np.ndarray] = {}
        for t in self.tenants:
            if t.query_pool > 0:
                self._pools[t.name] = self.rng.standard_normal(
                    (t.query_pool, t.rows_per_request, self.dim)
                ).astype(np.float32)
        self._next_id: Dict[str, int] = {}

    def initial_corpus(self, tenant: str,
                       rows: int) -> Tuple[np.ndarray, np.ndarray]:
        """Seed rows for one tenant; ids start at 0 and the per-tenant
        id counter continues from there for later upserts."""
        ids = np.arange(rows, dtype=np.int64)
        vecs = self.rng.standard_normal((rows, self.dim)).astype(np.float32)
        self._next_id[tenant] = rows
        return ids, vecs

    # -- per-tick streams -------------------------------------------------
    def queries_for_tick(self, extra: Dict[str, int] = None
                         ) -> List[QueryBatch]:
        """This tick's query batches. ``extra`` adds requests on top of
        a tenant's base rate (overload bursts)."""
        out: List[QueryBatch] = []
        for ti, spec in enumerate(self.tenants):
            n = int(self.rng.poisson(spec.requests_per_tick))
            n += int((extra or {}).get(spec.name, 0))
            pool = self._pools.get(spec.name)
            for _ in range(n):
                if pool is not None:
                    q = pool[int(self.rng.integers(len(pool)))]
                else:
                    q = self.rng.standard_normal(
                        (spec.rows_per_request, self.dim)
                    ).astype(np.float32)
                out.append(QueryBatch(spec.name, q))
        # Zipf-weighted shuffle: heavier tenants submit earlier more
        # often, but every batch stays in the tick.
        order = self.rng.permutation(len(out))
        return [out[i] for i in order]

    def mutations_for_tick(self, oracles: Dict[str, ShadowCorpus]
                           ) -> List[Mutation]:
        out: List[Mutation] = []
        for spec in self.tenants:
            if spec.upserts_per_tick > 0:
                start = self._next_id.get(spec.name, 0)
                ids = tuple(range(start, start + spec.upserts_per_tick))
                self._next_id[spec.name] = start + spec.upserts_per_tick
                vecs = self.rng.standard_normal(
                    (spec.upserts_per_tick, self.dim)).astype(np.float32)
                out.append(Mutation(spec.name, "upsert", ids, vecs))
            if spec.deletes_per_tick > 0:
                live = oracles[spec.name].ids()
                if len(live) > spec.deletes_per_tick * 4:
                    pick = self.rng.choice(len(live),
                                           size=spec.deletes_per_tick,
                                           replace=False)
                    ids = tuple(int(live[i]) for i in sorted(pick))
                    out.append(Mutation(spec.name, "delete", ids, None))
        return out
