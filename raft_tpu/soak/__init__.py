"""Production soak harness: time-compressed chaos composition with
continuous invariants and MTTR verdicts (docs/soak.md).

Everything the tree ships — the mutable tier, the serving fabric, the
guarded breakers, shard self-healing, SLO/brownout control, the fault
registry — composed into one deterministic, seeded run on a single
simulated clock. ``run_soak`` is the one-call entry; the pieces
(:mod:`workload`, :mod:`chaos`, :mod:`invariants`, :mod:`harness`)
are importable on their own for targeted drills.
"""
from .chaos import ChaosAction, ChaosPlan, standard_plan
from .harness import ARTIFACT_SCHEMA, SoakConfig, SoakHarness, run_soak
from .invariants import InvariantSuite, Violation
from .workload import (Mutation, QueryBatch, ShadowCorpus, SimClock,
                       TenantLoad, WorkloadGen)

__all__ = [
    "ARTIFACT_SCHEMA", "ChaosAction", "ChaosPlan", "InvariantSuite",
    "Mutation", "QueryBatch", "ShadowCorpus", "SimClock", "SoakConfig",
    "SoakHarness", "TenantLoad", "Violation", "WorkloadGen",
    "run_soak", "standard_plan",
]
