"""Seeded chaos composition for the soak harness.

A :class:`ChaosPlan` is two schedules sharing one injected clock:

* **fault stages** delegated to :class:`raft_tpu.core.faults.Scenario`
  — kernel faults, WAL torn tails, crash points, shard deaths, io
  errors, everything the fault registry can arm, with at/until windows
  and fire budgets;
* **harness actions** the fault registry cannot express — overload
  bursts (extra submits past a tenant's token bucket) and scheduled
  zero-downtime swaps — as (at_s, until_s, payload) windows the harness
  polls each tick.

Both halves serialize via :meth:`describe` into the soak artifact, so
the verdict records exactly what was armed and when, and two same-seed
runs must produce identical plans (the determinism test diffs them).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..core import faults

__all__ = ["ChaosAction", "ChaosPlan", "standard_plan"]


class ChaosAction:
    """One harness-level action window. Instant actions (``until_s``
    None) fire once when the clock passes ``at_s``; windowed actions
    are *active* for ``at_s <= now < until_s``."""

    def __init__(self, name: str, at_s: float,
                 until_s: Optional[float] = None, **payload):
        self.name = name
        self.at_s = float(at_s)
        self.until_s = None if until_s is None else float(until_s)
        if self.until_s is not None and self.until_s < self.at_s:
            raise ValueError(
                f"action {name!r}: until_s {until_s} < at_s {at_s}")
        self.payload = dict(payload)
        self.fired = False

    def to_dict(self) -> dict:
        return {"name": self.name, "at_s": self.at_s,
                "until_s": self.until_s, "payload": dict(self.payload),
                "fired": self.fired}


class ChaosPlan:
    """Composed fault + action schedule on one injectable clock."""

    def __init__(self, clock: Callable[[], float]):
        self._clock = clock
        self.scenario = faults.Scenario(clock=clock)
        self.actions: List[ChaosAction] = []
        self._started = False

    # -- building ---------------------------------------------------------
    def add_fault(self, kind: str, pattern: str = "*", *,
                  at_s: float = 0.0, until_s: Optional[float] = None,
                  count: Optional[int] = None, value=None) -> "ChaosPlan":
        self.scenario.add(kind, pattern, at_s=at_s, until_s=until_s,
                          count=count, value=value)
        return self

    def add_action(self, name: str, at_s: float,
                   until_s: Optional[float] = None,
                   **payload) -> "ChaosPlan":
        self.actions.append(ChaosAction(name, at_s, until_s, **payload))
        return self

    # -- driving ----------------------------------------------------------
    def start(self) -> None:
        self._started = True
        self.scenario.start()

    def step(self) -> List[str]:
        """Advance the fault schedule; returns transition strings."""
        return self.scenario.step() if self._started else []

    def stop(self) -> None:
        if self._started:
            self.scenario.stop()
            self._started = False

    def due_instants(self) -> List[ChaosAction]:
        """Un-fired instant actions whose time has come (marks them
        fired)."""
        now = self._clock()
        due = [a for a in self.actions
               if a.until_s is None and not a.fired and a.at_s <= now]
        for a in due:
            a.fired = True
        return due

    def active(self, name: str) -> List[ChaosAction]:
        """Windowed actions of ``name`` active right now."""
        now = self._clock()
        out = []
        for a in self.actions:
            if a.name == name and a.until_s is not None \
                    and a.at_s <= now < a.until_s:
                a.fired = True
                out.append(a)
        return out

    # -- introspection ----------------------------------------------------
    def fault_kinds(self) -> List[str]:
        return sorted({st["kind"] for st in self.scenario.stages()})

    def describe(self) -> dict:
        return {"stages": self.scenario.stages(),
                "actions": [a.to_dict() for a in self.actions]}


def standard_plan(clock: Callable[[], float], *, t0: float = 30.0,
                  window: float = 30.0, hot: str = "hot",
                  mut: str = "mut", cold: str = "cold",
                  guard_site: str = "soak.serve",
                  burst: int = 30) -> ChaosPlan:
    """The canonical compressed drill, scaled around a chaos window of
    ``[t0, t0 + window)`` sim-seconds:

    * ``kernel_fault`` on the hot tenant's guarded serving site for the
      first half of the window — breaker opens, exact fallback serves,
      probe re-closes after probation (→ ``heal.mttr.soak.serve``);
    * ``io_error`` on segment saves for the first half — the mutable
      merge abandons, its breaker opens, the post-window probe merge
      commits (→ ``heal.mttr.mutable.merge``);
    * one ``wal_torn_tail`` and one ``crash_point`` (pre-flip) on the
      mutation tenant — acked-write durability through crash recovery;
    * ``shard_dead`` on the sharded chaos target for the first half
      (→ ``shard.mttr`` once the post-window probe restores it);
    * an overload burst of ``burst`` extra hot-tenant requests per tick
      for the middle third — sheds, SLO breach, brownout step;
    * a zero-downtime swap of the cold tenant mid-window.
    """
    half = window / 2.0
    plan = ChaosPlan(clock)
    plan.add_fault("kernel_fault", guard_site, at_s=t0, until_s=t0 + half)
    plan.add_fault("io_error", "core.serialize.*", at_s=t0,
                   until_s=t0 + half)
    plan.add_fault("wal_torn_tail", "core.wal.append", at_s=t0 + 2.0,
                   until_s=t0 + window, count=1)
    # The io_error window opens the merge breaker; no merge reaches
    # pre_flip until its ~30 s probation elapses AND the probe merge
    # has re-closed it (an InjectedCrash *during* the probe would
    # re-arm the breaker for another 30 s and starve the MTTR verdict).
    # Worst case the breaker opens a merge-cadence (~8 s) into the
    # window, so arm the crash safely past probe time and keep it armed
    # long enough for the next ordinary merge to walk into it.
    crash_at = t0 + half + 34.0
    plan.add_fault("crash_point", "mutable.merge.pre_flip",
                   at_s=crash_at, until_s=crash_at + 40.0, count=1)
    plan.add_fault("shard_dead", "sharded_ann.cagra.shard0",
                   at_s=t0, until_s=t0 + half)
    plan.add_action("overload", t0 + window / 3.0,
                    t0 + 2.0 * window / 3.0, tenant=hot, extra=burst)
    plan.add_action("swap", t0 + half, tenant=cold)
    return plan
