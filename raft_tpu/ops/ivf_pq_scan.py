"""Query-grouped IVF-PQ scan: the LUT-in-VMEM similarity kernel.

Reference role: neighbors/detail/ivf_pq_compute_similarity-inl.cuh:271 —
per (query, probe) block, build the PQ lookup table in shared memory and
scan the list's packed codes. The TPU version rides the same pair
grouping as the IVF-Flat scan (ops/ivf_scan.py) and restates the math in
*expanded* form so the LUT depends only on the query:

    d(q, i) = ||q||² + ||c_l + dec_i||² − 2·q·c_l − 2·Σ_s q_s·cb[s, code_is]

The last term is one GEMM against a block-diagonal codebook matrix (the
per-query LUT), and the per-row sum over coded entries is a one-hot
GEMM — FLOP-rich but exactly the dense shape the MXU wants, while the
dataset stays PQ-compressed in HBM (the point of PQ: DEEP-1B-class
corpora that raw f32 cannot hold). Row norms ||c + dec||² precompute at
build like brute-force norms. The one-hot/LUT GEMM runs in bf16 when the
caller asks for the reference's fp16-LUT mode (lut_dtype), f32 when exact,
or int8 (the fp8-LUT role: per-subspace symmetric codebook quantization,
double-rate MXU int8 decode with exact int32 accumulation).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import cdiv, round_up_to
from .ivf_scan import _INT_BIG, _QG, merge_pairs, pack_pairs, scan_window

__all__ = ["ivf_pq_scan", "make_cb_matrix", "decoded_row_norms"]


def make_cb_matrix(codebooks: jax.Array) -> jax.Array:
    """(pq_dim, book, pq_len) PER_SUBSPACE codebooks → block-structured
    (rot_dim_pad, pq_dim*book) matrix CB with
    CB[s*pq_len + l, b*pq_dim + s] = cb[s, b, l], so q_rot @ CB yields the
    flat per-query LUT in one GEMM — no sub-lane reshapes or gathers
    in-kernel.

    CAVEAT (the documented ``pltpu.repeat`` quirk): the kernel's one-hot
    decode REQUIRES tiling semantics for the code expansion
    (codes_rep[row, b*pq_dim + s] = codes[row, s], i.e. ``np.tile``) —
    that is the layout this column order pairs with. On jax 0.4.37 the
    CPU interpreter's ``pltpu.repeat`` is ELEMENT-wise instead
    (``np.repeat``: codes_rep[row, i] = codes[row, i // book]), which
    scrambles the one-hot for EVERY lut_mode — the real cause behind the
    xfailed interpret-mode pallas/XLA parity tests (historically
    mislabelled an "int8-LUT quirk"). The Mosaic lowering is believed to
    tile but has never been validated on real TPU here; the first pod
    session must pin which semantics hardware implements (the
    analysis suite's ``fragile-repeat`` finding tracks this). The PQ
    edge-store rung avoids the question entirely via the repeat-free
    subspace-major one-hot (``ops.quant.pq_decode_table`` +
    ``graph_expand.edge_tile_widen``)."""
    pq_dim, book, pq_len = codebooks.shape
    rot_dim = pq_dim * pq_len
    rot_pad = round_up_to(rot_dim, 128)
    # pure-jnp construction (this also runs inside jit traces when a
    # caller searches an unprepared index under jit)
    cb = jnp.zeros((rot_pad, pq_dim * book), jnp.float32)
    cbj = jnp.asarray(codebooks, jnp.float32)
    for s in range(pq_dim):
        cb = cb.at[s * pq_len : (s + 1) * pq_len, s::pq_dim].set(cbj[s].T)
    return cb


def pq_chunk_rows(pq_dim: int, book: int,
                  budget_bytes: int = 2 << 30) -> int:
    """Row-chunk bound for ops whose per-row cost is a (pq_dim, book)
    f32 plane (the per-subspace encode argmin, and the codebook gather
    that XLA lowers through a one-hot contraction on TPU): an unbounded
    pass at 500k×pq64×book256 is ~33 GB and exhausts HBM. Also capped at
    256k rows regardless of the byte budget — small (pq_dim, book)
    planes otherwise admit half-million-row single-chunk programs that
    crash the tunnel's compile helper (observed at pq64×book16)."""
    return max(4096, min(1 << 18, budget_bytes // max(pq_dim * book * 4, 1)))


@jax.jit
def _row_norms_chunk(codes_c, labels_c, centers_rot, codebooks):
    pq_dim, book, pq_len = codebooks.shape
    c = centers_rot[labels_c]                        # (b, rot_dim)
    cs = c.reshape(c.shape[0], pq_dim, pq_len)
    # decoded vectors per subspace: (b, pq_dim, pq_len)
    dec = codebooks[jnp.arange(pq_dim)[None, :], codes_c]
    cross = 2.0 * jnp.sum(cs * dec, axis=(1, 2))
    dec2 = jnp.sum(dec * dec, axis=(1, 2))
    return jnp.sum(c * c, axis=1) + cross + dec2


def decoded_row_norms(codes, centers_rot, codebooks, list_offsets
                      ) -> jax.Array:
    """(n,) exact ||c_l(i) + decode(i)||² — subspaces are orthogonal, so
    the decode cross-terms vanish:
    = ||c||² + 2 Σ_s c_s·cb[s,code] + Σ_s ||cb[s,code]||².

    Runs in bounded row chunks (see pq_chunk_rows)."""
    codes = jnp.asarray(codes, jnp.int32)            # (n, pq_dim)
    pq_dim, book, pq_len = codebooks.shape
    n = codes.shape[0]
    sizes = np.diff(np.asarray(list_offsets))
    labels = jnp.asarray(np.repeat(np.arange(len(sizes)), sizes))
    chunk = pq_chunk_rows(pq_dim, book)
    if n <= chunk:
        return _row_norms_chunk(codes, labels, centers_rot, codebooks)
    # wrap the tail to the same chunk shape: one compiled executable
    parts = []
    for b0 in range(0, n, chunk):
        sel = jnp.asarray((np.arange(b0, b0 + chunk) % n).astype(np.int32))
        part = _row_norms_chunk(jnp.take(codes, sel, axis=0),
                                jnp.take(labels, sel, axis=0),
                                centers_rot, codebooks)
        parts.append(part[: min(chunk, n - b0)])
    return jnp.concatenate(parts)


def _kernel(offs_ref, sizes_ref, qb_ref, qn_ref, dn_ref, pen_ref, cent_ref,
            cb_ref, scl_ref, codes_ref, ov_ref, oi_ref, codes_vmem, sem,
            *, k: int, kp: int, lmax: int, pq_dim: int, book: int,
            metric: str, precision: str, has_pen: bool):
    g = pl.program_id(0)
    off = offs_ref[g]
    size = sizes_ref[g]

    # dead-group gate: see ivf_scan._kernel — the static group bound
    # leaves up to n_lists dead groups whose window DMAs are pure waste
    @pl.when(size <= 0)
    def _dead():
        ov_ref[0] = jnp.full((_QG, kp), jnp.inf, jnp.float32)
        oi_ref[0] = jnp.full((_QG, kp), -1, jnp.int32)

    @pl.when(size > 0)
    def _alive():
        _kernel_body(off, size, qb_ref, qn_ref, dn_ref, pen_ref,
                     cent_ref, cb_ref, scl_ref, codes_ref, ov_ref, oi_ref,
                     codes_vmem, sem, k=k, kp=kp, lmax=lmax, pq_dim=pq_dim,
                     book=book, metric=metric, precision=precision,
                     has_pen=has_pen)


def _kernel_body(off, size, qb_ref, qn_ref, dn_ref, pen_ref,
                 cent_ref, cb_ref, scl_ref, codes_ref, ov_ref, oi_ref,
                 codes_vmem, sem, *, k: int, kp: int, lmax: int,
                 pq_dim: int, book: int, metric: str, precision: str,
                 has_pen: bool):
    # off/size arrive as values: pl.program_id cannot be called inside a
    # pl.when branch (the CPU interpreter has no lowering for it there)
    off_al = (off // 8) * 8
    extra = off - off_al

    copy = pltpu.make_async_copy(
        codes_ref.at[pl.ds(off_al, lmax), :], codes_vmem, sem)
    copy.start()
    q = qb_ref[0]                                    # (QG, rot_pad)
    pqb = pq_dim * book
    lut_t = cb_ref.dtype        # bf16 = fp16-LUT mode; int8 = fp8-LUT role
    int8_mode = lut_t == jnp.int8
    qc = jax.lax.dot_general(
        q, cent_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision(precision))      # (QG, 1)
    copy.wait()

    # Associativity saves VMEM: q @ (CB @ OHᵀ) instead of (q @ CB) @ OHᵀ.
    # CB @ OHᵀ is exactly the chunk's *decoded rows* (rot_pad, cw) — a few
    # hundred KB — whereas the per-query LUT (QG, pqb) is megabytes at
    # large pq_dim. One-hot chunks are sized to ~4 MB; at very large lmax
    # this unrolls more GEMM pairs (compile-time cost), the accepted
    # tradeoff for a bounded VMEM footprint.
    #
    # int8 mode (role of the reference's fp8 smem LUT,
    # ivf_pq_types.hpp:110-146): CB arrives pre-quantized with
    # per-subspace symmetric scales; the one-hot is int8 too, so the
    # decode GEMM runs on the MXU's double-rate int8 path and accumulates
    # exactly in int32. The per-ROW scale vector (subspaces are disjoint
    # row/column blocks of CB) rescales the decoded chunk before scoring.
    itemsize = lut_t.itemsize
    chunk = max(128, min(lmax, ((4 << 20) // (pqb * itemsize)) // 128 * 128))
    scale = -2.0 if metric == "l2" else -1.0
    terms = []
    for c0 in range(0, lmax, chunk):
        cw = min(chunk, lmax - c0)
        codes_c = codes_vmem[c0 : c0 + cw, :pq_dim].astype(jnp.int32)
        # ASSUMES tiling semantics (codes_rep[r, b*pq_dim+s] = codes[r, s])
        # to pair with make_cb_matrix's column order. Interpret-mode
        # repeat is element-wise on this jax, which breaks the one-hot
        # below for every lut_mode (the xfailed interpret parity tests);
        # unvalidated on real TPU — see the make_cb_matrix caveat.
        codes_rep = pltpu.repeat(codes_c, book, axis=1)  # (cw, pqb)
        j = jax.lax.broadcasted_iota(jnp.int32, (cw, pqb), 1)
        oh = (codes_rep == j // pq_dim).astype(lut_t)
        if int8_mode:
            dec_i = jax.lax.dot_general(
                oh, cb_ref[:], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)    # (cw, rot_pad)
            decoded = dec_i.astype(jnp.float32) * scl_ref[:]
        else:
            decoded = jax.lax.dot_general(
                oh, cb_ref[:], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (cw, rot_pad)
        terms.append(scale * jax.lax.dot_general(
            q, decoded, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision(precision))) # (QG, cw)
    pq_term = jnp.concatenate(terms, axis=1) if len(terms) > 1 else terms[0]

    if metric == "l2":
        qn = qn_ref[0]                               # (QG, 1) ||q||²
        dist = jnp.maximum(qn + dn_ref[0, 0] - 2.0 * qc + pq_term, 0.0)
    else:                                            # "ip": min-order score
        dist = -qc + pq_term
    if has_pen:
        # in-kernel bitset filter as an additive penalty row (role of
        # detail/ivf_pq_search.cuh:795-797)
        dist = dist + pen_ref[0, 0]

    col = jax.lax.broadcasted_iota(jnp.int32, (_QG, lmax), 1)
    dist = jnp.where((col >= extra) & (col < extra + size), dist, jnp.inf)
    lane = jax.lax.broadcasted_iota(jnp.int32, (_QG, kp), 1)

    def extract(t, state):
        c, nv, ni = state
        best = jnp.min(c, axis=1, keepdims=True)
        pos = jnp.min(jnp.where(c <= best, col, _INT_BIG), axis=1,
                      keepdims=True)
        at = col == pos
        bid = jnp.where(jnp.isfinite(best), off_al + pos, -1)
        nv = jnp.where(lane == t, best, nv)
        ni = jnp.where(lane == t, bid, ni)
        return jnp.where(at, jnp.inf, c), nv, ni

    state = (dist, jnp.full((_QG, kp), jnp.inf, jnp.float32),
             jnp.full((_QG, kp), -1, jnp.int32))
    if k <= 16:
        for t in range(k):
            state = extract(t, state)
    else:
        state = jax.lax.fori_loop(0, k, extract, state)
    ov_ref[0] = state[1]
    oi_ref[0] = state[2]


@functools.partial(
    jax.jit,
    static_argnames=("k", "lmax", "n_groups", "pq_dim", "book", "metric",
                     "interpret", "precision", "has_pen"))
def _scan_groups(qblocks, qnorms, dn_slices, pen_slices, gcenters, cb_matrix,
                 scale_row, codes, goffs, gsizes, k, lmax, n_groups, pq_dim,
                 book, metric, interpret, precision, has_pen):
    kp = round_up_to(k, 128)
    rot_pad = qblocks.shape[2]
    kern = functools.partial(_kernel, k=k, kp=kp, lmax=lmax, pq_dim=pq_dim,
                             book=book, metric=metric, precision=precision,
                             has_pen=has_pen)
    pen_map = (lambda g, o, s: (g, 0, 0)) if has_pen else (
        lambda g, o, s: (0, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_groups,),
        in_specs=[
            pl.BlockSpec((1, _QG, rot_pad), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _QG, 1), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, lmax), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, lmax), pen_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, rot_pad), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),     # CB matrix (whole)
            pl.BlockSpec(memory_space=pltpu.VMEM),     # int8 row scales
            pl.BlockSpec(memory_space=pl.ANY),      # codes stay in HBM
        ],
        out_specs=[
            pl.BlockSpec((1, _QG, kp), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _QG, kp), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((lmax, codes.shape[1]), jnp.uint8),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_groups, _QG, kp), jnp.float32),
            jax.ShapeDtypeStruct((n_groups, _QG, kp), jnp.int32),
        ],
        interpret=interpret,
    )(goffs, gsizes, qblocks, qnorms, dn_slices, pen_slices, gcenters,
      cb_matrix, scale_row, codes)


def ivf_pq_scan(
    codes: jax.Array,           # (n, pq_dim) u8, cluster-sorted
    row_norms2: jax.Array,      # (n,) ||c + decode||²
    centers_rot: jax.Array,     # (L, rot_dim)
    cb_matrix: jax.Array,       # (rot_pad, pq_dim*book) block-diagonal
    probed: jax.Array,          # (m, p)
    offsets: jax.Array,         # (L,)
    sizes: jax.Array,           # (L,)
    q_rot: jax.Array,           # (m, rot_dim) rotated queries
    k: int,
    lmax: int,
    pq_dim: int,
    book: int,
    metric: str = "l2",
    lut_mode: str = "bf16",     # "f32" | "bf16" | "int8"
    interpret: Optional[bool] = None,
    precision: str = "highest",
    penalty: Optional[jax.Array] = None,   # (n,) f32: +inf excludes a row
) -> Tuple[jax.Array, jax.Array]:
    """Scan probed PQ lists → per-query k best (approx values, ROW ids).
    ``penalty`` is indexed in the sorted row order of ``codes``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    codes_p, norms_p = pad_codes_for_scan(codes, row_norms2, lmax, pq_dim)
    pen_p = None
    if penalty is not None:
        pen_p = jnp.pad(jnp.asarray(penalty, jnp.float32),
                        (0, scan_window(lmax)))
    return _ivf_pq_scan_jit(codes_p, norms_p, pen_p, centers_rot, cb_matrix,
                            probed, offsets, sizes, q_rot, k, lmax, pq_dim,
                            book, metric, lut_mode, interpret, precision)


@functools.partial(jax.jit, static_argnames=("lmax", "pq_dim"))
def pad_codes_for_scan(codes, row_norms2, lmax: int, pq_dim: int):
    """Pad codes/norms for the aligned DMA windows — a full copy of the
    compressed dataset; callers cache per index."""
    lmax_pad = scan_window(lmax)
    code_pad = round_up_to(pq_dim, 128)
    codes_p = jnp.pad(jnp.asarray(codes, jnp.uint8),
                      ((0, lmax_pad), (0, code_pad - pq_dim)))
    norms_p = jnp.pad(jnp.asarray(row_norms2, jnp.float32), (0, lmax_pad))
    return codes_p, norms_p


@functools.partial(
    jax.jit,
    static_argnames=("k", "lmax", "pq_dim", "book", "metric", "lut_mode",
                     "interpret", "precision"))
def _ivf_pq_scan_jit(codes_p, norms_p, pen_p, centers_rot, cb_matrix, probed,
                     offsets, sizes, q_rot, k, lmax, pq_dim, book, metric,
                     lut_mode, interpret, precision):
    m, p = probed.shape
    n_lists = offsets.shape[0]
    rot_dim = q_rot.shape[1]
    rot_pad = cb_matrix.shape[0]
    lmax_pad = scan_window(lmax)
    scale_row = jnp.ones((1, rot_pad), jnp.float32)
    if lut_mode == "int8":
        # fp8-LUT role (ivf_pq_types.hpp:110-146): per-subspace symmetric
        # quantization of the block-diagonal CB. Column b*pq_dim+s and row
        # s*pq_len+l both belong to subspace s and CB is block-diagonal in
        # s, so a per-COLUMN-subspace quantize + per-ROW-subspace rescale
        # round-trips exactly (up to the int8 rounding itself).
        pq_len = rot_dim // pq_dim
        absmax = jnp.max(jnp.abs(cb_matrix).reshape(rot_pad, book, pq_dim),
                         axis=(0, 1))                    # (pq_dim,)
        scales = jnp.maximum(absmax, 1e-12) / 127.0
        cb_matrix = jnp.clip(
            jnp.round(cb_matrix.reshape(rot_pad, book, pq_dim)
                      / scales[None, None, :]), -127, 127
        ).astype(jnp.int8).reshape(rot_pad, pq_dim * book)
        scale_row = jnp.pad(jnp.repeat(scales, pq_len),
                            (0, rot_pad - rot_dim),
                            constant_values=1.0)[None, :]
    elif lut_mode == "bf16":
        # fp16-LUT mode: cast here so the kernel's operand dtypes match
        cb_matrix = cb_matrix.astype(jnp.bfloat16)
    q = jnp.pad(jnp.asarray(q_rot, jnp.float32),
                ((0, 0), (0, rot_pad - rot_dim)))
    cent_p = jnp.pad(jnp.asarray(centers_rot, jnp.float32),
                     ((0, 0), (0, rot_pad - rot_dim)))

    qtable, glist, galive, flat, order, n_groups = pack_pairs(probed,
                                                              n_lists)
    qblocks = q[qtable]                              # (G, QG, rot_pad)
    qn = jnp.sum(qblocks * qblocks, axis=2, keepdims=True)
    gcenters = cent_p[glist][:, None, :]             # (G, 1, rot_pad)
    goffs = offsets[glist]
    gsizes = jnp.where(galive, sizes[glist], 0)
    goffs_al = (goffs // 8) * 8
    dn = jax.vmap(lambda o: jax.lax.dynamic_slice(
        norms_p, (o,), (lmax_pad,)))(goffs_al)[:, None, :]
    if pen_p is None:
        pen = jnp.zeros((1, 1, lmax_pad), jnp.float32)
    else:
        pen = jax.vmap(lambda o: jax.lax.dynamic_slice(
            pen_p, (o,), (lmax_pad,)))(goffs_al)[:, None, :]

    gv, gi = _scan_groups(qblocks, qn, dn, pen, gcenters, cb_matrix,
                          scale_row, codes_p, goffs, gsizes, k, lmax_pad,
                          int(n_groups), pq_dim, book, metric, interpret,
                          precision, pen_p is not None)
    return merge_pairs(gv, gi, flat, order, m, p, k)
