"""Gather-free CAGRA frontier expansion: streamed edge-tile scoring.

The graph hop's one HBM-bound op used to be a random row gather
(``cagra._gather_score``): each of the ``m·width`` expanded parents pulls
``degree`` scattered 128-256 B dataset rows, and the roofline measures
that access pattern at ~61 GB/s against ~640 GB/s streamed (BENCH_r05).
GGNN (Groh et al., arXiv:1912.01059) removes the same tax on GPU by
co-locating neighbor data with graph edges; this kernel is the TPU form:

* ``cagra.prepare_traversal`` packs, for every node, its ``degree``
  neighbors' *quantized* vectors into one contiguous ``(n, deg_p,
  dim_p)`` HBM array (int8 per-row-scaled by default, bf16 optional), so
  expanding a parent reads ONE contiguous tile (deg64×dim128 int8 =
  8 KB) instead of 64 random lines.
* Scalar-prefetched parent ids drive double-buffer-friendly async DMAs:
  the store stays in HBM (``pl.ANY``), and each grid step issues ``P``
  per-parent tile copies (plus their per-edge scale/norm rows) that are
  all in flight together before the step computes — the ivf_scan manual
  -DMA pattern, with enough concurrent 8 KB transfers to hide latency.
* Each grid step carries ``P_q`` queries and their ``P = P_q·width``
  parents: a one-hot matmul routes every parent its own query row, the
  tile is scored as a broadcast multiply + lane reduce (~2 flops per
  streamed byte — the VPU is nowhere near binding next to the DMA
  rate), the bitset-filter penalty and the pad-edge mask are applied
  in-kernel, and a per-parent top-``k'`` (value, edge position) is
  emitted — shrinking the host-side merge width from ``width·degree``
  to ``width·k'``.

The returned values are traversal scores in min-space (squared L2 or
-IP) at storage precision; CAGRA's exact f32 re-score of the final top-k
keeps returned distances exact regardless.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import round_up_to

__all__ = ["graph_expand", "edge_tile_widen", "score_dim"]

_INT_BIG = 2**30


def _pick_pq(width: int) -> int:
    """Queries per grid step: keep P = P_q·width parents per step near 16
    without exceeding it, and never below one query. P trades grid-step
    count against per-step DMA fan-in; per-parent DMA count is
    P-invariant, so the step count only has to amortize the grid
    bookkeeping while keeping ~2·P copies in flight to hide latency."""
    return max(1, min(8, 16 // max(width, 1)))


def edge_tile_widen(V, q_rows, mode: str, cb_ref=None, cbscl_ref=None):
    """Edge tile (P, deg_p, W) in storage form → per-edge f32 query
    cross-products ``(P, deg_p)``. The ONE scoring expression both the
    per-hop kernel here and the fused megakernel (ops/cagra_fused.py)
    call, so the engines stay bit-identical by construction across every
    storage rung:

    * ``dense`` — int8/bf16 rows widened through f32 in-register (Mosaic
      has no byte→bf16 cast — the ivf_scan idiom); f32 multiplies keep
      parity with the gather path's f32-highest einsum.
    * ``int4`` — nibble-packed rows (ops/quant.py split-half layout):
      lane-axis shift+mask into (low, high) planes and a split
      broadcast-mul/lane-reduce against the query's column halves.
    * ``pq`` — PQ codes decoded in-VMEM by a one-hot GEMM against the
      subspace-major decode table (``ops.quant.pq_decode_table``); the
      int8 table mode (the fp8-LUT role) accumulates exactly in int32
      and rescales per output column. The one-hot builds from plain
      per-subspace equality compares (NOT ``pltpu.repeat``, whose
      interpret semantics diverge from the tiling its other user
      assumes), and only major axes are ever reshaped — the
      (P·deg_p, pqb) flatten never touches the minor dim.
    """
    P, deg_p = V.shape[0], V.shape[1]
    if mode == "int4":
        from .quant import int4_nibbles

        half = V.shape[2]
        low, high = int4_nibbles(V.astype(jnp.int32))
        return jnp.sum(q_rows[:, None, :half] * low
                       + q_rows[:, None, half:] * high, axis=2)
    if mode == "pq":
        dim_p = cb_ref.shape[1]
        pq_dim = V.shape[2]
        book = cb_ref.shape[0] // pq_dim
        codes2 = V.reshape(P * deg_p, pq_dim).astype(jnp.int32)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (P * deg_p, book), 1)
        oh = jnp.concatenate(
            [codes2[:, s:s + 1] == iota_b for s in range(pq_dim)],
            axis=1).astype(cb_ref.dtype)                 # (P·deg_p, pqb)
        if cb_ref.dtype == jnp.int8:
            dec = jax.lax.dot_general(
                oh, cb_ref[:], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32).astype(jnp.float32)
            dec = dec * cbscl_ref[:]                     # (1, dim_p)
        else:
            dec = jax.lax.dot_general(
                oh, cb_ref[:], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        Vw = dec.reshape(P, deg_p, dim_p)
        return jnp.sum(q_rows[:, None, :] * Vw, axis=2)
    Vw = (V.astype(jnp.int32).astype(jnp.float32)
          if V.dtype in (jnp.int8, jnp.uint8) else V.astype(jnp.float32))
    return jnp.sum(q_rows[:, None, :] * Vw, axis=2)


def _kernel(pids_ref, q_ref, vecs_hbm, aux_hbm, *rest, P: int, P_q: int,
            width: int, deg_p: int, degree: int, k_out: int, kp: int,
            metric: str, with_pen: bool, mode: str):
    if mode == "pq":
        cb_ref, cbscl_ref, *rest = rest
    else:
        cb_ref = cbscl_ref = None
    if with_pen:
        pen_hbm, ov_ref, oi_ref, vtile, atile, ptile, sem = rest
    else:
        pen_hbm = ptile = None
        ov_ref, oi_ref, vtile, atile, sem = rest
    g = pl.program_id(0)

    # start every parent's copies before waiting on any: P tile DMAs
    # (plus the small aux/pen rows) in flight together hide the HBM
    # latency the way the grid pipeline does for fused_knn's tiles
    copies = []
    for j in range(P):
        pid = pids_ref[g * P + j]
        c = pltpu.make_async_copy(vecs_hbm.at[pid], vtile.at[j],
                                  sem.at[0, j])
        c.start()
        copies.append(c)
        c = pltpu.make_async_copy(aux_hbm.at[pid], atile.at[j],
                                  sem.at[1, j])
        c.start()
        copies.append(c)
        if with_pen:
            c = pltpu.make_async_copy(pen_hbm.at[pid], ptile.at[j],
                                      sem.at[2, j])
            c.start()
            copies.append(c)

    q = q_ref[:]                                     # (P_q, dim_p) f32
    for c in copies:
        c.wait()
    V = vtile[:]                                     # (P, deg_p, dim_p)
    A = atile[:]                                     # (P, 2, deg_p)
    scales = A[:, 0, :]                              # (P, deg_p)
    vnorm = A[:, 1, :]                               # ||dequant v||²

    # route each parent its own query row with a one-hot matmul — parent
    # j of the step belongs to query j // width — then score per parent
    # as an elementwise product + lane reduce. (A (P_q, P·deg_p) cross
    # product would need a minor-dim reshape at deg_p<128 granularity to
    # reach the per-parent (P, deg_p) extraction layout — a relayout
    # Mosaic handles far less reliably than these broadcast/reduce
    # forms; the VPU math is ~2 flops per streamed byte, nowhere near
    # binding next to the per-parent DMA issue rate.)
    prow = jax.lax.broadcasted_iota(jnp.int32, (P, P_q), 0) // width
    qcol = jax.lax.broadcasted_iota(jnp.int32, (P, P_q), 1)
    route = (prow == qcol).astype(jnp.float32)       # (P, P_q) one-hot
    qpar = jax.lax.dot_general(route, q, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    # storage-rung widen/decode + broadcast-mul/lane-reduce scoring —
    # shared with the fused megakernel (see edge_tile_widen)
    cross = edge_tile_widen(V, qpar, mode, cb_ref, cbscl_ref)  # (P, deg_p)
    cross = cross * scales                           # q·(s·v) = s·(q·v)
    if metric == "l2":
        qn_p = jnp.sum(qpar * qpar, axis=1, keepdims=True)   # (P, 1)
        dist = jnp.maximum(qn_p + vnorm - 2.0 * cross, 0.0)
    else:                                            # "ip": min-space -dot
        dist = -cross
    if with_pen:
        dist = dist + ptile[:].reshape(P, deg_p)
    col = jax.lax.broadcasted_iota(jnp.int32, (P, deg_p), 1)
    dist = jnp.where(col < degree, dist, jnp.inf)    # pad edges out

    lane = jax.lax.broadcasted_iota(jnp.int32, (P, kp), 1)

    def extract(t, state):
        c, nv, ni = state
        best = jnp.min(c, axis=1, keepdims=True)
        pos = jnp.min(jnp.where(c <= best, col, _INT_BIG), axis=1,
                      keepdims=True)
        at = col == pos
        bid = jnp.where(jnp.isfinite(best), pos, -1)
        nv = jnp.where(lane == t, best, nv)
        ni = jnp.where(lane == t, bid, ni)
        return jnp.where(at, jnp.inf, c), nv, ni

    state = (dist, jnp.full((P, kp), jnp.inf, jnp.float32),
             jnp.full((P, kp), -1, jnp.int32))
    if k_out <= 16:
        for t in range(k_out):
            state = extract(t, state)
    else:
        state = jax.lax.fori_loop(0, k_out, extract, state)
    ov_ref[:] = state[1]
    oi_ref[:] = state[2]


@functools.partial(
    jax.jit,
    static_argnames=("k_out", "metric", "width", "degree", "P_q",
                     "interpret", "with_pen", "mode"))
def _expand_padded(pids, q, vecs, aux, pen, cbm, cbscl, k_out: int,
                   metric: str, width: int, degree: int, P_q: int,
                   interpret: bool, with_pen: bool, mode: str):
    m_pad, dim_p = q.shape
    n, deg_p, store_w = vecs.shape
    P = P_q * width
    kp = round_up_to(k_out, 128)
    grid = (m_pad // P_q,)

    kern = functools.partial(_kernel, P=P, P_q=P_q, width=width,
                             deg_p=deg_p, degree=degree, k_out=k_out,
                             kp=kp, metric=metric, with_pen=with_pen,
                             mode=mode)
    in_specs = [
        pl.BlockSpec((P_q, dim_p), lambda g, p: (g, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pl.ANY),       # edge store stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),       # aux (scales, norms)
    ]
    args = [q, vecs, aux]
    if mode == "pq":
        # the decode matrix (and its int8 per-row rescale) live whole in
        # VMEM — a few hundred KB at pq8·book256·d128
        in_specs.append(pl.BlockSpec(memory_space=pltpu.VMEM))
        args.append(cbm)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.VMEM))
        args.append(cbscl)
    if with_pen:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        args.append(pen)
    scratch = [
        pltpu.VMEM((P, deg_p, store_w), vecs.dtype),
        pltpu.VMEM((P, 2, deg_p), jnp.float32),
    ]
    if with_pen:
        scratch.append(pltpu.VMEM((P, 1, deg_p), jnp.float32))
    scratch.append(pltpu.SemaphoreType.DMA((3, P)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((P, kp), lambda g, p: (g, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((P, kp), lambda g, p: (g, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=scratch,
    )
    vals, epos = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((m_pad * width, kp), jnp.float32),
            jax.ShapeDtypeStruct((m_pad * width, kp), jnp.int32),
        ],
        interpret=interpret,
    )(pids, *args)
    return vals, epos


def score_dim(vecs: jax.Array, mode: str, cbm=None) -> int:
    """Query width the kernel scores at for a storage mode: the store
    minor dim ("dense"), twice the packed byte width ("int4" — the
    (low, high) split), or the decode matrix's row space ("pq")."""
    if mode == "int4":
        return 2 * vecs.shape[2]
    if mode == "pq":
        return cbm.shape[1]       # decode-table columns = embedded dims
    return vecs.shape[2]


def graph_expand(
    parents: jax.Array,          # (m, width) int32 parent node ids
    queries: jax.Array,          # (m, dim) f32
    vecs: jax.Array,             # (n, deg_p, W) int8 | bf16 | u8 edge store
    aux: jax.Array,              # (n, 2, deg_p) f32: [scales, dequant norms]
    k_out: int,
    metric: str = "l2",
    degree: Optional[int] = None,
    pen: Optional[jax.Array] = None,   # (n, deg_p) f32: +inf excludes edge
    interpret: Optional[bool] = None,
    mode: str = "dense",
    cbm: Optional[jax.Array] = None,     # pq: (pq_dim*book, dim_p)
    #                                      subspace-major decode table
    cb_scale: Optional[jax.Array] = None,  # pq int8 CB: (1, dim_p) rescale
) -> Tuple[jax.Array, jax.Array]:
    """Score every parent's neighbor tile, return per-parent top-``k_out``.

    Returns ``(vals (m, width, k_out) f32, epos (m, width, k_out) int32)``
    best-first in min-space ("l2": squared L2 at storage precision;
    "ip": -dot). ``epos`` are EDGE positions into the parent's graph row
    (callers map them to global ids via ``graph[parent][epos]``); empty
    slots are ``(+inf, -1)``. ``degree``: real edge count (≤ ``deg_p``;
    pad edges are masked in-kernel). ``pen``: optional per-edge additive
    penalty in the same edge-major layout as the store (bitset filters).
    ``mode``: storage rung of ``vecs`` — "dense" (int8/bf16 rows),
    "int4" (nibble-packed, W = half the scored dim), or "pq" (W = codes
    per row; ``cbm`` is the ``(pq_dim*book, dim_p)`` SUBSPACE-MAJOR
    decode table from ``ops.quant.pq_decode_table`` — NOT
    ``ivf_pq_scan.make_cb_matrix``'s transposed layout — with
    ``cb_scale`` its int8-mode per-column rescale).
    """
    m, width = parents.shape
    n, deg_p, _ = vecs.shape
    dim_p = score_dim(vecs, mode, cbm)
    degree = deg_p if degree is None else degree
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    P_q = _pick_pq(width)
    m_pad = round_up_to(m, P_q)

    q = jnp.asarray(queries, jnp.float32)
    q = jnp.pad(q, ((0, m_pad - m), (0, dim_p - q.shape[1])))
    pids = jnp.clip(jnp.asarray(parents, jnp.int32), 0, n - 1)
    pids = jnp.pad(pids, ((0, m_pad - m), (0, 0))).reshape(-1)
    # None rides through jit as an empty pytree; the kernel only takes a
    # pen operand when with_pen
    pen3 = pen.reshape(n, 1, deg_p) if pen is not None else None

    vals, epos = _expand_padded(pids, q, vecs, aux, pen3, cbm, cb_scale,
                                k_out, metric, width, degree, P_q,
                                interpret, pen is not None, mode)
    vals = vals.reshape(m_pad, width, -1)[:m, :, :k_out]
    epos = epos.reshape(m_pad, width, -1)[:m, :, :k_out]
    return vals, epos
