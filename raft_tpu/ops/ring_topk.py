"""Ring-allreduce-top-k: the device-resident merge engine for sharded
search (SURVEY layer 2 ``comms_t``: the collective under every
distributed algorithm).

The allgather merge path materializes every shard's full candidate set
on every device — a (p, m, k) buffer per query batch — and then runs a
``select_k`` over the p·k-wide concatenation (knn_merge_parts.cuh:172).
This module replaces that with a ring: each shard keeps its local
(m, k) candidates resident, streams a block to its right neighbor at
each of the p−1 hops, and folds the arriving block into a running top-k
— so the live footprint stays O(k) per query and the merge work per hop
is a 2k-wide fold instead of one p·k-wide select.

Why the result is BIT-IDENTICAL (order included) to ``knn_merge_parts``:
``select_k``'s tie contract is lowest-column-first (lax.top_k
semantics; the KPASS kernel matches it by construction), so the merged
answer is exactly "the k best candidates of the (m, p·k) shard-ordered
concatenation under the total order (±distance, column position)".
Each candidate's column position is derivable — shard s's slot j sits
at column s·k + j — and top-k under a *total* order is associative, so
an incremental ring fold that carries (distance, gid) and re-derives
the position of each arriving block from its origin shard produces the
same k entries in the same order on every shard, dead-shard
(+inf, −1) sentinel rows included (they are ordinary candidates that
lose every comparison against a survivor, exactly as they do inside the
allgather's ``select_k``).

Three engines, one contract:

* ``allgather`` — the existing path, verbatim (``comms.allgather`` +
  ``knn_merge_parts``): the rehearsed fallback and the bit-identity
  reference.
* ``ring`` — the hop/mask logic in plain XLA: ``device_sendrecv``
  (a ``ppermute`` ring shift) store-and-forward with a
  (key, position)-lexicographic 2k-wide fold per hop. Runs on any
  backend — tier-1 asserts it bit-identical to ``knn_merge_parts`` on
  the 8-device virtual CPU mesh.
* ``ring_pallas`` — the TPU kernel: candidates live in VMEM,
  ``pltpu.make_async_remote_copy`` streams blocks over ICI with
  double-buffered slots, a remote credit semaphore gates slot reuse,
  and the same lexicographic fold runs in-VMEM at each hop. Zero HBM
  round trip for the gathered buffer, zero host sync.

A fourth, TOPOLOGY-AWARE composition sits above the three flat
engines: ``hier`` (multi-host fleets, :mod:`raft_tpu.parallel.topology`)
runs the ring within each host's ICI clique (grouped collectives over
``host_groups()`` — the flat ring engine verbatim, just on a subgroup),
then folds the per-host winner blocks across DCN with one grouped
allgather + lexicographic select. Each device moves ``(H−1)·m·k``
candidate cells over DCN instead of the flat allgather's ``(H−1)·D·m·k``
— a reduction factor of exactly ``devs_per_host``. Bit-identity to the
flat merge holds by a surrogate-position argument: a global top-k
member is always inside its own host's top-k (stage 1 keeps it), stage
1's stable sort emits each host block in ascending global-position
order, and host blocks occupy disjoint ascending global-position ranges
— so ranking stage-2 candidates by (±distance, host-block position
``h·k + j``) induces the same total order as (±distance, global concat
position), dead-shard (+inf, −1) sentinels included.

Engine resolution (``resolve_engine``) prefers a measured autotune
verdict (``tune_merge`` races the engines under a dtype/mesh-aware
key), then ``RAFT_TPU_SHARDED_MERGE``, then a backend default: the ring
kernel on TPU (VMEM budget permitting), allgather elsewhere. A
multi-host topology adds a tier ABOVE the autotune bucket — ``hier``
by default (the buckets were measured on single-host meshes and say
nothing about DCN) — while single-host meshes take the pre-existing
path byte-for-byte. Callers gate every non-allgather engine behind
``guarded_call("sharded.ring_topk")`` so a compile/execution failure on
an unrehearsed shape demotes to the bit-identical allgather path
instead of failing the query.
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import expects

__all__ = ["merge", "merge_step", "resolve_engine", "tune_merge",
           "ring_capable", "ENGINES", "ALL_ENGINES", "MERGE_SITE",
           "per_hop_bytes", "gathered_bytes", "active_engines",
           "note_engine", "note_fallback", "guarded_dispatch"]

ENGINES = ("allgather", "ring", "ring_pallas")
# the flat engines plus the topology-aware multi-host composition;
# "hier" needs a Topology at merge() time, so it lives outside ENGINES
# (the flat autotune/race vocabulary) but inside the dispatch contract
ALL_ENGINES = ENGINES + ("hier",)

# the guarded site every ring-engine dispatch runs under (ops/guarded.py):
# a ring compile/execution failure demotes to the allgather program
MERGE_SITE = "sharded.ring_topk"

_INT_BIG = 2 ** 30
# conservative VMEM budget for the full-residency ring kernel: running
# state (3 planes) + double-buffered comm slots (2×2 planes) + in/out
# (4 planes) + fold temporaries ≈ 12 live (mp, kp)/(mp, 2kp) f32 planes
_VMEM_CELL_CAP = 256 * 1024


# --------------------------------------------------------------------------
# traffic accounting (the bench decomposition's ICI math)
# --------------------------------------------------------------------------

def per_hop_bytes(m: int, k: int) -> int:
    """Bytes one shard moves over ICI per ring hop: an (m, k) f32
    distance block + an (m, k) i32 id block."""
    return m * k * (4 + 4)


def gathered_bytes(m: int, k: int, p: int) -> int:
    """Bytes of the (p, m, k) candidate buffer every device materializes
    under the allgather merge (distances + ids)."""
    return p * m * k * (4 + 4)


# --------------------------------------------------------------------------
# the (key, position)-lexicographic fold — shared by every ring engine
# --------------------------------------------------------------------------

def _lex_topk(kd: jax.Array, pos: jax.Array, gid: jax.Array, dd: jax.Array,
              k: int) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Top-k of (..., w) candidates under the total order
    (key asc, position asc), carrying the untransformed distance and the
    global id. ``lax.sort`` with two key operands is exactly this order."""
    kd2, pos2, gid2, dd2 = lax.sort((kd, pos, gid, dd), dimension=-1,
                                    is_stable=True, num_keys=2)
    return kd2[..., :k], pos2[..., :k], gid2[..., :k], dd2[..., :k]


def _fold(state, blk, k: int):
    """One ring fold: merge the arriving block into the running top-k."""
    cat = tuple(jnp.concatenate([a, b], axis=-1)
                for a, b in zip(state, blk))
    return _lex_topk(*cat, k)


def merge_step(run_d, run_pos, run_gid, blk_d, blk_pos, blk_gid, k: int,
               select_min: bool = True, engine: str = "xla",
               interpret: Optional[bool] = None):
    """One hop's in-VMEM merge, standalone: fold an arriving (m, w2)
    candidate block into a running (m, w1) top-k under the
    (±distance, position) total order. Returns (d, pos, gid) each
    (m, k), best-first.

    ``engine="xla"``: the ``lax.sort`` fold (the hop logic the XLA ring
    uses). ``engine="pallas"``: the VMEM fold kernel the TPU ring kernel
    runs per hop — ``interpret=True`` exercises it off-TPU (the tier-1
    kernel-parity test)."""
    expects(engine in ("xla", "pallas"),
            "unknown merge_step engine %r (one of 'xla', 'pallas')", engine)
    kd_r = run_d if select_min else -run_d
    kd_b = blk_d if select_min else -blk_d
    if engine == "pallas":
        kd, pos, gid = _merge_step_pallas(
            kd_r, run_pos, run_gid, kd_b, blk_pos, blk_gid, k,
            jax.default_backend() != "tpu" if interpret is None
            else interpret)
    else:
        kd, pos, gid, _ = _fold(
            (kd_r, run_pos, run_gid, run_d),
            (kd_b, blk_pos, blk_gid, blk_d), k)
    return (kd if select_min else -kd), pos, gid


# --------------------------------------------------------------------------
# XLA ring engine (the hop/mask logic; every backend)
# --------------------------------------------------------------------------

def _ring_xla(d, gid, k: int, select_min: bool, comms):
    """Store-and-forward ring merge in plain XLA, called per shard
    inside ``shard_map``. p−1 ``device_sendrecv`` hops (the ppermute
    ring), O(k) traffic per hop, (key, pos)-lex fold on arrival."""
    p = comms.get_size()
    rank = comms.get_rank()
    m = d.shape[0]
    kd = d if select_min else -d
    slot = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32), (m, k))
    state = (kd, rank.astype(jnp.int32) * k + slot, gid, d)
    send_kd, send_gid = kd, gid
    for h in range(p - 1):
        recv_kd = comms.device_sendrecv(send_kd, 1)
        recv_gid = comms.device_sendrecv(send_gid, 1)
        src = jnp.mod(rank - (h + 1), p).astype(jnp.int32)
        blk = (recv_kd, src * k + slot, recv_gid,
               recv_kd if select_min else -recv_kd)
        state = _fold(state, blk, k)
        send_kd, send_gid = recv_kd, recv_gid
    return state[3], state[2]


# --------------------------------------------------------------------------
# hierarchical ICI/DCN engine (multi-host fleets)
# --------------------------------------------------------------------------

def _hier(d, gid, k: int, select_min: bool, axis: str, topology):
    """Two-stage topology-aware merge, called per shard inside
    ``shard_map`` over a host-major fleet mesh.

    Stage 1 (ICI): the flat XLA ring, unchanged, over this host's
    ``host_groups()`` clique — within-group ranks make the stamped
    positions host-LOCAL (``l·k + slot``), which stage 2 relies on.
    Stage 2 (DCN): grouped allgather over ``cross_groups()`` (one peer
    per host, group rows in host order) → an (H, m, k) winner stack →
    one (±distance, host-block position) lexicographic select over the
    ``H·k``-wide concatenation. Surrogate positions ``h·k + j`` induce
    the flat merge's global-position order (module docstring), so the
    output is bit-identical to every flat engine, replica-identical on
    all p shards. D == 1 degenerates to the pure DCN fold; H == 1 is
    rejected by resolve_engine (single-host meshes never route here).
    """
    from ..comms import AxisComms

    H, D = topology.n_hosts, topology.devs_per_host
    p = topology.n_shards
    if D > 1:
        ici = AxisComms(axis, size=p, groups=topology.host_groups())
        hd, hg = _ring_xla(d, gid, k, select_min, ici)
    else:
        hd, hg = d, gid
    dcn = AxisComms(axis, size=p, groups=topology.cross_groups())
    all_d = dcn.allgather(hd)                      # (H, m, k), host order
    all_g = dcn.allgather(hg)
    m = d.shape[0]
    dd = jnp.transpose(all_d, (1, 0, 2)).reshape(m, H * k)
    gg = jnp.transpose(all_g, (1, 0, 2)).reshape(m, H * k)
    kd = dd if select_min else -dd
    pos = jnp.broadcast_to(jnp.arange(H * k, dtype=jnp.int32), (m, H * k))
    _, _, gid2, dd2 = _lex_topk(kd, pos, gg, dd, k)
    return dd2, gid2


# --------------------------------------------------------------------------
# Pallas ring kernel (TPU): VMEM-resident candidates, remote DMA hops
# --------------------------------------------------------------------------

def _vmem_fold(cd, cp, cg, k: int, kp: int, extra=()):
    """The in-kernel fold: k (min-value, then min-position) extraction
    passes over a (m, w) candidate plane — the KPASS pattern with an
    explicit position plane as the tie key, so ties retire in the same
    lowest-column order ``select_k`` uses. Mosaic has no sort, so the
    ``lax.sort`` fold is re-expressed as masked min-reductions.

    ``extra``: optional int32 payload planes (same (m, w) shape) carried
    through the fold — each output slot gets the payload of the cell it
    extracted (the CAGRA megakernel rides its explored flags here).
    Returns ``(d, pos, gid, *extras)``."""
    m = cd.shape[0]
    lane = lax.broadcasted_iota(jnp.int32, (m, kp), 1)

    def extract(t, state):
        alive, nd, npos, ng = state[:4]
        nex = state[4:]
        masked = jnp.where(alive, cd, jnp.inf)
        best = jnp.min(masked, axis=1, keepdims=True)
        cand = alive & (masked <= best)
        bpos = jnp.min(jnp.where(cand, cp, _INT_BIG), axis=1, keepdims=True)
        at = cand & (cp == bpos)
        # position uniqueness makes `at` single-cell among real
        # candidates, so a min-select extracts its gid; the sentinel must
        # exceed any legal global id (+inf pads share pos and select
        # their -1 gid together — the pad convention either way)
        g = jnp.min(jnp.where(at, cg, jnp.iinfo(jnp.int32).max), axis=1,
                    keepdims=True)
        hit = lane == t
        exs = tuple(
            jnp.where(hit,
                      jnp.min(jnp.where(at, ce, jnp.iinfo(jnp.int32).max),
                              axis=1, keepdims=True), ne)
            for ce, ne in zip(extra, nex))
        return (alive & ~at, jnp.where(hit, best, nd),
                jnp.where(hit, bpos, npos), jnp.where(hit, g, ng)) + exs

    state = (jnp.ones(cd.shape, jnp.bool_),
             jnp.full((m, kp), jnp.inf, jnp.float32),
             jnp.full((m, kp), _INT_BIG, jnp.int32),
             jnp.full((m, kp), -1, jnp.int32))
    state = state + tuple(jnp.zeros((m, kp), jnp.int32) for _ in extra)
    if k <= 32:
        for t in range(k):
            state = extract(t, state)
    else:
        state = lax.fori_loop(0, k, extract, state)
    return (state[1], state[2], state[3]) + tuple(state[4:])


def _merge_step_kernel(rd_ref, rp_ref, rg_ref, bd_ref, bp_ref, bg_ref,
                      od_ref, op_ref, og_ref, *, k: int, kp: int):
    cd = jnp.concatenate([rd_ref[...], bd_ref[...]], axis=1)
    cp = jnp.concatenate([rp_ref[...], bp_ref[...]], axis=1)
    cg = jnp.concatenate([rg_ref[...], bg_ref[...]], axis=1)
    od_ref[...], op_ref[...], og_ref[...] = _vmem_fold(cd, cp, cg, k, kp)


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def _merge_step_pallas(rd, rp, rg, bd, bp, bg, k: int, interpret: bool):
    """Standalone pallas_call around the VMEM fold (the unit the
    interpret-mode tier-1 test pins against the XLA fold)."""
    from jax.experimental import pallas as pl

    from ..utils import round_up_to

    m, w1 = rd.shape
    w2 = bd.shape[1]
    mp = round_up_to(m, 8)
    kp = round_up_to(k, 128)

    def pad(x, fill):
        return jnp.pad(x, ((0, mp - m), (0, 0)), constant_values=fill)

    args = [pad(rd.astype(jnp.float32), jnp.inf),
            pad(rp, _INT_BIG), pad(rg, -1),
            pad(bd.astype(jnp.float32), jnp.inf),
            pad(bp, _INT_BIG), pad(bg, -1)]
    out = pl.pallas_call(
        functools.partial(_merge_step_kernel, k=k, kp=kp),
        out_shape=[jax.ShapeDtypeStruct((mp, kp), jnp.float32),
                   jax.ShapeDtypeStruct((mp, kp), jnp.int32),
                   jax.ShapeDtypeStruct((mp, kp), jnp.int32)],
        interpret=interpret,
    )(*args)
    return tuple(o[:m, :k] for o in out)


def _ring_kernel(d_ref, g_ref, od_ref, og_ref, comm_d, comm_g, run_d,
                 run_p, run_g, send_sems, recv_sems, capacity_sem, *,
                 axis: str, p: int, k: int, kp: int):
    """The device-resident ring: one kernel instance per shard under
    ``shard_map``; p−1 double-buffered remote-DMA hops with the VMEM
    fold on arrival.

    Slot discipline (the semaphore-signalled double buffering): hop h
    writes the right neighbor's slot h%2; a slot written at hop h is
    consumed locally by the hop-h fold and re-read as the hop-(h+1)
    forward source, so it is free for the writer's hop-(h+2) reuse only
    after the hop-(h+1) send completes — at which point this shard
    signals one credit to its LEFT neighbor (the writer), and every
    send from hop 2 on first waits one credit. The opening barrier
    keeps a fast neighbor from writing before this kernel is live."""
    from jax.experimental.pallas import tpu as pltpu

    my_id = lax.axis_index(axis)
    right = lax.rem(my_id + 1, p)
    left = lax.rem(my_id + p - 1, p)

    barrier = pltpu.get_barrier_semaphore()
    for nb in (left, right):
        pltpu.semaphore_signal(barrier, inc=1, device_id=(nb,),
                               device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)

    m = d_ref.shape[0]
    lane = lax.broadcasted_iota(jnp.int32, (m, kp), 1)
    # local block, position-stamped: shard s's slot j is concat column
    # s·k + j; kp-pad lanes carry (+inf, INT_BIG, -1) so they lose every
    # comparison (they are exactly the allgather pad convention)
    run_d[...] = d_ref[...]
    run_p[...] = jnp.where(lane < k, my_id.astype(jnp.int32) * k + lane,
                           _INT_BIG)
    run_g[...] = g_ref[...]

    for h in range(p - 1):
        slot = h % 2
        if h >= 2:
            pltpu.semaphore_wait(capacity_sem, 1)
        src_d = d_ref if h == 0 else comm_d.at[(h - 1) % 2]
        src_g = g_ref if h == 0 else comm_g.at[(h - 1) % 2]
        rdma_d = pltpu.make_async_remote_copy(
            src_ref=src_d, dst_ref=comm_d.at[slot],
            send_sem=send_sems.at[0], recv_sem=recv_sems.at[0],
            device_id=(right,), device_id_type=pltpu.DeviceIdType.MESH)
        rdma_g = pltpu.make_async_remote_copy(
            src_ref=src_g, dst_ref=comm_g.at[slot],
            send_sem=send_sems.at[1], recv_sem=recv_sems.at[1],
            device_id=(right,), device_id_type=pltpu.DeviceIdType.MESH)
        rdma_d.start()
        rdma_g.start()
        rdma_d.wait()        # send read done AND this hop's block landed
        rdma_g.wait()
        if h >= 1:
            # the hop-(h−1) slot is now fully consumed (folded at h−1,
            # forwarded just above): credit its writer
            pltpu.semaphore_signal(capacity_sem, inc=1, device_id=(left,),
                                   device_id_type=pltpu.DeviceIdType.MESH)
        src = lax.rem(my_id - (h + 1) + p * (h + 1), p).astype(jnp.int32)
        blk_p = jnp.where(lane < k, src * k + lane, _INT_BIG)
        nd, npos, ng = _vmem_fold(
            jnp.concatenate([run_d[...], comm_d[slot]], axis=1),
            jnp.concatenate([run_p[...], blk_p], axis=1),
            jnp.concatenate([run_g[...], comm_g[slot]], axis=1), k, kp)
        run_d[...], run_p[...], run_g[...] = nd, npos, ng

    od_ref[...] = run_d[...]
    og_ref[...] = run_g[...]


def _ring_pallas(d, gid, k: int, select_min: bool, axis: str, p: int):
    """The TPU ring engine, called per shard inside ``shard_map``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ..utils import round_up_to

    m = d.shape[0]
    mp = round_up_to(max(m, 1), 8)
    kp = round_up_to(k, 128)
    kd = d.astype(jnp.float32) if select_min else -d.astype(jnp.float32)
    kd = jnp.pad(kd, ((0, mp - m), (0, kp - k)), constant_values=jnp.inf)
    g = jnp.pad(gid, ((0, mp - m), (0, kp - k)), constant_values=-1)

    out_d, out_g = pl.pallas_call(
        functools.partial(_ring_kernel, axis=axis, p=p, k=k, kp=kp),
        out_shape=[jax.ShapeDtypeStruct((mp, kp), jnp.float32),
                   jax.ShapeDtypeStruct((mp, kp), jnp.int32)],
        scratch_shapes=[
            pltpu.VMEM((2, mp, kp), jnp.float32),   # comm slots: distances
            pltpu.VMEM((2, mp, kp), jnp.int32),     # comm slots: ids
            pltpu.VMEM((mp, kp), jnp.float32),      # running top-k: key
            pltpu.VMEM((mp, kp), jnp.int32),        # running top-k: position
            pltpu.VMEM((mp, kp), jnp.int32),        # running top-k: gid
            pltpu.SemaphoreType.DMA((2,)),          # send sems (d, gid)
            pltpu.SemaphoreType.DMA((2,)),          # recv sems (d, gid)
            pltpu.SemaphoreType.REGULAR,            # slot-free credits
        ],
        compiler_params=pltpu.TPUCompilerParams(collective_id=7),
    )(kd, g)
    out_d = out_d[:m, :k]
    return (out_d if select_min else -out_d), out_g[:m, :k]


# --------------------------------------------------------------------------
# dispatch
# --------------------------------------------------------------------------

def merge(d: jax.Array, gid: jax.Array, k: int, select_min: bool,
          comms=None, axis: str = "shard", axis_size: Optional[int] = None,
          engine: str = "allgather", topology=None
          ) -> Tuple[jax.Array, jax.Array]:
    """Cross-shard top-k merge, called per shard INSIDE ``shard_map``.

    ``d``/``gid``: this shard's (m, k) local candidates — distances and
    GLOBAL row ids, dead-shard rows already masked to (±inf, −1).
    Returns the replica-identical merged (m, k) lists, bit-identical
    across engines (module docstring). ``comms``: an
    :class:`~raft_tpu.comms.AxisComms`-shaped communicator; built over
    ``axis``/``axis_size`` when absent. ``ring_pallas`` ignores comms
    subgroups and requires a plain 1-D mesh axis. ``engine="hier"``
    requires ``topology`` (a host-major
    :class:`~raft_tpu.parallel.topology.Topology` matching the mesh
    axis) and builds its own grouped communicators from it."""
    from ..comms import AxisComms

    expects(engine in ALL_ENGINES, "unknown sharded merge engine %r", engine)
    if engine == "hier":
        expects(topology is not None,
                "engine='hier' needs a topology (parallel.topology)")
        expects(axis_size is None or int(axis_size) == topology.n_shards,
                "hier merge: axis_size %s != topology shards %d",
                axis_size, topology.n_shards)
        return _hier(d, gid, k, select_min, axis, topology)
    if comms is None:
        expects(axis_size is not None,
                "merge needs a comms object or an explicit axis_size")
        comms = AxisComms(axis, size=axis_size)
    if engine == "ring":
        return _ring_xla(d, gid, k, select_min, comms)
    if engine == "ring_pallas":
        p = axis_size if axis_size is not None else comms.get_size()
        return _ring_pallas(d, gid, k, select_min, axis, int(p))
    from ..neighbors import brute_force

    all_d = comms.allgather(d)
    all_i = comms.allgather(gid)
    return brute_force.knn_merge_parts(all_d, all_i, select_min)


# family -> merge engine that ACTUALLY served the most recent sharded
# search in this process (fallbacks overwrite the resolved engine), the
# ops surface debugz reads through sharded_ann.ops_snapshot
active_engines: dict = {}


def note_engine(family: str, engine: str) -> None:
    active_engines[family] = engine


def note_fallback(family: str) -> None:
    """A ring-engine call was served by the allgather fallback (guarded
    demotion or injected fault): record it for the ops surface."""
    active_engines[family] = "allgather"
    try:
        from ..serve import metrics as _metrics

        _metrics.counter("sharded.ring.demotions").inc()
    except Exception:  # noqa: BLE001 - telemetry must not fail a search
        pass


def guarded_dispatch(family: str, engine: str, run):
    """THE dispatch contract for every sharded merge caller
    (sharded_ann's chokepoint and sharded_knn.search): record the
    engine for the ops surface, run ``run(engine)``, and gate ring
    engines behind ``guarded_call(MERGE_SITE)`` with the bit-identical
    allgather program — fallback serves reported via
    :func:`note_fallback`. ``run``: engine name → merged results
    (typically dispatching a freshly built ``shard_map`` program)."""
    note_engine(family, engine)
    if engine == "allgather":
        return run("allgather")
    from .guarded import guarded_call

    def fallback():
        note_fallback(family)
        return run("allgather")

    return guarded_call(MERGE_SITE, lambda: run(engine), fallback)


def _mesh_device(mesh_or_device):
    """First device of the SEARCH mesh — engine capability and autotune
    keys must follow the mesh actually searched, not the process default
    backend (a CPU emulation mesh on a TPU host must not resolve to the
    TPU-only remote-DMA kernel, and its measurements must not steer TPU
    buckets)."""
    if mesh_or_device is None:
        return jax.devices()[0]
    devs = getattr(mesh_or_device, "devices", None)
    return devs.flat[0] if devs is not None else mesh_or_device


def ring_capable(m: int, k: int, backend: Optional[str] = None) -> bool:
    """Whether the Pallas ring kernel can run this shape: a real TPU
    (remote DMA has no interpret emulation on this jax) and the
    full-residency VMEM budget. ``backend``: the SEARCH mesh's platform
    (defaults to the process backend)."""
    from ..utils import round_up_to

    backend = backend or jax.default_backend()
    cells = round_up_to(max(m, 1), 8) * round_up_to(k, 128)
    return backend == "tpu" and cells <= _VMEM_CELL_CAP


def _bucket(m: int, k: int, p: int, dtype, mesh=None) -> str:
    from . import autotune

    dev = _mesh_device(mesh)
    kind = getattr(dev, "device_kind", dev.platform).replace(" ", "_")
    return autotune.shape_bucket("sharded_merge", m=m, k=k, p=p,
                                 dt=str(jnp.dtype(dtype)),
                                 mesh=f"{dev.platform}-{kind}")


def resolve_engine(m: int, k: int, p: int, dtype=jnp.float32,
                   override: Optional[str] = None,
                   plain_axis: bool = True, mesh=None,
                   topology=None) -> str:
    """Pick the merge engine for one sharded search call.

    Order: explicit ``override`` (search param) → ``RAFT_TPU_SHARDED_MERGE``
    env → the measured autotune verdict for this (m, k, p, dtype) bucket
    (mesh-aware: the bucket key carries the SEARCH mesh's platform/kind
    and p) → backend default (the ring kernel when the mesh is TPU and
    the shape fits VMEM, allgather elsewhere — the CPU emulation mesh
    serializes ring hops, so allgather stays its default).
    ``plain_axis=False`` (an injected communicator with subgroups)
    forces allgather: the ring engines permute over the raw mesh axis.
    ``mesh``: the mesh (or a device) the search runs on; defaults to the
    process default device.

    ``topology``: a :class:`~raft_tpu.parallel.topology.Topology` when
    the mesh spans hosts. A MULTI-host topology adds a tier above the
    autotune bucket: override/env still win (``ring_pallas`` demotes to
    ``hier`` — remote-DMA ring hops must not cross DCN), otherwise
    ``hier`` — flat-bucket verdicts were measured within one host and
    say nothing about DCN cost. ``topology=None`` or a single-host
    topology leaves this function's pre-existing behavior untouched
    (the byte-for-byte single-host guarantee)."""
    platform = _mesh_device(mesh).platform
    if not plain_axis or p <= 1:
        return "allgather"
    if topology is not None and topology.multi_host:
        expects(p == topology.n_shards,
                "resolve_engine: p=%d != topology shards %d", p,
                topology.n_shards)
        eng = override or os.environ.get("RAFT_TPU_SHARDED_MERGE") or None
        if eng is not None:
            eng = str(eng).lower()
            expects(eng in ALL_ENGINES + ("auto",),
                    "unknown sharded merge engine %r (env/param); one of %s",
                    eng, ALL_ENGINES + ("auto",))
            if eng == "ring_pallas":
                return "hier"
            if eng != "auto":
                return eng
        return "hier"
    eng = override or os.environ.get("RAFT_TPU_SHARDED_MERGE") or None
    if eng is not None:
        eng = str(eng).lower()
        expects(eng in ENGINES + ("auto",),
                "unknown sharded merge engine %r (env/param); one of %s",
                eng, ENGINES + ("auto",))
        if eng != "auto":
            if eng == "ring_pallas" and not ring_capable(m, k, platform):
                return "ring"
            return eng
    from . import autotune

    hit = autotune.lookup(_bucket(m, k, p, dtype, mesh))
    if hit in ENGINES:
        if hit == "ring_pallas" and not ring_capable(m, k, platform):
            return "ring"
        return hit
    if ring_capable(m, k, platform):
        return "ring_pallas"
    return "allgather"


def tune_merge(mesh, m: int, k: int, select_min: bool = True,
               axis: str = "shard", reps: int = 5, engines=None):
    """Race the merge engines on this mesh for a (m, k) candidate shape
    and record the winner under the dtype/mesh-aware bucket — the
    decision ``resolve_engine`` (and through it every
    ``make_searcher`` sharded closure) picks up. Returns
    (winner, {engine: median_s}). Eager only."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils import shard_map_compat
    from . import autotune

    p = mesh.shape[axis]
    rng = np.random.default_rng(0)
    d = jnp.asarray(rng.standard_normal((p, m, k)).astype(np.float32))
    d = jnp.sort(d, axis=-1) if select_min else -jnp.sort(d, axis=-1)
    gid = jnp.arange(p * m * k, dtype=jnp.int32).reshape(p, m, k)
    dd = jax.device_put(d, NamedSharding(mesh, P(axis, None, None)))
    gg = jax.device_put(gid, NamedSharding(mesh, P(axis, None, None)))

    names = engines or [
        e for e in ENGINES if e != "ring_pallas"
        or ring_capable(m, k, _mesh_device(mesh).platform)]

    def make(eng):
        def body(ds, gs):
            return merge(ds[0], gs[0], k, select_min, axis=axis,
                         axis_size=p, engine=eng)
        return jax.jit(shard_map_compat(
            body, mesh=mesh, in_specs=(P(axis, None, None),) * 2,
            out_specs=(P(), P()), check=False))

    cands = {eng: make(eng) for eng in names}
    return autotune.tune_best(_bucket(m, k, p, jnp.float32, mesh), cands,
                              dd, gg, reps=reps, force=True)
