"""Measurement-driven engine/tile selection.

The reference hard-codes per-arch dispatch heuristics (e.g.
``choose_select_k_algorithm``, matrix/detail/select_k-inl.cuh:48-72, and the
ivf_pq kernel-variant table, detail/ivf_pq_search.cuh:615-676) tuned offline
per GPU generation. A TPU deployment sees far more variance — chip
generation, VMEM size, and (under remote tunnels) effective dispatch cost
all move the crossovers — so raft_tpu picks engines by *measuring them on
the device actually in use* and caching the winner.

Methodology note: each candidate is timed with a ``block_until_ready`` per
call (some backends elide dead dispatches, so blocking once after N calls
under-reports by orders of magnitude) and the median of several calls is
used. Winners are cached in-process and, when ``RAFT_TPU_AUTOTUNE_CACHE``
names a JSON file (or the default per-user cache path is writable), across
processes.

Nothing autotunes implicitly under ``jit`` tracing: callers consult
``lookup`` (cache-only, never measures) on traced values and expose an
explicit ``tune``/warmup entry point for eager callers and the bench.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, Mapping, Optional, Sequence, Tuple

import jax

from ..core import logging as rlog

__all__ = ["shape_bucket", "lookup", "record", "forget", "entries",
           "measure", "measure_throughput", "measure_value_read_wall",
           "tune_best", "cache_path", "load_cache", "save_cache",
           "TimingUnreliableError"]


class TimingUnreliableError(RuntimeError):
    """Both the original and a freshly-compiled executable timed below
    the physical plausibility floor: the backend window is lying and no
    honest number exists. Callers should skip the measurement rather
    than record an impossible one."""

_MEM_CACHE: Dict[str, str] = {}
# keys recorded with persist=False (guard demotions): NEVER written to
# disk, even when a later ordinary record() triggers save_cache()
_EPHEMERAL: set = set()
_DISK_LOADED = False

# count of plausibility-floor trips (see measure); benches report it so
# a recorded number can be traced to a defended measurement window
suspect_events = 0


def cache_path() -> Optional[str]:
    """Resolve the on-disk cache location (None disables persistence)."""
    p = os.environ.get("RAFT_TPU_AUTOTUNE_CACHE")
    if p == "":
        return None
    if p:
        return p
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "raft_tpu", "autotune.json")


def load_cache() -> None:
    global _DISK_LOADED
    if _DISK_LOADED:
        return
    _DISK_LOADED = True
    p = cache_path()
    if not p or not os.path.exists(p):
        return
    try:
        with open(p) as f:
            disk = json.load(f)
        for k, v in disk.items():
            _MEM_CACHE.setdefault(k, v)
    except (OSError, ValueError) as e:
        rlog.log_warn("autotune cache %s unreadable: %s", p, e)


def save_cache() -> None:
    p = cache_path()
    if not p:
        return
    try:
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + f".tmp{os.getpid()}"
        durable = {k: v for k, v in _MEM_CACHE.items()
                   if k not in _EPHEMERAL}
        with open(tmp, "w") as f:
            json.dump(durable, f, indent=1, sort_keys=True)
        os.replace(tmp, p)
    except OSError as e:
        rlog.log_warn("autotune cache %s unwritable: %s", p, e)


def _log2_bucket(x: int) -> int:
    return max(0, int(x - 1).bit_length())


def shape_bucket(family: str, **dims) -> str:
    """Cache key: backend + device kind + family + log2-bucketed dims.

    Integer dims bucket by log2; string values pass through verbatim as
    categorical tags (e.g. the brute-force race keys on the corpus
    storage dtype — ``store='bfloat16'`` — because HBM-traffic-bound
    crossovers move with the element width, and a winner measured for
    one storage mode must not steer another's dispatch)."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform).replace(" ", "_")
    parts = [dev.platform, kind, family]
    parts += [f"{name}{_log2_bucket(v) if isinstance(v, int) else v}"
              for name, v in sorted(dims.items())]
    return ":".join(parts)


def lookup(key: str) -> Optional[str]:
    """Cache-only lookup; safe to call from trace time. Never measures."""
    load_cache()
    return _MEM_CACHE.get(key)


def record(key: str, choice: str, persist: bool = True) -> None:
    """Record a winner. ``persist=False`` keeps the entry in-process only
    (used for guard demotions from transient failures that must not
    poison later processes through the disk cache) — such keys are also
    excluded from every later ``save_cache`` dump."""
    load_cache()
    _MEM_CACHE[key] = choice
    if persist:
        _EPHEMERAL.discard(key)
        save_cache()
    else:
        _EPHEMERAL.add(key)
    if ":guard:" not in key:
        # flight recorder: race verdicts steer future dispatch, so they
        # are operational events (guard demotions already record their
        # own richer guarded_demotion event — skip the double entry)
        try:
            from ..core import events as _events

            _events.record("autotune_verdict", key, choice=choice,
                           persist=persist)
        except Exception:  # noqa: BLE001 - telemetry must not break tuning
            pass


def entries() -> Dict[str, str]:
    """Point-in-time copy of every cached verdict (engine race winners
    AND guard demotions) — the debugz verdict table."""
    load_cache()
    return dict(_MEM_CACHE)


def forget(key: str) -> None:
    """Drop an entry (guard reset / test isolation). A durable (persisted)
    entry also rewrites the disk cache — an operator re-arming a demoted
    site must not have the stale demotion resurrected by the next
    process's load_cache."""
    was_durable = key in _MEM_CACHE and key not in _EPHEMERAL
    _MEM_CACHE.pop(key, None)
    _EPHEMERAL.discard(key)
    if was_durable:
        save_cache()


def _value_read(out) -> None:
    """Force a host-side value read of the output: some backends lie
    about ``block_until_ready`` itself (buffers report ready before the
    compute ran), and only a host value transitively dependent on the
    output is proof of completion. Costs one tiny dispatch + round trip."""
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(out)
              if isinstance(l, jax.Array)]
    if leaves:
        x = leaves[0].ravel()[:1].astype(jnp.float32)
        float(jnp.where(jnp.isfinite(x), x, 0.0)[0])


def _timed_reps(fn: Callable, args, reps: int, out0, value_read=False):
    import jax.numpy as jnp

    out = out0
    first = args[0] if args else None
    can_vary = (isinstance(first, jax.Array)
                and jnp.issubdtype(first.dtype, jnp.inexact))

    ts = []
    for r in range(reps):
        if can_vary:
            a0 = _perturbed(first, out, r)
            # settle the perturbation ops before the timed window opens:
            # for microsecond-scale probes the 3-4 eager ops building a0
            # would otherwise still be in flight at t0
            jax.block_until_ready(a0)
            args_r = (a0,) + args[1:]
        else:
            args_r = args
        t0 = time.perf_counter()
        out = fn(*args_r)
        jax.block_until_ready(out)
        if value_read:
            _value_read(out)
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def measure(fn: Callable, *args, reps: int = 5, out0=None,
            suspect_floor_s: float = 0.0,
            value_read: bool = False) -> float:
    """Median seconds per call, one blocking sync per call (see module
    docstring for why per-call blocking is load-bearing).

    Each rep scales the first float-array argument by a distinct factor
    a few ulps above 1 (dtype-aware — an additive 1e-6 would round away
    entirely for bf16 or large-magnitude f32) AND adds a *real* (nonzero,
    1e-12-scaled) dependency on the previous rep's output — a `* 0` chain
    could be shortcut by a value-analyzing backend: tunneled backends
    have been observed serving value-identical replays from a result
    cache (a 150 ms search "measuring" 0.1 ms on later reps), and the
    chain + perturb makes every rep distinct, ordered, real work.

    ``out0``: pre-warmed output of ``fn(*args)`` — pass it to skip the
    internal warmup call when the caller already compiled+ran ``fn``.

    ``suspect_floor_s``: physical-plausibility floor. The tunnel has a
    second lying mode where even value-distinct chained dispatches return
    "done" in ~50 us. Defense: when the median lands below the floor,
    ``fn`` is re-wrapped in a new outer ``jax.jit`` (fresh executable,
    compilation cache disabled) and re-measured. If the fresh median is
    credible, the larger median is returned; if it is ALSO below the
    floor — or the fresh compile itself fails while the original median
    is suspect — ``TimingUnreliableError`` is raised: no honest number
    exists and callers must skip the measurement. 0 disables the check.
    Callers set the floor to a lower bound no real call of theirs could
    beat (e.g. milliseconds for a 10k-query search batch).
    """
    if out0 is None:
        out0 = fn(*args)
        jax.block_until_ready(out0)      # compile + warm

    med = _timed_reps(fn, args, reps, out0, value_read=value_read)
    if suspect_floor_s and med < suspect_floor_s:
        global suspect_events
        suspect_events += 1
        rlog.log_warn(
            "measure: median %.3g s below plausibility floor %.3g s — "
            "re-measuring through a fresh executable (tunnel replay mode)",
            med, suspect_floor_s)
        # the fresh compile must NOT be served from the persistent
        # compilation cache: a cache hit would hand back the very
        # executable whose timing is under suspicion
        cache_dir = jax.config.jax_compilation_cache_dir
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            fresh = _fresh_executable(fn)
            out0 = fresh(*args)
            jax.block_until_ready(out0)      # fresh compile + warm
            med2 = _timed_reps(fresh, args, reps, out0,
                               value_read=value_read)
        except Exception as e:  # noqa: BLE001 - compile died / not re-jittable
            # classify as unreliable (cause chained): the suspect median
            # already tripped the floor, and retrying a fresh compile in
            # a degraded window costs minutes per attempt — callers'
            # lying-window fallbacks (tune_best) and no-retry policy
            # (median_time) are the right response, not flake retries
            raise TimingUnreliableError(
                f"median {med:.3g}s below plausibility floor and the "
                f"fresh-executable re-measure failed ({e})") from e
        finally:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        if med2 < suspect_floor_s:
            # recording nothing beats recording an impossible number
            # (252M QPS has been observed surviving the fresh compile)
            raise TimingUnreliableError(
                f"median {med2:.3g}s below plausibility floor "
                f"{suspect_floor_s:.3g}s even on a fresh executable")
        med = max(med, med2)
    return med


def _perturbed(first, out_prev, r: int):
    """Next-rep first argument: a few ulps of multiplicative variation per
    rep plus a real (nonzero, tiny-scaled) dependency on the previous
    output — every rep is distinct, ordered, uncacheable work (see
    ``measure``)."""
    import jax.numpy as jnp

    ulp = float(jnp.finfo(first.dtype).eps)
    a0 = first * jnp.asarray(1 + (r + 1) * 4 * ulp, first.dtype)
    leaves = jax.tree_util.tree_leaves(out_prev)
    if leaves and isinstance(leaves[0], jax.Array):
        dep = leaves[0].ravel()[0]
        depf = jnp.where(jnp.isfinite(dep), dep, 0).astype(jnp.float32)
        sgn = jnp.sign(depf) + (depf == 0)
        a0 = a0 + (sgn * (4 * float(jnp.finfo(first.dtype).tiny))
                   ).astype(first.dtype)
    return a0


class JitArgFn:
    """``tune_best`` candidate wrapper for engines whose jitted callable
    takes a large operand (an index pytree) as a jit ARGUMENT —
    closure-baking it would trace the arrays into the HLO as constants
    and blow the tunnel's remote-compile request limit at memory scale.
    Implements the ``fresh_executable`` protocol by re-wrapping the
    fitted callable in a new outer jit with the operand still passed as
    an argument."""

    def __init__(self, fitted: Callable, arg):
        self._f = fitted
        self._arg = arg

    def __call__(self, qq):
        return self._f(qq, self._arg)

    def fresh_executable(self) -> "JitArgFn":
        inner = self._f
        return JitArgFn(jax.jit(lambda qq, a: inner(qq, a)), self._arg)


def _fresh_executable(fn: Callable) -> Callable:
    """A callable backed by a freshly-compiled executable.

    Default: re-wrap in a new outer ``jax.jit``. Callables that hold
    large device arrays in Python closures (e.g. a multi-part search
    wrapper holding 500k-row indexes) MUST NOT be traced that way —
    tracing would bake the arrays into the HLO as constants and blow the
    tunnel's remote-compile request limit (observed HTTP 413 at 500k
    rows). Such callables expose ``fresh_executable()`` returning an
    equivalent wrapper whose inner jits are freshly re-wrapped with the
    arrays still passed as jit *arguments*."""
    hook = getattr(fn, "fresh_executable", None)
    if hook is not None:
        return hook()
    return jax.jit(lambda *a: fn(*a))


def measure_throughput(fn: Callable, *args, depth: int = 6, reps: int = 3,
                       out0=None, suspect_floor_s: float = 0.0) -> float:
    """Steady-state seconds per call with ``depth`` in-flight calls.

    ``measure`` blocks once per call, so through a remote tunnel every
    call pays the full dispatch round trip (~90 ms observed) — that is a
    *latency* number. Serving systems and the reference harness measure
    *throughput*: Google Benchmark's ``items_per_second`` runs iterations
    back-to-back with one wall clock around the whole loop
    (cpp/bench/ann/src/common/benchmark.hpp:337). This does the same:
    ``depth`` calls are enqueued with only the final output blocked, so
    dispatch overlaps device compute.

    Elision/replay defenses carry over from ``measure``: every call's
    first float-array argument is perturbed by a distinct ulp factor AND
    carries a real data dependency on the *previous call's output* — the
    chain forces ordering, makes each dispatch value-distinct, and means
    blocking the last output transitively waits for all of them.

    ``suspect_floor_s`` is a per-call plausibility floor as in
    ``measure`` (compared against wall/depth); a trip re-measures through
    a fresh executable and raises :class:`TimingUnreliableError` when the
    backend window is lying. Returns median-of-``reps`` seconds per call.

    CAVEAT: on backends whose lying extends to ``block_until_ready``
    itself (buffers reporting ready before compute ran — observed on the
    axon tunnel), this can still under-report; a recorded benchmark
    should close its window with a host-side VALUE read of a scalar
    dependent on every output (see bench.py ``measure_wall``, the
    recorded-QPS methodology there).
    """
    import jax.numpy as jnp

    if out0 is None:
        out0 = fn(*args)
        jax.block_until_ready(out0)      # compile + warm

    first = args[0] if args else None
    can_vary = (isinstance(first, jax.Array)
                and jnp.issubdtype(first.dtype, jnp.inexact))

    def run_window(f, out_prev, base):
        # the perturbation counter spans windows: restarting it per
        # window would make window 2+ bitwise replays of window 1, and
        # the replay-caching backend would serve them in ~50 us
        t0 = time.perf_counter()
        out = out_prev
        for r in range(depth):
            if can_vary:
                a0 = _perturbed(first, out, base + r)
                out = f(a0, *args[1:])
            else:
                out = f(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / depth, out

    ts = []
    out = out0
    for w in range(reps):
        dt, out = run_window(fn, out, w * depth)
        ts.append(dt)
    ts.sort()
    med = ts[len(ts) // 2]
    if suspect_floor_s and med < suspect_floor_s:
        global suspect_events
        suspect_events += 1
        rlog.log_warn(
            "measure_throughput: %.3g s/call below plausibility floor "
            "%.3g s — re-measuring through a fresh executable", med,
            suspect_floor_s)
        cache_dir = jax.config.jax_compilation_cache_dir
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            fresh = _fresh_executable(fn)
            outf = fresh(*args)
            jax.block_until_ready(outf)
            ts2 = []
            for w in range(reps):
                dt, outf = run_window(fresh, outf, (reps + w) * depth)
                ts2.append(dt)
            ts2.sort()
            med2 = ts2[len(ts2) // 2]
        except Exception as e:  # noqa: BLE001
            raise TimingUnreliableError(
                f"throughput {med:.3g}s/call below plausibility floor and "
                f"the fresh-executable re-measure failed ({e})") from e
        finally:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        if med2 < suspect_floor_s:
            raise TimingUnreliableError(
                f"throughput {med2:.3g}s/call below plausibility floor "
                f"{suspect_floor_s:.3g}s even on a fresh executable")
        med = max(med, med2)
    return med


def measure_value_read_wall(fn: Callable, inputs: Sequence, *args,
                            warm_input=None) -> float:
    """Wall seconds/call over ``inputs`` with a VALUE-READ close.

    The strongest timing this library has against lying backends: each
    call gets a genuinely different first input, calls are dispatched
    back-to-back (dispatch overlaps compute), the FIRST array leaf of
    every output folds into a scalar accumulator, and the window closes
    with a host ``float()`` of that accumulator — which cannot
    materialize before the compute feeding those leaves ran
    (readiness-level lies included; see bench.py's methodology notes).
    NOTE the guarantee covers the dependency chain of each output's
    first leaf; when ``fn`` is one jitted executable (the usual case)
    that is the whole program, but outputs assembled from several
    independent dispatches are only partially pinned. Pass
    ``warm_input`` (a throwaway input NOT in ``inputs``) to warm/compile
    outside the window so no timed call repeats content the backend has
    already served.
    """
    import jax.numpy as jnp

    def fold(out):
        leaves = [l for l in jax.tree_util.tree_leaves(out)
                  if isinstance(l, jax.Array)]
        x = leaves[0].ravel()[:1].astype(jnp.float32)
        return jnp.where(jnp.isfinite(x), x, 0.0)[0]

    if warm_input is not None:
        float(fold(fn(warm_input, *args)))
    t0 = time.perf_counter()
    acc = None
    for inp in inputs:
        s = fold(fn(inp, *args))
        acc = s if acc is None else acc + s
    _ = float(acc)
    return (time.perf_counter() - t0) / len(inputs)


def tune_best(key: str, candidates: Mapping[str, Callable], *args,
              reps: int = 5,
              force: bool = False,
              suspect_floor_s: float = 0.0,
              value_read: bool = False) -> Tuple[str, Dict[str, float]]:
    """Measure every candidate on device, record + return the winner.

    Returns (winner name, {name: median seconds}). Failures (e.g. a kernel
    whose constraints reject the shape) disqualify that candidate. When no
    candidate produced an honest timing but at least one was merely
    unmeasurable (TimingUnreliableError — a lying backend window), the
    first such working candidate is returned uncached; when every
    candidate genuinely failed, RuntimeError is raised.
    """
    if not force:
        hit = lookup(key)
        if hit in candidates:
            return hit, {}
    timings: Dict[str, float] = {}
    unreliable_names: list = []
    for name, fn in candidates.items():
        try:
            timings[name] = measure(fn, *args, reps=reps,
                                    suspect_floor_s=suspect_floor_s,
                                    value_read=value_read)
        except TimingUnreliableError as e:
            unreliable_names.append(name)
            rlog.log_warn("autotune %s: candidate %s unmeasurable: %s",
                          key, name, e)
        except Exception as e:  # noqa: BLE001 - any engine failure = skip
            rlog.log_warn("autotune %s: candidate %s failed: %s", key, name, e)
    if not timings:
        if unreliable_names:
            # at least one engine WORKS but the backend window lies about
            # its timing: fall back to the first such candidate WITHOUT
            # caching, so a later honest window re-measures (genuinely
            # failing candidates are never the fallback)
            fallback = unreliable_names[0]
            rlog.log_warn("autotune %s: no measurable candidate (lying "
                          "window); defaulting to %r (not cached)",
                          key, fallback)
            return fallback, {}
        raise RuntimeError(f"autotune {key}: every candidate failed")
    winner = min(timings, key=timings.get)
    record(key, winner)
    rlog.log_info("autotune %s -> %s (%s)", key, winner,
              {n: f"{t*1e3:.1f}ms" for n, t in timings.items()})
    return winner, timings
