"""Pallas TPU kernels — the performance layer.

These play the role of RAFT's fused CUDA kernels: the tiled pairwise
engine (distance/detail/pairwise_matrix/kernel_sm60.cuh), warpsort select
(matrix/detail/select_warpsort.cuh) and the fused IVF interleaved scan
(neighbors/detail/ivf_flat_interleaved_scan-inl.cuh). Composed XLA ops
top out well below 1% of MXU peak on the kNN hot path because the
per-tile full `lax.top_k` is a full sort; these kernels keep the GEMM on
the MXU and maintain a running k-best in VMEM instead.
"""
from .fused_knn import fused_knn  # noqa: F401
from .graph_expand import graph_expand  # noqa: F401
from .guarded import guarded_call  # noqa: F401
from .nn_descent import build_graph as nn_descent_graph  # noqa: F401
from .ring_topk import merge as ring_topk_merge  # noqa: F401
