"""Storage-ladder quantization: the ONE home for per-row corpus coding.

Every rung of the storage ladder (docs/perf.md "Storage ladder") shares
the same shape of machinery — per-row symmetric scales, a packed byte
representation, and an exact-norm side array — and before this module
each family grew its own copy (``brute_force.quantize_rows`` + cagra's
``prepare_search`` int8 pass were the second; int4 would have been the
third). The ladder now lives here:

* **f32 / bf16 / uint8 / int8** — :func:`quantize_rows` /
  :func:`dequantize_rows`, byte-for-byte the former
  ``brute_force.quantize_rows`` semantics (brute_force re-exports them,
  so pickled/serialized indexes and every call site are unchanged).
* **int4** — nibble-packed rows at 2x int8's density:
  :func:`quantize_int4` packs value ``j`` and value ``j + half`` of a
  row into one byte (*split-half* layout, so in-kernel unpacking is a
  lane-axis shift+mask — :func:`int4_nibbles` — and never a sub-128
  minor-axis reshape, the Mosaic-fragile relayout). Per-row scale =
  amax/7, values clipped to [-7, 7].
* **PQ row codes** — :func:`train_pq_rows` / :func:`encode_pq_rows`
  code whole rows (no coarse quantizer: the edge store codes *dataset
  rows*, not residuals) against per-subspace codebooks, reusing the
  ivf_pq LUT machinery (:func:`raft_tpu.ops.ivf_pq_scan.make_cb_matrix`
  builds the block-diagonal decode matrix the expand kernels consume;
  :func:`pq_int8_cb` applies the same per-subspace symmetric int8
  quantization as the ivf_pq scan's fp8-LUT mode).

``int8_scale_report`` (the health-report scale summary) also moved here
from brute_force, unchanged.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import cdiv, in_jax_trace, round_up_to

__all__ = ["quantize_rows", "dequantize_rows", "int8_scale_report",
           "quantize_int4", "dequantize_int4", "int4_half_width",
           "int4_nibbles", "train_pq_rows", "encode_pq_rows",
           "pq_decoded_norms", "pq_int8_cb", "default_pq_dim"]


def quantize_rows(dataset: jax.Array, dtype
                  ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """f32 rows → (stored rows, per-row scales|None) for a storage dtype.

    ``dtype``: a jnp dtype (float32/bfloat16/int8/uint8) or the string
    ``"int4"`` (nibble-packed — see :func:`quantize_int4`; the returned
    rows are ``(n, half_p)`` int8 and ALWAYS carry scales)."""
    from ..core.errors import expects

    if isinstance(dtype, str) and dtype in ("int4", "i4"):
        return quantize_int4(dataset)
    dtype = jnp.dtype(dtype)
    if dtype == jnp.float32:
        return dataset, None
    if dtype == jnp.bfloat16:
        return dataset.astype(jnp.bfloat16), None
    if dtype == jnp.uint8:
        # byte corpora (SIFT/DEEP): exact for integral [0, 255] inputs,
        # no scales (the reference's native uint8 dataset mode)
        q = jnp.clip(jnp.round(dataset), 0, 255)
        if not in_jax_trace():
            # silent clamping of float data would collapse recall with no
            # error; scaled float data belongs in int8 mode
            expects(bool(jnp.all(jnp.abs(dataset - q) < 1e-3)),
                    "uint8 storage expects byte-valued data (integral in "
                    "[0, 255]); use dtype='int8' for scaled float data")
        return q.astype(jnp.uint8), None
    expects(dtype == jnp.int8,
            "store dtype must be f32/bf16/int8/uint8/int4, got %s", dtype)
    amax = jnp.max(jnp.abs(dataset), axis=1)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(dataset / scale[:, None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_rows(rows: jax.Array,
                    scales: Optional[jax.Array]) -> jax.Array:
    """Stored rows (any non-packed dtype) → f32, applying int8 per-row
    scales. int4-packed rows need :func:`dequantize_int4` (the packed
    width is not the logical dim)."""
    out = rows.astype(jnp.float32)
    if scales is not None:
        out = out * scales[..., None]
    return out


def int8_scale_report(scales) -> dict:
    """Sampled per-row int8 scale stats for a health report: the f32
    originals are not retained by int8 stores, so the report carries the
    quantization *step bound* ``max_scale/2`` per component rather than
    a measured reconstruction error. Shared by every family with an
    int8 storage mode (brute_force, ivf_flat)."""
    sc = np.asarray(scales, np.float64)
    return {"int8": {
        "mean_scale": round(float(sc.mean()), 6),
        "max_scale": round(float(sc.max()), 6),
        "max_abs_err_bound": round(float(sc.max()) / 2.0, 6)}}


# --------------------------------------------------------------- int4 --

def int4_half_width(dim: int) -> int:
    """Packed byte width for a ``dim``-wide int4 row: ``ceil(dim/2)``
    rounded to the 64-byte sublane-pair multiple, so a query split into
    its (low, high) halves is ``2*half_p`` wide — a 128-lane multiple —
    and the packed corpus block keeps a power-of-two minor dim."""
    return round_up_to(cdiv(dim, 2), 64)


def quantize_int4(dataset: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
    """f32 rows → (packed (n, half_p) int8, per-row scales (n,) f32).

    Split-half layout: byte ``j`` of a row holds component ``j`` in its
    low nibble and component ``j + half_p`` in its high nibble (missing
    tail components are zero). Unpacking is therefore two lane-axis
    shift+mask passes over the SAME byte tile (:func:`int4_nibbles`) and
    the dot against a query splits into two half-width GEMMs — no
    nibble interleaving, no sub-128 reshapes anywhere."""
    dataset = jnp.asarray(dataset, jnp.float32)
    n, dim = dataset.shape
    half = int4_half_width(dim)
    amax = jnp.max(jnp.abs(dataset), axis=1)
    scale = jnp.maximum(amax, 1e-30) / 7.0
    q = jnp.clip(jnp.round(dataset / scale[:, None]), -7, 7)
    q = jnp.pad(q, ((0, 0), (0, 2 * half - dim))).astype(jnp.int32)
    lo = q[:, :half] & 0xF
    hi = q[:, half:] & 0xF
    packed = (lo | (hi << 4)).astype(jnp.uint8).astype(jnp.int8)
    return packed, scale


def int4_nibbles(packed_i32: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
    """Packed int4 bytes (already widened to int32) → (low, high) f32
    nibble planes with sign extension — the in-kernel unpack every
    consumer (fused_knn / graph_expand / cagra_fused) shares, so the
    arithmetic cannot drift between kernels. Pure lane-local shift+mask:
    ``low = (w << 28) >> 28`` (arithmetic), ``high = (w << 24) >> 28``."""
    w = packed_i32
    low = ((w << 28) >> 28).astype(jnp.float32)
    high = ((w << 24) >> 28).astype(jnp.float32)
    return low, high


def dequantize_int4(packed: jax.Array, scales: jax.Array,
                    dim: int) -> jax.Array:
    """Packed (n, half_p) int8 rows → (n, dim) f32 (the XLA-side decode
    the resident fallback engines use; bit-for-bit the kernels' nibble
    arithmetic)."""
    half = packed.shape[-1]
    low, high = int4_nibbles(packed.astype(jnp.int32))
    full = jnp.concatenate([low, high], axis=-1)[..., :dim]
    return full * scales[..., None]


# ----------------------------------------------------------------- PQ --

def default_pq_dim(dim: int) -> int:
    """Edge-store PQ sub-quantizer count: ~8 components per subspace
    (16 codes/row at d128 — an 8x byte cut vs the int8 edge rows that
    keeps refined recall within a few points of int8; halving it again
    with ``pq_dim=dim_p//16`` trades ~0.1 refined recall for the
    ISSUE's 0.6 GB/1M·deg64 point). Floored at 4, capped at 64."""
    dim_p = round_up_to(dim, 128)
    return max(4, min(64, dim_p // 8))


def train_pq_rows(dataset, pq_dim: int, book: int = 256,
                  iters: int = 20, seed: int = 0,
                  train_rows: int = 65536) -> jax.Array:
    """Per-subspace codebooks (pq_dim, book, pq_len) trained on WHOLE
    rows (zero-padded to the 128-multiple dim the expand kernels score
    in), reusing ivf_pq's vmapped fixed-iteration Lloyd. No coarse
    quantizer / residuals: the edge store codes dataset rows directly,
    and the decode matrix lives in the padded dim space so decoded
    vectors drop into the kernels' existing scoring unchanged."""
    from ..neighbors.ivf_pq import _kmeans_fixed

    dataset = jnp.asarray(dataset, jnp.float32)
    n, dim = dataset.shape
    dim_p = round_up_to(dim, 128)
    pq_len = dim_p // pq_dim
    if n > train_rows:
        stride = max(1, n // train_rows)
        dataset = dataset[::stride]
    x = jnp.pad(dataset, ((0, 0), (0, dim_p - dim)))
    slices = jnp.transpose(
        x.reshape(x.shape[0], pq_dim, pq_len), (1, 0, 2))
    keys = jax.random.split(jax.random.key(seed), pq_dim)
    book = min(book, x.shape[0])
    return jax.vmap(_kmeans_fixed, in_axes=(0, None, None, 0))(
        slices, book, iters, keys)


def encode_pq_rows(dataset, codebooks: jax.Array,
                   chunk: int = 1 << 16) -> jax.Array:
    """Rows → (n, pq_dim) uint8 codes (per-subspace argmin), in bounded
    chunks — the unbounded (n, pq_dim, book) argmin plane is the same
    HBM hazard ``ivf_pq_scan.pq_chunk_rows`` bounds."""
    from .ivf_pq_scan import pq_chunk_rows

    dataset = jnp.asarray(dataset, jnp.float32)
    n, dim = dataset.shape
    pq_dim, book, pq_len = codebooks.shape
    dim_p = pq_dim * pq_len
    chunk = min(chunk, pq_chunk_rows(pq_dim, book))

    @jax.jit
    def _enc(xb):
        xb = jnp.pad(xb, ((0, 0), (0, dim_p - dim)))
        s = xb.reshape(xb.shape[0], pq_dim, pq_len)
        d2 = (jnp.sum(s * s, axis=2)[:, :, None]
              - 2.0 * jnp.einsum("nsl,sbl->nsb", s, codebooks,
                                 precision="highest")
              + jnp.sum(codebooks * codebooks, axis=2)[None, :, :])
        return jnp.argmin(d2, axis=2).astype(jnp.uint8)

    if n <= chunk:
        return _enc(dataset)
    parts = []
    for b0 in range(0, n, chunk):
        sel = jnp.asarray((np.arange(b0, b0 + chunk) % n).astype(np.int32))
        parts.append(_enc(jnp.take(dataset, sel, axis=0))
                     [: min(chunk, n - b0)])
    return jnp.concatenate(parts)


def pq_decoded_norms(codes: jax.Array, codebooks: jax.Array) -> jax.Array:
    """(n,) ||decode(codes)||² — subspaces are disjoint coordinate
    blocks, so the norm is the sum of per-subspace codeword norms (one
    small gather, no decode materialization)."""
    pq_dim, book, pq_len = codebooks.shape
    cb2 = jnp.sum(codebooks * codebooks, axis=2)          # (s, b)
    c = jnp.asarray(codes, jnp.int32)
    return jnp.sum(cb2[jnp.arange(pq_dim)[None, :], c], axis=1)


def pq_decode_table(codebooks: jax.Array) -> jax.Array:
    """(pq_dim, book, pq_len) codebooks → the SUBSPACE-MAJOR decode
    table (pq_dim*book, dim_p): row ``s*book + b`` is codeword ``b`` of
    subspace ``s`` embedded at dims ``[s*pq_len, (s+1)*pq_len)``, zeros
    elsewhere. A one-hot row block per subspace times this table IS the
    decoded vector — and the one-hot builds from plain per-subspace
    equality compares, deliberately avoiding ``pltpu.repeat`` (whose
    interpret-mode semantics are element-wise where the ivf_pq scan's
    comment assumes tiling — the documented interpret/TPU quirk behind
    that module's xfailed pq_bits=4 int8-LUT test)."""
    pq_dim, book, pq_len = codebooks.shape
    dim_p = pq_dim * pq_len
    tbl = jnp.zeros((pq_dim * book, dim_p), jnp.float32)
    cbj = jnp.asarray(codebooks, jnp.float32)
    for s in range(pq_dim):
        tbl = tbl.at[s * book:(s + 1) * book,
                     s * pq_len:(s + 1) * pq_len].set(cbj[s])
    return tbl


def pq_int8_cb(table: jax.Array, pq_dim: int, book: int
               ) -> Tuple[jax.Array, jax.Array]:
    """Subspace-major decode table → (int8 table, (1, dim_p) f32
    per-column rescale) — the ivf_pq scan's fp8-LUT-role quantization:
    per-subspace symmetric quantize (the table is block-diagonal, so
    each output column belongs to exactly one subspace and the
    per-column rescale round-trips exactly up to the int8 rounding
    itself). The int8 one-hot GEMM then accumulates exactly in int32 at
    the MXU's double byte rate."""
    dim_p = table.shape[1]
    pq_len = dim_p // pq_dim
    absmax = jnp.max(jnp.abs(table).reshape(pq_dim, book * dim_p), axis=1)
    scales = jnp.maximum(absmax, 1e-12) / 127.0
    t_i8 = jnp.clip(
        jnp.round(table.reshape(pq_dim, book, dim_p)
                  / scales[:, None, None]), -127, 127
    ).astype(jnp.int8).reshape(pq_dim * book, dim_p)
    scale_row = jnp.repeat(scales, pq_len)[None, :]
    return t_i8, scale_row
