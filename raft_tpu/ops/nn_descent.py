"""Batched NN-descent kNN-graph construction — the CAGRA build fast path.

Reference: raft/neighbors/detail/nn_descent.cuh:342 (class GNND: iterative
local join over sampled new/old neighbors + reverse neighbors, bloom-filter
dedup, termination threshold). The reference sanctions NN-descent as one of
CAGRA's two graph builders (cagra_types.hpp:66 build_algo) precisely
because exact all-pairs stops scaling: the kNN graph is O(n²·d) exact but
O(rounds·n·C·d) by descent, and graph *candidate* quality — not exactness
— is the bar (optimize()'s detour pruning and the search-time exact
re-rank both tolerate imperfect candidate lists).

TPU design — everything round-shaped and device-resident:

* **Joint sample** per round: each node draws ``sample`` of its current
  neighbors (forward) plus up to ``sample`` nodes that drew *it* (the
  reverse sample — one stable-argsort grouping over the round's n·s
  sampled edges, the ``_rev_group_jit`` form, fully on device). The
  GNND new/old flag machinery is replaced by fresh uniform samples per
  round: redundant re-joins are bounded by the sample rotation and the
  update-rate early stop, and no per-edge host bookkeeping survives.
* **Neighbor-of-neighbor expansion**: candidates for a node are its
  joined nodes plus each joined node's closest ``join`` current
  neighbors (lists are kept distance-sorted by ``select_k``, so a
  static ``[:join]`` slice takes the best ones). Scoring is one batched
  gather + broadcast-mul/lane-reduce contraction (the
  ``ops/graph_expand.py`` scoring shape — no sub-128-lane reshapes),
  accumulated in f32 from a bf16 score copy on TPU (half the gather
  traffic; graph candidates tolerate ~1e-3 distance rounding the same
  way the reference tolerates IVF-PQ quantization).
* **Dedup** against the current list and within the candidate block is
  the ``cagra._dup_mask`` stable-argsort form — width-linear VMEM, no
  O(C²) planes.
* **Convergence by update rate**: one scalar per round (the fraction of
  list slots replaced) leaves the device; rounds stop early below
  ``termination`` (nn_descent_types.hpp:53 termination_threshold).

Host work per round is one python batch loop over wrapped constant-shape
node batches (two cached executables total: the init merge and the join
round) and a single scalar read — the (n, k) graph and distance state
never round-trips through the host until the final readback.

Knobs (all overridable per call): ``RAFT_TPU_NND_ROUNDS`` (default 15),
``RAFT_TPU_NND_SAMPLE`` (16), ``RAFT_TPU_NND_JOIN`` (24),
``RAFT_TPU_NND_TERM`` (0.002), ``RAFT_TPU_NND_BATCH`` (8192),
``RAFT_TPU_NND_DTYPE`` (score-copy dtype; bfloat16 on TPU else float32).
"""
from __future__ import annotations

import os
import time
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import logging as rlog
from ..core import tracing
from ..core.errors import expects
from ..distance.distance_types import DistanceType, canonical_metric
from ..matrix.select_k import select_k
from ..utils import env_int as _env_int

__all__ = ["build_graph", "supports"]


def supports(metric) -> bool:
    """Whether the descent builder can serve ``metric`` — cagra's auto
    resolver and its pre-guard validation both ask BEFORE dispatching
    here, so an unservable metric never reaches the guarded site (where
    the rejection would persist as a demotion)."""
    mt = canonical_metric(metric)
    return mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                  DistanceType.InnerProduct)


@partial(jax.jit, static_argnames=("s",))
def _rev_sample(fwd: jax.Array, s: int) -> jax.Array:
    """(n, s) forward sample → (n, s) reverse sample: node ``i`` appears
    in row ``j`` iff ``i`` sampled ``j`` this round (first ``s`` arrivals
    kept, -1 pad). Stable-argsort grouping over the round's n·s sampled
    edges — small enough to sort on device at every rehearsed n (8M
    elements at 500k×16), unlike the full n·k edge set ``_rev_group_jit``
    guards against."""
    n = fwd.shape[0]
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), s)
    tgt = fwd.reshape(-1)
    tgt = jnp.where((tgt >= 0) & (tgt < n), tgt, n)   # junk edges → row n
    order = jnp.argsort(tgt, stable=True)
    ts, cs = tgt[order], src[order]
    counts = jnp.bincount(ts, length=n + 1)
    seg = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]])
    pos = (jnp.arange(ts.shape[0], dtype=jnp.int32)
           - seg[ts].astype(jnp.int32))
    keep = (pos < s) & (ts < n)
    out = jnp.full((n + 1, s), -1, jnp.int32)
    out = out.at[jnp.where(keep, ts, n),
                 jnp.where(keep, pos, 0)].set(jnp.where(keep, cs, -1))
    return out[:n]


@partial(jax.jit, static_argnames=("k", "join", "mt_val"))
def _join_batch(score, norms, graph, dist, jlist, rows, k: int, join: int,
                mt_val: int):
    """One node batch of the neighbor-of-neighbor join.

    ``rows``: (b,) node ids; ``jlist``: (b, t) joined node ids (-1 pad).
    Candidates are the joined nodes themselves plus each one's closest
    ``join`` current neighbors; ``join=0`` is the init merge (``jlist``
    IS the candidate list — no neighbor gather is traced at all).
    Returns the merged (b, k) lists + per-row replaced-slot counts (the
    caller drops wrapped tail rows before summing — a duplicate row
    must not count twice toward the update rate).
    """
    from ..neighbors.cagra import _dup_mask

    mt = DistanceType(mt_val)
    b, t = jlist.shape
    g = graph[rows]                                   # (b, k)
    gd = dist[rows]                                   # (b, k)
    if join:
        nbr = graph[jnp.maximum(jlist, 0)][:, :, :join]   # (b, t, join)
        nbr = jnp.where(jlist[:, :, None] >= 0, nbr, -1)
        cand = jnp.concatenate([jlist, nbr.reshape(b, t * join)], axis=1)
    else:
        cand = jlist
    ok = (cand >= 0) & (cand != rows[:, None]) & ~_dup_mask(cand, keep=g)
    x = score[rows]                                   # (b, d) score dtype
    vecs = score[jnp.maximum(cand, 0)]                # (b, C, d)
    ip = jnp.einsum("bcd,bd->bc", vecs, x,
                    preferred_element_type=jnp.float32)
    if mt is DistanceType.InnerProduct:
        cd = -ip
    else:
        # L2 family: build order only needs squared L2 (sqrt is monotone)
        cd = jnp.maximum(
            norms[rows][:, None] + norms[jnp.maximum(cand, 0)] - 2.0 * ip,
            0.0)
    cd = jnp.where(ok, cd, jnp.inf)
    new_d, sel = select_k(jnp.concatenate([gd, cd], axis=1), k,
                          select_min=True)
    new_i = jnp.take_along_axis(jnp.concatenate([g, cand], axis=1), sel,
                                axis=1)
    changed = jnp.sum((sel >= k) & jnp.isfinite(new_d), axis=1)
    return new_i, new_d, changed


@tracing.annotate("raft_tpu::ops::nn_descent::build_graph")
def build_graph(dataset, k: int, metric=DistanceType.L2Expanded,
                rounds: int = 0, sample: int = 0, join: int = 0,
                termination: Optional[float] = None, seed: int = 0,
                batch: int = 0, init_graph=None,
                progress: Optional[Callable] = None) -> np.ndarray:
    """(n, k) approximate kNN graph by batched NN-descent.

    ``init_graph``: optional (n, k0) int32 candidate lists to seed from
    (e.g. the IVF-PQ candidate pass); default is a random init. Every
    returned id is a valid non-self row (shortfall slots cycle the row's
    valid neighbors — ``optimize`` and the traversal both index with
    them). ``progress(round, rounds, update_rate, elapsed_s)`` is called
    once per round; by default one log line per round breaks the silence
    of a minutes-long build. Deterministic for a fixed seed on a fixed
    backend (jax PRNG + stable sorts throughout).
    """
    dataset = np.asarray(dataset, np.float32)
    n, _d = dataset.shape
    mt = canonical_metric(metric)
    expects(supports(mt),
            "nn_descent supports L2/IP metrics, got %s", mt.name)
    expects(0 < k < n, "k %d out of range for n %d", k, n)
    rounds = rounds or _env_int("RAFT_TPU_NND_ROUNDS", 15)
    s = min(sample or _env_int("RAFT_TPU_NND_SAMPLE", 16), k)
    join = min(join or _env_int("RAFT_TPU_NND_JOIN", 24), k)
    term = (termination if termination is not None
            else float(os.environ.get("RAFT_TPU_NND_TERM", "0.002")))
    batch = min(batch or _env_int("RAFT_TPU_NND_BATCH", 8192), n)
    dt_env = os.environ.get("RAFT_TPU_NND_DTYPE")
    bf16 = (dt_env or ("bfloat16" if jax.default_backend() == "tpu"
                       else "float32")) in ("bfloat16", "bf16")

    data_j = jnp.asarray(dataset)
    score = data_j.astype(jnp.bfloat16) if bf16 else data_j
    # norms of the SCORE representation: candidate ordering stays
    # internally consistent with the rounded cross terms
    norms = jnp.sum(jnp.square(score.astype(jnp.float32)), axis=1)
    key = jax.random.PRNGKey(seed)

    graph = jnp.full((n, k), -1, jnp.int32)
    dist = jnp.full((n, k), jnp.inf, jnp.float32)
    rows_all = np.arange(n, dtype=np.int32)

    def run_pass(jlist, jn):
        """One full sweep of ``_join_batch`` over wrapped constant-shape
        node batches; state stays on device, outputs concatenate back
        into the (n, k) arrays, one changed-count scalar per sweep."""
        gs, ds_, ch = [], [], None
        for b0 in range(0, n, batch):
            rows = jnp.asarray((rows_all[b0:b0 + batch]
                                if b0 + batch <= n
                                else (np.arange(b0, b0 + batch) % n)
                                .astype(np.int32)))
            gi, di, c = _join_batch(score, norms, graph, dist,
                                    jnp.take(jlist, rows, axis=0), rows,
                                    k, jn, mt.value)
            gs.append(gi)
            ds_.append(di)
            c = jnp.sum(c[: n - b0])   # wrapped tail rows don't count
            ch = c if ch is None else ch + c
        if len(gs) == 1:
            return gs[0][:n], ds_[0][:n], ch
        return (jnp.concatenate(gs)[:n], jnp.concatenate(ds_)[:n], ch)

    if init_graph is not None:
        cand0 = jnp.asarray(np.asarray(init_graph, np.int32))
    else:
        key, kinit = jax.random.split(key)
        cand0 = jax.random.randint(kinit, (n, k), 0, n, dtype=jnp.int32)
    graph, dist, _ = run_pass(cand0, 0)

    t0 = time.perf_counter()
    for r in range(rounds):
        key, kc = jax.random.split(key)
        cols = jax.random.randint(kc, (n, s), 0, k, dtype=jnp.int32)
        # sampling an unfilled slot proposes a junk id the join masks out
        fwd = jnp.take_along_axis(graph, cols, axis=1)
        jlist = jnp.concatenate([fwd, _rev_sample(fwd, s)], axis=1)
        graph, dist, ch = run_pass(jlist, join)
        rate = float(ch) / float(n * k)               # the round's sync
        if progress is not None:
            progress(r + 1, rounds, rate, time.perf_counter() - t0)
        else:
            rlog.log_info(
                "nn_descent: round %d/%d update_rate=%.4f (%.0fs)",
                r + 1, rounds, rate, time.perf_counter() - t0)
        if rate < term:
            break

    # finalize: every slot a valid non-self id (cycle valid neighbors,
    # (row+1)%n when a row somehow has none) — optimize() and the
    # traversal index the graph directly and must never see -1
    from ..neighbors.cagra import _drop_self_pad

    ref = jnp.where(jnp.isfinite(dist), graph, -1)
    out = jax.jit(partial(_drop_self_pad, k=k, n=n))(
        ref, jnp.arange(n, dtype=jnp.int32))
    return np.asarray(out)
