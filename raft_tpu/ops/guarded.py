"""Guarded kernel dispatch: Pallas engines fall back to their XLA-path
equivalents on compile/execution failure.

Every custom-kernel engine in this library has an exact composed-XLA
equivalent (that is what the parity tests assert; gated sites today:
``select_k`` KPASS, the ivf_flat/ivf_pq scans, ``brute_force.fused``,
``cagra.graph_expand`` → the XLA gather hop, and the sharded merge's
``sharded.ring_topk`` → the allgather + ``knn_merge_parts`` program),
so a Pallas failure —
a Mosaic lowering bug on a new chip generation, a scoped-VMEM
compile-OOM on an unrehearsed shape, a driver hiccup — should cost one
log line and a slower call, never the request or the process. The
reference hard-fails on kernel errors (RAFT_CUDA_TRY); a serving stack
cannot.

``guarded_call(site, primary, fallback)`` is the single chokepoint:

* a **demoted** site (prior failure this process, or a ``guard:…`` entry
  in the autotune cache) skips the kernel path entirely;
* fault-injection probes (:mod:`raft_tpu.core.faults`) fire first, so
  every fallback path is deterministically testable
  (``RAFT_TPU_FAULTS='kernel_compile@*'``);
* a real failure logs ONCE per site, records the demotion in the
  autotune cache (in-process always; persisted to the cross-process
  cache only when ``RAFT_TPU_GUARD_PERSIST=1``, so a transient failure
  cannot poison future processes by default), and serves the fallback;
* injected faults never demote — they simulate per-call failure, and a
  simulation must not change later dispatch decisions.

Trace caveat: when the guarded call happens inside an outer ``jit``
trace, the kernel's own compilation may be deferred to the outer
executable's compile, outside this try block — the guard then catches
trace-time failures and armed faults, not late compile errors. Eager
dispatch (the serving pattern) is fully covered.
"""
from __future__ import annotations

import os
from typing import Callable, Dict

import jax

from ..core import faults, logging as rlog
from ..core.deadline import DeadlineExceeded
from ..core.interruptible import InterruptedException

__all__ = ["guarded_call", "demoted_sites", "reset"]

# site -> reason string; demoted sites dispatch straight to the fallback
_DEMOTED: Dict[str, str] = {}


def _guard_key(site: str) -> str:
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform).replace(" ", "_")
    return f"{dev.platform}:{kind}:guard:{site}"


def _demote(site: str, err: Exception, persist: bool) -> None:
    from . import autotune

    first = site not in _DEMOTED
    _DEMOTED[site] = f"{type(err).__name__}: {err}"
    if first:
        rlog.log_warn(
            "guarded %s: kernel path failed (%s: %s); demoted to the XLA "
            "fallback for the rest of this process", site,
            type(err).__name__, err)
        try:
            # serving telemetry: demotions are operational events the
            # metrics snapshot must surface (docs/serving.md)
            from ..serve import metrics as serve_metrics

            serve_metrics.counter("guarded.demotions").inc()
            # per-site magnitude: the SLO engine's demotion-rate target
            # and the drift-guard test read site-labeled counts
            serve_metrics.counter(f"guarded.demotions.{site}").inc()
            # flight recorder: stamped with the trace IDs of whatever
            # requests were in flight when the kernel path died
            from ..core import events as core_events

            core_events.record("guarded_demotion", site,
                               error=f"{type(err).__name__}: {err}")
        except Exception:  # noqa: BLE001 - telemetry must not break containment
            pass
    autotune.record(
        _guard_key(site), "fallback",
        persist=persist and os.environ.get("RAFT_TPU_GUARD_PERSIST") == "1")


def guarded_call(site: str, primary: Callable[[], object],
                 fallback: Callable[[], object]):
    """Run ``primary`` (the kernel engine) with ``fallback`` (its exact
    XLA equivalent) as the containment path. See module docstring for the
    demotion/injection contract. Cancellation and deadline exceptions
    pass through — they are control flow, not engine failures."""
    from . import autotune

    if site in _DEMOTED or autotune.lookup(_guard_key(site)) == "fallback":
        return fallback()
    try:
        faults.check("kernel_compile", site)
        faults.sleep_if(site)
        return primary()
    except faults.InjectedFault:
        # simulated failure: serve the fallback for THIS call only
        return fallback()
    except (KeyboardInterrupt, SystemExit, InterruptedException,
            DeadlineExceeded):
        raise
    except Exception as e:  # noqa: BLE001 - any engine failure = contain
        _demote(site, e, persist=True)
        return fallback()


def demoted_sites() -> Dict[str, str]:
    """Sites demoted this process and why (diagnostics)."""
    return dict(_DEMOTED)


def reset() -> None:
    """Clear in-process demotions (tests / operator re-arm after a fix)."""
    from . import autotune

    for site in list(_DEMOTED):
        autotune.forget(_guard_key(site))
    _DEMOTED.clear()
